"""tpuvsp — the Google TPU vendor-specific plugin.

The centerpiece of this build (BASELINE.json north star): the VSP that
makes TPU chips and ICI fabric endpoints first-class DPU-operator
devices. Plays the role the Intel/Marvell VSPs play in the reference
(SURVEY §2.4) with TPU semantics:

  Init             fabric bridge bring-up (+ optional uplink enslave),
                   slice topology discovery, returns the OPI bind addr
                   (reference: marvell main.go:280-317 OVS+SDP bring-up)
  GetDevices       ICI endpoint slices per local chip, each carrying the
                   chip's coordinates and ICI link inventory
  SetNumEndpoints  repartitions endpoints across local chips
                   (reference SetNumVfs → VF creation)
  CreateBridgePort attach the pod's host-side veth to the fabric bridge,
                   resolved by deterministic port name
                   (reference: OPI name → VF netdev math, main.go:331-449)
  Create/DeleteNetworkFunction
                   hairpin+fdb chain wiring (reference: OVS NF flows)
  Ping             heartbeat, optionally proxied to the native cp-agent
                   for real chip-health (octep_cp_agent heartbeat analogue)
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

import grpc
from google.protobuf import empty_pb2

from .. import faults
from ..dpu_api import services
from ..dpu_api.gen import bridge_port_pb2 as bp
from ..dpu_api.gen import dpu_api_pb2 as pb
from ..parallel.topology import SliceTopology

log = logging.getLogger(__name__)

DEFAULT_NUM_ENDPOINTS = 8
DEFAULT_OPI_PORT = 50151


class TpuVsp(
    services.LifeCycleServicer,
    services.NetworkFunctionServicer,
    services.DeviceServicer,
    services.HeartbeatServicer,
    services.BridgePortServicer,
):
    DEEP_HEALTH_TTL = 60.0

    def __init__(
        self,
        topology: Optional[SliceTopology] = None,
        dataplane=None,
        opi_ip: str = "127.0.0.1",
        opi_port: Optional[int] = None,
        cp_agent_client=None,
        num_endpoints: int = DEFAULT_NUM_ENDPOINTS,
    ):
        self._topology = topology
        self._dataplane = dataplane
        self._opi = (opi_ip, opi_port or int(os.environ.get("DPU_OPI_PORT", DEFAULT_OPI_PORT)))
        self._cp_agent = cp_agent_client
        self._lock = threading.Lock()
        # Serializes Init's blocking bring-up WITHOUT stalling the
        # request path: _lock is only ever held for state snapshots.
        self._init_lock = threading.Lock()
        self._num_endpoints = num_endpoints
        # Fresh per process: echoed in Ping so the daemon detects VSP
        # restarts deterministically (sub-heartbeat bounces included) and
        # re-applies the fabric partition the new process lost.
        import uuid as _uuid

        self._instance_id = _uuid.uuid4().hex
        self._initialized = False
        # Health caches, maintained by background threads (never refreshed
        # inline — a slow probe must not stall the kubelet's 5 s
        # ListAndWatch poll through GetDevices, VERDICT r1 weak #6).
        self._deep_health_cache: Optional[Dict[int, bool]] = None
        self._agent_health_cache: Dict[int, bool] = {}
        self._watcher_stop = threading.Event()
        self._watcher_threads: list = []
        # Set by a cp-agent `reset` event (chip bounced — octep PERST
        # analogue): wakes the deep-health loop for an immediate re-probe
        # instead of trusting the returned chip until the next TTL pass.
        self._deep_health_kick = threading.Event()
        self.resets_seen = 0

    # -- LifeCycle -----------------------------------------------------------

    def Init(self, request, context):
        # Bridge bring-up and comm-channel setup shell out to ip/nft
        # (with fallback retries on old kernels) — seconds, worst case.
        # They run under _init_lock, NOT _lock: _lock guards the state
        # Ping/GetDevices read on the request path, and the kubelet's
        # 5 s ListAndWatch poll plus the daemon's heartbeat must never
        # queue behind a slow bring-up (the module's no-inline-refresh
        # contract; regression: test_tpu_platform.py
        # test_ping_not_blocked_by_slow_init). _init_lock still keeps
        # two concurrent Inits from racing the bring-up itself.
        with self._init_lock:
            with self._lock:
                if self._topology is None:
                    self._topology = SliceTopology.from_env()
                    if not self._topology.chips:
                        self._topology = SliceTopology.single_chip()
                dataplane = self._dataplane
                opi = self._opi
            if dataplane is None:
                # Built into the LOCAL only — a dataplane must not be
                # visible to concurrent RPCs (CreateBridgePort gates on
                # `dp is not None`) until its bridge exists; the final
                # publish below is the only self._dataplane write.
                from .tpu_dataplane import (DebugDataplane,
                                            TpuFabricDataplane)

                uplink = os.environ.get("DPU_FABRIC_UPLINK")
                if os.environ.get("DPU_DATAPLANE", "bridge") == "debug":
                    dataplane = DebugDataplane(uplink=uplink)
                else:
                    dataplane = TpuFabricDataplane(uplink=uplink)
            try:
                # Blocking under _init_lock is the DESIGN here: only
                # other Inits contend on it, never Ping/GetDevices.
                # graftlint: disable=GL004
                dataplane.ensure_bridge()
            except Exception as e:
                log.warning("bridge bring-up failed (%s); debug dataplane", e)
                from .tpu_dataplane import DebugDataplane

                dataplane = DebugDataplane()
                dataplane.ensure_bridge()  # graftlint: disable=GL004
            # Optional IPv6 link-local control channel on the device that
            # joins host and DPU sides (reference Marvell fe80::1/::2 on
            # SDP, NetSec configureCommChannelIPs on the backplane): the
            # OPI address becomes a constant of the contract, no routed
            # IPs or discovery needed.
            comm_dev = os.environ.get("DPU_COMM_CHANNEL_DEV")
            if comm_dev:
                from .comm_channel import peer_target, setup_comm_channel

                try:
                    dpu_mode = request.dpu_mode == pb.DPU_MODE_DPU
                    # graftlint: disable=GL004 (same: _init_lock only)
                    conn = setup_comm_channel(comm_dev, dpu_mode=dpu_mode)
                    if not dpu_mode:
                        # The host daemon DIALS what Init returns; its own
                        # address is only the source — the target is the
                        # DPU side's fixed address over this device.
                        conn = peer_target(comm_dev)
                    opi = (conn, opi[1])
                except Exception as e:
                    log.warning(
                        "comm channel on %s failed (%s); OPI stays on %s",
                        comm_dev, e, opi[0],
                    )
            with self._lock:
                self._dataplane = dataplane
                self._opi = opi
                self._initialized = True
        self._start_health_watchers()
        log.info(
            "tpuvsp Init(id=%s): slice=%s chips=%d, OPI at %s:%d",
            request.dpu_identifier,
            self._topology.accelerator_type or "single",
            self._topology.num_chips,
            *opi,
        )
        return pb.IpPort(ip=opi[0], port=opi[1])

    # -- Devices -------------------------------------------------------------

    def GetDevices(self, request, context):
        resp = pb.DeviceListResponse()
        with self._lock:
            topo = self._topology or SliceTopology.single_chip()
            total = self._num_endpoints
        local = topo.local_chips() or topo.chips
        healthy = self._chip_health(len(local))
        for i in range(total):
            chip = local[i % len(local)]
            dev_id = f"tpu{chip.index}-ep{i // len(local)}"
            d = resp.devices[dev_id]
            d.id = dev_id
            d.health = pb.HEALTHY if healthy.get(chip.index, True) else pb.UNHEALTHY
            d.backing = f"/dev/accel{chip.index}"
            d.topology.coords = chip.coords_str
            d.topology.numa_node = chip.numa_node
            d.topology.worker_id = topo.worker_id
            d.topology.slice_id = topo.slice_id
            d.topology.num_slices = topo.num_slices
            for n in topo.neighbors(chip):
                d.topology.links.add(neighbor=n.coords_str, gbps=400)
        return resp

    def SetNumEndpoints(self, request, context):
        with self._lock:
            self._num_endpoints = request.count
            dataplane = self._dataplane
        # The partition has a dataplane effect, not just an inventory one
        # (reference SetNumVfs creates real VFs, vspnetutils.go:50): each
        # endpoint's egress share of the fabric budget is enforced per
        # attached port when the budget is known (tpu_dataplane).
        if dataplane is not None and hasattr(dataplane, "partition_endpoints"):
            try:
                dataplane.partition_endpoints(request.count)
            except Exception:
                log.exception("endpoint repartition failed on the dataplane")
        log.info("tpuvsp: fabric partitioned into %d endpoints", request.count)
        return pb.EndpointCount(count=request.count)

    # -- Heartbeat -----------------------------------------------------------

    def Ping(self, request, context):
        # Fault seam: the daemon's heartbeat-loss → Ready-flip →
        # recovery contract (tests/test_resilience.py) is exercised by
        # injecting a raise/hang/corrupt HERE instead of killing a VSP
        # process and hoping the timing lands.
        faults.fire("vsp.ping")
        healthy = True
        instance_id = self._instance_id
        if self._cp_agent is not None:
            try:
                healthy = self._cp_agent.healthy()
            except Exception:
                log.warning("cp-agent unreachable; reporting unhealthy")
                healthy = False
        with self._lock:
            dp = self._dataplane
        degradations = [
            s for s in (getattr(dp, "shaping_state", "ok"),
                        getattr(dp, "flow_state", "ok"))
            if s != "ok"
        ] if dp is not None else []
        return faults.wrap(
            "vsp.ping",
            pb.PingResponse(healthy=healthy, instance_id=instance_id,
                            degradations=degradations))

    def _chip_health(self, n_local: int) -> Dict[int, bool]:
        """Cache reads only — the caches are fed by background threads
        (_start_health_watchers), never refreshed on this path."""
        with self._lock:
            agent = dict(self._agent_health_cache)
            deep = self._deep_health_cache
        if deep is None:
            return agent
        return {i: agent.get(i, True) and deep.get(i, True) for i in
                set(agent) | set(deep)} or dict(deep)

    # -- background health watchers ------------------------------------------

    def _start_health_watchers(self) -> None:
        """Event-driven agent health + periodic deep health, both off the
        request path. The cp-agent watcher SUBSCRIBES to pushed
        health-change events (native event loop, monitor.cpp), so a
        vanished chip flips GetDevices health within the agent's inotify
        latency; it falls back to 2 s polling when the stream drops."""
        with self._lock:
            # Restartable: a prior stop_watchers() leaves dead threads and
            # a set Event behind — prune and clear so a re-Init (server
            # restart, retried Init RPC) gets live watchers again. The
            # lock also keeps two concurrent Inits from double-spawning.
            self._watcher_threads = [
                t for t in self._watcher_threads if t.is_alive()
            ]
            if self._watcher_threads:
                return
            self._watcher_stop.clear()
            if self._cp_agent is not None:
                t = threading.Thread(
                    target=self._agent_watch_loop, daemon=True, name="vsp-agent-health"
                )
                t.start()
                self._watcher_threads.append(t)
            if os.environ.get("DPU_DEEP_HEALTH") == "1":
                t = threading.Thread(
                    target=self._deep_health_loop, daemon=True, name="vsp-deep-health"
                )
                t.start()
                self._watcher_threads.append(t)

    def stop_watchers(self) -> None:
        self._watcher_stop.set()

    def _agent_watch_loop(self) -> None:
        from .cp_agent_client import CpAgentError

        while not self._watcher_stop.is_set():
            try:
                for event in self._cp_agent.subscribe(stop=self._watcher_stop):
                    if event.get("chips_reset"):
                        # A chip vanished and came back (dedicated `reset`
                        # event, or a baseline carrying resets that
                        # happened during our reconnect window): re-probe
                        # its compute path now — it may have bounced
                        # through a reset and hold stale state even
                        # though the device node reopened.
                        self.resets_seen += 1
                        log.warning(
                            "cp-agent reported chip reset (%s); re-probing",
                            event.get("chips_reset"),
                        )
                        self._deep_health_kick.set()
                    if "chips" in event:
                        with self._lock:
                            self._agent_health_cache = dict(event["chips"])
            except CpAgentError as e:
                log.debug("cp-agent event stream down (%s); poll fallback", e)
            except Exception:
                log.exception("cp-agent watcher error; poll fallback")
            # Stream gone: take one poll sample, then retry the stream.
            if self._watcher_stop.wait(2.0):
                return
            try:
                health = self._cp_agent.chip_health()
                with self._lock:
                    self._agent_health_cache = health
            except Exception:
                # Broad on purpose (like the stream handler above): a
                # malformed agent frame raises JSONDecodeError/ValueError
                # out of chip_health, and ANY escape here kills the
                # watcher thread — freezing the health cache forever.
                log.debug("cp-agent poll sample failed; stale health "
                          "cache until the stream returns", exc_info=True)

    def _deep_health_loop(self) -> None:
        """The MXU burn probe (compute-path liveness, the OCTEON mailbox
        analogue), refreshed every DEEP_HEALTH_TTL in the background so
        a slow/compiling burn can never freeze the device inventory."""
        while not self._watcher_stop.is_set():
            result: Dict[int, bool] = {}
            try:
                import math

                from ..parallel.fabric_probe import burn_example_args
                from ..parallel.pallas_burn import best_burn_step

                import jax

                fn = best_burn_step()
                args = burn_example_args()
                for i, dev in enumerate(jax.local_devices()):
                    try:
                        sig = float(
                            jax.device_put(fn(*[jax.device_put(a, dev) for a in args]))
                        )
                        result[i] = math.isfinite(sig)
                    except Exception:
                        result[i] = False
            except Exception:
                log.debug("deep health probe unavailable; skipping")
                result = {}
            with self._lock:
                self._deep_health_cache = result
            # TTL sleep, interruptible by stop OR a reset kick (chip
            # bounced: re-probe immediately, don't wait out the TTL).
            deadline = self.DEEP_HEALTH_TTL
            step = 0.2
            waited = 0.0
            while waited < deadline:
                if self._watcher_stop.wait(step):
                    return
                if self._deep_health_kick.is_set():
                    self._deep_health_kick.clear()
                    break
                waited += step

    # -- BridgePort ----------------------------------------------------------

    def CreateBridgePort(self, request, context):
        name = request.bridge_port.name
        mac = request.bridge_port.spec.mac_address
        mac_str = ":".join(f"{b:02x}" for b in mac) if mac else ""
        with self._lock:
            dp = self._dataplane
        if dp is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "tpuvsp not initialised")
        try:
            dp.attach_port(name, mac_str)
        except Exception as e:
            log.warning("attach_port(%s) failed: %s", name, e)
            context.abort(grpc.StatusCode.INTERNAL, f"attach failed: {e}")
        return bp.BridgePort(name=name)

    def DeleteBridgePort(self, request, context):
        with self._lock:
            dp = self._dataplane
        if dp is not None:
            dp.detach_port(request.name)
        return empty_pb2.Empty()

    # -- NetworkFunction -----------------------------------------------------

    def CreateNetworkFunction(self, request, context):
        with self._lock:
            dp = self._dataplane
        if dp is not None:
            # CR-declared policies ride the same automated path as the
            # chain itself (reference VSPs program their flow engines
            # from CreateNetworkFunction: marvell main.go:515-588).
            policies = [
                {"pref": p.pref, "action": p.action, "proto": p.proto,
                 "src_ip": p.src_ip, "dst_ip": p.dst_ip,
                 "src_port": p.src_port, "dst_port": p.dst_port}
                for p in request.policies
            ]
            dp.wire_network_function(request.input, request.output,
                                     policies=policies,
                                     transparent=request.transparent)
        return empty_pb2.Empty()

    def DeleteNetworkFunction(self, request, context):
        with self._lock:
            dp = self._dataplane
        if dp is not None:
            dp.unwire_network_function(request.input, request.output)
        return empty_pb2.Empty()


def main() -> None:  # container entrypoint (bindata/vsp/tpu/99.vsp-pod.yaml)
    from .server import VspServer

    logging.basicConfig(level=logging.INFO)
    cp_agent = None
    agent_sock = os.environ.get("DPU_CP_AGENT_SOCKET")
    if agent_sock:
        from .cp_agent_client import CpAgentClient

        cp_agent = CpAgentClient(agent_sock)
    server = VspServer(TpuVsp(cp_agent_client=cp_agent))
    server.start()
    server.wait()


if __name__ == "__main__":
    main()
