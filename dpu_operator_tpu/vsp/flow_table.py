"""Match-action flow table for the fabric dataplane.

The role P4Runtime tables play for the Intel VSP (cmd/intelvsp/p4rt-ctl
programs match-action entries — set-pipe, table add/del/dump — into the
FXP pipeline via infrap4d; p4rtclient.go:612-939 builds phy-port/host-VF/
NF rule sets) and OVS flows play for Marvell (main.go:515-588): a
programmable per-port rule table that classifies fabric traffic and
applies an action.

Backend: the kernel's own nf_tables engine, programmed over raw netlink
(cni/nftnl.py) — no `nft`, no `tc` classifier modules, no OVS/P4
userspace anywhere. Each bridge port gets a netdev-family ingress chain;
rules are nft expression programs (ethertype/proto/ip/port loads + cmp,
counter, verdict/fwd/dup/limit). The kernel is the single source of
truth: `list()` dumps rules back out of it — the operator's rule spec
rides in NFTA_RULE_USERDATA (the nft CLI's comment slot) and the
packet/byte counters come live from the counter expression, the
counter-read surface p4rt-ctl exposes.

Rule model:
    pref       — evaluation order (lower first); unique per port.
    match      — any of src_mac/dst_mac, proto (tcp/udp/icmp/sctp),
                 src_ip/dst_ip (CIDR ok), src_port/dst_port.
    action     — drop | accept | redirect:<dev> | mirror:<dev>
                 | police:<mbit>

`accept` terminates the chain (exempts the flow from later rules);
`mirror` duplicates to the target and CONTINUES, so a broader rule
below it still applies — the classic tap semantics.
"""

from __future__ import annotations

import ipaddress
import json
import logging
import re
import socket as socketlib
import struct
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..cni import nftnl

log = logging.getLogger(__name__)

TABLE = "dpu_fabric"
MAX_PREF = 32000
_PROTOS = {"tcp": 6, "udp": 17, "icmp": 1, "sctp": 132}
_MAC_RE = re.compile(r"^[0-9a-f]{2}(:[0-9a-f]{2}){5}$", re.IGNORECASE)


class FlowError(RuntimeError):
    pass


@dataclass
class FlowRule:
    pref: int
    action: str
    src_mac: Optional[str] = None
    dst_mac: Optional[str] = None
    proto: Optional[str] = None
    src_ip: Optional[str] = None
    dst_ip: Optional[str] = None
    src_port: Optional[int] = None
    dst_port: Optional[int] = None

    def validate(self) -> None:
        if not 1 <= self.pref <= MAX_PREF:
            raise FlowError(f"pref {self.pref} outside [1, {MAX_PREF}]")
        for name, mac in (("src_mac", self.src_mac), ("dst_mac", self.dst_mac)):
            if mac is not None and not _MAC_RE.match(mac):
                raise FlowError(f"{name} {mac!r} is not a MAC address")
        if self.proto is not None and self.proto not in _PROTOS:
            raise FlowError(f"proto {self.proto!r} not one of {sorted(_PROTOS)}")
        for name, cidr in (("src_ip", self.src_ip), ("dst_ip", self.dst_ip)):
            if cidr is not None:
                try:
                    net = ipaddress.ip_network(cidr, strict=False)
                    if net.version != 4:
                        raise FlowError(f"{name}: only IPv4 matches supported")
                except ValueError as e:
                    raise FlowError(f"{name} {cidr!r}: {e}") from e
        for name, port in (("src_port", self.src_port), ("dst_port", self.dst_port)):
            if port is not None:
                if self.proto not in ("tcp", "udp", "sctp"):
                    raise FlowError(f"{name} requires proto tcp/udp/sctp")
                if not 0 < port < 65536:
                    raise FlowError(f"{name} {port} outside [1, 65535]")
        kind = self.action.split(":", 1)[0]
        if kind in ("redirect", "mirror"):
            if ":" not in self.action or not self.action.split(":", 1)[1]:
                raise FlowError(f"{kind} action needs a device: {kind}:<dev>")
        elif kind == "police":
            import math

            try:
                mbit = float(self.action.split(":", 1)[1])
                if not math.isfinite(mbit) or mbit <= 0:
                    raise ValueError
            except (IndexError, ValueError):
                raise FlowError("police action needs a positive finite mbit "
                                "rate: police:<mbit>") from None
        elif kind not in ("drop", "accept"):
            raise FlowError(
                f"action {self.action!r} not drop/accept/redirect:<dev>/"
                "mirror:<dev>/police:<mbit>")

    # -- nft expression program ---------------------------------------------

    def _needs_ip(self) -> bool:
        return any((self.proto, self.src_ip, self.dst_ip,
                    self.src_port, self.dst_port))

    def to_nft_exprs(self) -> List[bytes]:
        """The rule as an nf_tables expression program: loads + compares
        narrowing the match, then counter, then the action."""
        self.validate()
        n = nftnl
        exprs: List[bytes] = []
        if self.src_mac:
            exprs += [n.payload_load(n.NFT_PAYLOAD_LL_HEADER, 6, 6),
                      n.cmp_eq(bytes.fromhex(self.src_mac.replace(":", "")))]
        if self.dst_mac:
            exprs += [n.payload_load(n.NFT_PAYLOAD_LL_HEADER, 0, 6),
                      n.cmp_eq(bytes.fromhex(self.dst_mac.replace(":", "")))]
        if self._needs_ip():
            # Ethertype guard: network/transport loads are meaningless on
            # non-IPv4 frames (ARP would otherwise false-match).
            exprs += [n.payload_load(n.NFT_PAYLOAD_LL_HEADER, 12, 2),
                      n.cmp_eq(b"\x08\x00")]
        if self.proto:
            exprs += [n.payload_load(n.NFT_PAYLOAD_NETWORK_HEADER, 9, 1),
                      n.cmp_eq(bytes([_PROTOS[self.proto]]))]
        for cidr, offset in ((self.src_ip, 12), (self.dst_ip, 16)):
            if not cidr:
                continue
            net = ipaddress.ip_network(cidr, strict=False)
            exprs.append(n.payload_load(n.NFT_PAYLOAD_NETWORK_HEADER, offset, 4))
            if net.prefixlen < 32:
                exprs.append(n.bitwise_mask(4, net.netmask.packed))
            exprs.append(n.cmp_eq(net.network_address.packed))
        for port, offset in ((self.src_port, 0), (self.dst_port, 2)):
            if port is None:
                continue
            exprs += [n.payload_load(n.NFT_PAYLOAD_TRANSPORT_HEADER, offset, 2),
                      n.cmp_eq(struct.pack(">H", port))]
        exprs.append(n.counter())
        kind, _, arg = self.action.partition(":")
        if kind == "drop":
            exprs.append(n.verdict(n.NF_DROP))
        elif kind == "accept":
            exprs.append(n.verdict(n.NF_ACCEPT))
        elif kind in ("redirect", "mirror"):
            try:
                exprs += n.fwd_to(arg) if kind == "redirect" else n.dup_to(arg)
            except OSError as e:
                # if_nametoindex on a vanished/typo'd device: a
                # CLI-grade error, not a raw OSError traceback.
                raise FlowError(f"{kind} target: no such netdev {arg!r}") from e
        elif kind == "police":
            exprs += [n.limit_over_mbit(float(arg)), n.verdict(n.NF_DROP)]
        return exprs

    def spec(self) -> Dict:
        return {k: v for k, v in self.__dict__.items() if v is not None}


def bridge_ports(bridge: str) -> List[str]:
    """Enslaved ports of a bridge (sysfs brif), for bridge-wide rule
    programming — the pipeline-scope p4rt-ctl tables have."""
    import os

    brif = f"/sys/class/net/{bridge}/brif"
    if not os.path.isdir(brif):
        raise FlowError(f"{bridge} is not a bridge (no {brif})")
    return sorted(os.listdir(brif))


class FlowTable:
    """Rule programming + readback for one netdev's ingress hook.

    add() is read-then-insert across two netlink transactions; the
    process-wide lock below serializes concurrent adds from the
    AUTOMATED path (VSP port attach + NF wiring run on gRPC worker
    threads). A concurrent `fabric-ctl` in another process can still
    interleave — that is the operator racing their own operator, the
    same exposure `nft` CLI batches have."""

    _add_lock = threading.Lock()

    def __init__(self, dev: str):
        self.dev = dev
        try:
            socketlib.if_nametoindex(dev)
        except OSError as e:
            raise FlowError(f"no such netdev {dev}") from e

    def _chain(self) -> str:
        return self.dev  # one ingress chain per port, named after it

    def _our_rules(self, nft: "nftnl.Nft") -> List[Dict]:
        """Kernel rules carrying our userdata spec, in evaluation order;
        foreign rules (no parseable spec) are left alone everywhere."""
        out = []
        for r in nft.dump_rules(TABLE, self._chain()):
            try:
                spec = json.loads(r["userdata"].decode())
            except (ValueError, UnicodeDecodeError):
                continue
            if not isinstance(spec, dict) or "pref" not in spec:
                continue  # foreign userdata that merely parses as JSON
            out.append({**spec, "handle": r["handle"],
                        "packets": r.get("packets"), "bytes": r.get("bytes")})
        return out

    def add(self, rule: FlowRule) -> None:
        exprs = rule.to_nft_exprs()  # validates first
        with self._add_lock, nftnl.Nft() as nft:
            existing = self._our_rules(nft)
            if any(r["pref"] == rule.pref for r in existing):
                raise FlowError(
                    f"pref {rule.pref} already programmed on {self.dev}")
            nft.ensure_table(TABLE)
            nft.ensure_ingress_chain(TABLE, self._chain(), self.dev)
            # Evaluation order IS list order: insert before the first
            # rule with a higher pref, else append.
            before = next((r["handle"] for r in existing
                           if r["pref"] > rule.pref), None)
            try:
                nft.add_rule(TABLE, self._chain(), exprs,
                             userdata=json.dumps(rule.spec()).encode(),
                             before_handle=before)
            except nftnl.NftError as e:
                raise FlowError(f"rule add on {self.dev}: {e}") from e

    def delete(self, pref: int) -> None:
        with nftnl.Nft() as nft:
            match = [r for r in self._our_rules(nft) if r["pref"] == pref]
            if not match:
                raise FlowError(f"no rule pref {pref} on {self.dev}")
            nft.delete_rule(TABLE, self._chain(), match[0]["handle"])

    def delete_many(self, prefs) -> int:
        """Delete our rules matching `prefs` in ONE dump + ONE atomic
        transaction (the NF-teardown path removes several rules per
        port; per-pref delete() would re-dump the chain each time).
        Missing prefs are not an error — teardown must be idempotent."""
        want = set(prefs)
        with self._add_lock, nftnl.Nft() as nft:
            handles = [r["handle"] for r in self._our_rules(nft)
                       if r["pref"] in want]
            nft.delete_rules(TABLE, self._chain(), handles)
            return len(handles)

    def flush(self) -> int:
        """Remove every rule WE programmed (foreign rules survive); the
        per-port chain is dropped when it ends up empty."""
        with nftnl.Nft() as nft:
            ours = self._our_rules(nft)
            nft.delete_rules(TABLE, self._chain(),
                             [r["handle"] for r in ours])
            if ours and not nft.dump_rules(TABLE, self._chain()):
                nft.delete_chain(TABLE, self._chain())
            return len(ours)

    def list(self, stats: bool = False) -> List[Dict]:
        """Rules as the KERNEL holds them, in evaluation order, with live
        packet/byte counters when stats=True."""
        with nftnl.Nft() as nft:
            rules = []
            for r in self._our_rules(nft):
                r.pop("handle")
                if not stats:
                    r.pop("packets", None)
                    r.pop("bytes", None)
                rules.append(r)
            return rules
