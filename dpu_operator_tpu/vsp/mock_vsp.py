"""Mock VSP — the in-process fake powering the integration-test tier.

Counterpart of reference internal/daemon/vendor-specific-plugins/mock-vsp/
mockvsp.go: Init returns a loopback OPI address (mockvsp.go:31-37),
GetDevices returns four fake fabric endpoints (mockvsp.go:39-50), and the
bridge/NF operations are recorded no-ops so tests can assert the call
sequence (mockvsp.go:52-70)."""

from __future__ import annotations

import logging
import threading
from typing import List, Tuple

from google.protobuf import empty_pb2

from ..dpu_api import services
from ..dpu_api.gen import bridge_port_pb2 as bp
from ..dpu_api.gen import dpu_api_pb2 as pb

log = logging.getLogger(__name__)


class MockVsp(
    services.LifeCycleServicer,
    services.NetworkFunctionServicer,
    services.DeviceServicer,
    services.HeartbeatServicer,
    services.BridgePortServicer,
):
    def __init__(self, opi_ip: str = "127.0.0.1", opi_port: int = 50151, num_devices: int = 4):
        self._opi = (opi_ip, opi_port)
        self._lock = threading.Lock()
        import uuid as _uuid

        self._instance_id = _uuid.uuid4().hex
        self._num_endpoints = num_devices
        self.init_calls: List[Tuple[int, str]] = []
        self.bridge_ports: List[str] = []
        self.network_functions: List[Tuple[str, str]] = []
        self.fail_bridge_port = False  # failure injection (rollback tests)
        self.degradations: List[str] = []  # injectable dataplane state

    # LifeCycle
    def Init(self, request, context):
        with self._lock:
            self.init_calls.append((request.dpu_mode, request.dpu_identifier))
        log.info("mock vsp Init(mode=%s, id=%s)", request.dpu_mode, request.dpu_identifier)
        return pb.IpPort(ip=self._opi[0], port=self._opi[1])

    # Devices
    def GetDevices(self, request, context):
        resp = pb.DeviceListResponse()
        with self._lock:
            n = self._num_endpoints
        for i in range(n):
            dev_id = f"mock-ep{i}"
            d = resp.devices[dev_id]
            d.id = dev_id
            d.health = pb.HEALTHY
            d.topology.coords = f"{i},0,0"
            d.topology.numa_node = 0
            d.backing = f"mockdev{i}"
        return resp

    def SetNumEndpoints(self, request, context):
        with self._lock:
            self._num_endpoints = request.count
        return pb.EndpointCount(count=request.count)

    # Heartbeat
    def Ping(self, request, context):
        with self._lock:
            degradations = list(self.degradations)
        return pb.PingResponse(healthy=True, instance_id=self._instance_id,
                               degradations=degradations)

    # NetworkFunction
    def CreateNetworkFunction(self, request, context):
        with self._lock:
            self.network_functions.append((request.input, request.output))
        return empty_pb2.Empty()

    def DeleteNetworkFunction(self, request, context):
        with self._lock:
            try:
                self.network_functions.remove((request.input, request.output))
            except ValueError:
                pass
        return empty_pb2.Empty()

    # BridgePort
    def CreateBridgePort(self, request, context):
        with self._lock:
            if self.fail_bridge_port:
                # Failure injection for rollback tests (the reference's
                # fakes are similarly steerable, hostsidemanager_test.go).
                raise RuntimeError("injected bridge-port failure")
            self.bridge_ports.append(request.bridge_port.name)
        return bp.BridgePort(name=request.bridge_port.name)

    def DeleteBridgePort(self, request, context):
        with self._lock:
            try:
                self.bridge_ports.remove(request.name)
            except ValueError:
                pass
        return empty_pb2.Empty()
