"""Mock VSP container entrypoint (bindata/vsp/mock/99.vsp-pod.yaml)."""

from __future__ import annotations

import logging

from .mock_vsp import MockVsp
from .server import VspServer


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    server = VspServer(MockVsp())
    server.start()
    server.wait()


if __name__ == "__main__":
    main()
