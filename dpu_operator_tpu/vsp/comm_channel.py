"""IPv6 link-local control channel between the host and DPU daemons.

The reference's VSPs bring up fixed link-local addresses on the device
that physically joins the two sides — Marvell puts fe80::1/fe80::2 on
the SDP interfaces (marvell/main.go:32-52), NetSec on the backplane VFs
(intel-netsec/main.go:131-177 configureCommChannelIPs, via
vspnetutils.EnableIPV6LinkLocal with optimistic DAD) — so the OPI/
heartbeat channel needs no DHCP, no routed subnet, and no discovery:
the address is a constant of the contract and the scope id pins it to
the right link.

TPU-native mapping: the "device that joins the two sides" is the fabric
uplink (DCN netdev on a TPU-VM, or the bridge uplink veth in the
2-cluster test topology). `DPU_COMM_CHANNEL_DEV` opts the tpuvsp in;
Init then advertises `[fe80::...:1%25dev]` — always the URI-encoded
scope form, since both our binder and dialer are gRPC (see
setup_comm_channel for why the reference's raw-% DPU-side form would
corrupt hex-prefixed device names here).
"""

from __future__ import annotations

import logging
import subprocess
import time

log = logging.getLogger(__name__)

# Fixed per-side addresses, the reference's IPv6AddrDpu/IPv6AddrHost
# analogues (distinct from the kernel's EUI-64 autoconf range).
DPU_LINK_LOCAL = "fe80::d1:1"
HOST_LINK_LOCAL = "fe80::d1:2"


class CommChannelError(RuntimeError):
    pass


def _run(argv: list) -> str:
    r = subprocess.run(argv, capture_output=True, text=True)
    if r.returncode != 0:
        raise CommChannelError(f"{' '.join(argv)}: {r.stderr.strip()}")
    return r.stdout


def enable_ipv6_link_local(ifname: str, addr: str, netns: str | None = None) -> None:
    """Static link-local + optimistic DAD on `ifname` (reference
    vspnetutils.EnableIPV6LinkLocal, common/vspnetutils.go:78-127):
    optimistic DAD lets the address be used immediately instead of
    waiting out duplicate-address detection."""
    ns = ["ip", "netns", "exec", netns] if netns else []
    # sysctl splits keys on every dot; interface names with dots (VLAN
    # devices like eth0.100) must be escaped as eth0/100.
    sysctl_if = ifname.replace(".", "/")
    for key, value in (
        (f"net.ipv6.conf.{sysctl_if}.disable_ipv6", "0"),
        # The channel addresses are fixed constants of the contract on a
        # point-to-point link — duplicates are impossible by design, and
        # DAD cannot even run until the peer side exists (no carrier),
        # which would leave the address tentative and unbindable exactly
        # when the VSP needs to bring the OPI server up first. Disable
        # DAD outright; optimistic_dad stays as a fallback for kernels
        # that ignore accept_dad on the interface.
        (f"net.ipv6.conf.{sysctl_if}.accept_dad", "0"),
        (f"net.ipv6.conf.{sysctl_if}.optimistic_dad", "1"),
    ):
        try:
            _run(ns + ["sysctl", "-w", f"{key}={value}"])
        except CommChannelError as e:
            # optimistic_dad is a CONFIG_IPV6_OPTIMISTIC_DAD option;
            # proceed without it (DAD just takes ~1 s longer).
            log.debug("sysctl %s: %s", key, e)
    _run(ns + ["ip", "link", "set", "dev", ifname, "up"])
    def _already(e: Exception) -> bool:
        return "File exists" in str(e) or "already assigned" in str(e)

    try:
        _run(ns + ["ip", "-6", "addr", "add", f"{addr}/64", "dev", ifname,
                   "scope", "link", "optimistic"])
    except CommChannelError as e:
        if not _already(e):
            # Retry without the optimistic flag (kernel without the option).
            try:
                _run(ns + ["ip", "-6", "addr", "add", f"{addr}/64", "dev",
                           ifname, "scope", "link"])
            except CommChannelError as e2:
                if not _already(e2):
                    raise


def wait_link_local_ready(ifname: str, addr: str, timeout: float = 5.0,
                          netns: str | None = None) -> None:
    """Wait for DAD to finish (address leaves `tentative`) — the
    reference's readiness waits (vspnetutils.go:301-359)."""
    ns = ["ip", "netns", "exec", netns] if netns else []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = _run(ns + ["ip", "-6", "addr", "show", "dev", ifname])
        # Strictly non-tentative ON OUR LINE (other addresses on the
        # device, e.g. the kernel's EUI-64 autoconf one, may still be
        # doing DAD — irrelevant). Binding a listener on a tentative
        # address fails (EADDRNOTAVAIL); with accept_dad=0 the address
        # never goes tentative, so this loop only matters on kernels
        # where the sysctl was refused and real DAD has to finish.
        for line in out.splitlines():
            if f"{addr}/" in line and "tentative" not in line:
                return
        time.sleep(0.05)
    raise CommChannelError(f"{addr} on {ifname} never left tentative")


def setup_comm_channel(ifname: str, dpu_mode: bool,
                       netns: str | None = None) -> str:
    """Bring up this side's fixed link-local address and return the
    connection string for the dpu-api IpPort.

    The scope separator is ALWAYS the URI-encoded `%25`: gRPC
    percent-decodes the whole authority, so a raw `%` followed by a
    device name that happens to start with a hex pair (`%cc...`) is
    silently decoded into a garbage byte and getaddrinfo fails. The
    reference returns a raw-`%` form for the DPU side
    (intel-netsec/main.go:163-168) because its server binds with Go's
    net.Listen; ours binds with grpc too, so both sides take the
    encoded form."""
    addr = DPU_LINK_LOCAL if dpu_mode else HOST_LINK_LOCAL
    enable_ipv6_link_local(ifname, addr, netns=netns)
    wait_link_local_ready(ifname, addr, netns=netns)
    return f"[{addr}%25{ifname}]"


def peer_target(ifname: str) -> str:
    """gRPC target the HOST side dials to reach the DPU-side OPI server
    over the channel (scope id is the LOCAL egress interface)."""
    return f"[{DPU_LINK_LOCAL}%25{ifname}]"
