from .server import VspServer
from .mock_vsp import MockVsp

__all__ = ["VspServer", "MockVsp"]
