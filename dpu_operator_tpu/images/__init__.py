"""Image manager — maps logical image keys to container image refs.

Counterpart of reference internal/images/ (images.go:5-14, env_manager.go:14-33,
dummy_manager.go:11-26). Image refs arrive as env vars on the operator and
daemon pods; DummyImageManager serves tests.
"""

from __future__ import annotations

import os
from typing import Dict, Mapping

# Logical image keys (reference images.go:5-14, plus the TPU VSP).
DPU_DAEMON_IMAGE = "dpu_daemon"
VSP_IMAGE_TPU = "tpu_vsp"
VSP_IMAGE_MOCK = "mock_vsp"
VSP_IMAGE_INTEL = "intel_ipu"
VSP_IMAGE_MARVELL = "marvell_dpu"
VSP_IMAGE_NETSEC = "intel_netsec"
NRI_IMAGE = "network_resources_injector"

ALL_KEYS = (
    DPU_DAEMON_IMAGE,
    VSP_IMAGE_TPU,
    VSP_IMAGE_MOCK,
    VSP_IMAGE_INTEL,
    VSP_IMAGE_MARVELL,
    VSP_IMAGE_NETSEC,
    NRI_IMAGE,
)

_ENV_PREFIX = "DPU_IMAGE_"


class ImageManager:
    """Interface: get_image(key) -> ref (reference images.go:16-19)."""

    def get_image(self, key: str) -> str:
        raise NotImplementedError


class EnvImageManager(ImageManager):
    """Reads DPU_IMAGE_<KEY> env vars (reference env_manager.go:14-33)."""

    def __init__(self, env: Mapping[str, str] | None = None):
        self._env = dict(env if env is not None else os.environ)

    def get_image(self, key: str) -> str:
        var = _ENV_PREFIX + key.upper()
        val = self._env.get(var)
        if not val:
            raise KeyError(f"image env var {var} not set")
        return val


class DummyImageManager(ImageManager):
    """Deterministic refs for tests (reference dummy_manager.go:11-26)."""

    def get_image(self, key: str) -> str:
        return f"{key}-mock-image"


def merge_vars_with_images(
    mgr: ImageManager,
    template_vars: Dict[str, str],
    keys=ALL_KEYS,
) -> Dict[str, str]:
    """Feed image refs into the manifest template vars, failing loudly on a
    missing ref (reference images.go:42-60 MergeVarsWithImages returns an
    error rather than rendering a broken manifest later)."""
    out = dict(template_vars)
    for key in keys:
        out[f"Image_{key}"] = mgr.get_image(key)
    return out
