"""Deterministic fault injection — the seam the self-healing plane is
proven against.

The recovery machinery in the serving plane (replica supervision,
requeue, watchdog, circuit breaker) is only trustworthy if every
recovery path is exercised by a fault we *chose*, at a step we *chose*
— not by whatever a flaky CI box happens to do. This module is that
choice: a process-global, test-controllable ``FaultPlan`` holding
specs keyed by **site** strings (``"replica0.step"``,
``"queue.submit"``, ``"fabric.connect"``, ``"vsp.ping"``). Production
code threads two tiny hooks through its seams:

    faults.fire(site)            # before the operation: may raise/hang
    faults.wrap(site, result)    # after it: may corrupt the return

Both are near-free no-ops until a plan is installed (one module-global
read), so the seams stay in the shipped code — the same binary that
serves traffic is the one chaos tests break on demand.

Triggers are deterministic by default: ``at_calls`` fires on exact
1-based call indices of the site, ``times`` caps total firings, and
``probability`` draws from the plan's own seeded RNG — a chaos run is
replayable from its seed. Behaviors: raise a chosen exception, hang
for N seconds (a wedged device step), or corrupt/None a return value.

``FaultyExecutor`` wraps any serving ``Executor`` so a single replica
of a pool can be targeted by name (sites ``{site}.step/.submit/
.collect/.reset``) without the scheduler knowing anything happened.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence

from .obs import trace as _obs_trace


class FaultError(RuntimeError):
    """Default exception type for injected raises."""


class FaultSpec:
    """One armed fault at one site. Mutable only through its plan."""

    __slots__ = ("site", "exc", "hang_s", "corrupt", "at_calls",
                 "probability", "times", "fired")

    def __init__(self, site: str, *, exc=None, hang_s: float = 0.0,
                 corrupt: Optional[Callable[[Any], Any]] = None,
                 at_calls: Optional[Sequence[int]] = None,
                 probability: Optional[float] = None,
                 times: Optional[int] = None):
        if exc is None and not hang_s and corrupt is None:
            raise ValueError(f"fault at {site!r} has no behavior "
                             f"(exc / hang_s / corrupt)")
        if at_calls is not None and probability is not None:
            raise ValueError("at_calls and probability are exclusive "
                             "triggers")
        self.site = site
        self.exc = exc
        self.hang_s = float(hang_s)
        self.corrupt = corrupt
        self.at_calls = frozenset(int(c) for c in at_calls) \
            if at_calls is not None else None
        self.probability = probability
        self.times = times
        self.fired = 0

    def __repr__(self):
        how = ("raise" if self.exc is not None
               else f"hang {self.hang_s}s" if self.hang_s else "corrupt")
        return (f"FaultSpec({self.site!r}, {how}, at={self.at_calls}, "
                f"p={self.probability}, fired={self.fired})")


class FaultPlan:
    """All armed faults plus per-site call accounting. Thread-safe:
    seams fire from batcher/worker/transport threads concurrently."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._pending = threading.local()  # site -> spec, fire→wrap
        self.calls: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}
        self.fired_at: Dict[str, List[float]] = {}

    def inject(self, site: str, **kw) -> FaultSpec:
        spec = FaultSpec(site, **kw)
        with self._lock:
            self._specs.setdefault(site, []).append(spec)
        return spec

    def clear(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._specs.clear()
            else:
                self._specs.pop(site, None)

    def _record_fired(self, site: str, spec: FaultSpec,
                      extra: Optional[dict] = None) -> None:
        spec.fired += 1
        self.fired[site] = self.fired.get(site, 0) + 1
        self.fired_at.setdefault(site, []).append(time.monotonic())
        # Fault firings are span EVENTS in the same monotonic timeline
        # the serving spans live in: a flight-recorder snapshot can
        # order injection → detection → recovery without correlating
        # clocks. Recorded before a hang behavior sleeps (this runs at
        # arm time), so the event marks when the fault STARTED.
        behavior = ("raise" if spec.exc is not None
                    else "hang" if spec.hang_s else "corrupt")
        attrs = {"site": site, "behavior": behavior,
                 "hang_s": spec.hang_s or None}
        if extra:
            # Seam-site context (e.g. the shard plane's rank): the
            # flight recorder's per-rank `shards` section groups on
            # it, so a kill-one-shard post-mortem shows the fault
            # firing IN the victim rank's own tail.
            attrs.update(extra)
        _obs_trace.event("fault.fired", attrs=attrs)

    def _arm(self, site: str,
             attrs: Optional[dict] = None) -> Optional[FaultSpec]:
        """Count the call; return the first spec that triggers on it.
        raise/hang specs are recorded as fired here; a corrupt-only
        spec is recorded only when wrap() APPLIES it — a fire-only
        seam (queue.submit, fabric.*) never calls wrap, and a fault
        that did nothing must not report itself as injected (the
        bench treats fired_at as a kill's ground truth)."""
        with self._lock:
            n = self.calls.get(site, 0) + 1
            self.calls[site] = n
            for spec in self._specs.get(site, ()):
                if spec.times is not None and spec.fired >= spec.times:
                    continue
                if spec.at_calls is not None:
                    hit = n in spec.at_calls
                elif spec.probability is not None:
                    hit = self._rng.random() < spec.probability
                else:
                    hit = True
                if hit:
                    if spec.exc is not None or spec.hang_s:
                        self._record_fired(site, spec, extra=attrs)
                    return spec
            return None

    def fire(self, site: str,
             attrs: Optional[dict] = None) -> None:
        # Drop any corruption armed by a PREVIOUS fire whose operation
        # raised before wrap() could consume it — a stale pending spec
        # must never corrupt a later, un-targeted call (and must not
        # record a firing at a call it never armed).
        pend = getattr(self._pending, "by_site", None)
        if pend:
            pend.pop(site, None)
        spec = self._arm(site, attrs=attrs)
        if spec is None:
            return
        if spec.hang_s:
            time.sleep(spec.hang_s)
        if spec.exc is not None:
            exc = spec.exc
            if isinstance(exc, type):
                exc = exc(f"injected fault at {site}")
            raise exc
        if spec.corrupt is not None:
            # Defer to wrap(): the corruption applies to the seam's
            # RESULT, which doesn't exist yet at fire time.
            if not hasattr(self._pending, "by_site"):
                self._pending.by_site = {}
            self._pending.by_site[site] = spec

    def wrap(self, site: str, result):
        pend = getattr(self._pending, "by_site", None)
        spec = pend.pop(site, None) if pend else None
        if spec is not None and spec.corrupt is not None:
            with self._lock:
                self._record_fired(site, spec)
            return spec.corrupt(result)
        return result


# -- process-global plan -------------------------------------------------------

_plan: Optional[FaultPlan] = None
_plan_lock = threading.Lock()


def install(plan: Optional[FaultPlan] = None, seed: int = 0) -> FaultPlan:
    """Install (and return) the process-global plan. Idempotence is
    deliberate NOT provided: chaos tests own the lifecycle and a
    leaked plan between tests is a bug worth surfacing."""
    global _plan
    with _plan_lock:
        _plan = plan if plan is not None else FaultPlan(seed)
        return _plan


def uninstall() -> None:
    global _plan
    with _plan_lock:
        _plan = None


def active_plan() -> Optional[FaultPlan]:
    return _plan


def fire(site: str, attrs: Optional[dict] = None) -> None:
    """Seam hook, pre-operation. No-op unless a plan is installed.
    ``attrs`` merge into the fault.fired span event (site context the
    site string alone can't carry structurally — the shard plane
    passes its rank)."""
    p = _plan
    if p is not None:
        p.fire(site, attrs=attrs)


def wrap(site: str, result):
    """Seam hook, post-operation. No-op unless a plan is installed."""
    p = _plan
    if p is not None:
        return p.wrap(site, result)
    return result


@contextmanager
def injected(seed: int = 0):
    """``with faults.injected() as plan:`` — install for a scope,
    always uninstall (a leaked plan would bleed faults across tests)."""
    plan = install(seed=seed)
    try:
        yield plan
    finally:
        uninstall()


# -- the executor-seam wrapper -------------------------------------------------


class FaultyExecutor:
    """Wrap one serving Executor so its seam methods pass through
    named fault points: ``{site}.step``, ``{site}.submit``,
    ``{site}.collect``, ``{site}.reset``. Everything else (slots, d,
    pipelined, steps, …) delegates to the wrapped executor, so the
    scheduler and pool treat it as the replica it wraps — per-replica
    targeting is just a distinct ``site`` per wrapped executor."""

    def __init__(self, inner, site: str = "executor"):
        self.inner = inner
        self.site = site

    def step(self, x):
        fire(f"{self.site}.step")
        return wrap(f"{self.site}.step", self.inner.step(x))

    def reset(self) -> None:
        fire(f"{self.site}.reset")
        self.inner.reset()

    def submit(self, updates, **meta):
        # **meta forwards the diagnostic/guard kwargs (step,
        # request_ids, the KV executors' gen) untouched — the wrapper
        # must never change what the scheduler told the replica.
        fire(f"{self.site}.submit")
        return wrap(f"{self.site}.submit",
                    self.inner.submit(updates, **meta))

    def collect(self, handle):
        fire(f"{self.site}.collect")
        return wrap(f"{self.site}.collect", self.inner.collect(handle))

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, name):
        return getattr(self.inner, name)
