"""Serving plane: continuous batching, backpressure, drain, HTTP contract.

Tier-1 scope: the smoke test runs the REAL path end to end (HTTP →
AdmissionQueue → ContinuousBatcher → jitted infer_step on a jax mesh)
with a tiny model; the batching-vs-serial comparison and the overload
test are the acceptance evidence for ISSUE 2 (≥2× over serial batch=1,
bounded p99 + 503 shedding under 2× overload). Scheduler-plane timing
tests use SyntheticExecutor so CI-box noise cannot flake them; only the
sustained-load soak is marked slow.
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dpu_operator_tpu.serving import (AdmissionQueue, ContinuousBatcher,
                                      Draining, GenerateRequest,
                                      LocalExecutor, QueueFull,
                                      ServingServer, SyntheticExecutor,
                                      encode_prompt)

# One compiled model shared by every LocalExecutor test (compile cost is
# the dominant line item, so the real-model tests share one server).
MODEL = dict(S=1, d=8, h=8, E=1)


def _post(url, body, timeout=30.0):
    data = json.dumps(body).encode()
    try:
        r = urllib.request.urlopen(
            urllib.request.Request(url + "/v1/generate", data=data,
                                   headers={"Content-Type":
                                            "application/json"}),
            timeout=timeout)
        return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _closed_loop(url, clients, per_client, max_tokens, deadline_ms=30000):
    """clients threads, each `per_client` sequential requests; returns
    (wall_s, latencies_ms_of_200s, all_codes, headers_of_503s)."""
    lat, codes, h503 = [], [], []
    lock = threading.Lock()

    def run(c):
        for i in range(per_client):
            t0 = time.perf_counter()
            code, _, headers = _post(url, {"prompt": f"c{c}-{i}",
                                           "max_tokens": max_tokens,
                                           "deadline_ms": deadline_ms})
            ms = (time.perf_counter() - t0) * 1000
            with lock:
                codes.append(code)
                if code == 200:
                    lat.append(ms)
                elif code == 503:
                    h503.append(headers)

    ts = [threading.Thread(target=run, args=(c,)) for c in range(clients)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return time.perf_counter() - t0, lat, codes, h503


@pytest.fixture(scope="module")
def batched_server():
    ex = LocalExecutor(slots=8, **MODEL)
    srv = ServingServer([ex], max_queue_depth=64).start()
    yield srv
    srv.stop()


# -- smoke: the real path, end to end -----------------------------------------


def test_generate_http_roundtrip(batched_server):
    url = batched_server.url
    code, doc, _ = _post(url, {"prompt": "hello fabric", "max_tokens": 6})
    assert code == 200, doc
    assert len(doc["tokens"]) == 6
    assert all(0 <= t < MODEL["d"] for t in doc["tokens"])
    assert doc["truncated"] is False
    assert doc["timings"]["total_ms"] > 0

    # Deterministic prompt encoding → deterministic greedy decode.
    code2, doc2, _ = _post(url, {"prompt": "hello fabric",
                                 "max_tokens": 6})
    assert code2 == 200 and doc2["tokens"] == doc["tokens"]

    # prompt_vec path: explicit state vector, same contract.
    vec = encode_prompt("hello fabric", MODEL["d"])
    code3, doc3, _ = _post(url, {"prompt_vec": [float(v) for v in vec],
                                 "max_tokens": 6})
    assert code3 == 200 and doc3["tokens"] == doc["tokens"]

    assert urllib.request.urlopen(url + "/healthz").status == 200
    assert urllib.request.urlopen(url + "/readyz").status == 200
    metrics = urllib.request.urlopen(url + "/metrics").read().decode()
    assert ('serving_requests_total{code="200",outcome="ok",'
            'tenant="default"}' in metrics)
    assert "serving_batch_occupancy_bucket" in metrics
    assert "serving_queue_depth" in metrics
    assert "serving_request_seconds_bucket" in metrics
    # The decode-loop decomposition (ISSUE 3): device time and host
    # gap are separate series, and the derived overlap fraction is a
    # scrape-time gauge — the win must be visible in /metrics, not
    # just the bench artifact.
    assert "serving_step_device_seconds_bucket" in metrics
    assert "serving_host_gap_seconds_bucket" in metrics
    assert "serving_host_gap_fraction" in metrics


def test_generate_rejects_malformed(batched_server):
    url = batched_server.url
    for body, frag in (
        ({"max_tokens": 4}, "prompt"),
        ({"prompt": "x", "max_tokens": 0}, "max_tokens"),
        ({"prompt": "x", "max_tokens": "NaN"}, "numbers"),
        ({"prompt": "x", "deadline_ms": -5}, "deadline_ms"),
        # json accepts Infinity/NaN literals and Python floats overflow
        # Event.wait — all three must die in validation, not mid-slot.
        ({"prompt": "x", "deadline_ms": 1e13}, "deadline_ms"),
        ({"prompt": "x", "deadline_ms": float("inf")}, "deadline_ms"),
        ({"prompt": "x", "deadline_ms": float("nan")}, "deadline_ms"),
        ({"prompt_vec": [1.0, 2.0], "max_tokens": 4}, "prompt_vec"),
    ):
        code, doc, _ = _post(url, body)
        assert code == 400, (body, doc)
        assert frag in doc["error"], (body, doc)
    # Non-numeric prompt_vec raises TypeError inside np.asarray — must
    # still be a 400, not a dropped connection.
    code, doc, _ = _post(url, {"prompt_vec": {"a": 1}, "max_tokens": 2})
    assert code == 400, doc
    # Non-finite prompt_vec (json.loads accepts NaN/Infinity literals).
    code, doc, _ = _post(url, {"prompt_vec":
                               [float("nan")] * MODEL["d"],
                               "max_tokens": 2})
    assert code == 400 and "finite" in doc["error"], doc
    # Oversized body → 413 before buffering it.
    big = urllib.request.Request(url + "/v1/generate",
                                 data=b" " * ((1 << 20) + 1))
    try:
        urllib.request.urlopen(big, timeout=10)
        assert False, "oversized body must be rejected"
    except urllib.error.HTTPError as e:
        assert e.code == 413
    except OSError:
        pass  # server closed mid-send after replying; also a rejection
    req = urllib.request.Request(url + "/v1/generate", data=b"{nope")
    try:
        urllib.request.urlopen(req, timeout=10)
        assert False, "malformed JSON must not 200"
    except urllib.error.HTTPError as e:
        assert e.code == 400


# -- the continuous-batching win (ISSUE 2 acceptance) -------------------------


def test_continuous_batching_at_least_2x_serial():
    """≥2× the serial batch=1 baseline on req/s over the REAL HTTP
    path: same front-end, same queue, same scheduler — only the slot
    count differs. The executors carry a FIXED 4 ms per-step cost (the
    accelerator cost model: an MXU-bound decode step prices a full
    batch the same as one row — the premise continuous batching exists
    to exploit; a jitted CPU matmul scales with batch instead, which
    would measure the wrong substrate, and its dispatch overhead is
    too small to clear this harness's in-process GIL-bound HTTP
    ceiling). bench_serving measures the same pair plus the real
    jitted-model path."""
    step_s = 0.004
    batched = ServingServer([SyntheticExecutor(slots=8, d=16,
                                               step_time_s=step_s)],
                            max_queue_depth=128).start()
    serial = ServingServer([SyntheticExecutor(slots=1, d=16,
                                              step_time_s=step_s)],
                           max_queue_depth=128).start()
    try:
        # Warm both HTTP paths (first-request thread spin-up).
        _closed_loop(batched.url, 2, 2, 2)
        _closed_loop(serial.url, 2, 2, 2)
        wall_b, lat_b, codes_b, _ = _closed_loop(
            batched.url, clients=16, per_client=2, max_tokens=32,
            deadline_ms=120_000)
        wall_s, lat_s, codes_s, _ = _closed_loop(
            serial.url, clients=16, per_client=2, max_tokens=32,
            deadline_ms=120_000)
        assert all(c == 200 for c in codes_b), codes_b
        assert all(c == 200 for c in codes_s), codes_s
        rate_b = len(codes_b) / wall_b
        rate_s = len(codes_s) / wall_s
        assert rate_b >= 2.0 * rate_s, (
            f"continuous batching {rate_b:.1f} req/s vs serial "
            f"{rate_s:.1f} req/s — win below 2x")
    finally:
        batched.stop()
        serial.stop()


# -- backpressure: overload is shed, admitted latency stays bounded -----------


def test_overload_503_and_bounded_p99():
    """Under ~2x overload with a small queue: the excess gets 503 +
    Retry-After, every ADMITTED request finishes within deadline +
    step-granularity slack, the queue never exceeds its depth, and the
    server stays healthy. SyntheticExecutor pins the per-step cost so
    the arithmetic of 'overload' is deterministic.

    The step cost is deliberately FAT (20 ms): the executor's step is
    a wall-clock sleep, immune to CPU throttle, while the 16 client
    threads are GIL-bound python that IS throttled late in a long
    tier-1 run — with a 5 ms step (100 req/s capacity) a throttled
    client pool could fall under capacity and the storm never shed
    (seen once at ~66% of a full suite run). At 25 req/s capacity the
    clients stay ~an order of magnitude over it even throttled."""
    step_s = 0.02
    ex = SyntheticExecutor(slots=4, d=16, step_time_s=step_s)
    srv = ServingServer([ex], max_queue_depth=6,
                        default_deadline_s=2.0).start()
    try:
        deadline_ms = 2000.0
        wall, lat, codes, h503 = _closed_loop(
            srv.url, clients=16, per_client=4, max_tokens=8,
            deadline_ms=deadline_ms)
        n_ok = sum(1 for c in codes if c == 200)
        n_503 = sum(1 for c in codes if c == 503)
        assert n_ok + n_503 == len(codes), codes  # no 5xx crashes
        assert n_ok >= 1
        assert n_503 >= 1, "2x overload over a 6-deep queue must shed"
        # Bounded tail for admitted work: deadline + one decode step +
        # hand-off grace, NOT proportional to offered load.
        assert max(lat) < deadline_ms + 8 * step_s * 1000 + 500, lat
        # Retry-After rides every 503.
        assert all("Retry-After" in h for h in h503), h503
        # Still alive and ready after the storm.
        assert urllib.request.urlopen(srv.url + "/healthz").status == 200
        metrics = urllib.request.urlopen(
            srv.url + "/metrics").read().decode()
        assert 'outcome="queue_full"' in metrics
    finally:
        srv.stop()


def test_queue_full_and_expiry_shed():
    """AdmissionQueue unit seam: depth is a hard bound; entries whose
    deadline lapsed while queued are failed at pop, not decoded."""
    q = AdmissionQueue(max_depth=2, retry_after_s=3.0)
    now = time.monotonic()
    mk = lambda dl: GenerateRequest(
        prompt_vec=np.zeros(4, np.float32), max_tokens=1, deadline=dl)
    q.submit(mk(now + 10))
    stale = mk(now - 0.001)
    q.submit(stale)
    with pytest.raises(QueueFull) as ei:
        q.submit(mk(now + 10))
    assert ei.value.retry_after_s == 3.0
    got = q.get_many(5)
    assert len(got) == 1 and got[0].deadline > now
    assert stale.done and "deadline" in stale.error
    assert q.shed_expired == 1
    q.begin_drain()
    with pytest.raises(Draining):
        q.submit(mk(now + 10))


def test_deadline_mid_decode_truncates():
    """A request whose deadline lands mid-decode returns 200 with the
    tokens it earned, marked truncated — bounded latency without
    throwing away paid-for work."""
    ex = SyntheticExecutor(slots=2, d=8, step_time_s=0.02)
    srv = ServingServer([ex]).start()
    try:
        code, doc, _ = _post(srv.url, {"prompt": "slow",
                                       "max_tokens": 500,
                                       "deadline_ms": 150})
        assert code == 200, doc
        assert doc["truncated"] is True
        assert 1 <= len(doc["tokens"]) < 500
    finally:
        srv.stop()


# -- drain: SIGTERM lets in-flight work finish, new work bounces --------------


def _drain_fixture_server(step_s=0.02):
    from dpu_operator_tpu import vars as v
    from dpu_operator_tpu.drain import Drainer
    from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster

    client = InMemoryClient(InMemoryCluster())
    client.create({"apiVersion": "v1", "kind": "Node",
                   "metadata": {"name": "serve-n0"}})
    client.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "fabric-pod", "namespace": "default"},
        "spec": {"nodeName": "serve-n0", "containers": [
            {"name": "c", "image": "i", "resources": {
                "requests": {v.DPU_RESOURCE_NAME: "1"}}}]},
    })
    ex = SyntheticExecutor(slots=2, d=8, step_time_s=step_s)
    srv = ServingServer([ex], drainer=Drainer(client),
                        node_name="serve-n0").start()
    return srv, client


def test_drain_completes_inflight_rejects_new_and_cordons():
    srv, client = _drain_fixture_server()
    try:
        result = {}

        def long_request():
            result["resp"] = _post(srv.url, {"prompt": "inflight",
                                             "max_tokens": 40,
                                             "deadline_ms": 30000})

        t = threading.Thread(target=long_request)
        t.start()
        deadline = time.monotonic() + 5
        while srv.pool.active() == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert srv.pool.active() == 1

        drained = threading.Thread(target=srv.begin_drain, args=(30.0,))
        drained.start()
        deadline = time.monotonic() + 5
        while not srv.draining and time.monotonic() < deadline:
            time.sleep(0.005)
        # New work during drain → 503, while the in-flight request is
        # still decoding.
        code, doc, headers = _post(srv.url, {"prompt": "late",
                                             "max_tokens": 2})
        assert code == 503 and doc["error"] == "draining"
        assert "Retry-After" in headers
        try:
            urllib.request.urlopen(srv.url + "/readyz")
            assert False, "readyz must be 503 while draining"
        except urllib.error.HTTPError as e:
            assert e.code == 503
        # Liveness stays green (kubelet must not kill a draining pod).
        assert urllib.request.urlopen(srv.url + "/healthz").status == 200

        t.join(timeout=30)
        drained.join(timeout=30)
        assert not drained.is_alive()
        code, doc, _ = result["resp"]
        assert code == 200 and len(doc["tokens"]) == 40, doc

        # The wired drain.Drainer ran: node cordoned, fabric pod evicted.
        node = client.get("v1", "Node", None, "serve-n0")
        assert node["spec"]["unschedulable"] is True
        assert client.get_or_none(
            "v1", "Pod", "default", "fabric-pod") is None
    finally:
        srv.stop()


def test_keepalive_connection_survives_early_503():
    """HTTP/1.1 keep-alive: paths that reply before the handler logic
    (drain 503, POST 404) must still have consumed the request body, or
    the leftover bytes desync every later request on the connection.
    urllib opens fresh connections and cannot catch this; a persistent
    http.client connection does."""
    import http.client

    ex = SyntheticExecutor(slots=2, d=8)
    srv = ServingServer([ex]).start()
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        body = json.dumps({"prompt": "x", "max_tokens": 2}).encode()
        # 404 path with a body, same connection reused after.
        conn.request("POST", "/nope", body=body)
        assert conn.getresponse().read() is not None
        conn.request("POST", "/v1/generate", body=body)
        r = conn.getresponse()
        assert r.status == 200, r.read()
        r.read()
        # Drain 503 path, then the connection must still be usable.
        srv.queue.begin_drain()
        srv._draining.set()
        conn.request("POST", "/v1/generate", body=body)
        r = conn.getresponse()
        assert r.status == 503, r.read()
        r.read()
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        assert r.status == 200
        r.read()
    finally:
        conn.close()
        srv.stop()


def test_sigterm_triggers_drain():
    srv, client = _drain_fixture_server(step_s=0.005)
    prev = srv.install_signal_handlers(stop_after=False)
    try:
        code, _, _ = _post(srv.url, {"prompt": "pre", "max_tokens": 2})
        assert code == 200
        os.kill(os.getpid(), signal.SIGTERM)
        assert srv.wait_drained(timeout=10)
        code, doc, _ = _post(srv.url, {"prompt": "post", "max_tokens": 2})
        assert code == 503 and doc["error"] == "draining"
        assert client.get("v1", "Node", None,
                          "serve-n0")["spec"]["unschedulable"] is True
    finally:
        signal.signal(signal.SIGTERM, prev)
        srv.stop()


# -- scheduler plane ----------------------------------------------------------


def test_batch_reforms_at_step_boundaries():
    """Continuous means continuous: a late request joins while an early
    long request is still decoding (no wait for the batch to clear),
    and the early one's finish frees its slot for the next waiter."""
    ex = SyntheticExecutor(slots=2, d=8, step_time_s=0.01)
    srv = ServingServer([ex]).start()
    try:
        out = {}

        def go(name, tokens):
            out[name] = _post(srv.url, {"prompt": name,
                                        "max_tokens": tokens,
                                        "deadline_ms": 30000})

        long_t = threading.Thread(target=go, args=("long", 60))
        long_t.start()
        time.sleep(0.1)  # long is mid-decode now
        t0 = time.perf_counter()
        go("short", 3)
        short_wall = time.perf_counter() - t0
        long_t.join(timeout=30)
        assert out["short"][0] == 200 and out["long"][0] == 200
        # The short request finished while long was still running: its
        # wall time is a few steps, nowhere near long's remaining ~0.5s.
        assert short_wall < 0.3, short_wall
    finally:
        srv.stop()


def test_replica_pool_spreads_load():
    """Two replicas over one queue: both take work — and a MIXED pool
    works, each batcher picking its loop off its own executor (one
    pipelined, one sync)."""
    ex0 = SyntheticExecutor(slots=1, d=8, step_time_s=0.002,
                            pipelined=True)
    ex1 = SyntheticExecutor(slots=1, d=8, step_time_s=0.002)
    srv = ServingServer([ex0, ex1], max_queue_depth=64).start()
    try:
        wall, lat, codes, _ = _closed_loop(srv.url, clients=4,
                                           per_client=4, max_tokens=8)
        assert all(c == 200 for c in codes)
        assert ex0.steps > 0 and ex1.steps > 0
    finally:
        srv.stop()


def test_mixed_feature_dim_pool_rejected():
    """prompt_vec width is validated once at the front door, so every
    replica must agree on d — a mixed pool would admit vectors some
    replica cannot hold."""
    with pytest.raises(ValueError, match="feature dim"):
        ServingServer([SyntheticExecutor(slots=1, d=16),
                       SyntheticExecutor(slots=1, d=8)])


def test_executor_failure_fails_requests_not_server():
    """Crash-only contract: a persistently failing replica costs the
    request its retry budget (500 retries_exhausted after max_attempts
    replica failures), never the server — the supervisor restarts the
    replica under backoff and /healthz stays green throughout (one
    replica is still nominally live, just flapping)."""

    class Exploding(SyntheticExecutor):
        def step(self, x):
            raise RuntimeError("replica lost")

    srv = ServingServer(
        [Exploding(slots=2, d=8)],
        pool_opts=dict(restart_backoff_s=0.01, poll_s=0.005)).start()
    try:
        code, doc, _ = _post(srv.url, {"prompt": "x", "max_tokens": 2,
                                       "deadline_ms": 5000})
        assert code == 500 and doc["error"] == "retries_exhausted"
        # The request rode max_attempts (3) replica failures; each one
        # restarted the replica rather than wedging the pool.
        assert sum(srv.pool.restarts) >= 2
        assert urllib.request.urlopen(srv.url + "/healthz").status == 200
    finally:
        srv.stop()


def test_idle_slots_do_not_steal_moe_capacity_on_ep_mesh():
    """A request's decode must not depend on how many batch slots are
    idle. On an ep-sharded mesh under capacity pressure (C=1 here), a
    zero-filled idle slot's uniform router softmax would win bucket
    slot 0 by stream priority and drop a real token's MoE dispatch —
    infer_step masks idle rows out of routing entirely, so the same
    prompt decodes identically in any slot position at any occupancy."""
    import jax

    from dpu_operator_tpu.parallel.train_step import (init_params,
                                                      shard_params)
    from dpu_operator_tpu.serving.infer import (make_infer_step,
                                                serving_mesh)

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices for an ep=2 mesh")
    mesh = serving_mesh(shape={"ep": 2})
    params = shard_params(init_params(S=1, d=8, h=8, E=2, seed=2), mesh)
    step = make_infer_step(mesh, capacity_factor=1.0)
    rng = np.random.RandomState(9)
    for _ in range(8):  # vectors routing to both experts get exercised
        r = rng.randn(8).astype(np.float32)
        first = np.zeros((4, 8), np.float32)
        first[0] = r
        last = np.zeros((4, 8), np.float32)
        last[3] = r
        y_first = np.asarray(step(params, first))
        y_last = np.asarray(step(params, last))
        np.testing.assert_allclose(y_first[0], y_last[3],
                                   rtol=1e-5, atol=1e-6)
        # Idle rows stay exactly zero — the scheduler's slot contract.
        assert not y_first[1:].any() and not y_last[:3].any()


# -- device-resident pipelined decode (ISSUE 3) -------------------------------


def _trace_reqs(n, d, toks):
    """A fixed admitted trace: distinct deterministic prompts, long
    deadlines (equivalence must not depend on deadline races)."""
    return [GenerateRequest(prompt_vec=encode_prompt(f"trace-{i}", d),
                            max_tokens=toks,
                            deadline=time.monotonic() + 600.0)
            for i in range(n)]


def _drive_trace(ex, reqs):
    """Run a preloaded request trace through a ContinuousBatcher (no
    HTTP — the loop under test is the scheduler/executor pair)."""
    q = AdmissionQueue(max_depth=len(reqs) + 1)
    b = ContinuousBatcher(ex, q)
    for r in reqs:
        q.submit(r)
    b.start()
    try:
        for r in reqs:
            assert r.wait(timeout=60), "request lost"
    finally:
        b.stop()
        ex.close()
    return b


def test_pipelined_sync_token_equivalence_synthetic():
    """Same trace, same seed: token streams are identical between the
    sync loop and the pipelined loop. Admissions land one step later
    in the pipelined loop (and slot assignment may differ), but rows
    decode independently — a shifted admission changes WHEN a token is
    computed, never what it is. More requests than slots so the
    one-step-delayed hand-off is actually exercised."""
    streams = {}
    for pipelined in (False, True):
        ex = SyntheticExecutor(slots=4, d=16, seed=3,
                               pipelined=pipelined)
        reqs = _trace_reqs(12, 16, 6)
        _drive_trace(ex, reqs)
        streams[pipelined] = [(r.error, list(r.tokens)) for r in reqs]
    assert all(e is None for e, _ in streams[True])
    assert streams[False] == streams[True]


def test_pipelined_sync_token_equivalence_local():
    """ISSUE 3 acceptance: identical decode token streams between the
    PR 2 synchronous LocalExecutor and the device-resident pipelined
    one for the same admitted trace, on the real jitted model."""
    streams = {}
    for mode in ("sync", "pipelined"):
        ex = LocalExecutor(slots=4, mode=mode, **MODEL)
        reqs = _trace_reqs(8, MODEL["d"], 5)
        _drive_trace(ex, reqs)
        streams[mode] = [(r.error, list(r.tokens)) for r in reqs]
    assert all(e is None for e, _ in streams["pipelined"])
    assert streams["sync"] == streams["pipelined"]


def test_pipelined_executor_overlaps_host_work():
    """The two-phase contract's point: with device step cost D and
    host work H per step, K pipelined steps cost ≈ K·max(D, H), not
    K·(D+H). SyntheticExecutor's worker thread is the controlled
    device; the host sleeps between submit and collect."""
    D = H = 0.03
    K = 8
    ex = SyntheticExecutor(slots=2, d=8, step_time_s=D, pipelined=True)
    try:
        ex.reset()
        h_prev = None
        t0 = time.perf_counter()
        for _ in range(K):
            h = ex.submit([])
            time.sleep(H)  # scheduler-bookkeeping stand-in
            if h_prev is not None:
                ex.collect(h_prev)
            h_prev = h
        ex.collect(h_prev)
        wall = time.perf_counter() - t0
    finally:
        ex.close()
    # Serial cost would be K*(D+H) = 0.48 s; overlapped ≈ K*max + one
    # step ≈ 0.27 s. The 0.8x line keeps CI-noise margin from both.
    assert wall < 0.8 * K * (D + H), wall
    assert wall >= K * max(D, H) - 0.01, wall


def test_pipelined_admission_lands_one_step_later():
    """The documented semantic delta: submit(k) precedes retire(k-1),
    so a slot freed by step k-1 is admitted at step k+1 — one stale
    step decodes per slot hand-off. Two 3-token requests through one
    slot: exactly 6 steps sync, exactly 8 pipelined (one hand-off step
    after each completion)."""
    counts = {}
    for pipelined, want in ((False, 6), (True, 8)):
        ex = SyntheticExecutor(slots=1, d=8, pipelined=pipelined)
        _drive_trace(ex, _trace_reqs(2, 8, 3))
        deadline = time.monotonic() + 5
        while ex.steps < want and time.monotonic() < deadline:
            time.sleep(0.002)
        counts[pipelined] = ex.steps
    assert counts == {False: 6, True: 8}, counts


def test_handoff_step_runs_with_finished_slot_zeroed():
    """A finished request must not ride the hand-off step as a ghost:
    submit(k) precedes retire(k-1), so without zero-ahead the step
    overlapping a completion would run the finished request's stale
    nonzero row — content-derived row masking (infer.py's any(x != 0))
    would count it ACTIVE, and on an ep-sharded mesh under capacity
    pressure a ghost competitor can evict a real row's MoE dispatch.
    Completion is predictable for the max_tokens path, so the
    scheduler zeroes the retiring row in the same scatter that
    dispatches the overlapping step. Asserted on the recorded batch
    states: from the hand-off step on, the finished slot is exactly
    zero at step time."""

    class Recording(SyntheticExecutor):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.states = []

        def step(self, x):
            self.states.append(np.array(x))
            return super().step(x)

    ex = Recording(slots=2, d=8, pipelined=True)
    a = GenerateRequest(prompt_vec=encode_prompt("short", 8),
                        max_tokens=2,
                        deadline=time.monotonic() + 600.0)
    b = GenerateRequest(prompt_vec=encode_prompt("long", 8),
                        max_tokens=5,
                        deadline=time.monotonic() + 600.0)
    _drive_trace(ex, [a, b])
    assert a.error is None and b.error is None
    assert len(a.tokens) == 2 and len(b.tokens) == 5
    states = ex.states
    assert len(states) >= 6, len(states)
    # Step 1 runs both admitted prompts; A (slot 0) finishes at the
    # retire overlapping step 3 — so steps 3+ must carry slot 0 as
    # exact zeros, pre-zeroed by the scatter, never A's stale state.
    assert states[0][0].any() and states[0][1].any()
    for k in (2, 3, 4):
        assert not states[k][0].any(), f"ghost row rode step {k + 1}"
    # B's own hand-off step (6) gets the same treatment.
    assert not states[5][1].any()


def test_admit_failure_reports_real_error():
    """The slot index binds BEFORE the guarded region: a request whose
    prompt_vec cannot land in a slot must fail with the real error
    (the old `i = free.pop(0)` inside the try raised NameError in its
    own handler, masking the cause) and must not leak the queue's
    inflight accounting or block later admissions."""
    ex = SyntheticExecutor(slots=2, d=8)
    q = AdmissionQueue(max_depth=8)
    b = ContinuousBatcher(ex, q)
    bad = GenerateRequest(prompt_vec=np.zeros(3, np.float32),
                          max_tokens=2,
                          deadline=time.monotonic() + 30)
    good = GenerateRequest(prompt_vec=np.zeros(8, np.float32),
                           max_tokens=1,
                           deadline=time.monotonic() + 30)
    q.submit(bad)
    q.submit(good)
    b._admit()
    assert bad.done and "admission failed" in bad.error, bad.error
    assert "NameError" not in bad.error
    assert not good.done
    assert q.inflight() == 0  # mark_placed ran for BOTH pops
    assert b.active == 1 and good in b._slots


# -- sustained load (slow tier) -----------------------------------------------


@pytest.mark.slow
def test_sustained_open_loop_holds_p99():
    """Open-loop arrivals at ~60% of measured capacity for several
    seconds: p99 stays near service time (no queue growth), nothing is
    shed. The bench's open-loop overload counterpart lives in
    serving/bench_serving.py."""
    step_s = 0.004
    tokens = 8
    ex = SyntheticExecutor(slots=4, d=16, step_time_s=step_s)
    srv = ServingServer([ex], max_queue_depth=64).start()
    try:
        capacity = ex.slots / (tokens * step_s)     # req/s, fully batched
        rate = 0.4 * capacity
        lat, codes = [], []
        lock = threading.Lock()

        def one(i):
            t0 = time.perf_counter()
            code, _, _ = _post(srv.url, {"prompt": f"s{i}",
                                         "max_tokens": tokens,
                                         "deadline_ms": 10000})
            with lock:
                codes.append(code)
                lat.append((time.perf_counter() - t0) * 1000)

        threads = []
        n = int(rate * 4.0)
        t0 = time.perf_counter()
        for i in range(n):
            target = t0 + i / rate
            time.sleep(max(0.0, target - time.perf_counter()))
            th = threading.Thread(target=one, args=(i,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=30)
        # Half-capacity load: overwhelmingly served. A small shed slice
        # is contention bursts on a shared box (steps cost more than
        # their sleep when the CPU is oversubscribed), not queue growth;
        # sustained overload sheds ~40% (see bench_serving).
        assert all(c in (200, 503) for c in codes), codes
        ok_frac = sum(1 for c in codes if c == 200) / len(codes)
        assert ok_frac >= 0.9, f"shed {1 - ok_frac:.2%} at half capacity"
        from dpu_operator_tpu.serving.bench_serving import nearest_rank

        lat = sorted(l for l, c in zip(lat, codes) if c == 200)
        p99 = nearest_rank(lat, 0.99)
        # Bounded means near service time, not near the 10 s deadline a
        # growing queue would march toward. Service time is taken from
        # the server's OWN step histogram (p95), not the nominal sleep:
        # on a contended box a 4 ms sleep-step costs several times that
        # (GIL + scheduler), and a bound that ignores it flakes exactly
        # when CI is busiest. Queue growth still blows past this within
        # the window — it compounds per request, contention doesn't.
        step_p95_s = srv.registry.quantile(
            "serving_step_seconds", 0.95, {"replica": "replica0"}) or step_s
        service_ms = tokens * max(step_s, step_p95_s) * 1000
        assert p99 < 10 * service_ms + 600, (p99, service_ms)
    finally:
        srv.stop()
