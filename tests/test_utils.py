"""Utils-layer tests (counterpart of reference internal/utils tests:
filesystem_mode_detector_test.go, path_manager behavior)."""

import os

from dpu_operator_tpu.utils import (
    FilesystemMode,
    FilesystemModeDetector,
    Flavour,
    PathManager,
    fileutils,
)


def test_path_manager_rerooting(tmp_path):
    pm = PathManager(root=str(tmp_path))
    assert pm.cni_server_socket().startswith(str(tmp_path))
    assert pm.vendor_plugin_socket().endswith("vendor-plugin/vendor-plugin.sock")
    assert pm.device_plugin_socket().endswith("device-plugins/tpu-dpu.sock")


def test_path_manager_cni_host_dir_matrix(tmp_path):
    pm = PathManager(root=str(tmp_path))
    assert pm.cni_host_dir(Flavour.MICROSHIFT, FilesystemMode.PACKAGE).endswith(
        "opt/cni/bin"
    )
    assert pm.cni_host_dir(Flavour.OPENSHIFT, FilesystemMode.IMAGE).endswith(
        "var/lib/cni/bin"
    )


def test_ensure_socket_dir_perms(tmp_path):
    pm = PathManager(root=str(tmp_path))
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    st = os.stat(os.path.dirname(sock))
    assert (st.st_mode & 0o077) == 0


def test_filesystem_mode_detector(tmp_path):
    det = FilesystemModeDetector(root=str(tmp_path))
    assert det.detect() == FilesystemMode.PACKAGE
    os.makedirs(tmp_path / "run", exist_ok=True)
    (tmp_path / "run" / "ostree-booted").touch()
    assert det.detect() == FilesystemMode.IMAGE


def test_fileutils_copy_and_executable(tmp_path):
    src = tmp_path / "src.bin"
    src.write_text("#!/bin/sh\necho hi\n")
    dst = str(tmp_path / "sub" / "dst.bin")
    fileutils.copy_file(str(src), dst)
    fileutils.make_executable(dst)
    assert os.access(dst, os.X_OK)


def test_atomic_write(tmp_path):
    p = str(tmp_path / "d" / "f.json")
    fileutils.atomic_write(p, "{}")
    assert open(p).read() == "{}"
