"""Utils-layer tests (counterpart of reference internal/utils tests:
filesystem_mode_detector_test.go, path_manager behavior)."""

import os

from dpu_operator_tpu.utils import (
    FilesystemMode,
    FilesystemModeDetector,
    Flavour,
    PathManager,
    fileutils,
)


def test_path_manager_rerooting(tmp_path):
    pm = PathManager(root=str(tmp_path))
    assert pm.cni_server_socket().startswith(str(tmp_path))
    assert pm.vendor_plugin_socket().endswith("vendor-plugin/vendor-plugin.sock")
    assert pm.device_plugin_socket().endswith("device-plugins/tpu-dpu.sock")


def test_path_manager_cni_host_dir_matrix(tmp_path):
    pm = PathManager(root=str(tmp_path))
    assert pm.cni_host_dir(Flavour.MICROSHIFT, FilesystemMode.PACKAGE).endswith(
        "opt/cni/bin"
    )
    assert pm.cni_host_dir(Flavour.OPENSHIFT, FilesystemMode.IMAGE).endswith(
        "var/lib/cni/bin"
    )


def test_ensure_socket_dir_perms(tmp_path):
    pm = PathManager(root=str(tmp_path))
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    st = os.stat(os.path.dirname(sock))
    assert (st.st_mode & 0o077) == 0


def test_filesystem_mode_detector(tmp_path):
    det = FilesystemModeDetector(root=str(tmp_path))
    assert det.detect() == FilesystemMode.PACKAGE
    os.makedirs(tmp_path / "run", exist_ok=True)
    (tmp_path / "run" / "ostree-booted").touch()
    assert det.detect() == FilesystemMode.IMAGE


def test_fileutils_copy_and_executable(tmp_path):
    src = tmp_path / "src.bin"
    src.write_text("#!/bin/sh\necho hi\n")
    dst = str(tmp_path / "sub" / "dst.bin")
    fileutils.copy_file(str(src), dst)
    fileutils.make_executable(dst)
    assert os.access(dst, os.X_OK)


def test_atomic_write(tmp_path):
    p = str(tmp_path / "d" / "f.json")
    fileutils.atomic_write(p, "{}")
    assert open(p).read() == "{}"


# -- fabric MTU policy (utils/mtu.py) ----------------------------------------


def test_resolve_fabric_mtu_default_is_veth_max(monkeypatch):
    """No override, no uplink: the bridge only carries intra-node
    traffic, where the veth maximum is the measured win (BASELINE.md
    bridge-gap diagnosis: 12.9 -> 17.8 Gbps)."""
    from dpu_operator_tpu.utils.mtu import VETH_MAX_MTU, resolve_fabric_mtu

    monkeypatch.delenv("DPU_FABRIC_MTU", raising=False)
    assert resolve_fabric_mtu() == VETH_MAX_MTU


def test_resolve_fabric_mtu_env_override(monkeypatch):
    from dpu_operator_tpu.utils.mtu import resolve_fabric_mtu

    monkeypatch.setenv("DPU_FABRIC_MTU", "8896")
    assert resolve_fabric_mtu() == 8896


def test_resolve_fabric_mtu_junk_env_ignored(monkeypatch):
    """A junk override must never break pod attach — log and fall
    through to the next policy tier."""
    from dpu_operator_tpu.utils.mtu import VETH_MAX_MTU, resolve_fabric_mtu

    monkeypatch.setenv("DPU_FABRIC_MTU", "jumbo")
    assert resolve_fabric_mtu() == VETH_MAX_MTU
    monkeypatch.setenv("DPU_FABRIC_MTU", "100")  # below IPv4 minimum
    assert resolve_fabric_mtu() == VETH_MAX_MTU


def test_resolve_fabric_mtu_follows_uplink(monkeypatch, tmp_path):
    """With an uplink the first hop is the binding constraint (gVNIC on
    a TPU-VM caps at 8896); frames above it would fragment or drop."""
    from dpu_operator_tpu.utils.mtu import VETH_MAX_MTU, resolve_fabric_mtu

    monkeypatch.delenv("DPU_FABRIC_MTU", raising=False)
    sysdir = tmp_path / "sys" / "class" / "net" / "gvnic0"
    os.makedirs(sysdir)
    (sysdir / "mtu").write_text("8896\n")
    assert resolve_fabric_mtu("gvnic0", root=str(tmp_path)) == 8896
    # Unreadable uplink fails SAFE (1500): guessing high would silently
    # drop every frame between the guess and the truth.
    from dpu_operator_tpu.utils.mtu import FAIL_SAFE_MTU

    assert VETH_MAX_MTU  # imported above; uplink tier never returns it blind
    assert resolve_fabric_mtu("missing0", root=str(tmp_path)) == FAIL_SAFE_MTU


def test_resolve_fabric_mtu_override_clamped_to_uplink(monkeypatch, tmp_path):
    """An override the uplink can't carry must not size pod veths above
    what the bridge can forward — oversized frames drop silently at L2
    (no ICMP), a bulk-TCP-only blackhole."""
    from dpu_operator_tpu.utils.mtu import resolve_fabric_mtu

    sysdir = tmp_path / "sys" / "class" / "net" / "gvnic0"
    os.makedirs(sysdir)
    (sysdir / "mtu").write_text("8896\n")
    monkeypatch.setenv("DPU_FABRIC_MTU", "9500")
    assert resolve_fabric_mtu("gvnic0", root=str(tmp_path)) == 8896
    # Override below the uplink MTU is honored as-is.
    monkeypatch.setenv("DPU_FABRIC_MTU", "4000")
    assert resolve_fabric_mtu("gvnic0", root=str(tmp_path)) == 4000
    # No uplink: override wins unclamped.
    monkeypatch.setenv("DPU_FABRIC_MTU", "9500")
    assert resolve_fabric_mtu() == 9500


def test_resolve_fabric_mtu_unclamped_for_uplink_applier(monkeypatch, tmp_path):
    """clamp_to_uplink=False returns the raw override — the VSP applies
    it TO the uplink (ensure_bridge), so pre-clamping to the boot-time
    MTU would make raising the uplink impossible."""
    from dpu_operator_tpu.utils.mtu import FAIL_SAFE_MTU, resolve_fabric_mtu

    sysdir = tmp_path / "sys" / "class" / "net" / "gvnic0"
    os.makedirs(sysdir)
    (sysdir / "mtu").write_text("1460\n")  # gVNIC boot default
    monkeypatch.setenv("DPU_FABRIC_MTU", "8896")
    assert resolve_fabric_mtu(
        "gvnic0", root=str(tmp_path), clamp_to_uplink=False
    ) == 8896
    # The clamped (default) resolution — what per-attach veth sizing
    # uses — still tracks the uplink's current value.
    assert resolve_fabric_mtu("gvnic0", root=str(tmp_path)) == 1460
    # Override with an UNREADABLE uplink fails safe even when clamping.
    monkeypatch.setenv("DPU_FABRIC_MTU", "9500")
    assert resolve_fabric_mtu("gone0", root=str(tmp_path)) == FAIL_SAFE_MTU
