"""Operator control-plane tests — the real reconcilers run in-process
against the in-memory cluster (the shape of reference
internal/controller/dpuoperatorconfig_controller_test.go:45-80 with
DummyImageManager)."""

import time

import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.api import v1
from dpu_operator_tpu.controller.main import build_manager
from dpu_operator_tpu.controller.nri import NetworkResourcesInjector
from dpu_operator_tpu.images import DummyImageManager
from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster, get_condition


def wait_for(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return predicate()


@pytest.fixture
def mgr_and_client():
    client = InMemoryClient(InMemoryCluster())
    mgr = build_manager(client, DummyImageManager())
    mgr.start()
    yield mgr, client
    mgr.stop()


def test_config_reconcile_renders_operands(mgr_and_client):
    mgr, client = mgr_and_client
    client.create(v1.new_dpu_operator_config())

    assert wait_for(
        lambda: client.get_or_none("apps/v1", "DaemonSet", v.NAMESPACE, "dpu-daemon")
        is not None
    ), "daemon DaemonSet not rendered"
    ds = client.get("apps/v1", "DaemonSet", v.NAMESPACE, "dpu-daemon")
    tmpl = ds["spec"]["template"]["spec"]
    assert tmpl["nodeSelector"] == {"dpu": "true"}
    assert tmpl["containers"][0]["image"] == "dpu_daemon-mock-image"
    # spec.mode / spec.logLevel reach the daemon as env (mode defaults
    # to auto; the daemon applies it as a detection override).
    env = {e["name"]: e.get("value") for e in tmpl["containers"][0]["env"]}
    assert env["DPU_MODE"] == "auto"
    assert env["DPU_LOG_LEVEL"] == "0"

    # Both NF NADs (reference ensureNetworkFunctioNAD :327-348).
    for nad_name in ("dpunfcni-conf", v.DEFAULT_HOST_NAD_NAME):
        assert wait_for(
            lambda n=nad_name: client.get_or_none(
                "k8s.cni.cncf.io/v1", "NetworkAttachmentDefinition", v.NAMESPACE, n
            )
            is not None
        ), f"NAD {nad_name} not rendered"
    nad = client.get(
        "k8s.cni.cncf.io/v1", "NetworkAttachmentDefinition", v.NAMESPACE, "dpunfcni-conf"
    )
    assert (
        nad["metadata"]["annotations"]["k8s.v1.cni.cncf.io/resourceName"]
        == v.DPU_RESOURCE_NAME
    )

    # NRI deployment + webhook config.
    assert wait_for(
        lambda: client.get_or_none(
            "apps/v1", "Deployment", v.NAMESPACE, "network-resources-injector"
        )
        is not None
    )

    # Ready condition on the config CR.
    assert wait_for(
        lambda: (
            get_condition(
                client.get(
                    v1.GROUP_VERSION, v1.KIND_DPU_OPERATOR_CONFIG,
                    v.NAMESPACE, v.DPU_OPERATOR_CONFIG_NAME,
                ),
                "Ready",
            )
            or {}
        ).get("status")
        == "True"
    )


def test_config_deletion_cleans_up(mgr_and_client):
    mgr, client = mgr_and_client
    client.create(v1.new_dpu_operator_config())
    assert wait_for(
        lambda: client.get_or_none("apps/v1", "DaemonSet", v.NAMESPACE, "dpu-daemon")
        is not None
    )
    client.delete(
        v1.GROUP_VERSION, v1.KIND_DPU_OPERATOR_CONFIG, v.NAMESPACE,
        v.DPU_OPERATOR_CONFIG_NAME,
    )
    # Finalizer runs → operands removed → CR gone.
    assert wait_for(
        lambda: client.get_or_none("apps/v1", "DaemonSet", v.NAMESPACE, "dpu-daemon")
        is None
    ), "DaemonSet survived config deletion"
    assert wait_for(
        lambda: client.get_or_none(
            v1.GROUP_VERSION, v1.KIND_DPU_OPERATOR_CONFIG, v.NAMESPACE,
            v.DPU_OPERATOR_CONFIG_NAME,
        )
        is None
    ), "config CR not released by finalizer"


def test_dpu_reconciler_launches_and_cleans_vsp_pod(mgr_and_client):
    mgr, client = mgr_and_client
    dpu = v1.new_data_processing_unit("tpu-v5e-w0-dpu", "TPU v5e", True, "node-a")
    dpu["metadata"]["labels"] = {"dpu.tpu.io/vendor": "tpu"}
    client.create(dpu)
    pod_name = "vsp-tpu-node-a"
    assert wait_for(
        lambda: client.get_or_none("v1", "Pod", v.NAMESPACE, pod_name) is not None
    ), "VSP pod not created"
    pod = client.get("v1", "Pod", v.NAMESPACE, pod_name)
    assert pod["spec"]["nodeName"] == "node-a"
    assert pod["spec"]["containers"][0]["image"] == "tpu_vsp-mock-image"
    # Fabric policy env rendered into the VSP pod (same values the
    # daemonset gets): uplink/MTU sizing + the endpoint-share budget.
    env = {e["name"]: e.get("value") for e in pod["spec"]["containers"][0]["env"]}
    assert {"DPU_FABRIC_UPLINK", "DPU_FABRIC_MTU", "DPU_FABRIC_GBPS"} <= set(env)

    client.delete(v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE,
                  "tpu-v5e-w0-dpu")
    assert wait_for(
        lambda: client.get_or_none("v1", "Pod", v.NAMESPACE, pod_name) is None
    ), "VSP pod not cleaned up after DPU removal"


def test_dpuconfig_propagates_num_endpoints(mgr_and_client):
    mgr, client = mgr_and_client
    dpu = v1.new_data_processing_unit("tpu-x-dpu", "TPU v5e", True, "node-a")
    dpu["metadata"]["labels"] = {"dpu.tpu.io/vendor": "tpu"}
    client.create(dpu)
    client.create(
        v1.new_data_processing_unit_config("tune", num_endpoints=16)
    )
    assert wait_for(
        lambda: client.get(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, "tpu-x-dpu"
        )["metadata"]
        .get("annotations", {})
        .get("config.tpu.io/num-endpoints")
        == "16"
    )


def test_sfc_cluster_reconciler_sets_accepted(mgr_and_client):
    mgr, client = mgr_and_client
    sfc = v1.new_service_function_chain(
        "chain-a", network_functions=[{"name": "fw", "image": "img"}]
    )
    client.create(sfc)
    assert wait_for(
        lambda: (
            get_condition(
                client.get(
                    v1.GROUP_VERSION, v1.KIND_SERVICE_FUNCTION_CHAIN, v.NAMESPACE,
                    "chain-a",
                ),
                "Accepted",
            )
            or {}
        ).get("status")
        == "True"
    )


# -- NRI ---------------------------------------------------------------------


def _nad(name, resource=None, namespace=v.NAMESPACE):
    obj = {
        "apiVersion": "k8s.cni.cncf.io/v1",
        "kind": "NetworkAttachmentDefinition",
        "metadata": {"name": name, "namespace": namespace},
    }
    if resource:
        obj["metadata"]["annotations"] = {"k8s.v1.cni.cncf.io/resourceName": resource}
    return obj


def test_nri_injects_resources_for_double_attachment():
    client = InMemoryClient(InMemoryCluster())
    client.create(_nad("dpunfcni-conf", v.DPU_RESOURCE_NAME))
    injector = NetworkResourcesInjector(client)
    pod = {
        "metadata": {
            "name": "nf-pod",
            "namespace": "default",
            "annotations": {
                "k8s.v1.cni.cncf.io/networks": "dpunfcni-conf, dpunfcni-conf"
            },
        },
        "spec": {"containers": [{"name": "nf", "resources": {}}]},
    }
    allowed, _, patch = injector.mutate({"object": pod, "namespace": "default"})
    assert allowed and patch
    values = {
        (p["path"], p["value"]) for p in patch if "endpoint" in p["path"]
    }
    escaped = v.DPU_RESOURCE_NAME.replace("/", "~1")
    assert (f"/spec/containers/0/resources/requests/{escaped}", "2") in values
    assert (f"/spec/containers/0/resources/limits/{escaped}", "2") in values


def test_nri_passes_through_unannotated_pods():
    client = InMemoryClient(InMemoryCluster())
    injector = NetworkResourcesInjector(client)
    allowed, _, patch = injector.mutate(
        {"object": {"metadata": {"name": "p"}, "spec": {"containers": [{}]}}}
    )
    assert allowed and patch is None


def test_nri_control_switches_disable_injection():
    """The nri-control-switches ConfigMap turns injection off at runtime
    (reference networkresourcesinjector.go:231-245)."""
    from dpu_operator_tpu.controller.nri import (
        CONTROL_SWITCHES_CONFIGMAP,
        NetworkResourcesInjector,
    )

    client = InMemoryClient(InMemoryCluster())
    client.create({
        "apiVersion": "k8s.cni.cncf.io/v1",
        "kind": "NetworkAttachmentDefinition",
        "metadata": {
            "name": "dpunfcni-conf",
            "namespace": v.NAMESPACE,
            "annotations": {"k8s.v1.cni.cncf.io/resourceName": v.DPU_RESOURCE_NAME},
        },
    })
    pod = {
        "metadata": {
            "name": "p", "namespace": "default",
            "annotations": {"k8s.v1.cni.cncf.io/networks": "dpunfcni-conf"},
        },
        "spec": {"containers": [{"name": "c", "image": "i"}]},
    }
    injector = NetworkResourcesInjector(client)
    ok, _, patch = injector.mutate({"object": pod})
    assert ok and patch, "baseline injection should produce a patch"

    client.create({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": CONTROL_SWITCHES_CONFIGMAP, "namespace": v.NAMESPACE},
        "data": {"resourceInjection": "false"},
    })
    injector2 = NetworkResourcesInjector(client)  # fresh cache
    ok, _, patch = injector2.mutate({"object": pod})
    assert ok and patch is None, "injection should be switched off"


def test_nri_serves_mutate_over_tls(tmp_path):
    """The injector's production wiring: TLS serving with the mounted
    cert (reference serves :8443 TLS, networkresourcesinjector.go:190);
    missing secret mount degrades to plain HTTP instead of crash-looping
    (the deployment marks the volume optional)."""
    import json as jsonlib
    import ssl
    import urllib.request

    from test_webhook_tls import _mint_cert

    from dpu_operator_tpu.api.webhook import AdmissionWebhook
    from dpu_operator_tpu.controller.nri import (
        NetworkResourcesInjector,
        resolve_tls,
    )
    from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster

    # Missing cert pair → plain HTTP fallback.
    assert resolve_tls(str(tmp_path / "nope.crt"), str(tmp_path / "nope.key")) == (
        None, None,
    )
    assert resolve_tls(None, None) == (None, None)

    certfile, keyfile = _mint_cert(tmp_path, serial=31)
    assert resolve_tls(certfile, keyfile) == (certfile, keyfile)

    client = InMemoryClient(InMemoryCluster())
    client.create({
        "apiVersion": "k8s.cni.cncf.io/v1",
        "kind": "NetworkAttachmentDefinition",
        "metadata": {
            "name": "dpunfcni-conf", "namespace": v.NAMESPACE,
            "annotations": {
                "k8s.v1.cni.cncf.io/resourceName": v.DPU_RESOURCE_NAME,
            },
        },
    })
    injector = NetworkResourcesInjector(client)
    wh = AdmissionWebhook(port=0, certfile=certfile, keyfile=keyfile)
    wh.register("/mutate", injector.mutate)
    wh.start()
    try:
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": "tls-nri",
                "namespace": v.NAMESPACE,
                "object": {
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {
                        "name": "nf", "namespace": v.NAMESPACE,
                        "annotations": {
                            "k8s.v1.cni.cncf.io/networks":
                                "dpunfcni-conf, dpunfcni-conf",
                        },
                    },
                    "spec": {"containers": [{"name": "c", "image": "i"}]},
                },
            },
        }
        ctx = ssl.create_default_context(cafile=certfile)
        req = urllib.request.Request(
            f"https://localhost:{wh.port}/mutate",
            data=jsonlib.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = jsonlib.loads(urllib.request.urlopen(req, context=ctx).read())
        assert resp["response"]["allowed"] is True
        assert resp["response"]["patchType"] == "JSONPatch"
        import base64

        patch = jsonlib.loads(base64.b64decode(resp["response"]["patch"]))
        assert any(
            str(op.get("value")) == "2" and "resources" in op.get("path", "")
            for op in patch
        ), patch
    finally:
        wh.stop()


def test_nri_rollout_survives_missing_cert_manager():
    """Clusters without cert-manager CRDs: the Certificate/Issuer applies
    fail, but the rest of the NRI rollout (deployment, service, webhook
    config) must land — the injector then serves plain HTTP (its secret
    volume is optional)."""

    class NoCertManagerClient(InMemoryClient):
        def create(self, obj):
            if obj.get("apiVersion", "").startswith("cert-manager.io"):
                raise RuntimeError(
                    'no matches for kind "Certificate" in version "cert-manager.io/v1"'
                )
            return super().create(obj)

    client = NoCertManagerClient(InMemoryCluster())
    mgr = build_manager(client, DummyImageManager())
    mgr.start()
    try:
        client.create(v1.new_dpu_operator_config())
        assert wait_for(
            lambda: client.get_or_none(
                "apps/v1", "Deployment", v.NAMESPACE, "network-resources-injector"
            ) is not None
        ), "NRI deployment never rendered"
        assert client.get_or_none(
            "admissionregistration.k8s.io/v1", "MutatingWebhookConfiguration",
            None, "network-resources-injector",
        ) is not None
        # The cert objects were skipped, not rendered.
        assert client.get_or_none(
            "cert-manager.io/v1", "Certificate", v.NAMESPACE,
            "network-resources-injector-cert",
        ) is None
    finally:
        mgr.stop()


def test_nri_cert_rendered_into_operand_namespace():
    """With cert-manager present, the Certificate lands in the operand
    namespace with SANs matching the Service the apiserver dials."""
    client = InMemoryClient(InMemoryCluster())
    mgr = build_manager(client, DummyImageManager())
    mgr.start()
    try:
        client.create(v1.new_dpu_operator_config())
        assert wait_for(
            lambda: client.get_or_none(
                "cert-manager.io/v1", "Certificate", v.NAMESPACE,
                "network-resources-injector-cert",
            ) is not None
        ), "NRI Certificate never rendered"
        cert = client.get(
            "cert-manager.io/v1", "Certificate", v.NAMESPACE,
            "network-resources-injector-cert",
        )
        assert f"network-resources-injector.{v.NAMESPACE}.svc" in cert["spec"]["dnsNames"]
        assert cert["spec"]["secretName"] == "network-resources-injector-certs"
        wh = client.get(
            "admissionregistration.k8s.io/v1", "MutatingWebhookConfiguration",
            None, "network-resources-injector",
        )
        assert wh["metadata"]["annotations"]["cert-manager.io/inject-ca-from"] == (
            f"{v.NAMESPACE}/network-resources-injector-cert"
        )
    finally:
        mgr.stop()
