"""Shared launcher for virtual-mesh subprocess tests: a clean
interpreter (no sitecustomize on PYTHONPATH, so jax is not pinned to the
tunnelled TPU) on the 8-device virtual CPU platform — the same
environment the driver's dryrun uses. One copy so an environment fix
(new XLA flag, sitecustomize workaround) can never land in one test
file and miss another."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_virtual(code: str, timeout: int = 600) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )
