"""Pipeline (`pp`) and expert (`ep`) parallelism on the virtual mesh —
the two axes that complete the framework's tp/pp/dp/sp/ep taxonomy.
Correctness is against sequential/dense ground truth, not just shape
checks; schedules and drops are asserted, not assumed."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def _mesh(axes):
    from jax.sharding import Mesh

    n = int(np.prod([s for _, s in axes]))
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, have {len(devs)}")
    shape = tuple(s for _, s in axes)
    names = tuple(n_ for n_, _ in axes)
    return Mesh(np.array(devs[:n]).reshape(shape), names)


def test_pipeline_matches_sequential():
    """S=4 stages over the pp axis, M=6 microbatches: the pipelined
    schedule must produce exactly what running the stages in order
    produces — stage weights all differ, so a permuted or off-by-one
    schedule cannot pass."""
    from dpu_operator_tpu.parallel.pipeline import (
        demo_stage_params, make_pipeline, mlp_stage, sequential_reference,
        shard_stage_params, stack_stage_params)

    mesh = _mesh([("pp", 4)])
    S, M, mb, d = 4, 6, 8, 16
    per_stage = demo_stage_params(S, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, d))

    stacked = shard_stage_params(stack_stage_params(per_stage), mesh)
    out = np.asarray(jax.jit(make_pipeline(mesh, mlp_stage))(stacked, x))
    ref = np.asarray(sequential_reference(per_stage, x, mlp_stage))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_pipeline_single_microbatch_and_many():
    """Edge schedules: M=1 (pure bubble) and M >> S both line up."""
    from dpu_operator_tpu.parallel.pipeline import (
        demo_stage_params, make_pipeline, mlp_stage, sequential_reference,
        shard_stage_params, stack_stage_params)

    mesh = _mesh([("pp", 2)])
    for M in (1, 9):
        per_stage = demo_stage_params(2, 8, seed=M)
        x = jax.random.normal(jax.random.PRNGKey(M), (M, 4, 8))
        stacked = shard_stage_params(stack_stage_params(per_stage), mesh)
        out = np.asarray(make_pipeline(mesh, mlp_stage)(stacked, x))
        ref = np.asarray(sequential_reference(per_stage, x, mlp_stage))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_pipeline_composes_with_dp_axis():
    """pp inside a larger mesh: extra axes present must not disturb the
    schedule (the shard_map specs only touch pp)."""
    from dpu_operator_tpu.parallel.pipeline import (
        demo_stage_params, make_pipeline, mlp_stage, sequential_reference,
        shard_stage_params, stack_stage_params)

    mesh = _mesh([("dp", 2), ("pp", 2), ("tp", 2)])
    per_stage = demo_stage_params(2, 8)
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 4, 8))
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh,
                                 axis="pp")
    out = np.asarray(make_pipeline(mesh, mlp_stage, axis="pp")(stacked, x))
    ref = np.asarray(sequential_reference(per_stage, x, mlp_stage))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_moe_matches_dense_reference():
    """E=4 experts over the ep axis with capacity ≥ tokens: the
    dispatched/exchanged/combined output must equal computing every
    expert densely and gathering by the router's argmax."""
    from dpu_operator_tpu.parallel.moe import (
        dense_reference, demo_moe_params, make_moe, shard_expert_params)

    mesh = _mesh([("ep", 4)])
    E, t, d, h = 4, 32, 16, 32
    router_w, w1, w2 = demo_moe_params(E, d, h)
    x = jax.random.normal(jax.random.PRNGKey(7), (t, d))

    # capacity_factor=E gives C = t_local per (source shard, expert)
    # pair — every local token fits even if all route to one expert.
    moe = make_moe(mesh, capacity_factor=float(E))
    out = np.asarray(jax.jit(moe)(
        x, router_w,
        shard_expert_params(w1, mesh), shard_expert_params(w2, mesh)))
    ref = np.asarray(dense_reference(x, router_w, w1, w2))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_moe_top2_matches_dense_reference():
    """top_k=2 (the classic MoE shape): each token's output is the
    renormalized-gate sum of its two best experts — must equal the
    dense reference at full capacity. Distinct expert weights make a
    rank mix-up or a wrong renormalization numerically loud."""
    from dpu_operator_tpu.parallel.moe import (
        dense_reference, demo_moe_params, make_moe, shard_expert_params)

    mesh = _mesh([("ep", 4)])
    E, t, d, h = 4, 32, 16, 32
    router_w, w1, w2 = demo_moe_params(E, d, h, seed=13)
    x = jax.random.normal(jax.random.PRNGKey(17), (t, d))

    # Capacity ≥ 2x local tokens: both ranks of every token fit.
    moe = make_moe(mesh, capacity_factor=2.0 * E, top_k=2)
    out = np.asarray(jax.jit(moe)(
        x, router_w,
        shard_expert_params(w1, mesh), shard_expert_params(w2, mesh)))
    ref = np.asarray(dense_reference(x, router_w, w1, w2, top_k=2))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_moe_top2_rank_priority_under_pressure():
    """Under capacity pressure, rank-0 assignments MUST win bucket
    slots over rank-1 ones (the priority-ordered assignment stream):
    with capacity sized exactly to the rank-0 load, every token keeps
    its primary expert's contribution whenever primaries are evenly
    spread."""
    import jax.numpy as jnp
    from dpu_operator_tpu.parallel._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpu_operator_tpu.parallel.moe import switch_moe_local

    mesh = _mesh([("ep", 2)])
    d, h, t = 8, 16, 8
    k1, k2, k3, kx = jax.random.split(jax.random.PRNGKey(23), 4)
    # Router engineered so primaries split evenly: tokens alternate
    # preference between the two experts.
    router_w = jnp.stack([jnp.ones(d), -jnp.ones(d)], axis=1) * 0.5
    w1 = jax.random.normal(k1, (2, d, h)) / np.sqrt(d)
    w2 = jax.random.normal(k2, (2, h, d)) / np.sqrt(h)
    signs = jnp.where(jnp.arange(t) % 2 == 0, 1.0, -1.0)
    x = jnp.abs(jax.random.normal(kx, (t, d))) * signs[:, None]

    def per_device(xl, rw, w1l, w2l):
        # cf=0.5 with k=2: C = ceil(2*4/2*0.5) = 2 — exactly the
        # rank-0 load, zero slack for rank-1.
        return switch_moe_local(xl, rw, w1l[0], w2l[0], axis="ep",
                                capacity_factor=0.5, top_k=2)

    out = shard_map(
        per_device, mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=P("ep"), check_vma=False,
    )(x, router_w,
      jax.device_put(w1, NamedSharding(mesh, P("ep"))),
      jax.device_put(w2, NamedSharding(mesh, P("ep"))))
    # Every token's primary fits (2 primaries per expert per shard,
    # C = ceil(4/2*1.0) = 2), so no row may be all-zero.
    assert not np.any(np.all(np.asarray(out) == 0, axis=1))


def test_moe_top_k_out_of_range_rejected_clearly():
    """top_k beyond the ep axis (or < 1) must fail with a clear
    ValueError at make_moe time, not an opaque XLA shape error from
    lax.top_k deep inside the traced program."""
    import pytest

    from dpu_operator_tpu.parallel.moe import make_moe

    mesh = _mesh([("ep", 2)])
    with pytest.raises(ValueError, match="top_k=3"):
        make_moe(mesh, top_k=3)
    with pytest.raises(ValueError, match="top_k=0"):
        make_moe(mesh, top_k=0)


def test_moe_capacity_drops_are_exact():
    """Over-capacity tokens drop to ZERO output (the Switch contract) —
    and only those: with capacity 1 per expert, each expert serves its
    first-routed token exactly, everything else is zero."""
    from dpu_operator_tpu.parallel.moe import (
        dense_reference, demo_moe_params, make_moe, shard_expert_params)

    mesh = _mesh([("ep", 2)])
    E, t, d, h = 2, 8, 8, 16
    router_w, w1, w2 = demo_moe_params(E, d, h, seed=5)
    x = jax.random.normal(jax.random.PRNGKey(9), (t, d))

    # capacity_factor such that C = 1 per source shard (tokens arrive
    # sharded over ep: shard s owns x[s*t/E:(s+1)*t/E]).
    t_local = t // E
    moe = make_moe(mesh, capacity_factor=E / t_local)
    out = np.asarray(moe(x, router_w,
                         shard_expert_params(w1, mesh),
                         shard_expert_params(w2, mesh)))
    ref = np.asarray(dense_reference(x, router_w, w1, w2))

    logits = np.asarray(x @ router_w)
    expert = logits.argmax(-1)
    served = set()  # (source shard, expert) pairs already at capacity
    for i in range(t):
        key = (i // t_local, int(expert[i]))
        if key not in served:
            served.add(key)
            np.testing.assert_allclose(out[i], ref[i], rtol=2e-5,
                                       atol=2e-5)
        else:
            np.testing.assert_array_equal(out[i], np.zeros(d))


def test_pipeline_and_moe_aot_lower_for_tpu():
    """AOT-lower both schedules for an 8-device TPU target via
    jax.export (same proof the ring kernels carry, test_ring_probe.py):
    the collective-permute pipeline hops and the all_to_all expert
    exchanges must survive TPU lowering without multi-chip hardware —
    and the collectives must actually be IN the module, not optimized
    into a local no-op."""
    from virtual_mesh import REPO, run_virtual

    r = run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from dpu_operator_tpu.parallel.pipeline import (\n"
        "    demo_stage_params, make_pipeline, mlp_stage,\n"
        "    stack_stage_params)\n"
        "from dpu_operator_tpu.parallel.moe import (\n"
        "    demo_moe_params, make_moe)\n"
        "mesh = Mesh(np.array(jax.devices()).reshape(4, 2),\n"
        "            axis_names=('pp', 'ep'))\n"
        "stacked = stack_stage_params(demo_stage_params(4, 8))\n"
        "p_spec = jax.tree.map(lambda a: jax.ShapeDtypeStruct(\n"
        "    a.shape, a.dtype, sharding=NamedSharding(mesh, P('pp'))),\n"
        "    stacked)\n"
        "x_spec = jax.ShapeDtypeStruct((3, 4, 8), jnp.float32,\n"
        "    sharding=NamedSharding(mesh, P()))\n"
        "exp = jax.export.export(jax.jit(make_pipeline(mesh, mlp_stage)),\n"
        "                        platforms=['tpu'])(p_spec, x_spec)\n"
        "assert 'collective_permute' in exp.mlir_module()\n"
        "router_w, w1, w2 = demo_moe_params(2, 8, 16)\n"
        "sh = lambda a, s: jax.ShapeDtypeStruct(\n"
        "    a.shape, a.dtype, sharding=NamedSharding(mesh, s))\n"
        "exp = jax.export.export(\n"
        "    jax.jit(make_moe(mesh, axis='ep')), platforms=['tpu'])(\n"
        "    jax.ShapeDtypeStruct((8, 8), jnp.float32,\n"
        "        sharding=NamedSharding(mesh, P('ep'))),\n"
        "    sh(router_w, P()), sh(w1, P('ep')), sh(w2, P('ep')))\n"
        "assert 'all_to_all' in exp.mlir_module()\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_moe_composes_with_dp_axis():
    from dpu_operator_tpu.parallel.moe import (
        dense_reference, demo_moe_params, make_moe, shard_expert_params)

    mesh = _mesh([("dp", 2), ("ep", 2), ("tp", 2)])
    E, t, d, h = 2, 16, 8, 16
    router_w, w1, w2 = demo_moe_params(E, d, h, seed=2)
    x = jax.random.normal(jax.random.PRNGKey(11), (t, d))
    moe = make_moe(mesh, axis="ep", capacity_factor=float(E))
    out = np.asarray(moe(x, router_w,
                         shard_expert_params(w1, mesh, axis="ep"),
                         shard_expert_params(w2, mesh, axis="ep")))
    ref = np.asarray(dense_reference(x, router_w, w1, w2))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


# -- 1F1B / interleaved pipeline schedules ------------------------------------


def _1f1b_setup(n, M, v, d=12, rows=6, seed=11):
    from dpu_operator_tpu.parallel.pipeline import demo_stage_params, mlp_stage
    from dpu_operator_tpu.parallel.pipeline_1f1b import interleave_stack
    from dpu_operator_tpu.parallel.pipeline import shard_stage_params

    mesh = _mesh([("pp", n)])
    per_stage = demo_stage_params(n * v, d, seed=seed)
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed + 1))
    x = jax.random.normal(k1, (M, rows, d))
    tgt = jax.random.normal(k2, (M, rows, d))
    stacked = shard_stage_params(interleave_stack(per_stage, n, v), mesh)
    return mesh, per_stage, x, tgt, stacked, mlp_stage


@pytest.mark.parametrize("n,M,v", [(4, 6, 1), (4, 8, 2), (2, 5, 3)])
def test_1f1b_gradients_match_sequential_ad(n, M, v):
    """The hand-scheduled 1F1B backward (rematerialize + VJP, cotangent
    ring, static instruction tables) must produce the SAME loss and the
    SAME gradients as jax.grad of the sequential reference — for the
    classic v=1 schedule and interleaved v>1."""
    from dpu_operator_tpu.parallel.pipeline_1f1b import (
        make_1f1b, sequential_loss, uninterleave)

    mesh, per_stage, x, tgt, stacked, stage_fn = _1f1b_setup(n, M, v)
    step = jax.jit(make_1f1b(mesh, stage_fn, v=v, M=M))
    loss, grads = step(stacked, x, tgt)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda ps: sequential_loss(ps, x, tgt, stage_fn))(per_stage)
    assert np.isclose(float(loss), float(ref_loss), rtol=1e-5), (
        float(loss), float(ref_loss))
    # Pipeline grads come back stage-stacked in interleaved layout.
    got = uninterleave(jax.tree.map(np.asarray, grads), n, v)
    for i, ref in enumerate(ref_grads):
        for key in ref:
            np.testing.assert_allclose(
                got[key][i], np.asarray(ref[key]), rtol=2e-4, atol=1e-6,
                err_msg=f"grad mismatch at stage {i} key {key}")


def test_1f1b_memory_is_bounded_by_depth_not_microbatches():
    """THE 1F1B property: peak in-flight microbatches per device is the
    warmup depth W_d = (v-1)n + (n-d), independent of M — GPipe's AD
    backward stashes all M. Asserted from the scheduler's measured
    high-water marks, for a deep M."""
    from dpu_operator_tpu.parallel.pipeline_1f1b import build_schedule

    n = 4
    for M in (8, 32, 128):
        s = build_schedule(n, M, v=1)
        assert s.max_inflight.tolist() == [4, 3, 2, 1], (
            M, s.max_inflight.tolist())
        assert s.Ks <= 4, (M, s.Ks)  # stash slots, not O(M)


def test_1f1b_bubble_matches_gpipe_and_interleaved_beats_it():
    """Schedule accounting from the emitted tables: v=1 1F1B has
    exactly GPipe's bubble (its win is memory, the textbook result);
    interleaved v=2 must measurably beat it on the same (n, M·v) work."""
    from dpu_operator_tpu.parallel.pipeline_1f1b import (
        build_schedule, gpipe_bubble)

    n, M = 4, 8
    s1 = build_schedule(n, M, v=1)
    assert np.isclose(s1.bubble, gpipe_bubble(n, M)), (
        s1.bubble, gpipe_bubble(n, M))
    s2 = build_schedule(n, M, v=2)
    assert s2.bubble < s1.bubble, (s2.bubble, s1.bubble)
    # And deeper interleaving keeps helping on bigger M.
    s4 = build_schedule(n, 16, v=4)
    assert s4.bubble < build_schedule(n, 16, v=1).bubble


def test_1f1b_rejects_wrong_chunk_count():
    from dpu_operator_tpu.parallel.pipeline_1f1b import make_1f1b

    mesh = _mesh([("pp", 2)])
    from dpu_operator_tpu.parallel.pipeline import (
        demo_stage_params, mlp_stage, shard_stage_params,
        stack_stage_params)

    # 4 stages stacked onto a 2-way axis with v=1 → each device sees 2
    # chunks where the schedule expects 1.
    stacked = shard_stage_params(
        stack_stage_params(demo_stage_params(4, 8)), mesh)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8))
    step = make_1f1b(mesh, mlp_stage, v=1, M=2)
    with pytest.raises(ValueError, match="v=1"):
        step(stacked, x, x)


def test_1f1b_masked_grads_survive_division_bearing_stage():
    """ADVICE r5: run_schedule's masked backward used to accumulate
    `dpl * gmask` — on IDLE ticks the rematerialized VJP runs over the
    ZERO-filled buffers, and any stage_fn with a division (rmsnorm,
    softmax denominators) yields NaN there, which NaN·0 = NaN then
    smeared into the gradient accumulator for every real microbatch.
    Masking must SELECT (jnp.where), not multiply. The stage here is an
    rmsnorm-style map: finite on real data, 0/0 = NaN on the idle
    zeros — so this test fails loudly on the multiplicative form."""
    import jax.numpy as jnp

    from dpu_operator_tpu.parallel.pipeline_1f1b import (
        make_1f1b, sequential_loss)

    def rms_stage(p, x):
        h = x @ p["w"]
        return h / jnp.sqrt(jnp.mean(h ** 2))  # NaN on all-zero input

    n, M, v, d, rows = 2, 3, 1, 8, 4
    mesh = _mesh([("pp", n)])
    rng = np.random.RandomState(5)
    per_stage = [{"w": jnp.asarray(
        rng.randn(d, d).astype(np.float32) / np.sqrt(d))}
        for _ in range(n * v)]
    stacked = {"w": jnp.stack([p["w"] for p in per_stage])}
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = {"w": jax.device_put(
        stacked["w"], NamedSharding(mesh, P("pp")))}
    x = jnp.asarray(rng.randn(M, rows, d).astype(np.float32))
    tgt = jnp.asarray(rng.randn(M, rows, d).astype(np.float32))

    step = jax.jit(make_1f1b(mesh, rms_stage, v=v, M=M))
    loss, grads = step(stacked, x, tgt)
    assert np.isfinite(float(loss)), float(loss)
    gw = np.asarray(grads["w"])
    assert np.isfinite(gw).all(), "IDLE-tick NaN poisoned the grads"

    ref_loss, ref_grads = jax.value_and_grad(
        lambda ps: sequential_loss(ps, x, tgt, rms_stage))(per_stage)
    assert np.isclose(float(loss), float(ref_loss), rtol=1e-5)
    for i, ref in enumerate(ref_grads):
        np.testing.assert_allclose(gw[i], np.asarray(ref["w"]),
                                   rtol=2e-4, atol=1e-6,
                                   err_msg=f"stage {i}")
