"""api/v1 schema + webhook tests (counterpart of reference
api/v1/dpuoperatorconfig_webhook_test.go + webhook_suite_test.go)."""

import json
import urllib.request

import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.api import AdmissionWebhook, v1
from dpu_operator_tpu.api.webhook import (
    validate_dpu_operator_config,
    validate_service_function_chain,
)


def test_constructors_produce_wire_format():
    cfg = v1.new_dpu_operator_config()
    assert cfg["apiVersion"] == "config.tpu.io/v1"
    assert cfg["metadata"]["name"] == v.DPU_OPERATOR_CONFIG_NAME
    dpu = v1.new_data_processing_unit("tpu-w0-dpu", "TPU v5e", True, "node-a")
    assert dpu["spec"] == {
        "dpuProductName": "TPU v5e",
        "isDpuSide": True,
        "nodeName": "node-a",
    }


def test_singleton_name_enforced():
    bad = v1.new_dpu_operator_config(name="something-else")
    with pytest.raises(v1.ValidationError, match="must be named"):
        v1.validate_dpu_operator_config_spec(bad)
    v1.validate_dpu_operator_config_spec(v1.new_dpu_operator_config())


def test_mode_and_loglevel_validation():
    cfg = v1.new_dpu_operator_config()
    cfg["spec"]["mode"] = "sideways"
    with pytest.raises(v1.ValidationError, match="mode"):
        v1.validate_dpu_operator_config_spec(cfg)
    cfg = v1.new_dpu_operator_config(log_level=7)
    with pytest.raises(v1.ValidationError, match="logLevel"):
        v1.validate_dpu_operator_config_spec(cfg)


def test_sfc_validation():
    sfc = v1.new_service_function_chain(
        "chain", network_functions=[{"name": "fw", "image": "img"}]
    )
    v1.validate_service_function_chain_spec(sfc)
    sfc["spec"]["networkFunctions"].append({"name": "fw", "image": "img2"})
    with pytest.raises(v1.ValidationError, match="duplicate"):
        v1.validate_service_function_chain_spec(sfc)
    with pytest.raises(v1.ValidationError, match="name and image"):
        v1.validate_service_function_chain_spec(
            v1.new_service_function_chain("c2", network_functions=[{"name": "x"}])
        )


def _post_review(port, path, obj):
    review = {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"uid": "test-uid", "object": obj},
    }
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())["response"]


def test_webhook_server_round_trip():
    wh = AdmissionWebhook()
    wh.register("/validate-dpuoperatorconfig", validate_dpu_operator_config)
    wh.register("/validate-sfc", validate_service_function_chain)
    wh.start()
    try:
        ok = _post_review(
            wh.port, "/validate-dpuoperatorconfig", v1.new_dpu_operator_config()
        )
        assert ok["allowed"] is True and ok["uid"] == "test-uid"

        denied = _post_review(
            wh.port,
            "/validate-dpuoperatorconfig",
            v1.new_dpu_operator_config(name="wrong"),
        )
        assert denied["allowed"] is False
        assert "must be named" in denied["status"]["message"]

        # malformed body → denied, not a 500
        req = urllib.request.Request(
            f"http://127.0.0.1:{wh.port}/validate-dpuoperatorconfig",
            data=b"not json",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())["response"]
        assert out["allowed"] is False
    finally:
        wh.stop()


def test_webhook_health_endpoint():
    wh = AdmissionWebhook()
    wh.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{wh.port}/healthz") as resp:
            assert resp.read() == b"ok"
    finally:
        wh.stop()


def test_crd_manifests_parse():
    import glob
    import os

    import yaml

    crd_dir = os.path.join(os.path.dirname(__file__), "..", "config", "crd")
    files = sorted(
        f
        for f in glob.glob(os.path.join(crd_dir, "*.yaml"))
        if not f.endswith("kustomization.yaml")
    )
    assert len(files) == 4
    kinds = set()
    for f in files:
        crd = yaml.safe_load(open(f))
        assert crd["kind"] == "CustomResourceDefinition"
        assert crd["spec"]["group"] == "config.tpu.io"
        kinds.add(crd["spec"]["names"]["kind"])
    assert kinds == {
        "DpuOperatorConfig",
        "DataProcessingUnit",
        "ServiceFunctionChain",
        "DataProcessingUnitConfig",
    }


def test_dpu_config_spec_validation():
    """numEndpoints junk is rejected at admission, not in the daemon's
    fabric-partition path."""
    import pytest

    from dpu_operator_tpu.api import v1

    ok = v1.new_data_processing_unit_config("t", num_endpoints=8)
    v1.validate_data_processing_unit_config_spec(ok)  # no raise
    v1.validate_data_processing_unit_config_spec(
        v1.new_data_processing_unit_config("t"))  # numEndpoints optional

    for bad_spec in (
        {"numEndpoints": 0},
        {"numEndpoints": -4},
        {"numEndpoints": 1000},
        {"numEndpoints": "eight"},
        {"numEndpoints": True},
        {"dpuSelector": "not-a-map"},
        {"dpuSelector": {"k": 3}},
    ):
        obj = v1.new_data_processing_unit_config("t")
        obj["spec"].update(bad_spec)
        with pytest.raises(v1.ValidationError):
            v1.validate_data_processing_unit_config_spec(obj)

    # The webhook handler surfaces the rejection through the admission
    # contract.
    from dpu_operator_tpu.api.webhook import validate_data_processing_unit_config

    bad = v1.new_data_processing_unit_config("t")
    bad["spec"]["numEndpoints"] = 0
    allowed, msg, _ = validate_data_processing_unit_config({"object": bad})
    assert not allowed and "numEndpoints" in msg
