"""Host-RAM KV tier (ISSUE 17): spill/restore byte-identity, the hard
host-bytes budget, the owner-tagged tier-lease ledger, the chained-hash
re-verification degrade path, the spill-vs-fork lock contract, and the
/metrics exposition of the per-tier hit series.

The acceptance contract mirrors the allocator's: every test ends with
BOTH leak ledgers clean — zero leaked HBM blocks and zero leaked tier
leases — and every degrade path (corrupt entry, dropped spill, OOM
restore) must produce a byte-identical token stream, just slower."""

import threading
import time

import numpy as np
import pytest

from dpu_operator_tpu.serving import (AdmissionQueue, ContinuousBatcher,
                                      GenerateRequest,
                                      SyntheticKVExecutor)
from dpu_operator_tpu.serving.kvcache import (CACHE_OWNER, HostKVTier,
                                              PrefixTree,
                                              verify_block_tokens)
from dpu_operator_tpu.serving.kvcache.allocator import _ROOT


def _req(prompt, max_tokens=5, deadline_s=60.0):
    return GenerateRequest(prompt_vec=None, max_tokens=max_tokens,
                           deadline=time.monotonic() + deadline_s,
                           prompt_tokens=list(prompt))


def _drive(ex, reqs, timeout=30.0):
    q = AdmissionQueue(max_depth=len(reqs) + 1)
    b = ContinuousBatcher(ex, q)
    for r in reqs:
        q.submit(r)
    b.start()
    try:
        for r in reqs:
            assert r.wait(timeout=timeout), "request lost"
    finally:
        b.stop()
    for r in reqs:
        assert r.error is None, r.error
    return [list(r.tokens) for r in reqs]


def _planes(fill=7, n=100):
    """A fake exported block: ~104 bytes of codes + scales."""
    return [(np.full(n, fill, np.int8), np.ones(1, np.float32))]


PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]  # 3 blocks at bs=4


def _tiered_ex(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("vocab", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("host_tier_bytes", 1 << 20)
    return SyntheticKVExecutor(**kw)


def _assert_both_clean(ex):
    ex.prefix.flush()
    ex.allocator.assert_clean()
    ex.tier.assert_clean()


# -- verify_block_tokens: the GL019 blessed helper ---------------------------


def test_verify_block_tokens_rederives_the_chain():
    chunk = (5, 6, 7, 8)
    key = PrefixTree._key(_ROOT, chunk)
    assert verify_block_tokens(_ROOT, chunk, key)
    assert verify_block_tokens(_ROOT, chunk, key, stored_tokens=chunk)
    # Wrong key, wrong parent, tampered stored tokens: all refused.
    assert not verify_block_tokens(_ROOT, chunk, "deadbeef")
    assert not verify_block_tokens("elsewhere", chunk, key)
    assert not verify_block_tokens(_ROOT, chunk, key,
                                   stored_tokens=(5, 6, 7, 9))


# -- HostKVTier unit contracts -----------------------------------------------


def test_tier_put_checkout_checkin_roundtrip_and_ledger():
    tier = HostKVTier(budget_bytes=1 << 16)
    key = PrefixTree._key(_ROOT, (1, 2))
    assert tier.put(key, _ROOT, (1, 2), _planes())
    entry = tier.checkout(key, "r1")
    assert entry is not None and entry.tokens == (1, 2)
    assert tier.leaked() == {"r1": [key]}
    with pytest.raises(AssertionError, match="r1"):
        tier.assert_clean()
    tier.checkin(key, "r1", restored=True)
    tier.assert_clean()
    st = tier.stats()
    assert st["spilled_blocks"] == 1 and st["restored_blocks"] == 1
    # Double check-in is the double-free class: refuse it loudly.
    with pytest.raises(ValueError, match="not held"):
        tier.checkin(key, "r1")
    # Missing key is a plain miss, not an error.
    assert tier.checkout("nope", "r1") is None


def test_tier_budget_is_hard_lru_evicts_and_overflow_drops():
    # Each entry is 104 bytes; budget fits exactly two.
    tier = HostKVTier(budget_bytes=208)
    keys = [PrefixTree._key(_ROOT, (i,)) for i in range(3)]
    assert tier.put(keys[0], _ROOT, (0,), _planes(0))
    assert tier.put(keys[1], _ROOT, (1,), _planes(1))
    # Touch keys[0] so keys[1] is the LRU victim.
    tier.checkout(keys[0], "toucher")
    tier.checkin(keys[0], "toucher")
    assert tier.put(keys[2], _ROOT, (2,), _planes(2))
    assert sorted(tier.keys()) == sorted([keys[0], keys[2]])
    assert tier.stats()["evicted_blocks"] == 1
    # An oversized block can never fit: dropped, counted, no growth.
    assert not tier.put("big", _ROOT, (9,), _planes(9, n=4096))
    assert tier.stats()["dropped_blocks"] == 1
    assert tier.stats()["bytes_used"] <= tier.budget_bytes
    tier.assert_clean()


def test_tier_pinned_entries_survive_eviction_pressure():
    tier = HostKVTier(budget_bytes=208)
    k0 = PrefixTree._key(_ROOT, (0,))
    k1 = PrefixTree._key(_ROOT, (1,))
    tier.put(k0, _ROOT, (0,), _planes(0))
    tier.put(k1, _ROOT, (1,), _planes(1))
    tier.checkout(k0, "reader")
    tier.checkout(k1, "reader")
    # Everything resident is pinned by in-flight restores: the spill
    # must drop (counted), never evict under a reader.
    assert not tier.put("k2", _ROOT, (2,), _planes(2))
    assert tier.stats()["dropped_blocks"] == 1
    assert sorted(tier.keys()) == sorted([k0, k1])
    tier.checkin(k0, "reader")
    tier.checkin(k1, "reader")
    tier.assert_clean()


# -- spill -> restore end to end ---------------------------------------------


def test_evict_spills_to_tier_and_restore_is_byte_identical():
    """The tentpole roundtrip: prefill once, evict the whole chain to
    host RAM, run the same prompt again — the stream is identical, the
    hits are credited to the HOST tier, and both ledgers are clean."""
    ex = _tiered_ex()
    try:
        first = _drive(ex, [_req(PROMPT)])[0]
        cached_keys = set(ex.prefix.keys())
        assert len(cached_keys) == 3
        freed = ex.prefix.evict(99)
        assert freed == 3
        # Evict-to-tier: every dropped chain key is parked, not lost.
        assert set(ex.tier.keys()) == cached_keys
        assert ex.tier.stats()["spilled_blocks"] == 3

        again = _drive(ex, [_req(PROMPT)])[0]
        assert again == first
        st = ex.kv_stats()
        # match cap is (12-1)//4 = 2 blocks = 8 tokens, all restored.
        assert st["prefix_hit_tokens_host"] == 8
        assert st["tier_restored_blocks"] == 2
        _assert_both_clean(ex)
    finally:
        ex.close()


def test_restored_chain_republishes_so_next_hit_is_hbm():
    ex = _tiered_ex()
    try:
        _drive(ex, [_req(PROMPT)])
        ex.prefix.evict(99)
        _drive(ex, [_req(PROMPT)])    # host-tier restore
        _drive(ex, [_req(PROMPT)])    # now resident again
        st = ex.kv_stats()
        assert st["prefix_hit_tokens_host"] == 8
        assert st["prefix_hit_tokens_hbm"] >= 8
        _assert_both_clean(ex)
    finally:
        ex.close()


def test_tier_corruption_degrades_to_byte_identical_reprefill():
    """Chained-hash re-verification: tamper a parked entry's token ids
    and its payload — BOTH tampers must be caught at restore, drop the
    entry, and fall back to prefilling the same bytes."""
    ex = _tiered_ex()
    try:
        first = _drive(ex, [_req(PROMPT)])[0]
        # The restore walks the chain root-forward, so tampering the
        # FIRST restorable block exercises the detection; everything
        # past it degrades to prefill that round.
        first_key = PrefixTree._key(
            _ROOT, tuple(PROMPT[:ex.block_size]))

        # Tamper 1: payload rot (token ids intact, bytes diverge —
        # caught by the backend's restored-content check).
        ex.prefix.evict(99)
        e = ex.tier._entries[first_key]
        e.planes = [(arr + 1.0, scale) for arr, scale in e.planes]
        again = _drive(ex, [_req(PROMPT)])[0]
        assert again == first
        assert ex.kv_stats()["tier_corrupt_blocks"] == 1
        assert first_key not in ex.tier.keys()  # dropped, never reused

        # Tamper 2: token ids no longer match the claimed chain key
        # (caught by verify_block_tokens before any bytes move).
        ex.prefix.evict(99)
        e = ex.tier._entries[first_key]
        e.tokens = tuple(t + 1 for t in e.tokens)
        again = _drive(ex, [_req(PROMPT)])[0]
        assert again == first
        assert ex.kv_stats()["tier_corrupt_blocks"] == 2
        assert first_key not in ex.tier.keys()
        assert ex.kv_stats()["prefix_hit_tokens_host"] == 0
        _assert_both_clean(ex)
    finally:
        ex.close()


def test_spill_drop_on_zero_room_budget_still_correct():
    """A tier too small for even one block degrades to today's
    drop-on-evict — correctness unchanged, drops counted."""
    ex = _tiered_ex(host_tier_bytes=8)
    try:
        first = _drive(ex, [_req(PROMPT)])[0]
        ex.prefix.evict(99)
        assert len(ex.tier) == 0
        assert ex.tier.stats()["dropped_blocks"] == 3
        again = _drive(ex, [_req(PROMPT)])[0]
        assert again == first
        assert ex.kv_stats()["prefix_hit_tokens_host"] == 0
        _assert_both_clean(ex)
    finally:
        ex.close()


# -- the spill-vs-fork race (satellite: event-sequenced regression) ----------


def test_spill_runs_under_tree_lock_so_fork_cannot_race():
    """ISSUE 17's race: eviction offers a victim's bytes to the tier
    and THEN releases the cache ref. If the spill ran outside the tree
    lock, a concurrent match_and_fork could fork the victim block in
    the window after the node left the tree walk but before/while its
    bytes were read — a freed-block fork ("fork of non-live block")
    or a fork of a block the tier snapshot no longer matches.

    Event sequence enforced here: park the spill (tier.put) mid-evict,
    start a concurrent match, and assert the match is BLOCKED for as
    long as the spill is parked — i.e. the hook demonstrably runs
    under the tree lock. A regression that moves the spill outside the
    lock fails the lock-held probe AND the blocked-match assertion."""
    ex = _tiered_ex()
    try:
        _drive(ex, [_req(PROMPT)])

        entered, release = threading.Event(), threading.Event()
        lock_held_during_spill = []
        orig_put = ex.tier.put

        def parked_put(*a, **kw):
            # Probe: the tree lock must be held while the tier reads
            # the victim's bytes.
            lock_held_during_spill.append(ex.prefix._lock.locked())
            entered.set()
            release.wait(timeout=10.0)
            return orig_put(*a, **kw)

        ex.tier.put = parked_put

        evictor = threading.Thread(target=lambda: ex.prefix.evict(99))
        evictor.start()
        assert entered.wait(timeout=10.0), "spill hook never ran"

        match_result, match_err = [], []

        def matcher():
            try:
                match_result.append(
                    ex.prefix.match_and_fork(PROMPT, "racer"))
            except Exception as e:  # pragma: no cover - the regression
                match_err.append(e)

        racer = threading.Thread(target=matcher)
        racer.start()
        racer.join(timeout=0.3)
        # The decisive assertion: with the spill parked under the tree
        # lock, the concurrent match CANNOT have completed.
        assert racer.is_alive(), \
            "match_and_fork completed while a spill was mid-flight — " \
            "the spill hook is no longer under the tree lock"
        release.set()
        evictor.join(timeout=10.0)
        racer.join(timeout=10.0)
        assert not racer.is_alive() and not evictor.is_alive()
        assert not match_err, f"racing fork blew up: {match_err}"
        assert lock_held_during_spill and all(lock_held_during_spill)

        # The race resolved to the miss side: the whole chain was
        # already spilled, so the match came back empty (never a fork
        # of a freed block) — and the tier now restores it cleanly.
        blocks, cached = match_result[0]
        if blocks:
            ex.allocator.release(blocks, "racer")
        ex.tier.put = orig_put
        blocks, cached = ex.kv_match_prefix(PROMPT, "racer")
        assert cached == 8 and len(blocks) == 2
        ex.allocator.release(blocks, "racer")
        _assert_both_clean(ex)
    finally:
        ex.close()


# -- /metrics exposition (satellite: per-tier hit accounting) ----------------


def test_metrics_exposition_of_per_tier_hit_series():
    """serving_prefix_hit_tokens_total{tier=...} and
    serving_prefix_hit_frac appear in a real scrape, and the response
    body carries the per-request cached_by_tier split."""
    import json
    import urllib.request

    from dpu_operator_tpu.serving import ServingServer

    ex = _tiered_ex(num_blocks=64)
    srv = ServingServer([ex]).start()
    try:
        body = json.dumps({"prompt_tokens": PROMPT, "max_tokens": 4,
                           "deadline_ms": 10000}).encode()

        def post():
            return json.loads(urllib.request.urlopen(
                urllib.request.Request(srv.url + "/v1/generate",
                                       data=body), timeout=10).read())

        post()
        ex.prefix.evict(99)          # park the chain in host RAM
        out = post()                 # host-tier restore serves it
        assert out["kv"]["cached_by_tier"].get("host", 0) == 8
        text = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=5).read().decode()
    finally:
        srv.stop()
    host = [l for l in text.splitlines()
            if l.startswith("serving_prefix_hit_tokens_total")
            and 'tier="host"' in l]
    assert host, text
    assert float(host[0].split()[-1]) == 8
    assert any(l.startswith("serving_prefix_hit_frac")
               for l in text.splitlines())
    _assert_both_clean(ex)
    ex.close()
