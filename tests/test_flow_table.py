"""Match-action flow table (vsp/flow_table.py) — the p4rt-ctl table
add/del/dump analogue, realised as nf_tables programs over raw netlink
(cni/nftnl.py). Unit tier checks the rule model + expression-program
translation; the root tier programs real kernel rules and proves they
classify traffic: drop blocks, counters count, mirror taps without
stealing, redirect steals without leaking, delete restores."""

import subprocess
import uuid

import pytest

from dpu_operator_tpu.vsp.flow_table import FlowError, FlowRule, FlowTable


# -- unit: rule model --------------------------------------------------------


def test_rule_validation_rejects_garbage():
    for bad in (
        FlowRule(pref=0, action="drop"),                      # pref range
        FlowRule(pref=40000, action="drop"),                  # pref range
        FlowRule(pref=1, action="teleport"),                  # unknown action
        FlowRule(pref=1, action="redirect"),                  # missing dev
        FlowRule(pref=1, action="police:fast"),               # junk rate
        FlowRule(pref=1, action="police:-3"),                 # negative rate
        FlowRule(pref=1, action="drop", src_mac="nope"),      # mac grammar
        FlowRule(pref=1, action="drop", src_ip="10.0.0.300"), # ip grammar
        FlowRule(pref=1, action="drop", dst_port=80),         # port w/o proto
        FlowRule(pref=1, action="drop", proto="icmp", dst_port=80),
        FlowRule(pref=1, action="drop", proto="tcp", dst_port=70000),
    ):
        with pytest.raises(FlowError):
            bad.validate()


def _expr_names(exprs):
    """Decode the expression names back out of the wire encoding — the
    nft program structure is the translation contract."""
    from dpu_operator_tpu.cni import nftnl

    names = []
    for e in exprs:
        attrs = nftnl._parse_attrs(e[4:])  # strip LIST_ELEM header
        names.append(attrs[nftnl.NFTA_EXPR_NAME].rstrip(b"\0").decode())
    return names


def test_rule_nft_translation():
    rule = FlowRule(
        pref=7, action="drop", proto="tcp",
        src_ip="10.56.0.0/24", dst_port=443, dst_mac="02:AA:bb:cc:dd:ee",
    )
    names = _expr_names(rule.to_nft_exprs())
    # dst_mac load+cmp, ethertype guard, ip_proto, src_ip (masked CIDR:
    # load+bitwise+cmp), dst_port, counter, verdict.
    assert names == [
        "payload", "cmp",              # dst_mac
        "payload", "cmp",              # ethertype 0x0800 guard
        "payload", "cmp",              # ip_proto tcp
        "payload", "bitwise", "cmp",   # src_ip/24 — mask then compare
        "payload", "cmp",              # dst_port
        "counter", "immediate",        # stats + drop verdict
    ]

    # MAC-only rules must not emit the IPv4 ethertype guard (they match
    # every ethertype) and a /32 needs no bitwise mask.
    mac_only = FlowRule(pref=1, action="accept", src_mac="02:00:00:00:00:01")
    assert _expr_names(mac_only.to_nft_exprs()) == [
        "payload", "cmp", "counter", "immediate"]
    host = FlowRule(pref=2, action="drop", dst_ip="10.0.0.9/32")
    assert "bitwise" not in _expr_names(host.to_nft_exprs())

    police = FlowRule(pref=3, action="police:100")
    assert _expr_names(police.to_nft_exprs()) == ["counter", "limit", "immediate"]


# -- root tier: rules classify real traffic ----------------------------------


@pytest.fixture
def bridged_pair(netns):
    """Two netns 'pods' on a fabric bridge, pingable — the minimal
    topology every dataplane test rides."""
    tag = uuid.uuid4().hex[:5]
    bridge = "brF" + tag
    spec = []  # (netns, host_if)
    subprocess.run(["ip", "link", "add", bridge, "type", "bridge"], check=True)
    subprocess.run(["ip", "link", "set", bridge, "up"], check=True)
    try:
        for i in (0, 1):
            ns, host_if, pod_if = f"fns{i}{tag}", f"fh{i}{tag}", f"fp{i}{tag}"
            subprocess.run(["ip", "netns", "add", ns], check=True)
            subprocess.run(
                ["ip", "link", "add", host_if, "type", "veth",
                 "peer", "name", pod_if], check=True)
            subprocess.run(["ip", "link", "set", pod_if, "netns", ns], check=True)
            subprocess.run(["ip", "link", "set", host_if, "master", bridge], check=True)
            subprocess.run(["ip", "link", "set", host_if, "up"], check=True)
            subprocess.run(["ip", "-n", ns, "link", "set", pod_if, "up"], check=True)
            subprocess.run(
                ["ip", "-n", ns, "addr", "add", f"10.97.0.{i + 1}/24",
                 "dev", pod_if], check=True)
            spec.append((ns, host_if))
        yield spec
    finally:
        for i in (0, 1):
            subprocess.run(["ip", "netns", "del", f"fns{i}{tag}"],
                           capture_output=True)
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)


_SERVER_PY = (
    "import socket, sys\n"
    "s = socket.socket()\n"
    "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
    "s.bind(('{ip}', {port})); s.listen(8)\n"
    "print('READY', flush=True)\n"
    "s.settimeout(10)\n"
    "try:\n"
    "    while True: s.accept()\n"
    "except OSError: pass\n"
)


def _tcp_reach(client_ns: str, server_ns: str, ip: str, port: int) -> bool:
    """One TCP connect across the bridge (no ping binary in this image;
    a connect also exercises the proto/port matchers for real). The
    server prints READY after listen, so there is no bind race."""
    server = subprocess.Popen(
        ["ip", "netns", "exec", server_ns, "python", "-c",
         _SERVER_PY.format(ip=ip, port=port)],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert server.stdout.readline().strip() == "READY"
        client = subprocess.run(
            ["ip", "netns", "exec", client_ns, "python", "-c",
             f"import socket; socket.create_connection(('{ip}', {port}), 1)"],
            capture_output=True)
        return client.returncode == 0
    finally:
        server.kill()
        server.wait()


def test_drop_rule_blocks_and_delete_restores(bridged_pair):
    """table-add semantics end to end: a tcp/dst_port drop rule on pod
    0's bridge port blocks its connects; counters prove the rule
    matched; the delete restores connectivity (p4rt-ctl table add/del)."""
    (ns0, host0), (ns1, _h1) = bridged_pair
    assert _tcp_reach(ns0, ns1, "10.97.0.2", 7777), "baseline connectivity"

    table = FlowTable(host0)
    table.add(FlowRule(pref=10, action="drop", proto="tcp", dst_port=7777))
    assert not _tcp_reach(ns0, ns1, "10.97.0.2", 7777), "drop rule must block"

    rules = table.list(stats=True)
    assert len(rules) == 1
    assert rules[0]["pref"] == 10
    assert rules[0]["action"] == "drop"
    assert rules[0]["proto"] == "tcp"
    assert rules[0]["dst_port"] == 7777
    assert rules[0].get("packets", 0) >= 1, "counter must show the match"

    # Duplicate pref is rejected — one slot, one rule (table semantics).
    with pytest.raises(FlowError, match="already programmed"):
        table.add(FlowRule(pref=10, action="accept"))

    table.delete(10)
    assert table.list() == []
    assert _tcp_reach(ns0, ns1, "10.97.0.2", 7777), "delete must restore traffic"


def test_specific_match_leaves_other_traffic_alone(bridged_pair):
    """A dst_ip-scoped drop must only hit the scoped destination —
    classification, not a blanket block."""
    (ns0, host0), (ns1, _h1) = bridged_pair
    table = FlowTable(host0)
    table.add(FlowRule(pref=5, action="drop", dst_ip="10.97.0.99/32"))
    try:
        assert _tcp_reach(ns0, ns1, "10.97.0.2", 7778), \
            "unscoped traffic must still flow"
    finally:
        table.flush()


def test_flush_and_kernel_as_source_of_truth(bridged_pair):
    (ns0, host0), _ = bridged_pair
    table = FlowTable(host0)
    table.add(FlowRule(pref=1, action="drop", proto="icmp"))
    table.add(FlowRule(pref=2, action="accept", src_mac="02:00:00:00:00:01"))
    # A second FlowTable instance sees both rules: no shadow state.
    assert [r["pref"] for r in FlowTable(host0).list()] == [1, 2]
    assert FlowTable(host0).flush() == 2
    assert table.list() == []


def test_fabric_ctl_rule_verbs(bridged_pair):
    """The CLI surface: rule-add / rule-list / rule-del round trip
    through fabric_ctl.main (p4rt-ctl's operator entry point)."""
    import json as jsonlib

    from dpu_operator_tpu import fabric_ctl

    (ns0, host0), (ns1, _h1) = bridged_pair
    assert fabric_ctl.main(
        ["rule-add", host0, "--pref", "9", "--action", "drop",
         "--proto", "tcp", "--dst-port", "7779"]) == 0
    assert not _tcp_reach(ns0, ns1, "10.97.0.2", 7779)

    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert fabric_ctl.main(["rule-list", host0, "--stats"]) == 0
    rules = jsonlib.loads(buf.getvalue())
    assert rules and rules[0]["pref"] == 9

    assert fabric_ctl.main(["rule-del", host0, "9"]) == 0
    assert _tcp_reach(ns0, ns1, "10.97.0.2", 7779)

    # Error path: junk action reports through the CLI error contract.
    assert fabric_ctl.main(
        ["rule-add", host0, "--pref", "1", "--action", "warp"]) == 1


def _rx_packets(dev: str, ns: str = None) -> int:
    args = (["ip", "netns", "exec", ns] if ns else []) + [
        "cat", f"/sys/class/net/{dev}/statistics/rx_packets"]
    return int(subprocess.run(args, capture_output=True, text=True).stdout or 0)


def _udp_burst(ns: str, target: str, port: int, count: int = 20):
    subprocess.run(
        ["ip", "netns", "exec", ns, "python", "-c",
         "import socket; s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM); "
         f"[s.sendto(b'y' * 64, ('{target}', {port})) for _ in range({count})]"],
        check=True)


def test_mirror_taps_without_stealing(bridged_pair):
    """mirror:<dev> duplicates matched frames to the tap device and
    CONTINUES — the original still reaches its destination (tap
    semantics, the OVS mirror / P4 clone analogue)."""
    import time

    (ns0, host0), (ns1, _h1) = bridged_pair
    tag = uuid.uuid4().hex[:5]
    tap_a, tap_b = "ta" + tag, "tb" + tag
    subprocess.run(["ip", "link", "add", tap_a, "type", "veth",
                    "peer", "name", tap_b], check=True)
    try:
        for d in (tap_a, tap_b):
            subprocess.run(["ip", "link", "set", d, "up"], check=True)
        table = FlowTable(host0)
        table.add(FlowRule(pref=1, action=f"mirror:{tap_a}", proto="udp"))
        before_tap = _rx_packets(tap_b)
        _udp_burst(ns0, "10.97.0.2", 6001)
        time.sleep(0.3)
        tapped = _rx_packets(tap_b) - before_tap
        assert tapped >= 20, f"tap only saw {tapped} of 20 mirrored packets"
        # Continue semantics: traffic still flows to the real destination.
        assert _tcp_reach(ns0, ns1, "10.97.0.2", 6002), \
            "mirror must not steal the original"
        table.flush()
    finally:
        subprocess.run(["ip", "link", "del", tap_a], capture_output=True)


def test_redirect_steals_matched_traffic(bridged_pair):
    """redirect:<dev> forwards matched frames out the target device
    INSTEAD of the bridge path (nft fwd, the P4 port-forward analogue):
    the scoped flow is stolen, everything else still bridges."""
    import time

    (ns0, host0), (ns1, _h1) = bridged_pair
    tag = uuid.uuid4().hex[:5]
    red_a, red_b = "ra" + tag, "rb" + tag
    subprocess.run(["ip", "link", "add", red_a, "type", "veth",
                    "peer", "name", red_b], check=True)
    try:
        for d in (red_a, red_b):
            subprocess.run(["ip", "link", "set", d, "up"], check=True)
        table = FlowTable(host0)
        table.add(FlowRule(pref=1, action=f"redirect:{red_a}",
                           proto="udp", dst_port=6003))
        before = _rx_packets(red_b)
        _udp_burst(ns0, "10.97.0.2", 6003)
        time.sleep(0.3)
        stolen = _rx_packets(red_b) - before
        assert stolen >= 20, f"redirect target saw {stolen} of 20"
        # The unmatched flow (different port) still bridges normally.
        assert _tcp_reach(ns0, ns1, "10.97.0.2", 6004)
        table.flush()
    finally:
        subprocess.run(["ip", "link", "del", red_a], capture_output=True)


def test_out_of_order_pref_inserts_in_eval_order(bridged_pair):
    """pref IS evaluation order even when rules arrive out of order —
    the insert-before-handle path (NFTA_RULE_POSITION) must place the
    middle rule between its neighbours in the kernel's list."""
    (_ns0, host0), _ = bridged_pair
    table = FlowTable(host0)
    table.add(FlowRule(pref=10, action="accept", proto="icmp"))
    table.add(FlowRule(pref=30, action="drop", proto="udp"))
    table.add(FlowRule(pref=20, action="accept", proto="tcp"))  # middle, last
    try:
        # list() reflects the KERNEL's rule order, not insertion order.
        assert [r["pref"] for r in table.list()] == [10, 20, 30]
    finally:
        table.flush()


def test_foreign_userdata_left_alone(bridged_pair):
    """A rule programmed by another tool — including one whose userdata
    happens to parse as non-dict JSON — must be skipped by list/flush,
    never crashed on or deleted."""
    from dpu_operator_tpu.cni import nftnl
    from dpu_operator_tpu.vsp.flow_table import TABLE

    (_ns0, host0), _ = bridged_pair
    table = FlowTable(host0)
    table.add(FlowRule(pref=1, action="accept", proto="icmp"))
    with nftnl.Nft() as nft:
        nft.add_rule(TABLE, host0, [nftnl.counter()], userdata=b"7")
    try:
        assert [r["pref"] for r in table.list()] == [1]  # foreign skipped
        assert table.flush() == 1  # only ours deleted
        with nftnl.Nft() as nft:
            assert len(nft.dump_rules(TABLE, host0)) == 1, \
                "foreign rule must survive the flush"
    finally:
        with nftnl.Nft() as nft:
            nft.delete_chain(TABLE, host0)  # fails if rules remain


def test_bridge_wide_rule_programming(bridged_pair):
    """--bridge applies the rule to every enslaved port (pipeline scope,
    like a p4rt table): traffic from EITHER pod matching the rule drops;
    flush clears all ports."""
    import io
    import json as jsonlib
    from contextlib import redirect_stdout

    from dpu_operator_tpu import fabric_ctl

    (ns0, host0), (ns1, host1) = bridged_pair
    bridge = "brF" + host0[3:]  # fixture names: fh<i><tag> / brF<tag>
    assert fabric_ctl.main(
        ["rule-add", "--bridge", bridge, "--pref", "4", "--action", "drop",
         "--proto", "tcp", "--dst-port", "7800"]) == 0
    # Blocked in BOTH directions (rule sits on both ports' ingress).
    assert not _tcp_reach(ns0, ns1, "10.97.0.2", 7800)
    assert not _tcp_reach(ns1, ns0, "10.97.0.1", 7800)

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert fabric_ctl.main(["rule-list", "--bridge", bridge]) == 0
    per_dev = jsonlib.loads(buf.getvalue())
    assert set(per_dev) == {host0, host1}
    assert all(rules[0]["pref"] == 4 for rules in per_dev.values())

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert fabric_ctl.main(["rule-flush", "--bridge", bridge]) == 0
    assert jsonlib.loads(buf.getvalue())["flushed"] == {host0: 1, host1: 1}
    assert _tcp_reach(ns0, ns1, "10.97.0.2", 7800)

    # Convergence after a partial apply: one port already carries the
    # identical rule -> bridge-wide add reports unchanged/added (rc 0),
    # never an unrecoverable mid-bridge abort; delete is idempotent at
    # pipeline scope (absent ports are fine).
    assert fabric_ctl.main(
        ["rule-add", host0, "--pref", "6", "--action", "drop",
         "--proto", "udp"]) == 0
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert fabric_ctl.main(
            ["rule-add", "--bridge", bridge, "--pref", "6", "--action",
             "drop", "--proto", "udp"]) == 0
    outcomes = jsonlib.loads(buf.getvalue())["added"]
    assert outcomes == {host0: "unchanged", host1: "added"}
    # Same pref, DIFFERENT spec: a real conflict must surface as error.
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert fabric_ctl.main(
            ["rule-add", "--bridge", bridge, "--pref", "6", "--action",
             "accept"]) == 1
    outcomes = jsonlib.loads(buf.getvalue())["added"]
    assert all(o.startswith("error") for o in outcomes.values())
    fabric_ctl.main(["rule-del", "--bridge", bridge, "6"])
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert fabric_ctl.main(["rule-del", "--bridge", bridge, "6"]) == 0
    assert jsonlib.loads(buf.getvalue())["deleted"] == {
        host0: "absent", host1: "absent"}
