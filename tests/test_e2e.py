"""End-to-end suite — the counterpart of reference e2e_test/e2e_test.go,
run against the zero-hardware tier: in-memory apiserver, real operator
reconcilers, real daemon with TPU FakePlatform detection, the REAL tpuvsp
served over the vendor-plugin gRPC socket, KubeletSim standing in for the
kubelet (registration + ListAndWatch + scheduling + Allocate), and —
when root — real veth/netns pod interfaces bridged by the TPU fabric
dataplane, verified with an actual ping (e2e_test.go:439-456).

Covered, in the reference's order: webhook singleton validation
(:229-359), workload pod with secondary net reaching Running (:432-438),
pod↔pod ping over net1 (:439-456), SFC pod creation with image+resource
assertions (:458-478), SFC deletion (:547-555), and resource-exhaustion
scheduling (N+1 chains vs capacity, pending pod unblocking, :558-626)."""

import json
import os
import socket
import subprocess
import time
import urllib.request
import uuid

import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.api import v1
from dpu_operator_tpu.api.webhook import (
    AdmissionWebhook,
    validate_dpu_operator_config,
)
from dpu_operator_tpu.cni import CniRequest, do_cni
from dpu_operator_tpu.controller.main import build_manager
from dpu_operator_tpu.daemon import Daemon
from dpu_operator_tpu.images import DummyImageManager
from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster, get_condition
from dpu_operator_tpu.parallel import SliceTopology
from dpu_operator_tpu.platform import FakePlatform
from dpu_operator_tpu.testutils import KubeletSim
from dpu_operator_tpu.vsp import VspServer
from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

NODE = "tpu-e2e-node"
TPU_ENV = {"TPU_ACCELERATOR_TYPE": "v5litepod-8", "TPU_WORKER_ID": "0"}
NUM_ENDPOINTS = 8  # the daemon partitions the fabric into 8 (reference SetNumVfs(8))


def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _have_netns() -> bool:
    import os

    if os.geteuid() != 0:
        return False
    probe = "e2e" + uuid.uuid4().hex[:6]
    r = subprocess.run(
        ["ip", "link", "add", probe + "a", "type", "veth", "peer", "name", probe + "b"],
        capture_output=True,
    )
    if r.returncode == 0:
        subprocess.run(["ip", "link", "del", probe + "a"], capture_output=True)
        return True
    return False


HAVE_NETNS = _have_netns()


class Stack:
    """The whole system in one process.

    mode="inmem": components bind the store directly (fast path).
    mode="http": the same store is served over real REST by
    k8s.http_server.ApiServer and every component talks through the
    production HttpClient via a kubeconfig — chunked watch, 409s, status
    subresource and finalizer deletion all cross a real wire (the
    reference proves its client path the same way against Kind/envtest,
    internal/testutils/kindcluster.go:47-64,162-214)."""

    def __init__(self, pm, mode: str = "inmem"):
        self.pm = pm
        self.mode = mode
        self.apiserver = None
        if mode == "http":
            from dpu_operator_tpu.k8s.http_client import HttpClient
            from dpu_operator_tpu.k8s.http_server import ApiServer

            self.apiserver = ApiServer(InMemoryCluster()).start()
            # Direct construction, not client_from_kubeconfig: that helper
            # prefers an in-cluster SA mount when one exists, which inside a
            # real pod would point this stack at the production apiserver.
            self.client = HttpClient(self.apiserver.url)
        else:
            self.client = InMemoryClient(InMemoryCluster())
        self.client.create(
            {
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {"name": NODE, "labels": {v.NODE_OPT_IN_LABEL: v.NODE_OPT_IN_VALUE}},
            }
        )
        # Operator control plane.
        self.operator = build_manager(self.client, DummyImageManager())
        self.operator.start()
        self.client.create(v1.new_dpu_operator_config())

        # Real tpuvsp on the vendor socket; unique bridge per run.
        self.bridge = None
        topology = SliceTopology.from_env(TPU_ENV)
        if HAVE_NETNS:
            from dpu_operator_tpu.vsp.tpu_dataplane import TpuFabricDataplane

            self.bridge = "brE2E" + uuid.uuid4().hex[:6]
            dataplane = TpuFabricDataplane(bridge=self.bridge)
        else:
            from dpu_operator_tpu.vsp.tpu_dataplane import DebugDataplane

            dataplane = DebugDataplane()
        self.vsp = TpuVsp(
            topology=topology,
            dataplane=dataplane,
            opi_port=free_port(),
            num_endpoints=NUM_ENDPOINTS,
        )
        self.vsp_server = VspServer(self.vsp, pm)
        self.vsp_server.start()

        # Kubelet simulator for this node.
        self.kubelet = KubeletSim(self.client, NODE, pm)
        self.kubelet.start()

        # Node daemon with TPU platform detection.
        self.daemon = Daemon(
            self.client,
            FakePlatform(product="Google Cloud TPU", node=NODE, env=TPU_ENV),
            path_manager=pm,
            tick_interval=0.05,
            register_device_plugin=True,
        )
        self.daemon.start()

    def side_manager(self):
        for md in self.daemon.managed().values():
            return md.manager
        return None

    def stop(self):
        self.daemon.stop()
        self.kubelet.stop()
        self.vsp_server.stop()
        self.operator.stop()
        if self.apiserver is not None:
            self.apiserver.stop()
        if self.bridge:
            subprocess.run(["ip", "link", "del", self.bridge], capture_output=True)


@pytest.fixture(scope="module", params=["inmem", "http"])
def stack(request, tmp_path_factory):
    import shutil
    import tempfile

    from dpu_operator_tpu.utils import PathManager

    d = tempfile.mkdtemp(prefix="dpu-")
    s = Stack(PathManager(root=d), mode=request.param)
    try:
        assert wait_for(lambda: s.side_manager() is not None), "daemon never spawned a side manager"
        yield s
    finally:
        s.stop()
        shutil.rmtree(d, ignore_errors=True)


# -- 1. webhook validation (reference e2e_test.go:229-359) --------------------


def test_webhook_rejects_wrong_singleton_name(stack):
    ok, msg, _ = validate_dpu_operator_config(
        {"object": v1.new_dpu_operator_config(name="not-the-singleton")}
    )
    assert not ok and "dpu-operator-config" in msg

    # And over HTTP, the way the apiserver calls it.
    wh = AdmissionWebhook()
    wh.register("/validate-dpuoperatorconfig", validate_dpu_operator_config)
    wh.start()
    try:
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": "e2e-uid",
                "object": v1.new_dpu_operator_config(name="bad-name"),
            },
        }
        req = urllib.request.Request(
            f"http://127.0.0.1:{wh.port}/validate-dpuoperatorconfig",
            data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = json.loads(urllib.request.urlopen(req).read())
        assert resp["response"]["allowed"] is False
    finally:
        wh.stop()


# -- 2. operand rollout + device inventory ------------------------------------


def test_daemonset_rendered_and_dpu_cr_ready(stack):
    assert wait_for(
        lambda: stack.client.get_or_none("apps/v1", "DaemonSet", v.NAMESPACE, "dpu-daemon")
        is not None
    ), "operator never rendered the daemon DaemonSet"
    cr_name = "tpu-v5litepod-8-w0-dpu"
    def ready():
        cr = stack.client.get_or_none(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, cr_name
        )
        if cr is None:
            return False
        cond = get_condition(cr, "Ready")
        return cond is not None and cond["status"] == "True"
    assert wait_for(ready, timeout=30), "DataProcessingUnit CR never went Ready"


def test_node_reports_allocatable_endpoints(stack):
    """Device plugin registered with the (simulated) kubelet and the node
    shows allocatable fabric endpoints (reference
    dpusidemanager_test.go:22-49 waitAllNodesDpuAllocatable)."""
    def allocatable():
        node = stack.client.get("v1", "Node", None, NODE)
        return int(node.get("status", {}).get("allocatable", {}).get(v.DPU_RESOURCE_NAME, "0"))
    assert wait_for(lambda: allocatable() == NUM_ENDPOINTS, timeout=30), (
        f"allocatable={allocatable()}, want {NUM_ENDPOINTS}"
    )


# -- 3. workload pod with secondary network (reference :432-456) --------------


def _workload_pod(name: str) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": "default",
            "annotations": {"k8s.v1.cni.cncf.io/networks": v.DEFAULT_HOST_NAD_NAME},
        },
        "spec": {
            "nodeSelector": {v.NODE_OPT_IN_LABEL: v.NODE_OPT_IN_VALUE},
            "containers": [
                {
                    "name": name,
                    "image": "quay.io/example/workload:1",
                    "resources": {
                        "requests": {v.DPU_RESOURCE_NAME: "1"},
                        "limits": {v.DPU_RESOURCE_NAME: "1"},
                    },
                }
            ],
        },
    }


def test_workload_pod_reaches_running(stack):
    stack.client.create(_workload_pod("workload-a"))
    assert wait_for(
        lambda: (stack.client.get_or_none("v1", "Pod", "default", "workload-a") or {})
        .get("status", {})
        .get("phase")
        == "Running",
        timeout=30,
    ), "workload pod never reached Running"
    pod = stack.client.get("v1", "Pod", "default", "workload-a")
    granted = pod["metadata"]["annotations"].get("dpu.test/allocated")
    assert granted, "no device allocated"

    # The pod can actually use what it was granted: the AllocateResponse
    # mounts exactly the granted endpoints' backing /dev/accel* nodes and
    # carries the TPU runtime env (round-2 verdict Missing #2 — the
    # reference's env-only Allocate, deviceplugin.go:114-142, leaves a
    # char-device accelerator unreachable from the pod).
    from google.protobuf import empty_pb2

    inventory = stack.vsp.GetDevices(empty_pb2.Empty(), None).devices
    want_nodes = sorted({inventory[d].backing for d in granted.split(",")})
    aresp = stack.kubelet.allocate_response(
        v.DPU_RESOURCE_NAME, "default", "workload-a"
    )
    assert aresp is not None, "kubelet recorded no AllocateResponse"
    cresp = aresp.container_responses[0]
    assert sorted(d.host_path for d in cresp.devices) == want_nodes
    assert all(
        d.container_path == d.host_path and d.permissions == "rw"
        for d in cresp.devices
    )
    assert cresp.envs["TPU_VISIBLE_DEVICES"] == ",".join(
        n.replace("/dev/accel", "") for n in want_nodes
    )
    assert cresp.envs["TPU_WORKER_ID"] == "0"
    assert pod["metadata"]["annotations"]["dpu.test/device-nodes"] == ",".join(
        want_nodes
    )
    stack.client.delete("v1", "Pod", "default", "workload-a")


@pytest.mark.skipif(not HAVE_NETNS, reason="needs root + netns/veth")
def test_pod_uses_chip_grant_and_fabric_together(stack):
    """The operator plane and the compute plane meet in ONE workload
    (VERDICT r3 Next #3; reference runs real traffic through granted VFs
    inside pods, e2e_test.go:439-486): a scheduled pod's AllocateResponse
    grants device nodes + TPU env, the CNI gives it net1 on the fabric —
    and a single subprocess INSIDE the pod netns, running with exactly
    the granted env, opens every granted device node rw WHILE streaming
    bytes over net1 to a peer pod. Fails if the Allocate mounts/env or
    the NAD plumbing regress."""
    import os as _os
    import stat as _stat
    import sys as _sys

    # The device plugin must be registered before the pod lands — the
    # kubelet sim (like a real kubelet) can only account extended
    # resources whose plugin it knows about.
    assert wait_for(
        lambda: stack.kubelet.allocatable(v.DPU_RESOURCE_NAME) > 0,
        timeout=20,
    ), "device plugin never registered its resource"
    # Kubelet-path allocation for a scheduled workload pod.
    stack.client.create(_workload_pod("workload-ch"))
    assert wait_for(
        lambda: (stack.client.get_or_none("v1", "Pod", "default", "workload-ch")
                 or {}).get("status", {}).get("phase") == "Running",
        timeout=30,
    ), "workload pod never reached Running"
    # Running is set on the pod before the kubelet sim records the
    # AllocateResponse — wait for the record, not just the phase.
    assert wait_for(
        lambda: stack.kubelet.allocate_response(
            v.DPU_RESOURCE_NAME, "default", "workload-ch") is not None,
        timeout=15,
    ), "kubelet recorded no AllocateResponse"
    aresp = stack.kubelet.allocate_response(
        v.DPU_RESOURCE_NAME, "default", "workload-ch")
    cresp = aresp.container_responses[0]
    assert cresp.devices, "no device nodes granted"

    # This container has no real /dev/accel* (the chip rides the axon
    # tunnel); stand in char nodes (mem/null numbers) for exactly the
    # granted paths so open(O_RDWR) is a real permission+path check.
    created = []
    pod_ns = "e2echip-" + uuid.uuid4().hex[:6]
    peer_ns = "e2epeer-" + uuid.uuid4().hex[:6]
    reqs = []
    try:
        for d in cresp.devices:
            if not _os.path.exists(d.host_path):
                _os.mknod(d.host_path, 0o600 | _stat.S_IFCHR,
                          _os.makedev(1, 3))
                created.append(d.host_path)
        for n in (pod_ns, peer_ns):
            subprocess.run(["ip", "netns", "add", n], check=True)
        podr, _pod_ip, _ = _cni_attach(stack, "chw", pod_ns)
        reqs.append(podr)
        peerr, peer_ip, _ = _cni_attach(stack, "chp", peer_ns)
        reqs.append(peerr)

        payload = b"chip+fabric-" + uuid.uuid4().hex.encode()
        server = subprocess.Popen(
            ["ip", "netns", "exec", peer_ns, _sys.executable, "-u", "-c",
             "import socket\n"
             "s = socket.socket()\n"
             f"s.bind(('{peer_ip}', 9201))\n"
             "s.listen(1)\n"
             "print('listening', flush=True)\n"
             "c, _ = s.accept()\n"
             "buf = b''\n"
             "while True:\n"
             "    d = c.recv(65536)\n"
             "    if not d: break\n"
             "    buf += d\n"
             "print(len(buf), flush=True)\n"],
            stdout=subprocess.PIPE, text=True)
        assert server.stdout.readline().strip() == "listening"

        # THE workload: granted env, granted devices, fabric socket —
        # one process, inside the pod's netns.
        workload = (
            "import json, os, socket, sys\n"
            "devs = sys.argv[1].split(',')\n"
            "for d in devs:\n"
            "    fd = os.open(d, os.O_RDWR)\n"
            "    os.close(fd)\n"
            "env = {k: os.environ[k] for k in ('TPU_VISIBLE_DEVICES',"
            "'TPU_WORKER_ID', 'TPU_CHIP_COORDS', 'TPU_SLICE_ID',"
            "'TPU_NUM_SLICES')}\n"
            f"s = socket.create_connection(('{peer_ip}', 9201), timeout=10)\n"
            f"s.sendall({payload!r} * 1000)\n"
            "s.close()\n"
            "print(json.dumps({'opened': devs, 'env': env}))\n"
        )
        env = dict(os.environ)
        env.update(dict(cresp.envs))
        r = subprocess.run(
            ["ip", "netns", "exec", pod_ns, _sys.executable, "-c", workload,
             ",".join(d.host_path for d in cresp.devices)],
            capture_output=True, text=True, env=env, timeout=30)
        assert r.returncode == 0, f"pod workload failed:\n{r.stderr}"
        result = json.loads(r.stdout)
        assert result["opened"] == [d.host_path for d in cresp.devices]
        assert result["env"]["TPU_VISIBLE_DEVICES"]
        assert result["env"]["TPU_NUM_SLICES"] == "1"
        out = server.communicate(timeout=15)[0]
        assert int(out.strip().splitlines()[-1]) == len(payload) * 1000, out
    finally:
        try:
            if server.poll() is None:
                server.kill()
        except NameError:
            pass  # failed before the server started
        for req in reqs:
            _cni_detach(stack, req)
        for n in (pod_ns, peer_ns):
            subprocess.run(["ip", "netns", "del", n], capture_output=True)
        for path in created:
            try:
                _os.unlink(path)
            except OSError:
                pass
        stack.client.delete("v1", "Pod", "default", "workload-ch")


@pytest.mark.skipif(not HAVE_NETNS, reason="needs root + netns/veth")
def test_jax_distributed_collectives_over_operator_fabric(stack):
    """THE capstone (VERDICT r4 Next #1): the operator-built fabric
    carries real multi-process JAX. Two pods — each with a kubelet-path
    chip grant (AllocateResponse device nodes + TPU env) and a CNI
    fabric attachment — run two REAL JAX processes that
    `jax.distributed.initialize` across the fabric addresses and
    execute a verified cross-process psum plus a 2-worker dp slice of
    the five-axis training step (loss == dense reference, descending).
    The flow-table baseline counters on each pod's bridge port must
    show the collective's bytes actually transited the bridge.

    This is the reference's pod↔pod-over-net1 e2e
    (e2e_test/e2e_test.go:439-456) elevated to the TPU-native workload
    class: the traffic is not iperf but the allreduce/gradient-sync a
    training job would run."""
    import os as _os
    import stat as _stat
    import sys as _sys

    from dpu_operator_tpu.vsp.flow_table import FlowTable
    from dpu_operator_tpu.vsp.tpu_dataplane import BASELINE_PREF

    assert wait_for(
        lambda: stack.kubelet.allocatable(v.DPU_RESOURCE_NAME) > 0,
        timeout=20,
    ), "device plugin never registered its resource"

    # Chip grants: one workload pod per JAX worker through the kubelet
    # allocation path.
    pods, cresps, created = [], [], []
    for i in range(2):
        name = f"jaxwork-{i}"
        stack.client.create(_workload_pod(name))
        pods.append(name)
    try:
        for name in pods:
            assert wait_for(
                lambda n=name: stack.kubelet.allocate_response(
                    v.DPU_RESOURCE_NAME, "default", n) is not None,
                timeout=30,
            ), f"kubelet recorded no AllocateResponse for {name}"
            cresp = stack.kubelet.allocate_response(
                v.DPU_RESOURCE_NAME, "default", name).container_responses[0]
            assert cresp.devices, "no device nodes granted"
            cresps.append(cresp)
            for d in cresp.devices:
                if not _os.path.exists(d.host_path):
                    _os.mknod(d.host_path, 0o600 | _stat.S_IFCHR,
                              _os.makedev(1, 3))
                    created.append(d.host_path)

        # Fabric attachments: two pod netns through the CNI path.
        namespaces, reqs, ips, ports = [], [], [], []
        for i in range(2):
            ns = f"jaxpod{i}-" + uuid.uuid4().hex[:6]
            subprocess.run(["ip", "netns", "add", ns], check=True)
            # A real CRI runs the loopback CNI before any secondary
            # network; without lo, a process dialing its own fabric
            # address (the coordinator does) blackholes.
            subprocess.run(["ip", "-n", ns, "link", "set", "lo", "up"],
                           check=True)
            namespaces.append(ns)
        try:
            from dpu_operator_tpu.cni.dataplane.fabric import _host_ifname

            for i, ns in enumerate(namespaces):
                req, ip, _mac = _cni_attach(stack, f"jx{i}", ns)
                reqs.append(req)
                ips.append(ip)
                ports.append(_host_ifname(req.container_id, req.ifname))

            def baseline_bytes(port):
                for r in FlowTable(port).list(stats=True):
                    if r["pref"] == BASELINE_PREF:
                        return r["bytes"] or 0
                return 0

            before = [baseline_bytes(p) for p in ports]

            # Launch the two JAX workers: process 0 (coordinator) in
            # pod 0's netns, process 1 in pod 1's — rendezvous address
            # is pod 0's FABRIC ip, so even the coordination-service
            # dial rides the bridge.
            coord = f"{ips[0]}:9401"
            procs = []
            for i, ns in enumerate(namespaces):
                env = dict(os.environ)
                env.update(dict(cresps[i].envs))
                procs.append(subprocess.Popen(
                    ["ip", "netns", "exec", ns, _sys.executable, "-m",
                     "dpu_operator_tpu.parallel.fabric_worker",
                     "--process-id", str(i), "--num-processes", "2",
                     "--coordinator", coord, "--bind-ip", ips[i],
                     "--payload-mb", "4", "--iters", "5",
                     "--peer-ips", ",".join(ips),
                     "--devices",
                     ",".join(d.host_path for d in cresps[i].devices)],
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True, env=env,
                    cwd=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__)))))
            results = []
            try:
                for i, p in enumerate(procs):
                    out, err = p.communicate(timeout=240)
                    assert p.returncode == 0, (
                        f"jax worker {i} failed rc={p.returncode}:"
                        f"\n{err[-4000:]}")
                    results.append(json.loads(out.strip().splitlines()[-1]))
            except subprocess.TimeoutExpired:
                dumps = []
                for i, p in enumerate(procs):
                    p.kill()
                    out, err = p.communicate(timeout=10)
                    dumps.append(f"worker {i} stderr:\n{err[-3000:]}")
                raise AssertionError(
                    "jax worker hung on the fabric:\n" + "\n".join(dumps))

            for i, r in enumerate(results):
                assert r["ok"] and r["psum_ok"], r
                assert r["process_count"] == 2 and r["n_devices"] == 2, r
                # With --peer-ips wired, the custom pipelined ring
                # transport must actually carry the headline allreduce
                # (a silent fall-back to gloo would quietly undo the
                # ISSUE-1 optimization while staying green).
                assert r["collective_transport"] == "ring", r
                assert r["ring_ok"], r
                assert r["train_matches_dense"] and r["train_loss_descends"], r
                assert r["devices_opened"] == [
                    d.host_path for d in cresps[i].devices], r
                assert r["granted_env"].get("TPU_VISIBLE_DEVICES"), r
            # Both processes agree on the loss trajectory — one global
            # program, not two local ones.
            assert results[0]["train_losses"] == results[1]["train_losses"]

            # The bytes crossed the OPERATOR's bridge: each pod's port
            # counter grew by at least one reduce step's payload.
            after = [baseline_bytes(p) for p in ports]
            for i, port in enumerate(ports):
                delta = after[i] - before[i]
                assert delta >= results[i]["min_port_bytes"], (
                    f"port {port} moved only {delta} bytes; the "
                    f"collective cannot have transited the fabric")
        finally:
            for req in reqs:
                _cni_detach(stack, req)
            for ns in namespaces:
                subprocess.run(["ip", "netns", "del", ns],
                               capture_output=True)
    finally:
        for path in created:
            try:
                _os.unlink(path)
            except OSError:
                pass
        for name in pods:
            stack.client.delete("v1", "Pod", "default", name)


@pytest.mark.skipif(not HAVE_NETNS, reason="needs root + netns/veth")
def test_pod_to_pod_ping_over_net1(stack):
    """Two pod netns, both attached through the CNI path, REAL ping over
    the fabric bridge (reference pingTest, e2e_test.go:439-456)."""
    sm = stack.side_manager()
    sock = sm.cni_server.socket_path
    conf = {"cniVersion": "1.0.0", "name": v.DEFAULT_HOST_NAD_NAME, "type": "dpu-cni"}
    namespaces, ips, reqs = [], [], []
    try:
        for i in range(2):
            ns = f"e2epod{i}-" + uuid.uuid4().hex[:6]
            subprocess.run(["ip", "netns", "add", ns], check=True)
            namespaces.append(ns)
            req = CniRequest(
                command="ADD",
                container_id=f"e2ec{i}" + uuid.uuid4().hex[:10],
                netns=ns,
                ifname="net1",
                config=conf,
            )
            reqs.append(req)
            result = do_cni(sock, req)
            ips.append(result["ips"][0]["address"].split("/")[0])
        # No ping binary in this image; a TCP round-trip across the two
        # pod netns proves the same L3 path through the fabric bridge.
        import sys

        server = subprocess.Popen(
            [
                "ip", "netns", "exec", namespaces[1], sys.executable, "-c",
                "import socket\n"
                "s = socket.socket()\n"
                f"s.bind(('{ips[1]}', 9000))\n"
                "s.listen(1)\n"
                "c, _ = s.accept()\n"
                "print(c.recv(16).decode(), flush=True)\n",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            time.sleep(0.5)
            r = subprocess.run(
                [
                    "ip", "netns", "exec", namespaces[0], sys.executable, "-c",
                    "import socket\n"
                    f"s = socket.create_connection(('{ips[1]}', 9000), timeout=5)\n"
                    "s.send(b'e2e-traffic')\n"
                    "s.close()\n",
                ],
                capture_output=True,
                text=True,
                timeout=10,
            )
            assert r.returncode == 0, f"TCP connect failed:\n{r.stdout}\n{r.stderr}"
            out, err = server.communicate(timeout=10)
            assert "e2e-traffic" in out, f"server never got payload: {out!r} {err!r}"
        finally:
            if server.poll() is None:
                server.kill()
    finally:
        for req in reqs:
            try:
                do_cni(sock, CniRequest(
                    command="DEL", container_id=req.container_id, netns=req.netns,
                    ifname="net1", config=conf,
                ))
            except Exception:
                pass
        for ns in namespaces:
            subprocess.run(["ip", "netns", "del", ns], capture_output=True)


# -- 4. service function chains (reference :458-478, :547-555) ---------------


def _sfc(i: int) -> dict:
    return v1.new_service_function_chain(
        f"sfc-test{i}",
        v.NAMESPACE,
        node_selector={v.NODE_OPT_IN_LABEL: v.NODE_OPT_IN_VALUE},
        network_functions=[{"name": f"test-nf{i}", "image": "quay.io/example/nf:1"}],
    )


def test_sfc_pod_created_and_running(stack):
    stack.client.create(_sfc(0))
    def nf_pod():
        return stack.client.get_or_none("v1", "Pod", v.NAMESPACE, "test-nf0")
    assert wait_for(lambda: nf_pod() is not None, timeout=15), "NF pod never created"
    pod = nf_pod()
    ctr = pod["spec"]["containers"][0]
    assert ctr["image"] == "quay.io/example/nf:1"
    assert ctr["resources"]["requests"][v.DPU_RESOURCE_NAME] == "2"
    assert wait_for(
        lambda: (nf_pod() or {}).get("status", {}).get("phase") == "Running",
        timeout=30,
    ), "NF pod never scheduled against fabric endpoints"


def test_sfc_deletion_removes_nf_pod(stack):
    stack.client.delete(
        v1.GROUP_VERSION, v1.KIND_SERVICE_FUNCTION_CHAIN, v.NAMESPACE, "sfc-test0"
    )
    assert wait_for(
        lambda: stack.client.get_or_none("v1", "Pod", v.NAMESPACE, "test-nf0") is None,
        timeout=15,
    ), "NF pod survived SFC deletion"


# -- 5. resource exhaustion (reference :558-626) ------------------------------


def test_resource_exhaustion_and_unblock(stack):
    """With 4 endpoints and 2 per NF pod, the 3rd chain must stay Pending;
    deleting one chain unblocks it."""
    n_fit = NUM_ENDPOINTS // 2
    for i in range(1, n_fit + 2):
        stack.client.create(_sfc(i))
    for i in range(1, n_fit + 1):
        assert wait_for(
            lambda i=i: (stack.client.get_or_none("v1", "Pod", v.NAMESPACE, f"test-nf{i}") or {})
            .get("status", {})
            .get("phase")
            == "Running",
            timeout=30,
        ), f"NF pod {i} never ran"
    extra = n_fit + 1
    time.sleep(0.5)
    pod = stack.client.get_or_none("v1", "Pod", v.NAMESPACE, f"test-nf{extra}")
    assert pod is not None and pod.get("status", {}).get("phase") != "Running", (
        "over-capacity NF pod should be Pending"
    )
    # Delete one running chain → the pending pod gets its endpoints.
    stack.client.delete(
        v1.GROUP_VERSION, v1.KIND_SERVICE_FUNCTION_CHAIN, v.NAMESPACE, "sfc-test1"
    )
    assert wait_for(
        lambda: (stack.client.get_or_none("v1", "Pod", v.NAMESPACE, f"test-nf{extra}") or {})
        .get("status", {})
        .get("phase")
        == "Running",
        timeout=30,
    ), "pending NF pod never unblocked after capacity freed"
    for i in range(2, n_fit + 2):
        stack.client.delete_if_exists(
            v1.GROUP_VERSION, v1.KIND_SERVICE_FUNCTION_CHAIN, v.NAMESPACE, f"sfc-test{i}"
        )


# -- 5. external + NF traffic (reference :479-546) ----------------------------
#
# The reference drives pod↔NF, NF↔external, and pod↔external over lab
# hardware with EXTERNAL_CLIENT_IP/DEV + NF_INGRESS_IP env config
# (e2e_test.go:106-134,479-546) and honors SKIP_NF_TESTING (:421-423).
# Here "external" is a netns attached to the fabric bridge through an
# uplink veth — the same topology, zero hardware.

SKIP_NF = os.environ.get("SKIP_NF_TESTING", "").lower() in ("1", "true")


def _cni_attach(stack, tag, netns, ifname="net1"):
    """CNI ADD into an existing netns; returns (request, ip, mac)."""
    sm = stack.side_manager()
    conf = {"cniVersion": "1.0.0", "name": v.DEFAULT_HOST_NAD_NAME, "type": "dpu-cni"}
    req = CniRequest(
        command="ADD", container_id=tag + uuid.uuid4().hex[:8], netns=netns,
        ifname=ifname, config=conf,
    )
    result = do_cni(sm.cni_server.socket_path, req)
    ip = result["ips"][0]["address"].split("/")[0]
    mac = json.loads(subprocess.run(
        ["ip", "-n", netns, "-j", "link", "show", "dev", ifname],
        capture_output=True, text=True, check=True,
    ).stdout)[0]["address"]
    return req, ip, mac


def _cni_detach(stack, req):
    sm = stack.side_manager()
    try:
        do_cni(sm.cni_server.socket_path, CniRequest(
            command="DEL", container_id=req.container_id, netns=req.netns,
            ifname=req.ifname, config=req.config,
        ))
    except Exception:
        pass


def _tcp_roundtrip(server_ns, server_ip, client_ns, payload, port=9100):
    import sys as _sys

    server = subprocess.Popen(
        ["ip", "netns", "exec", server_ns, _sys.executable, "-u", "-c",
         "import socket\n"
         "s = socket.socket()\n"
         f"s.bind(('{server_ip}', {port}))\n"
         "s.listen(1)\n"
         "print('listening', flush=True)\n"
         "c, _ = s.accept()\n"
         "print(c.recv(64).decode(), flush=True)\n"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        assert server.stdout.readline().strip() == "listening", "server died"
        r = subprocess.run(
            ["ip", "netns", "exec", client_ns, _sys.executable, "-c",
             "import socket, time\n"
             "deadline = time.monotonic() + 10\n"
             "while True:\n"
             "    try:\n"
             f"        s = socket.create_connection(('{server_ip}', {port}), timeout=5)\n"
             "        break\n"
             "    except OSError:\n"
             "        if time.monotonic() > deadline: raise\n"
             "        time.sleep(0.05)\n"
             f"s.send({payload!r}.encode())\n"
             "s.close()\n"],
            capture_output=True, text=True, timeout=25,
        )
        assert r.returncode == 0, f"client failed:\n{r.stdout}\n{r.stderr}"
        out, err = server.communicate(timeout=10)
        assert payload in out, f"server never got payload: {out!r} {err!r}"
    finally:
        if server.poll() is None:
            server.kill()


class _External:
    """An 'external client': netns reachable through an uplink veth
    enslaved to the fabric bridge (EXTERNAL_CLIENT_IP/DEV analogue)."""

    def __init__(self, bridge):
        self.ns = "e2eext-" + uuid.uuid4().hex[:6]
        self.ip = os.environ.get("EXTERNAL_CLIENT_IP", "10.56.0.254")
        dev = os.environ.get("EXTERNAL_CLIENT_DEV", "extup" + uuid.uuid4().hex[:4])
        self.dev = dev
        try:
            subprocess.run(["ip", "netns", "add", self.ns], check=True)
            subprocess.run(["ip", "link", "add", dev, "type", "veth",
                            "peer", "name", dev + "p"], check=True)
            subprocess.run(["ip", "link", "set", dev, "master", bridge], check=True)
            subprocess.run(["ip", "link", "set", dev, "up"], check=True)
            subprocess.run(["ip", "link", "set", dev + "p", "netns", self.ns], check=True)
            subprocess.run(["ip", "-n", self.ns, "link", "set", dev + "p", "up"], check=True)
            subprocess.run(["ip", "-n", self.ns, "addr", "add", self.ip + "/24",
                            "dev", dev + "p"], check=True)
        except Exception:
            self.close()  # never leak half-built netns/veth state
            raise

    def close(self):
        subprocess.run(["ip", "link", "del", self.dev], capture_output=True)
        subprocess.run(["ip", "netns", "del", self.ns], capture_output=True)


@pytest.mark.skipif(not HAVE_NETNS, reason="needs root + netns/veth")
def test_pod_to_external_traffic(stack):
    """Pod ↔ external client through the bridge uplink (reference
    pod-to-external, e2e_test.go:487-546)."""
    ns = "e2epodx-" + uuid.uuid4().hex[:6]
    ext = req = None
    try:
        subprocess.run(["ip", "netns", "add", ns], check=True)
        ext = _External(stack.bridge)
        req, pod_ip, _ = _cni_attach(stack, "extc", ns)
        # Both directions: pod serves / external connects, then reversed.
        _tcp_roundtrip(ns, pod_ip, ext.ns, "pod-from-external")
        _tcp_roundtrip(ext.ns, ext.ip, ns, "external-from-pod", port=9101)
    finally:
        if req:
            _cni_detach(stack, req)
        if ext:
            ext.close()
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)


@pytest.mark.skipif(not HAVE_NETNS, reason="needs root + netns/veth")
@pytest.mark.skipif(SKIP_NF, reason="SKIP_NF_TESTING set")
def test_pod_and_external_to_nf_with_chain_wiring(stack):
    """The NF scenarios (reference pod↔NF :479-486, NF↔external
    :487-546): an NF netns gets TWO fabric attachments (the two-NAD pod
    shape, sfc.go:35-76), the VSP chains their MACs over the dpu-api
    contract, the dataplane records hairpin + static-FDB pinning
    (verifiable via fabric-ctl ports), and real traffic reaches the NF
    from a pod and from the external client."""
    import grpc as grpclib

    from dpu_operator_tpu.dpu_api import services
    from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb

    nf_ns = "e2enf-" + uuid.uuid4().hex[:6]
    pod_ns = "e2epodn-" + uuid.uuid4().hex[:6]
    ext = None
    reqs = []
    try:
        for n in (nf_ns, pod_ns):
            subprocess.run(["ip", "netns", "add", n], check=True)
        ext = _External(stack.bridge)
        nf1, nf1_ip, nf1_mac = _cni_attach(stack, "nfa", nf_ns, ifname="net1")
        reqs.append(nf1)
        nf2, _, nf2_mac = _cni_attach(stack, "nfa", nf_ns, ifname="net2")
        reqs.append(nf2)
        podr, _, _ = _cni_attach(stack, "podn", pod_ns)
        reqs.append(podr)

        # Chain the two NF ports over the vendor-plugin gRPC contract.
        chan = grpclib.insecure_channel(f"unix://{stack.pm.vendor_plugin_socket()}")
        stub = services.NetworkFunctionStub(chan)
        stub.CreateNetworkFunction(
            pb.NFRequest(input=nf1_mac, output=nf2_mac), timeout=10
        )

        # Dataplane state: both NF ports hairpinned with static-pinned
        # MACs — read back through the ops CLI.
        from dpu_operator_tpu.fabric_ctl import main as fabric_ctl
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert fabric_ctl(["ports", "--bridge", stack.bridge]) == 0
        ports = json.loads(buf.getvalue())["ports"]
        chained = [
            p for p in ports.values()
            if p["hairpin"] and any(
                e["mac"] in (nf1_mac, nf2_mac) and "static" in str(e)
                for e in p["fdb"]
            )
        ]
        assert len(chained) == 2, f"expected 2 chained NF ports: {ports}"

        # pod → NF and external → NF traffic.
        _tcp_roundtrip(nf_ns, nf1_ip, pod_ns, "nf-from-pod", port=9102)
        _tcp_roundtrip(nf_ns, nf1_ip, ext.ns, "nf-from-external", port=9103)

        stub.DeleteNetworkFunction(
            pb.NFRequest(input=nf1_mac, output=nf2_mac), timeout=10
        )
        chan.close()
    finally:
        for req in reqs:
            _cni_detach(stack, req)
        if ext:
            ext.close()
        for n in (nf_ns, pod_ns):
            subprocess.run(["ip", "netns", "del", n], capture_output=True)
