"""Multi-tenant QoS, KV-aware preemption, role-aware autoscaling
(ISSUE 20).

Three planes, one invariant each:

  * admission — per-tenant token buckets (429 + honest Retry-After),
    strict priority classes, weighted-fair interleave within a class,
    and a per-tenant depth cap so one flooding tenant cannot own the
    queue;
  * preemption — an interactive arrival with every slot full parks the
    coldest batch occupant's KV into the host tier and requeues it at
    the front of its own class; resume restores from the tier and the
    stream is BYTE-IDENTICAL to an unpreempted run, with strictly
    fewer replayed device steps than a re-decode (proven in the
    trace), `attempts` untouched, settle exactly once;
  * autoscaling — the RoleAutoscaler's tick() is a public thread-free
    seam, so hysteresis/cooldown/dampening/park-unpark are all
    deterministic unit decisions, and a live flip_role() under load
    loses zero settled tokens.

All tier-1, SyntheticKVExecutor for scheduler-plane determinism plus
PagedKVExecutor (the jitted plane) for the byte-identical acceptance.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from collections import Counter

import pytest

from dpu_operator_tpu import faults
from dpu_operator_tpu.obs import trace as obs_trace
from dpu_operator_tpu.serving import (PRIORITIES, AdmissionQueue,
                                      ContinuousBatcher, DisaggPool,
                                      GenerateRequest, QueueFull,
                                      RoleAutoscaler, ServingServer,
                                      SyntheticExecutor,
                                      SyntheticKVExecutor, TenantBudget,
                                      TenantOverBudget)
from dpu_operator_tpu.utils.metrics import Registry

POOL_OPTS = dict(watchdog_s=0.5, restart_backoff_s=0.01, poll_s=0.005)

# Lane clock: stamped by the first RUN test in this file, not at
# import time — an import-time stamp would charge this lane for every
# suite that runs before it in a full tier-1 pass.
_LANE_T0: list = []


@pytest.fixture(autouse=True)
def _lane_clock():
    if not _LANE_T0:
        _LANE_T0.append(time.perf_counter())
    yield


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    leaked = faults.active_plan()
    faults.uninstall()
    assert leaked is None, "test leaked an installed FaultPlan"


@pytest.fixture()
def settle_counts(monkeypatch):
    counts = Counter()
    orig = GenerateRequest.finish

    def counting(self):
        counts[self.request_id] += 1
        orig(self)

    monkeypatch.setattr(GenerateRequest, "finish", counting)
    return counts


def _req(prompt=None, max_tokens=6, deadline_s=60.0, tenant="default",
         priority="interactive"):
    return GenerateRequest(
        prompt_vec=None, max_tokens=max_tokens,
        deadline=time.monotonic() + deadline_s,
        prompt_tokens=list(prompt) if prompt is not None else [1, 2, 3],
        tenant=tenant, priority=priority)


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.002)
    assert cond(), f"timed out waiting for {msg}"


# -- admission: token buckets, priorities, weighted-fair pop ------------------


def test_token_bucket_429_with_honest_retry_hint():
    q = AdmissionQueue(max_depth=16, retry_after_s=0.5,
                       tenants={"slow": TenantBudget(rate=2.0,
                                                     burst=1.0)})
    q.submit(_req(tenant="slow"))  # burns the single burst token
    with pytest.raises(TenantOverBudget) as ei:
        q.submit(_req(tenant="slow"))
    # The hint is the real refill time when it exceeds the static
    # default: 1/rate = 0.5s here, never less than retry_after_s.
    assert ei.value.retry_after_s >= 0.5
    assert q.rejected_over_budget == 1
    # Unmetered tenants are untouched by someone else's bucket.
    q.submit(_req(tenant="other"))
    assert q.depth() == 2


def test_strict_priority_pop_order():
    q = AdmissionQueue(max_depth=16)
    batch = [_req(priority="batch") for _ in range(3)]
    inter = [_req(priority="interactive") for _ in range(2)]
    for r in batch + inter:
        q.submit(r)
    got = q.get_many(5)
    q.mark_placed(len(got))
    # Every interactive pops before any batch, submission order aside.
    assert [r.priority for r in got] == (["interactive"] * 2
                                         + ["batch"] * 3)
    assert q.waiting("interactive") == 0 and q.waiting("batch") == 0


def test_weighted_fair_interleave_within_class():
    q = AdmissionQueue(max_depth=64,
                       tenants={"heavy": TenantBudget(weight=2.0),
                                "light": TenantBudget(weight=1.0)})
    for _ in range(6):
        q.submit(_req(tenant="heavy"))
        q.submit(_req(tenant="light"))
    got = q.get_many(9)
    q.mark_placed(len(got))
    # Weighted round-robin: the weight is the consecutive-pop quantum,
    # so the stream runs heavy,heavy,light repeating.
    assert [r.tenant for r in got] == ["heavy", "heavy", "light"] * 3


def test_tenant_depth_cap_leaves_room_for_others():
    q = AdmissionQueue(max_depth=8,
                       tenants={"flood": TenantBudget(weight=1.0),
                                "quiet": TenantBudget(weight=1.0)})
    admitted = 0
    with pytest.raises(QueueFull):
        for _ in range(9):
            q.submit(_req(tenant="flood"))
            admitted += 1
    # Equal weights over max_depth=8: flood caps at its half.
    assert admitted == 4
    # The other tenant still has its whole share.
    for _ in range(4):
        q.submit(_req(tenant="quiet"))
    assert q.depth() == 8


def test_single_tenant_back_compat_no_cap():
    # No tenants configured: the ISSUE 5 contract exactly — depth is
    # the only bound, everything defaults to interactive/default.
    q = AdmissionQueue(max_depth=4)
    for _ in range(4):
        q.submit(_req())
    with pytest.raises(QueueFull):
        q.submit(_req())


# -- requeue x preemption: exempt, front-of-class, attempts untouched ---------


def test_preempted_requeue_front_of_class_drain_and_depth_exempt():
    q = AdmissionQueue(max_depth=2)
    a, b = _req(priority="batch"), _req(priority="batch")
    q.submit(a)
    q.submit(b)
    victim = _req(priority="batch")
    victim.attempts = 0
    q.begin_drain()  # a draining queue refuses submit()...
    q.requeue(victim, preempted=True)  # ...but never a preemptee
    assert q.depth() == 3  # depth bound exempt too
    assert victim.attempts == 0, \
        "preemption is policy, not failure — no attempts burn"
    assert q.preempted_requeued == 1
    got = q.get_many(3)
    q.mark_placed(len(got))
    # Front of its OWN class: the victim pops before a/b.
    assert got[0] is victim


def test_preempted_ahead_of_batch_behind_interactive():
    q = AdmissionQueue(max_depth=8)
    q.submit(_req(priority="batch"))
    q.submit(_req(priority="interactive"))
    victim = _req(priority="batch")
    q.requeue(victim, preempted=True)
    got = q.get_many(3)
    q.mark_placed(len(got))
    assert [r.priority for r in got] == ["interactive", "batch",
                                         "batch"]
    assert got[1] is victim


def test_deadline_while_parked_truncates_once_and_releases_pins(
        settle_counts):
    """A preempted request whose deadline lapses while its KV sits in
    the host tier settles EXACTLY once, as a truncated 200 (it has
    settled tokens), through finish() — which releases the ParkedKV's
    tier pins."""
    ex = SyntheticKVExecutor(slots=1, block_size=4, num_blocks=64,
                             prefill_chunk=4, pipelined=False,
                             host_tier_bytes=1 << 20)
    prompt = list(range(8))
    r = _req(prompt, max_tokens=4, priority="batch")
    lease = ex.kv_attach(0, r)
    # Decode two tokens so the park has settled work to keep.
    while len(r.tokens) < 2:
        t = int(ex.collect(ex.submit((), gen=ex.kv_gen()))[0])
        if t >= 0:
            r.tokens.append(t)
    res = ex.kv_preempt_slot(0, r)
    assert res is not None and res["parked_blocks"] > 0
    assert r.kv_lease is not None and r.kv_lease.resumable
    assert ex.tier.leaked(), "park must hold tier pins while queued"

    q = AdmissionQueue(max_depth=4)
    r.deadline = time.monotonic() - 0.001  # lapse while parked
    q.requeue(r, preempted=True)
    assert q.get_many(1) == []  # shed at pop: deadline disposition
    assert r.done and r.error is None and r.truncated
    assert list(r.tokens), "truncated 200 keeps the settled tokens"
    assert settle_counts[r.request_id] == 1
    ex.tier.assert_clean()  # finish() hook checked the pins back in
    if ex.prefix is not None:
        ex.prefix.flush()
    ex.tier.flush()
    ex.allocator.assert_clean()
    ex.close()


# -- preempt -> park -> resume: byte-identical streams ------------------------


def _mk_kv_executor(backend, pipelined):
    if backend == "synthetic":
        return SyntheticKVExecutor(
            slots=1, block_size=4, num_blocks=64,
            max_blocks_per_req=16, prefill_chunk=8,
            pipelined=pipelined, step_time_s=0.02,
            host_tier_bytes=1 << 20)
    from dpu_operator_tpu.serving import PagedKVExecutor

    return PagedKVExecutor(
        slots=1, block_size=4, num_blocks=64, max_blocks_per_req=16,
        prefill_chunk=8, d=16, heads=2, vocab=32,
        mode="pipelined" if pipelined else "sync",
        host_tier_bytes=1 << 20)


@pytest.mark.parametrize("pipelined", [True, False])
@pytest.mark.parametrize("backend", ["synthetic", "paged"])
def test_preempt_park_resume_byte_identical(backend, pipelined,
                                            settle_counts):
    """The ISSUE 20 acceptance: a batch request preempted mid-decode
    (KV parked to the host tier, requeued front-of-class) resumes to
    the EXACT stream an unpreempted run produces, on both loop shapes
    and both the jax-free and jitted planes — and the trace proves the
    resume replayed strictly fewer device steps than a re-decode."""
    t0 = time.perf_counter()
    plen, chunk, max_toks = 16, 8, 8
    batch_prompt = [int(x) for x in range(plen)]
    inter_prompt = [int(x) + 1 for x in range(plen)]

    def run(preempt):
        ex = _mk_kv_executor(backend, pipelined)
        q = AdmissionQueue(max_depth=8)
        b = ContinuousBatcher(ex, q)
        victim = _req(batch_prompt, max_tokens=max_toks,
                      priority="batch", tenant="bulk")
        inter = _req(inter_prompt, max_tokens=3,
                     priority="interactive", tenant="live")
        q.submit(victim)
        b.start()
        try:
            if preempt:
                # Land the interactive arrival mid-decode: with the
                # single slot occupied, _maybe_preempt_kv parks the
                # batch occupant on the next loop iteration.
                _wait(lambda: len(victim.tokens) >= 1,
                      msg="victim decoding")
                q.submit(inter)
                assert inter.wait(20), "interactive request lost"
            assert victim.wait(20), "victim lost"
            if not preempt:
                q.submit(inter)
                assert inter.wait(20), "interactive request lost"
        finally:
            b.stop()
        assert victim.error is None and inter.error is None
        if ex.prefix is not None:
            ex.prefix.flush()
        if ex.tier is not None:
            ex.tier.assert_clean()
            ex.tier.flush()
        ex.allocator.assert_clean()
        stats = dict(preempted=ex.preempted_total,
                     resumed=ex.preempt_resumed_total,
                     requeued=q.preempted_requeued)
        if hasattr(ex, "close"):
            ex.close()
        return (list(victim.tokens), list(inter.tokens)), victim, stats

    golden, _, base_stats = run(preempt=False)
    assert base_stats["preempted"] == 0
    with obs_trace.scoped() as tr:
        streams, victim, stats = run(preempt=True)
        spans = tr.spans_snapshot()

    assert streams == golden, (streams, golden)
    assert victim.preemptions >= 1
    assert victim.attempts == 0, "preemption must not burn attempts"
    assert stats["preempted"] >= 1 and stats["resumed"] >= 1
    assert stats["requeued"] == victim.preemptions
    assert set(settle_counts.values()) == {1}, settle_counts

    # Trace proof of the cheap resume: the victim appears in strictly
    # fewer post-preempt device steps than re-decoding the prompt plus
    # every token again would need.
    preempts = [s for s in spans if s.name == "batcher.preempt"
                and s.request_id == victim.request_id]
    assert preempts, "preempt event missing from trace"
    assert preempts[0].attrs.get("parked_blocks", 0) > 0
    queue_rq = [s for s in spans if s.name == "queue.requeue"
                and s.request_id == victim.request_id]
    assert queue_rq and queue_rq[0].attrs.get("preempted"), \
        "requeue did not ride the preempted path"
    t_pre = preempts[0].t0
    replayed = sum(
        1 for s in spans
        if s.name == "step.device" and s.t0 > t_pre
        and victim.request_id in (s.attrs.get("request_ids") or ()))
    full_redecode = -(-plen // chunk) + max_toks
    assert 0 < replayed < full_redecode, (replayed, full_redecode)
    assert time.perf_counter() - t0 < 30.0


# -- autoscaler: deterministic tick() decisions -------------------------------


class _StubRole:
    def __init__(self, name_prefix, n_live=2):
        self.name_prefix = name_prefix
        self._names = [f"{name_prefix}{i}" for i in range(n_live)]
        self._parked = []

    def live_count(self):
        return len(self._names) - len(self._parked)

    def park_replica(self, min_live=0):
        live = [n for n in self._names if n not in self._parked]
        if len(live) - 1 < min_live:
            return None
        name = live[-1]
        self._parked.append(name)
        return name

    def unpark_replica(self, i):
        name = self._names[i]
        if name not in self._parked:
            return None
        self._parked.remove(name)
        return name


class _StubDepth:
    def __init__(self):
        self.n = 0

    def depth(self):
        return self.n


class _StubDisagg:
    def __init__(self):
        self.queue = _StubDepth()
        self.decode_queue = _StubDepth()
        self.backlog = 0
        self.prefill_pool = _StubRole("prefill", n_live=2)
        self.decode_pool = _StubRole("decode", n_live=2)
        self.flips = []
        self.flip_ok = True
        self._active = 0

    def transfer_backlog(self):
        return self.backlog

    def active(self):
        return self._active

    def flip_role(self, from_role):
        self.flips.append(from_role)
        return f"moved-{from_role}" if self.flip_ok else None


def test_autoscaler_flip_needs_hysteresis_then_cooldown():
    pool = _StubDisagg()
    asc = RoleAutoscaler(pool, flip_margin=4, hysteresis=3,
                         cooldown_s=10.0)
    pool.queue.n = 9  # prefill-starved: skew +9
    assert asc.tick(now=0.0) is None
    assert asc.tick(now=0.1) is None
    assert asc.tick(now=0.2) == "flip_to_prefill"
    assert pool.flips == ["decode"]  # borrow FROM the decode pool
    # Cooldown: pressure persists but the controller holds.
    assert asc.tick(now=0.3) is None
    assert asc.tick(now=0.4) is None
    assert asc.tick(now=0.5) is None
    assert pool.flips == ["decode"]
    # Past the cooldown the streak has rebuilt; it flips again.
    assert asc.tick(now=11.0) == "flip_to_prefill"
    assert asc.flips == 2


def test_autoscaler_streak_resets_on_balanced_tick():
    pool = _StubDisagg()
    asc = RoleAutoscaler(pool, flip_margin=4, hysteresis=3,
                         cooldown_s=0.0)
    pool.queue.n = 9
    asc.tick(now=0.0)
    asc.tick(now=0.1)
    pool.queue.n = 0  # one balanced tick kills the streak
    asc.tick(now=0.2)
    pool.queue.n = 9
    asc.tick(now=0.3)
    asc.tick(now=0.4)
    assert pool.flips == []  # never reached hysteresis
    assert asc.tick(now=0.5) == "flip_to_prefill"


def test_autoscaler_decode_pressure_counts_transfer_backlog():
    pool = _StubDisagg()
    asc = RoleAutoscaler(pool, flip_margin=4, hysteresis=1,
                         cooldown_s=0.0)
    # decode queue alone is under the margin; the in-flight transfer
    # backlog is decode work the pool has not absorbed yet.
    pool.decode_queue.n = 2
    pool.backlog = 3
    assert asc.tick(now=0.0) == "flip_to_decode"
    assert pool.flips == ["prefill"]


def test_autoscaler_host_gap_dampens_decode_flip():
    reg = Registry()
    pool = _StubDisagg()
    asc = RoleAutoscaler(pool, registry=reg, flip_margin=4,
                         hysteresis=1, cooldown_s=0.0,
                         host_gap_ceiling=0.9)
    # Decode steps 95% host-gap: another decode replica adds another
    # python loop to the same wall, so the flip is vetoed.
    reg.observe("serving_host_gap_seconds", 0.95,
                {"replica": "decode0"})
    reg.observe("serving_step_device_seconds", 0.05,
                {"replica": "decode0"})
    pool.decode_queue.n = 9
    assert asc.tick(now=0.0) is None
    assert pool.flips == [] and asc.dampened == 1
    assert reg.counter_value("serving_autoscale_dampened_total",
                             {"reason": "host_gap"}) == 1
    # Device-bound decode (gap share under the ceiling) flips.
    reg.observe("serving_step_device_seconds", 10.0,
                {"replica": "decode0"})
    assert asc.tick(now=1.0) == "flip_to_decode"
    assert pool.flips == ["prefill"]


def test_autoscaler_parks_on_idle_and_unparks_on_pressure():
    pool = _StubDisagg()
    asc = RoleAutoscaler(pool, flip_margin=4, hysteresis=3,
                         cooldown_s=0.0, idle_park_s=1.0, min_live=1)
    assert asc.tick(now=0.0) is None  # idle clock starts
    assert asc.tick(now=0.5) is None  # not idle long enough
    assert asc.tick(now=1.5) == "park"
    assert asc.tick(now=3.0) == "park"
    # Both pools at min_live=1 now: no further parks.
    assert asc.tick(now=5.0) is None
    assert asc.parks == 2
    assert pool.prefill_pool._parked == ["prefill1"]
    assert pool.decode_pool._parked == ["decode1"]
    # First tick of returning pressure wakes capacity, LIFO.
    pool.queue.n = 1
    assert asc.tick(now=6.0) == "unpark"
    assert pool.decode_pool._parked == []
    assert asc.tick(now=6.1) == "unpark"
    assert pool.prefill_pool._parked == []
    assert asc.unparks == 2


def test_autoscaler_never_unparks_breaker_parked_replicas():
    pool = _StubDisagg()
    asc = RoleAutoscaler(pool, idle_park_s=0.1)
    # The breaker parked prefill1 (crash-looping): the controller has
    # no record of it, so pressure must not wake it.
    pool.prefill_pool._parked.append("prefill1")
    pool.queue.n = 5
    for i in range(5):
        asc.tick(now=float(i))
    assert pool.prefill_pool._parked == ["prefill1"]
    assert asc.unparks == 0


def test_autoscaler_tick_survives_flip_refusal():
    pool = _StubDisagg()
    pool.flip_ok = False  # min_live floor: pool refuses to give one up
    asc = RoleAutoscaler(pool, flip_margin=4, hysteresis=1,
                         cooldown_s=0.0)
    pool.queue.n = 9
    assert asc.tick(now=0.0) is None
    assert asc.flips == 0  # refusal is not a flip


# -- role flip under load: zero settled tokens lost ---------------------------


def _synth_kv(**kw):
    args = dict(slots=2, block_size=4, num_blocks=64,
                max_blocks_per_req=16, prefill_chunk=8, pipelined=True)
    args.update(kw)
    return SyntheticKVExecutor(**args)


def test_flip_role_under_load_loses_zero_settled_tokens(settle_counts):
    """Live prefill->decode flip with requests in flight: every
    request completes error-free with the no-flip run's exact stream,
    every settle lands exactly once, and the flipped executor really
    serves its new role."""
    prompts = [[int(x) + i for x in range(12)] for i in range(6)]
    max_toks = 6

    def run(flip):
        pre = [_synth_kv(step_time_s=0.01), _synth_kv()]
        dec = [_synth_kv()]
        q = AdmissionQueue(max_depth=32)
        pool = DisaggPool(pre, dec, q, pool_opts=dict(POOL_OPTS))
        reqs = [_req(p, max_tokens=max_toks) for p in prompts]
        pool.start()
        try:
            for r in reqs:
                q.submit(r)
            if flip:
                _wait(lambda: any(len(r.tokens) > 0 for r in reqs),
                      msg="load in flight")
                name = pool.flip_role("prefill")
                assert name is not None and name.startswith("decode")
                assert pool.prefill_pool.live_count() == 1
                assert pool.decode_pool.live_count() == 2
            for r in reqs:
                assert r.wait(30), "request lost across the flip"
        finally:
            pool.stop()
        for r in reqs:
            assert r.error is None, r.error
        for ex in pre + dec:
            ex.allocator.assert_clean()
            ex.close()
        return [list(r.tokens) for r in reqs], reqs

    baseline, _ = run(flip=False)
    streams, reqs = run(flip=True)
    assert streams == baseline
    assert any(len(set(s)) > 1 for s in baseline), \
        "degenerate streams would make this equality vacuous"
    assert set(settle_counts.values()) == {1}, settle_counts
    # No attempts burned: a flip requeues as policy, not failure.
    assert all(r.attempts == 0 for r in reqs)


def test_flip_role_refuses_below_min_live():
    pre, dec = _synth_kv(), _synth_kv()
    q = AdmissionQueue(max_depth=4)
    pool = DisaggPool([pre], [dec], q, pool_opts=dict(POOL_OPTS))
    pool.start()
    try:
        assert pool.flip_role("prefill") is None
        assert pool.flip_role("decode") is None
        assert pool.prefill_pool.live_count() == 1
        assert pool.decode_pool.live_count() == 1
    finally:
        pool.stop()
    pre.close()
    dec.close()


# -- tenant/priority end-to-end through the HTTP front door -------------------


def _post(url, body, headers=None, timeout=30.0):
    data = json.dumps(body).encode()
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    try:
        r = urllib.request.urlopen(
            urllib.request.Request(url + "/v1/generate", data=data,
                                   headers=h),
            timeout=timeout)
        return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_server_tenant_priority_end_to_end():
    reg = Registry()
    srv = ServingServer(
        [SyntheticExecutor(slots=4, d=16)], registry=reg,
        max_queue_depth=16,
        tenants={"metered": TenantBudget(rate=0.5, burst=1.0)}).start()
    url = srv.url
    try:
        # Tenant via JSON body, priority validated against PRIORITIES.
        code, doc, _ = _post(url, {"prompt": "a", "max_tokens": 2,
                                   "tenant": "acme",
                                   "priority": "batch"})
        assert code == 200 and doc["tokens"]
        # Tenant via X-Tenant header when the body says nothing.
        code, _, _ = _post(url, {"prompt": "b", "max_tokens": 2},
                           headers={"X-Tenant": "hdr-tenant"})
        assert code == 200
        # Unknown priority is a 400, not a silent new class.
        code, doc, _ = _post(url, {"prompt": "c", "max_tokens": 2,
                                   "priority": "urgent"})
        assert code == 400 and "priority" in doc["error"]
        assert sorted(PRIORITIES) == ["batch", "interactive"]
        # Token bucket: second metered request inside the refill
        # window 429s with an honest Retry-After.
        code, _, _ = _post(url, {"prompt": "d", "max_tokens": 2,
                                 "tenant": "metered"})
        assert code == 200
        code, doc, headers = _post(url, {"prompt": "e",
                                         "max_tokens": 2,
                                         "tenant": "metered"})
        assert code == 429
        assert float(headers["Retry-After"]) >= 2.0  # 1/rate
        # Tenant-labelled series: requests by tenant, shed by tenant,
        # and the per-tenant latency histogram (its own metric — the
        # shared serving_request_seconds keeps its label keys).
        metrics = urllib.request.urlopen(url + "/metrics").read() \
            .decode()
        assert 'serving_requests_total{' in metrics
        assert 'tenant="acme"' in metrics
        assert 'tenant="hdr-tenant"' in metrics
        assert 'serving_tenant_request_seconds' in metrics
        assert reg.counter_value(
            "serving_queue_shed_total",
            {"reason": "over_budget", "tenant": "metered"}) == 1
    finally:
        srv.stop()


def test_server_tenant_label_cardinality_is_bounded():
    from dpu_operator_tpu.serving.api import TENANT_LABEL_CAP

    srv = ServingServer([SyntheticExecutor(slots=4, d=16)],
                        registry=Registry(),
                        max_queue_depth=64).start()
    try:
        for i in range(TENANT_LABEL_CAP + 4):
            code, _, _ = _post(srv.url, {"prompt": f"t{i}",
                                         "max_tokens": 1,
                                         "tenant": f"tenant-{i}"})
            assert code == 200
        metrics = urllib.request.urlopen(srv.url + "/metrics") \
            .read().decode()
        labels = set()
        for line in metrics.splitlines():
            if line.startswith("serving_requests_total{") \
                    and 'tenant="' in line:
                labels.add(line.split('tenant="')[1].split('"')[0])
        # Past the cap every new tenant folds into "other": the
        # scrape stays bounded no matter what names arrive.
        assert "other" in labels
        assert len(labels) <= TENANT_LABEL_CAP + 1
    finally:
        srv.stop()

# -- lane budget --------------------------------------------------------------


def test_qos_lane_wall_budget():
    """The whole QoS lane (queue units + preemption matrix + autoscaler
    + HTTP end-to-end) must fit its documented tier-1 budget
    (docs/ci.md) — runs last in file order (tier-1 runs -p
    no:randomly)."""
    elapsed = time.perf_counter() - _LANE_T0[0]
    assert elapsed < 60.0, f"qos lane took {elapsed:.1f}s (budget 60s)"
