"""Wire codecs for the fabric collectives (parallel/quantize.py):
round-trip error bounds (the documented contract), jittable
encode/decode twins, the error-feedback residual, the self-describing
frame headers, and the segment/chunk edges the quantized ring leans on
(world > n_elems, zero-length segments, odd element counts vs int8
chunking)."""

import numpy as np
import pytest

from dpu_operator_tpu.parallel.fabric_collectives import _segment_bounds
from dpu_operator_tpu.parallel.quantize import (Bf16Codec, CodecError,
                                                ErrorFeedback,
                                                Int8Codec,
                                                bf16_decode_xp,
                                                bf16_encode_xp,
                                                get_codec,
                                                int8_block_decode_xp,
                                                int8_block_encode_xp,
                                                int8_decode_xp,
                                                int8_encode_xp)


# -- round-trip error bounds (the documented contract) ------------------------


def test_int8_roundtrip_error_at_most_half_scale():
    """Symmetric per-chunk int8: scale = max|x|/127, per-element
    absolute error <= scale/2 — the bound BASELINE.md documents and
    the bench verifies against the allreduce."""
    rng = np.random.RandomState(0)
    for n in (1, 7, 1000, 4097):
        x = (rng.randn(n) * rng.uniform(0.01, 50)).astype(np.float32)
        c = Int8Codec()
        wire, scale = c.encode(x)
        assert wire.dtype == np.int8 and wire.shape == (n,)
        assert scale == pytest.approx(np.max(np.abs(x)) / 127.0)
        back = c.decode(wire, n, scale)
        assert np.max(np.abs(back - x)) <= scale / 2 + 1e-9


def test_int8_all_zero_chunk_decodes_exact_zero():
    c = Int8Codec()
    wire, scale = c.encode(np.zeros(16, np.float32))
    assert scale == 1.0  # not 0/0
    assert np.all(c.decode(wire, 16, scale) == 0.0)


def test_bf16_exact_range_roundtrips_bitwise():
    """bf16 round-trips EXACTLY any value already representable in
    its 7-bit mantissa: small integers, powers of two, and their sums
    up to 256 — the exact-range half of the documented bound."""
    vals = np.array([0.0, 1.0, -1.0, 2.0, 0.5, 0.25, 96.0, 255.0,
                     -256.0, 1.5, -3.75], np.float32)
    c = Bf16Codec()
    wire, scale = c.encode(vals)
    assert wire.dtype == np.uint16 and scale == 1.0
    assert np.array_equal(c.decode(wire, vals.size, scale), vals)


def test_bf16_general_relative_error_bound():
    rng = np.random.RandomState(1)
    x = (rng.randn(5000) * 100).astype(np.float32)
    c = Bf16Codec()
    wire, scale = c.encode(x)
    back = c.decode(wire, x.size, scale)
    rel = np.abs(back - x) / np.maximum(np.abs(x), 1e-30)
    # Round-to-nearest on bf16's 7-bit mantissa: half an ulp = 2^-8.
    assert np.max(rel) <= 2.0 ** -8 + 1e-7


# -- jittable twins -----------------------------------------------------------


def test_codec_twins_jit_under_jax_and_match_numpy():
    """The encode/decode twins take the array module as ``xp`` and use
    only traceable ufuncs — the SAME math must jit under jax and
    produce the numpy results bit-for-bit (int8 codes and bf16 code
    words are integer, so equality is exact)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    x = (rng.randn(257) * 3).astype(np.float32)

    q_np, s_np = int8_encode_xp(x)
    q_j, s_j = jax.jit(lambda a: int8_encode_xp(a, xp=jnp))(x)
    assert np.array_equal(q_np, np.asarray(q_j))
    assert float(s_np) == pytest.approx(float(s_j), rel=1e-6)
    d_j = jax.jit(lambda q, s: int8_decode_xp(q, s, xp=jnp))(
        np.asarray(q_j), np.float32(s_j))
    assert np.allclose(int8_decode_xp(q_np, np.float32(s_np)),
                       np.asarray(d_j), rtol=1e-6, atol=1e-7)

    w_np = bf16_encode_xp(x)
    w_j = jax.jit(lambda a: bf16_encode_xp(a, xp=jnp))(x)
    assert np.array_equal(w_np, np.asarray(w_j))
    b_j = jax.jit(lambda w: bf16_decode_xp(w, xp=jnp))(np.asarray(w_j))
    assert np.array_equal(bf16_decode_xp(w_np), np.asarray(b_j))


# -- block-axis twins (ISSUE 13: the resident paged-KV codec) -----------------


def test_int8_block_codec_per_block_scales_and_bound():
    """Per-block symmetric int8 over a leading axis: each block gets
    its OWN scale = max|x_b|/127 (a hot block cannot coarsen a quiet
    one), per-element absolute error <= scale_b/2, and an all-zero
    block decodes to exact zero via the scale-1.0 convention."""
    rng = np.random.RandomState(3)
    x = (rng.randn(6, 4, 2, 8) * rng.uniform(
        0.01, 40, size=(6, 1, 1, 1))).astype(np.float32)
    x[2] = 0.0
    q, scales = int8_block_encode_xp(x)
    assert q.dtype == np.int8 and q.shape == x.shape
    assert scales.shape == (6,) and scales.dtype == np.float32
    for b in range(6):
        amax = np.max(np.abs(x[b]))
        want = amax / 127.0 if amax > 0 else 1.0
        assert scales[b] == pytest.approx(want)
    back = int8_block_decode_xp(q, scales)
    err = np.abs(back - x).reshape(6, -1).max(axis=1)
    assert np.all(err <= scales / 2 + 1e-9)
    assert np.all(back[2] == 0.0)


def test_int8_block_codec_jit_matches_numpy():
    """The block twins must jit under jax and reproduce numpy exactly
    (codes are integer: equality is exact; scales to fp tolerance) —
    the same contract as the chunk twins, because the resident pools
    encode on device while the transfer path decodes host-side."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(4)
    x = (rng.randn(5, 4, 16) * 3).astype(np.float32)
    q_np, s_np = int8_block_encode_xp(x)
    q_j, s_j = jax.jit(lambda a: int8_block_encode_xp(a, xp=jnp))(x)
    assert np.array_equal(q_np, np.asarray(q_j))
    assert np.allclose(s_np, np.asarray(s_j), rtol=1e-6)
    d_j = jax.jit(lambda q, s: int8_block_decode_xp(q, s, xp=jnp))(
        np.asarray(q_j), np.asarray(s_j))
    assert np.allclose(int8_block_decode_xp(q_np, s_np),
                       np.asarray(d_j), rtol=1e-6, atol=1e-7)


def test_int8_block_decode_broadcasts_gathered_scales():
    """The paged-attention table gather hands the twin ``[S, B]``
    scales against ``[S, B, bs, e]`` codes — the prefix-broadcast
    contract the decode twin documents."""
    rng = np.random.RandomState(5)
    x = rng.randn(4, 3, 8).astype(np.float32)
    q, scales = int8_block_encode_xp(x)
    gq = q[None].repeat(2, axis=0)          # [2, 4, 3, 8]
    gs = scales[None].repeat(2, axis=0)     # [2, 4]
    back = int8_block_decode_xp(gq, gs)
    assert back.shape == gq.shape
    assert np.allclose(back[0], int8_block_decode_xp(q, scales))


# -- error feedback -----------------------------------------------------------


def test_error_feedback_residual_converges_repeated_payload():
    """EF keeps what rounding dropped and feeds it to the next call:
    for a REPEATED payload the running mean of decodes converges on
    the true value, where the plain codec repeats the identical
    rounding forever. The per-step serving collective is exactly this
    shape (same buffer, every step)."""
    c = Int8Codec()
    ef = ErrorFeedback(c)
    # A value deliberately between two int8 levels at this scale.
    x = np.full(64, 0.7003, np.float32)
    x[0] = 127.0 / 127.0  # pins scale = 1/127 ... max is 1.0
    plain = c.roundtrip(x)[1]
    plain_err = abs(plain - 0.7003)
    decs = []
    for _ in range(64):
        wire, scale = ef.encode(x)
        decs.append(float(c.decode(wire, x.size, scale)[1]))
    ef_err = abs(np.mean(decs) - 0.7003)
    assert ef_err < plain_err / 4, (ef_err, plain_err)
    # And every individual decode stays within the one-shot bound of
    # the FED value (residual <= scale/2 keeps it inside ~1.5 scale).
    assert np.max(np.abs(np.asarray(decs) - 0.7003)) <= 1.5 * scale


# -- framing + registry -------------------------------------------------------


def test_frame_header_mismatch_is_typed():
    i8, b16 = Int8Codec(), Bf16Codec()
    hdr = i8.frame_header(0.5)
    assert i8.parse_header(hdr) == pytest.approx(0.5)
    with pytest.raises(CodecError, match="mismatch"):
        b16.parse_header(hdr)


def test_get_codec_registry():
    assert get_codec(None) is None
    assert get_codec("fp32") is None  # the identity: raw path intact
    assert isinstance(get_codec("bf16"), Bf16Codec)
    assert isinstance(get_codec("int8"), Int8Codec)
    with pytest.raises(CodecError, match="unknown"):
        get_codec("int4")  # typed, never a silent fp32 fallback


def test_empty_chunk_encodes_and_decodes():
    """Zero-length segments are legal (world > n_elems): the empty
    chunk frames with scale 1.0 and no payload."""
    for c in (Int8Codec(), Bf16Codec()):
        wire, scale = c.encode(np.empty(0, np.float32))
        assert c.decode(wire, 0, scale).size == 0


# -- segment/chunk edges the quantized ring leans on --------------------------


def test_segment_bounds_world_larger_than_elems():
    """world > n_elems: the first n_elems ranks get one element each,
    the rest get ZERO-LENGTH segments — still world entries, still an
    exact cover (an empty-segment rank participates in every
    collective with empty chunks)."""
    bounds = _segment_bounds(3, 5)
    assert bounds == [(0, 1), (1, 2), (2, 3), (3, 3), (3, 3)]
    assert _segment_bounds(0, 4) == [(0, 0)] * 4


def test_int8_chunking_covers_odd_element_counts():
    """Odd element counts vs int8 chunking: wire-sized chunks (1 byte
    per element) must tile a ragged segment exactly — encode/decode
    per chunk and reassemble, no element dropped or double-counted."""
    from dpu_operator_tpu.parallel.fabric_collectives import RingTransport

    t = RingTransport(0, 3, "127.0.0.1", ["a", "b", "c"],
                      chunk_bytes=64 << 10, codec="int8")
    n = (64 << 10) * 2 + 17  # two full wire chunks + a ragged tail
    covered = []
    for lo, hi in t._codec_chunks((0, n)):
        assert hi - lo <= 64 << 10
        covered.append((lo, hi))
    assert covered[0][0] == 0 and covered[-1][1] == n
    for (a, b), (c_, d) in zip(covered, covered[1:]):
        assert b == c_
    # int8 chunks carry 4x the ELEMENTS of an fp32 chunk of the same
    # wire size — the striping answer to quarter-size payloads.
    t_fp = RingTransport(0, 3, "127.0.0.1", ["a", "b", "c"],
                         chunk_bytes=64 << 10)
    fp_chunk = max(1, t_fp.chunk_bytes // 4)
    assert covered[0][1] == 4 * fp_chunk
    t.close()
    t_fp.close()
