"""Traffic-flow test harness (dpu_operator_tpu/tft) — counterpart of the
reference's hack/traffic_flow_tests.sh + kubernetes-traffic-flow-tests
submodule wiring (SURVEY §4 tier 4)."""

import json
import os
import subprocess
import sys

import pytest

from dpu_operator_tpu.tft import ConnectionSpec, load_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_load_reference_shaped_config(tmp_path):
    cfg = tmp_path / "tft.yaml"
    cfg.write_text(
        """
tft:
  - name: "Test 1"
    namespace: "default"
    duration: "5"
    connections:
      - name: "c1"
        type: "iperf-udp"
        instances: 2
        secondary_network_nad: "default-ici-net"
      - name: "c2"
        type: "netperf-tcp-rr"
"""
    )
    tests = load_config(str(cfg))
    assert len(tests) == 1
    t = tests[0]
    assert t.duration == 5.0
    assert [c.type for c in t.connections] == ["iperf-udp", "netperf-tcp-rr"]
    assert t.connections[0].instances == 2
    assert t.secondary_network_nad == "default-ici-net"


def test_unsupported_type_rejected():
    with pytest.raises(ValueError, match="unsupported type"):
        ConnectionSpec(name="x", type="iperf-sctp")


def test_engine_loopback_round_trip():
    """Engines work without netns: server+client over loopback."""
    from dpu_operator_tpu.tft.tft import run_connection

    r = run_connection(
        ConnectionSpec(name="lo", type="iperf-tcp"),
        server_netns=None,
        client_netns=None,
        server_ip="127.0.0.1",
        duration=0.5,
        port=20944,
    )
    assert r["type"] == "tcp-stream"
    assert r["gbps"] > 0


@pytest.mark.slow
def test_traffic_flow_script_self_contained(netns):
    """hack/traffic_flow_tests.sh end-to-end: real bridge, two netns, all
    four connection types."""
    env = dict(os.environ, TFT_DURATION="0.5")
    r = subprocess.run(
        [os.path.join(REPO, "hack", "traffic_flow_tests.sh")],
        capture_output=True,
        text=True,
        timeout=180,
        env=env,
        cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    last = r.stdout.strip().splitlines()[-1]
    results = json.loads(last)["tft_results"]
    assert len(results) == 4
    by_type = {x["type"]: x for x in results}
    assert by_type["udp"]["gbps"] > 0
    assert by_type["tcp-stream"]["gbps"] > 0
    assert by_type["tcp-rr"]["tps"] > 0
