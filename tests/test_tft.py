"""Traffic-flow test harness (dpu_operator_tpu/tft) — counterpart of the
reference's hack/traffic_flow_tests.sh + kubernetes-traffic-flow-tests
submodule wiring (SURVEY §4 tier 4)."""

import json
import os
import subprocess
import time
import sys

import pytest

from dpu_operator_tpu.tft import ConnectionSpec, load_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_load_reference_shaped_config(tmp_path):
    cfg = tmp_path / "tft.yaml"
    cfg.write_text(
        """
tft:
  - name: "Test 1"
    namespace: "default"
    duration: "5"
    connections:
      - name: "c1"
        type: "iperf-udp"
        instances: 2
        secondary_network_nad: "default-ici-net"
      - name: "c2"
        type: "netperf-tcp-rr"
"""
    )
    tests = load_config(str(cfg))
    assert len(tests) == 1
    t = tests[0]
    assert t.duration == 5.0
    assert [c.type for c in t.connections] == ["iperf-udp", "netperf-tcp-rr"]
    assert t.connections[0].instances == 2
    assert t.secondary_network_nad == "default-ici-net"


def test_unsupported_type_rejected():
    with pytest.raises(ValueError, match="unsupported type"):
        ConnectionSpec(name="x", type="iperf-sctp")


def test_engine_loopback_round_trip():
    """Engines work without netns: server+client over loopback."""
    from dpu_operator_tpu.tft.tft import run_connection

    r = run_connection(
        ConnectionSpec(name="lo", type="iperf-tcp"),
        server_netns=None,
        client_netns=None,
        server_ip="127.0.0.1",
        duration=0.5,
        port=20944,
    )
    assert r["type"] == "tcp-stream"
    assert r["gbps"] > 0


@pytest.mark.slow
def test_traffic_flow_script_self_contained(netns):
    """hack/traffic_flow_tests.sh end-to-end: real bridge, two netns, all
    four connection types."""
    env = dict(os.environ, TFT_DURATION="0.5")
    r = subprocess.run(
        [os.path.join(REPO, "hack", "traffic_flow_tests.sh")],
        capture_output=True,
        text=True,
        timeout=180,
        env=env,
        cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    last = r.stdout.strip().splitlines()[-1]
    results = json.loads(last)["tft_results"]
    assert len(results) == 4
    by_type = {x["type"]: x for x in results}
    assert by_type["udp"]["gbps"] > 0
    assert by_type["tcp-stream"]["gbps"] > 0
    assert by_type["tcp-rr"]["tps"] > 0


def test_endpoint_partition_changes_measured_throughput(netns):
    """SetNumEndpoints has a DATAPLANE meaning (round-2 verdict Missing
    #4; reference SetNumVfs creates real VFs, vspnetutils.go:50): with a
    known fabric budget, each endpoint gets an HTB egress share on its
    bridge port — measured throughput tracks the partition count. 8
    endpoints → ~budget/8 each; repartition to 2 → ~budget/2 each."""
    import uuid

    from dpu_operator_tpu.tft.tft import ConnectionSpec, run_connection
    from dpu_operator_tpu.vsp.tpu_dataplane import TpuFabricDataplane

    bridge = "brEP" + uuid.uuid4().hex[:6]
    ns_a = "epA" + uuid.uuid4().hex[:6]
    ns_b = "epB" + uuid.uuid4().hex[:6]
    budget_gbps = 2.0

    def sh(*args):
        subprocess.run(args, check=True, capture_output=True)

    try:
        dp = TpuFabricDataplane(bridge=bridge, fabric_gbps=budget_gbps)
        dp.ensure_bridge()
        for ns, host_if, ip in ((ns_a, "vepA", "10.99.0.1"), (ns_b, "vepB", "10.99.0.2")):
            sh("ip", "netns", "add", ns)
            sh("ip", "link", "add", host_if, "type", "veth", "peer", "name", "eth0",
               "netns", ns)
            sh("ip", "-n", ns, "addr", "add", f"{ip}/24", "dev", "eth0")
            sh("ip", "-n", ns, "link", "set", "eth0", "up")
            sh("ip", "-n", ns, "link", "set", "lo", "up")
            dp.attach_port(host_if, "02:00:00:00:00:0" + host_if[-1])

        conn = ConnectionSpec(name="part", type="iperf-tcp")

        def measure() -> float:
            r = run_connection(conn, ns_b, ns_a, "10.99.0.2", duration=1.5,
                               port=15201)
            return float(r["gbps"])

        dp.partition_endpoints(8)
        g8 = measure()
        dp.partition_endpoints(2)
        g2 = measure()

        share8 = budget_gbps / 8
        share2 = budget_gbps / 2
        # HTB on veth overshoots a little with bursts; generous windows
        # still cleanly separate the two partitions (0.25 vs 1.0 Gb/s).
        assert 0.4 * share8 < g8 < 2.0 * share8, f"8-part share {g8} Gb/s"
        assert 0.4 * share2 < g2 < 1.6 * share2, f"2-part share {g2} Gb/s"
        assert g2 > 2.0 * g8, (
            f"repartition 8→2 should ~4x throughput (got {g8} → {g2} Gb/s)"
        )
    finally:
        for ns in (ns_a, ns_b):
            subprocess.run(["ip", "netns", "del", ns], capture_output=True)
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)


def test_native_pump_preferred_and_tagged(tmp_path):
    """When native/build/tft-pump exists the engines exec it (interpreter
    out of the byte loop); TFT_PUMP=python forces the fallback. Both tag
    their JSON with `engine` so recorded numbers are honest about what
    produced them (VERDICT r1 Weak #2)."""
    from dpu_operator_tpu.tft.engine import find_pump

    pump = find_pump()
    if pump is None:
        pytest.skip("native tft-pump not built")

    def run_pair(env_extra):
        port = 21000 + os.getpid() % 2000 + (1 if env_extra else 0)
        env = dict(os.environ, **env_extra)
        srv = subprocess.Popen(
            [sys.executable, "-m", "dpu_operator_tpu.tft.engine",
             "server", "netperf-tcp-rr", "127.0.0.1", str(port), "1"],
            stdout=subprocess.PIPE, text=True, env=env)
        time.sleep(0.3)
        cli = subprocess.run(
            [sys.executable, "-m", "dpu_operator_tpu.tft.engine",
             "client", "netperf-tcp-rr", "127.0.0.1", str(port), "1"],
            capture_output=True, text=True, env=env, timeout=30)
        srv_out, _ = srv.communicate(timeout=30)
        return (json.loads(srv_out.strip().splitlines()[-1]),
                json.loads(cli.stdout.strip().splitlines()[-1]))

    srv_res, cli_res = run_pair({})
    assert srv_res["engine"] == "c" and cli_res["engine"] == "c"
    assert cli_res["transactions"] > 0

    srv_res, cli_res = run_pair({"TFT_PUMP": "python"})
    assert srv_res["engine"] == "python" and cli_res["engine"] == "python"
    assert cli_res["transactions"] > 0


# -- numbered case matrix (tft/cases.py) --------------------------------------


def test_case_selection_grammar():
    """The reference's selection grammar: single ids, lists, ranges —
    and loud failure on junk (a typo'd case silently not running is the
    worst outcome for a perf matrix)."""
    from dpu_operator_tpu.tft.cases import parse_cases

    assert parse_cases("1") == [1]
    assert parse_cases("1,3,17") == [1, 3, 17]
    assert parse_cases("1-4,15-19") == [1, 2, 3, 4, 15, 16, 17, 18, 19]
    assert parse_cases("2,1-3") == [2, 1, 3]  # dedup, order-preserving
    with pytest.raises(ValueError, match="unknown test case"):
        parse_cases("99")
    with pytest.raises(ValueError, match="> "):
        parse_cases("9-1")
    with pytest.raises(ValueError):
        parse_cases("banana")


def test_case_table_covers_reference_range():
    """Every id in the reference's advertised '1-9,15-19' selection must
    resolve — supported locally or carrying an explicit skip reason."""
    from dpu_operator_tpu.tft.cases import CASES, case_reason, parse_cases

    for cid in parse_cases("1-9,15-19"):
        assert cid in CASES
        entry = CASES[cid]
        if case_reason(cid) is None:
            assert entry[1] in ("pod", "host") and entry[2] in ("pod", "host")


def test_case_matrix_topologies_carry_traffic(netns):
    """Root tier: the endpoint-topology shapes actually carry engine
    traffic — pod/pod same node, pod/pod across the two-bridge fabric,
    clusterIP through the NAT service plane, host-to-host across nodes
    (which must NOT short-circuit over loopback: server host lives in
    node B's netns), and host-to-pod."""
    from dpu_operator_tpu.tft import ConnectionSpec, TestSpec
    from dpu_operator_tpu.tft.tft import run_case_matrix

    spec = TestSpec(
        name="matrix", duration=0.5,
        connections=[ConnectionSpec(name="c", type="iperf-tcp")],
        test_cases="1,2,5,16,17",
    )
    results = run_case_matrix([spec])
    by_case = {r["case"]: r for r in results}
    assert set(by_case) == {1, 2, 5, 16, 17}
    for cid in (1, 2, 5, 16, 17):
        assert by_case[cid]["gbps"] > 0, by_case[cid]
        assert by_case[cid]["case_name"]
    # The clusterIP case really rode the service plane.
    assert by_case[5]["service"] == "clusterip"
    # Case 15 isn't here, but its sibling host-host-diff must have a
    # netns server (the loopback-short-circuit guard).
    # Nothing leaked: no bta/btb bridges or tc/tn netns remain.
    links = subprocess.run(["ip", "-o", "link"], capture_output=True,
                           text=True).stdout
    assert "bta" not in links and "btb" not in links


def test_service_plane_cases_real_nat(netns):
    """The kube-proxy-analogue NAT plane (VERDICT r3 Next #1): nodePort
    with real port rewriting (client dials nodeIP:30xxx, server binds
    backend:20xxx), the v6 flavour through an ip6-family table, and
    external egress through masquerade — all moving real bytes, with
    conntrack NAT state to prove the path, and nothing left behind."""
    from dpu_operator_tpu.tft import ConnectionSpec, TestSpec
    from dpu_operator_tpu.tft.tft import run_case_matrix

    spec = TestSpec(
        name="svc", duration=0.5,
        connections=[ConnectionSpec(name="c", type="iperf-tcp")],
        test_cases="10,13,25",
    )
    results = run_case_matrix([spec], duration_override=0.5)
    by_case = {r["case"]: r for r in results}
    assert set(by_case) == {10, 13, 25}
    assert by_case[10]["gbps"] > 0 and by_case[10]["service"] == "nodeport"
    assert by_case[13]["gbps"] > 0 and by_case[13]["service"] == "nodeport6"
    assert by_case[25]["gbps"] > 0 and by_case[25]["service"] == "external"
    # Cleanup really handed global state back: no leaked nft service
    # tables in either family, sysctls restored by the topology cleanup.
    from dpu_operator_tpu.cni.nftnl import (
        NFPROTO_IPV4, NFPROTO_IPV6, NFTA_TABLE_NAME, Nft, _parse_attrs)

    for fam in (NFPROTO_IPV4, NFPROTO_IPV6):
        with Nft(family=fam) as n:
            names = [_parse_attrs(o).get(NFTA_TABLE_NAME, b"")
                     .rstrip(b"\0").decode() for o in n._dump(1, b"")]
        assert not [t for t in names if t.startswith("dpusvc")], names


def test_service_plane_udp_and_rr(netns):
    """DNAT must carry all four traffic shapes, not just TCP stream:
    UDP (separate per-protocol rules, like kube-proxy's) and TCP-RR
    (many small round-trips through conntrack) over one clusterIP."""
    from dpu_operator_tpu.tft import ConnectionSpec, TestSpec
    from dpu_operator_tpu.tft.tft import run_case_matrix

    spec = TestSpec(
        name="svcmix", duration=0.5,
        connections=[ConnectionSpec(name="u", type="iperf-udp"),
                     ConnectionSpec(name="r", type="netperf-tcp-rr")],
        test_cases="6",
    )
    results = run_case_matrix([spec], duration_override=0.5)
    by_conn = {r["connection"]: r for r in results}
    assert by_conn["u"]["gbps"] > 0, by_conn["u"]
    assert by_conn["r"]["tps"] > 0, by_conn["r"]


def test_nodeport_requires_port_range():
    """NodePort cases program exact DNAT port pairs — building one
    without the engine port range must fail loudly, not silently skip
    the rewrite."""
    from dpu_operator_tpu.tft.cases import build_case_topology

    with pytest.raises(ValueError, match="port_base"):
        build_case_topology(9)


def test_empty_case_selection_is_loud():
    from dpu_operator_tpu.tft.cases import parse_cases

    with pytest.raises(ValueError, match="selects no cases"):
        parse_cases("")
    with pytest.raises(ValueError, match="selects no cases"):
        parse_cases(" , ")


def test_cases_flag_requires_case_matrix_mode(tmp_path):
    """--cases without --case-matrix must error, not silently run the
    self-contained pair instead of the requested topologies."""
    from dpu_operator_tpu.tft.__main__ import main

    cfg = tmp_path / "t.yaml"
    cfg.write_text("tft:\n  - name: t\n    connections:\n"
                   "      - name: c\n        type: iperf-tcp\n")
    with pytest.raises(SystemExit) as e:
        main([str(cfg), "--self-contained", "--cases", "1-4"])
    assert e.value.code == 2
