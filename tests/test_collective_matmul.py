"""Collective matmul (parallel/collective_matmul.py) — the overlapped
allgather-matmul / matmul-reduce-scatter pair. Proof standard matches
the ring-collective family: XLA paths correct on the virtual mesh,
pallas kernels EXECUTED under TPU interpret mode against the naive
reference, and AOT-lowered for a multi-device TPU topology so Mosaic
compilation is proven without multi-chip hardware."""

import numpy as np
import pytest

from virtual_mesh import REPO, run_virtual as _run_virtual


def _mesh(shape=(1, 1, 8)):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()).reshape(shape),
                axis_names=("dp", "sp", "tp"))


def test_xla_overlapped_matches_naive():
    """The decomposed ppermute loop computes exactly AllGather(x) @ w —
    block placement (src indexing) and the skipped final permute are the
    parts worth distrusting."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpu_operator_tpu.parallel.collective_matmul import make_allgather_matmul

    for shape, n in (((1, 1, 8), 8), ((2, 1, 4), 4), ((4, 1, 2), 2)):
        mesh = _mesh(shape)
        b, k, f = 2 * n, 16, 8 * n
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (b, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (k, f), jnp.float32)
        xs = jax.device_put(x, NamedSharding(mesh, P("tp", None)))
        ws = jax.device_put(w, NamedSharding(mesh, P(None, "tp")))
        naive = make_allgather_matmul(mesh, "tp", overlap=False)(xs, ws)
        fused = make_allgather_matmul(mesh, "tp", overlap=True)(xs, ws)
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(naive), rtol=1e-6)
        # vs numpy: accumulation order differs (XLA blocked dot), so a
        # handful of elements land a few ulps apart at f32.
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(x) @ np.asarray(w),
            rtol=1e-4, atol=1e-5)


def test_xla_matmul_reduce_scatter_matches_reference():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpu_operator_tpu.parallel.collective_matmul import (
        make_matmul_reduce_scatter,
    )

    for shape, n in (((1, 1, 8), 8), ((2, 1, 4), 4)):
        mesh = _mesh(shape)
        b, k, f = 2 * n, 8 * n, 16
        x = jax.random.normal(jax.random.PRNGKey(2), (b, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(3), (k, f), jnp.float32)
        xs = jax.device_put(x, NamedSharding(mesh, P(None, "tp")))
        ws = jax.device_put(w, NamedSharding(mesh, P("tp", None)))
        out = make_matmul_reduce_scatter(mesh, "tp")(xs, ws)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(x) @ np.asarray(w),
            rtol=1e-4, atol=1e-4)


def test_pallas_collective_matmul_interpret_mode():
    """Both fused kernels EXECUTE under TPU interpret mode on the
    virtual mesh and match the XLA paths — the ag-matmul's
    compute-between-start-and-wait overlap and the mm-rs kernel's
    on-demand partial blocks both ride the shared credit protocol, so
    execution is the only honest check."""
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "from dpu_operator_tpu.parallel.collective_matmul import (\n"
        "    make_allgather_matmul, make_matmul_reduce_scatter)\n"
        "with pltpu.force_tpu_interpret_mode():\n"
        "    for shape, n in (((1, 1, 8), 8), ((2, 1, 4), 4), ((1, 4, 2), 2)):\n"
        "        mesh = Mesh(np.array(jax.devices()).reshape(shape),\n"
        "                    axis_names=('dp', 'sp', 'tp'))\n"
        "        b, k, f = 2 * n, 16, 8 * n\n"
        "        x = jax.random.normal(jax.random.PRNGKey(0), (b, k), jnp.float32)\n"
        "        w = jax.random.normal(jax.random.PRNGKey(1), (k, f), jnp.float32)\n"
        "        xs = jax.device_put(x, NamedSharding(mesh, P('tp', None)))\n"
        "        ws = jax.device_put(w, NamedSharding(mesh, P(None, 'tp')))\n"
        "        ref = np.asarray(make_allgather_matmul(mesh, 'tp',\n"
        "              use_pallas=False, overlap=False)(xs, ws))\n"
        "        out = np.asarray(make_allgather_matmul(mesh, 'tp',\n"
        "              use_pallas=True)(xs, ws))\n"
        "        np.testing.assert_allclose(out, ref, rtol=1e-5)\n"
        "        x2 = jax.random.normal(jax.random.PRNGKey(2), (2 * n, 8 * n))\n"
        "        w2 = jax.random.normal(jax.random.PRNGKey(3), (8 * n, 16))\n"
        "        x2s = jax.device_put(x2, NamedSharding(mesh, P(None, 'tp')))\n"
        "        w2s = jax.device_put(w2, NamedSharding(mesh, P('tp', None)))\n"
        "        ref2 = np.asarray(make_matmul_reduce_scatter(mesh, 'tp',\n"
        "               use_pallas=False)(x2s, w2s))\n"
        "        out2 = np.asarray(make_matmul_reduce_scatter(mesh, 'tp',\n"
        "               use_pallas=True)(x2s, w2s))\n"
        "        np.testing.assert_allclose(out2, ref2, rtol=1e-4, atol=1e-4)\n"
        "    # bf16 inputs on the widest ring: both backends keep the\n"
        "    # reduction at f32 (f32 scratch / f32 psum_scatter), so they\n"
        "    # must agree to bf16 output resolution — per-hop bf16\n"
        "    # rounding would drift visibly at n=8.\n"
        "    mesh = Mesh(np.array(jax.devices()).reshape(1, 1, 8),\n"
        "                axis_names=('dp', 'sp', 'tp'))\n"
        "    xb = jax.random.normal(jax.random.PRNGKey(4), (16, 64)\n"
        "         ).astype(jnp.bfloat16)\n"
        "    wb = jax.random.normal(jax.random.PRNGKey(5), (64, 16)\n"
        "         ).astype(jnp.bfloat16)\n"
        "    xbs = jax.device_put(xb, NamedSharding(mesh, P(None, 'tp')))\n"
        "    wbs = jax.device_put(wb, NamedSharding(mesh, P('tp', None)))\n"
        "    refb = np.asarray(make_matmul_reduce_scatter(mesh, 'tp',\n"
        "           use_pallas=False)(xbs, wbs)).astype(np.float32)\n"
        "    outb = np.asarray(make_matmul_reduce_scatter(mesh, 'tp',\n"
        "           use_pallas=True)(xbs, wbs)).astype(np.float32)\n"
        "    np.testing.assert_allclose(outb, refb, rtol=1e-2, atol=1e-2)\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout


@pytest.mark.slow
def test_pallas_collective_matmul_aot_lowers_for_tpu():
    """Mosaic compilation proof without multi-chip hardware: AOT-lower
    both fused kernels for an abstract 8-device TPU v5e topology."""
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from dpu_operator_tpu.parallel.collective_matmul import (\n"
        "    make_allgather_matmul, make_matmul_reduce_scatter)\n"
        "mesh = Mesh(np.array(jax.devices()).reshape(1, 1, 8),\n"
        "            axis_names=('dp', 'sp', 'tp'))\n"
        "xa = jax.ShapeDtypeStruct((256, 256), jnp.bfloat16,\n"
        "     sharding=NamedSharding(mesh, P('tp', None)))\n"
        "wa = jax.ShapeDtypeStruct((256, 1024), jnp.bfloat16,\n"
        "     sharding=NamedSharding(mesh, P(None, 'tp')))\n"
        "fn = make_allgather_matmul(mesh, 'tp', use_pallas=True)\n"
        "exp = jax.export.export(fn, platforms=['tpu'])(xa, wa)\n"
        "assert 'tpu_custom_call' in exp.mlir_module()\n"
        "x2 = jax.ShapeDtypeStruct((256, 1024), jnp.bfloat16,\n"
        "     sharding=NamedSharding(mesh, P(None, 'tp')))\n"
        "w2 = jax.ShapeDtypeStruct((1024, 256), jnp.bfloat16,\n"
        "     sharding=NamedSharding(mesh, P('tp', None)))\n"
        "rs = make_matmul_reduce_scatter(mesh, 'tp', use_pallas=True)\n"
        "exp2 = jax.export.export(rs, platforms=['tpu'])(x2, w2)\n"
        "assert 'tpu_custom_call' in exp2.mlir_module()\n"
        "print('ok')\n" % REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout
