"""HTTP apiserver tier: the production HttpClient against ApiServer.

Covers the wire semantics the controllers depend on and that the
in-process tier can't prove (VERDICT r1 Missing #1): REST CRUD with
k8s Status errors, 409 optimistic-concurrency conflicts, AlreadyExists,
the /status subresource, finalizer-gated deletion over the wire, chunked
`?watch=1` streaming with resourceVersion resume, label-selector lists,
kubeconfig loading, and bearer-token auth. Reference counterpart:
internal/testutils/kindcluster.go:47-64,162-214 (envtest/Kind reuse)."""

import threading
import time

import pytest

from dpu_operator_tpu.k8s import InMemoryCluster
from dpu_operator_tpu.k8s.http_client import HttpClient, client_from_kubeconfig
from dpu_operator_tpu.k8s.http_server import ApiServer
from dpu_operator_tpu.k8s.store import AlreadyExists, Conflict, NotFound


@pytest.fixture()
def server():
    s = ApiServer(InMemoryCluster()).start()
    try:
        yield s
    finally:
        s.stop()


@pytest.fixture()
def client(server):
    return HttpClient(server.url)


def _pod(name, ns="default", labels=None):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {"containers": []},
    }


def test_crud_roundtrip(client):
    created = client.create(_pod("p1"))
    assert created["metadata"]["uid"]
    assert created["metadata"]["resourceVersion"]

    got = client.get("v1", "Pod", "default", "p1")
    assert got["metadata"]["uid"] == created["metadata"]["uid"]

    got["spec"]["nodeName"] = "n1"
    updated = client.update(got)
    assert updated["spec"]["nodeName"] == "n1"
    assert updated["metadata"]["resourceVersion"] != got["metadata"]["resourceVersion"]

    client.delete("v1", "Pod", "default", "p1")
    with pytest.raises(NotFound):
        client.get("v1", "Pod", "default", "p1")


def test_create_conflict_is_already_exists(client):
    client.create(_pod("dup"))
    with pytest.raises(AlreadyExists):
        client.create(_pod("dup"))


def test_stale_resource_version_conflicts(client):
    client.create(_pod("c1"))
    a = client.get("v1", "Pod", "default", "c1")
    b = client.get("v1", "Pod", "default", "c1")
    a["spec"]["nodeName"] = "first"
    client.update(a)
    b["spec"]["nodeName"] = "second"
    with pytest.raises(Conflict):
        client.update(b)


def test_status_subresource_only_touches_status(client):
    client.create(_pod("s1"))
    cur = client.get("v1", "Pod", "default", "s1")
    cur["spec"]["nodeName"] = "should-not-apply"
    cur["status"] = {"phase": "Running"}
    out = client.update_status(cur)
    assert out["status"]["phase"] == "Running"
    assert "nodeName" not in out["spec"]


def test_finalizer_gates_deletion_over_the_wire(client):
    pod = _pod("f1")
    pod["metadata"]["finalizers"] = ["dpu.tpu.io/test"]
    client.create(pod)
    client.delete("v1", "Pod", "default", "f1")
    # Still present, now with deletionTimestamp.
    cur = client.get("v1", "Pod", "default", "f1")
    assert cur["metadata"]["deletionTimestamp"]
    # Dropping the finalizer reaps it.
    cur["metadata"]["finalizers"] = []
    client.update(cur)
    with pytest.raises(NotFound):
        client.get("v1", "Pod", "default", "f1")


def test_label_selector_list(client):
    client.create(_pod("l1", labels={"app": "a"}))
    client.create(_pod("l2", labels={"app": "b"}))
    names = {p["metadata"]["name"] for p in client.list("v1", "Pod", "default", {"app": "a"})}
    assert names == {"l1"}


def test_cluster_scoped_resources(client):
    client.create({"apiVersion": "v1", "kind": "Node", "metadata": {"name": "n1"}})
    assert client.get("v1", "Node", None, "n1")["metadata"]["name"] == "n1"


def test_custom_resource_group_urls(client):
    client.create(
        {
            "apiVersion": "dpu.tpu.io/v1",
            "kind": "DataProcessingUnit",
            "metadata": {"name": "d1", "namespace": "dpu"},
            "spec": {"vendor": "tpu"},
        }
    )
    got = client.get("dpu.tpu.io/v1", "DataProcessingUnit", "dpu", "d1")
    assert got["spec"]["vendor"] == "tpu"


def test_watch_streams_chunked_events(client):
    w = client.watch("v1", "Pod", "default")
    try:
        client.create(_pod("w1"))
        ev = w.events.get(timeout=10)
        assert ev.type == "ADDED" and ev.object["metadata"]["name"] == "w1"
        cur = client.get("v1", "Pod", "default", "w1")
        cur["spec"]["nodeName"] = "n"
        client.update(cur)
        types = [w.events.get(timeout=10).type for _ in range(1)]
        assert "MODIFIED" in types
        client.delete("v1", "Pod", "default", "w1")
        seen = set()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and "DELETED" not in seen:
            try:
                seen.add(w.events.get(timeout=1).type)
            except Exception:
                pass
        assert "DELETED" in seen
    finally:
        client.stop_watch(w)


def test_watch_resume_skips_old_objects(server):
    """The ?resourceVersion= floor: a watch opened after a list must not
    replay objects the list already returned."""
    import json
    import urllib.request

    direct = HttpClient(server.url)
    direct.create(_pod("old1"))
    rv = server.cluster.resource_version
    direct.create(_pod("new1"))

    url = f"{server.url}/api/v1/namespaces/default/pods?watch=1&resourceVersion={rv}"
    events = []
    done = threading.Event()

    def read():
        with urllib.request.urlopen(url, timeout=10) as resp:
            for line in resp:
                events.append(json.loads(line))
                done.set()
                return

    t = threading.Thread(target=read, daemon=True)
    t.start()
    assert done.wait(10)
    assert [e["object"]["metadata"]["name"] for e in events] == ["new1"]


def test_watch_resume_replays_deletion_in_the_gap(server):
    """A delete that lands between the client's list and the watch
    registration must be replayed as DELETED (event-history resume), not
    silently lost leaving the informer with a ghost object."""
    direct = HttpClient(server.url)
    direct.create(_pod("ghost"))
    _, rv = server.cluster.list_with_rv("v1", "Pod", "default")
    direct.delete("v1", "Pod", "default", "ghost")

    w = server.cluster.watch("v1", "Pod", "default", since_rv=rv)
    ev = w.events.get(timeout=5)
    assert ev.type == "DELETED" and ev.object["metadata"]["name"] == "ghost"
    server.cluster.stop_watch(w)


def test_watch_resume_past_history_window_is_410():
    """A resume point older than the retained history answers 410 Gone
    and the production client recovers by relisting. Runs against a
    small-HISTORY cluster: aging out the production window (4096
    events) takes ~8k HTTP round trips ≈ 48 s of pure churn — the
    semantics under test (resume point older than the retained deque)
    are identical at HISTORY=16, and CI wall-time is a budgeted
    resource (docs/ci.md)."""
    import urllib.error
    import urllib.request

    class SmallHistoryCluster(InMemoryCluster):
        HISTORY = 16

    server = ApiServer(SmallHistoryCluster()).start()
    try:
        direct = HttpClient(server.url)
        direct.create(_pod("h0"))
        for i in range(SmallHistoryCluster.HISTORY + 8):
            cur = direct.get("v1", "Pod", "default", "h0")
            cur["metadata"]["labels"] = {"i": str(i)}
            direct.update(cur)
        url = (f"{server.url}/api/v1/namespaces/default/pods"
               f"?watch=1&resourceVersion=1")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=10)
        assert ei.value.code == 410

        # The production client's watch loop relists after the 410 and
        # still converges on current state.
        w = direct.watch("v1", "Pod", "default")
        ev = w.events.get(timeout=10)
        assert ev.object["metadata"]["name"] == "h0"
        direct.stop_watch(w)
    finally:
        server.stop()


def test_namespace_object_roundtrip(client):
    """/api/v1/namespaces/<name> is the Namespace object, not a scope
    prefix — create/get/delete by name must work."""
    client.create({"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "ns-x"}})
    got = client.get("v1", "Namespace", None, "ns-x")
    assert got["metadata"]["name"] == "ns-x"
    client.delete("v1", "Namespace", None, "ns-x")
    with pytest.raises(NotFound):
        client.get("v1", "Namespace", None, "ns-x")


def test_bearer_token_required_when_configured():
    s = ApiServer(InMemoryCluster(), token="sekrit").start()
    try:
        denied = HttpClient(s.url)
        with pytest.raises(RuntimeError, match="401"):
            denied.create(_pod("x"))
        ok = HttpClient(s.url, token="sekrit")
        ok.create(_pod("x"))
        assert ok.get("v1", "Pod", "default", "x")
    finally:
        s.stop()


def test_client_from_kubeconfig(server, tmp_path):
    path = server.write_kubeconfig(str(tmp_path / "kubeconfig"))
    c = client_from_kubeconfig(path)
    c.create(_pod("kc1"))
    assert c.get("v1", "Pod", "default", "kc1")["metadata"]["name"] == "kc1"
