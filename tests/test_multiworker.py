"""Multi-worker slice e2e (VERDICT r1 #10): two KubeletSims + two daemons
as worker 0/1 of one v5litepod-8.

One cluster, two TPU-VM worker nodes of the same slice. Proves:
  * per-worker DataProcessingUnit CRs appear and go Ready
  * each worker's advertised device inventory maps exactly onto
    SliceTopology.local_chips() for its TPU_WORKER_ID — the k8s view and
    the topology view of the slice agree, and the workers partition the
    slice with no overlap
  * cross-node heartbeat over the OPI TCP endpoints
  * a ServiceFunctionChain whose NF pods cannot fit on one worker spans
    both (scheduler + device allocation across nodes)
  * the JAX view: build_mesh over the same 8-device slice (virtual CPU
    backend, as dryrun_multichip uses) covers exactly the chips the two
    k8s workers advertise
  * (root) CNI ADD plumbs a pod interface on BOTH workers

Reference counterpart: the Kind multi-node tier the reference leans on
(internal/daemon/daemon_test.go + dpusidemanager_test.go) — scaled to a
slice instead of a single node."""

import json
import shutil
import socket
import subprocess
import tempfile
import time
import uuid

import grpc
import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.api import v1
from dpu_operator_tpu.daemon import Daemon
from dpu_operator_tpu.dpu_api import services
from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb
from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster, get_condition
from dpu_operator_tpu.parallel import SliceTopology
from dpu_operator_tpu.platform import FakePlatform
from dpu_operator_tpu.testutils import KubeletSim
from dpu_operator_tpu.utils import PathManager
from dpu_operator_tpu.vsp import VspServer
from dpu_operator_tpu.vsp.tpu_dataplane import DebugDataplane
from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

ACCEL = "v5litepod-8"


def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Worker:
    """One TPU-VM worker of the slice: VSP + kubelet sim + daemon."""

    def __init__(self, client, worker_id: int):
        self.worker_id = worker_id
        self.node = f"tpu-w{worker_id}"
        self.env = {"TPU_ACCELERATOR_TYPE": ACCEL, "TPU_WORKER_ID": str(worker_id)}
        self.topology = SliceTopology.from_env(self.env)
        self.root = tempfile.mkdtemp(prefix=f"dpu-w{worker_id}-", dir="/tmp")
        self.pm = PathManager(root=self.root)
        client.create(
            {
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {
                    "name": self.node,
                    "labels": {v.NODE_OPT_IN_LABEL: v.NODE_OPT_IN_VALUE},
                },
            }
        )
        self.opi_port = free_port()
        # num_endpoints left default: the daemon's setup_devices
        # repartitions to 8 on init (reference SetNumVfs(8) hardcode,
        # dpudevicehandler.go:84-106); the SFC test shrinks it via a
        # DataProcessingUnitConfig CR, the supported knob.
        self.vsp = TpuVsp(
            topology=self.topology,
            dataplane=DebugDataplane(),
            opi_port=self.opi_port,
        )
        self.vsp_server = VspServer(self.vsp, self.pm)
        self.vsp_server.start()
        self.kubelet = KubeletSim(client, self.node, self.pm)
        self.kubelet.start()
        self.daemon = Daemon(
            client,
            FakePlatform(product="Google Cloud TPU", node=self.node, env=self.env),
            path_manager=self.pm,
            tick_interval=0.05,
            register_device_plugin=True,
        )
        self.daemon.start()

    def advertised_ids(self):
        with self.kubelet._lock:
            return set(self.kubelet._devices.get(v.DPU_RESOURCE_NAME, ()))

    def stop(self):
        self.daemon.stop()
        self.kubelet.stop()
        self.vsp_server.stop()
        shutil.rmtree(self.root, ignore_errors=True)


@pytest.fixture(scope="module")
def slice_cluster():
    client = InMemoryClient(InMemoryCluster())
    workers = [Worker(client, 0), Worker(client, 1)]
    try:
        yield client, workers
    finally:
        for w in workers:
            w.stop()


def _chip_indices(dev_ids):
    """tpu<chip>-ep<q> → {chip}."""
    out = set()
    for dev_id in dev_ids:
        assert dev_id.startswith("tpu"), dev_id
        out.add(int(dev_id.split("-")[0][len("tpu"):]))
    return out


def test_per_worker_crs_ready(slice_cluster):
    client, workers = slice_cluster
    for w in workers:
        cr_name = f"tpu-{ACCEL}-w{w.worker_id}-dpu"
        assert wait_for(
            lambda: client.get_or_none(
                v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, cr_name
            ) is not None
        ), f"{cr_name} never appeared"
        assert wait_for(
            lambda: (
                get_condition(
                    client.get(
                        v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT,
                        v.NAMESPACE, cr_name,
                    ),
                    "Ready",
                ) or {}
            ).get("status") == "True",
            timeout=20,
        ), f"{cr_name} never went Ready"
        cr = client.get(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, cr_name
        )
        assert cr["spec"]["nodeName"] == w.node
        assert cr["spec"]["isDpuSide"] is True


def test_inventory_partitions_slice_by_local_chips(slice_cluster):
    """Each worker advertises endpoints backed by EXACTLY its own chips
    (SliceTopology.local_chips), and together the workers cover the
    slice disjointly — the k8s inventory view equals the topology view."""
    _, workers = slice_cluster
    per_worker = {}
    for w in workers:
        # setup_devices partitions into 8 endpoints over 4 local chips.
        assert wait_for(
            lambda: len(w.advertised_ids()) == 8, timeout=20
        ), f"worker {w.worker_id} never advertised 8 endpoints"
        advertised = _chip_indices(w.advertised_ids())
        local = {c.index for c in w.topology.local_chips()}
        assert advertised == local, (
            f"worker {w.worker_id}: advertised {advertised} != local {local}"
        )
        assert len(local) == 4  # v5litepod-8 = 8 chips over 2 workers
        per_worker[w.worker_id] = advertised
    assert per_worker[0].isdisjoint(per_worker[1])
    assert per_worker[0] | per_worker[1] == {
        c.index for c in workers[0].topology.chips
    }


def test_cross_node_heartbeat_over_opi(slice_cluster):
    """Worker 0 pings worker 1's OPI heartbeat endpoint and vice versa —
    the cross-node TCP control plane the reference runs between host and
    DPU daemons (hostsidemanager.go:238-269)."""
    _, workers = slice_cluster
    for src, dst in ((workers[0], workers[1]), (workers[1], workers[0])):
        assert wait_for(lambda: _ping(dst, f"w{src.worker_id}")), (
            f"w{src.worker_id} → w{dst.worker_id} heartbeat failed"
        )


def _ping(dst, sender: str) -> bool:
    chan = grpc.insecure_channel(f"127.0.0.1:{dst.opi_port}")
    try:
        resp = services.HeartbeatStub(chan).Ping(
            pb.PingRequest(timestamp_ns=time.monotonic_ns(), sender_id=sender),
            timeout=5,
        )
        return resp.healthy
    except grpc.RpcError:
        return False
    finally:
        chan.close()


def test_sfc_spans_workers(slice_cluster):
    """Shrink every worker to 2 endpoints via DataProcessingUnitConfig
    (the supported partitioning knob), then run a chain of two NF pods —
    each requesting a full worker's endpoints — which must land on
    different workers (reference resource-exhaustion scheduling,
    e2e_test.go:558-626, scaled across a slice)."""
    client, workers = slice_cluster
    client.create(
        v1.new_data_processing_unit_config(name="shrink-all", num_endpoints=2)
    )
    for w in workers:
        assert wait_for(
            lambda: len(w.advertised_ids()) == 2, timeout=20
        ), f"worker {w.worker_id} never repartitioned to 2 endpoints"

    # Both daemons record their own DPU in the config's status and
    # preserve the other's entry (each owns only its managed DPUs), so
    # the CR shows the whole slice applied — two entries, two distinct
    # DPUs, both at the requested count — even with both daemons writing
    # the status concurrently (409s retry on later ticks).
    def applied_to():
        cfg = client.get_or_none(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT_CONFIG,
            v.NAMESPACE, "shrink-all",
        )
        return (cfg or {}).get("status", {}).get("appliedTo", [])

    def both_recorded():
        a = applied_to()
        return (
            len(a) == 2
            and len({e["dpu"] for e in a}) == 2
            and all(e["numEndpoints"] == 2 for e in a)
        )

    assert wait_for(both_recorded, timeout=20), (
        f"slice-wide status never converged: {applied_to()}"
    )
    # Both daemons have labelled their node dpuside=dpu by now.
    for w in workers:
        assert wait_for(
            lambda: (
                client.get("v1", "Node", None, w.node)["metadata"]["labels"].get(
                    v.DPU_SIDE_LABEL
                )
            ) == v.DPU_SIDE_DPU
        )
    sfc = v1.new_service_function_chain(
        name="span-chain",
        node_selector={v.DPU_SIDE_LABEL: v.DPU_SIDE_DPU},
        network_functions=[
            {"name": "span-nf-a", "image": "img:a"},
            {"name": "span-nf-b", "image": "img:b"},
        ],
    )
    client.create(sfc)
    try:
        def bound_nodes():
            nodes = {}
            for name in ("span-nf-a", "span-nf-b"):
                pod = client.get_or_none("v1", "Pod", v.NAMESPACE, name)
                if pod and pod["spec"].get("nodeName") and (
                    pod.get("status", {}).get("phase") == "Running"
                ):
                    nodes[name] = pod["spec"]["nodeName"]
            return nodes

        assert wait_for(lambda: len(bound_nodes()) == 2, timeout=30), (
            f"NF pods never all ran: {bound_nodes()}"
        )
        nodes = bound_nodes()
        assert set(nodes.values()) == {workers[0].node, workers[1].node}, (
            f"chain did not span both workers: {nodes}"
        )
        # Each pod was allocated that worker's full endpoint set.
        for w in workers:
            assert w.kubelet.allocatable(v.DPU_RESOURCE_NAME) == 0
    finally:
        client.delete_if_exists(
            v1.GROUP_VERSION, v1.KIND_SERVICE_FUNCTION_CHAIN, v.NAMESPACE,
            "span-chain",
        )
        for name in ("span-nf-a", "span-nf-b"):
            client.delete_if_exists("v1", "Pod", v.NAMESPACE, name)
        client.delete_if_exists(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT_CONFIG, v.NAMESPACE,
            "shrink-all",
        )


def test_jax_mesh_covers_the_same_slice(slice_cluster):
    """The dryrun_multichip mesh over the same slice size covers exactly
    the chips the two k8s workers advertise: the JAX view and the k8s
    view describe one slice."""
    from dpu_operator_tpu.parallel.mesh import build_mesh

    _, workers = slice_cluster
    all_chips = set()
    for w in workers:
        all_chips |= {c.index for c in w.topology.local_chips()}
    mesh = build_mesh(n_devices=workers[0].topology.num_chips)
    assert mesh.devices.size == len(all_chips) == 8
    # Same factoring the dry-run jits the train step over.
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    assert sizes.get("dp", 1) * sizes.get("sp", 1) * sizes.get("tp", 1) == 8


def test_cni_add_on_both_workers(slice_cluster, netns):
    """Pod attach on both workers of the slice: CNI ADD through each
    daemon's CNI server plumbs net1 into a distinct pod netns."""
    from dpu_operator_tpu.cni import CniRequest, do_cni

    _, workers = slice_cluster
    spawned = []
    try:
        for w in workers:
            ns = f"mwpod{w.worker_id}-{uuid.uuid4().hex[:6]}"
            r = subprocess.run(
                ["ip", "netns", "add", ns], capture_output=True, text=True
            )
            assert r.returncode == 0, r.stderr
            spawned.append(ns)
            sock = w.pm.cni_server_socket()
            assert wait_for(
                lambda: subprocess.run(
                    ["test", "-S", sock], capture_output=True
                ).returncode == 0
            ), f"CNI server socket never appeared for {w.node}"
            req = CniRequest(
                command="ADD",
                container_id=f"mw{w.worker_id}" + "0" * 10,
                netns=f"/var/run/netns/{ns}",
                ifname="net1",
                config={
                    "cniVersion": "1.0.0",
                    "name": "default-ici-net",
                    "type": "dpu-cni",
                },
            )
            resp = do_cni(sock, req)
            assert "error" not in resp, resp
            assert resp["ips"], resp
            allocated = resp["ips"][0]["address"].split("/")[0]
            out = subprocess.run(
                ["ip", "-n", ns, "-j", "addr", "show", "dev", "net1"],
                capture_output=True, text=True,
            )
            assert out.returncode == 0, out.stderr
            addrs = json.loads(out.stdout)[0]["addr_info"]
            assert any(a["local"] == allocated for a in addrs), (allocated, addrs)
            do_cni(sock, CniRequest(
                command="DEL", container_id=req.container_id,
                netns=req.netns, ifname="net1", config=req.config,
            ))
    finally:
        for ns in spawned:
            subprocess.run(["ip", "netns", "del", ns], capture_output=True)
