"""Units for the ISSUE 19 lifecycle layer (analysis/lifecycle/):
CFG exception-edge structure — including the two subtleties the
whole-package triage surfaced (break/continue must route through
in-loop ``finally`` bodies; ``len``/``isinstance``/``id`` are not
exception edges) — plus machine-vocabulary drift guards and focused
typestate behaviour the per-rule fixtures don't isolate. The fixture
pairs and acceptance scratch-copies live in test_graftlint.py; this
file is the white-box half.
"""

import ast
import textwrap
from pathlib import Path

from dpu_operator_tpu.analysis import run_analysis
from dpu_operator_tpu.analysis.lifecycle.cfg import build_cfg
from dpu_operator_tpu.analysis.lifecycle.machines import (
    KVBLOCKS, KVLEASE, MACHINES, SLOTBIND)
from dpu_operator_tpu.analysis.lifecycle.rules_life import (
    IllegalLifecycleTransition, LifecycleLeakOnException)

REPO = Path(__file__).resolve().parent.parent


# -- CFG helpers --------------------------------------------------------------


def _cfg(src: str):
    fn = ast.parse(textwrap.dedent(src)).body[0]
    return build_cfg(fn)


def _node_of(cfg, pred):
    hits = [n for n in cfg.nodes if pred(n)]
    assert len(hits) == 1, [(n.idx, n.kind) for n in hits]
    return hits[0]


def _stmt_node(cfg, stmt_type):
    return _node_of(cfg, lambda n: isinstance(n.stmt, stmt_type))


def _call_node(cfg, text):
    def pred(n):
        try:
            return (n.expr_root is not None
                    and text in ast.unparse(n.expr_root))
        except Exception:
            return False
    return _node_of(cfg, pred)


def _reaches(cfg, src, dst, normal_only=False):
    seen, work = set(), [src]
    while work:
        i = work.pop()
        if i == dst:
            return True
        if i in seen:
            continue
        seen.add(i)
        work.extend(t for t, exc in cfg.nodes[i].succ
                    if not (normal_only and exc))
    return False


# -- CFG structure ------------------------------------------------------------


def test_virtual_frame_nodes():
    cfg = _cfg("def f():\n    pass\n")
    assert [cfg.nodes[i].kind for i in
            (cfg.entry, cfg.exit, cfg.raise_exit)] == [
        "entry", "exit", "raise_exit"]


def test_call_statement_gets_exception_edge_to_raise_exit():
    cfg = _cfg("def f(x):\n    x.work()\n")
    node = _call_node(cfg, "x.work()")
    assert (cfg.raise_exit, True) in node.succ


def test_cant_raise_builtins_make_no_exception_edge():
    """len/isinstance/id are C-level queries on values this codebase
    hands them — modelling them as can-raise produced the kv_attach
    false positive (`need = need_total - len(cached)` read as an
    unprotected seam between fork and release)."""
    cfg = _cfg("def f(x):\n"
               "    n = len(x)\n"
               "    ok = isinstance(x, list)\n"
               "    k = id(x)\n"
               "    return n + ok + k\n")
    assert not any(exc for n in cfg.nodes for _t, exc in n.succ)
    # ...but any other call keeps its edge.
    cfg = _cfg("def f(x):\n    n = int(x)\n")
    assert (cfg.raise_exit, True) in _call_node(cfg, "int(x)").succ


def test_try_body_exceptions_land_in_handler_not_raise_exit():
    cfg = _cfg("def f(x):\n"
               "    try:\n"
               "        x.work()\n"
               "    except Exception:\n"
               "        x.undo()\n")
    node = _call_node(cfg, "x.work()")
    handler = _node_of(cfg, lambda n: n.kind == "handler")
    exc_targets = [t for t, exc in node.succ if exc]
    assert exc_targets == [handler.idx]
    assert handler.handler_of is not None


def test_break_routes_through_in_loop_finally():
    """A `break` inside try/finally must run the finalbody before
    leaving the loop — without this edge, a finally-released resource
    looked live at the loop exit (the _extend_from_tier false
    positive this PR fixed)."""
    cfg = _cfg("def f(items, res):\n"
               "    for it in items:\n"
               "        try:\n"
               "            if it:\n"
               "                break\n"
               "        finally:\n"
               "            res.close()\n"
               "    return 1\n")
    brk = _stmt_node(cfg, ast.Break)
    fin = _call_node(cfg, "res.close()")
    ret = _stmt_node(cfg, ast.Return)
    # break -> finally body, and no direct break -> after-loop edge.
    assert [t for t, _e in brk.succ] == [fin.idx]
    assert _reaches(cfg, fin.idx, ret.idx, normal_only=True)


def test_continue_routes_through_in_loop_finally():
    cfg = _cfg("def f(items, res):\n"
               "    for it in items:\n"
               "        try:\n"
               "            if it:\n"
               "                continue\n"
               "            it.work()\n"
               "        finally:\n"
               "            res.close()\n")
    cont = _stmt_node(cfg, ast.Continue)
    fin = _call_node(cfg, "res.close()")
    head = _node_of(cfg, lambda n: n.kind == "iter")
    assert [t for t, _e in cont.succ] == [fin.idx]
    assert _reaches(cfg, fin.idx, head.idx, normal_only=True)


def test_raise_in_try_routes_through_finally_to_raise_exit():
    cfg = _cfg("def f(x, res):\n"
               "    try:\n"
               "        raise ValueError(x)\n"
               "    finally:\n"
               "        res.close()\n")
    rs = _stmt_node(cfg, ast.Raise)
    fin = _call_node(cfg, "res.close()")
    assert all(t == fin.idx for t, _e in rs.succ)
    assert _reaches(cfg, fin.idx, cfg.raise_exit)
    # The raise never shortcuts past the finalbody.
    assert (cfg.raise_exit, True) not in rs.succ


# -- machine-vocabulary drift -------------------------------------------------


def _serving_defs():
    names = set()
    for p in (REPO / "dpu_operator_tpu" / "serving").rglob("*.py"):
        names.update(n.name for n in ast.walk(ast.parse(p.read_text()))
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)))
        names.update(n.name for n in ast.walk(ast.parse(p.read_text()))
                     if isinstance(n, ast.ClassDef))
    return names


def test_machine_vocabulary_binds_to_real_serving_names():
    """Every create/transition/handoff name a machine declares must
    exist as a def or class under serving/ — a renamed runtime method
    silently blinds the typestate walk otherwise."""
    defs = _serving_defs()
    for m in MACHINES:
        for ev in m.creates + m.transitions:
            assert ev.name in defs, f"{m.name}: {ev.name} not in serving/"
        for ctor in m.handoff_ctors:
            assert ctor in defs, f"{m.name}: ctor {ctor} not in serving/"


def test_release_names_are_terminal_transitions_plus_handoffs():
    assert KVBLOCKS.release_names() == {"release", "KVLease"}
    # detach is a transfer, not a settle: it must NOT make a handler
    # trusted for leases.
    assert "detach" not in KVLEASE.release_names()
    assert {"release", "on_request_settled"} <= KVLEASE.release_names()
    assert SLOTBIND.field_lifetime_at_exit  # the PR 7 shape depends on it


# -- focused typestate behaviour ----------------------------------------------

_HEADER = "# graftlint-fixture-path: dpu_operator_tpu/serving/fx_unit.py\n"
_LIFE_RULES = (IllegalLifecycleTransition, LifecycleLeakOnException)


def _life_findings(tmp_path, body):
    p = tmp_path / "fx.py"
    p.write_text(_HEADER + textwrap.dedent(body))
    report = run_analysis([str(p)], rules=[r() for r in _LIFE_RULES])
    return report.findings


def test_continue_through_finally_release_stays_clean(tmp_path):
    findings = _life_findings(tmp_path, """\
        class P:
            def drain(self, items, owner):
                for it in items:
                    blocks = self.allocator.acquire(4, owner)
                    try:
                        if not self.admit(it):
                            continue
                        self.consume(it)
                    finally:
                        self.allocator.release(blocks, owner)
        """)
    assert not findings, [f.format() for f in findings]


def test_unwind_shape_stays_clean_and_its_loss_fires(tmp_path):
    unwound = textwrap.dedent("""\
        class P:
            def pull(self, tokens, owner):
                blocks, n = self.prefix.match_and_fork(tokens, owner)
                try:
                    meta = self.spec.fingerprint(tokens)
                except Exception:
                    self.allocator.release(blocks, owner)
                    raise
                self.allocator.release(blocks, owner)
                return n, meta
        """)
    findings = _life_findings(tmp_path, unwound)
    assert not findings, [f.format() for f in findings]
    bare = unwound.replace(
        "        try:\n"
        "            meta = self.spec.fingerprint(tokens)\n"
        "        except Exception:\n"
        "            self.allocator.release(blocks, owner)\n"
        "            raise\n",
        "        meta = self.spec.fingerprint(tokens)\n")
    assert bare != unwound
    findings = _life_findings(tmp_path, bare)
    assert [f.rule for f in findings] == ["GL022"]


def test_double_release_fires_gl021_once(tmp_path):
    findings = _life_findings(tmp_path, """\
        class P:
            def shed(self, owner):
                blocks = self.allocator.acquire(4, owner)
                self.allocator.release(blocks, owner)
                self.allocator.release(blocks, owner)
        """)
    assert [f.rule for f in findings] == ["GL021"]


def test_escape_by_return_absorbs_the_object(tmp_path):
    """Returning the blocks hands ownership to the caller — absorbed,
    no leak. The can-raise call sits BEFORE the acquire on purpose:
    a raise between acquire and return is a real leak and must keep
    firing (the second function pins that)."""
    findings = _life_findings(tmp_path, """\
        class P:
            def lend(self, owner):
                self.audit(owner)
                blocks = self.allocator.acquire(4, owner)
                return blocks

            def lend_risky(self, owner):
                blocks = self.allocator.acquire(4, owner)
                self.audit(owner)
                return blocks
        """)
    assert [f.rule for f in findings] == ["GL022"]
    assert findings[0].func == "P.lend_risky"
