"""Fused Pallas paged-attention kernel + int8 KV residency (ISSUE 13).

Equivalence strategy: the Pallas kernel and the XLA composition run
behind the SAME ``PagedDecodeStep`` signature with bit-identical
quantization math (scale updates run in XLA for both), so

  * pool CONTENTS (fp32 rows, int8 codes, per-block scales) must match
    BITWISE between the two kernels — appends are the same writes;
  * token STREAMS must match exactly — the only float divergence is
    the online-softmax reassociation in the attention sum (<= ~1e-5
    relative on the logits at these shapes), which argmax absorbs.

That pair is the documented numeric tolerance of the equivalence
lane: exact where bytes are the contract (pools, tokens), reassocia-
tion-level where floats are (attention internals). Off-TPU the Pallas
path runs under the interpreter (pallas_guide.md interpret mode);
construction AOT-compiles like every executor, ~2 s per instance at
these shapes — the docs/ci.md lane budget entry.

The int8 residency quality lane reuses the PR 9 methodology: measured
per-element error of the dequantized resident pools against the
fp32-resident truth must sit inside the documented
``paged_kv_error_bound`` per block, per step.
"""

import time

import numpy as np
import pytest

from dpu_operator_tpu.serving import (AdmissionQueue, ContinuousBatcher,
                                      GenerateRequest, PagedKVExecutor)
from dpu_operator_tpu.serving.kvcache import (kv_bytes_per_slot,
                                              paged_kv_error_bound)

# Tiny-but-honest shapes: prompts cross block boundaries, prefill is
# chunked, the table has room for decode past the prompt.
DIMS = dict(slots=2, vocab=16, d=8, heads=2, block_size=4,
            num_blocks=32, max_blocks_per_req=4, prefill_chunk=4,
            seed=0)

# Two prompts: one crossing two blocks mid-chunk, one short — plus
# decode to 4 tokens each keeps every lane under a second of steps.
PROMPTS = [[1, 2, 3, 4, 5, 6], [7, 8, 9]]
MAX_TOKENS = 4


def _mk(kernel, pool_dtype, mode="sync", **kw):
    args = dict(DIMS, kernel=kernel, pool_dtype=pool_dtype, mode=mode,
                interpret=True if kernel == "pallas" else None)
    args.update(kw)
    return PagedKVExecutor(**args)


def _req(prompt, max_tokens=MAX_TOKENS, deadline_s=60.0):
    return GenerateRequest(prompt_vec=None, max_tokens=max_tokens,
                           deadline=time.monotonic() + deadline_s,
                           prompt_tokens=list(prompt))


def _drive_direct(ex, prompts, max_tokens=MAX_TOKENS):
    """Sync-loop the executor directly (no batcher): attach all,
    submit/collect until every stream has max_tokens, release. Returns
    (streams, blocks_per_req) — blocks captured before release so the
    error-bound lane can find each request's pages."""
    reqs = [_req(p, max_tokens) for p in prompts]
    for s, r in enumerate(reqs):
        ex.kv_attach(s, r)
    streams = [[] for _ in reqs]
    for _ in range(200):
        toks = ex.collect(ex.submit((), gen=ex.kv_gen()))
        for s in range(len(reqs)):
            if toks[s] >= 0 and len(streams[s]) < max_tokens:
                streams[s].append(int(toks[s]))
                reqs[s].tokens.append(int(toks[s]))
        if all(len(st) == max_tokens for st in streams):
            break
    assert all(len(st) == max_tokens for st in streams), streams
    blocks = [list(r.kv_lease.blocks) for r in reqs]
    for s, r in enumerate(reqs):
        ex.kv_release_slot(s, cache=False)
        r.finish()
    ex.allocator.assert_clean()
    return streams, blocks


def _drive_batched(ex, prompts, max_tokens=MAX_TOKENS, timeout=30.0):
    q = AdmissionQueue(max_depth=len(prompts) + 1)
    b = ContinuousBatcher(ex, q)
    reqs = [_req(p, max_tokens) for p in prompts]
    for r in reqs:
        q.submit(r)
    b.start()
    try:
        for r in reqs:
            assert r.wait(timeout=timeout), "request lost"
    finally:
        b.stop()
    for r in reqs:
        assert r.error is None, r.error
    return [list(r.tokens) for r in reqs]


# -- the Pallas-vs-XLA equivalence lane ---------------------------------------


@pytest.mark.parametrize("pool_dtype", ["fp32", "int8"])
def test_pallas_matches_xla_pools_bitwise_and_streams(pool_dtype):
    """Same seed, same prompts, both kernels: resident pools (codes +
    scales) must be BITWISE equal — the append path is the same math
    in both — and the token streams identical (the online-softmax
    reassociation stays under argmax's decision margin; see module
    docstring for the documented tolerance)."""
    ex_x = _mk("xla", pool_dtype)
    ex_p = _mk("pallas", pool_dtype)
    streams_x, _ = _drive_direct(ex_x, PROMPTS)
    streams_p, _ = _drive_direct(ex_p, PROMPTS)
    assert streams_p == streams_x
    assert any(len(set(s)) > 1 for s in streams_x), \
        "degenerate streams would make this equality vacuous"
    np.testing.assert_array_equal(np.asarray(ex_p._kpool),
                                  np.asarray(ex_x._kpool))
    np.testing.assert_array_equal(np.asarray(ex_p._vpool),
                                  np.asarray(ex_x._vpool))
    np.testing.assert_array_equal(np.asarray(ex_p._kscale),
                                  np.asarray(ex_x._kscale))
    np.testing.assert_array_equal(np.asarray(ex_p._vscale),
                                  np.asarray(ex_x._vscale))


def test_fp32_kernel_path_sync_pipelined_streams_byte_identical():
    """ISSUE 13 acceptance: the kernel path under the REAL batcher —
    sync vs pipelined loops over fp32 pools decode byte-identical
    streams (plans depend only on committed cursors; the kernel sits
    behind the unchanged submit/collect seam)."""
    streams = {}
    for mode in ("sync", "pipelined"):
        ex = _mk("pallas", "fp32", mode=mode)
        streams[mode] = _drive_batched(ex, PROMPTS)
    assert streams["sync"] == streams["pipelined"]


# -- the valid-block guard (ISSUE 13 satellite) -------------------------------


@pytest.mark.parametrize("kernel,pool_dtype", [
    ("xla", "fp32"), ("xla", "int8"),
    ("pallas", "fp32"), ("pallas", "int8")])
def test_poisoned_unwritten_blocks_cannot_leak(kernel, pool_dtype):
    """Regression (ISSUE 13 satellite): attention validity used to
    rest solely on the additive -1e30 score mask — which cannot stop
    garbage on the VALUE path (softmax weight 0 times NaN is NaN),
    exactly the exposure once pools hold dequantized int8 scratch.
    Poison EVERYTHING (codes at full-scale garbage, scales at NaN,
    fp32 rows at NaN), re-decode the same prompts, and the streams
    must be identical to the clean run: every attended position is
    re-written before attention can reach it, and the explicit
    valid-block guard zeroes everything beyond the written context."""
    ex = _mk(kernel, pool_dtype, prefix_cache=False)
    golden, _ = _drive_direct(ex, PROMPTS)
    import jax.numpy as jnp

    if pool_dtype == "int8":
        poison = jnp.full(ex._kpool.shape, 113, jnp.int8)
        ex._kpool, ex._vpool = poison, -poison
    else:
        ex._kpool = jnp.full(ex._kpool.shape, np.nan, jnp.float32)
        ex._vpool = jnp.full(ex._vpool.shape, np.nan, jnp.float32)
    ex._kscale = jnp.full(ex._kscale.shape, np.nan, jnp.float32)
    ex._vscale = jnp.full(ex._vscale.shape, np.nan, jnp.float32)
    again, _ = _drive_direct(ex, PROMPTS)
    assert again == golden, (again, golden)


# -- int8 residency quality: the PR 9 error-bound methodology ----------------


def test_int8_residency_error_bounded_and_streams_match_fp32():
    """Drive identical traces over fp32-resident and int8-resident
    pools (XLA kernel, same seed => same weights, same allocator order
    => same physical blocks). Per written block, the dequantized int8
    K/V must sit within the documented ``paged_kv_error_bound`` of the
    fp32 truth — rounding scale/2 plus any clip excess beyond the
    block's first-write dynamic range. At these shapes the bound is
    tight enough that the token streams also stay identical (pinned
    seed: a future change that flips a token is a quality regression
    to re-justify, not noise)."""
    ex_f = _mk("xla", "fp32")
    ex_q = _mk("xla", "int8")
    streams_f, blocks_f = _drive_direct(ex_f, PROMPTS)
    streams_q, blocks_q = _drive_direct(ex_q, PROMPTS)
    assert blocks_q == blocks_f, "allocator order must match"
    assert streams_q == streams_f
    kf = np.asarray(ex_f._kpool)
    vf = np.asarray(ex_f._vpool)
    kq, vq = ex_q._paged.dequantized_pools(
        ex_q._kpool, ex_q._kscale, ex_q._vpool, ex_q._vscale)
    kscale = np.asarray(ex_q._kscale)
    vscale = np.asarray(ex_q._vscale)
    checked = 0
    for blocks in blocks_f:
        for b in blocks:
            for deq, ref, sc in ((kq, kf, kscale[b]),
                                 (vq, vf, vscale[b])):
                err = float(np.max(np.abs(deq[b] - ref[b])))
                amax = float(np.max(np.abs(ref[b])))
                bound = paged_kv_error_bound(float(sc), amax)
                assert err <= bound + 1e-6, (b, err, bound)
                checked += 1
    assert checked >= 8  # really walked written blocks


# -- residency accounting -----------------------------------------------------


def test_kv_bytes_per_slot_reduction_at_least_3_5x():
    """The acceptance arithmetic, at both the test shapes and a
    bench/deploy-sized layout: int8 codes + per-block scales vs fp32
    rows is >= 3.5x fewer resident bytes per slot."""
    for dims in ((4, 4, 2, 4),          # the test shapes above
                 (32, 16, 8, 128)):     # deploy-sized pages
        B, bs, H, dh = dims
        fp32 = kv_bytes_per_slot(B, bs, H, dh, "fp32")
        int8 = kv_bytes_per_slot(B, bs, H, dh, "int8")
        assert fp32 / int8 >= 3.5, (dims, fp32 / int8)
    ex = _mk("xla", "int8")
    assert ex._paged.kv_bytes_per_slot() == kv_bytes_per_slot(
        4, 4, 2, 4, "int8")


def test_prefix_cache_hit_reproduces_stream_on_kernel_path():
    """Prefix reuse on the Pallas+int8 path: a cache-hit rerun decodes
    the same stream as the cold run. Designed property, not luck:
    cached blocks are reused byte-for-byte, and fresh appends restart
    at a block-aligned cursor so their quantization groups equal the
    cold run's (the scale-once append rule)."""
    ex = _mk("pallas", "int8", mode="sync")
    (first,) = _drive_batched(ex, [PROMPTS[0]])
    hits0 = ex.prefix.hit_tokens
    (second,) = _drive_batched(ex, [PROMPTS[0]])
    assert second == first
    assert ex.prefix.hit_tokens > hits0, "the rerun never hit the cache"
    ex.prefix.flush()
    ex.allocator.assert_clean()


# -- kernel-path re-attach (the chaos-matrix property, executor level) --------


def test_reattach_resumes_identically_on_kernel_path():
    """Kill/resume on the Pallas+int8 path: decode part-way, reset()
    (pools survive), re-attach from settled tokens — the continuation
    must equal the uninterrupted golden stream (append idempotence of
    the scale-once quantizer; a whole-block requantizer would diverge
    here)."""
    ex = _mk("pallas", "int8")
    golden, _ = _drive_direct(ex, [PROMPTS[0]])
    req = _req(PROMPTS[0])
    ex.kv_attach(0, req)
    while len(req.tokens) < 2:
        t = int(ex.collect(ex.submit((), gen=ex.kv_gen()))[0])
        if t >= 0:
            req.tokens.append(t)
    ex.reset()
    assert req.kv_lease.resumable
    ex.kv_attach(0, req)
    while len(req.tokens) < MAX_TOKENS:
        t = int(ex.collect(ex.submit((), gen=ex.kv_gen()))[0])
        if t >= 0:
            req.tokens.append(t)
    assert list(req.tokens) == golden[0]
    ex.kv_release_slot(0, cache=False)
    req.finish()
    ex.allocator.assert_clean()


# -- Mosaic lowering proof (no TPU hardware needed) ---------------------------


@pytest.mark.slow
def test_pallas_paged_attn_aot_lowers_for_tpu():
    """AOT-lower the fused kernel for an abstract TPU target — Mosaic
    compilation is proven without hardware, the collective-matmul
    discipline."""
    import jax
    import jax.export  # explicit: not re-exported at the jax top level
    import jax.numpy as jnp

    from dpu_operator_tpu.parallel.pallas_paged_attn import (
        make_paged_attn_step,
    )

    S, C, B, bs, H, dh, N = 4, 8, 8, 16, 4, 128, 64
    step = make_paged_attn_step(S, C, B, bs, H, dh, N,
                                pool_dtype="int8", interpret=False)
    f32 = jnp.float32
    args = (
        jax.ShapeDtypeStruct((S, B), jnp.int32),
        jax.ShapeDtypeStruct((S,), jnp.int32),
        jax.ShapeDtypeStruct((S,), jnp.int32),
        jax.ShapeDtypeStruct((S, C, H, dh), f32),
        jax.ShapeDtypeStruct((S, C, H, dh), f32),
        jax.ShapeDtypeStruct((S, C, H, dh), f32),
        jax.ShapeDtypeStruct((S, C), f32),
        jax.ShapeDtypeStruct((S, C), f32),
        jax.ShapeDtypeStruct((S, B), f32),
        jax.ShapeDtypeStruct((S, B), f32),
        jax.ShapeDtypeStruct((N, bs, H, dh), jnp.int8),
        jax.ShapeDtypeStruct((N, bs, H, dh), jnp.int8),
    )
    exp = jax.export.export(jax.jit(step), platforms=["tpu"])(*args)
    assert "tpu_custom_call" in exp.mlir_module()
