"""Paged KV-cache decode (ISSUE 7): allocator/prefix-tree contracts,
paged-attention invariances, chunked prefill, scheduler integration,
retry re-attach plumbing, and the /metrics exposition of the new
series.

Correctness strategy for the device step: INVARIANCE, not a duplicated
reference model — the same prompt must decode the same stream under
every scheduling decomposition (sync vs pipelined loop, chunk=1 vs
chunk=8 prefill, block_size 2 vs 8 paging, prefix cache on vs off).
Those axes are exactly where paged attention can go wrong (append
offsets, causal masks, table gathers, cache reuse), and any bug in one
of them breaks cross-decomposition equality.

Every test that touches an allocator asserts ZERO leaked blocks at the
end — the ISSUE 7 acceptance contract, enforced here as teardown."""

import time

import numpy as np
import pytest

from dpu_operator_tpu.serving import (AdmissionQueue, ContinuousBatcher,
                                      GenerateRequest, ReplicaPool,
                                      SyntheticKVExecutor)
from dpu_operator_tpu.serving.api import KV_OOM_ERROR
from dpu_operator_tpu.serving.kvcache import (CACHE_OWNER,
                                              KVBlockAllocator,
                                              KVCacheOOM, KVLease,
                                              PrefixTree)

MODEL = dict(vocab=32, d=16, heads=2)


def _req(prompt, max_tokens=5, deadline_s=60.0):
    return GenerateRequest(prompt_vec=None, max_tokens=max_tokens,
                           deadline=time.monotonic() + deadline_s,
                           prompt_tokens=list(prompt))


def _drive(ex, reqs, timeout=30.0):
    """Run requests through a real ContinuousBatcher over `ex`."""
    q = AdmissionQueue(max_depth=len(reqs) + 1)
    b = ContinuousBatcher(ex, q)
    for r in reqs:
        q.submit(r)
    b.start()
    try:
        for r in reqs:
            assert r.wait(timeout=timeout), "request lost"
    finally:
        b.stop()
    for r in reqs:
        assert r.error is None, r.error
    return [list(r.tokens) for r in reqs]


# -- allocator ---------------------------------------------------------------


def test_allocator_acquire_release_refcount_and_oom():
    a = KVBlockAllocator(num_blocks=4, block_size=2)
    b1 = a.acquire(2, "r1")
    assert len(b1) == 2 and a.free_count() == 2
    a.fork(b1, "r2")                      # shared: ref 2 each
    assert a.stats() == {"used": 2, "free": 2, "shared": 2}
    assert a.release(b1, "r1") == 0       # r2 still holds them
    assert a.release(b1, "r2") == 2       # now they free
    assert a.free_count() == 4
    with pytest.raises(KVCacheOOM):
        a.acquire(5, "r3")
    # Atomic OOM: the failed grant must not have consumed anything.
    assert a.free_count() == 4
    a.assert_clean()


def test_allocator_leak_ledger_names_owner_and_double_free_raises():
    a = KVBlockAllocator(num_blocks=4, block_size=2)
    blocks = a.acquire(2, "leaky")
    assert a.leaked() == {"leaky": sorted(blocks)}
    with pytest.raises(AssertionError, match="leaky"):
        a.assert_clean()
    a.release(blocks, "leaky")
    with pytest.raises(ValueError, match="not held"):
        a.release(blocks, "leaky")        # the double free
    a.assert_clean()


def test_lease_release_idempotent_and_settle_hook_fires():
    a = KVBlockAllocator(num_blocks=4, block_size=2)
    blocks = a.acquire(2, "r1")
    lease = KVLease(a, "ex", "r1", blocks, (1, 2, 3), 0)
    req = _req([1, 2, 3])
    req.kv_lease = lease
    # Any settle path (here: a failure) must return the pages via the
    # finish hook — and a second release must no-op, not double-free.
    req.fail("boom")
    assert not lease.resumable
    assert a.free_count() == 4
    assert lease.release() is False
    a.assert_clean()


# -- prefix tree -------------------------------------------------------------


def test_prefix_tree_matches_full_blocks_and_never_whole_prompt():
    a = KVBlockAllocator(num_blocks=8, block_size=4)
    t = PrefixTree(a)
    toks = list(range(12))
    blocks = a.acquire(3, "r1")
    t.insert(toks, blocks)                # 3 full blocks cached
    # Identical prompt: the cap leaves the LAST token to recompute, so
    # only 2 of 3 full blocks match (12 tokens → limit (12-1)//4 = 2).
    got, n = t.match_and_fork(toks, "r2")
    assert n == 8 and got == blocks[:2]
    a.release(got, "r2")
    # Diverging second block: only the first matches.
    other = toks[:4] + [99, 98, 97, 96] + toks[8:]
    got2, n2 = t.match_and_fork(other, "r3")
    assert n2 == 4 and got2 == blocks[:1]
    a.release(got2, "r3")
    a.release(blocks, "r1")
    assert t.flush() == 3
    a.assert_clean(ignore=())


def test_prefix_tree_evicts_lru_leaves_only():
    a = KVBlockAllocator(num_blocks=4, block_size=2)
    t = PrefixTree(a)
    b = a.acquire(2, "r1")
    t.insert([1, 2, 3, 4], b)             # chain: b0 -> b1
    a.release(b, "r1")
    assert a.free_count() == 2            # cache holds both
    # One block wanted: the LEAF (b1) goes first, never the interior.
    assert t.evict(1) == 1
    got, n = t.match_and_fork([1, 2, 9, 9, 9], CACHE_OWNER + "x")
    assert n == 2 and got == [b[0]]       # b0 survived
    a.release(got, CACHE_OWNER + "x")
    t.flush()
    a.assert_clean(ignore=())


# -- scheduling: chunked prefill protects decode -----------------------------


def test_decode_never_stalls_behind_chunked_prefill():
    """The Sarathi property, asserted at plan granularity: a slot in
    decode emits a token EVERY step even while a long prompt prefills
    in another slot under the shared token budget."""
    ex = SyntheticKVExecutor(slots=2, prefill_chunk=4, pipelined=False,
                            num_blocks=64)
    ra = _req([1, 2, 3], max_tokens=32)
    assert ex.kv_attach(0, ra) == 0
    # Drive A to decode phase.
    toks = ex.collect(ex.submit((), gen=ex.kv_gen()))
    assert toks[0] >= 0
    ra.tokens.append(int(toks[0]))
    # Long prompt lands mid-run in slot 1.
    rb = _req(list(np.arange(24) % 7), max_tokens=4)
    ex.kv_attach(1, rb)
    for _ in range(5):                    # B prefills for 24/4 steps
        toks = ex.collect(ex.submit((), gen=ex.kv_gen()))
        assert toks[0] >= 0, "decode starved by prefill"
        ra.tokens.append(int(toks[0]))
    assert ex.steps_mixed >= 5            # prefill really co-ran
    ex.kv_release_slot(0)
    ex.kv_release_slot(1)
    ra.finish()
    rb.finish()
    ex.allocator.assert_clean()
    ex.close()


def test_prefill_budget_round_robin_makes_progress_for_all_prompts():
    ex = SyntheticKVExecutor(slots=2, prefill_chunk=4, prefill_budget=4,
                            pipelined=False, num_blocks=64)
    r0 = _req(list(np.arange(16) % 5), max_tokens=2)
    r1 = _req(list(np.arange(16) % 3), max_tokens=2)
    streams = _drive(ex, [r0, r1])
    assert all(len(s) == 2 for s in streams)
    ex.allocator.assert_clean()
    ex.close()


# -- invariance: the same stream under every decomposition -------------------


def _paged(**kw):
    from dpu_operator_tpu.serving import PagedKVExecutor

    args = dict(slots=2, block_size=4, num_blocks=64,
                max_blocks_per_req=8, prefill_chunk=8, seed=0, **MODEL)
    args.update(kw)
    return PagedKVExecutor(**args)


@pytest.fixture(scope="module")
def paged_pair():
    """One compiled executor per loop shape (compile cost dominates;
    reuse is safe — each batcher reset()s at start)."""
    return {"pipelined": _paged(mode="pipelined"),
            "sync": _paged(mode="sync")}


# The 26-token prompt makes plen + max_tokens == 32 == the FULL
# 8-block table at block_size 4: the pipelined loop's one phantom
# plan after the final emitted token appends at position 31 — the
# last reserved slot — and any off-by-one there would walk off the
# block table into the zero tail (= real block 0) instead.
PROMPTS = [list(np.arange(25) % 13), [3, 1, 4, 1, 5], [9] * 12,
           list(np.arange(26) % 13)]


def test_paged_sync_and_pipelined_streams_byte_identical(paged_pair):
    """ISSUE 7 acceptance: the pipelined paged-KV loop (device-chained
    recurrence, one-step-later admissions) produces byte-identical
    token streams to the sync loop on a fixed trace that includes a
    long prompt chunk-prefilled mid-run."""
    streams = {}
    for mode, ex in paged_pair.items():
        streams[mode] = _drive(ex, [_req(p, max_tokens=6)
                                    for p in PROMPTS])
        ex.allocator.assert_clean()
    assert streams["pipelined"] == streams["sync"]
    assert any(len(set(s)) > 1 for s in streams["sync"]), \
        "degenerate streams would make this equality vacuous"


def test_synthetic_sync_and_pipelined_streams_byte_identical():
    streams = {}
    for pipelined in (True, False):
        ex = SyntheticKVExecutor(slots=2, pipelined=pipelined,
                                num_blocks=64)
        streams[pipelined] = _drive(
            ex, [_req(p, max_tokens=6) for p in PROMPTS])
        ex.allocator.assert_clean()
        ex.close()
    assert streams[True] == streams[False]


def test_paged_stream_invariant_under_chunk_and_block_size():
    """Paging must be invisible: chunk=1 (token-at-a-time prefill) vs
    chunk=8, and block_size 2 vs 8 (same total context so the weights
    match), all decode the identical stream — the axes where append
    offsets, causal masks and table gathers would break. Pinned to
    fp32 pools: geometry invariance is EXACT there; on the int8
    resident default the quantization groups change with block/chunk
    size by design, and that divergence is bounded separately
    (tests/test_paged_attn.py's error-bound lane)."""
    prompt = list(np.arange(13) % 7)
    golden = None
    for kw in (dict(prefill_chunk=8, block_size=4, max_blocks_per_req=8),
               dict(prefill_chunk=1, block_size=4, max_blocks_per_req=8),
               dict(prefill_chunk=8, block_size=2, max_blocks_per_req=16),
               dict(prefill_chunk=8, block_size=8, max_blocks_per_req=4)):
        ex = _paged(mode="sync", pool_dtype="fp32", **kw)
        (stream,) = _drive(ex, [_req(prompt, max_tokens=6)])
        ex.allocator.assert_clean()
        if golden is None:
            golden = stream
        assert stream == golden, (kw, stream, golden)
    assert len(set(golden)) > 1


def test_paged_prefix_cache_hit_reproduces_uncached_stream(paged_pair):
    ex = paged_pair["pipelined"]
    prompt = list(np.arange(21) % 11)
    (first,) = _drive(ex, [_req(prompt, max_tokens=5)])
    hits0 = ex.prefix.hit_tokens
    req = _req(prompt, max_tokens=5)
    (second,) = _drive(ex, [req])
    assert second == first
    assert req.kv_lease.cached_tokens > 0
    assert ex.prefix.hit_tokens > hits0
    ex.allocator.assert_clean()
    # And with the cache disabled the stream is still the same.
    nocache = _paged(mode="sync", prefix_cache=False)
    (third,) = _drive(nocache, [_req(prompt, max_tokens=5)])
    assert third == first
    nocache.allocator.assert_clean()


# -- retry re-attach plumbing ------------------------------------------------


def test_reattach_resumes_from_settled_tokens():
    """The rewind contract: k settled tokens → re-attach replays ONLY
    the in-flight remainder, and the resumed stream equals an
    uninterrupted run's (the synthetic token fn is position-dependent,
    so a wrong rewind shows)."""
    prompt = list(np.arange(16) % 9)
    ref = SyntheticKVExecutor(slots=1, pipelined=False, num_blocks=64)
    (golden,) = _drive(ref, [_req(prompt, max_tokens=6)])
    ref.allocator.assert_clean()
    ref.close()

    ex = SyntheticKVExecutor(slots=1, pipelined=False, num_blocks=64)
    req = _req(prompt, max_tokens=6)
    ex.kv_attach(0, req)
    steps = 0
    while len(req.tokens) < 3:            # decode part-way, then "die"
        t = int(ex.collect(ex.submit((), gen=ex.kv_gen()))[0])
        steps += 1
        if t >= 0:
            req.tokens.append(t)
    ex.reset()                            # replica restart
    assert req.kv_lease.resumable
    ex.kv_attach(0, req)                  # re-attach, not re-prefill
    assert ex.resumed_total == 1
    resumed_steps = 0
    while len(req.tokens) < 6:
        t = int(ex.collect(ex.submit((), gen=ex.kv_gen()))[0])
        resumed_steps += 1
        if t >= 0:
            req.tokens.append(t)
    assert list(req.tokens) == golden
    # Strictly fewer replayed steps than prompt re-decode: resume cost
    # is the remaining tokens only, never the prefill again.
    assert resumed_steps == 3 < steps + resumed_steps
    ex.kv_release_slot(0)
    req.finish()
    ex.allocator.assert_clean()
    ex.close()


def test_foreign_lease_released_and_stream_restarts_identically():
    """A lease seized from replica A means nothing in replica B's
    pool: B releases it (via A's allocator — no leak on EITHER side),
    clears the partial tokens, and re-decodes from the prompt to the
    same deterministic stream."""
    prompt = list(np.arange(12) % 5)
    a = SyntheticKVExecutor(slots=1, pipelined=False, num_blocks=64)
    b = SyntheticKVExecutor(slots=1, pipelined=False, num_blocks=64)
    (golden,) = _drive(SyntheticKVExecutor(slots=1, pipelined=False,
                                          num_blocks=64),
                       [_req(prompt, max_tokens=4)])
    req = _req(prompt, max_tokens=4)
    a.kv_attach(0, req)
    while not req.tokens:
        t = int(a.collect(a.submit((), gen=a.kv_gen()))[0])
        if t >= 0:
            req.tokens.append(t)
    lease_a = req.kv_lease
    a.reset()                             # A's replica died
    b.kv_attach(0, req)                   # B picks the requeue up
    assert req.kv_lease is not lease_a and not lease_a.resumable
    assert req.tokens == []               # fresh decode, no half state
    while len(req.tokens) < 4:
        t = int(b.collect(b.submit((), gen=b.kv_gen()))[0])
        if t >= 0:
            req.tokens.append(t)
    assert list(req.tokens) == golden
    b.kv_release_slot(0)
    req.finish()
    a.allocator.assert_clean()
    b.allocator.assert_clean()
    a.close()
    b.close()


def test_stale_generation_submit_is_noop():
    """The seize-race guard: a submit carrying a pre-reset generation
    must neither advance cursors nor emit (NO_TOKEN everywhere)."""
    ex = SyntheticKVExecutor(slots=1, pipelined=False, num_blocks=64)
    req = _req([1, 2, 3], max_tokens=4)
    ex.kv_attach(0, req)
    stale_gen = ex.kv_gen()
    ex.reset()
    out = ex.collect(ex.submit((), gen=stale_gen))
    assert (out == -1).all()
    req.finish()
    ex.allocator.assert_clean()
    ex.close()


def test_zero_work_slot_raced_by_retire_readmit_noops_at_collect():
    """Regression (ISSUE 15 satellite): the collect owner guard
    (kvcache/executor.py) is load-bearing for speculative rollback,
    and its ZERO-TOKEN case was untested — a budget-starved slot
    (n_new == 0: the plan recorded the owner but planned no work)
    raced by retire + re-admit between submit and collect must be a
    PURE no-op at collect: no watermark advance, no last_token stamp
    on the slot's new occupant. Both guards are exercised: the
    owner mismatch (rebound slot) and the n_new == 0 check (same
    owner, zero work)."""
    ex = SyntheticKVExecutor(slots=2, prefill_chunk=4, prefill_budget=4,
                             pipelined=False, num_blocks=64,
                             prefix_cache=False)
    long_req = _req(list(np.arange(16) % 5), max_tokens=2)
    starved = _req(list(np.arange(8) % 3), max_tokens=2)
    ex.kv_attach(0, long_req)
    ex.kv_attach(1, starved)
    # First plan (rotating start at slot 0): slot 0 takes the whole
    # 4-token budget, slot 1 gets n_new == 0.
    h = ex.submit((), gen=ex.kv_gen())
    assert int(h.plan.n_new[1]) == 0 and h.plan.owners[1] is not None
    # Case 1 — same owner, zero work: nothing may move at collect.
    st = ex._states[1]
    confirmed0, last0 = st.confirmed, st.last_token
    ex.collect(h)
    assert st.confirmed == confirmed0 and st.last_token is last0

    # Step 2's rotating start favors slot 1; collect it so step 3
    # starts at slot 0 again and slot 1 is starved once more.
    ex.collect(ex.submit((), gen=ex.kv_gen()))

    # Case 2 — budget-starved slot retired + re-admitted between
    # submit and collect: the rebound slot's fresh state must be
    # untouched by the old zero-work handle.
    h2 = ex.submit((), gen=ex.kv_gen())
    assert int(h2.plan.n_new[1]) == 0
    assert h2.plan.owners[1] == starved.request_id
    ex.kv_release_slot(1, cache=False)       # retire
    starved.fail("seized elsewhere")
    fresh = _req([7, 7, 7], max_tokens=2)
    ex.kv_attach(1, fresh)                   # re-admit
    st2 = ex._states[1]
    confirmed0, last0 = st2.confirmed, st2.last_token
    ex.collect(h2)
    assert st2.confirmed == confirmed0 and st2.last_token is last0
    ex.kv_release_slot(0, cache=False)
    ex.kv_release_slot(1, cache=False)
    long_req.finish()
    fresh.finish()
    ex.allocator.assert_clean()
    ex.close()


@pytest.mark.parametrize("pipelined", [False, True])
def test_decode_token_counter_matches_delivered(pipelined):
    """Regression: decode_tokens was counted at PLAN time, so the
    pipelined loop's phantom post-retire step (submit(k+1) precedes
    retire(k)) inflated the counter — and the bench's headline
    serving_tokens_per_s — by one step per request, while sync mode
    under-counted the prefill-finish emit. Both modes must now report
    exactly the tokens clients received."""
    ex = SyntheticKVExecutor(slots=2, pipelined=pipelined,
                             num_blocks=64, prefix_cache=False)
    reqs = [_req([1 + i, 2, 3, 4, 5], max_tokens=4) for i in range(5)]
    streams = _drive(ex, reqs)
    delivered = sum(len(s) for s in streams)
    assert delivered == 5 * 4
    assert ex.kv_stats()["decode_tokens"] == delivered
    ex.allocator.assert_clean()
    ex.close()


def test_admit_unwind_releases_executor_slot_binding():
    """Regression: when a statement AFTER a successful kv_attach in
    the admit path raised (here: the tracer's admit event), the
    generic unwind cleared the batcher slot but left the executor's
    slot state bound — poisoning the slot ("already bound" for every
    future admit on it) and planning decode for a ghost state."""

    class _AdmitBoom:
        enabled = True

        def event(self, name, **kw):
            if name == "batcher.admit":
                raise RuntimeError("trace plane down")

        def decision(self, *a, **kw):
            pass

        def record_span(self, *a, **kw):
            pass

    ex = SyntheticKVExecutor(slots=1, pipelined=False, num_blocks=64)
    q = AdmissionQueue(max_depth=4)
    b = ContinuousBatcher(ex, q)
    real_tracer = b.tracer
    b.tracer = _AdmitBoom()
    doomed = _req([1, 2, 3], max_tokens=3)
    q.submit(doomed)
    b.start()
    try:
        assert doomed.wait(10)
        assert doomed.error and "admission failed" in doomed.error
        b.tracer = real_tracer
        ok = _req([1, 2, 3], max_tokens=3)
        q.submit(ok)
        assert ok.wait(10)
    finally:
        b.stop()
    assert ok.error is None, ok.error
    assert len(ok.tokens) == 3
    ex.allocator.assert_clean()
    ex.close()


def test_kv_attach_unwinds_forked_blocks_when_tier_restore_raises():
    """Regression (found by GL022): kv_attach forks the HBM-resident
    prefix chain, then extends it from the host tier. When the tier
    itself RAISES mid-restore (a dying host buffer — distinct from
    the injected kvtier.restore fault, which degrades to prefill),
    the already-forked and already-restored blocks must be released
    on the unwind, not stranded: the attach failed, nobody owns
    them."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
    ex = SyntheticKVExecutor(slots=2, vocab=32, block_size=4,
                             num_blocks=32, host_tier_bytes=1 << 20,
                             pipelined=False)
    try:
        _drive(ex, [_req(prompt, max_tokens=4)])
        ex.prefix.evict(99)          # spill the whole chain to host
        assert ex.tier.keys()

        real_checkout = ex.tier.checkout
        calls = {"n": 0}

        def dying_checkout(key, owner):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise RuntimeError("host tier read error")
            return real_checkout(key, owner)

        ex.tier.checkout = dying_checkout
        victim = _req(prompt, max_tokens=4)
        with pytest.raises(RuntimeError, match="host tier read"):
            ex.kv_attach(0, victim)
        assert calls["n"] >= 2       # one block restored, then died
        assert victim.kv_lease is None
        ex.tier.checkout = real_checkout

        # The pool still serves the same prompt normally...
        ok = _req(prompt, max_tokens=4)
        _drive(ex, [ok])
        assert len(ok.tokens) == 4
        # ...and the unwind left NOTHING held: not the forked chain,
        # not the block restored before the failure, not a tier pin.
        ex.prefix.flush()
        ex.allocator.assert_clean()
        ex.tier.assert_clean()
    finally:
        ex.close()


# -- admission control -------------------------------------------------------


def test_kv_oom_sheds_request_with_exact_error():
    """Worst-case pages are reserved at attach: a pool too small for
    prompt+max_tokens sheds THIS request with KV_OOM_ERROR (503 at the
    front door) and the batcher keeps serving the rest."""
    ex = SyntheticKVExecutor(slots=2, num_blocks=4, block_size=4,
                            pipelined=False)
    big = _req(list(np.arange(10) % 3), max_tokens=10)  # needs 5 blocks
    ok = _req([1, 2, 3], max_tokens=3)                  # needs 2
    q = AdmissionQueue(max_depth=4)
    b = ContinuousBatcher(ex, q)
    q.submit(big)
    q.submit(ok)
    b.start()
    try:
        assert big.wait(10) and ok.wait(10)
    finally:
        b.stop()
    assert big.error == KV_OOM_ERROR
    assert ok.error is None and len(ok.tokens) == 3
    ex.allocator.assert_clean()
    ex.close()


def test_queued_deadline_lapse_truncates_kept_token_requeue():
    """Regression: the pop-side deadline shed 503'd requeued KV
    requests that CARRY settled tokens, discarding them — while the
    identical state lapsing a moment earlier inside the supervisor's
    _requeue settles as a truncated 200 (the mid-decode truncation
    contract). Unreachable before ISSUE 7 (requeue always cleared
    tokens); resumable leases keep them, so the queue must apply the
    same disposition. The truncated settle must also release the
    lease through the finish() choke point."""
    from dpu_operator_tpu.serving.api import DEADLINE_QUEUED_ERROR

    a = KVBlockAllocator(num_blocks=4, block_size=2)
    q = AdmissionQueue(max_depth=4)
    req = _req([1, 2, 3], max_tokens=6, deadline_s=0.02)
    req.tokens.extend([7, 8])
    req.kv_lease = KVLease(a, "pool", req.request_id,
                           a.acquire(2, req.request_id), (1, 2, 3), 0)
    q.requeue(req)
    time.sleep(0.03)
    assert q.get_many(4) == []
    assert req.done and req.error is None and req.truncated
    assert req.tokens == [7, 8]
    a.assert_clean()
    # A token-less lapsed request still sheds with the queued 503.
    bare = _req([1, 2, 3], max_tokens=6, deadline_s=0.0)
    q.requeue(bare)
    assert q.get_many(4) == []
    assert bare.error == DEADLINE_QUEUED_ERROR
    # An already-settled request popped later is DROPPED — a second
    # settle would rewrite the response after it was sent.
    settled = _req([1, 2, 3], max_tokens=6, deadline_s=0.0)
    settled.fail("wedged")
    q.requeue(settled)
    assert q.get_many(4) == []
    assert settled.error == "wedged"


def test_pool_requeue_keeps_tokens_only_for_resumable_lease():
    """Unit check on the supervisor's requeue disposition (the chaos
    matrix proves it end-to-end): a resumable lease keeps the decoded
    tokens and rides the queue; without one the retry re-decodes."""
    a = KVBlockAllocator(num_blocks=4, block_size=2)
    q = AdmissionQueue(max_depth=4)
    ex = SyntheticKVExecutor(slots=1, pipelined=False)
    pool = ReplicaPool([ex], q, supervise=False)
    req = _req([1, 2, 3], max_tokens=6)
    req.tokens.extend([7, 8])
    req.kv_lease = KVLease(a, "elsewhere", req.request_id,
                           a.acquire(2, req.request_id), (1, 2, 3), 0)
    pool._requeue(0, [req])
    assert req.tokens == [7, 8] and q.depth() == 1
    plain = _req([1, 2, 3], max_tokens=6)
    plain.tokens.extend([7, 8])
    pool._requeue(0, [plain])
    assert plain.tokens == [] and q.depth() == 2
    req.kv_lease.release()
    a.assert_clean()
    ex.close()


def test_uncollected_prefill_chunk_never_enters_prefix_cache():
    """Regression: a mid-prefill deadline truncation retires a slot
    while its latest chunk is dispatched but UNCOLLECTED; ctx advances
    at plan time, so a ctx-derived cache insert published blocks whose
    KV a failing step never wrote — and match_and_fork would serve
    them as truth to every later same-prefix request (pools and the
    prefix cache deliberately survive reset). The insert must cover
    only collect-confirmed positions."""
    prompt = list(range(1, 9))                     # 2 full blocks
    ex = SyntheticKVExecutor(slots=1, block_size=4, num_blocks=64,
                             prefill_chunk=4, pipelined=False)
    req = _req(prompt, max_tokens=2)
    ex.kv_attach(0, req)
    ex.submit(gen=ex.kv_gen())      # chunk 1 dispatched, NOT collected
    ex.kv_release_slot(0, cache=True)
    assert len(ex.prefix) == 0, "uncollected positions were cached"
    req.finish()
    # Collected prefill caches normally — and a later request hits it.
    req2 = _req(prompt, max_tokens=2)
    ex.kv_attach(0, req2)
    for _ in range(2):
        ex.collect(ex.submit(gen=ex.kv_gen()))
    ex.kv_release_slot(0, cache=True)
    assert len(ex.prefix) == 2
    req2.finish()
    req3 = _req(prompt, max_tokens=2)
    assert ex.kv_attach(0, req3) == 4      # capped at plen-1 blocks
    ex.kv_release_slot(0, cache=True)
    req3.finish()
    ex.prefix.flush()
    ex.allocator.assert_clean()
    ex.close()


# -- satellite: DecodeStep overflow error names step + request ids -----------


@pytest.mark.parametrize("via", ["direct", "executor"])
def test_decode_step_overflow_error_names_step_and_requests(via):
    """Regression (ISSUE 7 satellite): the >slots update rejection
    used to be a bare ValueError — useless against a flight snapshot
    when the seize path races admissions near the limit. It must name
    the step and the admitting request ids, through the executor seam
    too."""
    from dpu_operator_tpu.serving import LocalExecutor

    ex = LocalExecutor(slots=2, S=1, d=8, h=8, E=1, warmup=False)
    rows = [(i, np.zeros(8, np.float32)) for i in range(3)]
    with pytest.raises(ValueError) as ei:
        if via == "direct":
            ex._decode(ex._decode.init_state(), rows, step=7,
                       request_ids=["req-a", "req-b", "req-c"])
        else:
            ex.submit(rows, step=7,
                      request_ids=["req-a", "req-b", "req-c"])
    msg = str(ei.value)
    assert "step 7" in msg and "req-a" in msg and "req-c" in msg
    # Without caller context it still names its own call count.
    with pytest.raises(ValueError, match="step"):
        ex._decode(ex._decode.init_state(), rows)


# -- /metrics exposition -----------------------------------------------------


def test_metrics_exposition_of_kv_series():
    """Satellite: the new counters/gauges appear in a real /metrics
    scrape — prefill/decode token counters, the per-state block gauge,
    and the scrape-time prefix-hit fraction."""
    import urllib.request

    from dpu_operator_tpu.serving import ServingServer

    ex = SyntheticKVExecutor(slots=2, pipelined=True, num_blocks=64)
    srv = ServingServer([ex]).start()
    try:
        import json as _json
        body = _json.dumps({"prompt_tokens": [1, 2, 3, 4, 5, 6, 7, 8,
                                              9],
                            "max_tokens": 4,
                            "deadline_ms": 10000}).encode()
        for _ in range(2):
            urllib.request.urlopen(
                urllib.request.Request(srv.url + "/v1/generate",
                                       data=body), timeout=10).read()
        text = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=5).read().decode()
    finally:
        srv.stop()
    assert "serving_prefill_tokens_total" in text
    assert "serving_decode_tokens_total" in text
    for state in ("used", "free", "shared"):
        assert f'serving_kv_blocks{{state="{state}"}}' in text
    assert "serving_kv_prefix_hit_frac" in text
    # The counters carry real values (9 prompt tokens prefilled twice
    # minus the second run's cache hit; 4 decode tokens each).
    pre = [l for l in text.splitlines()
           if l.startswith("serving_prefill_tokens_total")]
    dec = [l for l in text.splitlines()
           if l.startswith("serving_decode_tokens_total")]
    assert float(pre[0].split()[-1]) >= 9
    assert float(dec[0].split()[-1]) >= 8
    ex.allocator.assert_clean()
    ex.close()
