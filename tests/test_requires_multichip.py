"""The multi-chip debt ledger (VERDICT r4 Next #8).

Everything here runs the pallas RDMA collectives on REAL multi-chip ICI
— no interpret mode, no virtual devices. This environment has ONE chip,
so these tests skip with an honest reason; on the first multi-chip
environment they are the FIRST thing to run (`pytest -m
requires_multichip`), because interpret-mode semaphore/credit semantics
are not Mosaic hardware semantics and every claim the README makes
about the kernels' multi-chip behavior is bounded by exactly this
suite's status.

What interpret mode + AOT lowering + single-chip runs HAVE shown (the
per-module test files): protocol correctness against XLA, Mosaic
compilability for a TPU target, and single-device execution. What only
this suite can show: real RDMA timing/ordering, semaphore waits against
actual DMA completion, credit backpressure under real link latency.

Each test runs in a subprocess with the default (axon/TPU) platform —
the in-process test session is pinned to the virtual CPU mesh by
conftest and must stay that way.
"""

import functools
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpu_plausible() -> bool:
    """Cheap pre-probe before committing a subprocess to TPU device
    discovery: a local chip shows up as /dev/accel* or /dev/vfio, and
    the axon tunnel serves 127.0.0.1:{8082..8117}. Where NONE of those
    exist, jax.devices() can only block until the 120 s probe timeout —
    pure wall-time (measured: the single biggest line item in the
    suite, docs/ci.md) — so answer 'no chips' immediately. The whole
    documented port range is scanned (not a sample): a closed local
    port refuses in microseconds, so even the all-down case costs
    nothing next to the probe it guards."""
    import glob
    import socket

    if glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*"):
        return True
    for port in range(8082, 8118):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1.0):
                return True
        except OSError:
            continue
    return False


@functools.lru_cache(maxsize=1)
def _real_tpu_chip_count() -> int:
    """Count REAL TPU chips in a subprocess (the in-process jax is
    pinned to CPU; and when the axon tunnel is down, an in-process
    devices() call can block forever — the subprocess carries the
    timeout). Cached and called LAZILY from inside the tests, never at
    collection time — a down tunnel must not stall every unrelated
    pytest run for the probe timeout."""
    if not _tpu_plausible():
        return 0
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; ds = jax.devices(); "
             "print(sum(1 for d in ds if d.platform != 'cpu'))"],
            capture_output=True, text=True, timeout=120,
            env={k: v for k, v in os.environ.items()
                 if k not in ("JAX_PLATFORMS", "XLA_FLAGS")},
        )
        return int(r.stdout.strip().splitlines()[-1]) if r.returncode == 0 else 0
    except Exception:
        return 0


multichip = pytest.mark.requires_multichip


def _skip_unless_multichip() -> None:
    chips = _real_tpu_chip_count()
    if chips < 2:
        pytest.skip(
            f"needs >=2 REAL TPU chips for live-ICI pallas collectives, "
            f"have {chips}; interpret-mode equivalence is already "
            f"covered by the per-module tests")


def _run_on_chips(body: str) -> dict:
    """Run `body` (prints one JSON line) on the real chips."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-c", body], capture_output=True, text=True,
        timeout=600, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-3000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


_PRELUDE = """
import json
import jax, numpy as np
devs = [d for d in jax.devices() if d.platform != "cpu"]
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(devs).reshape(1, len(devs), 1), ("dp", "sp", "tp"))
n = len(devs)
"""


@multichip
def test_pallas_ring_collectives_live_ici():
    """all-gather / reduce-scatter / all-to-all: pallas RDMA == XLA on
    real links."""
    _skip_unless_multichip()
    out = _run_on_chips(_PRELUDE + """
from dpu_operator_tpu.parallel.ring_probe import (
    make_all_to_all, make_ring_all_gather, make_ring_reduce_scatter)
import jax.numpy as jnp
x = jax.device_put(jnp.arange(8 * n * 128, dtype=jnp.float32).reshape(-1, 128),
                   NamedSharding(mesh, P("sp", None)))
ok = True
for mk in (make_ring_all_gather, make_ring_reduce_scatter, make_all_to_all):
    a = np.asarray(mk(mesh, "sp", use_pallas=True)(x))
    b = np.asarray(mk(mesh, "sp", use_pallas=False)(x))
    ok = ok and np.allclose(a, b, rtol=1e-5, atol=1e-5)
print(json.dumps({"ok": bool(ok)}))
""")
    assert out["ok"]


@multichip
def test_pallas_ring_attention_live_ici():
    _skip_unless_multichip()
    out = _run_on_chips(_PRELUDE + """
from dpu_operator_tpu.parallel.ring_attention import make_ring_attention
import jax.numpy as jnp
S = 8 * n
sh = NamedSharding(mesh, P("sp", None))
q, k, v = (jax.device_put(jax.random.normal(jax.random.PRNGKey(i), (S, 128)), sh)
           for i in range(3))
a = np.asarray(make_ring_attention(mesh, "sp", causal=True, use_pallas=True)(q, k, v))
b = np.asarray(make_ring_attention(mesh, "sp", causal=True, use_pallas=False)(q, k, v))
print(json.dumps({"ok": bool(np.allclose(a, b, rtol=2e-5, atol=2e-5))}))
""")
    assert out["ok"]


@multichip
def test_pallas_ulysses_attention_live_ici():
    _skip_unless_multichip()
    out = _run_on_chips(_PRELUDE + """
from dpu_operator_tpu.parallel.ulysses_attention import make_ulysses_attention
import jax.numpy as jnp
S, H = 8 * n, 2 * n
sh = NamedSharding(mesh, P("sp", None, None))
q, k, v = (jax.device_put(jax.random.normal(jax.random.PRNGKey(i), (S, H, 128)), sh)
           for i in range(3))
a = np.asarray(make_ulysses_attention(mesh, "sp", causal=True, use_pallas=True)(q, k, v))
b = np.asarray(make_ulysses_attention(mesh, "sp", causal=True, use_pallas=False)(q, k, v))
print(json.dumps({"ok": bool(np.allclose(a, b, rtol=2e-5, atol=2e-5))}))
""")
    assert out["ok"]


@multichip
def test_pallas_collective_matmul_live_ici():
    _skip_unless_multichip()
    out = _run_on_chips(_PRELUDE + """
from dpu_operator_tpu.parallel.collective_matmul import (
    make_allgather_matmul, make_matmul_reduce_scatter)
import jax.numpy as jnp
tp_mesh = Mesh(np.array(devs).reshape(1, 1, len(devs)), ("dp", "sp", "tp"))
tp = len(devs)
x = jax.device_put(jnp.arange(2 * tp * 128, dtype=jnp.float32).reshape(-1, 128) / 100.0,
                   NamedSharding(tp_mesh, P("tp", None)))
w = jax.device_put(jnp.arange(128 * 4 * tp, dtype=jnp.float32).reshape(128, -1) / 100.0,
                   NamedSharding(tp_mesh, P(None, "tp")))
a = np.asarray(make_allgather_matmul(tp_mesh, "tp", use_pallas=True)(x, w))
b = np.asarray(make_allgather_matmul(tp_mesh, "tp", use_pallas=False)(x, w))
x2 = jax.device_put(jnp.arange(2 * tp * 4 * tp, dtype=jnp.float32)
                    .reshape(2 * tp, -1) / 100.0,
                    NamedSharding(tp_mesh, P(None, "tp")))
w2 = jax.device_put(jnp.arange(4 * tp * 128, dtype=jnp.float32)
                    .reshape(-1, 128) / 100.0,
                    NamedSharding(tp_mesh, P("tp", None)))
c = np.asarray(make_matmul_reduce_scatter(tp_mesh, "tp", use_pallas=True)(x2, w2))
d = np.asarray(make_matmul_reduce_scatter(tp_mesh, "tp", use_pallas=False)(x2, w2))
print(json.dumps({"ok": bool(np.allclose(a, b, rtol=1e-4, atol=1e-4)
                             and np.allclose(c, d, rtol=1e-4, atol=1e-4))}))
""")
    assert out["ok"]
