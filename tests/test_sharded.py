"""Fabric-sharded serving replicas (ISSUE 8).

The FabricExecutor coordinator + shard plane, proven tier-1 on the
SyntheticShardSet (thread shards, controlled step/collective cost —
no multi-process rendezvous on CI boxes):

  * token-stream equivalence: a sharded replica decodes byte-identical
    streams to the single-host executor it shards — vs
    SyntheticExecutor for the jax-free double, vs the REAL jitted
    LocalExecutor for the tensor-parallel model slice, in both sync
    and pipelined modes (the ISSUE 8 acceptance);
  * the pipelined overlap contract carries over: submit broadcasts
    and returns, the shard plane is the "device";
  * bounded-time failure: a hung peer surfaces as a typed error
    inside the collective deadline, a reset aborts outstanding steps
    (the GL010 runtime contract);
  * the new /metrics series (`serving_shard_collective_seconds`,
    `serving_shard_step_skew_seconds`, `serving_pool_replicas`'s
    `sharded` dimension) and the fabric_worker stdout-protocol
    hardening the shard worker inherits.

The REAL multi-process rendezvous (shard_worker subprocesses reducing
over fabric_collectives, ring order from topology.ring_order) rides
the slow lane — tier-1 stays CPU-cheap (wall budget asserted in-lane,
docs/ci.md)."""

import json
import subprocess
import sys
import time

import numpy as np
import pytest

from dpu_operator_tpu.serving import (AdmissionQueue, ContinuousBatcher,
                                      FabricExecutor, GenerateRequest,
                                      LocalExecutor, ReplicaPool,
                                      SyntheticExecutor,
                                      SyntheticShardSet, encode_prompt)
from dpu_operator_tpu.serving.sharded import (ShardAborted,
                                              ShardCollectiveStall,
                                              ShardError)
from dpu_operator_tpu.utils.metrics import Registry

MODEL = dict(S=1, d=8, h=8, E=1)

# Lane clock starts when the FIRST test in this module RUNS — not at
# import (pytest imports every module during collection; an
# import-time stamp would charge this lane for every earlier suite).
# Slow-marked tests (the subprocess rendezvous smokes) are exempt by
# SUBTRACTION, not by assumption: a plain `pytest tests/test_sharded.py`
# runs them too, and ~30 s of subprocess jax compiles must not bill
# the tier-1 budget.
_LANE_T0: list = []
_SLOW_SPENT = [0.0]


@pytest.fixture(autouse=True)
def _lane_clock(request):
    if not _LANE_T0:
        _LANE_T0.append(time.perf_counter())
    if request.node.get_closest_marker("slow") is None:
        yield
    else:
        t0 = time.perf_counter()
        yield
        _SLOW_SPENT[0] += time.perf_counter() - t0


def _real_params(**model):
    from dpu_operator_tpu.parallel.train_step import init_params

    return {k: np.asarray(v, np.float32)
            for k, v in init_params(seed=0, **model).items()}


def _trace_reqs(n, d, toks):
    return [GenerateRequest(prompt_vec=encode_prompt(f"sh-{i}", d),
                            max_tokens=toks,
                            deadline=time.monotonic() + 600.0)
            for i in range(n)]


def _drive(ex, reqs):
    q = AdmissionQueue(max_depth=len(reqs) + 1)
    b = ContinuousBatcher(ex, q)
    for r in reqs:
        q.submit(r)
    b.start()
    try:
        for r in reqs:
            assert r.wait(timeout=60), "request lost"
    finally:
        b.stop()
        ex.close()


# -- satellite: the shard worker's stdout protocol ----------------------------


def test_fabric_worker_stdout_protocol_survives_noisy_logging():
    """Regression (ISSUE 8 satellite): fabric_worker prints exactly
    one JSON object on stdout as its protocol, but library logging
    (an absl/basicConfig handler bound to stdout) and stray prints
    used to interleave into the stream and corrupt the parse.
    protocol_stdout() makes the fix structural: everything after the
    guard lands on stderr, the protocol line alone on the real
    stdout. The sharded shard_worker inherits the same guard."""
    snippet = (
        "import json, logging, sys\n"
        # A hostile pre-existing config: root handler bound to stdout.
        "logging.basicConfig(stream=sys.stdout)\n"
        "from dpu_operator_tpu.parallel.fabric_worker import "
        "protocol_stdout\n"
        "out = protocol_stdout()\n"
        "logging.getLogger('noisy').warning('rendezvous retry %d', 3)\n"
        "print('stray diagnostic print')\n"
        "print(json.dumps({'ok': True}), file=out, flush=True)\n")
    r = subprocess.run([sys.executable, "-c", snippet],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    lines = [l for l in r.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, (
        f"stdout must carry exactly the one protocol object, got "
        f"{r.stdout!r}")
    assert json.loads(lines[0]) == {"ok": True}
    assert "rendezvous retry 3" in r.stderr
    assert "stray diagnostic print" in r.stderr


def test_protocol_recv_deadline_covers_whole_frame():
    """Regression (review catch): recv_msg's timeout is a deadline
    over the WHOLE frame, not per recv syscall — a sick peer dripping
    one byte per near-timeout interval must not stretch one receive
    to timeout x frame bytes. The dripped header below keeps every
    individual byte inside the 0.4 s window; only a frame-level
    deadline fires."""
    import socket as _socket
    import threading

    from dpu_operator_tpu.serving.sharded.protocol import recv_msg

    a, b = _socket.socketpair()
    try:
        def drip():
            for _ in range(6):
                time.sleep(0.15)
                try:
                    b.send(b"\x00")
                except OSError:
                    return

        t = threading.Thread(target=drip, daemon=True)
        t.start()
        t0 = time.perf_counter()
        with pytest.raises(_socket.timeout):
            recv_msg(a, timeout=0.4)
        assert time.perf_counter() - t0 < 1.0
        t.join(timeout=5)
    finally:
        a.close()
        b.close()


def test_protocol_multipart_zero_copy_payload():
    """send_msg takes buffer-protocol parts (ISSUE 9 zero-copy path):
    numpy arrays and bytes interleave into ONE frame whose payload is
    their concatenation on the receiving side — and an empty array
    part frames as zero bytes instead of tripping memoryview.cast."""
    import socket as _socket

    from dpu_operator_tpu.serving.sharded.protocol import (recv_msg,
                                                           send_msg)

    a, b = _socket.socketpair()
    try:
        toks = np.arange(3, dtype=np.int32)
        state = np.full((2, 2), 7.0, np.float32)
        send_msg(a, {"op": "tokens", "step": 9}, toks,
                 np.empty(0, np.float32), state)
        msg, payload = recv_msg(b, timeout=5.0)
        assert msg == {"op": "tokens", "step": 9}
        assert payload == toks.tobytes() + state.tobytes()
        send_msg(a, {"op": "ack"})  # no parts at all
        msg2, payload2 = recv_msg(b, timeout=5.0)
        assert msg2 == {"op": "ack"} and payload2 == b""
    finally:
        a.close()
        b.close()


# -- token-stream equivalence (the acceptance contract) -----------------------


@pytest.mark.parametrize("mode", ["sync", "pipelined"])
def test_sharded_token_equivalence_synthetic_double(mode):
    """FabricExecutor over 3 shard threads of the seeded double
    decodes the SAME streams as the single SyntheticExecutor it
    shards — the per-rank partials allreduce (rank-ordered sum) to
    the full product; argmax tolerates the fp-order delta. More
    requests than slots so slot hand-offs are exercised."""
    streams = {}
    for kind in ("local", "sharded"):
        if kind == "local":
            ex = SyntheticExecutor(slots=4, d=16, seed=3,
                                   pipelined=(mode == "pipelined"))
        else:
            ex = FabricExecutor(
                SyntheticShardSet(world=3, slots=4, d=16, seed=3),
                mode=mode)
        reqs = _trace_reqs(10, 16, 5)
        _drive(ex, reqs)
        streams[kind] = [(r.error, list(r.tokens)) for r in reqs]
    assert all(e is None for e, _ in streams["sharded"])
    assert streams["local"] == streams["sharded"]


@pytest.mark.parametrize("mode", ["sync", "pipelined"])
def test_sharded_token_equivalence_vs_local_jitted(mode):
    """ISSUE 8 acceptance (tier-1 half): a FabricExecutor replica
    whose shards hold tensor-parallel slices of the REAL train_step
    params produces byte-identical token streams to the jitted
    LocalExecutor on the same params and request trace — the Megatron
    column/row split is exact, and every shard's post-reduce state
    stays replicated. (The real-jitted-shard half of this contract
    rides the slow lane's subprocess rendezvous below.)"""
    params = _real_params(**MODEL)
    streams = {}
    for kind in ("local", "sharded"):
        if kind == "local":
            ex = LocalExecutor(slots=4, mode=mode, seed=0, **MODEL)
        else:
            ex = FabricExecutor(
                SyntheticShardSet(world=2, slots=4, params=params),
                mode=mode)
        reqs = _trace_reqs(8, MODEL["d"], 5)
        _drive(ex, reqs)
        streams[kind] = [(r.error, list(r.tokens)) for r in reqs]
    assert all(e is None for e, _ in streams["sharded"])
    assert streams["local"] == streams["sharded"]


def test_tp_slice_multistage_matches_world1():
    """The stage LOOP of the tensor-parallel slice (S > 1: each
    stage's partial→reduce→finish feeds the next) decodes identically
    at world=3 and world=1 on the same stage-stacked params — the
    Megatron split must compose across stages, not just within one."""
    params = _real_params(S=2, d=8, h=8, E=1)
    streams = {}
    for world in (1, 3):
        ex = FabricExecutor(
            SyntheticShardSet(world=world, slots=2, params=params),
            mode="sync")
        try:
            ex.reset()
            x = np.stack([encode_prompt(f"ms-{i}", 8)
                          for i in range(2)]).astype(np.float32)
            toks = []
            for _ in range(4):
                x = ex.step(x)
                toks.append(np.argmax(x, axis=1).tolist())
            streams[world] = toks
        finally:
            ex.close()
    assert streams[1] == streams[3]


# -- the pipelined overlap contract -------------------------------------------


def test_sharded_submit_overlaps_host_work():
    """submit() broadcasts and returns while the shard plane runs the
    step: K pipelined steps with device cost D and host work H cost
    ≈ K·max(D, H), never K·(D+H) — same contract as the
    SyntheticExecutor worker thread, now across a shard SET."""
    D = H = 0.03
    K = 8
    ex = FabricExecutor(
        SyntheticShardSet(world=2, slots=2, d=8, step_time_s=D))
    try:
        ex.reset()
        h_prev = None
        t0 = time.perf_counter()
        for _ in range(K):
            h = ex.submit([])
            time.sleep(H)  # scheduler-bookkeeping stand-in
            if h_prev is not None:
                ex.collect(h_prev)
            h_prev = h
        ex.collect(h_prev)
        wall = time.perf_counter() - t0
    finally:
        ex.close()
    assert wall < 0.8 * K * (D + H), wall
    assert wall >= K * max(D, H) - 0.01, wall


# -- bounded-time failure (the GL010 runtime contract) ------------------------


def test_hung_peer_surfaces_inside_collective_deadline():
    """One shard hangs past the collective deadline: its PEERS raise
    ShardCollectiveStall in bounded time and collect() fails typed —
    never an unbounded block. (Under a supervised pool the watchdog
    sees the wedge first; this is the executor-level floor.)"""
    from dpu_operator_tpu import faults

    with faults.injected() as plan:
        plan.inject("stall1.step", hang_s=5.0, at_calls=[1])
        ex = FabricExecutor(
            SyntheticShardSet(world=2, slots=2, d=8,
                              collective_timeout_s=0.3,
                              fault_site="stall"),
            step_timeout_s=2.0)
        try:
            ex.reset()
            t0 = time.perf_counter()
            with pytest.raises(ShardError):
                ex.collect(ex.submit([]))
            assert time.perf_counter() - t0 < 2.5
        finally:
            ex.close()


def test_reset_aborts_outstanding_steps_and_respawns():
    """reset() is the re-rendezvous: outstanding handles fail with
    ShardAborted (the old batcher's collect must not hang), stale
    shard threads are abandoned, fresh ones spawn with zeroed state,
    and the ledger reads clean."""
    shards = SyntheticShardSet(world=2, slots=2, d=8,
                               step_time_s=0.2)
    ex = FabricExecutor(shards)
    try:
        ex.reset()
        h = ex.submit([(0, np.ones(8, np.float32))])
        ex.reset()  # mid-step: the 0.2 s step is still running
        with pytest.raises(ShardAborted):
            ex.collect(h)
        assert shards.outstanding() == 0
        # The respawned generation serves cleanly from zeroed state.
        tokens = ex.collect(ex.submit([]))
        assert tokens.shape == (2,)
        assert shards.live_shards() == 2
    finally:
        ex.close()


def test_shard_step_error_lands_typed_in_collect():
    from dpu_operator_tpu import faults
    from dpu_operator_tpu.serving.sharded import ShardStepError

    with faults.injected() as plan:
        plan.inject("dead0.step", exc=RuntimeError("chip fell off"),
                    at_calls=[2])
        ex = FabricExecutor(
            SyntheticShardSet(world=2, slots=2, d=8,
                              fault_site="dead"),
            step_timeout_s=2.0)
        try:
            ex.reset()
            ex.collect(ex.submit([]))  # call 1: clean
            with pytest.raises(ShardStepError) as ei:
                ex.collect(ex.submit([]))
            assert ei.value.rank == 0
        finally:
            ex.close()


# -- metrics (ISSUE 8 satellite) ----------------------------------------------


def test_shard_metrics_exposition():
    """serving_shard_collective_seconds (histogram) and
    serving_shard_step_skew_seconds appear with the {replica, codec}
    labels (ISSUE 9: a quantized replica's latencies must never
    aggregate with an fp32 one's), and the skew series MOVES when one
    shard is slower than the other (per-rank step_time_s)."""
    reg = Registry()
    ex = FabricExecutor(
        SyntheticShardSet(world=2, slots=2, d=8,
                          step_time_s=[0.0, 0.03],
                          collective_time_s=0.005),
        registry=reg, name="shardtest")
    try:
        ex.reset()
        for _ in range(3):
            ex.collect(ex.submit([]))
    finally:
        ex.close()
    text = reg.render()
    assert 'serving_shard_collective_seconds_bucket' in text
    assert 'replica="shardtest"' in text
    assert 'codec="fp32"' in text
    labels = {"replica": "shardtest", "codec": "fp32"}
    # The slow shard's 30 ms compute gap dominates the skew median.
    skew = reg.quantile("serving_shard_step_skew_seconds", 0.5, labels)
    assert skew is not None and skew >= 0.01, skew
    coll = reg.quantile("serving_shard_collective_seconds", 0.5, labels)
    assert coll is not None and coll >= 0.005, coll


def test_shard_metrics_codec_label_tracks_transport():
    """A quantized shard set stamps its codec on the shard series: the
    int8 replica's observations land on codec="int8", never the fp32
    series."""
    reg = Registry()
    ex = FabricExecutor(
        SyntheticShardSet(world=2, slots=2, d=8, codec="int8",
                          collective_time_s=0.002),
        registry=reg, name="qshard")
    try:
        ex.reset()
        for _ in range(2):
            ex.collect(ex.submit([]))
    finally:
        ex.close()
    assert 'codec="int8"' in reg.render()
    coll = reg.quantile("serving_shard_collective_seconds", 0.5,
                        {"replica": "qshard", "codec": "int8"})
    assert coll is not None and coll >= 0.002, coll
    assert reg.quantile("serving_shard_collective_seconds", 0.5,
                        {"replica": "qshard", "codec": "fp32"}) is None


def test_pool_publishes_sharded_replica_dimension():
    """serving_pool_replicas carries the `sharded` and `role` labels:
    a mixed pool reports its fabric-sharded and single-host capacity
    separately, under its serving role (unified here; prefill|decode
    in the disagg plane — tests/test_disagg.py covers those)."""
    reg = Registry()
    q = AdmissionQueue(max_depth=4)
    ex_sh = FabricExecutor(SyntheticShardSet(world=2, slots=2, d=8))
    ex_lo = SyntheticExecutor(slots=2, d=8, pipelined=True)
    pool = ReplicaPool([ex_sh, ex_lo], q, registry=reg, poll_s=0.005)
    pool.start()
    try:
        assert reg.gauge_value(
            "serving_pool_replicas",
            {"state": "live", "sharded": "true",
             "role": "unified"}) == 1.0
        assert reg.gauge_value(
            "serving_pool_replicas",
            {"state": "live", "sharded": "false",
             "role": "unified"}) == 1.0
        assert ex_sh._registry is reg  # bind_registry hook ran
    finally:
        pool.stop()


def test_pool_registry_binds_into_shard_series():
    """The pool's registry rides bind_registry into the
    FabricExecutor: one request served by a pool-owned sharded
    replica is enough for /metrics to carry the shard series — no
    extra wiring at the server layer."""
    reg = Registry()
    q = AdmissionQueue(max_depth=4)
    ex_sh = FabricExecutor(SyntheticShardSet(world=2, slots=2, d=8))
    pool = ReplicaPool([ex_sh], q, registry=reg, poll_s=0.005)
    pool.start()
    try:
        r = GenerateRequest(prompt_vec=encode_prompt("m", 8),
                            max_tokens=2,
                            deadline=time.monotonic() + 30.0)
        q.submit(r)
        assert r.wait(timeout=10)
    finally:
        pool.stop()
    text = reg.render()
    assert "serving_shard_collective_seconds" in text
    assert "serving_shard_step_skew_seconds" in text


def test_shard_worker_survives_idle_gap():
    """Regression (review catch): the worker used to EXIT on
    idle-timeout silence, so a drained serving replica self-destructed
    after every lull and the next request paid a spurious replica
    failure + full re-rendezvous. Idle is not death: the wait just
    re-arms; only a CLOSED control socket (dead coordinator) ends the
    worker. One world=1 worker, idle timeout far below the gap."""
    import socket as _socket

    from dpu_operator_tpu.serving.sharded.protocol import (recv_msg,
                                                           send_msg)

    lst = _socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    lst.settimeout(30)
    port = lst.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "dpu_operator_tpu.serving.sharded.shard_worker",
         "--rank", "0", "--world", "1", "--slots", "2", "--d", "4",
         "--coordinator", f"127.0.0.1:{port}",
         "--peers", "127.0.0.1:1",
         "--idle-timeout", "0.2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        c, _ = lst.accept()
        msg, _ = recv_msg(c, timeout=30)
        assert msg == {"op": "hello", "rank": 0}
        time.sleep(1.0)  # five idle timeouts deep
        assert proc.poll() is None, "worker exited during an idle gap"
        rows = np.ones((1, 4), np.float32)
        send_msg(c, {"op": "step", "step": 1, "slots": [0],
                     "want_state": False}, rows.tobytes())
        reply, payload = recv_msg(c, timeout=30)
        assert reply["op"] == "tokens" and reply["step"] == 1
        assert len(payload) == 2 * 4  # [slots] int32 segment
        send_msg(c, {"op": "close"})
        c.close()
        assert proc.wait(timeout=30) == 0
        out = json.loads(proc.stdout.read().strip().splitlines()[-1])
        assert out["ok"] and out["steps"] == 1
    finally:
        lst.close()
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)


def test_shard_worker_jit_compiles_and_matches_numpy():
    """Regression (review catch): jax.jit over the slice's numpy
    ufuncs raised TracerArrayConversionError at warmup, so --jit
    silently fell back to numpy forever — the jitted shard path was
    dead code and the rendezvous smoke passed vacuously. The slice
    math now traces through `self.xp`; this asserts the jit REALLY
    compiles (jitted flag true) and matches the numpy math per
    stage."""
    from dpu_operator_tpu.serving.sharded.shard_math import TpShardSlice
    from dpu_operator_tpu.serving.sharded.shard_worker import _maybe_jit

    params = _real_params(S=2, d=8, h=8, E=1)
    sl = TpShardSlice(params, 0, 2)
    pf, ff, jitted = _maybe_jit(sl, True, slots=4)
    assert jitted, "jit fell back to numpy; jitted shard path is dead"
    ref = TpShardSlice(params, 0, 2)
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    for s in range(sl.stages):
        d_ref = ref.partial(x, s)
        np.testing.assert_allclose(pf(x, s), d_ref,
                                   rtol=1e-5, atol=1e-6)
        out = ff(x, d_ref, s)
        np.testing.assert_allclose(out, ref.finish(x, d_ref, s),
                                   rtol=1e-5, atol=1e-6)
        # finish's output IS the next decode state the worker
        # scatters updates into: np.asarray over a jax array is a
        # read-only view, which crashed every jitted step that
        # carried an admit (regression).
        assert out.flags.writeable


def test_procset_ring_ports_are_distinct():
    """Regression (review catch): sequential bind-then-close port
    allocation can hand the same ephemeral port out twice; the ring
    addresses are allocated from simultaneously-held binds so
    ring_order can never see a duplicate from our own allocator."""
    from dpu_operator_tpu.serving.sharded.procset import _distinct_ports

    ports = _distinct_ports(16)
    assert len(set(ports)) == 16


# -- compute/communication overlap + quantized collectives (ISSUE 9) ----------


@pytest.mark.parametrize("mode", ["sync", "pipelined"])
def test_overlap_token_equivalence_synthetic_double(mode):
    """forward_overlapped's double-buffered block schedule decodes the
    SAME streams as the single-host SyntheticExecutor: row-splitting
    never reorders a row's rank-ordered sum, so overlap is a latency
    schedule, not a numerics change."""
    streams = {}
    for kind in ("local", "sharded"):
        if kind == "local":
            ex = SyntheticExecutor(slots=4, d=16, seed=3,
                                   pipelined=(mode == "pipelined"))
        else:
            ex = FabricExecutor(
                SyntheticShardSet(world=3, slots=4, d=16, seed=3,
                                  overlap=True),
                mode=mode)
        reqs = _trace_reqs(10, 16, 5)
        _drive(ex, reqs)
        streams[kind] = [(r.error, list(r.tokens)) for r in reqs]
    assert all(e is None for e, _ in streams["sharded"])
    assert streams["local"] == streams["sharded"]


def test_overlap_token_equivalence_vs_local_jitted_multistage():
    """Overlap across the STAGE boundary (S > 1: stage k's in-flight
    reduces overlap stage k+1's partials) still decodes byte-identical
    streams to the jitted LocalExecutor on the same real params —
    quantization OFF, so the acceptance byte-identity contract holds
    with overlap enabled."""
    model = dict(S=2, d=8, h=8, E=1)
    params = _real_params(**model)
    streams = {}
    for kind in ("local", "sharded"):
        if kind == "local":
            ex = LocalExecutor(slots=4, mode="pipelined", seed=0,
                               **model)
        else:
            ex = FabricExecutor(
                SyntheticShardSet(world=2, slots=4, params=params,
                                  overlap=True, overlap_blocks=2),
                mode="pipelined")
        reqs = _trace_reqs(8, model["d"], 5)
        _drive(ex, reqs)
        streams[kind] = [(r.error, list(r.tokens)) for r in reqs]
    assert all(e is None for e, _ in streams["sharded"])
    assert streams["local"] == streams["sharded"]


def test_overlap_blocks_exceeding_slots_degrades_to_per_row():
    """blocks > slots: empty row blocks drop out and the schedule
    degrades to per-row pipelining — same tokens, no empty reduce."""
    from dpu_operator_tpu.serving.sharded.shard_math import \
        DoubleShardSlice

    sl = DoubleShardSlice(8, seed=1, rank=0, world=1)
    x = np.random.RandomState(2).randn(3, 8).astype(np.float32)
    calls = []

    def submit(part, stage, block):
        calls.append((stage, block, part.shape[0]))
        return part

    x_ref, tok_ref = sl.forward(x.copy(), lambda p, s: p)
    x_ov, tok_ov = sl.forward_overlapped(x.copy(), submit,
                                         lambda t: t, blocks=8)
    assert tok_ref.tolist() == tok_ov.tolist()
    assert np.allclose(x_ref, x_ov)
    assert [c[2] for c in calls] == [1, 1, 1]  # one row per block


def test_quantized_sharded_streams_deterministic_and_isolated():
    """int8-quantized sharded decode is DETERMINISTIC (two identical
    runs produce identical streams — the codec rounds the same way
    every time) while quantization stays opt-in: the fp32 set on the
    same trace still matches the unsharded executor byte-for-byte
    (proven by the equivalence tests above — never silently on)."""
    def run():
        ex = FabricExecutor(
            SyntheticShardSet(world=3, slots=4, d=16, seed=3,
                              codec="int8", overlap=True),
            mode="pipelined")
        reqs = _trace_reqs(8, 16, 5)
        _drive(ex, reqs)
        assert all(r.error is None for r in reqs)
        return [list(r.tokens) for r in reqs]

    assert run() == run()


def test_overlap_lowers_blocked_collective_wait():
    """The overlap contract at the executor seam: with compute to hide
    behind (step cost ≈ collective cost), the overlapped schedule's
    reported collective_s — the time the compute thread actually
    BLOCKED — is measurably below the serialized schedule's, which
    pays compute + full wire serially. Costs are chosen an order of
    magnitude above scheduler noise."""
    def median_coll(overlap):
        ex = FabricExecutor(
            SyntheticShardSet(world=2, slots=4, d=16, seed=7,
                              step_time_s=0.04,
                              collective_time_s=0.04,
                              overlap=overlap))
        try:
            ex.reset()
            samples = []
            for _ in range(7):
                h = ex.submit([])
                # Reach through the seam for the raw StepOutput: the
                # executor's pipelined handle wraps the backend's
                # (trace context rides along since ISSUE 11).
                out = ex.shards.collect(h.handle, timeout=10.0)
                ex._finish_step(h, out)
                samples.append(max(out.collective_s))
            return sorted(samples)[len(samples) // 2]
        finally:
            ex.close()

    off, on = median_coll(False), median_coll(True)
    # Serialized: ~40 ms blocked at the board. Overlapped: each 20 ms
    # block reduce hides behind the other block's 20 ms compute, so
    # the blocked wait collapses toward the un-hideable tail (~20 ms
    # ideal — the margin below leaves ~2x headroom for a busy box).
    assert on < 0.85 * off, (on, off)


def test_mesh_stage_fn_matches_slice_and_uses_collective_matmul():
    """The jax-shard form of the overlapped stage: make_mesh_stage_fn
    (collective_matmul.make_allgather_matmul inside the w1 matmul, a
    psum closing w2) decodes the same tokens as TpShardSlice at
    world=1 on the same stage-stacked params, overlap on and off."""
    import jax
    from jax.sharding import Mesh

    from dpu_operator_tpu.serving.sharded.shard_math import (
        TpShardSlice, make_mesh_stage_fn)

    model = dict(S=2, d=8, h=8, E=1)
    params = _real_params(**model)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    ref = TpShardSlice(params, 0, 1)
    x0 = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    for overlap in (True, False):
        step = make_mesh_stage_fn(mesh, params, overlap=overlap)
        x_ref, x_mesh = x0.copy(), x0.copy()
        for _ in range(3):
            x_ref, tok_ref = ref.forward(x_ref, lambda p, s: p)
            x_mesh, tok_mesh = step(x_mesh)
            assert tok_ref.tolist() == tok_mesh.tolist()
            np.testing.assert_allclose(x_ref, x_mesh, rtol=1e-4,
                                       atol=1e-5)
    with pytest.raises(ValueError, match="divide"):
        step(np.zeros((3, 8), np.float32))


def test_procset_codec_and_overlap_over_real_workers():
    """ShardProcessSet threads the codec/overlap knobs to real
    shard_worker subprocesses: an int8+overlap set (numpy math — no
    jax import cost in tier-1) serves steps, reports collective
    timings, and tears down with a clean ledger."""
    from dpu_operator_tpu.serving import ShardProcessSet

    procs = ShardProcessSet(world=2, slots=4, d=8, jit=False,
                            codec="int8", overlap=True,
                            spawn_timeout_s=60.0)
    assert procs.codec_name == "int8"
    try:
        procs.reset()
        out = procs.collect(
            procs.submit(1, [(0, np.ones(8, np.float32))]),
            timeout=30.0)
        assert out.tokens.shape == (4,)
        out2 = procs.collect(procs.submit(2, [], want_state=True),
                             timeout=30.0)
        assert out2.state is not None and out2.state.shape == (4, 8)
    finally:
        procs.close()
    assert procs.outstanding() == 0


# -- the real multi-process rendezvous (multiworker/slow lane) ----------------


@pytest.mark.slow
def test_procset_stale_generation_collect_cannot_kill_restart():
    """Regression (review catch): a collect against a handle from a
    torn-down generation fails fast with ShardAborted and must NOT
    tear down the freshly respawned incarnation — the supervisor's
    restart path would otherwise be killed by the wedged batcher
    thread it just abandoned. A reset with an outstanding step goes
    straight to kill+respawn (the positional control stream holds
    unread frames; no polite path exists)."""
    from dpu_operator_tpu.serving import ShardProcessSet
    from dpu_operator_tpu.serving.sharded import ShardAborted

    procs = ShardProcessSet(world=2, slots=2, d=8, jit=False,
                            spawn_timeout_s=60.0)
    try:
        procs.reset()  # first spawn
        h_old = procs.submit(1, [])
        procs.reset()  # outstanding step → full re-rendezvous
        assert procs.respawns == 1
        with pytest.raises(ShardAborted):
            procs.collect(h_old, timeout=5.0)
        # The restarted generation is intact and serves.
        out = procs.collect(procs.submit(2, []), timeout=60.0)
        assert out.tokens.shape == (2,)
    finally:
        procs.close()
    assert procs.outstanding() == 0


@pytest.mark.slow
def test_real_shard_worker_rendezvous_token_equivalence():
    """The multiworker-lane half of the ISSUE 8 acceptance: REAL
    shard_worker subprocesses — jitted local math, ring allreduce
    over parallel/fabric_collectives sockets, ring order from
    topology.ring_order — decode byte-identical token streams to the
    jitted LocalExecutor, and a mid-session reset re-rendezvouses."""
    from dpu_operator_tpu.serving import ShardProcessSet

    params = _real_params(S=1, d=16, h=32, E=1)
    streams = {}
    for kind in ("local", "sharded"):
        if kind == "local":
            ex = LocalExecutor(slots=4, mode="pipelined", seed=0,
                               S=1, d=16, h=32, E=1)
        else:
            shards = ShardProcessSet(world=2, slots=4, params=params,
                                     jit=True)
            ex = FabricExecutor(shards, mode="pipelined",
                                step_timeout_s=120.0)
        reqs = _trace_reqs(6, 16, 4)
        _drive(ex, reqs)
        streams[kind] = [(r.error, list(r.tokens)) for r in reqs]
    assert all(e is None for e, _ in streams["sharded"])
    assert streams["local"] == streams["sharded"]


# -- lane budget --------------------------------------------------------------


# -- cross-process tracing plane (ISSUE 11) -----------------------------------


def _shard_taxonomy(tracer):
    """(name, rank) multiset of the per-step shard spans — the
    cross-backend comparison key (ids/timestamps differ by
    construction; the TAXONOMY must not)."""
    from collections import Counter

    return Counter(
        (s.name, s.attrs.get("rank"))
        for s in tracer.spans_snapshot()
        if s.name in ("shard.step", "shard.compute",
                      "shard.reduce_blocked"))


def _drive_steps(ex, n_steps):
    from dpu_operator_tpu.obs import trace as obs_trace

    with obs_trace.scoped() as tr:
        ex.reset()
        try:
            for k in range(n_steps):
                h = ex.submit([(0, np.full(ex.d, 1.0 + k,
                                           np.float32))],
                              occupants=[f"rq-{k}"])
                ex.collect(h)
            return _shard_taxonomy(tr), tr
        finally:
            ex.close()


def test_cross_process_trace_taxonomy_equivalence():
    """ISSUE 11 satellite: the SAME decode trace driven over synthetic
    thread shards and over REAL shard_worker subprocesses must produce
    the SAME span taxonomy — shard.step per step, shard.compute and
    shard.reduce_blocked per rank per step — so everything tier-1
    proves about shard traces transfers to the multi-process plane."""
    from dpu_operator_tpu.serving import ShardProcessSet

    n_steps, world = 3, 2
    syn_tax, _ = _drive_steps(
        FabricExecutor(SyntheticShardSet(world=world, slots=4, d=8,
                                         seed=3),
                       mode="pipelined"),
        n_steps)
    proc_tax, proc_tr = _drive_steps(
        FabricExecutor(ShardProcessSet(world=world, slots=4, d=8,
                                       seed=3, jit=False,
                                       spawn_timeout_s=60.0),
                       mode="pipelined"),
        n_steps)
    assert syn_tax == proc_tax, (syn_tax, proc_tax)
    assert syn_tax[("shard.step", None)] == n_steps
    for rank in range(world):
        assert syn_tax[("shard.compute", rank)] == n_steps
    # The subprocess run's foreign spans are clock-stamped: offset
    # AND uncertainty on every one (the alignment error bar).
    foreign = [s for s in proc_tr.spans_snapshot()
               if s.name == "shard.compute"]
    assert foreign
    for s in foreign:
        assert "clock_offset_s" in s.attrs
        assert "clock_unc_s" in s.attrs or \
            s.attrs.get("clock_unaligned")


def test_procset_piggyback_federates_spans_and_metrics():
    """One real-worker run proves the whole piggyback contract: spans
    and metrics arrive ON the tokens reply (zero extra round trips —
    StepOutput carries them, no other protocol op exists), worker
    series re-export rank/codec-labelled, and the coordinator's
    shard.step parents the workers' shard.compute spans."""
    from dpu_operator_tpu.obs import trace as obs_trace
    from dpu_operator_tpu.serving import ShardProcessSet

    reg = Registry()
    with obs_trace.scoped() as tr:
        procs = ShardProcessSet(world=2, slots=4, d=8, jit=False,
                                spawn_timeout_s=60.0,
                                metrics_interval=1)
        ex = FabricExecutor(procs, mode="pipelined", registry=reg,
                            name="xp")
        try:
            ex.reset()
            # Reach through the seam once to see the raw piggyback.
            h = ex.submit([(0, np.ones(8, np.float32))])
            out = procs.collect(h.handle, timeout=30.0)
            assert out.spans_by_rank, "no spans rode the reply"
            assert set(out.spans_by_rank) <= {0, 1}
            assert out.metrics_by_rank, "no metrics rode the reply"
            assert out.clock_by_rank
            for off, unc in out.clock_by_rank.values():
                assert unc >= 0 and abs(off) < 10.0
            ex._finish_step(h, out)
            ex.collect(ex.submit([]))
        finally:
            ex.close()
        spans = tr.spans_snapshot()
        steps = {s.span_id for s in spans if s.name == "shard.step"}
        comp = [s for s in spans if s.name == "shard.compute"]
        assert comp and all(c.parent_id in steps for c in comp)
    text = reg.render()
    assert ('shard_steps_total{codec="fp32",rank="0",replica="xp"}'
            in text)
    assert ('shard_steps_total{codec="fp32",rank="1",replica="xp"}'
            in text)
    assert ('shard_step_compute_seconds_bucket{codec="fp32",'
            in text)


def test_piggyback_loss_counter_nonzero_under_pressure():
    """Satellite: a worker whose ship buffer is too small for its
    span volume DROPS and COUNTS — the coordinator re-exports the
    loss as serving_shard_trace_dropped_total, so piggyback loss is a
    visible number, never silence."""
    from dpu_operator_tpu.obs import trace as obs_trace
    from dpu_operator_tpu.serving import ShardProcessSet

    reg = Registry()
    with obs_trace.scoped():
        ex = FabricExecutor(
            ShardProcessSet(world=2, slots=4, d=8, jit=False,
                            spawn_timeout_s=60.0, span_buffer=1),
            mode="pipelined", registry=reg, name="pressure")
        try:
            ex.reset()
            for k in range(3):
                ex.collect(ex.submit([]))
        finally:
            ex.close()
    assert reg.counter_total(
        "serving_shard_trace_dropped_total") > 0


def test_sharded_lane_wall_budget():
    """The tier-1 sharded lane must fit its documented budget
    (docs/ci.md: ~10 s measured, 60 s ceiling). Runs last in file
    order (tier-1 runs -p no:randomly); the subprocess rendezvous
    smoke is slow-marked and exempt."""
    elapsed = (time.perf_counter() - _LANE_T0[0]) - _SLOW_SPENT[0]
    assert elapsed < 60.0, (f"sharded lane took {elapsed:.1f}s "
                            f"excluding slow-marked tests "
                            f"(budget 60s)")
