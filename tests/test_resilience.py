"""Failure-detection / recovery scenarios (SURVEY §5): heartbeat loss
flips the DataProcessingUnit Ready condition and recovery restores it;
concurrent CNI attaches don't serialize or cross wires."""

import concurrent.futures
import socket
import time
import uuid

import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.api import v1
from dpu_operator_tpu.daemon import Daemon
from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster, get_condition
from dpu_operator_tpu.platform import FakePlatform
from dpu_operator_tpu.vsp import MockVsp, VspServer

TPU_ENV = {"TPU_ACCELERATOR_TYPE": "v5litepod-8", "TPU_WORKER_ID": "0"}
CR_NAME = "tpu-v5litepod-8-w0-dpu"


def wait_for(predicate, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def two_sides(tmp_root):
    import shutil
    import tempfile

    from test_daemon_e2e import TwoSideHarness

    from dpu_operator_tpu.utils import PathManager

    d = tempfile.mkdtemp(prefix="dpu-")
    harness = TwoSideHarness(host_pm=tmp_root, dpu_pm=PathManager(root=d))
    harness.start()
    try:
        yield harness
    finally:
        harness.stop()
        shutil.rmtree(d, ignore_errors=True)


def _ready(client):
    cr = client.get_or_none(
        v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, CR_NAME
    )
    if cr is None:
        return None
    cond = get_condition(cr, "Ready")
    return cond["status"] if cond else None


def test_vsp_restart_recovers_ready_condition(tmp_root):
    """Kill the VSP: Ready flips False (heartbeat/ping lost). Restart it
    on the same socket: the plugin re-Inits ('already initialized' path,
    reference vendorplugin.go:74-78) and Ready returns."""
    client = InMemoryClient(InMemoryCluster())
    client.create(
        {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "tpu-node-0"}}
    )
    port = free_port()
    vsp = MockVsp(opi_port=port)
    server = VspServer(vsp, tmp_root)
    server.start()
    daemon = Daemon(
        client,
        FakePlatform(product="Google Cloud TPU", node="tpu-node-0", env=TPU_ENV),
        path_manager=tmp_root,
        tick_interval=0.05,
        register_device_plugin=False,
    )
    daemon.start()
    try:
        assert wait_for(lambda: _ready(client) == "True"), "never became Ready"

        # A config partitions the fabric to a non-default count; the VSP
        # inventory follows.
        client.create(
            v1.new_data_processing_unit_config(
                "resil-tune", dpu_selector={"dpu.tpu.io/vendor": "tpu"},
                num_endpoints=12,
            )
        )
        assert wait_for(
            lambda: len(vsp.GetDevices(None, None).devices) == 12, timeout=10
        ), "partition never applied before the restart"

        # VSP dies. The converged manager's own OPI server keeps heartbeats
        # local, but VSP liveness is tracked via the plugin channel: the
        # next Ping forward fails → Ready must flip.
        server.stop()
        assert wait_for(lambda: _ready(client) == "False", timeout=30), (
            "Ready never flipped after VSP death"
        )

        # VSP restarts on the same socket (fresh process semantics).
        vsp2 = MockVsp(opi_port=port)
        server2 = VspServer(vsp2, tmp_root)
        server2.start()
        try:
            assert wait_for(lambda: _ready(client) == "True", timeout=30), (
                "Ready never recovered after VSP restart"
            )
            assert len(vsp2.init_calls) >= 1, "plugin never re-Init'ed the new VSP"

            # The fresh process lost its partition; the daemon must
            # notice the restart, forget applied_endpoints, and re-apply
            # the config's count — not trust its stale record.
            assert wait_for(
                lambda: len(vsp2.GetDevices(None, None).devices) == 12,
                timeout=15,
            ), "endpoint partition never re-applied after VSP restart"
        finally:
            server2.stop()
    finally:
        daemon.stop()


def test_concurrent_cni_adds_do_not_cross_wires(two_sides, netns):
    """16 parallel ADDs for distinct pods: per-key locking must neither
    serialize the node nor mix up interfaces/IPs (the reference
    serializes everything under one mutex, cniserver.go:231-235 — we
    assert the stronger property)."""
    import subprocess

    from dpu_operator_tpu.cni import CniRequest, do_cni

    sock = two_sides.host.cni_server.socket_path
    conf = {"cniVersion": "1.0.0", "name": "default-ici-net", "type": "dpu-cni"}
    namespaces = []
    try:
        for i in range(16):
            ns = f"cc{i}-" + uuid.uuid4().hex[:6]
            subprocess.run(["ip", "netns", "add", ns], check=True)
            namespaces.append(ns)

        def attach(i):
            req = CniRequest(
                command="ADD",
                container_id=f"cc{i:02d}" + uuid.uuid4().hex[:10],
                netns=namespaces[i],
                ifname="net1",
                config=conf,
            )
            t0 = time.perf_counter()
            result = do_cni(sock, req)
            return req, result, time.perf_counter() - t0

        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(max_workers=16) as pool:
            outcomes = list(pool.map(attach, range(16)))
        wall = time.perf_counter() - t0

        ips = [o[1]["ips"][0]["address"] for o in outcomes]
        assert len(set(ips)) == 16, f"duplicate IPs handed out: {ips}"
        assert len(two_sides.dpu_vsp.bridge_ports) == 16
        # Parallelism check: wall time must be well under the serial sum.
        serial_sum = sum(o[2] for o in outcomes)
        assert wall < serial_sum * 0.7, (
            f"attaches serialized: wall={wall:.3f}s vs serial {serial_sum:.3f}s"
        )

        for req, _, _ in outcomes:
            do_cni(sock, CniRequest(
                command="DEL", container_id=req.container_id, netns=req.netns,
                ifname="net1", config=conf,
            ))
        assert wait_for(lambda: len(two_sides.dpu_vsp.bridge_ports) == 0)
    finally:
        for ns in namespaces:
            subprocess.run(["ip", "netns", "del", ns], capture_output=True)


def test_cni_add_rolls_back_when_bridge_port_fails(two_sides, netns):
    """DPU-side CreateBridgePort failure mid-ADD: the host must unplumb
    the already-created veth and report a CNI error — no half-attached
    pod state left behind (host_side.py:132-136; reference hostsidemanager
    dials with backoff then fails the ADD)."""
    import subprocess

    from dpu_operator_tpu.cni import CniRequest, do_cni

    ns = "rbpod-" + uuid.uuid4().hex[:6]
    subprocess.run(["ip", "netns", "add", ns], check=True)
    try:
        two_sides.dpu_vsp.fail_bridge_port = True
        conf = {"cniVersion": "1.0.0", "name": "default-ici-net", "type": "dpu-cni"}
        cid = "rb" + uuid.uuid4().hex[:12]
        req = CniRequest(
            command="ADD", container_id=cid, netns=ns, ifname="net1", config=conf,
        )
        sock = two_sides.host.cni_server.socket_path
        from dpu_operator_tpu.cni.types import CniError

        with pytest.raises(CniError, match="CreateBridgePort"):
            do_cni(sock, req)

        # The veth was rolled back out of the pod netns.
        r = subprocess.run(
            ["ip", "-n", ns, "link", "show", "dev", "net1"],
            capture_output=True, text=True,
        )
        assert r.returncode != 0, "net1 left behind after failed ADD"

        # Recovery: VSP healthy again → the same pod attaches cleanly.
        two_sides.dpu_vsp.fail_bridge_port = False
        result = do_cni(sock, req)
        assert result.get("interfaces"), result
        assert result["interfaces"][0]["name"] == "net1"
        do_cni(sock, CniRequest(
            command="DEL", container_id=cid, netns=ns, ifname="net1", config=conf,
        ))
    finally:
        two_sides.dpu_vsp.fail_bridge_port = False
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)


def test_fast_vsp_bounce_reapplies_partition(tmp_root):
    """A VSP that restarts FASTER than the heartbeat interval (no failed
    ping in between) is still detected — the per-process instance_id
    echoed in Ping changes — and the fabric partition is re-applied to
    the fresh process instead of trusting the daemon's stale record."""
    client = InMemoryClient(InMemoryCluster())
    client.create(
        {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "tpu-node-0"}}
    )
    port = free_port()
    vsp = MockVsp(opi_port=port)
    server = VspServer(vsp, tmp_root)
    server.start()
    daemon = Daemon(
        client,
        FakePlatform(product="Google Cloud TPU", node="tpu-node-0", env=TPU_ENV),
        path_manager=tmp_root,
        tick_interval=0.05,
        register_device_plugin=False,
    )
    daemon.start()
    server2 = None
    try:
        assert wait_for(lambda: _ready(client) == "True"), "never became Ready"
        client.create(
            v1.new_data_processing_unit_config(
                "bounce-tune", dpu_selector={"dpu.tpu.io/vendor": "tpu"},
                num_endpoints=6,
            )
        )
        assert wait_for(
            lambda: len(vsp.GetDevices(None, None).devices) == 6, timeout=10
        )

        # Bounce: stop and immediately restart on the same socket — far
        # inside the 1 s heartbeat interval.
        server.stop()
        vsp2 = MockVsp(opi_port=port)
        server2 = VspServer(vsp2, tmp_root)
        server2.start()

        assert wait_for(
            lambda: len(vsp2.GetDevices(None, None).devices) == 6, timeout=20
        ), "partition never re-applied after fast bounce"
    finally:
        daemon.stop()
        if server2 is not None:
            server2.stop()
        else:
            server.stop()
