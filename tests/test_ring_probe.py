"""ICI ring-bandwidth probe (parallel/ring_probe.py): XLA fallback
correctness on the virtual 8-device mesh, pallas kernel execution on the
live TPU backend, and a pure-python simulation of the ring schedule for
the multi-chip step logic that needs hardware this environment lacks."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ring_schedule_covers_all_chunks():
    """Simulate the kernel's step arithmetic for rings of 2..8 devices:
    after num_devices-1 steps every device has every chunk exactly once
    in the right slot."""
    for n in range(2, 9):
        # comm[d] mirrors each device's double-buffered slot contents;
        # out[d] the output chunks.
        out = {d: {d} for d in range(n)}
        slot = {d: d for d in range(n)}  # payload currently in the live slot
        for step in range(n - 1):
            # All devices send concurrently: dst receives src's live slot.
            incoming = {}
            for d in range(n):
                dst = (d + 1) % n
                incoming[dst] = slot[d]
            for d in range(n):
                src_expected = (d - step - 1) % n
                assert incoming[d] == src_expected, (
                    f"n={n} step={step}: device {d} got chunk {incoming[d]}, "
                    f"kernel records it as {src_expected}"
                )
                out[d].add(incoming[d])
            slot = incoming
        for d in range(n):
            assert out[d] == set(range(n)), f"device {d} missing chunks"


def test_xla_fallback_all_gather_correct():
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, "-c", (
            "import sys; sys.path.insert(0, %r)\n"
            "import jax, jax.numpy as jnp, numpy as np\n"
            "from jax.sharding import NamedSharding, PartitionSpec as P\n"
            "from dpu_operator_tpu.parallel.mesh import build_mesh\n"
            "from dpu_operator_tpu.parallel.ring_probe import "
            "make_ring_all_gather, measure_ring_bandwidth\n"
            "mesh = build_mesh(n_devices=8)\n"
            "x = jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8)\n"
            "xs = jax.device_put(x, NamedSharding(mesh, P('sp', None)))\n"
            "out = make_ring_all_gather(mesh, 'sp')(xs)\n"
            "np.testing.assert_array_equal(np.asarray(out), np.asarray(x))\n"
            "r = measure_ring_bandwidth(mesh, mbytes=1, rounds=2)\n"
            "assert r['effective_gbps'] > 0 and r['axis_size'] == 2\n"
            "print('ok')\n"
        ) % REPO],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.slow
def test_pallas_ring_kernel_runs_on_tpu_backend():
    """The pallas RDMA kernel compiles and executes on the live TPU
    backend (ring of size 1 on a single chip; multi-chip rings exercise
    the same code with real remote copies)."""
    try:
        import jax

        if jax.devices()[0].platform != "tpu":
            pytest.skip("no TPU backend")
    except Exception:
        pytest.skip("jax unavailable")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpu_operator_tpu.parallel.mesh import build_mesh
    from dpu_operator_tpu.parallel.ring_probe import make_ring_all_gather

    mesh = build_mesh(n_devices=1)
    fn = make_ring_all_gather(mesh, "sp", use_pallas=True)
    x = jnp.arange(8 * 512, dtype=jnp.float32).reshape(8, 512)
    xs = jax.device_put(x, NamedSharding(mesh, P("sp", None)))
    np.testing.assert_array_equal(np.asarray(fn(xs)), np.asarray(x))
