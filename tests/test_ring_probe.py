"""ICI ring-bandwidth probe (parallel/ring_probe.py).

Four execution tiers so the pallas RDMA kernel is *proven*, not just
written (round-2 verdict: the kernel had zero execution coverage, and
its first interpret-mode run exposed a real slot-overwrite race):

1. pure-python simulation of the ring schedule arithmetic;
2. XLA-fallback correctness on the virtual 8-device mesh;
3. the pallas kernel EXECUTED under TPU interpret mode on the virtual
   mesh — semaphores, MESH neighbour addressing, double-buffer indexing
   and the ack-credit backpressure all run, on the max-skew 8-wide ring
   and on a multi-axis mesh;
4. AOT lowering for an 8-device TPU target (Mosaic kernel generation)
   plus, when the axon tunnel is up, real execution on the live chip via
   a bench-style subprocess (conftest pins in-process jax to CPU).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from virtual_mesh import REPO, run_virtual as _run_virtual


def test_ring_schedule_covers_all_chunks():
    """Simulate the kernel's step arithmetic for rings of 2..8 devices:
    after num_devices-1 steps every device has every chunk exactly once
    in the right slot."""
    for n in range(2, 9):
        # comm[d] mirrors each device's double-buffered slot contents;
        # out[d] the output chunks.
        out = {d: {d} for d in range(n)}
        slot = {d: d for d in range(n)}  # payload currently in the live slot
        for step in range(n - 1):
            # All devices send concurrently: dst receives src's live slot.
            incoming = {}
            for d in range(n):
                dst = (d + 1) % n
                incoming[dst] = slot[d]
            for d in range(n):
                src_expected = (d - step - 1) % n
                assert incoming[d] == src_expected, (
                    f"n={n} step={step}: device {d} got chunk {incoming[d]}, "
                    f"kernel records it as {src_expected}"
                )
                out[d].add(incoming[d])
            slot = incoming
        for d in range(n):
            assert out[d] == set(range(n)), f"device {d} missing chunks"


def _simulate_ring(n, credit, pick, max_events=100000):
    """Data-level simulation of the kernel's double-buffer ring protocol.

    Each device's step k is split into the two events the kernel performs:
    `send(d, k)` — read own slot k%2 NOW and land it in right's slot
    (k+1)%2 (in-flight delivery is modelled as immediate, the worst case
    for overwrite) — and `complete(d, k)` — the recv_sem wait + out-copy,
    enabled once left's step-k send delivered. With `credit`, send(d, k>0)
    additionally requires the right neighbour to have completed step k-1
    (the ack grant). `pick` chooses among enabled events, so adversarial
    and random interleavings are both expressible. Returns True iff every
    device gathered every chunk correctly."""
    buf = [[None, None] for _ in range(n)]
    out = [{d: d} for d in range(n)]
    sent = [0] * n  # next send index per device
    completed = [0] * n  # next complete index per device
    for d in range(n):
        buf[d][0] = d
    steps = n - 1
    for _ in range(max_events):
        events = []
        for d in range(n):
            k = sent[d]
            right = (d + 1) % n
            if k < steps and completed[d] >= k:
                if not credit or k == 0 or completed[right] >= k:
                    events.append(("send", d, k))
            k = completed[d]
            left = (d - 1) % n
            if k < steps and sent[d] > k and sent[left] > k:
                events.append(("complete", d, k))
        if not events:
            break
        kind, d, k = pick(events)
        if kind == "send":
            right = (d + 1) % n
            buf[right][(k + 1) % 2] = buf[d][k % 2]
            sent[d] = k + 1
        else:
            src = (d - k - 1) % n
            out[d][src] = buf[d][(k + 1) % 2]
            completed[d] = k + 1
    if not all(c == steps for c in completed):
        return False  # deadlock
    return all(
        out[d] == {c: c for c in range(n)} for d in range(n)
    )


def test_ring_credit_prevents_slot_overwrite():
    """The ack-credit protocol added after interpret mode exposed the
    race: without credits a device can run ≥2 sends ahead and overwrite a
    slot its right neighbour has not yet forwarded/recorded (the naive
    guide pattern corrupts under an adversarial schedule); with credits
    every adversarial and random interleaving gathers correctly."""
    import random

    def most_ahead(events):
        # Adversarial: always advance the device furthest along, sends
        # first — maximises neighbour skew.
        return max(events, key=lambda e: (e[2], e[0] == "send"))

    for n in (4, 8):
        assert not _simulate_ring(n, credit=False, pick=most_ahead), (
            f"n={n}: naive protocol unexpectedly survived the adversarial "
            "schedule — simulation no longer models the race"
        )
        assert _simulate_ring(n, credit=True, pick=most_ahead), (
            f"n={n}: credit protocol corrupted under adversarial schedule"
        )

    rng = random.Random(1234)
    for trial in range(200):
        n = rng.choice((2, 3, 4, 5, 8))
        assert _simulate_ring(n, credit=True, pick=rng.choice), (
            f"n={n} trial={trial}: credit protocol corrupted under random "
            "interleaving"
        )


def test_xla_fallback_all_gather_correct():
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import jax, jax.numpy as jnp, numpy as np\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "from dpu_operator_tpu.parallel.mesh import build_mesh\n"
        "from dpu_operator_tpu.parallel.ring_probe import "
        "make_ring_all_gather, measure_ring_bandwidth\n"
        "mesh = build_mesh(n_devices=8)\n"
        "x = jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8)\n"
        "xs = jax.device_put(x, NamedSharding(mesh, P('sp', None)))\n"
        "out = make_ring_all_gather(mesh, 'sp')(xs)\n"
        "np.testing.assert_array_equal(np.asarray(out), np.asarray(x))\n"
        "r = measure_ring_bandwidth(mesh, mbytes=1, rounds=2)\n"
        "assert r['effective_gbps'] > 0 and r['axis_size'] == 2\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.slow
def test_pallas_ring_interpret_mode_executes():
    """Both pallas kernels EXECUTE under TPU interpret mode on the
    virtual mesh and match the XLA fallback: the one-way ring on 8-wide
    (7 steps — maximum neighbour skew, the case that exposed the missing
    backpressure) and 4-wide multi-axis meshes, and the bidirectional
    ring (both duplex directions carrying half of every chunk, separate
    credit chains per direction) on 8/4/2-wide rings."""
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "from dpu_operator_tpu.parallel.ring_probe import make_ring_all_gather\n"
        "with pltpu.force_tpu_interpret_mode():\n"
        "    for shape, n in (((1, 8, 1), 8), ((2, 4, 1), 4), ((1, 2, 4), 2)):\n"
        "        mesh = Mesh(np.array(jax.devices()).reshape(shape),\n"
        "                    axis_names=('dp', 'sp', 'tp'))\n"
        "        x = jnp.arange(4 * n * 8, dtype=jnp.float32).reshape(-1, 8)\n"
        "        xs = jax.device_put(x, NamedSharding(mesh, P('sp', None)))\n"
        "        ref = np.asarray(make_ring_all_gather(mesh, 'sp',\n"
        "                         use_pallas=False)(xs))\n"
        "        for bidir in (False, True):\n"
        "            out = np.asarray(make_ring_all_gather(mesh, 'sp',\n"
        "                  use_pallas=True, bidirectional=bidir)(xs))\n"
        "            np.testing.assert_array_equal(out, ref)\n"
        "            np.testing.assert_array_equal(out, np.asarray(x))\n"
        "    # Odd per-shard chunk (3 rows): bidirectional halves can't\n"
        "    # split, so the request must fall back to the one-way ring\n"
        "    # and still gather correctly.\n"
        "    mesh = Mesh(np.array(jax.devices()).reshape(1, 8, 1),\n"
        "                axis_names=('dp', 'sp', 'tp'))\n"
        "    x = jnp.arange(3 * 8 * 8, dtype=jnp.float32).reshape(24, 8)\n"
        "    xs = jax.device_put(x, NamedSharding(mesh, P('sp', None)))\n"
        "    out = np.asarray(make_ring_all_gather(mesh, 'sp',\n"
        "          use_pallas=True, bidirectional=True)(xs))\n"
        "    np.testing.assert_array_equal(out, np.asarray(x))\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout


@pytest.mark.slow
def test_pallas_reduce_scatter_interpret_mode():
    """The ring reduce-scatter kernel EXECUTES under interpret mode and
    matches both psum_scatter and a numpy reference at 8/4/2-wide rings
    (chunk j circulates from device (j+1)%n accumulating contributions;
    shifted credit protocol). Together with the all-gather this composes
    a bandwidth-optimal all-reduce."""
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "from dpu_operator_tpu.parallel.ring_probe import ("
        "make_ring_reduce_scatter, make_ring_all_gather)\n"
        "for shape, n in (((1, 8, 1), 8), ((2, 4, 1), 4), ((1, 2, 4), 2)):\n"
        "    mesh = Mesh(np.array(jax.devices()).reshape(shape),\n"
        "                axis_names=('dp', 'sp', 'tp'))\n"
        "    rows = 2 * n\n"
        "    X = jax.random.normal(jax.random.PRNGKey(n), (n * rows, 8),\n"
        "                          dtype=jnp.float32)\n"
        "    Xs = jax.device_put(X, NamedSharding(mesh, P('sp', None)))\n"
        "    Xn = np.asarray(X).reshape(n, rows, 8)\n"
        "    chunk = rows // n\n"
        "    expect = np.concatenate([\n"
        "        Xn[:, j*chunk:(j+1)*chunk].sum(axis=0) for j in range(n)])\n"
        "    ref = np.asarray(make_ring_reduce_scatter(mesh, 'sp',\n"
        "                     use_pallas=False)(Xs))\n"
        "    np.testing.assert_allclose(ref, expect, rtol=1e-4, atol=1e-5)\n"
        "    with pltpu.force_tpu_interpret_mode():\n"
        "        out = np.asarray(make_ring_reduce_scatter(mesh, 'sp',\n"
        "                         use_pallas=True)(Xs))\n"
        "        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)\n"
        "        # all-reduce = reduce-scatter o all-gather on the axis.\n"
        "        rs = make_ring_reduce_scatter(mesh, 'sp', use_pallas=True)\n"
        "        ag = make_ring_all_gather(mesh, 'sp', use_pallas=True)\n"
        "        allred = np.asarray(ag(rs(Xs)))\n"
        "        np.testing.assert_allclose(allred, expect, rtol=1e-4,\n"
        "                                   atol=1e-5)\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout


@pytest.mark.slow
def test_pallas_all_to_all_interpret_mode():
    """The all-to-all kernel (Ulysses-style sequence/expert-parallel
    exchange; arbitrary-target RDMAs, all-devices barrier, shared
    arrival-counting semaphore) EXECUTES under interpret mode and
    matches jax.lax.all_to_all and a numpy reference at 8/4/2 widths."""
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "from dpu_operator_tpu.parallel.ring_probe import make_all_to_all\n"
        "for shape, n in (((1, 8, 1), 8), ((2, 4, 1), 4), ((1, 2, 4), 2)):\n"
        "    mesh = Mesh(np.array(jax.devices()).reshape(shape),\n"
        "                axis_names=('dp', 'sp', 'tp'))\n"
        "    rows = 2 * n\n"
        "    X = jax.random.normal(jax.random.PRNGKey(n), (n * rows, 8),\n"
        "                          dtype=jnp.float32)\n"
        "    Xs = jax.device_put(X, NamedSharding(mesh, P('sp', None)))\n"
        "    ref = np.asarray(make_all_to_all(mesh, 'sp', use_pallas=False)(Xs))\n"
        "    Xn = np.asarray(X).reshape(n, n, rows // n, 8)\n"
        "    expect = Xn.transpose(1, 0, 2, 3).reshape(n * rows, 8)\n"
        "    np.testing.assert_allclose(ref, expect, rtol=1e-6)\n"
        "    with pltpu.force_tpu_interpret_mode():\n"
        "        out = np.asarray(make_all_to_all(mesh, 'sp',\n"
        "                         use_pallas=True)(Xs))\n"
        "    np.testing.assert_allclose(out, expect, rtol=1e-6)\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout


def test_pallas_ring_aot_lowers_for_tpu():
    """AOT-lower the pallas ring for an 8-device TPU topology via
    jax.export: Mosaic kernel generation runs (the lowering would reject
    malformed semaphore/DMA programs) and the module carries the
    tpu_custom_call, proving the multi-device path compiles without
    multi-chip hardware."""
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from dpu_operator_tpu.parallel.ring_probe import make_ring_all_gather\n"
        "mesh = Mesh(np.array(jax.devices()).reshape(1, 8, 1),\n"
        "            axis_names=('dp', 'sp', 'tp'))\n"
        "spec = jax.ShapeDtypeStruct((32, 8), jnp.float32,\n"
        "        sharding=NamedSharding(mesh, P('sp', None)))\n"
        "for bidir in (False, True):\n"
        "    fn = make_ring_all_gather(mesh, 'sp', use_pallas=True,\n"
        "                              bidirectional=bidir)\n"
        "    exp = jax.export.export(fn, platforms=['tpu'])(spec)\n"
        "    assert 'tpu_custom_call' in exp.mlir_module()\n"
        "from dpu_operator_tpu.parallel.ring_probe import "
        "make_ring_reduce_scatter\n"
        "rs = make_ring_reduce_scatter(mesh, 'sp', use_pallas=True)\n"
        "# Each device's local contribution needs n chunks: 8*16 rows\n"
        "# globally -> 16 local rows -> chunk 2.\n"
        "rs_spec = jax.ShapeDtypeStruct((128, 8), jnp.float32,\n"
        "          sharding=NamedSharding(mesh, P('sp', None)))\n"
        "exp = jax.export.export(rs, platforms=['tpu'])(rs_spec)\n"
        "assert 'tpu_custom_call' in exp.mlir_module()\n"
        "from dpu_operator_tpu.parallel.ring_probe import make_all_to_all\n"
        "a2a = make_all_to_all(mesh, 'sp', use_pallas=True)\n"
        "exp = jax.export.export(a2a, platforms=['tpu'])(rs_spec)\n"
        "assert 'tpu_custom_call' in exp.mlir_module()\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr


def _tunnel_alive() -> bool:
    for port in (8082, 8092, 8102, 8112):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1.0):
                return True
        except OSError:
            continue
    return False


@pytest.mark.slow
def test_pallas_ring_kernel_runs_on_tpu_backend():
    """The pallas RDMA kernel compiles (Mosaic) and executes on the live
    TPU chip. In-process jax is pinned to CPU by conftest, so reach the
    chip the way bench.py does: a subprocess with the default environment
    (sitecustomize routes it through the axon tunnel), timeout-guarded
    because a wedged tunnel blocks device discovery forever."""
    if not _tunnel_alive():
        pytest.skip("axon tunnel not reachable")
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import json, jax\n"
        "dev = jax.devices()[0]\n"
        "if dev.platform != 'tpu':\n"
        "    print(json.dumps({'skip': dev.platform})); sys.exit(0)\n"
        "import jax.numpy as jnp, numpy as np\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "from dpu_operator_tpu.parallel.mesh import build_mesh\n"
        "from dpu_operator_tpu.parallel.ring_probe import make_ring_all_gather\n"
        "mesh = build_mesh(n_devices=1)\n"
        "fn = make_ring_all_gather(mesh, 'sp', use_pallas=True)\n"
        "x = jnp.arange(8 * 512, dtype=jnp.float32).reshape(8, 512)\n"
        "xs = jax.device_put(x, NamedSharding(mesh, P('sp', None)))\n"
        "np.testing.assert_array_equal(np.asarray(fn(xs)), np.asarray(x))\n"
        "print(json.dumps({'ok': True, 'device': str(dev.device_kind)}))\n"
    ) % REPO
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=900, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("tpu subprocess timed out (tunnel wedged)")
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    result = json.loads(r.stdout.strip().splitlines()[-1])
    if "skip" in result:
        pytest.skip(f"backend is {result['skip']}, not tpu")
    assert result["ok"]
