"""Deploy-asset sanity: every YAML in config/, bundle/, examples/ and the
controller bindata parses; kustomization resource references resolve; the
CRD set covers all four kinds (counterpart of the reference's kustomize/
OLM asset tree, SURVEY §2.6)."""

import glob
import json
import os

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _yaml_files():
    pats = [
        "config/**/*.yaml",
        "bundle/**/*.yaml",
        "examples/*.yaml",
        "hack/cluster-configs/*.yaml",
        "dpu_operator_tpu/controller/bindata/**/*.yaml",
    ]
    files = []
    for p in pats:
        files.extend(glob.glob(os.path.join(REPO, p), recursive=True))
    return sorted(set(files))


def _rendered(path: str) -> str:
    """File text with bindata {{var}} template placeholders rendered with
    dummies — shared by every test that parses asset YAML."""
    import re

    with open(path) as fh:
        text = fh.read()
    if "bindata" in path:
        text = re.sub(r"{{\s*([a-zA-Z0-9_]+)\s*}}", "placeholder", text)
    return text


def test_all_yaml_parses():
    files = _yaml_files()
    assert len(files) > 20, f"expected a full asset tree, found {len(files)}"
    for f in files:
        list(yaml.safe_load_all(_rendered(f))), f


def test_kustomizations_resolve():
    for kfile in glob.glob(os.path.join(REPO, "config/**/kustomization.yaml"), recursive=True):
        base = os.path.dirname(kfile)
        with open(kfile) as fh:
            doc = yaml.safe_load(fh)
        for res in doc.get("resources", []):
            assert os.path.exists(os.path.join(base, res)), f"{kfile}: missing {res}"


def test_crds_cover_all_kinds():
    kinds = set()
    for f in glob.glob(os.path.join(REPO, "config/crd/*.yaml")):
        with open(f) as fh:
            for doc in yaml.safe_load_all(fh):
                if doc and doc.get("kind") == "CustomResourceDefinition":
                    kinds.add(doc["spec"]["names"]["kind"])
    assert kinds == {
        "DpuOperatorConfig",
        "DataProcessingUnit",
        "ServiceFunctionChain",
        "DataProcessingUnitConfig",
    }


def _load_csv():
    csv_path = os.path.join(
        REPO, "bundle/manifests/tpu-dpu-operator.clusterserviceversion.yaml"
    )
    with open(csv_path) as fh:
        return yaml.safe_load(fh)


def test_csv_owns_all_crds():
    csv = _load_csv()
    owned = {c["kind"] for c in csv["spec"]["customresourcedefinitions"]["owned"]}
    assert owned == {
        "DpuOperatorConfig",
        "DataProcessingUnit",
        "ServiceFunctionChain",
        "DataProcessingUnitConfig",
    }


def test_csv_is_installable():
    """The CSV carries a working install strategy — deployment spec,
    RBAC, webhooks, samples — not an empty shell (VERDICT r1 Missing #2:
    'make deploy as shipped cannot produce a working OLM install')."""
    csv = _load_csv()
    spec = csv["spec"]["install"]["spec"]
    dep = spec["deployments"][0]
    containers = dep["spec"]["template"]["spec"]["containers"]
    assert containers and containers[0]["image"]
    assert spec["permissions"][0]["rules"], "namespace permissions empty"
    assert spec["clusterPermissions"][0]["rules"], "clusterPermissions empty"
    # Lease RBAC present for leader election.
    lease_rules = [
        r for r in spec["permissions"][0]["rules"]
        if "leases" in r.get("resources", [])
    ]
    assert lease_rules, "no coordination.k8s.io/leases permission"
    # Webhooks declared OLM-style.
    whs = csv["spec"]["webhookdefinitions"]
    assert {w["generateName"] for w in whs} == {
        "vdpuoperatorconfig.kb.io", "vservicefunctionchain.kb.io",
        "vdataprocessingunitconfig.kb.io",
    }
    # Samples render as alm-examples.
    examples = yaml.safe_load(csv["metadata"]["annotations"]["alm-examples"])
    assert {e["kind"] for e in examples} >= {"DpuOperatorConfig"}


def test_bundle_structure_matches_reference_shape():
    """Same file classes as the reference bundle/: per-CRD manifests,
    metrics + webhook services, metrics-reader role, scorecard config."""
    expected = [
        "manifests/config.tpu.io_dpuoperatorconfigs.yaml",
        "manifests/config.tpu.io_dataprocessingunits.yaml",
        "manifests/config.tpu.io_servicefunctionchains.yaml",
        "manifests/config.tpu.io_dataprocessingunitconfigs.yaml",
        "manifests/tpu-dpu-operator-controller-manager-metrics-service_v1_service.yaml",
        "manifests/tpu-dpu-operator-metrics-reader_rbac.authorization.k8s.io_v1_clusterrole.yaml",
        "manifests/tpu-dpu-operator-webhook-service_v1_service.yaml",
        "manifests/tpu-dpu-operator.clusterserviceversion.yaml",
        "metadata/annotations.yaml",
        "tests/scorecard/config.yaml",
    ]
    for rel in expected:
        assert os.path.exists(os.path.join(REPO, "bundle", rel)), f"missing {rel}"
    with open(os.path.join(REPO, "bundle/tests/scorecard/config.yaml")) as fh:
        scorecard = yaml.safe_load(fh)
    suites = {t["labels"]["suite"] for t in scorecard["stages"][0]["tests"]}
    assert suites == {"basic", "olm"}


def test_bundle_is_fresh():
    """The committed bundle/ is exactly what scripts/gen_bundle.py emits
    from config/ (the `make bundle` regeneration contract)."""
    import subprocess
    import sys

    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "gen_bundle.py"), "--check"],
        capture_output=True,
        text=True,
    )
    assert rc.returncode == 0, rc.stdout + rc.stderr


def test_nad_configs_are_valid_cni_json():
    """Every NetworkAttachmentDefinition (bindata + examples) embeds a
    spec.config that parses as JSON, names the dpu-cni plugin, and — when
    it carries an `ipam` section — uses only keys the fabric dataplane's
    host-local grammar understands (a typo'd key would silently fall back
    to defaults in production)."""
    from dpu_operator_tpu.cni.ipam import (DelegatedIpam,
                                       KNOWN_IPAM_KEYS)

    nads = 0
    for pattern in ("dpu_operator_tpu/controller/bindata/**/*.yaml",
                    "examples/*.yaml"):
        for path in glob.glob(os.path.join(REPO, pattern), recursive=True):
            for doc in yaml.safe_load_all(_rendered(path)):
                    if not doc or doc.get("kind") != "NetworkAttachmentDefinition":
                        continue
                    nads += 1
                    conf = json.loads(doc["spec"]["config"])
                    assert conf["type"] == "dpu-cni", path
                    assert conf.get("cniVersion"), path
                    ipam = conf.get("ipam")
                    if ipam:
                        itype = ipam.get("type")
                        if itype and itype != "host-local":
                            # Delegated to an external CNI IPAM plugin
                            # (fabric._ipam_for): ITS grammar, not ours —
                            # only the exec-safety rule applies, and the
                            # RUNTIME predicate is the authority (the
                            # ctor raises on a type the dpu-cni would
                            # refuse to exec at pod-attach time).
                            DelegatedIpam(conf)  # raises IpamError if bad
                            continue
                        unknown = set(ipam) - KNOWN_IPAM_KEYS
                        assert not unknown, f"{path}: unknown ipam keys {unknown}"
                        assert "subnet" in ipam, f"{path}: ipam without subnet"
                        for r in ipam.get("routes", []):
                            assert "dst" in r, f"{path}: route without dst"
    assert nads >= 3, f"expected the NAD set, found {nads}"


def test_webhook_manifest_paths_match_served_routes():
    """Every ValidatingWebhookConfiguration path must have a registered
    handler and vice versa — a mismatch 404s admission requests and,
    with failurePolicy Fail, rejects every CR create in the cluster
    (this exact bug shipped once: manifest used kubebuilder-style paths
    while main() registered short ones)."""
    import yaml

    from dpu_operator_tpu.controller.main import WEBHOOK_ROUTES

    with open(os.path.join(REPO, "config", "webhook", "webhook.yaml")) as f:
        docs = list(yaml.safe_load_all(f))
    vwc = next(d for d in docs if d["kind"] == "ValidatingWebhookConfiguration")
    manifest_paths = {
        wh["clientConfig"]["service"]["path"] for wh in vwc["webhooks"]
    }
    assert manifest_paths == set(WEBHOOK_ROUTES), (
        f"manifest {sorted(manifest_paths)} != served {sorted(WEBHOOK_ROUTES)}"
    )
    # The OLM CSV duplicates the paths in webhookdefinitions — a typo
    # there ships the same outage through the bundle install path.
    with open(os.path.join(
            REPO, "bundle", "manifests",
            "tpu-dpu-operator.clusterserviceversion.yaml")) as f:
        csv = yaml.safe_load(f)
    csv_paths = {
        wh["webhookPath"] for wh in csv["spec"]["webhookdefinitions"]
    }
    assert csv_paths == set(WEBHOOK_ROUTES), (
        f"CSV {sorted(csv_paths)} != served {sorted(WEBHOOK_ROUTES)}"
    )
    # failurePolicy Fail + a webhook for every validated kind.
    kinds = {r for wh in vwc["webhooks"] for r in wh["rules"][0]["resources"]}
    assert kinds == {
        "dpuoperatorconfigs", "servicefunctionchains",
        "dataprocessingunitconfigs",
    }
