"""Deploy-asset sanity: every YAML in config/, bundle/, examples/ and the
controller bindata parses; kustomization resource references resolve; the
CRD set covers all four kinds (counterpart of the reference's kustomize/
OLM asset tree, SURVEY §2.6)."""

import glob
import os

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _yaml_files():
    pats = [
        "config/**/*.yaml",
        "bundle/**/*.yaml",
        "examples/*.yaml",
        "hack/cluster-configs/*.yaml",
        "dpu_operator_tpu/controller/bindata/**/*.yaml",
    ]
    files = []
    for p in pats:
        files.extend(glob.glob(os.path.join(REPO, p), recursive=True))
    return sorted(set(files))


def test_all_yaml_parses():
    files = _yaml_files()
    assert len(files) > 20, f"expected a full asset tree, found {len(files)}"
    for f in files:
        with open(f) as fh:
            text = fh.read()
        # bindata templates hold {{var}} placeholders; render with dummies.
        if "bindata" in f:
            import re

            text = re.sub(r"{{\s*([a-zA-Z0-9_]+)\s*}}", "placeholder", text)
        list(yaml.safe_load_all(text)), f


def test_kustomizations_resolve():
    for kfile in glob.glob(os.path.join(REPO, "config/**/kustomization.yaml"), recursive=True):
        base = os.path.dirname(kfile)
        with open(kfile) as fh:
            doc = yaml.safe_load(fh)
        for res in doc.get("resources", []):
            assert os.path.exists(os.path.join(base, res)), f"{kfile}: missing {res}"


def test_crds_cover_all_kinds():
    kinds = set()
    for f in glob.glob(os.path.join(REPO, "config/crd/*.yaml")):
        with open(f) as fh:
            for doc in yaml.safe_load_all(fh):
                if doc and doc.get("kind") == "CustomResourceDefinition":
                    kinds.add(doc["spec"]["names"]["kind"])
    assert kinds == {
        "DpuOperatorConfig",
        "DataProcessingUnit",
        "ServiceFunctionChain",
        "DataProcessingUnitConfig",
    }


def test_csv_owns_all_crds():
    csv_path = os.path.join(
        REPO, "bundle/manifests/tpu-dpu-operator.clusterserviceversion.yaml"
    )
    with open(csv_path) as fh:
        csv = yaml.safe_load(fh)
    owned = {c["kind"] for c in csv["spec"]["customresourcedefinitions"]["owned"]}
    assert owned == {
        "DpuOperatorConfig",
        "DataProcessingUnit",
        "ServiceFunctionChain",
        "DataProcessingUnitConfig",
    }
