"""Leader election: Lease semantics, mutual exclusion, failover.

Reference enables controller-runtime leader election in the manager
(cmd/main.go:80-102); this tier proves our LeaderElector gives the same
guarantees: at most one leader, clean-release fast handover, expired
leases stolen, starvation abdication, and the same behavior through the
production HttpClient as in-process (VERDICT r1 Missing #3)."""

import datetime
import threading
import time

import pytest

from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster
from dpu_operator_tpu.k8s.http_client import HttpClient
from dpu_operator_tpu.k8s.http_server import ApiServer
from dpu_operator_tpu.k8s.leaderelection import (
    LEASE_API_VERSION,
    LEASE_KIND,
    LeaderElector,
    _now_micro,
)

NS = "openshift-dpu-operator"

# Fast-but-ordered timings: retry < renew_deadline < lease_duration.
FAST = dict(lease_duration=1.2, renew_deadline=0.7, retry_period=0.15)


def _elector(client, identity, **kw):
    args = dict(FAST)
    args.update(kw)
    return LeaderElector(client, "op-leader", NS, identity=identity, **args)


def _wait(predicate, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


@pytest.fixture()
def client():
    return InMemoryClient(InMemoryCluster())


def test_single_elector_acquires_and_records_lease(client):
    started = threading.Event()
    e = _elector(client, "a", on_started_leading=started.set)
    e.start()
    try:
        assert started.wait(3)
        assert e.is_leader
        lease = client.get(LEASE_API_VERSION, LEASE_KIND, NS, "op-leader")
        assert lease["spec"]["holderIdentity"] == "a"
        assert lease["spec"]["leaseTransitions"] == 0  # first acquire, no handover yet
        assert e.leader_identity() == "a"
    finally:
        e.stop()


def test_two_electors_exactly_one_leader(client):
    a = _elector(client, "a")
    b = _elector(client, "b")
    a.start()
    b.start()
    try:
        assert _wait(lambda: a.is_leader or b.is_leader)
        # Let both run a few renew cycles; the invariant must hold throughout.
        for _ in range(10):
            assert int(a.is_leader) + int(b.is_leader) <= 1
            time.sleep(0.1)
        assert int(a.is_leader) + int(b.is_leader) == 1
    finally:
        a.stop()
        b.stop()


def test_clean_stop_hands_over_fast(client):
    a = _elector(client, "a")
    a.start()
    assert _wait(lambda: a.is_leader)
    b = _elector(client, "b")
    b.start()
    try:
        time.sleep(0.3)
        assert not b.is_leader
        t0 = time.monotonic()
        a.stop()  # releases the lease
        assert _wait(lambda: b.is_leader, timeout=3)
        # Handover must beat the full lease duration (release worked).
        assert time.monotonic() - t0 < FAST["lease_duration"]
    finally:
        b.stop()


def test_stop_with_hung_renew_skips_release(client):
    """If the renew thread outlives join(timeout), stop() must NOT
    release: a late in-flight renew could rewrite holderIdentity after
    the release, resurrecting a lease nobody holds (ADVICE r2). The lease
    is left to expire naturally instead."""
    a = _elector(client, "a")
    a.start()
    assert _wait(lambda: a.is_leader)
    # Wedge the renew thread: swap in a stand-in that never exits join.
    real_thread = a._thread

    class Hung:
        def join(self, timeout=None):
            time.sleep(timeout or 0)

        def is_alive(self):
            return True

    a._thread = Hung()
    try:
        a.stop(timeout=0.1)
        lease = client.get("coordination.k8s.io/v1", "Lease", NS, "op-leader")
        assert (lease.get("spec") or {}).get("holderIdentity") == "a", (
            "lease was released despite a live renew thread"
        )
    finally:
        a._thread = real_thread
        a.stop()


def test_expired_lease_is_stolen(client):
    stale = datetime.datetime.now(datetime.timezone.utc) - datetime.timedelta(seconds=60)
    client.create(
        {
            "apiVersion": LEASE_API_VERSION,
            "kind": LEASE_KIND,
            "metadata": {"name": "op-leader", "namespace": NS},
            "spec": {
                "holderIdentity": "dead-operator",
                "leaseDurationSeconds": 2,
                "renewTime": stale.strftime("%Y-%m-%dT%H:%M:%S.%fZ"),
                "leaseTransitions": 4,
            },
        }
    )
    b = _elector(client, "b")
    b.start()
    try:
        assert _wait(lambda: b.is_leader)
        lease = client.get(LEASE_API_VERSION, LEASE_KIND, NS, "op-leader")
        assert lease["spec"]["holderIdentity"] == "b"
        assert lease["spec"]["leaseTransitions"] == 5
    finally:
        b.stop()


def test_leader_abdicates_when_lease_stolen(client):
    """If another holder somehow owns a valid lease (apiserver said no to
    every renewal), the leader must call on_stopped_leading within the
    renew deadline — the caller treats this as fatal."""
    stopped = threading.Event()
    a = _elector(client, "a", on_stopped_leading=stopped.set)
    a.start()
    try:
        assert _wait(lambda: a.is_leader)
        lease = client.get(LEASE_API_VERSION, LEASE_KIND, NS, "op-leader")
        lease["spec"]["holderIdentity"] = "usurper"
        lease["spec"]["leaseDurationSeconds"] = 3600
        lease["spec"]["renewTime"] = _now_micro()
        client.update(lease)
        assert stopped.wait(FAST["renew_deadline"] + 2)
        assert not a.is_leader
    finally:
        a.stop()


def test_election_through_http_apiserver():
    """Same behavior through the production HttpClient (chunked REST), so
    the Lease path is proven against real wire semantics."""
    server = ApiServer(InMemoryCluster()).start()
    try:
        a = _elector(HttpClient(server.url), "a")
        b = _elector(HttpClient(server.url), "b")
        a.start()
        b.start()
        try:
            assert _wait(lambda: a.is_leader or b.is_leader)
            time.sleep(0.5)
            assert int(a.is_leader) + int(b.is_leader) == 1
            leader, follower = (a, b) if a.is_leader else (b, a)
            leader.stop()
            assert _wait(lambda: follower.is_leader, timeout=3)
        finally:
            a.stop()
            b.stop()
    finally:
        server.stop()


def test_voluntary_stop_does_not_fire_on_stopped(client):
    """Clean shutdown releases the lease WITHOUT invoking
    on_stopped_leading — callers wire that to a fatal exit, which must
    only happen on involuntary loss."""
    stopped = threading.Event()
    a = _elector(client, "a", on_stopped_leading=stopped.set)
    a.start()
    assert _wait(lambda: a.is_leader)
    a.stop()
    assert not stopped.is_set()
    assert not a.is_leader
    # Lease is released for the next candidate.
    assert a.leader_identity() is None


def test_on_started_failure_abdicates_fatally(client):
    """If on_started_leading raises (manager failed to start), the
    elector must release the lease and take the fatal on_stopped path —
    never sit on the lease doing nothing."""
    stopped = threading.Event()

    def boom():
        raise RuntimeError("manager failed to start")

    a = _elector(client, "a", on_started_leading=boom, on_stopped_leading=stopped.set)
    a.start()
    try:
        assert stopped.wait(3)
        assert not a.is_leader
        assert a.leader_identity() is None  # lease released for the standby
        b = _elector(client, "b")
        b.start()
        try:
            assert _wait(lambda: b.is_leader, timeout=3)
        finally:
            b.stop()
    finally:
        a.stop()


def test_timing_constraints_validated(client):
    with pytest.raises(ValueError):
        LeaderElector(client, "x", NS, lease_duration=5, renew_deadline=5, retry_period=1)
    with pytest.raises(ValueError):
        LeaderElector(client, "x", NS, lease_duration=5, renew_deadline=3, retry_period=3)
