"""Webhook TLS: the ssl-context branch a real apiserver uses, plus cert
hot-reload (VERDICT r1 Missing #4 / Weak #5).

Self-signed certs are minted in a tmpdir with `cryptography`; the
hot-reload test rotates them on disk and asserts the rotated serial is
served by the same listener without a restart — the guarantee the
reference gets from fsnotify (cmd/nri/networkresourcesinjector.go:190-230)."""

import datetime
import ipaddress
import json
import socket
import ssl
import time
import urllib.request

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID

from dpu_operator_tpu.api.webhook import AdmissionWebhook, validate_dpu_operator_config


def _mint_cert(tmp_path, serial: int):
    """Self-signed localhost cert; returns (certfile, keyfile)."""
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(serial)
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName("localhost"),
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    certfile = tmp_path / "tls.crt"
    keyfile = tmp_path / "tls.key"
    certfile.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    keyfile.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(certfile), str(keyfile)


def _served_serial(port: int) -> int:
    """Handshake and return the serial of the cert the server presents."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    with socket.create_connection(("127.0.0.1", port), timeout=5) as sock:
        with ctx.wrap_socket(sock, server_hostname="localhost") as tls:
            der = tls.getpeercert(binary_form=True)
    return x509.load_der_x509_certificate(der).serial_number


def _review(obj: dict) -> dict:
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"uid": "u-1", "object": obj},
    }


def test_admission_over_tls_with_verified_chain(tmp_path):
    """Full AdmissionReview round trip over HTTPS, client *verifying* the
    server cert — exactly what a real apiserver does with caBundle."""
    certfile, keyfile = _mint_cert(tmp_path, serial=100)
    wh = AdmissionWebhook(port=0, certfile=certfile, keyfile=keyfile)
    wh.register("/validate-dpuoperatorconfig", validate_dpu_operator_config)
    wh.start()
    try:
        ctx = ssl.create_default_context(cafile=certfile)
        good = _review(
            {
                "metadata": {"name": "dpu-operator-config"},
                "spec": {"logLevel": 1},
            }
        )
        req = urllib.request.Request(
            f"https://localhost:{wh.port}/validate-dpuoperatorconfig",
            data=json.dumps(good).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = json.loads(urllib.request.urlopen(req, context=ctx).read())
        assert resp["response"]["allowed"] is True
        assert resp["response"]["uid"] == "u-1"

        bad = _review({"metadata": {"name": "wrong-name"}, "spec": {}})
        req = urllib.request.Request(
            f"https://localhost:{wh.port}/validate-dpuoperatorconfig",
            data=json.dumps(bad).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = json.loads(urllib.request.urlopen(req, context=ctx).read())
        assert resp["response"]["allowed"] is False
    finally:
        wh.stop()


def test_cert_hot_reload_same_listener(tmp_path):
    certfile, keyfile = _mint_cert(tmp_path, serial=1111)
    wh = AdmissionWebhook(
        port=0, certfile=certfile, keyfile=keyfile, cert_reload_interval=0.1
    )
    wh.register("/validate-dpuoperatorconfig", validate_dpu_operator_config)
    wh.start()
    try:
        port = wh.port
        assert _served_serial(port) == 1111

        # Rotate on disk — same paths, new pair (cert-manager style).
        _mint_cert(tmp_path, serial=2222)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and wh.certs_reloaded == 0:
            time.sleep(0.05)
        assert wh.certs_reloaded >= 1

        # Same port, no restart, new cert served.
        assert _served_serial(port) == 2222
        assert wh.port == port
    finally:
        wh.stop()
