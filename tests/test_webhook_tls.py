"""Webhook TLS: the ssl-context branch a real apiserver uses, plus cert
hot-reload (VERDICT r1 Missing #4 / Weak #5).

Self-signed certs are minted in a tmpdir with `cryptography`; the
hot-reload test rotates them on disk and asserts the rotated serial is
served by the same listener without a restart — the guarantee the
reference gets from fsnotify (cmd/nri/networkresourcesinjector.go:190-230)."""

import datetime
import ipaddress
import json
import socket
import ssl
import time
import urllib.request

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID

from dpu_operator_tpu.api.webhook import AdmissionWebhook, validate_dpu_operator_config


def _mint_cert(tmp_path, serial: int):
    """Self-signed localhost cert; returns (certfile, keyfile)."""
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(serial)
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName("localhost"),
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    certfile = tmp_path / "tls.crt"
    keyfile = tmp_path / "tls.key"
    certfile.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    keyfile.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(certfile), str(keyfile)


def _served_serial(port: int) -> int:
    """Handshake and return the serial of the cert the server presents."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    with socket.create_connection(("127.0.0.1", port), timeout=5) as sock:
        with ctx.wrap_socket(sock, server_hostname="localhost") as tls:
            der = tls.getpeercert(binary_form=True)
    return x509.load_der_x509_certificate(der).serial_number


def _review(obj: dict) -> dict:
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"uid": "u-1", "object": obj},
    }


def test_admission_over_tls_with_verified_chain(tmp_path):
    """Full AdmissionReview round trip over HTTPS, client *verifying* the
    server cert — exactly what a real apiserver does with caBundle."""
    certfile, keyfile = _mint_cert(tmp_path, serial=100)
    wh = AdmissionWebhook(port=0, certfile=certfile, keyfile=keyfile)
    wh.register("/validate-dpuoperatorconfig", validate_dpu_operator_config)
    wh.start()
    try:
        ctx = ssl.create_default_context(cafile=certfile)
        good = _review(
            {
                "metadata": {"name": "dpu-operator-config"},
                "spec": {"logLevel": 1},
            }
        )
        req = urllib.request.Request(
            f"https://localhost:{wh.port}/validate-dpuoperatorconfig",
            data=json.dumps(good).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = json.loads(urllib.request.urlopen(req, context=ctx).read())
        assert resp["response"]["allowed"] is True
        assert resp["response"]["uid"] == "u-1"

        bad = _review({"metadata": {"name": "wrong-name"}, "spec": {}})
        req = urllib.request.Request(
            f"https://localhost:{wh.port}/validate-dpuoperatorconfig",
            data=json.dumps(bad).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = json.loads(urllib.request.urlopen(req, context=ctx).read())
        assert resp["response"]["allowed"] is False
    finally:
        wh.stop()


def test_cert_hot_reload_same_listener(tmp_path):
    certfile, keyfile = _mint_cert(tmp_path, serial=1111)
    wh = AdmissionWebhook(
        port=0, certfile=certfile, keyfile=keyfile, cert_reload_interval=0.1
    )
    wh.register("/validate-dpuoperatorconfig", validate_dpu_operator_config)
    wh.start()
    try:
        port = wh.port
        assert _served_serial(port) == 1111

        # Rotate on disk — same paths, new pair (cert-manager style).
        _mint_cert(tmp_path, serial=2222)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and wh.certs_reloaded == 0:
            time.sleep(0.05)
        assert wh.certs_reloaded >= 1

        # Same port, no restart, new cert served.
        assert _served_serial(port) == 2222
        assert wh.port == port
    finally:
        wh.stop()


def test_rotation_under_concurrent_load_no_handshake_failures(tmp_path):
    """Hammer the webhook with concurrent AdmissionReviews while rotating
    certs repeatedly: no request may ever see a handshake or HTTP failure
    (round-2 verdict Weak #7 — the fsnotify-window race the reference's
    hot-reload code exists for, networkresourcesinjector.go:190-230). The
    client trusts both generations, mirroring an apiserver whose caBundle
    covers the rotation overlap."""
    import concurrent.futures
    import threading

    # One CA signs every generation (cert-manager's model): the client
    # trusts the CA, so every rotated leaf verifies.
    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "test-ca")])
    now = datetime.datetime.now(datetime.timezone.utc)
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(1000)
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    ca_pem = tmp_path / "ca.pem"
    ca_pem.write_bytes(ca_cert.public_bytes(serialization.Encoding.PEM))

    def mint_leaf(directory, serial):
        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        cert = (
            x509.CertificateBuilder()
            .subject_name(
                x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
            )
            .issuer_name(ca_name)
            .public_key(key.public_key())
            .serial_number(serial)
            .not_valid_before(now - datetime.timedelta(minutes=1))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(
                x509.SubjectAlternativeName(
                    [
                        x509.DNSName("localhost"),
                        x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                    ]
                ),
                critical=False,
            )
            .sign(ca_key, hashes.SHA256())
        )
        cf, kf = directory / "tls.crt", directory / "tls.key"
        cf.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
        kf.write_bytes(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
        )
        return str(cf), str(kf)

    certfile, keyfile = mint_leaf(tmp_path, serial=1)
    wh = AdmissionWebhook(
        port=0, certfile=certfile, keyfile=keyfile, cert_reload_interval=0.05
    )
    wh.register("/validate-dpuoperatorconfig", validate_dpu_operator_config)
    wh.start()
    stop = threading.Event()
    failures: list = []
    ROTATIONS = 8
    try:
        port = wh.port
        minted = [(certfile, keyfile)]
        for serial in range(2, ROTATIONS + 2):
            d = tmp_path / f"gen{serial}"
            d.mkdir()
            minted.append(mint_leaf(d, serial=serial))
        ctx = ssl.create_default_context(cafile=str(ca_pem))

        good = _review(
            {"metadata": {"name": "dpu-operator-config"}, "spec": {"logLevel": 1}}
        )
        payload = json.dumps(good).encode()

        def client_loop(worker: int) -> int:
            n = 0
            while not stop.is_set():
                try:
                    req = urllib.request.Request(
                        f"https://localhost:{port}/validate-dpuoperatorconfig",
                        data=payload,
                        headers={"Content-Type": "application/json"},
                    )
                    resp = json.loads(
                        urllib.request.urlopen(req, context=ctx, timeout=5).read()
                    )
                    assert resp["response"]["allowed"] is True
                    n += 1
                except Exception as e:  # noqa: BLE001 — every failure counts
                    failures.append(f"worker {worker}: {type(e).__name__}: {e}")
            return n

        with concurrent.futures.ThreadPoolExecutor(4) as pool:
            futs = [pool.submit(client_loop, w) for w in range(4)]
            # Rotate through every minted generation while requests fly.
            for serial in range(2, ROTATIONS + 2):
                src_cert, src_key = minted[serial - 1]
                reloads = wh.certs_reloaded
                open(certfile, "w").write(open(src_cert).read())
                open(keyfile, "w").write(open(src_key).read())
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline and wh.certs_reloaded == reloads:
                    time.sleep(0.02)
                assert wh.certs_reloaded > reloads, "rotation not picked up"
            time.sleep(0.2)
            stop.set()
            total = sum(f.result(timeout=10) for f in futs)

        assert not failures, f"{len(failures)} failed requests: {failures[:5]}"
        assert total > ROTATIONS * 4, f"only {total} requests completed"
        assert _served_serial(port) == ROTATIONS + 1
    finally:
        stop.set()
        wh.stop()
