"""Observability plane (ISSUE 6): tracer, flight recorder, JSON-lines
logging, the /metrics snapshot-render fix, and the end-to-end span-tree
acceptance over the real serving plane.

Layers:
  * tracer unit contracts — lock-light per-thread buffers drained into
    a bounded ring, drop accounting on BOTH bounds, implicit parenting,
    disabled == near-free no-op;
  * flight recorder — bounded snapshot files, pruning, span-tail cap;
  * structured logging — JSON lines carrying request_id/replica/
    component via extra= and thread-local context;
  * the satellite regression: /metrics render must never hold the
    registry lock while formatting (a slow scraper must not stall the
    batcher's hot-path observe());
  * ISSUE 6 acceptance: GET /debug/traces?request_id= returns a span
    tree covering queue→admit→per-step→retire for a completed request
    in sync AND pipelined modes, on Synthetic AND real jitted Local
    executors; every response carries X-Request-Id.

The whole lane asserts its own wall budget at the end (docs/ci.md).
"""

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from io import StringIO

import pytest

from dpu_operator_tpu import faults
from dpu_operator_tpu.obs import FlightRecorder, Tracer
from dpu_operator_tpu.obs import logging as obs_logging
from dpu_operator_tpu.obs import trace as obs_trace
from dpu_operator_tpu.serving import ServingServer, SyntheticExecutor
from dpu_operator_tpu.utils.metrics import Registry

# Lane clock starts when the FIRST test in this module RUNS — not at
# import: pytest imports every module during collection, so an
# import-time stamp would charge this lane for every suite that runs
# before it in a full tier-1 pass.
_LANE_T0: list = []


@pytest.fixture(autouse=True)
def _lane_clock():
    if not _LANE_T0:
        _LANE_T0.append(time.perf_counter())
    yield

MODEL = dict(S=1, d=8, h=8, E=1)


# -- tracer unit contracts ----------------------------------------------------


def test_span_nesting_and_explicit_parenting():
    tr = Tracer()
    with tr.span("outer", request_id="r1") as outer:
        with tr.span("inner") as inner:
            assert inner.parent_id == outer.span_id
        ev_id = tr.event("mark", request_id="r1",
                         parent_id=outer.span_id, attrs={"k": 1})
    spans = tr.spans_snapshot()
    # Snapshot order is start-time order.
    assert [s.name for s in spans] == ["outer", "inner", "mark"]
    mark = next(s for s in spans if s.span_id == ev_id)
    assert mark.kind == "event" and mark.t0 == mark.t1
    tree = tr.span_tree("r1")
    # outer + mark own the rid; inner rides the DESCENDANT closure
    # (ISSUE 11): a child of a request-owned span belongs to the
    # request even when it carries no rid of its own — that is how
    # shard-worker spans reach /debug/traces.
    assert tree["span_count"] == 3


def test_cross_thread_parenting_via_explicit_parent_id():
    tr = Tracer()
    root = tr.start("request", request_id="r2")
    done = threading.Event()

    def worker():
        tr.event("child", request_id="r2", parent_id=root.span_id)
        done.set()

    threading.Thread(target=worker, daemon=True).start()
    assert done.wait(2.0)
    tr.finish(root)
    tree = tr.span_tree("r2")
    assert tree["tree"][0]["name"] == "request"
    assert [c["name"] for c in tree["tree"][0]["children"]] == ["child"]


def test_request_ids_attr_links_shared_spans_into_tree():
    """A decode step serves many requests at once: it carries their ids
    in a request_ids attr and the query attaches it to each occupant's
    tree as a linked child."""
    tr = Tracer()
    root = tr.start("request", request_id="r3")
    tr.finish(root)
    tr.record_span("step.device", 1.0, 2.0,
                   attrs={"request_ids": ["r3", "other"]})
    tree = tr.span_tree("r3")
    (req_root,) = tree["tree"]
    assert [c["name"] for c in req_root["children"]] == ["step.device"]
    assert req_root["children"][0]["linked"] is True
    # The other occupant sees the same span in ITS tree.
    assert tr.span_tree("other")["span_count"] == 1


def test_ring_bound_and_dropped_counter():
    tr = Tracer(capacity=8, per_thread_cap=4)
    for i in range(10):
        tr.event(f"e{i}")
    # Per-thread cap 4: six events never made the buffer.
    assert tr.dropped_total() == 6
    assert len(tr.spans_snapshot()) == 4
    # Now overflow the ring itself: drain between records so the
    # per-thread buffer never fills.
    for i in range(10):
        tr.event(f"ring{i}")
        tr.drain()
    assert len(tr.spans_snapshot()) == 8  # ring capacity
    assert tr.dropped_total() == 6 + 6   # 4 + 10 events into a ring of 8


def test_dead_thread_buffers_drain_and_prune():
    tr = Tracer()

    def worker():
        tr.event("from-dead-thread")

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    spans = tr.spans_snapshot()
    assert [s.name for s in spans] == ["from-dead-thread"]
    # The dead thread's (now empty) buffer is pruned from the registry.
    with tr._lock:
        assert all(b.thread.is_alive() for b in tr._bufs)


def test_metrics_only_scrape_path_prunes_dead_thread_buffers():
    """A production server scraped ONLY via /metrics never calls
    spans_snapshot() — dropped_total() (the scrape path's one tracer
    read) must drain too, or every finished connection thread leaks a
    _ThreadBuf in tr._bufs forever."""
    tr = Tracer()
    for i in range(8):
        t = threading.Thread(target=lambda: tr.event("conn-span"))
        t.start()
        t.join()
    with tr._lock:
        n_before = len(tr._bufs)
    assert n_before == 8  # one registered buffer per dead thread
    assert tr.dropped_total() == 0
    with tr._lock:
        assert not tr._bufs  # drained into the ring AND pruned
    assert len(tr.spans_snapshot()) == 8  # spans survived the prune


def test_disabled_tracer_records_nothing():
    tr = Tracer()
    tr.enabled = False
    with tr.span("s") as sp:
        assert obs_trace.is_noop(sp)
    assert tr.event("e") is None
    assert tr.record_span("r", 0.0, 1.0) is None
    tr.decision("d")
    tr.enabled = True
    assert tr.spans_snapshot() == []
    assert tr.decisions_snapshot() == []


def test_scoped_tracer_installs_and_restores():
    before = obs_trace.get_tracer()
    with obs_trace.scoped() as tr:
        assert obs_trace.get_tracer() is tr is not before
        obs_trace.event("inside")
        assert [s.name for s in tr.spans_snapshot()] == ["inside"]
    assert obs_trace.get_tracer() is before


def test_decision_log_is_bounded():
    tr = Tracer(decision_cap=4)
    for i in range(10):
        tr.decision("admit", slot=i)
    decs = tr.decisions_snapshot()
    assert len(decs) == 4 and decs[-1]["slot"] == 9


def test_fault_firing_becomes_span_event():
    with obs_trace.scoped() as tr:
        with faults.injected() as plan:
            plan.inject("obs.site", exc=faults.FaultError, at_calls=[1])
            with pytest.raises(faults.FaultError):
                faults.fire("obs.site")
        (ev,) = [s for s in tr.spans_snapshot()
                 if s.name == "fault.fired"]
        assert ev.attrs["site"] == "obs.site"
        assert ev.attrs["behavior"] == "raise"


# -- flight recorder ----------------------------------------------------------


def test_flight_snapshot_writes_bounded_pruned_files(tmp_path):
    with obs_trace.scoped() as tr:
        for i in range(10):
            tr.event(f"pre{i}")
        rec = FlightRecorder(flight_dir=str(tmp_path), keep=3,
                             max_spans=5)
        paths = [rec.snapshot(f"test{i}")["path"] for i in range(5)]
        assert all(p for p in paths)
        files = sorted(tmp_path.glob("flight-*.json"))
        assert len(files) == 3  # pruned to keep
        data = json.loads(files[-1].read_text())
        assert data["reason"] == "test4"
        assert len(data["spans"]) == 5  # max_spans tail
        assert data["spans_truncated"] == 5
        # The tail is the RECENT end of the ring.
        assert data["spans"][-1]["name"] == "pre9"


def test_flight_snapshot_on_demand_no_write(tmp_path):
    with obs_trace.scoped() as tr:
        tr.event("x")
        rec = FlightRecorder(flight_dir=str(tmp_path))
        data = rec.snapshot("on_demand", write=False)
        assert "path" not in data and len(data["spans"]) == 1
        assert list(tmp_path.iterdir()) == []


def test_flight_counts_snapshots_in_registry(tmp_path):
    reg = Registry()
    with obs_trace.scoped():
        rec = FlightRecorder(flight_dir=str(tmp_path), registry=reg)
        rec.snapshot("wedged", write=False)
    assert reg.counter_value("serving_flight_snapshots_total",
                             {"reason": "wedged"}) == 1.0


# -- structured logging -------------------------------------------------------


def _emit_json_line(emit):
    buf = StringIO()
    root = logging.getLogger()
    prev_level = root.level
    handler = obs_logging.setup("testcomp", stream=buf)
    try:
        emit(logging.getLogger("obs.under.test"))
    finally:
        root.removeHandler(handler)
        root.setLevel(prev_level)
    lines = [json.loads(l) for l in buf.getvalue().splitlines()]
    return lines


def test_json_lines_formatter_fields():
    (line,) = _emit_json_line(
        lambda log: log.warning("hello %s", "world",
                                extra={"request_id": "abc123"}))
    assert line["msg"] == "hello world"
    assert line["level"] == "WARNING"
    assert line["component"] == "testcomp"
    assert line["request_id"] == "abc123"
    assert line["logger"] == "obs.under.test"
    assert "replica" not in line  # absent != empty


def test_context_binding_stamps_thread_records():
    def emit(log):
        with obs_logging.context(replica="replica7"):
            log.info("inside")
            # Explicit extra= wins over the bound context.
            log.info("explicit", extra={"replica": "replica9"})
        log.info("outside")

    inside, explicit, outside = _emit_json_line(emit)
    assert inside["replica"] == "replica7"
    assert explicit["replica"] == "replica9"
    assert "replica" not in outside


def test_exception_lands_in_exc_field():
    def emit(log):
        try:
            raise ValueError("boom")
        except ValueError:
            log.exception("failed", extra={"request_id": "r"})

    (line,) = _emit_json_line(emit)
    assert "ValueError: boom" in line["exc"]
    # The line itself is still one parseable JSON object (the whole
    # point of the format).
    assert "\n" not in json.dumps(line["msg"])


# -- satellite: /metrics render must not hold the lock while formatting -------


class _SlowLabel(str):
    started = threading.Event()

    def __str__(self):
        _SlowLabel.started.set()
        time.sleep(0.5)
        return "slow-" + super().__str__()


def test_slow_scraper_does_not_stall_hot_path_observe():
    """Regression (pre-fix failure): render() formatted inside the
    registry lock, so a scrape that was slow to stringify (or merely a
    big registry) blocked every batcher-thread observe() for the full
    render. Render now snapshots under the lock and formats outside:
    an observe() racing a 0.5 s-slow render completes in
    milliseconds."""
    _SlowLabel.started.clear()
    reg = Registry()
    reg.gauge_set("obs_slow_gauge", 1.0, {"l": _SlowLabel("x")})
    reg.observe("obs_hot_hist", 0.5)

    rendered = {}

    def scrape():
        rendered["out"] = reg.render()

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    assert _SlowLabel.started.wait(2.0), "render never reached the label"
    t0 = time.perf_counter()
    reg.observe("obs_hot_hist", 0.7)
    reg.counter_inc("obs_hot_counter")
    blocked = time.perf_counter() - t0
    t.join(timeout=5.0)
    assert blocked < 0.2, (
        f"hot-path observe blocked {blocked:.3f}s behind a slow scrape")
    assert 'l="slow-x"' in rendered["out"]


# -- acceptance: span trees over the real serving plane -----------------------


def _post(url, body, timeout=15):
    data = json.dumps(body).encode()
    r = urllib.request.urlopen(
        urllib.request.Request(url + "/v1/generate", data=data),
        timeout=timeout)
    return r, json.loads(r.read())


def _get_json(url, timeout=5):
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _tree_names(tree):
    names = []

    def walk(n):
        names.append(n["name"])
        for c in n["children"]:
            walk(c)

    for n in tree["tree"]:
        walk(n)
    return names


# queue→admit→per-step→retire: the ISSUE 6 acceptance span chain.
_REQUIRED = {"request", "queue.enqueue", "queue.wait", "batcher.admit",
             "step.device", "batcher.retire"}


def _assert_trace_contract(srv, pipelined: bool):
    r, body = _post(srv.url, {"prompt": "trace-me", "max_tokens": 4,
                              "deadline_ms": 20000})
    rid = body["id"]
    assert r.headers.get("X-Request-Id") == rid
    code, tree = _get_json(
        srv.url + f"/debug/traces?request_id={rid}")
    assert code == 200
    names = _tree_names(tree)
    missing = _REQUIRED - set(names)
    assert not missing, f"span tree missing {missing}: {names}"
    if pipelined:
        assert "executor.submit" in names
        assert "executor.collect" in names
    # One root: the request span, carrying the outcome.
    (root,) = tree["tree"]
    assert root["name"] == "request"
    assert root["attrs"]["outcome"] == "ok"
    assert root["attrs"]["code"] == 200
    # Steps are ordered inside the request window and admit precedes
    # retire.
    by_name = {}
    for n in root["children"]:
        by_name.setdefault(n["name"], []).append(n)
    admit = by_name["batcher.admit"][0]
    retire = by_name["batcher.retire"][0]
    assert admit["t0"] <= retire["t0"]
    assert admit["attrs"]["pipelined"] is pipelined
    # Every decode step span names this request as an occupant.
    for step in by_name["step.device"]:
        assert rid in step["attrs"]["request_ids"]
    return tree


@pytest.mark.parametrize("pipelined", [False, True],
                         ids=["sync", "pipelined"])
def test_debug_traces_synthetic(pipelined):
    with obs_trace.scoped():
        srv = ServingServer(
            [SyntheticExecutor(slots=2, d=8, step_time_s=0.002,
                               pipelined=pipelined)]).start()
        try:
            _assert_trace_contract(srv, pipelined)
        finally:
            srv.stop()


@pytest.fixture(scope="module")
def local_executors():
    """One compiled LocalExecutor per mode (compile cost dominates;
    reuse across tests is safe — each pool reset()s at start)."""
    from dpu_operator_tpu.serving import LocalExecutor

    return {"sync": LocalExecutor(slots=2, mode="sync", **MODEL),
            "pipelined": LocalExecutor(slots=2, mode="pipelined",
                                       **MODEL)}


@pytest.mark.parametrize("mode", ["sync", "pipelined"])
def test_debug_traces_local_jitted(mode, local_executors):
    """The same queue→admit→per-step→retire tree over the REAL jitted
    model — the trace layer must not depend on the synthetic double."""
    with obs_trace.scoped():
        srv = ServingServer([local_executors[mode]]).start()
        try:
            _assert_trace_contract(srv, mode == "pipelined")
        finally:
            srv.stop()


def test_debug_traces_bad_requests():
    with obs_trace.scoped():
        srv = ServingServer([SyntheticExecutor(slots=1, d=8)]).start()
        try:
            code, body = _get_json(srv.url + "/debug/traces")
            assert code == 400 and "request_id" in body["error"]
            code, _body = _get_json(
                srv.url + "/debug/traces?request_id=nope")
            assert code == 404
        finally:
            srv.stop()


def test_debug_flight_on_demand_over_http():
    with obs_trace.scoped():
        srv = ServingServer(
            [SyntheticExecutor(slots=1, d=8)]).start()
        try:
            _post(srv.url, {"prompt": "f", "max_tokens": 2,
                            "deadline_ms": 10000})
            code, data = _get_json(srv.url + "/debug/flight")
            assert code == 200
            assert data["reason"] == "on_demand"
            assert any(s["name"] == "request" for s in data["spans"])
            assert any(d["kind"] == "admit"
                       for d in data["decisions"])
        finally:
            srv.stop()


def test_trace_dropped_counter_on_metrics():
    """The ring bound is PROVEN at scrape time: a tracer sized to drop
    must surface a nonzero serving_trace_dropped_total; an unpressured
    one still exports the series at 0."""
    tiny = Tracer(capacity=16, per_thread_cap=2)
    with obs_trace.scoped(tiny):
        srv = ServingServer(
            [SyntheticExecutor(slots=2, d=8, step_time_s=0.001)],
            tracer=tiny).start()
        try:
            _post(srv.url, {"prompt": "d", "max_tokens": 8,
                            "deadline_ms": 10000})
            text = urllib.request.urlopen(
                srv.url + "/metrics", timeout=5).read().decode()
            val = next(
                float(l.rsplit(" ", 1)[1]) for l in text.splitlines()
                if l.startswith("serving_trace_dropped_total"))
            assert val > 0
        finally:
            srv.stop()
    with obs_trace.scoped():
        srv = ServingServer([SyntheticExecutor(slots=1, d=8)]).start()
        try:
            text = urllib.request.urlopen(
                srv.url + "/metrics", timeout=5).read().decode()
            assert "serving_trace_dropped_total 0.0" in text
        finally:
            srv.stop()


# -- cross-process tracing plane (ISSUE 11) -----------------------------------


def test_clock_sync_midpoint_bound_property():
    """Property test of the NTP four-timestamp estimator: for random
    true offsets and ASYMMETRIC wire delays, the estimate must land
    within its own published uncertainty of the truth, and aligning a
    causally-ordered cross-process pair (send happens-before receive)
    must preserve order within that uncertainty."""
    import random

    from dpu_operator_tpu.obs.xproc import ClockSync

    rng = random.Random(11)
    for _case in range(200):
        true_offset = rng.uniform(-500.0, 500.0)
        sync = ClockSync(window=8)
        for _s in range(5):
            t_tx = rng.uniform(0, 1000.0)
            d_fwd = rng.uniform(0.0001, 0.02)   # asymmetric on
            d_bwd = rng.uniform(0.0001, 0.02)   # purpose
            proc = rng.uniform(0.0, 0.05)       # remote step time
            t_rx_remote = t_tx + d_fwd + true_offset
            t_tx_remote = t_rx_remote + proc
            t_rx_local = t_tx_remote - true_offset + d_bwd
            sync.observe(t_tx, t_rx_remote, t_tx_remote, t_rx_local)
        off, unc = sync.estimate
        assert sync.ready
        assert abs(off - true_offset) <= unc + 1e-9, (
            f"estimate {off} missed true {true_offset} "
            f"past its own uncertainty {unc}")
        # Causal order: a local event at t, then a remote event whose
        # true time is t + gap. Aligned via the estimate, order must
        # hold whenever gap exceeds the uncertainty.
        t_local_event = 100.0
        gap = 2.01 * unc + 1e-6
        t_remote_event = t_local_event + gap + true_offset
        aligned = sync.to_local(t_remote_event)
        assert aligned + unc >= t_local_event, (
            "causally-later remote event aligned before the local "
            "one past the stamped uncertainty")


def test_clock_sync_rejects_causality_violating_samples():
    from dpu_operator_tpu.obs.xproc import ClockSync

    sync = ClockSync()
    # Reply arrives "before" the request net of processing: garbage.
    sync.observe(10.0, 500.0, 500.0, 9.0)
    assert not sync.ready
    assert sync.estimate == (0.0, float("inf"))


def test_span_ship_bounds_and_counts_losses():
    """The piggyback buffer contract: bounded, losses COUNTED (the
    satellite's loss-counter-nonzero-under-pressure case), filter
    keeps per-chunk fabric noise out."""
    from dpu_operator_tpu.obs.xproc import SpanShip

    tr = Tracer()
    for i in range(6):
        tr.record_span("shard.compute", float(i), float(i) + 0.5,
                       attrs={"rank": 0, "step": i})
    # Wire noise that must be filtered, not shipped:
    tr.record_span("fabric.send", 0.0, 0.1, attrs={"rank": 0})
    ship = SpanShip(cap=4)
    shipped = ship.harvest(tr)
    assert shipped == 4
    assert ship.dropped_total == 2  # 6 shippable - cap
    wire = ship.flush()
    assert len(wire) == 4 and len(ship) == 0
    assert all(w[0] == "shard.compute" for w in wire)
    # harvest CONSUMED the tracer ring (exactly-once shipping).
    assert tr.spans_snapshot() == []


def test_ingest_remaps_ids_shifts_clock_and_stamps():
    """Tracer.ingest: shipment-local ids remap to fresh local ids,
    in-shipment parent links follow, a parent the shipment lost is
    dropped (never aliased onto a local span), a coordinator-space
    parent rides attrs['xparent'] verbatim, timestamps shift by
    -offset, and the stamp attrs land on every span."""
    tr = Tracer()
    local_parent = tr.reserve_id()
    # Worker-local ids 1 and 2 deliberately collide with the
    # coordinator's own counter values.
    wires = [
        ["shard.compute", 1, None, None, "span", 100.0, 100.5,
         {"rank": 3, "xparent": local_parent}],
        ["shard.reduce_blocked", 2, 1, None, "span", 100.1, 100.2,
         {"rank": 3}],
        ["shard.encode", 3, 999, None, "span", 100.3, 100.4,
         {"rank": 3}],  # parent 999 was lost to the worker's buffer
    ]
    n = tr.ingest(wires, offset=90.0,
                  attrs={"clock_offset_s": 90.0, "clock_unc_s": 0.01})
    assert n == 3
    spans = {s.name: s for s in tr.spans_snapshot()}
    comp = spans["shard.compute"]
    red = spans["shard.reduce_blocked"]
    enc = spans["shard.encode"]
    assert comp.span_id not in (1, 2, 3)
    assert comp.parent_id == local_parent      # xparent passthrough
    assert red.parent_id == comp.span_id       # in-shipment remap
    assert enc.parent_id is None               # lost parent dropped
    assert abs(comp.t0 - 10.0) < 1e-9          # shifted onto our axis
    for s in (comp, red, enc):
        assert s.attrs["clock_offset_s"] == 90.0
        assert s.attrs["clock_unc_s"] == 0.01
        assert s.attrs["rank"] == 3


def test_record_span_with_reserved_id_parents_children():
    """The reserve-then-record pattern the coordinator's shard.step
    (and every shard.compute) uses: children recorded BEFORE the
    parent still nest under it in the tree."""
    tr = Tracer()
    sid = tr.reserve_id()
    tr.record_span("child", 1.0, 2.0, parent_id=sid)
    got = tr.record_span("parent", 0.5, 3.0, request_id="rq",
                         span_id=sid)
    assert got == sid
    tree = tr.span_tree("rq")
    assert tree["span_count"] == 2
    (root,) = tree["tree"]
    assert root["name"] == "parent"
    assert [c["name"] for c in root["children"]] == ["child"]


def test_debug_traces_recent_listing():
    """?recent=N: the discoverability mode — the most recently active
    request ids, newest first, without needing an X-Request-Id."""
    with obs_trace.scoped():
        srv = ServingServer(
            [SyntheticExecutor(slots=2, d=8,
                               step_time_s=0.001)]).start()
        try:
            rids = []
            for i in range(2):
                _r, body = _post(srv.url,
                                 {"prompt": f"recent-{i}",
                                  "max_tokens": 2,
                                  "deadline_ms": 10000})
                rids.append(body["id"])
            code, data = _get_json(srv.url + "/debug/traces?recent=5")
            assert code == 200
            listed = [e["request_id"] for e in data["recent"]]
            assert set(rids) <= set(listed)
            for e in data["recent"]:
                assert e["spans"] > 0 and e["t_last"] >= e["t0"]
            # Newest-first ordering.
            lasts = [e["t_last"] for e in data["recent"]]
            assert lasts == sorted(lasts, reverse=True)
            code, _ = _get_json(srv.url + "/debug/traces?recent=0")
            assert code == 400
            code, _ = _get_json(srv.url + "/debug/traces?recent=x")
            assert code == 400
        finally:
            srv.stop()


def test_debug_traces_unknown_id_stable_404_under_concurrent_drain():
    """The satellite contract: an unknown-but-well-formed request id
    answers a STABLE 404 while other threads drain/record
    concurrently — never a 500, never a half-drained partial tree."""
    with obs_trace.scoped() as tr:
        srv = ServingServer(
            [SyntheticExecutor(slots=2, d=8,
                               step_time_s=0.0005)]).start()
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                tr.record_span("noise", float(i), float(i) + 0.1,
                               request_id=f"other-{i % 7}")
                if i % 5 == 0:
                    tr.drain()
                if i % 11 == 0:
                    tr.spans_snapshot()
                i += 1

        threads = [threading.Thread(target=churn, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        try:
            _post(srv.url, {"prompt": "seed", "max_tokens": 2,
                            "deadline_ms": 10000})
            for _ in range(60):
                code, body = _get_json(
                    srv.url + "/debug/traces?request_id=req-unknown")
                assert code == 404, (code, body)
                assert "req-unknown" in body["error"]
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
            srv.stop()


def test_flight_shards_section_groups_rank_tails(tmp_path):
    """FlightRecorder snapshots grow a `shards` section: every
    rank-attributed span grouped per rank, tail-bounded PER RANK and
    taken before the main-tail truncation — the victim rank's last
    moments survive a flooded coordinator ring."""
    with obs_trace.scoped() as tr:
        sid = tr.reserve_id()
        tr.record_span("shard.step", 1.0, 2.0, span_id=sid,
                       attrs={"replica": "r0", "step": 1})
        for rank in (0, 1):
            tr.record_span("shard.compute", 1.1, 1.9, parent_id=sid,
                           attrs={"rank": rank, "step": 1})
            tr.record_span("shard.reduce_blocked", 1.2, 1.5,
                           attrs={"rank": rank, "step": 1})
        # Flood the main tail with un-ranked coordinator spans.
        rec = FlightRecorder(flight_dir=str(tmp_path), max_spans=4,
                             shard_tail=8)
        for i in range(50):
            tr.record_span("step.host", 2.0 + i, 2.1 + i,
                           attrs={"replica": "r0"})
        snap = rec.snapshot("chaos", write=False)
        assert set(snap["shards"]) == {"0", "1"}
        for rank in ("0", "1"):
            names = [s["name"] for s in snap["shards"][rank]]
            assert names == ["shard.compute", "shard.reduce_blocked"]
        # The main tail truncated away the shard spans — the shards
        # section is exactly what preserved them.
        assert all(s["name"] == "step.host" for s in snap["spans"])


def test_obs_lane_wall_budget():
    """The whole obs lane (tracer units + jitted-model acceptance)
    must fit its documented tier-1 budget (docs/ci.md: ~9 s measured,
    60 s ceiling) — an observability lane that balloons CI is the
    overhead problem wearing a different hat. Runs last in file order
    (tier-1 runs -p no:randomly)."""
    elapsed = time.perf_counter() - _LANE_T0[0]
    assert elapsed < 60.0, f"obs lane took {elapsed:.1f}s (budget 60s)"
