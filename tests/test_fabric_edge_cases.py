"""Fabric dataplane edge cases: idempotent re-ADD (kubelet retries),
rollback on mid-ADD failure, IPAM exhaustion, DEL idempotency — the
behaviors the reference guards in sriov.go (NetConf cache, vfReleased
gate) and networkfn.go (rollback protocol)."""

import subprocess
import uuid

import pytest

from dpu_operator_tpu.cni.dataplane.fabric import FabricDataplane
from dpu_operator_tpu.cni.ipam import HostLocalIpam, IpamError
from dpu_operator_tpu.cni.statestore import StateStore
from dpu_operator_tpu.cni.types import CniError, CniRequest


@pytest.fixture
def pod_ns(netns):
    ns = "fe-" + uuid.uuid4().hex[:8]
    subprocess.run(["ip", "netns", "add", ns], check=True)
    yield ns
    subprocess.run(["ip", "netns", "del", ns], capture_output=True)


@pytest.fixture
def dataplane(tmp_path):
    return FabricDataplane(
        StateStore(str(tmp_path / "state")),
        HostLocalIpam(str(tmp_path / "ipam"), "10.77.0.0/29"),  # 6 usable hosts (no gw)
    )


def _req(ns, cid=None, command="ADD"):
    return CniRequest(
        command=command,
        container_id=cid or ("fec" + uuid.uuid4().hex[:12]),
        netns=ns,
        ifname="net1",
        config={"cniVersion": "1.0.0", "name": "t", "type": "dpu-cni"},
    )


def test_re_add_is_idempotent(dataplane, pod_ns):
    """kubelet retries ADD after a timeout; the second ADD must return
    the SAME result (ip/mac) without double-allocating
    (reference NetConf disk cache, sriov.go:492-503)."""
    req = _req(pod_ns)
    first = dataplane.cmd_add(req)
    second = dataplane.cmd_add(req)
    assert first.to_json() == second.to_json()
    # Only one lease consumed.
    out = subprocess.run(
        ["ip", "-n", pod_ns, "-j", "addr", "show", "dev", "net1"],
        capture_output=True, text=True, check=True,
    ).stdout
    assert first.ips[0]["address"].split("/")[0] in out
    dataplane.cmd_del(_req(pod_ns, req.container_id, "DEL"))


def test_add_rolls_back_on_ifname_conflict(dataplane, pod_ns):
    """If the pod netns already has an interface with the requested name
    (and no recorded state), the ADD fails and leaves no host-side veth
    or lease behind."""
    subprocess.run(
        ["ip", "-n", pod_ns, "link", "add", "net1", "type", "veth",
         "peer", "name", "net1p"],
        check=True,
    )
    req = _req(pod_ns)
    with pytest.raises(CniError):
        dataplane.cmd_add(req)
    # No stranded host interface.
    from dpu_operator_tpu.cni.dataplane.fabric import _host_ifname

    host_if = _host_ifname(req.container_id, "net1")
    r = subprocess.run(["ip", "link", "show", "dev", host_if], capture_output=True)
    assert r.returncode != 0, "host veth leaked after rollback"
    # Lease released: all 6 of the /29's usable leases must still be
    # allocatable afterwards.
    for i in range(6):
        dataplane._ipam.allocate(f"probe{i}")


def test_ipam_exhaustion_fails_cleanly(dataplane, netns):
    """Range exhaustion surfaces as a CNI error and releases nothing it
    shouldn't (reference ipam delegation failure path, sriov.go:426-487)."""
    namespaces = []
    reqs = []
    try:
        for i in range(6):  # /29 with no gateway = 6 usable leases
            ns = "fx%d-" % i + uuid.uuid4().hex[:6]
            subprocess.run(["ip", "netns", "add", ns], check=True)
            namespaces.append(ns)
            req = _req(ns)
            reqs.append(req)
            dataplane.cmd_add(req)
        ns = "fxover-" + uuid.uuid4().hex[:6]
        subprocess.run(["ip", "netns", "add", ns], check=True)
        namespaces.append(ns)
        over = _req(ns)
        with pytest.raises(CniError, match="exhausted|ADD failed"):
            dataplane.cmd_add(over)
        # A DEL frees a lease and the ADD then succeeds.
        dataplane.cmd_del(_req(reqs[0].netns, reqs[0].container_id, "DEL"))
        result = dataplane.cmd_add(over)
        assert result.ips
        dataplane.cmd_del(_req(ns, over.container_id, "DEL"))
        for req in reqs[1:]:
            dataplane.cmd_del(_req(req.netns, req.container_id, "DEL"))
    finally:
        for ns in namespaces:
            subprocess.run(["ip", "netns", "del", ns], capture_output=True)


def test_del_without_state_is_idempotent(dataplane, pod_ns):
    result, released = dataplane.cmd_del(_req(pod_ns, command="DEL"))
    assert released is False  # gates DeleteBridgePort (sriov.go:507-593)


def test_del_releases_and_gates(dataplane, pod_ns):
    req = _req(pod_ns)
    dataplane.cmd_add(req)
    _, released = dataplane.cmd_del(_req(pod_ns, req.container_id, "DEL"))
    assert released is True
    # Second DEL: idempotent, no release signal.
    _, released2 = dataplane.cmd_del(_req(pod_ns, req.container_id, "DEL"))
    assert released2 is False


def test_cni_check_semantics(dataplane, pod_ns):
    """CHECK passes on an intact attachment, errors after teardown or for
    unknown containers (CNI spec; reference forwards CHECK as no-op —
    this is the stronger implementation)."""
    req = _req(pod_ns)
    dataplane.cmd_add(req)
    assert dataplane.cmd_check(_req(pod_ns, req.container_id, "CHECK")) == {}
    # Break the attachment: remove the pod interface.
    subprocess.run(["ip", "-n", pod_ns, "link", "del", "net1"], check=True)
    with pytest.raises(CniError, match="missing"):
        dataplane.cmd_check(_req(pod_ns, req.container_id, "CHECK"))
    dataplane.cmd_del(_req(pod_ns, req.container_id, "DEL"))
    with pytest.raises(CniError, match="no recorded attachment"):
        dataplane.cmd_check(_req(pod_ns, req.container_id, "CHECK"))
