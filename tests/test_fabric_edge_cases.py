"""Fabric dataplane edge cases: idempotent re-ADD (kubelet retries),
rollback on mid-ADD failure, IPAM exhaustion, DEL idempotency — the
behaviors the reference guards in sriov.go (NetConf cache, vfReleased
gate) and networkfn.go (rollback protocol)."""

import subprocess
import uuid

import pytest

from dpu_operator_tpu.cni.dataplane.fabric import FabricDataplane
from dpu_operator_tpu.cni.ipam import HostLocalIpam, IpamError
from dpu_operator_tpu.cni.statestore import StateStore
from dpu_operator_tpu.cni.types import CniError, CniRequest


@pytest.fixture
def pod_ns(netns):
    ns = "fe-" + uuid.uuid4().hex[:8]
    subprocess.run(["ip", "netns", "add", ns], check=True)
    yield ns
    subprocess.run(["ip", "netns", "del", ns], capture_output=True)


@pytest.fixture
def dataplane(tmp_path):
    return FabricDataplane(
        StateStore(str(tmp_path / "state")),
        HostLocalIpam(str(tmp_path / "ipam"), "10.77.0.0/29"),  # 6 usable hosts (no gw)
    )


def _req(ns, cid=None, command="ADD"):
    return CniRequest(
        command=command,
        container_id=cid or ("fec" + uuid.uuid4().hex[:12]),
        netns=ns,
        ifname="net1",
        config={"cniVersion": "1.0.0", "name": "t", "type": "dpu-cni"},
    )


def test_re_add_is_idempotent(dataplane, pod_ns):
    """kubelet retries ADD after a timeout; the second ADD must return
    the SAME result (ip/mac) without double-allocating
    (reference NetConf disk cache, sriov.go:492-503)."""
    req = _req(pod_ns)
    first = dataplane.cmd_add(req)
    second = dataplane.cmd_add(req)
    assert first.to_json() == second.to_json()
    # Only one lease consumed.
    out = subprocess.run(
        ["ip", "-n", pod_ns, "-j", "addr", "show", "dev", "net1"],
        capture_output=True, text=True, check=True,
    ).stdout
    assert first.ips[0]["address"].split("/")[0] in out
    dataplane.cmd_del(_req(pod_ns, req.container_id, "DEL"))


def test_add_rolls_back_on_ifname_conflict(dataplane, pod_ns):
    """If the pod netns already has an interface with the requested name
    (and no recorded state), the ADD fails and leaves no host-side veth
    or lease behind."""
    subprocess.run(
        ["ip", "-n", pod_ns, "link", "add", "net1", "type", "veth",
         "peer", "name", "net1p"],
        check=True,
    )
    req = _req(pod_ns)
    with pytest.raises(CniError):
        dataplane.cmd_add(req)
    # No stranded host interface.
    from dpu_operator_tpu.cni.dataplane.fabric import _host_ifname

    host_if = _host_ifname(req.container_id, "net1")
    r = subprocess.run(["ip", "link", "show", "dev", host_if], capture_output=True)
    assert r.returncode != 0, "host veth leaked after rollback"
    # Lease released: all 6 of the /29's usable leases must still be
    # allocatable afterwards.
    for i in range(6):
        dataplane._ipam.allocate(f"probe{i}")


def test_ipam_exhaustion_fails_cleanly(dataplane, netns):
    """Range exhaustion surfaces as a CNI error and releases nothing it
    shouldn't (reference ipam delegation failure path, sriov.go:426-487)."""
    namespaces = []
    reqs = []
    try:
        for i in range(6):  # /29 with no gateway = 6 usable leases
            ns = "fx%d-" % i + uuid.uuid4().hex[:6]
            subprocess.run(["ip", "netns", "add", ns], check=True)
            namespaces.append(ns)
            req = _req(ns)
            reqs.append(req)
            dataplane.cmd_add(req)
        ns = "fxover-" + uuid.uuid4().hex[:6]
        subprocess.run(["ip", "netns", "add", ns], check=True)
        namespaces.append(ns)
        over = _req(ns)
        with pytest.raises(CniError, match="exhausted|ADD failed"):
            dataplane.cmd_add(over)
        # A DEL frees a lease and the ADD then succeeds.
        dataplane.cmd_del(_req(reqs[0].netns, reqs[0].container_id, "DEL"))
        result = dataplane.cmd_add(over)
        assert result.ips
        dataplane.cmd_del(_req(ns, over.container_id, "DEL"))
        for req in reqs[1:]:
            dataplane.cmd_del(_req(req.netns, req.container_id, "DEL"))
    finally:
        for ns in namespaces:
            subprocess.run(["ip", "netns", "del", ns], capture_output=True)


def test_del_without_state_is_idempotent(dataplane, pod_ns):
    result, released = dataplane.cmd_del(_req(pod_ns, command="DEL"))
    assert released is False  # gates DeleteBridgePort (sriov.go:507-593)


def test_del_releases_and_gates(dataplane, pod_ns):
    req = _req(pod_ns)
    dataplane.cmd_add(req)
    _, released = dataplane.cmd_del(_req(pod_ns, req.container_id, "DEL"))
    assert released is True
    # Second DEL: idempotent, no release signal.
    _, released2 = dataplane.cmd_del(_req(pod_ns, req.container_id, "DEL"))
    assert released2 is False


def test_cni_check_semantics(dataplane, pod_ns):
    """CHECK passes on an intact attachment, errors after teardown or for
    unknown containers (CNI spec; reference forwards CHECK as no-op —
    this is the stronger implementation)."""
    req = _req(pod_ns)
    dataplane.cmd_add(req)
    assert dataplane.cmd_check(_req(pod_ns, req.container_id, "CHECK")) == {}
    # Break the attachment: remove the pod interface.
    subprocess.run(["ip", "-n", pod_ns, "link", "del", "net1"], check=True)
    with pytest.raises(CniError, match="missing"):
        dataplane.cmd_check(_req(pod_ns, req.container_id, "CHECK"))
    dataplane.cmd_del(_req(pod_ns, req.container_id, "DEL"))
    with pytest.raises(CniError, match="no recorded attachment"):
        dataplane.cmd_check(_req(pod_ns, req.container_id, "CHECK"))


def test_ipam_range_start_end_exclude(tmp_path):
    """Upstream host-local grammar: rangeStart/rangeEnd bound allocation,
    exclude carves addresses out, gateway is never handed out."""
    ipam = HostLocalIpam(
        str(tmp_path / "ipam2"), "10.88.0.0/28",
        gateway="10.88.0.1",
        range_start="10.88.0.4", range_end="10.88.0.7",
        exclude=["10.88.0.5", "10.88.0.6/31"],
    )
    # Range 4..7; .5 excluded singly, .6 and .7 via the /31 → only .4 left.
    assert ipam.allocate("own0")[0] == "10.88.0.4/28"
    with pytest.raises(IpamError, match="exhausted"):
        ipam.allocate("own2")
    ipam.release("own0")
    assert ipam.allocate("own3")[0].startswith("10.88.0.4/")

    with pytest.raises(IpamError, match="rangeStart"):
        HostLocalIpam(str(tmp_path / "ipam3"), "10.88.0.0/28",
                      range_start="10.99.0.1")


FAKE_IPAM = """#!/bin/sh
# Fake external CNI IPAM plugin: records its invocation env + stdin,
# answers ADD with a canned CNI result, DEL with nothing.
echo "cmd=$CNI_COMMAND cid=$CNI_CONTAINERID ifname=$CNI_IFNAME netns=$CNI_NETNS" >> "$IPAM_LOG"
cat >> "$IPAM_LOG.stdin"
if [ "$CNI_COMMAND" = "ADD" ]; then
  printf '{"ips":[{"address":"10.91.0.7/24","gateway":"10.91.0.1"}],"routes":[{"dst":"192.168.91.0/24","gw":"10.91.0.1"}]}'
fi
"""


def _delegated_req(ns, tmp_path, ipam_type="whereabouts"):
    req = _req(ns)
    req.config["ipam"] = {"type": ipam_type,
                          "range": "10.91.0.0/24"}  # foreign grammar
    return req


def test_delegated_ipam_execs_external_plugin(dataplane, pod_ns, tmp_path,
                                              monkeypatch):
    """A NAD whose ipam.type is not the native grammar must be delegated
    to the named CNI IPAM binary via per-request env + config-on-stdin
    (reference sriov.go:426-487): its result addresses/routes are
    plumbed, and DEL invokes the plugin again for release."""
    import json as _json
    import os as _os

    bindir = tmp_path / "cnibin"
    bindir.mkdir()
    plug = bindir / "whereabouts"
    plug.write_text(FAKE_IPAM)
    plug.chmod(0o755)
    log = tmp_path / "ipam.log"
    monkeypatch.setenv("CNI_PATH", str(bindir))
    monkeypatch.setenv("IPAM_LOG", str(log))

    req = _delegated_req(pod_ns, tmp_path)
    result = dataplane.cmd_add(req)
    assert result.ips[0]["address"] == "10.91.0.7/24"
    # The plugin, not our allocator, owns the lease: no native lease file.
    assert not list((tmp_path / "ipam").glob("ipam-10.91*")), (
        "native allocator touched a delegated range")
    # Address + plugin-returned route are really in the pod netns.
    out = subprocess.run(
        ["ip", "-n", pod_ns, "-j", "addr", "show", "dev", "net1"],
        capture_output=True, text=True, check=True).stdout
    assert "10.91.0.7" in out
    routes = subprocess.run(
        ["ip", "-n", pod_ns, "route"], capture_output=True, text=True,
        check=True).stdout
    assert "192.168.91.0/24" in routes
    # Env-passing protocol: ADD seen with our container identifiers, and
    # the FULL net conf (incl. the foreign ipam grammar) on stdin.
    entries = log.read_text().strip().splitlines()
    assert entries[0].startswith(f"cmd=ADD cid={req.container_id} "
                                 f"ifname=net1")
    stdin_conf = _json.loads((tmp_path / "ipam.log.stdin").read_text())
    assert stdin_conf["ipam"]["range"] == "10.91.0.0/24"

    dataplane.cmd_del(_del_with_conf(req))
    entries = log.read_text().strip().splitlines()
    assert any(e.startswith(f"cmd=DEL cid={req.container_id}")
               for e in entries), entries


def _del_with_conf(add_req):
    return CniRequest(command="DEL", container_id=add_req.container_id,
                      netns=add_req.netns, ifname=add_req.ifname,
                      config=add_req.config)


def test_delegated_ipam_failure_propagates_stderr(dataplane, pod_ns,
                                                  tmp_path, monkeypatch):
    """A failing external plugin must surface ITS error text (stderr is
    the CNI plugin error contract), and the ADD must roll back clean."""
    bindir = tmp_path / "cnibin"
    bindir.mkdir()
    plug = bindir / "whereabouts"
    plug.write_text("#!/bin/sh\necho 'range 10.91.0.0/24 exhausted' >&2\n"
                    "exit 3\n")
    plug.chmod(0o755)
    monkeypatch.setenv("CNI_PATH", str(bindir))

    req = _delegated_req(pod_ns, tmp_path)
    with pytest.raises(CniError, match="range 10.91.0.0/24 exhausted"):
        dataplane.cmd_add(req)
    # Rollback: no half-plumbed interface left in the pod.
    out = subprocess.run(
        ["ip", "-n", pod_ns, "link", "show", "dev", "net1"],
        capture_output=True, text=True).returncode
    assert out != 0, "net1 left behind after failed delegated ADD"


def test_delegated_ipam_missing_binary_is_clear(dataplane, pod_ns,
                                                tmp_path, monkeypatch):
    monkeypatch.setenv("CNI_PATH", str(tmp_path / "empty"))
    req = _delegated_req(pod_ns, tmp_path, ipam_type="dhcp")
    with pytest.raises(CniError, match="not found in CNI_PATH"):
        dataplane.cmd_add(req)


def _seed_delegated_state(dataplane, req):
    """Record an attachment as if a delegated ADD had completed — the
    DEL-path behaviors under test must hold regardless of whether THIS
    environment can build the veth (hostIf points nowhere, so cmd_del
    skips link teardown and goes straight to the IPAM release)."""
    dataplane._store.save(req.container_id, req.ifname, {
        "containerId": req.container_id,
        "ifname": req.ifname,
        "hostIf": "vepnonexistent",
        "mac": "02:00:00:00:00:99",
        "address": "10.91.0.7/24",
        "gateway": "10.91.0.1",
        "netns": req.netns,
        "owner": f"{req.container_id}/{req.ifname}",
        "sandbox": req.netns,
    })


def test_corrupt_delegated_binary_does_not_break_del_idempotency(
        dataplane, tmp_path, monkeypatch):
    """ADVICE r5 #1: a plugin binary that passes the isfile/X_OK probe
    but fails to EXEC (ENOEXEC on a corrupt file) raises OSError from
    subprocess — which must surface as IpamError and be swallowed by
    both DEL paths, or every kubelet DEL retry re-raises and the pod
    wedges in Terminating."""
    bindir = tmp_path / "cnibin"
    bindir.mkdir()
    plug = bindir / "whereabouts"
    # No shebang, not ELF: execve returns ENOEXEC while the isfile/X_OK
    # probe still passes.
    plug.write_bytes(b"\x00\x01corrupt\x02")
    plug.chmod(0o755)
    monkeypatch.setenv("CNI_PATH", str(bindir))

    req = _delegated_req("ipam-ns-del", tmp_path)
    _seed_delegated_state(dataplane, req)

    # Stateful DEL: must drop the record and report released despite
    # the plugin exec failure.
    _, released = dataplane.cmd_del(_del_with_conf(req))
    assert released, "exec-failed plugin release broke the DEL gate"
    assert dataplane._store.load(req.container_id, req.ifname) is None

    # Stateless DEL (kubelet retry after the state was dropped): same
    # request again must stay idempotent, not raise.
    _, released = dataplane.cmd_del(_del_with_conf(req))
    assert released is False

    # And the failure really is the exec-OSError path, wrapped in the
    # IPAM error contract (not a bare OSError escaping).
    from dpu_operator_tpu.cni.ipam import DelegatedIpam
    with pytest.raises(IpamError, match="exec failed"):
        DelegatedIpam(req.config).release(
            f"{req.container_id}/net1", netns=req.netns)


def test_delegated_release_carries_attachment_netns(
        dataplane, tmp_path, monkeypatch):
    """ADVICE r5 #2: the stateful DEL knows the pod netns — the plugin
    must see it in CNI_NETNS (dhcp-style plugins key lease identity on
    it; "" leaks the lease). The stateless fallback, with no record or
    request netns to consult, keeps ""."""
    bindir = tmp_path / "cnibin"
    bindir.mkdir()
    plug = bindir / "whereabouts"
    plug.write_text(FAKE_IPAM)
    plug.chmod(0o755)
    log = tmp_path / "ipam.log"
    monkeypatch.setenv("CNI_PATH", str(bindir))
    monkeypatch.setenv("IPAM_LOG", str(log))

    req = _delegated_req("ipam-ns-keep", tmp_path)
    _seed_delegated_state(dataplane, req)
    dataplane.cmd_del(_del_with_conf(req))
    dels = [e for e in log.read_text().strip().splitlines()
            if e.startswith("cmd=DEL")]
    assert dels, "plugin never saw the DEL"
    assert f"netns={req.netns}" in dels[0], (
        f"plugin DEL saw the wrong CNI_NETNS: {dels[0]}")

    # Stateless DEL with no netns on the request: "" is all that's left.
    log.write_text("")
    bare = _del_with_conf(req)
    bare.netns = ""
    dataplane.cmd_del(bare)
    dels = [e for e in log.read_text().strip().splitlines()
            if e.startswith("cmd=DEL")]
    assert dels and dels[0].endswith("netns="), dels


def test_nad_level_ipam_config_drives_allocation(dataplane, pod_ns):
    """A NetworkAttachmentDefinition carrying its own `ipam` section
    (subnet + rangeStart + routes) allocates from THAT range — not the
    daemon default — and programs the declared routes in the pod netns."""
    req = _req(pod_ns)
    req.config["ipam"] = {
        "type": "host-local",
        "subnet": "10.89.0.0/24",
        "rangeStart": "10.89.0.50",
        "gateway": "10.89.0.1",
        "routes": [{"dst": "192.168.77.0/24", "gw": "10.89.0.1"}],
    }
    result = dataplane.cmd_add(req)
    addr = result.ips[0]["address"]
    assert addr.startswith("10.89.0.5"), addr
    routes = subprocess.run(
        ["ip", "-n", pod_ns, "route"], capture_output=True, text=True, check=True
    ).stdout
    assert "192.168.77.0/24 via 10.89.0.1" in routes
    assert "default via 10.89.0.1" in routes

    # DEL resolves the same per-NAD allocator and frees the lease.
    del_req = _req(pod_ns, req.container_id, "DEL")
    del_req.config = req.config
    dataplane.cmd_del(del_req)
    ipam, _ = dataplane._ipam_for(req)
    assert ipam.allocate("fresh")[0].startswith("10.89.0.50/"), (
        "lease not released through the per-NAD allocator"
    )


def test_bad_nad_ipam_config_rolls_back_cleanly(dataplane, pod_ns):
    """A malformed NAD ipam section (bad subnet / rangeStart outside the
    range) must surface as a CniError AND leave nothing behind — no pod
    interface, no host veth, no consumed netns (kubelet retries would
    otherwise leak a veth pair per attempt)."""
    from dpu_operator_tpu.cni.dataplane.fabric import _host_ifname

    for bad_ipam in (
        {"subnet": "10.89.0.0/24", "rangeStart": "10.99.0.1"},  # outside
        {"subnet": "not-a-subnet"},                             # ValueError
    ):
        req = _req(pod_ns)
        req.config["ipam"] = bad_ipam
        with pytest.raises(CniError):
            dataplane.cmd_add(req)
        r = subprocess.run(
            ["ip", "-n", pod_ns, "link", "show", "dev", "net1"],
            capture_output=True,
        )
        assert r.returncode != 0, f"pod interface leaked for {bad_ipam}"
        host_if = _host_ifname(req.container_id, "net1")
        r = subprocess.run(["ip", "link", "show", "dev", host_if],
                           capture_output=True)
        assert r.returncode != 0, f"host veth leaked for {bad_ipam}"


def test_ipam_exclude_covers_block_edges(tmp_path):
    """An excluded CIDR excludes ALL its addresses — including the
    block's network/broadcast addresses, which are ordinary allocatable
    hosts of the enclosing range."""
    ipam = HostLocalIpam(
        str(tmp_path / "ipam4"), "10.90.0.0/28", exclude=["10.90.0.4/30"],
    )
    got = {ipam.allocate(f"o{i}")[0].split("/")[0] for i in range(10)}
    assert got == {f"10.90.0.{n}" for n in (1, 2, 3, 8, 9, 10, 11, 12, 13, 14)}
    with pytest.raises(IpamError, match="exhausted"):
        ipam.allocate("over")


def test_stale_lease_gc(tmp_path):
    """Leases whose owner has no recorded attachment (pod died without a
    DEL — daemon crash mid-teardown, node reset) are released at startup
    across EVERY range file, incl. per-NAD allocators' (reference
    PCIAllocator's liveness sweep, pci_allocator.go:25-61)."""
    store = StateStore(str(tmp_path / "state"))
    ipam_dir = str(tmp_path / "leases")
    default = HostLocalIpam(ipam_dir, "10.77.0.0/24")
    nad = HostLocalIpam(ipam_dir, "10.78.0.0/24")

    default.allocate("live1/net1")
    default.allocate("dead1/net1")
    nad.allocate("live1/net2")
    nad.allocate("dead2/net1")
    store.save("live1", "net1", {"containerId": "live1", "ifname": "net1"})
    store.save("live1", "net2", {"containerId": "live1", "ifname": "net2"})

    dp = FabricDataplane(store, default)
    assert dp.gc_stale_leases() == 2
    assert set(default.leases().values()) == {"live1/net1"}
    assert set(nad.leases().values()) == {"live1/net2"}
    # Idempotent.
    assert dp.gc_stale_leases() == 0


def test_stale_lease_gc_fails_safe(tmp_path):
    """GC must not crash on a corrupt lease file (power loss mid-save)
    and must SKIP entirely when the attachment state is unreadable — a
    missing record could belong to a live pod whose address would
    otherwise be handed out twice."""
    import os

    store = StateStore(str(tmp_path / "state"))
    ipam_dir = str(tmp_path / "leases")
    ipam = HostLocalIpam(ipam_dir, "10.79.0.0/24")
    ipam.allocate("dead/net1")
    dp = FabricDataplane(store, ipam)

    # Corrupt range file: skipped with a warning, not a crash.
    with open(os.path.join(ipam_dir, "ipam-10.80.0.0-24.json"), "w") as f:
        f.write("{truncated")
    assert dp.gc_stale_leases() == 1  # the good file still sweeps

    # Corrupt ATTACHMENT record: GC skips everything (fail closed).
    ipam.allocate("dead2/net1")
    attach_dir = os.path.join(str(tmp_path / "state"), "attachments")
    with open(os.path.join(attach_dir, "broken-net1.json"), "w") as f:
        f.write("{nope")
    assert dp.gc_stale_leases() == 0
    assert "dead2/net1" in ipam.leases().values()


def test_default_fabric_mtu_applied_to_both_veth_ends(tmp_path, pod_ns):
    """When the NAD config carries no `mtu`, the node fabric MTU policy
    (utils/mtu.py) sizes both ends of the veth pair; a NAD-level `mtu`
    still wins per network. Measured rationale in BASELINE.md: 1500-byte
    frames cost ~40% of fabric throughput to per-packet CPU."""
    from dpu_operator_tpu.cni import netlink as nl
    from dpu_operator_tpu.cni.dataplane.fabric import _host_ifname

    dp = FabricDataplane(
        StateStore(str(tmp_path / "state")),
        HostLocalIpam(str(tmp_path / "ipam"), "10.78.0.0/29"),
        default_mtu=9000,
    )

    def mtu_of(dev, ns=None):
        return nl.get_link(dev, ns)["mtu"]

    req = _req(pod_ns)
    dp.cmd_add(req)
    host_if = _host_ifname(req.container_id, "net1")
    assert mtu_of("net1", pod_ns) == 9000
    assert mtu_of(host_if) == 9000
    dp.cmd_del(_req(pod_ns, req.container_id, "DEL"))

    # Per-NAD override beats the node default (reference NetConf knob).
    req2 = _req(pod_ns)
    req2.config["mtu"] = 4000
    dp.cmd_add(req2)
    assert mtu_of("net1", pod_ns) == 4000
    dp.cmd_del(_req(pod_ns, req2.container_id, "DEL"))


def test_bridge_pins_fabric_mtu_ports_keep_their_own(netns, tmp_path):
    """TpuFabricDataplane pins the bridge MTU so a small port can't clamp
    everyone else — but it must NOT resize an attached port: the CNI
    sized both veth ends (policy or per-NAD override), and forcing only
    the bridge-side end would make the pair asymmetric (the kernel
    accepts per-end veth MTUs independently; oversized frames then
    vanish at the smaller peer with no error)."""
    from dpu_operator_tpu.cni import netlink as nl
    from dpu_operator_tpu.vsp.tpu_dataplane import TpuFabricDataplane

    bridge = "brM" + uuid.uuid4().hex[:6]
    va = "vm" + uuid.uuid4().hex[:6]
    vb = "vn" + uuid.uuid4().hex[:6]
    subprocess.run(
        ["ip", "link", "add", va, "mtu", "4000",
         "type", "veth", "peer", "name", vb, "mtu", "4000"], check=True
    )
    try:
        dp = TpuFabricDataplane(bridge=bridge, mtu=65535)
        dp.ensure_bridge()

        def mtu_of(dev):
            return nl.get_link(dev)["mtu"]

        assert mtu_of(bridge) == 65535
        dp.attach_port(va, "02:00:00:00:00:aa")
        # Port keeps the MTU the CNI (or NAD override) gave the pair;
        # the pinned bridge stays at the fabric MTU regardless.
        assert mtu_of(va) == 4000
        assert mtu_of(bridge) == 65535
    finally:
        subprocess.run(["ip", "link", "del", va], capture_output=True)
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)


def test_uplink_carries_fabric_mtu_or_clamps(netns, tmp_path):
    """ensure_bridge propagates the fabric MTU to the enslaved uplink —
    a bridge forwarding frames bigger than its uplink's MTU drops them
    silently (L2, no ICMP). veth accepts 65535, so the propagate path
    is observable directly."""
    from dpu_operator_tpu.cni import netlink as nl
    from dpu_operator_tpu.vsp.tpu_dataplane import TpuFabricDataplane

    bridge = "brU" + uuid.uuid4().hex[:6]
    up_a = "uq" + uuid.uuid4().hex[:6]
    up_b = "ur" + uuid.uuid4().hex[:6]
    subprocess.run(
        ["ip", "link", "add", up_a, "type", "veth", "peer", "name", up_b],
        check=True,
    )
    try:
        dp = TpuFabricDataplane(bridge=bridge, uplink=up_a, mtu=65535)
        dp.ensure_bridge()
        assert nl.get_link(up_a)["mtu"] == 65535
    finally:
        subprocess.run(["ip", "link", "del", up_a], capture_output=True)
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)


def test_override_raises_uplink_above_boot_mtu(netns, monkeypatch):
    """The motivating override case: an uplink that boots at a small MTU
    (gVNIC: 1460) with DPU_FABRIC_MTU set higher must be RAISED by
    ensure_bridge — not have the override silently pre-clamped to the
    boot value."""
    from dpu_operator_tpu.cni import netlink as nl
    from dpu_operator_tpu.vsp.tpu_dataplane import TpuFabricDataplane

    bridge = "brR" + uuid.uuid4().hex[:6]
    up_a = "us" + uuid.uuid4().hex[:6]
    up_b = "ut" + uuid.uuid4().hex[:6]
    subprocess.run(
        ["ip", "link", "add", up_a, "mtu", "1460",
         "type", "veth", "peer", "name", up_b, "mtu", "1460"], check=True
    )
    monkeypatch.setenv("DPU_FABRIC_MTU", "9000")
    try:
        dp = TpuFabricDataplane(bridge=bridge, uplink=up_a)
        assert dp.mtu == 9000  # unclamped target
        dp.ensure_bridge()
        assert nl.get_link(up_a)["mtu"] == 9000
        assert nl.get_link(bridge)["mtu"] == 9000
    finally:
        subprocess.run(["ip", "link", "del", up_a], capture_output=True)
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)


def test_rollback_release_failure_logs_not_swallows(dataplane, caplog):
    """Regression (graftlint GL005 triage): _rollback used to wrap the
    ipam release in `except Exception: pass` — a failed release leaked
    the lease with ZERO trace, and even programming errors (a TypeError
    from a bad allocator double) vanished into the same pass. Now the
    legitimate best-effort failures (IpamError, OSError) leave a
    warning carrying the owner identity, and anything else surfaces."""
    import logging

    class ReleaseExplodes:
        delegated = False

        def __init__(self, exc):
            self.exc = exc

        def release(self, owner):
            raise self.exc

    owner = "cid-reg/net1"
    with caplog.at_level(
            logging.WARNING, logger="dpu_operator_tpu.cni.dataplane.fabric"):
        dataplane._rollback("hxreg0", "txreg0", "net1", None, owner,
                            ipam=ReleaseExplodes(IpamError("state dir gone")))
    assert any(owner in r.message and "leaked" in r.message
               for r in caplog.records), caplog.records

    # Corrupt lease-file json raises ValueError from release — an
    # environmental failure, best-effort like the DEL handlers' tuple.
    with caplog.at_level(
            logging.WARNING, logger="dpu_operator_tpu.cni.dataplane.fabric"):
        dataplane._rollback("hxreg0", "txreg0", "net1", None, owner,
                            ipam=ReleaseExplodes(ValueError("bad json")))
    assert any("bad json" in r.message for r in caplog.records)

    # A programming error in the release path must PROPAGATE: the old
    # blanket swallow turned an always-broken rollback into silence.
    with pytest.raises(TypeError):
        dataplane._rollback("hxreg0", "txreg0", "net1", None, owner,
                            ipam=ReleaseExplodes(TypeError("bad allocator")))
