"""Chaos matrix for the self-healing serving plane (ISSUE 5).

Every recovery path is driven by a DETERMINISTIC injected fault
(dpu_operator_tpu.faults: count-triggered, seeded) — never by luck:

  * the acceptance matrix: {step-raise, step-hang, submit-raise,
    collect-hang, device-worker-raise} × {sync, pipelined} ×
    {SyntheticExecutor, real jitted LocalExecutor} — the pool returns
    to full live-replica count, every seized in-flight request
    completes with the token stream an uninjected run produces, and no
    request is settled twice;
  * the watchdog: a hung collect() is detected within its deadline and
    the replica restarts — a wedge the loop itself could never time
    out of;
  * the health contract: /readyz 503 "degraded" while live < quorum
    and back to 200 after recovery; /healthz red only when every
    replica's breaker is open (nothing is ever coming back);
  * the breaker: a flapping replica is parked after K failures in the
    window instead of crash-looping forever.

All tier-1, all wall-time-budgeted (each case asserts its own ceiling;
the lane total is documented in docs/ci.md). SyntheticExecutor keeps
the scheduler-plane cases immune to CI-box noise; the LocalExecutor
cases prove the same contracts over the real jitted model.
"""

import json
import time
import urllib.request
from collections import Counter

import pytest

from dpu_operator_tpu import faults
from dpu_operator_tpu.faults import FaultError, FaultPlan, FaultyExecutor
from dpu_operator_tpu.obs import FlightRecorder
from dpu_operator_tpu.obs import trace as obs_trace
from dpu_operator_tpu.serving import (AdmissionQueue, GenerateRequest,
                                      LocalExecutor, ReplicaPool,
                                      ServingServer, SyntheticExecutor,
                                      encode_prompt)
from dpu_operator_tpu.utils.metrics import Registry

MODEL = dict(S=1, d=8, h=8, E=1)

# Wall ceiling for any single chaos case: generous against CI noise,
# tight enough that a recovery path that waits out a deadline instead
# of healing shows up as a failure, not a slow creep.
CASE_BUDGET_S = 12.0


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    # A plan leaked across tests would inject faults into unrelated
    # suites — UNINSTALL FIRST (so the leak is contained even when we
    # fail), then flag the leaking test loudly.
    leaked = faults.active_plan()
    faults.uninstall()
    assert leaked is None, "test leaked an installed FaultPlan"


@pytest.fixture()
def settle_counts(monkeypatch):
    """Count settles per request id: finish() (fail() funnels through
    it) must run EXACTLY once per request — the no-double-settle
    acceptance check."""
    counts = Counter()
    orig = GenerateRequest.finish

    def counting(self):
        counts[self.request_id] += 1
        orig(self)

    monkeypatch.setattr(GenerateRequest, "finish", counting)
    return counts


def _reqs(n, d, toks, prefix="chaos", deadline_s=60.0):
    return [GenerateRequest(prompt_vec=encode_prompt(f"{prefix}-{i}", d),
                            max_tokens=toks,
                            deadline=time.monotonic() + deadline_s)
            for i in range(n)]


def _run_pool(executors, reqs, *, registry=None, watchdog_s=0.25,
              timeout=20.0, flight_dir=None, **pool_kw):
    q = AdmissionQueue(max_depth=len(reqs) + 1)
    if flight_dir is not None:
        pool_kw["flight_recorder"] = FlightRecorder(
            flight_dir=str(flight_dir))
    pool = ReplicaPool(executors, q, registry=registry,
                       watchdog_s=watchdog_s, restart_backoff_s=0.01,
                       poll_s=0.005, **pool_kw)
    for r in reqs:
        q.submit(r)
    pool.start()
    try:
        for r in reqs:
            assert r.wait(timeout=timeout), "request lost"
        return pool, q
    except BaseException:
        pool.stop()
        raise


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    assert cond(), f"timed out waiting for {msg}"


# -- the FaultPlan itself -----------------------------------------------------


def test_fault_plan_triggers_are_deterministic():
    plan = FaultPlan(seed=7)
    plan.inject("s.a", exc=FaultError, at_calls=[2, 4])
    plan.inject("s.b", exc=FaultError, probability=0.5, times=1)
    hits = []
    for _ in range(5):
        try:
            plan.fire("s.a")
            hits.append(False)
        except FaultError:
            hits.append(True)
    assert hits == [False, True, False, True, False]
    assert plan.calls["s.a"] == 5 and plan.fired["s.a"] == 2
    # probability draws come from the plan's own seeded RNG: the same
    # seed fires on the same call index every run.
    b_hits = []
    for _ in range(20):
        try:
            plan.fire("s.b")
            b_hits.append(False)
        except FaultError:
            b_hits.append(True)
    assert sum(b_hits) == 1  # times=1 caps it
    ref = FaultPlan(seed=7)
    ref.inject("s.b", exc=FaultError, probability=0.5, times=1)
    ref_hits = []
    for _ in range(20):
        try:
            ref.fire("s.b")
            ref_hits.append(False)
        except FaultError:
            ref_hits.append(True)
    assert b_hits == ref_hits


def test_fault_plan_corrupt_wraps_return_value():
    with faults.injected() as plan:
        plan.inject("s.c", corrupt=lambda r: None, at_calls=[2])
        assert faults.wrap("s.c", "ok") == "ok"  # no fire() yet: no-op
        faults.fire("s.c")
        assert faults.wrap("s.c", "ok") == "ok"   # call 1: not armed
        faults.fire("s.c")
        assert faults.wrap("s.c", "ok") is None   # call 2: corrupted
        faults.fire("s.c")
        assert faults.wrap("s.c", "ok") == "ok"


def test_fire_is_noop_without_installed_plan():
    faults.fire("nowhere.at-all")
    assert faults.wrap("nowhere.at-all", 42) == 42


# -- satellite: the synthetic worker must never die silently ------------------


def test_synthetic_worker_survives_poison_item():
    """Regression (pre-fix hang): an exception outside the step guard
    — e.g. a malformed work item — killed the worker thread silently,
    so collect() on the NEXT handle blocked forever. The whole loop
    body is now guarded; the worker logs and survives."""
    ex = SyntheticExecutor(slots=2, d=8, pipelined=True)
    try:
        ex.collect(ex.submit([]))          # spin the worker up
        ex._worker._work.put(("bogus",))   # the pre-fix killer
        h = ex.submit([])
        assert h.event.wait(2.0), \
            "worker died on the poison item: collect() would hang forever"
        ex.collect(h)
    finally:
        ex.close()


def test_synthetic_worker_step_error_reraised_from_collect():
    """A device-side step failure lands in the owning handle and
    re-raises from collect() — with a bounded wait, proving the
    pre-fix failure mode (silent thread death, infinite collect)
    cannot recur."""
    with faults.injected() as plan:
        plan.inject("dev.step", exc=FaultError, at_calls=[2])
        ex = SyntheticExecutor(slots=2, d=8, pipelined=True,
                               fault_site="dev")
        try:
            ex.collect(ex.submit([]))      # call 1: clean
            h = ex.submit([])              # call 2: raises on worker
            assert h.event.wait(2.0), "worker died instead of reporting"
            with pytest.raises(FaultError):
                ex.collect(h)
            ex.collect(ex.submit([]))      # worker survived the error
        finally:
            ex.close()


def test_synthetic_reset_error_reraised_not_hung():
    """A worker-side reset failure re-raises from reset() instead of
    reporting a clean session over poisoned state (or hanging the
    caller forever on a dead worker)."""
    with faults.injected() as plan:
        ex = SyntheticExecutor(slots=2, d=8, pipelined=True)
        try:
            ex.collect(ex.submit([]))
            # Force the reset branch itself to fail on the worker.
            ex.slots = "poison"  # np.zeros((..)) will raise TypeError
            with pytest.raises(TypeError):
                ex.reset()
            ex.slots = 2
            ex.reset()                     # worker survived
            ex.collect(ex.submit([]))
        finally:
            ex.close()


# -- the acceptance test: count-triggered kill at 2x overload -----------------


def test_replica_kill_at_2x_overload_recovers_requeues_and_preserves_streams(
        settle_counts):
    """ISSUE 5 acceptance: two replicas, queue preloaded at 2x slot
    capacity, a count-triggered step failure kills replica0 mid-run.
    The pool must return to full live-replica count, every in-flight
    request from the dead replica must be retried and complete with
    the SAME token stream as an uninjected run, and nothing may be
    settled twice."""
    t0 = time.perf_counter()

    def run(inject):
        ex0 = SyntheticExecutor(slots=2, d=8, seed=5)
        ex1 = SyntheticExecutor(slots=2, d=8, seed=5)
        execs = [FaultyExecutor(ex0, site="r0") if inject else ex0, ex1]
        reg = Registry()
        reqs = _reqs(8, 8, 6)  # 8 requests over 4 slots: 2x overload
        pool, _q = _run_pool(execs, reqs, registry=reg)
        try:
            if inject:
                _wait(lambda: pool.live_count() == 2, msg="full recovery")
                assert sum(pool.restarts) >= 1
                assert reg.counter_value(
                    "serving_requeue_total",
                    {"replica": "replica0", "outcome": "requeued"}) >= 1
        finally:
            pool.stop()
        return [(r.error, list(r.tokens)) for r in reqs]

    baseline = run(inject=False)
    with faults.injected() as plan:
        plan.inject("r0.step", exc=RuntimeError("injected kill"),
                    at_calls=[4])
        injected = run(inject=True)
    assert all(e is None for e, _ in injected), injected
    assert injected == baseline
    assert set(settle_counts.values()) == {1}, \
        f"double-settle: {settle_counts}"
    assert time.perf_counter() - t0 < 2 * CASE_BUDGET_S


# -- the watchdog: a wedged collect() cannot time itself out ------------------


def test_collect_hang_watchdog_detects_within_deadline(settle_counts):
    """A hang injected into a pipelined replica's collect() parks the
    batcher thread forever — only the supervisor's watchdog deadline
    can see it. Detection must land within ~watchdog_s + poll jitter,
    the seized requests must complete on the other replica well before
    the hang resolves, and the wedged replica must rejoin the pool."""
    t0 = time.perf_counter()
    hang_s, watchdog_s = 1.5, 0.2
    with faults.injected() as plan:
        plan.inject("r0.collect", hang_s=hang_s, at_calls=[2])
        ex0 = FaultyExecutor(
            SyntheticExecutor(slots=2, d=8, seed=5, pipelined=True),
            site="r0")
        ex1 = SyntheticExecutor(slots=2, d=8, seed=5, pipelined=True)
        reqs = _reqs(8, 8, 6)
        pool, _q = _run_pool([ex0, ex1], reqs,
                             watchdog_s=watchdog_s, timeout=10.0)
        try:
            done_at = time.perf_counter()
            # All requests completed without waiting out the hang: the
            # watchdog seized and requeued them to the live replica.
            kill_t = plan.fired_at["r0.collect"][0]
            assert done_at - t0 < hang_s + 1.0
            _wait(lambda: pool.live_count() == 2,
                  msg="wedged replica rejoining")
            recovery_s = time.monotonic() - kill_t
            assert sum(pool.restarts) >= 1
        finally:
            pool.stop()
    assert all(r.error is None for r in reqs)
    assert set(settle_counts.values()) == {1}
    assert time.perf_counter() - t0 < CASE_BUDGET_S, recovery_s


# -- flight recorder: the chaos post-mortem artifact (ISSUE 6) ----------------


def _flight_doc(flight_dir, reason):
    files = sorted(flight_dir.glob(f"flight-{reason}-*.json"))
    assert files, (f"no flight snapshot for reason={reason!r} in "
                   f"{sorted(p.name for p in flight_dir.iterdir())}")
    return json.loads(files[-1].read_text())


def _flight_spans(flight_dir, reason):
    return _flight_doc(flight_dir, reason)["spans"]


def _t0_slack(span):
    """Clock-alignment slack for ordering claims (ISSUE 11): an
    in-process span is exact (same clock, 0); a foreign span's claims
    are only good to its stamped offset uncertainty."""
    return span["attrs"].get("clock_unc_s", 0.0) or 0.0


def _assert_shard_flight(doc, victim_rank, world, expect_stalls):
    """The ISSUE 11 kill-one-shard acceptance: ONE flight snapshot
    shows the fault firing ON the victim rank (in its own `shards`
    tail), the ring peers' reduce-stall spans, and the coordinator's
    detect→seize→restart — all on one clock-aligned timeline, with
    every cross-clock ordering claim made only within the stamped
    uncertainty."""
    shards = doc.get("shards")
    assert shards, "flight snapshot has no shards section"
    victim = shards.get(str(victim_rank))
    assert victim, f"victim rank {victim_rank} missing from shards"
    fault = next((s for s in victim if s["name"] == "fault.fired"),
                 None)
    assert fault, ("victim rank's shards tail is missing its "
                   "fault.fired")
    assert fault["attrs"]["rank"] == victim_rank
    if expect_stalls:
        peers = [r for r in range(world) if r != victim_rank]
        for r in peers:
            tail = shards.get(str(r), [])
            stalls = [s for s in tail
                      if s["name"] == "shard.reduce_stall"]
            assert stalls, (f"peer rank {r} shows no reduce-stall "
                            f"span in the shards tail")
            # The peers stalled AFTER the victim's fault fired,
            # within clock-alignment slack.
            for st in stalls:
                assert (st["t0"] + _t0_slack(st) + _t0_slack(fault)
                        >= fault["t0"]), (st, fault)
    # The coordinator chain orders after the fault on the same axis.
    spans = doc["spans"]
    detect = next(s for s in spans
                  if s["name"] == "supervisor.detect")
    assert fault["t0"] <= detect["t0"] + _t0_slack(fault)


def _assert_recovery_chain(spans, fault_point):
    """The injected fault's span event plus the recovery chain, on one
    monotonic timeline, with the exactly-once requeue VISIBLE in the
    trace (not only in the settle counter)."""

    def first(name, **match):
        for s in spans:
            if s["name"] == name and all(
                    s["attrs"].get(k) == v for k, v in match.items()):
                return s
        return None

    fault = first("fault.fired", site=fault_point)
    detect = first("supervisor.detect")
    seize = first("supervisor.seize")
    restart = first("supervisor.restart")
    assert fault, f"fault.fired({fault_point}) missing from snapshot"
    assert detect and seize and restart, (
        "recovery chain incomplete: detect=%s seize=%s restart=%s"
        % (bool(detect), bool(seize), bool(restart)))
    assert (fault["t0"] <= detect["t0"] <= seize["t0"]
            <= restart["t0"]), "timeline out of order"
    requeued = [s for s in spans if s["name"] == "supervisor.requeue"
                and s["attrs"]["outcome"] == "requeued"]
    rids = [s["request_id"] for s in requeued]
    assert len(rids) == len(set(rids)), (
        f"requeue not exactly-once in the trace: {rids}")
    assert set(rids) == set(seize["attrs"]["request_ids"]), (
        "every seized request must appear exactly once in the requeue "
        "chain")


def test_step_hang_flight_recorder_timeline(tmp_path, settle_counts):
    """ISSUE 6 acceptance: an injected step-hang produces a
    flight-recorder snapshot whose timeline shows fault firing →
    watchdog wedge detection → seize → requeue → restart."""
    t0 = time.perf_counter()
    with obs_trace.scoped():
        with faults.injected() as plan:
            plan.inject("fr0.step", hang_s=1.2, at_calls=[3])
            ex0 = FaultyExecutor(SyntheticExecutor(slots=2, d=8, seed=5),
                                 site="fr0")
            ex1 = SyntheticExecutor(slots=2, d=8, seed=5)
            reqs = _reqs(8, 8, 5)
            pool, _q = _run_pool([ex0, ex1], reqs, timeout=10.0,
                                 flight_dir=tmp_path)
            try:
                _wait(lambda: pool.live_count() == 2,
                      msg="wedged replica recovered")
                assert sum(pool.restarts) >= 1
            finally:
                pool.stop()
    # The wedge-time snapshot captured the evidence at detection...
    assert sorted(tmp_path.glob("flight-wedged-*.json"))
    # ...and the restart-time snapshot holds the whole chain.
    _assert_recovery_chain(_flight_spans(tmp_path, "restart"),
                           "fr0.step")
    assert all(r.error is None for r in reqs)
    assert set(settle_counts.values()) == {1}
    assert time.perf_counter() - t0 < CASE_BUDGET_S


# -- the chaos matrix ---------------------------------------------------------

_SYNTH_CASES = [
    ("sync", "step-raise"),
    ("sync", "step-hang"),
    ("pipelined", "submit-raise"),
    ("pipelined", "submit-hang"),
    ("pipelined", "collect-hang"),
    ("pipelined", "worker-step-raise"),
]


_FAULT_POINT = {"step-raise": "step", "submit-raise": "submit",
                "worker-step-raise": "step", "step-hang": "step",
                "submit-hang": "submit", "collect-hang": "collect"}


def _arm(plan, site, fault, at_call=3):
    point = f"{site}.{_FAULT_POINT[fault]}"
    if fault.endswith("raise"):
        plan.inject(point, exc=RuntimeError(f"injected {fault}"),
                    at_calls=[at_call])
    else:
        plan.inject(point, hang_s=1.2, at_calls=[at_call])


@pytest.mark.parametrize("mode,fault", _SYNTH_CASES,
                         ids=[f"{m}-{f}" for m, f in _SYNTH_CASES])
def test_chaos_matrix_synthetic(mode, fault, settle_counts, tmp_path):
    """Each injection point × loop shape over SyntheticExecutor: the
    pool recovers to full strength, requeued requests complete with
    the uninjected run's token streams, nothing settles twice, the
    whole case fits its wall budget — and (ISSUE 6) the flight
    recorder wrote a snapshot containing the injected fault's span
    event plus the recovery chain, exactly-once requeue included."""
    t0 = time.perf_counter()
    pipelined = mode == "pipelined"

    def mk(inject):
        inner = SyntheticExecutor(
            slots=2, d=8, seed=5, pipelined=pipelined,
            fault_site="r0dev" if inject and fault == "worker-step-raise"
            else None)
        if inject and fault != "worker-step-raise":
            return FaultyExecutor(inner, site="r0")
        return inner

    def run(inject):
        execs = [mk(inject),
                 SyntheticExecutor(slots=2, d=8, seed=5,
                                   pipelined=pipelined)]
        reqs = _reqs(8, 8, 5)
        pool, _q = _run_pool(
            execs, reqs, timeout=10.0,
            flight_dir=tmp_path if inject else None)
        try:
            if inject:
                _wait(lambda: pool.live_count() == 2,
                      msg="full live-replica count")
                assert sum(pool.restarts) >= 1
        finally:
            pool.stop()
        return [(r.error, list(r.tokens)) for r in reqs]

    baseline = run(inject=False)
    site = "r0dev" if fault == "worker-step-raise" else "r0"
    with obs_trace.scoped():
        with faults.injected() as plan:
            _arm(plan, site, fault)
            injected = run(inject=True)
    assert all(e is None for e, _ in injected), injected
    assert injected == baseline
    assert set(settle_counts.values()) == {1}
    _assert_recovery_chain(_flight_spans(tmp_path, "restart"),
                           f"{site}.{_FAULT_POINT[fault]}")
    assert time.perf_counter() - t0 < 2 * CASE_BUDGET_S


_LOCAL_CASES = [
    ("sync", "step-raise"),
    ("pipelined", "submit-raise"),
    ("pipelined", "collect-hang"),
]


@pytest.fixture(scope="module")
def local_executors():
    """One compiled LocalExecutor per mode, shared by every local
    chaos case (compile cost dominates; close() is a no-op so reuse
    across pools is safe — each pool's batcher reset()s at start)."""
    return {"sync": LocalExecutor(slots=2, mode="sync", **MODEL),
            "pipelined": LocalExecutor(slots=2, mode="pipelined",
                                       **MODEL)}


@pytest.mark.parametrize("mode,fault", _LOCAL_CASES,
                         ids=[f"local-{m}-{f}" for m, f in _LOCAL_CASES])
def test_chaos_matrix_local(mode, fault, local_executors, settle_counts):
    """The same contracts over the REAL jitted model: single-replica
    pool, so requeued requests re-decode on the restarted replica and
    stream equality proves the restart path re-creates clean device
    state (executor.reset())."""
    t0 = time.perf_counter()
    inner = local_executors[mode]

    def run(inject, site):
        ex = FaultyExecutor(inner, site=site) if inject else inner
        reqs = _reqs(6, MODEL["d"], 4)
        pool, _q = _run_pool([ex], reqs, timeout=15.0)
        try:
            if inject:
                _wait(lambda: pool.live_count() == 1,
                      msg="replica restarted")
                assert sum(pool.restarts) >= 1
        finally:
            pool.stop()
        return [(r.error, list(r.tokens)) for r in reqs]

    site = f"L{mode}-{fault}"
    baseline = run(False, site)
    with faults.injected() as plan:
        _arm(plan, site, fault, at_call=2)
        injected = run(True, site)
    assert all(e is None for e, _ in injected), injected
    assert injected == baseline
    assert set(settle_counts.values()) == {1}
    assert time.perf_counter() - t0 < 2 * CASE_BUDGET_S


# -- sharded replicas (ISSUE 8): one shard of a replica dies/hangs ------------


_SHARD_CASES = [
    ("pipelined", "shard-step-raise", {}),
    ("pipelined", "shard-step-hang", {}),
    ("sync", "shard-step-raise", {}),
    ("pipelined", "collective-send-raise", {}),
    # ISSUE 9 acceptance: the matrix must hold UNCHANGED with the
    # quantized collective + overlapped schedule enabled — the codec
    # rounds deterministically (streams still compare byte-identical
    # injected-vs-not) and a poisoned generation must fail the
    # overlapped reducer threads exactly like the serialized path.
    ("pipelined", "shard-step-raise",
     {"codec": "int8", "overlap": True}),
    ("pipelined", "collective-send-raise",
     {"codec": "int8", "overlap": True}),
]


@pytest.mark.parametrize(
    "mode,fault,shard_opts", _SHARD_CASES,
    ids=[f"{m}-{f}" + ("-int8-overlap" if o else "")
         for m, f, o in _SHARD_CASES])
def test_chaos_matrix_sharded(mode, fault, shard_opts, settle_counts,
                              tmp_path):
    """The new failure domain: ONE shard of a fabric-sharded replica
    killed or hung mid-decode (the `shard{r}.step` site inside the
    shard thread, or the reused `fabric.send` site inside the
    collective). Must hold: the watchdog/death-detector sees it, the
    supervisor seizes and requeues exactly-once (proven in the
    flight-recorder trace), the restarted replica RE-RENDEZVOUSES
    (fresh shard generation — `resets` moves past the startup one),
    token streams are byte-identical to an uninjected run, and the
    shard plane's outstanding-step leak ledger reads clean at
    teardown. (Sharded replicas are row-plane: the paged-KV leak
    ledger is covered by the KV chaos case below, which keeps its
    assert_clean teardown.)"""
    from dpu_operator_tpu.serving import FabricExecutor, SyntheticShardSet

    t0 = time.perf_counter()
    pipelined = mode == "pipelined"

    def run(inject):
        # Equal nonzero step cost on BOTH replicas: replica0 pays a
        # shard-thread spawn at reset, and with free steps replica1
        # would drain the whole preloaded queue before replica0's
        # first pop — the fault site would never even be called.
        shards = SyntheticShardSet(
            world=3, slots=2, d=8, seed=5, step_time_s=0.005,
            fault_site="c0shard" if inject else None, **shard_opts)
        ex0 = FabricExecutor(shards, mode=mode, step_timeout_s=5.0)
        ex1 = SyntheticExecutor(slots=2, d=8, seed=5,
                                step_time_s=0.005,
                                pipelined=pipelined)
        reqs = _reqs(8, 8, 5)
        pool, _q = _run_pool(
            [ex0, ex1], reqs, timeout=10.0,
            flight_dir=tmp_path if inject else None)
        try:
            if inject:
                _wait(lambda: pool.live_count() == 2,
                      msg="full live-replica count")
                assert sum(pool.restarts) >= 1
                # Re-rendezvous: the restarted batcher's reset tears
                # down the wounded shard generation and spawns a
                # fresh one (startup reset is #1; the LIVE flip
                # precedes the new thread's reset, so wait for it).
                _wait(lambda: shards.resets >= 2,
                      msg="shard set re-rendezvous")
        finally:
            pool.stop()
        assert shards.outstanding() == 0, \
            "shard plane leaked an un-aborted in-flight step"
        return [(r.error, list(r.tokens)) for r in reqs]

    baseline = run(inject=False)
    if fault == "collective-send-raise":
        point = "fabric.send"
    else:
        point = "c0shard1.step"
    with obs_trace.scoped():
        with faults.injected() as plan:
            if fault == "shard-step-hang":
                plan.inject(point, hang_s=1.2, at_calls=[3])
            elif fault == "collective-send-raise":
                # fabric.send fires once per shard per reduce (world
                # = 3): call 7 lands inside the third decode step.
                plan.inject(point,
                            exc=RuntimeError("injected send fail"),
                            at_calls=[7])
            else:
                plan.inject(point,
                            exc=RuntimeError("injected shard kill"),
                            at_calls=[3])
            injected = run(inject=True)
    assert all(e is None for e, _ in injected), injected
    assert injected == baseline
    assert set(settle_counts.values()) == {1}, settle_counts
    doc = _flight_doc(tmp_path, "restart")
    _assert_recovery_chain(doc["spans"], point)
    if fault in ("shard-step-raise", "shard-step-hang"):
        # ISSUE 11 acceptance: the SAME snapshot carries the per-rank
        # story — fault.fired in the victim's shards tail, reduce
        # stalls on its ring peers (raise poisons the board eagerly;
        # a hang surfaces as the peers' stall too, but its timing is
        # the stall deadline's, so only the raise case asserts it),
        # coordinator detect→seize→restart clock-aligned after it.
        _assert_shard_flight(doc, victim_rank=1, world=3,
                             expect_stalls=(fault
                                            == "shard-step-raise"))
    assert time.perf_counter() - t0 < 2 * CASE_BUDGET_S


# -- paged-KV re-attach (ISSUE 7): retry without re-decode --------------------


@pytest.mark.parametrize("backend", ["synthetic", "paged",
                                     "paged-pallas"])
def test_kv_kill_mid_decode_reattaches_pages_instead_of_redecoding(
        backend, settle_counts, tmp_path):
    """Chaos-matrix extension: a replica killed MID-DECODE of a
    paged-KV request recovers by re-attaching the victim's KV pages —
    the supervisor's seize/requeue carries block-table ownership
    through the queue. Must hold: byte-identical token streams vs an
    uninjected run, exactly-once settle, ZERO leaked blocks, and the
    recovery trace shows strictly fewer replayed steps than a full
    re-decode from the prompt (the whole point of keeping the pages)."""
    t0 = time.perf_counter()
    plen, chunk, max_toks = 32, 8, 6
    prompt = [int(x) for x in range(plen)]
    if backend == "synthetic":
        from dpu_operator_tpu.serving import SyntheticKVExecutor

        inner = SyntheticKVExecutor(slots=2, block_size=4,
                                    num_blocks=64,
                                    max_blocks_per_req=16,
                                    prefill_chunk=chunk, pipelined=True)
    else:
        from dpu_operator_tpu.serving import PagedKVExecutor

        # "paged" = the tier-1 CPU default (XLA composition over the
        # int8 resident pools); "paged-pallas" = the fused kernel
        # under the interpreter — the ISSUE 13 acceptance runs the
        # chaos matrix on BOTH kernel= paths.
        inner = PagedKVExecutor(slots=2, block_size=4, num_blocks=64,
                                max_blocks_per_req=16,
                                prefill_chunk=chunk, d=16, heads=2,
                                vocab=32, mode="pipelined",
                                kernel=("pallas"
                                        if backend == "paged-pallas"
                                        else None),
                                interpret=(True
                                           if backend == "paged-pallas"
                                           else None))

    def run(inject, flight_dir=None):
        ex = FaultyExecutor(inner, site="kv0") if inject else inner
        reqs = [GenerateRequest(prompt_vec=None, max_tokens=max_toks,
                                deadline=time.monotonic() + 60.0,
                                prompt_tokens=list(prompt))]
        pool, _q = _run_pool([ex], reqs, timeout=20.0,
                             flight_dir=flight_dir)
        try:
            if inject:
                _wait(lambda: pool.live_count() == 1,
                      msg="replica restarted")
                assert sum(pool.restarts) >= 1
        finally:
            pool.stop()
        inner.allocator.assert_clean()
        return [(r.error, list(r.tokens)) for r in reqs], reqs

    baseline, _ = run(inject=False)
    with obs_trace.scoped() as tr:
        with faults.injected() as plan:
            # The baseline primed the prefix cache, so prefill is one
            # chunk step; submit 4 lands mid-decode (a few tokens
            # settled, more to go).
            plan.inject("kv0.submit", exc=RuntimeError("injected kill"),
                        at_calls=[4])
            injected, reqs = run(inject=True, flight_dir=tmp_path)
        spans = tr.spans_snapshot()
    assert injected == baseline, (injected, baseline)
    assert all(e is None for e, _ in injected)
    assert set(settle_counts.values()) == {1}, settle_counts
    victim = reqs[0].request_id
    assert getattr(inner, "resumed_total") >= 1

    # The trace proves the cheap retry: the requeue rode with KV
    # blocks, and the victim appears in strictly fewer post-requeue
    # steps than a full re-decode (prefill chunks + every token again)
    # would need.
    requeues = [s for s in spans if s.name == "supervisor.requeue"
                and s.attrs.get("outcome") == "requeued_kv"]
    assert [s.request_id for s in requeues] == [victim]
    queue_rq = [s for s in spans if s.name == "queue.requeue"
                and s.request_id == victim]
    assert queue_rq and queue_rq[0].attrs.get("kv_blocks", 0) > 0, \
        "block-table ownership did not ride the queue"
    requeue_t = requeues[0].t0
    replayed = sum(
        1 for s in spans
        if s.name == "step.device" and s.t0 > requeue_t
        and victim in (s.attrs.get("request_ids") or ()))
    full_redecode = -(-plen // chunk) + max_toks
    assert 0 < replayed < full_redecode, (replayed, full_redecode)
    # Flight recorder: the restart snapshot carries the same chain.
    flight = _flight_spans(tmp_path, "restart")
    assert any(s["name"] == "supervisor.requeue"
               and s["attrs"].get("outcome") == "requeued_kv"
               for s in flight)
    if hasattr(inner, "close"):
        inner.close()
    assert time.perf_counter() - t0 < 2 * CASE_BUDGET_S


# -- context-parallel paged KV (ISSUE 16): kill one shard mid-decode ----------


@pytest.mark.parametrize("shard_axis", ["head", "page"])
def test_shard_kill_mid_decode_sharded_kv_reattaches_all_ranks(
        shard_axis, settle_counts, tmp_path):
    """Chaos-matrix extension (ISSUE 16): killing ONE rank of a
    context-parallel sharded-KV replica mid-decode must recover through
    the same seize→requeue→re-attach chain as a whole-replica kill —
    the lease's block table re-attaches with EVERY rank's page set
    intact (byte-identical streams vs an uninjected run prove the
    per-rank pools survived the re-rendezvous; the recurrence is
    position- and content-dependent, so a rank that lost its K/V slice
    would diverge visibly). Exactly-once settle, BOTH leak ledgers
    clean (block allocator + the shard set's in-flight board), and the
    flight snapshot carries the victim rank's own fault.fired plus the
    re-rendezvous span."""
    from dpu_operator_tpu.serving import ShardedPagedKVExecutor

    t0 = time.perf_counter()
    plen, chunk, max_toks, world = 32, 8, 6, 2
    prompt = [int(x) for x in range(plen)]
    inner = ShardedPagedKVExecutor(
        slots=2, block_size=4, num_blocks=64, max_blocks_per_req=16,
        prefill_chunk=chunk, d=16, heads=2, vocab=32, mode="pipelined",
        world=world, shard_axis=shard_axis, fault_site="kvshard",
        step_timeout_s=5.0)

    def run(inject, flight_dir=None):
        reqs = [GenerateRequest(prompt_vec=None, max_tokens=max_toks,
                                deadline=time.monotonic() + 60.0,
                                prompt_tokens=list(prompt))]
        resets0 = inner.shards.resets
        pool, _q = _run_pool([inner], reqs, timeout=20.0,
                             flight_dir=flight_dir)
        try:
            if inject:
                _wait(lambda: pool.live_count() == 1,
                      msg="replica restarted")
                assert sum(pool.restarts) >= 1
                # Re-rendezvous: the restart's reset() tears down the
                # poisoned shard generation and respawns all world
                # rank threads against the SURVIVING pools.
                _wait(lambda: inner.shards.resets > resets0 + 1,
                      msg="shard set re-rendezvous")
        finally:
            pool.stop()
        # Both leak ledgers: no block leaked by the seize, and no
        # un-aborted in-flight step left on the shard board.
        inner.allocator.assert_clean()
        assert inner.shards.outstanding() == 0, \
            "shard set leaked an un-aborted in-flight step"
        return [(r.error, list(r.tokens)) for r in reqs], reqs

    baseline, _ = run(inject=False)
    with obs_trace.scoped() as tr:
        with faults.injected() as plan:
            # The baseline primed the prefix cache, so prefill is one
            # chunk step; rank 1's 4th step lands mid-decode. The
            # fault fires INSIDE the victim rank's step thread — the
            # coordinator poisons the generation and the batcher's
            # collect() surfaces ShardStepError(rank=1).
            plan.inject("kvshard1.step",
                        exc=RuntimeError("injected shard kill"),
                        at_calls=[4])
            injected, reqs = run(inject=True, flight_dir=tmp_path)
        spans = tr.spans_snapshot()
    assert injected == baseline, (injected, baseline)
    assert all(e is None for e, _ in injected)
    assert set(settle_counts.values()) == {1}, settle_counts
    victim = reqs[0].request_id
    assert inner.resumed_total >= 1

    # The cheap retry: requeue rode with the block table, and the
    # victim replayed strictly fewer steps than a full re-decode.
    requeues = [s for s in spans if s.name == "supervisor.requeue"
                and s.attrs.get("outcome") == "requeued_kv"]
    assert [s.request_id for s in requeues] == [victim]
    queue_rq = [s for s in spans if s.name == "queue.requeue"
                and s.request_id == victim]
    assert queue_rq and queue_rq[0].attrs.get("kv_blocks", 0) > 0, \
        "block-table ownership did not ride the queue"
    requeue_t = requeues[0].t0
    replayed = sum(
        1 for s in spans
        if s.name == "step.device" and s.t0 > requeue_t
        and victim in (s.attrs.get("request_ids") or ()))
    full_redecode = -(-plen // chunk) + max_toks
    assert 0 < replayed < full_redecode, (replayed, full_redecode)

    # The per-rank story rides the SAME timeline: the rank-stamped
    # fault.fired groups into the victim's shards tail of the restart
    # snapshot, and the re-rendezvous span is on the main tail.
    doc = _flight_doc(tmp_path, "restart")
    flight = doc["spans"]
    assert any(s["name"] == "fault.fired"
               and s["attrs"].get("site") == "kvshard1.step"
               for s in flight)
    assert any(s["name"] == "supervisor.requeue"
               and s["attrs"].get("outcome") == "requeued_kv"
               for s in flight)
    shards_sec = doc.get("shards", {})
    victim_tail = shards_sec.get("1", [])
    assert any(s["name"] == "fault.fired"
               and s["attrs"].get("rank") == 1 for s in victim_tail), \
        "victim rank's shards tail is missing its fault.fired"
    rendezvous = [s for s in spans if s.name == "kvshard.rendezvous"]
    assert rendezvous and any(s.attrs.get("world") == world
                              for s in rendezvous), \
        "re-rendezvous span missing from the recovery trace"
    inner.close()
    assert time.perf_counter() - t0 < 2 * CASE_BUDGET_S


# -- speculative decode (ISSUE 15): kill mid-verify ---------------------------


@pytest.mark.parametrize("backend", ["synthetic", "paged"])
def test_kv_kill_mid_verify_resumes_from_confirmed_watermark(
        backend, settle_counts, tmp_path):
    """Chaos-matrix extension (ISSUE 15): a replica killed MID-VERIFY
    of a speculative request must resume from the COLLECT-CONFIRMED
    watermark — never from accepted-but-uncollected draft positions
    (the killed step's provisional ctx advance dies with the
    incarnation; _reattach rebuilds cursors from settled tokens).
    Byte-identical streams vs the uninjected speculative run prove it
    (the recurrences are position-dependent, so a resume that trusted
    an uncollected verify window diverges visibly), settle exactly
    once, leak ledger clean."""
    from dpu_operator_tpu.serving.spec import OracleDraft, SpecConfig

    t0 = time.perf_counter()
    plen, chunk, max_toks, k = 32, 8, 8, 4
    prompt = [int(x) for x in range(plen)]
    if backend == "synthetic":
        from dpu_operator_tpu.serving import SyntheticKVExecutor

        inner = SyntheticKVExecutor(
            slots=2, block_size=4, num_blocks=64,
            max_blocks_per_req=16, prefill_chunk=chunk,
            pipelined=False,
            spec=SpecConfig(OracleDraft(k=k, accept_rate=0.6,
                                        vocab=64, target_seed=0), k))
    else:
        from dpu_operator_tpu.serving import PagedKVExecutor

        # The int8 resident default on the XLA composition: resume
        # replays re-plan the SAME verify windows (drafts are pure
        # functions of (last, ctx)), so even quantization groups
        # reproduce and streams stay byte-identical vs uninjected.
        inner = PagedKVExecutor(slots=2, block_size=4, num_blocks=64,
                                max_blocks_per_req=16,
                                prefill_chunk=chunk, d=16, heads=2,
                                vocab=32, mode="speculative", spec_k=k)

    def run(inject, flight_dir=None):
        ex = FaultyExecutor(inner, site="kvs0") if inject else inner
        reqs = [GenerateRequest(prompt_vec=None, max_tokens=max_toks,
                                deadline=time.monotonic() + 60.0,
                                prompt_tokens=list(prompt))]
        pool, _q = _run_pool([ex], reqs, timeout=20.0,
                             flight_dir=flight_dir)
        try:
            if inject:
                _wait(lambda: pool.live_count() == 1,
                      msg="replica restarted")
                assert sum(pool.restarts) >= 1
        finally:
            pool.stop()
        inner.allocator.assert_clean()
        return [(r.error, list(r.tokens)) for r in reqs], reqs

    baseline, _ = run(inject=False)
    runs_before = inner.spec.stats.runs
    assert runs_before > 0, "the baseline never speculated"
    with obs_trace.scoped() as tr:
        with faults.injected() as plan:
            # The baseline primed the prefix cache: prefill is one
            # chunk step, so submit 3 is the SECOND verify step —
            # tokens settled, a verify window in flight.
            plan.inject("kvs0.submit",
                        exc=RuntimeError("injected mid-verify kill"),
                        at_calls=[3])
            injected, reqs = run(inject=True, flight_dir=tmp_path)
        spans = tr.spans_snapshot()
    assert injected == baseline, (injected, baseline)
    assert all(e is None for e, _ in injected)
    assert set(settle_counts.values()) == {1}, settle_counts
    assert inner.resumed_total >= 1
    assert inner.spec.stats.runs > runs_before
    victim = reqs[0].request_id
    requeues = [s for s in spans if s.name == "supervisor.requeue"
                and s.attrs.get("outcome") == "requeued_kv"]
    assert [s.request_id for s in requeues] == [victim]
    flight = _flight_spans(tmp_path, "restart")
    assert any(s["name"] == "supervisor.requeue"
               and s["attrs"].get("outcome") == "requeued_kv"
               for s in flight)
    if hasattr(inner, "close"):
        inner.close()
    assert time.perf_counter() - t0 < 2 * CASE_BUDGET_S


@pytest.mark.parametrize("backend", ["synthetic", "paged"])
def test_kv_kill_mid_pipelined_verify_with_window_in_flight(
        backend, settle_counts, tmp_path):
    """Chaos-matrix extension (ISSUE 18): kill a PIPELINED
    speculative replica while a plan-ahead verify window is in
    flight. The killed incarnation dies holding (a) an uncollected
    verify window and (b) the provisional ctx advance of the window
    planned from its unverified proposals — both must evaporate:
    _reattach rebuilds cursors from the confirmed watermark's settled
    tokens, and the restarted replica re-plans from there. Streams
    byte-identical vs the uninjected pipelined-spec run, settle
    exactly once, leak ledger clean, flight doc shows the
    fault + KV-preserving requeue (the rollback's observable).

    int8 stays exact here (paged default): drafts are pure functions
    of (last, ctx), so the dead window's provisional appends are
    byte-identical to the restart's re-appends at the same positions
    — the set-once scale a dead window seeded is the scale the replay
    would have written (and on a mis-predicted plan-ahead, BOTH runs
    seeded the same wrong-byte scale before rolling back)."""
    from dpu_operator_tpu.serving.spec import OracleDraft, SpecConfig

    t0 = time.perf_counter()
    plen, chunk, max_toks, k = 32, 8, 8, 4
    prompt = [int(x) for x in range(plen)]
    if backend == "synthetic":
        from dpu_operator_tpu.serving import SyntheticKVExecutor

        inner = SyntheticKVExecutor(
            slots=2, block_size=4, num_blocks=64,
            max_blocks_per_req=16, prefill_chunk=chunk,
            pipelined=True,
            spec=SpecConfig(OracleDraft(k=k, accept_rate=0.6,
                                        vocab=64, target_seed=0), k))
    else:
        from dpu_operator_tpu.serving import PagedKVExecutor

        inner = PagedKVExecutor(slots=2, block_size=4, num_blocks=64,
                                max_blocks_per_req=16,
                                prefill_chunk=chunk, d=16, heads=2,
                                vocab=32,
                                mode="speculative-pipelined",
                                spec_k=k)

    def run(inject, flight_dir=None):
        ex = FaultyExecutor(inner, site="kvs0") if inject else inner
        reqs = [GenerateRequest(prompt_vec=None, max_tokens=max_toks,
                                deadline=time.monotonic() + 60.0,
                                prompt_tokens=list(prompt))]
        pool, _q = _run_pool([ex], reqs, timeout=20.0,
                             flight_dir=flight_dir)
        try:
            if inject:
                _wait(lambda: pool.live_count() == 1,
                      msg="replica restarted")
                assert sum(pool.restarts) >= 1
        finally:
            pool.stop()
        inner.allocator.assert_clean()
        return [(r.error, list(r.tokens)) for r in reqs], reqs

    baseline, _ = run(inject=False)
    runs_before = inner.spec.stats.runs
    assert runs_before > 0, "the baseline never speculated"
    assert inner.kv_stats()["spec_pipeline_peak"] >= 2, \
        "the baseline never overlapped draft with verify"
    with obs_trace.scoped() as tr:
        with faults.injected() as plan:
            # Prefix cache primed: prefill is one chunk step, submit
            # 2 is the post-prefill bubble (last_token in flight), 3
            # the first verify window. Submit 4 is planned from
            # window 3's UNVERIFIED proposals while 3 is still in
            # flight — killing there dies with both a pending collect
            # and a provisional plan-ahead advance.
            plan.inject("kvs0.submit",
                        exc=RuntimeError("injected pipelined kill"),
                        at_calls=[4])
            injected, reqs = run(inject=True, flight_dir=tmp_path)
        spans = tr.spans_snapshot()
    assert injected == baseline, (injected, baseline)
    assert all(e is None for e, _ in injected)
    assert set(settle_counts.values()) == {1}, settle_counts
    assert inner.resumed_total >= 1
    assert inner.spec.stats.runs > runs_before
    victim = reqs[0].request_id
    requeues = [s for s in spans if s.name == "supervisor.requeue"
                and s.attrs.get("outcome") == "requeued_kv"]
    assert [s.request_id for s in requeues] == [victim]
    flight = _flight_spans(tmp_path, "restart")
    assert any(s["name"] == "fault.fired"
               and s["attrs"].get("site") == "kvs0.submit"
               for s in flight), "flight doc is missing the kill"
    assert any(s["name"] == "supervisor.requeue"
               and s["attrs"].get("outcome") == "requeued_kv"
               for s in flight), \
        "flight doc is missing the watermark-preserving requeue"
    if hasattr(inner, "close"):
        inner.close()
    assert time.perf_counter() - t0 < 2 * CASE_BUDGET_S


# -- health contract over HTTP ------------------------------------------------


def _get(url):
    try:
        r = urllib.request.urlopen(url, timeout=5)
        return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code


def test_readyz_flips_degraded_then_ready():
    """One replica killed by a one-shot fault: /readyz reports 503
    'degraded' while live < quorum and flips back to 200 after the
    supervisor restarts it; /healthz stays 200 throughout (a replica
    is coming back — liveness must not kill the pod)."""
    with faults.injected() as plan:
        # Keep replica0 down long enough to OBSERVE degraded: the
        # restart's own reset re-arms it once, then it comes up clean.
        plan.inject("hr0.step", exc=RuntimeError("kill"), at_calls=[2])
        plan.inject("hr0.reset", exc=RuntimeError("still down"),
                    at_calls=[2, 3])
        ex0 = FaultyExecutor(SyntheticExecutor(slots=1, d=8), site="hr0")
        ex1 = SyntheticExecutor(slots=1, d=8)
        srv = ServingServer(
            [ex0, ex1],
            pool_opts=dict(restart_backoff_s=0.05, poll_s=0.005,
                           breaker_threshold=50)).start()
        try:
            assert _get(srv.url + "/readyz") == 200
            # Trip the fault with one request (it retries on ex1).
            import json as _json
            data = _json.dumps({"prompt": "x", "max_tokens": 3,
                                "deadline_ms": 10000}).encode()
            urllib.request.urlopen(
                urllib.request.Request(srv.url + "/v1/generate",
                                       data=data), timeout=10).read()
            _wait(lambda: srv.pool.live_count() < 2, msg="replica down")
            assert _get(srv.url + "/readyz") == 503
            assert _get(srv.url + "/healthz") == 200
            # A restart flips LIVE before the new thread's reset runs,
            # and the armed reset faults kill the first two comebacks
            # — wait until the THIRD restart (the one whose reset is
            # clean) is up before asserting the stable ready state.
            _wait(lambda: sum(srv.pool.restarts) >= 3
                  and _get(srv.url + "/readyz") == 200,
                  msg="ready again after the clean restart")
            assert srv.pool.live_count() == 2
            assert _get(srv.url + "/healthz") == 200
        finally:
            srv.stop()


def test_breaker_parks_flapping_replica_healthz_red_at_zero_live():
    """A replica that dies on every restart is PARKED after
    breaker_threshold failures (no infinite crash loop), with
    serving_breaker_state=1 and the pool degraded. With ALL replicas
    parked, /healthz finally goes red — zero live, none coming back."""
    with faults.injected() as plan:
        # reset fires on every (re)start of the pipelined loop: the
        # replica can never come up.
        plan.inject("br0.reset", exc=RuntimeError("dead on arrival"))
        ex = FaultyExecutor(
            SyntheticExecutor(slots=1, d=8, pipelined=True), site="br0")
        reg = Registry()
        srv = ServingServer(
            [ex], registry=reg,
            pool_opts=dict(restart_backoff_s=0.01, poll_s=0.005,
                           breaker_threshold=3,
                           breaker_window_s=30.0)).start()
        try:
            _wait(lambda: srv.pool.states()["replica0"] == "parked",
                  msg="breaker opening")
            restarts_at_park = sum(srv.pool.restarts)
            assert reg.gauge_value("serving_breaker_state",
                                   {"replica": "replica0"}) == 1.0
            assert _get(srv.url + "/healthz") == 503
            assert _get(srv.url + "/readyz") == 503
            # Parked means parked: no further restarts accrue.
            time.sleep(0.1)
            assert sum(srv.pool.restarts) == restarts_at_park
            assert reg.gauge_value(
                "serving_pool_replicas",
                {"state": "parked", "sharded": "false",
                 "role": "unified"}) == 1.0
        finally:
            srv.stop()


def test_reset_hang_on_restart_is_watchdogged_not_invisibly_live():
    """Review catch: after a wedge, the restarted batcher's first act
    is executor.reset(), which can serialize behind the still-hung
    device step — pre-fix it blocked there with blocked_since unset,
    so the supervisor reported the replica LIVE forever while it
    served nothing. reset() now runs under the watchdog clock: a
    hanging reset is detected like any other wedge and the breaker
    parks the replica instead of wedging it invisibly."""
    with faults.injected() as plan:
        # Startup reset (call 1) is clean; the replica dies once (on
        # its first submit — the pipelined loop's seam), and every
        # restart's reset hangs.
        plan.inject("wr0.submit", exc=RuntimeError("kill"), at_calls=[1])
        plan.inject("wr0.reset", hang_s=1.0,
                    at_calls=list(range(2, 12)))
        ex = FaultyExecutor(
            SyntheticExecutor(slots=1, d=8, pipelined=True), site="wr0")
        q = AdmissionQueue(max_depth=8)
        pool = ReplicaPool([ex], q, watchdog_s=0.2,
                           restart_backoff_s=0.01, poll_s=0.005,
                           breaker_threshold=3)
        for r in _reqs(1, 8, 3):
            q.submit(r)
        pool.start()
        try:
            _wait(lambda: pool.states()["replica0"] == "parked",
                  timeout=8.0, msg="hanging-reset replica parked")
        finally:
            pool.stop()


def test_queue_submit_fault_returns_500_not_dropped_connection():
    """An injected AdmissionQueue.submit failure must surface as a
    JSON 500 on THIS request and leave the server serving — not tear
    down the handler connection."""
    import json as _json
    with faults.injected() as plan:
        plan.inject("queue.submit", exc=RuntimeError("queue blew up"),
                    at_calls=[1])
        srv = ServingServer([SyntheticExecutor(slots=1, d=8)]).start()
        try:
            def post():
                data = _json.dumps({"prompt": "x", "max_tokens": 2,
                                    "deadline_ms": 5000}).encode()
                try:
                    r = urllib.request.urlopen(
                        urllib.request.Request(srv.url + "/v1/generate",
                                               data=data), timeout=10)
                    r.read()
                    return r.status
                except urllib.error.HTTPError as e:
                    e.read()
                    return e.code

            assert post() == 500
            assert post() == 200  # the plane survived its queue fault
        finally:
            srv.stop()


# -- the VSP heartbeat seam ---------------------------------------------------


def test_vsp_ping_fault_seam():
    """The daemon-facing heartbeat breaks on demand: an injected raise
    surfaces to the caller (heartbeat-loss path), an injected corrupt
    flips the response unhealthy without touching the VSP."""
    from dpu_operator_tpu.parallel.topology import SliceTopology
    from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

    vsp = TpuVsp(topology=SliceTopology.single_chip())
    with faults.injected() as plan:
        plan.inject("vsp.ping", exc=RuntimeError("heartbeat eaten"),
                    at_calls=[1])
        with pytest.raises(RuntimeError):
            vsp.Ping(None, None)
        resp = vsp.Ping(None, None)
        assert resp.healthy

        def unhealthy(r):
            r.healthy = False
            return r

        plan.inject("vsp.ping", corrupt=unhealthy, at_calls=[3])
        assert not vsp.Ping(None, None).healthy
        assert vsp.Ping(None, None).healthy


# -- the cluster prefix cache (ISSUE 17) ---------------------------------------


def _kv_req(prompt, max_tokens=5):
    return GenerateRequest(prompt_vec=None, max_tokens=max_tokens,
                           deadline=time.monotonic() + 60.0,
                           prompt_tokens=list(prompt))


def _drive_kv(ex, queue, req, timeout=20.0):
    from dpu_operator_tpu.serving import ContinuousBatcher

    b = ContinuousBatcher(ex, queue)
    b.start()
    try:
        assert req.wait(timeout=timeout), "request lost"
    finally:
        b.stop()
    assert req.error is None, req.error
    return list(req.tokens)


def test_kvtier_restore_fault_and_corruption_degrade_to_reprefill(
        settle_counts):
    """Tier chaos: an injected restore fault AND a corrupted host
    entry (caught by the chained-hash re-verification) both degrade to
    re-prefilling the SAME byte-identical stream — the tier is an
    optimization, never a failure domain — with both leak ledgers
    clean after every round."""
    from dpu_operator_tpu.serving import SyntheticKVExecutor
    from dpu_operator_tpu.serving.kvcache import PrefixTree
    from dpu_operator_tpu.serving.kvcache.allocator import _ROOT

    t_start = time.monotonic()
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
    ex = SyntheticKVExecutor(slots=2, vocab=32, block_size=4,
                             num_blocks=32, host_tier_bytes=1 << 20)

    def drive():
        q = AdmissionQueue(max_depth=2)
        r = _kv_req(prompt)
        q.submit(r)
        return _drive_kv(ex, q, r)

    try:
        baseline = drive()

        # Round 1: the restore path itself fails (host RAM read
        # error, a dying tier) — prefill covers the whole prompt.
        ex.prefix.evict(99)
        with faults.injected() as plan:
            plan.inject("kvtier.restore", exc=FaultError("tier dead"),
                        at_calls=[1])
            assert drive() == baseline
        assert ex.kv_stats()["prefix_hit_tokens_host"] == 0

        # Round 2: the tier answers, but its entry rotted — the
        # chained-hash re-verification refuses it BEFORE any bytes
        # are published, drops the entry, and prefill covers it.
        ex.prefix.evict(99)
        first_key = PrefixTree._key(_ROOT, tuple(prompt[:4]))
        entry = ex.tier._entries[first_key]
        entry.tokens = tuple(t + 1 for t in entry.tokens)
        assert drive() == baseline
        assert ex.kv_stats()["tier_corrupt_blocks"] >= 1
        assert first_key not in ex.tier.keys()

        ex.prefix.flush()
        ex.allocator.assert_clean()
        ex.tier.assert_clean()
        assert set(settle_counts.values()) == {1}, settle_counts
        assert time.monotonic() - t_start < CASE_BUDGET_S
    finally:
        ex.close()


def test_kvtier_spill_fault_degrades_to_drop_on_evict(settle_counts):
    """Tier chaos in the OTHER direction: the spill hook itself dies
    while the prefix tree evicts (host buffer allocation failing
    mid-put). The contract is drop-on-evict — the victim block frees
    anyway (admission is never blocked on a sick tier), the entry
    just never reaches the host, and the next request degrades to
    re-prefilling the SAME byte-identical stream with every ledger
    clean."""
    from dpu_operator_tpu.serving import SyntheticKVExecutor

    t_start = time.monotonic()
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
    ex = SyntheticKVExecutor(slots=2, vocab=32, block_size=4,
                             num_blocks=32, host_tier_bytes=1 << 20)

    def drive():
        q = AdmissionQueue(max_depth=2)
        r = _kv_req(prompt)
        q.submit(r)
        return _drive_kv(ex, q, r)

    try:
        baseline = drive()
        with faults.injected() as plan:
            # No at_calls: EVERY spill this evict attempts fails.
            plan.inject("kvtier.spill",
                        exc=FaultError("host buffer alloc failed"))
            freed = ex.prefix.evict(99)
            assert plan.fired.get("kvtier.spill", 0) >= 1
        assert freed > 0               # eviction still freed capacity
        assert not ex.tier.keys()      # nothing made it to the host
        assert drive() == baseline     # degrade = plain re-prefill
        assert ex.kv_stats()["prefix_hit_tokens_host"] == 0

        ex.prefix.flush()
        ex.allocator.assert_clean()
        ex.tier.assert_clean()
        assert set(settle_counts.values()) == {1}, settle_counts
        assert time.monotonic() - t_start < CASE_BUDGET_S
    finally:
        ex.close()


def test_router_pull_cut_midstream_falls_back_to_local_prefill(
        settle_counts, tmp_path):
    """Router chaos: the cross-replica prefix pull is cut mid-stream
    (injected socket death between segments). The request must
    complete on the chosen replica by LOCAL prefill with the exact
    stream an unrouted run produces, both replicas' allocator AND
    tier ledgers stay clean, and one flight-recorder timeline carries
    the whole story: router decision -> failed pull -> the replica's
    queue leg."""
    from dpu_operator_tpu.serving import SyntheticKVExecutor
    from dpu_operator_tpu.serving.router import (PrefixRouter,
                                                 RouterReplica)

    t_start = time.monotonic()
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]

    def mk(name):
        ex = SyntheticKVExecutor(slots=2, vocab=32, block_size=4,
                                 num_blocks=32,
                                 host_tier_bytes=1 << 20)
        return RouterReplica(name, AdmissionQueue(max_depth=64), ex)

    reg = Registry()
    with obs_trace.scoped() as tr:
        # Inside the scoped tracer: the queues capture it at
        # construction, which is what stitches the replicas' queue
        # legs into the same timeline as the router's events.
        a, b = mk("a"), mk("b")
        recorder = FlightRecorder(tracer=tr,
                                  flight_dir=str(tmp_path))
        router = PrefixRouter([a, b], cadence_s=0.0, max_load_skew=2,
                              registry=reg, tracer=tr)
        try:
            r1 = _kv_req(prompt)
            assert router.submit(r1) is a
            baseline = _drive_kv(a.executor, a.queue, r1)

            # Swamp a past the skew so the router places on b and
            # tries to move the prefix there.
            for _ in range(5):
                a.queue.submit(_kv_req(prompt))

            with faults.injected() as plan:
                plan.inject("kvstream.send",
                            exc=OSError("mid-stream cut"),
                            at_calls=[1])
                r2 = _kv_req(prompt)
                chosen = router.submit(r2)
            assert chosen is b
            assert reg.counter_value(
                "serving_router_pull_failed_total") == 1
            assert _drive_kv(b.executor, b.queue, r2) == baseline
            # Nothing remote was published: the pull died, prefill
            # covered the whole prompt.
            st = b.executor.kv_stats()
            assert st["prefix_hit_tokens_remote"] == 0

            for rep in (a, b):
                rep.executor.prefix.flush()
                rep.executor.allocator.assert_clean()
                rep.executor.tier.assert_clean()
            recorder.snapshot("router-pull-cut",
                              extra={"request_id": r2.request_id})
        finally:
            router.close()
            a.executor.close()
            b.executor.close()
        spans = tr.spans_snapshot()

    # One timeline, three legs, one request id.
    mine = [s for s in spans if s.request_id == r2.request_id]
    route = [s for s in mine if s.name == "router.route"]
    pull = [s for s in mine if s.name == "router.pull"]
    queued = [s for s in mine if s.name.startswith("queue.")]
    assert route and route[0].attrs["outcome"] == "load"
    assert pull and pull[0].attrs["outcome"] == "failed"
    assert "mid-stream cut" in pull[0].attrs.get("error", "")
    assert queued, "the replica's queue leg is missing"
    # The pull resolves INSIDE the routing decision (route's event is
    # the decision record, emitted after); both precede the queue leg.
    assert pull[0].t0 <= route[0].t0 <= min(s.t0 for s in queued)

    # The same timeline persisted as a flight document.
    files = sorted(tmp_path.glob("flight-router-pull-cut-*.json"))
    assert files, sorted(p.name for p in tmp_path.iterdir())
    doc = json.loads(files[0].read_text())
    names = {s["name"] for s in doc["spans"]
             if s.get("request_id") == r2.request_id}
    assert {"router.route", "router.pull"} <= names

    assert set(settle_counts.values()) == {1}, settle_counts
    assert time.monotonic() - t_start < CASE_BUDGET_S


# -- QoS preemption chaos (ISSUE 20): the park and resume seams ---------------


def test_preempt_park_fault_crashes_replica_and_lease_lands_once(
        settle_counts):
    """Chaos at the park seam: the host tier dies MID-PARK (after the
    victim's slot is already mid-export). kv_preempt_slot unwinds its
    partial pins and re-raises; under crash-only the replica dies with
    the victim still BOUND, so the supervisor's seize/requeue owns the
    lease — it lands in the queue exactly once, resumes through the
    ordinary reattach, and both streams match an uninjected run with
    every leak ledger clean."""
    from dpu_operator_tpu.serving import ReplicaPool, SyntheticKVExecutor

    t_start = time.monotonic()
    plen, max_toks = 16, 8
    b_prompt = [int(x) for x in range(plen)]
    i_prompt = [int(x) + 1 for x in range(plen)]

    def run(inject):
        ex = SyntheticKVExecutor(slots=1, block_size=4, num_blocks=64,
                                 max_blocks_per_req=16,
                                 prefill_chunk=8, pipelined=True,
                                 step_time_s=0.02,
                                 host_tier_bytes=1 << 20)
        q = AdmissionQueue(max_depth=8)
        pool = ReplicaPool([ex], q, watchdog_s=0.25,
                           restart_backoff_s=0.01, poll_s=0.005)
        victim = GenerateRequest(prompt_vec=None, max_tokens=max_toks,
                                 deadline=time.monotonic() + 60.0,
                                 prompt_tokens=list(b_prompt),
                                 priority="batch")
        inter = GenerateRequest(prompt_vec=None, max_tokens=3,
                                deadline=time.monotonic() + 60.0,
                                prompt_tokens=list(i_prompt))
        q.submit(victim)
        pool.start()
        try:
            # Interactive lands mid-decode with the single slot full:
            # the next loop iteration parks the batch occupant — and
            # with the fault armed, dies doing it.
            _wait(lambda: len(victim.tokens) >= 1, msg="mid-decode")
            q.submit(inter)
            assert victim.wait(20), "victim lost"
            assert inter.wait(20), "interactive lost"
            if inject:
                _wait(lambda: pool.live_count() == 1,
                      msg="replica restarted")
                assert sum(pool.restarts) >= 1
        finally:
            pool.stop()
        assert victim.error is None and inter.error is None
        ex.prefix.flush()
        ex.tier.assert_clean()   # partial-park pins were unwound
        ex.tier.flush()
        ex.allocator.assert_clean()
        streams = (list(victim.tokens), list(inter.tokens))
        ex.close()
        return streams, victim

    baseline, base_victim = run(inject=False)
    assert base_victim.preemptions >= 1  # uninjected park committed
    with faults.injected() as plan:
        plan.inject("kvpreempt.park",
                    exc=FaultError("tier died mid-park"), at_calls=[1])
        injected, victim = run(inject=True)
        assert plan.fired.get("kvpreempt.park", 0) >= 1
    assert injected == baseline, (injected, baseline)
    assert set(settle_counts.values()) == {1}, settle_counts
    # The crashed park never committed: no preemption was recorded,
    # the requeue rode the supervisor's replica-fault path instead
    # (which DOES bill the attempts budget — a dead replica is a
    # fault, a committed park is policy).
    assert victim.preemptions == 0
    assert victim.attempts >= 1
    assert time.monotonic() - t_start < 2 * CASE_BUDGET_S


def test_preempt_resume_fault_settles_exactly_once_with_pins_released(
        settle_counts):
    """Chaos at the resume seam: the tier restore dies while a parked
    victim re-admits. The admission guard fails the request through
    the finish() choke point — settled exactly once, the ParkedKV's
    tier pins checked back in by the settle hook, no wedge, no leak —
    and the replica keeps serving (an admission failure is not a
    replica fault)."""
    from dpu_operator_tpu.serving import ReplicaPool, SyntheticKVExecutor

    t_start = time.monotonic()
    plen = 16
    b_prompt = [int(x) for x in range(plen)]
    i_prompt = [int(x) + 1 for x in range(plen)]

    ex = SyntheticKVExecutor(slots=1, block_size=4, num_blocks=64,
                             max_blocks_per_req=16, prefill_chunk=8,
                             pipelined=True, step_time_s=0.02,
                             host_tier_bytes=1 << 20)
    q = AdmissionQueue(max_depth=8)
    pool = ReplicaPool([ex], q, watchdog_s=0.25,
                       restart_backoff_s=0.01, poll_s=0.005)
    victim = GenerateRequest(prompt_vec=None, max_tokens=8,
                             deadline=time.monotonic() + 60.0,
                             prompt_tokens=list(b_prompt),
                             priority="batch")
    inter = GenerateRequest(prompt_vec=None, max_tokens=3,
                            deadline=time.monotonic() + 60.0,
                            prompt_tokens=list(i_prompt))
    with faults.injected() as plan:
        plan.inject("kvpreempt.resume",
                    exc=FaultError("tier restore died"), at_calls=[1])
        q.submit(victim)
        pool.start()
        try:
            _wait(lambda: len(victim.tokens) >= 1, msg="mid-decode")
            q.submit(inter)
            assert victim.wait(20), "victim lost"
            assert inter.wait(20), "interactive lost"
            # The fault cost one request, never the replica.
            assert pool.live_count() == 1
            assert sum(pool.restarts) == 0
        finally:
            pool.stop()
        assert plan.fired.get("kvpreempt.resume", 0) >= 1
    assert inter.error is None
    assert victim.error is not None \
        and "admission failed" in victim.error
    assert victim.preemptions == 1  # the park itself committed
    assert set(settle_counts.values()) == {1}, settle_counts
    ex.prefix.flush()
    ex.tier.assert_clean()  # fail() -> finish() hook released the pins
    ex.tier.flush()
    ex.allocator.assert_clean()
    ex.close()
    assert time.monotonic() - t_start < CASE_BUDGET_S
