"""Slice-topology fidelity: known shapes, torus wrap, bisection, and
topology-aware allocation on the shapes where it matters (VERDICT r1
weak #4: a v5litepod-16 is 4x4, not 2x8; neighbour lists, wrap,
bisection_gbps and GetPreferredAllocation all derive from the grid).
Shape source: public TPU generation docs (reference topology contract:
dpu-api/api.proto:38-40)."""

import pytest

from dpu_operator_tpu.parallel.topology import SliceTopology


def _env(accel, worker="0", **extra):
    env = {"TPU_ACCELERATOR_TYPE": accel, "TPU_WORKER_ID": worker}
    env.update(extra)
    return env


# -- known v5e shapes ---------------------------------------------------------


@pytest.mark.parametrize(
    "accel,grid",
    [
        ("v5litepod-4", (2, 2, 1)),
        ("v5litepod-8", (2, 4, 1)),
        ("v5litepod-16", (4, 4, 1)),
        ("v5litepod-32", (4, 8, 1)),
        ("v5litepod-64", (8, 8, 1)),
        ("v5litepod-256", (16, 16, 1)),
    ],
)
def test_v5e_known_grids(accel, grid):
    topo = SliceTopology.from_env(_env(accel))
    assert topo.grid == grid
    assert topo.num_chips == grid[0] * grid[1] * grid[2]


def test_v5e_16_is_square_not_stacked():
    """The regression the table fixes: host stacking said 2x8."""
    topo = SliceTopology.from_env(_env("v5litepod-16"))
    assert topo.grid == (4, 4, 1)
    # 4 hosts of 2x2 tiling a 4x4: workers 0..3 with 4 chips each.
    workers = {c.worker for c in topo.chips}
    assert workers == {0, 1, 2, 3}
    for w in workers:
        assert sum(1 for c in topo.chips if c.worker == w) == 4


def test_v5e_sub_pod_has_no_torus_wrap():
    for accel in ("v5litepod-8", "v5litepod-16", "v5litepod-32", "v5litepod-64"):
        topo = SliceTopology.from_env(_env(accel))
        assert topo.wrap == (False, False, False), accel


def test_v5e_128_sub_pod_16_dim_does_not_wrap():
    """8x16 is a sub-pod: its 16-long dim has NO wrap links; only the
    full 16x16 pod is a torus."""
    topo = SliceTopology.from_env(_env("v5litepod-128"))
    assert topo.grid == (8, 16, 1)
    assert topo.wrap == (False, False, False)


def test_fallback_halves_tensorcore_names():
    """Out-of-table v4/v5p sizes: the suffix counts TensorCores, so the
    fallback must halve it (v5p-4096 = 2048 chips, not 4096)."""
    topo = SliceTopology.from_env(_env("v5p-4096"))
    assert topo.num_chips == 2048


def test_v5e_full_pod_wraps():
    topo = SliceTopology.from_env(_env("v5litepod-256"))
    assert topo.wrap == (True, True, False)
    # Corner chip sees 4 neighbours through the wrap.
    corner = next(c for c in topo.chips if c.coords == (0, 0, 0))
    coords = {n.coords for n in topo.neighbors(corner)}
    assert coords == {(1, 0, 0), (15, 0, 0), (0, 1, 0), (0, 15, 0)}


def test_v5e_16_corner_neighbours_mesh_semantics():
    topo = SliceTopology.from_env(_env("v5litepod-16"))
    corner = next(c for c in topo.chips if c.coords == (0, 0, 0))
    coords = {n.coords for n in topo.neighbors(corner)}
    assert coords == {(1, 0, 0), (0, 1, 0)}  # no phantom wrap links
    center = next(c for c in topo.chips if c.coords == (1, 1, 0))
    assert len(topo.neighbors(center)) == 4


# -- v4 3D cubes --------------------------------------------------------------


@pytest.mark.parametrize(
    "accel,grid,wrap",
    [
        # names count TensorCores; chips = count/2
        ("v4-8", (2, 2, 1), (False, False, False)),
        ("v4-32", (2, 2, 4), (False, False, True)),
        ("v4-128", (4, 4, 4), (True, True, True)),
        ("v5p-128", (4, 4, 4), (True, True, True)),
    ],
)
def test_v4_family_cubes(accel, grid, wrap):
    topo = SliceTopology.from_env(_env(accel))
    assert topo.grid == grid
    assert topo.wrap == wrap


def test_v4_cube_wrap_neighbours():
    topo = SliceTopology.from_env(_env("v4-128"))  # 4x4x4 torus
    corner = next(c for c in topo.chips if c.coords == (0, 0, 0))
    assert len(topo.neighbors(corner)) == 6  # all dims wrap


# -- bisection ----------------------------------------------------------------


def test_bisection_v5e_16_vs_32():
    t16 = SliceTopology.from_env(_env("v5litepod-16"))
    t32 = SliceTopology.from_env(_env("v5litepod-32"))
    # Cut across the largest dim: 4 links on both (x-width 4), no wrap.
    assert t16.bisection_gbps() == 4 * 400
    assert t32.bisection_gbps() == 4 * 400
    # The full pod doubles through wrap links.
    t256 = SliceTopology.from_env(_env("v5litepod-256"))
    assert t256.bisection_gbps() == 16 * 400 * 2


# -- runtime-provided bounds still win ---------------------------------------


def test_explicit_host_bounds_override_table():
    topo = SliceTopology.from_env(
        _env("v5litepod-16", TPU_HOST_BOUNDS="1,4,1", TPU_CHIPS_PER_HOST_BOUNDS="2,2,1")
    )
    assert topo.grid == (2, 8, 1)


# -- ICI-ordered mesh construction (VERDICT r1 weak #7) -----------------------


class _FakeDev:
    def __init__(self, i, coords):
        self.id = i
        self.coords = coords

    def __repr__(self):
        return f"d{self.id}{self.coords}"


def test_order_by_ici_sorts_raster():
    from dpu_operator_tpu.parallel.mesh import order_by_ici

    # Enumeration order scrambled vs the 2x4 physical grid.
    devs = [
        _FakeDev(0, (1, 3, 0)),
        _FakeDev(1, (0, 0, 0)),
        _FakeDev(2, (1, 0, 0)),
        _FakeDev(3, (0, 3, 0)),
        _FakeDev(4, (0, 1, 0)),
        _FakeDev(5, (1, 1, 0)),
        _FakeDev(6, (0, 2, 0)),
        _FakeDev(7, (1, 2, 0)),
    ]
    ordered = order_by_ici(devs)
    assert [d.coords for d in ordered] == [
        (0, 0, 0), (1, 0, 0), (0, 1, 0), (1, 1, 0),
        (0, 2, 0), (1, 2, 0), (0, 3, 0), (1, 3, 0),
    ]


def test_ring_adjacency_detection():
    import numpy as np

    from dpu_operator_tpu.parallel.mesh import ring_is_ici_adjacent

    class _FakeMesh:
        def __init__(self, devices, shape, names):
            self.devices = np.array(devices, dtype=object).reshape(shape)
            self.axis_names = names

    # tp pairs adjacent along x, sp hops adjacent along y: both True.
    raster = [
        _FakeDev(i, (x, y, 0)) for y in range(4) for x in range(2) for i in [0]
    ]
    m = _FakeMesh(raster, (2, 2, 2), ("dp", "sp", "tp"))
    assert ring_is_ici_adjacent(m, "tp") is True
    assert ring_is_ici_adjacent(m, "sp") is True
    # dp hops jump two rows — not single ICI hops.
    assert ring_is_ici_adjacent(m, "dp") is False

    # Scrambled order: even tp pairs break.
    scrambled = [raster[i] for i in (0, 5, 2, 7, 4, 1, 6, 3)]
    m2 = _FakeMesh(scrambled, (2, 2, 2), ("dp", "sp", "tp"))
    assert ring_is_ici_adjacent(m2, "tp") is False
    # No coords → None (virtual platform).
    plain = [object() for _ in range(2)]
    m3 = _FakeMesh(plain, (1, 1, 2), ("dp", "sp", "tp"))
    assert ring_is_ici_adjacent(m3, "tp") is None


# -- topology-aware allocation on the corrected grid --------------------------


def test_preferred_allocation_adjacency_on_v5e_16(tmp_root):
    """On the 4x4 grid, (0,1) and (1,0) are both adjacent to a pod pinned
    at (0,0); the 2x8 mis-grid would have put (0,2) nearer than (2,0)."""
    from dpu_operator_tpu.daemon.device_plugin import DevicePlugin
    from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb
    from dpu_operator_tpu.dpu_api.gen import kubelet_deviceplugin_pb2 as kdp

    topo = SliceTopology.from_env(_env("v5litepod-16"))

    class TopoVsp:
        def get_devices(self):
            devs = {}
            for chip in topo.chips:
                d = pb.Device(id=f"tpu{chip.index}-ep0", health=pb.HEALTHY)
                d.topology.coords = chip.coords_str
                devs[d.id] = d
            return devs

    dp = DevicePlugin(TopoVsp(), tmp_root)
    all_ids = [f"tpu{c.index}-ep0" for c in topo.chips]
    anchor = next(f"tpu{c.index}-ep0" for c in topo.chips if c.coords == (0, 0, 0))
    req = kdp.PreferredAllocationRequest(
        container_requests=[
            kdp.ContainerPreferredAllocationRequest(
                available_deviceIDs=all_ids,
                must_include_deviceIDs=[anchor],
                allocation_size=3,
            )
        ]
    )
    resp = dp.GetPreferredAllocation(req, None)
    chosen = list(resp.container_responses[0].deviceIDs)
    by_id = {f"tpu{c.index}-ep0": c.coords for c in topo.chips}
    picked = [by_id[d] for d in chosen]
    assert picked[0] == (0, 0, 0)
    # Greedy min-total-distance: every extra pick lands ICI-adjacent to
    # some already-chosen chip (ties may grow a line or an L; both are
    # contiguous). On the broken 2x8 grid the anchor's neighbourhood
    # would have been different chips entirely.
    for i, coords in enumerate(picked[1:], start=1):
        assert any(
            sum(abs(a - b) for a, b in zip(coords, prev)) == 1
            for prev in picked[:i]
        ), (coords, picked[:i])


def test_multislice_env_parsed():
    """MEGASCALE_* env → slice identity; absent or junk values read as
    the single-slice default instead of crashing topology modeling."""
    from dpu_operator_tpu.parallel import SliceTopology

    base = {
        "TPU_ACCELERATOR_TYPE": "v5litepod-8",
        "TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1",
        "TPU_HOST_BOUNDS": "1,2,1",
        "TPU_WORKER_ID": "0",
    }
    topo = SliceTopology.from_env(dict(base))
    assert (topo.slice_id, topo.num_slices) == (0, 1)

    topo = SliceTopology.from_env(
        dict(base, MEGASCALE_SLICE_ID="2", MEGASCALE_NUM_SLICES="4"))
    assert (topo.slice_id, topo.num_slices) == (2, 4)
    assert topo.to_dict()["sliceId"] == 2
    assert topo.to_dict()["numSlices"] == 4

    topo = SliceTopology.from_env(
        dict(base, MEGASCALE_SLICE_ID="banana", MEGASCALE_NUM_SLICES=""))
    assert (topo.slice_id, topo.num_slices) == (0, 1)

    # The operator's Allocate grant closes the loop: a pod holding the
    # TPU_SLICE_ID/TPU_NUM_SLICES env the device plugin exported builds
    # the same multislice topology without GCE metadata (MEGASCALE_*
    # still wins when both are present — it is the runtime's own view).
    topo = SliceTopology.from_env(
        dict(base, TPU_SLICE_ID="1", TPU_NUM_SLICES="2"))
    assert (topo.slice_id, topo.num_slices) == (1, 2)
    topo = SliceTopology.from_env(
        dict(base, TPU_SLICE_ID="1", TPU_NUM_SLICES="2",
             MEGASCALE_SLICE_ID="3", MEGASCALE_NUM_SLICES="4"))
    assert (topo.slice_id, topo.num_slices) == (3, 4)
    # Junk metadata must not MASK a valid operator grant, and a
    # one-sided pair must not produce slice_id >= num_slices.
    topo = SliceTopology.from_env(
        dict(base, TPU_SLICE_ID="1", TPU_NUM_SLICES="2",
             MEGASCALE_NUM_SLICES="banana"))
    assert (topo.slice_id, topo.num_slices) == (1, 2)
    topo = SliceTopology.from_env(dict(base, TPU_SLICE_ID="1"))
    assert (topo.slice_id, topo.num_slices) == (0, 1)


# -- ring-order selection (sharded serving replicas, ISSUE 8) -----------------


def test_ring_order_is_total_and_deterministic():
    from dpu_operator_tpu.parallel.topology import ring_order

    addrs = ["10.0.0.3:9411", "10.0.0.1:9411", "10.0.0.2:9411"]
    order = ring_order(addrs)
    assert sorted(order) == sorted(addrs)          # total: nothing lost
    assert order == ring_order(list(addrs))        # deterministic


def test_ring_order_stable_under_permutation():
    """Two coordinators discovering the same shard set in different
    orders (or a supervisor re-rendezvousing a restarted replica) must
    agree on the ring, or neighbours dial past each other forever."""
    import itertools

    from dpu_operator_tpu.parallel.topology import ring_order

    addrs = ["10.0.0.2:9500", "10.0.0.10:9500", "127.0.0.1:9001",
             "127.0.0.1:9002"]
    want = ring_order(addrs)
    for perm in itertools.permutations(addrs):
        assert ring_order(list(perm)) == want


def test_ring_order_numeric_ip_not_lexical():
    """10.0.0.10 sorts AFTER 10.0.0.2 (numeric octets): lexical order
    would interleave hosts across racks and churn the ring whenever a
    two-digit host joins."""
    from dpu_operator_tpu.parallel.topology import ring_order

    assert ring_order(["10.0.0.10:1", "10.0.0.2:1"]) == [
        "10.0.0.2:1", "10.0.0.10:1"]
    # Same host: port breaks the tie (several shards stacked on
    # loopback in tests).
    assert ring_order(["127.0.0.1:9002", "127.0.0.1:9001"]) == [
        "127.0.0.1:9001", "127.0.0.1:9002"]
    # Hostnames fall back to string order, after numeric IPs.
    assert ring_order(["shard-b:1", "10.9.9.9:1", "shard-a:1"]) == [
        "10.9.9.9:1", "shard-a:1", "shard-b:1"]


def test_ring_order_rejects_duplicate_addresses():
    from dpu_operator_tpu.parallel.topology import ring_order

    with pytest.raises(ValueError):
        ring_order(["10.0.0.1:9411", "10.0.0.1:9411"])
