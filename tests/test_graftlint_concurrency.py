"""Unit tests for the graftlint concurrency passes (GL012/GL013):
thread-root discovery shapes, root multiplicity, and the
interprocedural must-hold propagation — the model docs/static-analysis
.md § "the thread-root model" documents. The rule-level TP/NM pairs
live in tests/fixtures/graftlint/ with the other rules'."""

from dpu_operator_tpu.analysis import run_analysis

_HDR = "# graftlint-fixture-path: dpu_operator_tpu/serving/fx_conc.py\n"


def _findings(tmp_path, source, rule=None):
    p = tmp_path / "fx.py"
    p.write_text(_HDR + source)
    report = run_analysis([str(p)])
    if rule is None:
        return report.findings
    return [f for f in report.findings if f.rule == rule]


def test_http_handler_root_is_multi_instance(tmp_path):
    """ThreadingHTTPServer runs one thread per connection: a bare
    read-modify-write in a do_* method races ANOTHER connection's —
    one handler root must count as two threads."""
    src = (
        "class Handler:\n"
        "    hits = 0\n"
        "    def do_POST(self):\n"
        "        self.hits += 1\n"
    )
    got = _findings(tmp_path, src, "GL012")
    assert len(got) == 1 and "do_POST" in got[0].func, [
        f.format() for f in got]


def test_loop_spawned_thread_root_is_multi_instance(tmp_path):
    """N copies of one target racing each other need no second root
    kind (the bench client-fleet shape)."""
    src = (
        "import threading\n"
        "class Fan:\n"
        "    def start(self):\n"
        "        for _ in range(4):\n"
        "            threading.Thread(target=self._work).start()\n"
        "    def _work(self):\n"
        "        self.done += 1\n"
    )
    got = _findings(tmp_path, src, "GL012")
    assert len(got) == 1 and "_work" in got[0].func, [
        f.format() for f in got]


def test_worker_wrapper_and_lambda_targets_are_roots(tmp_path):
    """_GuardedWorker's callable arguments (including functions a
    lambda argument calls) run on the worker thread — the executor
    seam's step_fn/reset_fn idiom."""
    src = (
        "class Ex:\n"
        "    def __init__(self):\n"
        "        self._worker = _GuardedWorker(\n"
        "            'w', step_fn=lambda p: self._step(p),\n"
        "            reset_fn=self._zero)\n"
        "    def _step(self, p):\n"
        "        self.steps += 1\n"
        "    def _zero(self):\n"
        "        self.steps = 0\n"
        "    def kick(self):\n"
        "        self.steps += 1\n"
    )
    got = _findings(tmp_path, src, "GL012")
    funcs = {f.func for f in got}
    # Both bare RMWs fire (worker root via the wrapper, main root via
    # the public method); the _zero publish stays exempt.
    assert funcs == {"Ex._step", "Ex.kick"}, [f.format() for f in got]


def test_timer_callback_is_a_root(tmp_path):
    src = (
        "import threading\n"
        "class Beat:\n"
        "    def arm(self):\n"
        "        threading.Timer(5.0, self._fire).start()\n"
        "    def bump(self):\n"
        "        self.n += 1\n"
        "    def _fire(self):\n"
        "        self.n += 1\n"
    )
    got = _findings(tmp_path, src, "GL012")
    assert {f.func for f in got} == {"Beat.bump", "Beat._fire"}, [
        f.format() for f in got]


def test_thread_root_pragma_annotates_opaque_callbacks(tmp_path):
    """`# graftlint: thread-root` above a def marks a root the
    discovery pass cannot see (a callback registered with an opaque
    framework) — the documented escape hatch for new root shapes."""
    src = (
        "class W:\n"
        "    def register(self, bus):\n"
        "        bus.subscribe(self._on_event)\n"
        "        self.n += 1\n"
        "    # graftlint: thread-root\n"
        "    def _on_event(self):\n"
        "        self.n += 1\n"
    )
    got = _findings(tmp_path, src, "GL012")
    assert {f.func for f in got} == {"W.register", "W._on_event"}, [
        f.format() for f in got]


def test_must_hold_propagates_through_shared_helpers(tmp_path):
    """A helper ONLY ever called under the lock inherits it (entry
    must-hold): the _retire-under-_settle_lock shape must stay clean
    even though the helper itself never names the lock."""
    src = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = {}\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._run).start()\n"
        "    def _run(self):\n"
        "        while True:\n"
        "            with self._lock:\n"
        "                self._put('a')\n"
        "    def put_public(self):\n"
        "        with self._lock:\n"
        "            self._put('b')\n"
        "    def _put(self, k):\n"
        "        self.items[k] = 1\n"
    )
    got = _findings(tmp_path, src, "GL012")
    assert not got, [f.format() for f in got]


def test_one_bare_caller_breaks_must_hold(tmp_path):
    """Same shape, but one caller reaches the helper without the lock:
    must-hold intersects to empty and the helper's subscript store is
    the reported site."""
    src = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = {}\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._run).start()\n"
        "    def _run(self):\n"
        "        while True:\n"
        "            with self._lock:\n"
        "                self._put('a')\n"
        "    def put_public(self):\n"
        "        self._put('b')\n"
        "    def _put(self, k):\n"
        "        self.items[k] = 1\n"
    )
    got = _findings(tmp_path, src, "GL012")
    assert len(got) == 1 and got[0].func == "Box._put", [
        f.format() for f in got]


def test_root_entry_caps_must_hold_even_with_locked_callers(tmp_path):
    """A function that is BOTH a thread target and called from under a
    lock is not must-locked — the root enters it bare, so its bare
    compound write must still fire (the locked call site alone used to
    mask it)."""
    src = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = {}\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._pump).start()\n"
        "    def kick(self):\n"
        "        with self._lock:\n"
        "            self._pump()\n"
        "    def _pump(self):\n"
        "        self.items['k'] = 1\n"
    )
    got = _findings(tmp_path, src, "GL012")
    assert len(got) == 1 and got[0].func == "Box._pump", [
        f.format() for f in got]


def test_blocking_pedigree_propagates_and_timeout_bounds(tmp_path):
    """GL013's cross-root blocking sees THROUGH a helper (the
    send_msg -> sendall chain), and a timeout-ish keyword on the call
    bounds it — the armed-deadline near-miss stays silent."""
    base = (
        "import threading\n"
        "def push(sock, data{sig}):\n"
        "    sock.sendall(data)\n"
        "class Tx:\n"
        "    def __init__(self, peer):\n"
        "        self._lock = threading.Lock()\n"
        "        self._peer = peer\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._run).start()\n"
        "    def _run(self):\n"
        "        while True:\n"
        "            with self._lock:\n"
        "                push(self._peer, b'x'{arg})\n"
        "    def flush(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    fired = _findings(
        tmp_path, base.format(sig="", arg=""), "GL013")
    assert len(fired) == 1 and fired[0].func == "Tx._run", [
        f.format() for f in fired]
    bounded = _findings(
        tmp_path,
        base.format(sig=", timeout=None", arg=", timeout=1.0"),
        "GL013")
    assert not bounded, [f.format() for f in bounded]
