"""Device-plugin protocol tests with a fake kubelet — the counterpart of
the reference's Kind device-plugin assertions (dpusidemanager_test.go
waitAllNodesDpuAllocatable) without needing a real kubelet: we run both
ends of the v1beta1 protocol over real unix sockets."""

import concurrent.futures
import threading
import time

import grpc
import pytest

from dpu_operator_tpu.dpu_api import services
from dpu_operator_tpu.dpu_api.gen import kubelet_deviceplugin_pb2 as kdp
from dpu_operator_tpu.daemon.device_plugin import DevicePlugin
from dpu_operator_tpu.daemon.plugin import GrpcPlugin
from dpu_operator_tpu.vsp import MockVsp, VspServer


class FakeKubelet(services.KubeletRegistrationServicer):
    """Serves the Registration endpoint like kubelet does, then (like
    kubelet) dials back the plugin's socket and consumes ListAndWatch."""

    def __init__(self, plugin_dir_pm):
        self._pm = plugin_dir_pm
        self.registered = threading.Event()
        self.resource_name = None
        self.devices = {}
        self._lock = threading.Lock()
        self._server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=2))
        services.add_kubelet_registration(self, self._server)

    def start(self):
        sock = self._pm.kubelet_registry_socket()
        self._pm.ensure_socket_dir(sock)
        self._pm.remove_stale_socket(sock)
        self._server.add_insecure_port(f"unix://{sock}")
        self._server.start()

    def stop(self):
        self._server.stop(0)

    def Register(self, request, context):
        self.resource_name = request.resource_name
        endpoint = request.endpoint
        self.registered.set()
        t = threading.Thread(
            target=self._consume, args=(endpoint,), daemon=True, name="kubelet-law"
        )
        t.start()
        return kdp.Empty()

    def _consume(self, endpoint):
        import os

        sock = os.path.join(self._pm.kubelet_plugin_dir(), endpoint)
        channel = grpc.insecure_channel(f"unix://{sock}")
        stub = services.DevicePluginStub(channel)
        try:
            for resp in stub.ListAndWatch(kdp.Empty()):
                with self._lock:
                    self.devices = {d.ID: d.health for d in resp.devices}
        except grpc.RpcError:
            pass

    def allocatable(self):
        with self._lock:
            return dict(self.devices)


@pytest.fixture
def vsp_and_plugin(tmp_root):
    vsp = MockVsp()
    server = VspServer(vsp, tmp_root)
    server.start()
    plugin = GrpcPlugin(tmp_root.vendor_plugin_socket())
    yield vsp, plugin
    plugin.close()
    server.stop()


def wait_for(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return predicate()


def test_register_and_list_and_watch(vsp_and_plugin, tmp_root):
    vsp, plugin = vsp_and_plugin
    kubelet = FakeKubelet(tmp_root)
    kubelet.start()
    dp = DevicePlugin(plugin, tmp_root, poll_interval=0.1)
    try:
        dp.serve(register=True)
        assert kubelet.registered.wait(timeout=5)
        assert kubelet.resource_name == "tpu.dpu.io/endpoint"
        assert wait_for(lambda: len(kubelet.allocatable()) == 4)
        assert all(h == "Healthy" for h in kubelet.allocatable().values())

        # Inventory change propagates through the stream.
        plugin.set_num_endpoints(2)
        assert wait_for(lambda: len(kubelet.allocatable()) == 2)
    finally:
        dp.stop()
        kubelet.stop()


def test_allocate_healthy_and_unknown(vsp_and_plugin, tmp_root):
    vsp, plugin = vsp_and_plugin
    dp = DevicePlugin(plugin, tmp_root, poll_interval=0.1)
    try:
        dp.start()
        channel = grpc.insecure_channel(f"unix://{tmp_root.device_plugin_socket()}")
        stub = services.DevicePluginStub(channel)
        # Prime the health cache by consuming one ListAndWatch frame.
        stream = stub.ListAndWatch(kdp.Empty())
        first = next(iter(stream))
        assert len(first.devices) == 4

        req = kdp.AllocateRequest()
        creq = req.container_requests.add()
        creq.devices_ids.extend(["mock-ep0", "mock-ep1"])
        resp = stub.Allocate(req)
        assert resp.container_responses[0].envs["NF-DEV"] == "mock-ep0,mock-ep1"

        bad = kdp.AllocateRequest()
        bad.container_requests.add().devices_ids.append("nope")
        with pytest.raises(grpc.RpcError) as e:
            stub.Allocate(bad)
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

        # Mock devices are netdev-backed ("mockdevN"), not char devices:
        # the reference's env-only semantics apply — no DeviceSpec mounts,
        # no TPU env.
        resp = stub.Allocate(req)
        cresp = resp.container_responses[0]
        assert len(cresp.devices) == 0
        assert "TPU_VISIBLE_DEVICES" not in cresp.envs
        channel.close()
    finally:
        dp.stop()


def test_reregisters_after_kubelet_restart(vsp_and_plugin, tmp_root):
    """A restarted kubelet forgets every plugin and recreates its
    registry socket; the plugin watches the socket's identity and
    registers again, so the resource never silently drops off the node
    (the failure mode upstream device plugins guard against; the
    reference relies on the same re-registration behavior)."""
    vsp, plugin = vsp_and_plugin
    kubelet = FakeKubelet(tmp_root)
    kubelet.start()
    dp = DevicePlugin(plugin, tmp_root, poll_interval=0.1)
    try:
        dp.serve(register=True)
        assert kubelet.registered.wait(timeout=5)
        kubelet.stop()

        # "Restart": a brand-new kubelet process, fresh registry socket.
        kubelet2 = FakeKubelet(tmp_root)
        kubelet2.start()
        try:
            assert kubelet2.registered.wait(timeout=10), (
                "plugin never re-registered with the restarted kubelet"
            )
            assert kubelet2.resource_name == "tpu.dpu.io/endpoint"
            assert wait_for(lambda: len(kubelet2.allocatable()) == 4)
        finally:
            kubelet2.stop()
    finally:
        dp.stop()


def test_allocate_exports_slice_identity(tmp_root):
    """Multislice identity reaches the pod (VERDICT r3 Weak #5): on a
    simulated 2-slice MEGASCALE deployment, Allocate env carries
    TPU_SLICE_ID/TPU_NUM_SLICES from the VSP topology — a pod can place
    itself in the DCN mesh without scraping GCE metadata."""
    from dpu_operator_tpu.parallel.topology import SliceTopology
    from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

    topo = SliceTopology.from_env({
        "TPU_ACCELERATOR_TYPE": "v5litepod-8", "TPU_WORKER_ID": "0",
        "MEGASCALE_SLICE_ID": "1", "MEGASCALE_NUM_SLICES": "2",
    })
    vsp = TpuVsp(topology=topo)
    server = VspServer(vsp, tmp_root)
    server.start()
    plugin = GrpcPlugin(tmp_root.vendor_plugin_socket())
    dp = DevicePlugin(plugin, tmp_root, poll_interval=0.1)
    try:
        dp.start()
        channel = grpc.insecure_channel(
            f"unix://{tmp_root.device_plugin_socket()}")
        stub = services.DevicePluginStub(channel)
        next(iter(stub.ListAndWatch(kdp.Empty())))
        req = kdp.AllocateRequest()
        req.container_requests.add().devices_ids.extend(["tpu0-ep0"])
        cresp = stub.Allocate(req).container_responses[0]
        assert cresp.envs["TPU_SLICE_ID"] == "1"
        assert cresp.envs["TPU_NUM_SLICES"] == "2"
    finally:
        dp.stop()
        server.stop()


def test_allocate_mounts_tpu_chips(tmp_root):
    """Endpoints backed by /dev/accel* become usable inside the pod:
    Allocate returns DeviceSpec mounts for each distinct backing chip
    plus the TPU runtime env (visible devices, worker id, chip coords).
    The reference stops at env (deviceplugin.go:114-142) because its
    devices are network-plumbed; a char-device accelerator needs the
    node mounted or the grant is unusable (round-2 verdict Missing #2)."""
    from dpu_operator_tpu.parallel.topology import SliceTopology
    from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

    topo = SliceTopology.from_env(
        {"TPU_ACCELERATOR_TYPE": "v5litepod-8", "TPU_WORKER_ID": "0"}
    )
    vsp = TpuVsp(topology=topo)
    server = VspServer(vsp, tmp_root)
    server.start()
    plugin = GrpcPlugin(tmp_root.vendor_plugin_socket())
    dp = DevicePlugin(plugin, tmp_root, poll_interval=0.1)
    try:
        dp.start()
        channel = grpc.insecure_channel(f"unix://{tmp_root.device_plugin_socket()}")
        stub = services.DevicePluginStub(channel)
        first = next(iter(stub.ListAndWatch(kdp.Empty())))
        ids = {d.ID for d in first.devices}
        assert {"tpu0-ep0", "tpu0-ep1", "tpu1-ep0"} <= ids

        from google.protobuf import empty_pb2
        inventory = vsp.GetDevices(empty_pb2.Empty(), None).devices

        # Two endpoints of the SAME chip: one DeviceSpec, deduped.
        req = kdp.AllocateRequest()
        req.container_requests.add().devices_ids.extend(["tpu0-ep0", "tpu0-ep1"])
        cresp = stub.Allocate(req).container_responses[0]
        assert [d.host_path for d in cresp.devices] == ["/dev/accel0"]
        assert cresp.devices[0].container_path == "/dev/accel0"
        assert cresp.devices[0].permissions == "rw"
        assert cresp.envs["TPU_VISIBLE_DEVICES"] == "0"
        assert cresp.envs["TPU_WORKER_ID"] == "0"
        assert cresp.envs["TPU_CHIP_COORDS"] == inventory["tpu0-ep0"].topology.coords
        assert cresp.envs["NF-DEV"] == "tpu0-ep0,tpu0-ep1"

        # Endpoints on two different chips: two mounts, both visible.
        req = kdp.AllocateRequest()
        req.container_requests.add().devices_ids.extend(["tpu2-ep0", "tpu1-ep0"])
        cresp = stub.Allocate(req).container_responses[0]
        assert [d.host_path for d in cresp.devices] == ["/dev/accel1", "/dev/accel2"]
        assert cresp.envs["TPU_VISIBLE_DEVICES"] == "1,2"
        assert cresp.envs["TPU_CHIP_COORDS"] == ";".join(
            inventory[f"tpu{i}-ep0"].topology.coords for i in (1, 2)
        )
        channel.close()
    finally:
        dp.stop()
        plugin.close()
        server.stop()


def test_preferred_allocation_prefers_ici_adjacent(tmp_root):
    """GetPreferredAllocation picks ICI-adjacent chips' endpoints (a
    TPU-first capability the reference leaves unimplemented)."""
    from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb
    from dpu_operator_tpu.dpu_api.gen import kubelet_deviceplugin_pb2 as kdp

    class TopoVsp:
        def get_devices(self):
            devs = {}
            for dev_id, coords in {
                "ep-a": "0,0,0",
                "ep-b": "3,3,0",
                "ep-c": "0,1,0",
                "ep-d": "3,2,0",
            }.items():
                d = pb.Device(id=dev_id, health=pb.HEALTHY)
                d.topology.coords = coords
                devs[dev_id] = d
            return devs

        def set_num_endpoints(self, n):
            return n

    from dpu_operator_tpu.daemon.device_plugin import DevicePlugin

    dp = DevicePlugin(TopoVsp(), tmp_root)
    opts = dp.GetDevicePluginOptions(kdp.Empty(), None)
    assert opts.get_preferred_allocation_available is True

    req = kdp.PreferredAllocationRequest(
        container_requests=[
            kdp.ContainerPreferredAllocationRequest(
                available_deviceIDs=["ep-a", "ep-b", "ep-c", "ep-d"],
                must_include_deviceIDs=["ep-a"],
                allocation_size=2,
            )
        ]
    )
    resp = dp.GetPreferredAllocation(req, None)
    # ep-c at (0,1,0) is the ICI neighbour of ep-a at (0,0,0).
    assert list(resp.container_responses[0].deviceIDs) == ["ep-a", "ep-c"]

    # Without must_include: picks a tight pair deterministically.
    req2 = kdp.PreferredAllocationRequest(
        container_requests=[
            kdp.ContainerPreferredAllocationRequest(
                available_deviceIDs=["ep-b", "ep-d"],
                allocation_size=2,
            )
        ]
    )
    resp2 = dp.GetPreferredAllocation(req2, None)
    assert set(resp2.container_responses[0].deviceIDs) == {"ep-b", "ep-d"}


def test_id_policy_enforced_per_side(tmp_root):
    """Host side only advertises addressable IDs (PCI or tpuN-epM); DPU
    side allows abstract ids (reference dpudevicehandler.go:58-73,
    resolving VERDICT r1 Weak #3)."""
    from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb

    class MixedVsp:
        def get_devices(self):
            out = {}
            for dev_id in (
                "tpu0-ep0", "0000:00:05.0", "mock-ep3", "some-uuid", "eth0",
            ):
                d = pb.Device(id=dev_id, health=pb.HEALTHY)
                out[dev_id] = d
            return out

    host_dp = DevicePlugin(MixedVsp(), tmp_root, id_policy="host")
    assert set(host_dp._fetch_devices()) == {
        "tpu0-ep0", "0000:00:05.0", "mock-ep3",
    }

    dpu_dp = DevicePlugin(MixedVsp(), tmp_root, id_policy="dpu")
    assert set(dpu_dp._fetch_devices()) == {
        "tpu0-ep0", "0000:00:05.0", "mock-ep3", "some-uuid", "eth0",
    }

    with pytest.raises(ValueError):
        DevicePlugin(MixedVsp(), tmp_root, id_policy="nope")


def test_sides_construct_with_their_policies(tmp_root):
    """HostSideManager enforces 'host', DpuSideManager 'dpu' — the flag
    is live on the real construction paths, not dead code."""
    from dpu_operator_tpu.daemon.dpu_side import DpuSideManager
    from dpu_operator_tpu.daemon.host_side import HostSideManager
    from dpu_operator_tpu.utils import PathManager

    host = HostSideManager(
        object(), "n1", path_manager=tmp_root, register_device_plugin=False
    )
    assert host.device_plugin._id_policy == "host"

    dpu_pm = PathManager(root=str(tmp_root.root) + "/dpu")
    dpu = DpuSideManager(
        object(), "n1", path_manager=dpu_pm, register_device_plugin=False
    )
    assert dpu.device_plugin._id_policy == "dpu"
