# graftlint-fixture-path: dpu_operator_tpu/cni/fx_gl005_tp.py
"""GL005 true positive: broad excepts in a CNI path that neither
re-raise, log, nor narrow — the failed teardown's only trace,
erased (the _rollback lease-leak shape)."""


def rollback(ipam, owner):
    try:
        ipam.release(owner)
    except Exception:
        pass


def teardown(links):
    for name in links:
        try:
            links[name].delete()
        except BaseException:
            continue
