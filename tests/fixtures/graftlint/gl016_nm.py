# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl016_nm.py
"""GL016 near-misses that must stay silent: detach handed to the
transfer plane (the handoff hook / the stream's send_pages), detach
paired with the failure-path reattach, detach settled through a
release, and .detach() on receivers with no lease pedigree (a torch
tensor, a thread)."""


class Router:
    def hand_off(self, slot, req):
        # Handed to the transfer plane: the handoff callable owns it.
        detach = self.executor.kv_detach_slot(slot)
        self.handoff(req, detach)

    def ship(self, req, detach):
        # Streamed with a failure-path ack: reattach on any raise.
        lease = detach["lease"]
        lease.detach()
        try:
            return self.stream.send_pages(self.meta(req), self.planes)
        except Exception:
            lease.reattach()
            raise

    def teardown(self, detach):
        # Settled: release IS the success/teardown ack.
        detach["lease"].release()

    def unrelated(self, grad, worker):
        # No lease pedigree: autograd detach and a thread detach.
        flat = grad.detach()
        worker.detach()
        return flat
