# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl010_nm.py
"""GL010 near-misses that must stay silent: a timeout/deadline
argument on the call, the scheduler's blocked_since watchdog bracket,
a one-shot receive outside any loop, and gc.collect (no peer to hang
on). The module-level settimeout grant is its own near-miss, exercised
in tests/test_graftlint.py (it silences a whole module, so it cannot
share this file)."""
import gc


def pump_frames(sock, frames, io_timeout):
    while True:
        msg, data = recv_msg(sock, timeout=io_timeout)  # bounded call
        if not data:
            return
        frames.append(data)


def gather_with_deadline(shards, handles, step_timeout_s):
    out = []
    for h in handles:
        out.append(shards.collect(h, timeout=step_timeout_s))
    return out


class WatchdoggedLoop:
    def run(self, executor, clock):
        while not self.stopped:
            self.blocked_since = clock()   # the PR 5 watchdog hook
            tokens = executor.collect(self.prev)
            self.blocked_since = None
            self.retire(tokens)


def warmup(executor):
    # One-shot constructor warmup: not a transport loop.
    return executor.collect(executor.submit([]))


def sweep_garbage():
    while True:
        gc.collect()                   # no pedigree, no peer


def recv_msg(sock, timeout):
    sock.settimeout2 = timeout         # stub for the fixture
    return None, b""
