# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl014_tp.py
"""GL014 true positives: wall-clock time.time() feeding
duration/deadline arithmetic in a serving module. Wall clocks slew
and step under NTP — a span length, a deadline comparison, or a
watchdog age computed from them is garbage exactly when nobody is
watching. Both shapes fire: the direct operand, and the
assign-then-subtract two lines later."""
import time


def step_duration(run_step):
    t0 = time.time()                      # later subtracted: fires
    run_step()
    return time.time() - t0               # direct operand: fires


def deadline_lapsed(deadline_mono):
    # Wall stamp compared against a monotonic deadline — garbage
    # always, not just during an NTP step.
    return time.time() >= deadline_mono   # direct operand: fires
