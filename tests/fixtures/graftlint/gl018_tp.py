# graftlint-fixture-path: dpu_operator_tpu/serving/disagg/fx_gl018_tp.py
"""GL018 true positives: per-rank KV geometry re-derived inline in a
transfer module. Two findings: a resident-capacity split that ignores
the spec's uneven-tail partition, and an inline block-range formula —
one finding for the whole compound expression (outermost match), not
one per operator."""


class Streamer:
    def plan_capacity(self):
        # TP 1: the spec's rank_blocks gives rank world-1 the tail
        # remainder; this even split disagrees with it.
        per_rank = self.num_blocks // self.world
        return per_rank

    def rank_range(self, rank, world):
        # TP 2 (ONE finding): the classic inline partition — drifts
        # the moment the spec's formula or axis changes.
        lo = rank * self.num_blocks // world
        return lo
