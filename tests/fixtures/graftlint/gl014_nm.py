# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl014_nm.py
"""GL014 near-misses that must stay silent: wall time recorded as a
VALUE (the right clock for human-facing timestamps — a log field, a
snapshot's wall_time, a plain return) with no arithmetic on it, and
the monotonic clocks every duration in this tree is supposed to
use."""
import time


def snapshot_header(reason):
    # Wall time as a human-facing stamp: a value, never an operand.
    return {"reason": reason, "wall_time": time.time()}


def step_duration_monotonic(run_step):
    t0 = time.monotonic()                 # the required clock
    run_step()
    return time.monotonic() - t0


def wall_stamp():
    return time.time()                    # returned, not arithmetic
