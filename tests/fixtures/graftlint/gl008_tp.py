# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl008_tp.py
"""GL008 true positives: log lines on the request path that bind no
request id — the pre-ISSUE-6 serving-plane shape, where an admission
failure logged only the replica name and the one fact that mattered
(WHICH request) was discarded at the moment it existed. Two findings:
one directly in a request-scoped root, one in a helper reachable from
it."""
import logging

log = logging.getLogger(__name__)


class Batcher:
    def _pop_admissions(self, free):
        for req in free:
            try:
                self._place(req)
            except Exception:
                # TP 1: request-scoped, no request id anywhere.
                log.exception("batcher %s: admit failed", self.replica)

    def _settle(self, req):
        if req.done:
            self._evict(req)
        return req.done

    def _evict(self, req):
        # TP 2: reachable from _settle (request-scoped), still only
        # replica context.
        log.warning("evicting abandoned slot on %s", self.replica)

    def _place(self, req):
        raise NotImplementedError
