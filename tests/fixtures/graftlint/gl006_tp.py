# graftlint-fixture-path: dpu_operator_tpu/parallel/fx_gl006_tp.py
"""GL006 true positive: a collective over an axis name ('pd' — a typo
of 'dp') that no mesh construction declares; surfaces three layers away
as an opaque tracing error, or silently with check_vma=False."""
import jax
from jax.sharding import Mesh, PartitionSpec as P

AXES = ("dp", "tp")


def make(devs, x):
    mesh = Mesh(devs, axis_names=AXES)
    spec = P("dp", None)

    def body(v):
        return jax.lax.psum(v, "pd")  # typo: undeclared axis

    return mesh, spec, body(x)
