# graftlint-fixture-path: dpu_operator_tpu/cni/fx_gl005_nm.py
"""GL005 near-misses that must stay silent: a broad except that LOGS
what it swallowed, one that re-raises, and a NARROW handler that may
stay quiet (the caller chose the types)."""
import logging

log = logging.getLogger(__name__)


def rollback(ipam, owner, metrics):
    try:
        ipam.release(owner)
    except Exception as e:
        log.warning("release for %s failed: %s", owner, e)


def handle(req, handler):
    try:
        return handler(req)
    except Exception:
        metrics_mark_error(req)
        raise


def garp(sock, frame):
    try:
        sock.send(frame)
    except OSError:
        return False  # narrow: best-effort announce


def metrics_mark_error(req):
    pass
