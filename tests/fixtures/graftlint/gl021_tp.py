# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl021_tp.py
"""GL021 true positives: transitions the runtime ledgers raise on.
Three findings, one per machine family: allocator blocks released
twice, a lease detached while already mid-transfer, and a host-tier
checkin of a key/owner pair the tier no longer holds."""


class Plane:
    def double_release(self, owner):
        blocks = self.allocator.acquire(4, owner)
        self.allocator.release(blocks, owner)
        # TP 1: released twice — the refcount ledger raises here.
        self.allocator.release(blocks, owner)

    def double_detach(self, owner):
        lease = KVLease(self.allocator, 1, owner, [1], (), 0)
        try:
            lease.detach()
            # TP 2: detach of an in-transit lease — the PR 14
            # double-detach ValueError, caught before runtime.
            lease.detach()
        finally:
            lease.release()

    def double_checkin(self, key, owner):
        entry = self.tier.checkout(key, owner)
        if entry is None:
            return None
        self.tier.checkin(key, owner)
        # TP 3: checkin of a pin already returned — "not held by".
        self.tier.checkin(key, owner)
        return entry
