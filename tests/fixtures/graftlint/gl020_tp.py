# graftlint-fixture-path: dpu_operator_tpu/serving/kvcache/fx_gl020_tp.py
"""GL020 true positives: the provisionally-advanced slot cursor read
by rollback-unaware consumers. Two findings: a stats export that
reports ctx as 'tokens generated', and a cache-publish helper that
sizes its insert from ctx — both see positions whose KV may still be
rejected by the in-flight verify window."""


class Executor:
    def kv_stats(self):
        # TP 1: ctx runs past the confirmed watermark while a
        # speculative window is in flight — exporting it as progress
        # counts tokens the verify step may throw away.
        total = 0
        for st in self._states:
            if st is not None:
                total += st.ctx
        return {"generated_tokens": total}

    def publish_finished(self, slot, tokens):
        # TP 2: sizing the prefix-cache insert from the provisional
        # cursor publishes unverified speculative KV — the bug class
        # the watermark exists to prevent.
        st = self._states[slot]
        self.prefix.insert(tokens[:st.ctx], st.lease.blocks)
