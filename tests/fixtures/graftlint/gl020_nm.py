# graftlint-fixture-path: dpu_operator_tpu/serving/kvcache/fx_gl020_nm.py
"""GL020 near-misses that must stay silent: ctx reads inside the
plan/collect sites that own the provisional advance and its rollback,
the settled-token rebuild, watermark-aware consumers, frozen
step-plan snapshots, and locals that merely share the name."""


class Executor:
    def _plan_step(self):
        # The advance's owner: planning reads AND moves the cursor.
        for s, st in enumerate(self._states):
            if st is not None:
                self._ctx_vec[s] = st.ctx
                st.ctx += 1

    def _collect_spec(self, handle, raw):
        # The rollback's owner: acceptance truncates ctx back to the
        # watermark under the owner guard.
        for s, st in enumerate(self._states):
            if st is not None and st.ctx > st.confirmed:
                st.ctx = st.confirmed

    def _reattach(self, slot, req):
        # Cursors rebuilt from SETTLED tokens — durable truth.
        st = self._states[slot]
        st.ctx = len(req.prompt_tokens) + len(req.tokens)
        return st.ctx

    def export_pages(self, slot):
        # Watermark-aware: clamping to confirmed is exactly the
        # discipline the rule wants; the ctx read rides along.
        st = self._states[slot]
        n = min(st.ctx, st.confirmed)
        return self._gather(st.lease.blocks, n)

    def _dispatch(self, plan):
        # A step plan's ctx is a frozen snapshot taken at plan time —
        # dispatch geometry, not live slot state.
        return self._step(plan.host_tok, plan.ctx, plan.n_new)

    def window_size(self, base, k):
        # A local that merely shares the name.
        ctx = base + k
        return ctx
