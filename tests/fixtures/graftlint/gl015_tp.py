# graftlint-fixture-path: dpu_operator_tpu/serving/kvcache/fx_gl015_tp.py
"""GL015 true positives: resident fp32 pools with no dtype policy.
Two findings: an explicit float32 pool allocation, and the sneakier
dtype-less form (the allocator default IS fp32 — the exact shape a
refactor reintroduces without anyone typing 'float32')."""

import numpy as np


class PoolPlane:
    def init_pools(self, shape):
        # TP 1: explicit fp32, no marker — 4x the HBM per slot, green
        # tests, silent capacity loss.
        self._kpool = np.zeros(shape, np.float32)
        return self._kpool

    def scratch_pool(self, n, bs, h, dh):
        # TP 2: implicit dtype — the default is fp32 whether or not
        # anyone wrote it down.
        vpool = np.zeros((n, bs, h, dh))
        return vpool
