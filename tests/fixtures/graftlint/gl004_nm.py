# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl004_nm.py
"""GL004 near-misses that must stay silent: dict .get under a lock
(instant), str.join (no receiver hint), and Condition.wait on the
condition wrapping the SAME held lock (wait RELEASES it — the
AdmissionQueue pattern)."""
import threading


class Queue:
    def __init__(self):
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._cache = {}

    def get_many(self, key, timeout):
        with self._lock:
            entry = self._cache.get(key)      # dict get: instant
            label = ", ".join(["a", "b"])     # str join: no hint
            if entry is None and timeout > 0:
                self._nonempty.wait(timeout)  # releases _lock
            return entry, label
