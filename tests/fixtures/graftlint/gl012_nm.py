# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl012_nm.py
"""GL012 near-miss: two thread roots write shared attributes, but
every write is benign — whole-attribute assignments (one GIL-atomic
STORE_ATTR: the blocked_since publish idiom) and deque appends (the
audited-atomic allowlist: obs/trace.py's lock-free hot path). No lock
anywhere, and none needed."""

import threading
import time
from collections import deque


class Probe:
    def __init__(self):
        self.last_beat = None  # published whole-value, read-tolerant
        self.events = deque()  # deque: append/popleft are atomic
        self._stop = threading.Event()

    def start(self):
        threading.Thread(target=self._beat, daemon=True).start()
        threading.Thread(target=self._watch, daemon=True).start()

    def _beat(self):
        while not self._stop.is_set():
            self.last_beat = time.monotonic()   # atomic publish
            self.events.append(("beat", self.last_beat))

    def _watch(self):
        while not self._stop.is_set():
            beat = self.last_beat
            if beat is not None and time.monotonic() - beat > 5.0:
                self.last_beat = None           # publish, second root
                self.events.append(("stale", beat))
