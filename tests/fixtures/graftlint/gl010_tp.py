# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl010_tp.py
"""GL010 true positives: blocking transport receives in a loop with no
deadline anywhere — no timeout argument on the call, no settimeout
discipline in the module, no blocked_since publication in the
function. A dead or wedged peer parks these threads forever, invisibly
to the supervisor's watchdog."""


def pump_frames(sock, frames):
    while True:
        data = sock.recv(65536)        # unbounded: peer gone = forever
        if not data:
            return
        frames.append(data)


def drive_decode(executor, steps):
    tokens = []
    for handle in steps:
        tokens.append(executor.collect(handle))  # unbounded collect
    return tokens
