# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl008_nm.py
"""GL008 near-misses that must stay silent: a request-scoped log that
carries req.request_id as a message arg, one that binds context via
extra= (the JSON-lines formatter's field channel), and replica-
LIFECYCLE logging outside the request-scoped call graph — "replica
restarted" describes a replica, not a request, and must not be forced
to invent one."""
import logging

log = logging.getLogger(__name__)


class Batcher:
    def _pop_admissions(self, free):
        for req in free:
            try:
                self._place(req)
            except Exception:
                # rid in the message args: grep-by-request works.
                log.exception("batcher %s: admit failed (request %s)",
                              self.replica, req.request_id)

    def _settle(self, req):
        if req.done:
            # extra= carries the id into the JSON line's fields.
            log.warning("evicting abandoned slot",
                        extra={"request_id": req.request_id})
        return req.done

    def _run(self):
        # Replica lifecycle, not request-scoped: no request exists to
        # bind, and the function is outside the request-scoped graph.
        log.error("batcher %s: replica failed; awaiting supervision",
                  self.replica)

    def _place(self, req):
        raise NotImplementedError
