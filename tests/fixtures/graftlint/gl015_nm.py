# graftlint-fixture-path: dpu_operator_tpu/serving/kvcache/fx_gl015_nm.py
"""GL015 near-misses that must stay silent: the int8 resident
default, fp32 pools carrying the kv-dtype-policy marker (trailing and
comment-block-above forms), and fp32 allocations that are not pools
(per-block scale vectors, staging rows)."""

import numpy as np


class PoolPlane:
    def init_pools(self, shape, n):
        # The resident default: int8 codes — no marker needed.
        self._kpool = np.zeros(shape, np.int8)
        # Not a pool: the per-block scale vector rides fp32 always.
        kscale = np.ones((n,), np.float32)
        # kv-dtype-policy: fp32 reference layout for the exact
        # byte-identical invariance lanes; resident default is int8.
        ref_kpool = np.zeros(shape, np.float32)
        vpool = np.zeros(shape, np.float32)  # kv-dtype-policy: ditto
        return self._kpool, kscale, ref_kpool, vpool

    def staging(self, rows, d):
        # Not pool-named: a host staging buffer is not residency.
        stage = np.empty((rows, d), np.float32)
        return stage

    def wrapped_pool(self, n, bs, h, dh):
        # Multi-line allocation with the marker on the CLOSING line:
        # still an explicit policy statement.
        dbg_kpool = np.zeros(
            (n, bs, h, dh),
            np.float32)  # kv-dtype-policy: fp32 debug mirror
        return dbg_kpool
