# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl003_nm.py
"""GL003 near-miss: the name binds BEFORE the try (the fixed `_admit`
shape) — the handler can always run it; rebinding inside the try is
fine. Must NOT fire."""


def admit(free, queue, slots):
    for req in queue:
        i = free.pop(0)
        try:
            slots[i] = req
            i = i + 0  # rebind inside try: still bound before
        except Exception:
            slots[i] = None
            req.fail("admission failed")
