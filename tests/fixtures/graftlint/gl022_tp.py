# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl022_tp.py
"""GL022 true positives: a lifecycle object live in a non-terminal
state on an exception path with no release in reach. Two findings:
the bench/kv_match_prefix shape (forked blocks released on the happy
path only — a raise between fork and release strands them, which
GL009's local pairing cannot see), and a tier pin surviving a
swallowed exception to the function's normal exit."""


class Plane:
    def match_then_release_happy_path_only(self, tokens, owner):
        blocks, cached = self.prefix.match_and_fork(tokens, owner)
        # TP 1: fingerprint() can raise -> `blocks` still acquired on
        # the unwind, and nothing up-stack holds them.
        meta = self.spec.fingerprint(tokens)
        self.allocator.release(blocks, owner)
        return meta, cached

    def swallow_keeps_pin(self, key, owner):
        entry = self.tier.checkout(key, owner)
        if entry is None:
            return False
        try:
            self.decode_segments(key)
        except Exception:
            # TP 2: the failure is swallowed but the pin is never
            # checked in on this path — tier.assert_clean() will name
            # it at teardown.
            log.warning("restore failed for %s", key)
            return False
        self.tier.checkin(key, owner)
        return True
