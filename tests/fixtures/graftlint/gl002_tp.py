# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl002_tp.py
"""GL002 true positive: host-device syncs inside the decode hot path —
DecodeStep.__call__ and the pipelined scheduler loop (the PR 2
np.asarray-per-step decode loop this rule exists to keep dead)."""
import jax
import numpy as np


class DecodeStep:
    def __call__(self, x, updates=()):
        y = self._step(x)
        return float(y)  # blocks dispatch until y is on host


def _run_pipelined(ex, state):
    while True:
        tok = ex.submit(state)
        ex.blocked_since = 0.0  # watchdog bracket (GL010's near-miss)
        state = np.asarray(ex.collect(tok))  # materializes every step
        ex.blocked_since = None
        if tok.item() < 0:  # device round-trip per step
            return state
