# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl013_tp.py
"""GL013 true positives: (a) the two ingest/flush roots nest the same
two locks in OPPOSITE orders — the classic inversion that deadlocks
the moment both roots enter at once (one finding per closing edge);
(b) a third root blocks on the wire while holding a lock the other
roots need — the PR 8 ShardProcessSet shape."""

import threading


class Ledger:
    def __init__(self, peer):
        self._meta_lock = threading.Lock()
        self._data_lock = threading.Lock()
        self._peer = peer
        self.rows = {}
        self._stop = threading.Event()

    def start(self):
        threading.Thread(target=self._ingest, daemon=True).start()
        threading.Thread(target=self._flush, daemon=True).start()
        threading.Thread(target=self._report, daemon=True).start()

    def _ingest(self):
        while not self._stop.is_set():
            with self._meta_lock:          # meta -> data
                with self._data_lock:
                    self.rows["head"] = 1

    def _flush(self):
        while not self._stop.is_set():
            with self._data_lock:          # data -> meta: inversion
                with self._meta_lock:
                    self.rows["head"] = 0

    def _report(self):
        while not self._stop.is_set():
            with self._meta_lock:
                self._peer.sendall(b"rows")  # blocks holding meta
