# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl023_nm.py
"""GL023 near-misses that must stay silent: seams the chaos matrix
already drives (their literals appear in tests/), a covered site
reaching the seam through a fault_site= default, and a dynamic
f-string site (no literal — the base string is collected at its
declaration site instead, never here)."""
from dpu_operator_tpu import faults


def restore(buf):
    faults.fire("kvtier.restore")
    return buf


def send(payload, fault_site="kvstream.send"):
    faults.fire(fault_site)
    return payload


def dynamic(name):
    faults.fire(f"dyn.{name}")
    return name
