# graftlint-fixture-path: dpu_operator_tpu/serving/kvcache/fx_gl017_tp.py
"""GL017 true positives: collect-owned decode state written at PLAN
time. Two findings: the phantom-step counter inflation (decode_tokens
bumped while planning — the exact class PR 7's review fixed by hand),
and a submit-path last_token stamp (a retired request's emit can land
in a freshly re-admitted slot state)."""


class Executor:
    def _plan_step(self):
        plan = self._build_plan()
        # TP 1: counted at plan time — the pipelined loop's phantom
        # post-retire step inflates throughput by ~1/max_tokens.
        self.decode_tokens += int(plan.n_new.sum())
        return plan

    def submit(self, updates=()):
        plan = self._plan_step()
        raw = self._dispatch(plan)
        for s, st in enumerate(self._states):
            if st is not None and plan.emit[s]:
                # TP 2: stamped before collect attributes the emit to
                # the state that planned it.
                st.last_token = int(plan.host_tok[s, 0])
        return raw
