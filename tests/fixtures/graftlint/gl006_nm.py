# graftlint-fixture-path: dpu_operator_tpu/parallel/fx_gl006_nm.py
"""GL006 near-misses that must stay silent: collectives over DECLARED
axes (including via the module AXES constant and tuple args), and an
axis passed as a VARIABLE (the caller's contract, unknowable here)."""
import jax
from jax.sharding import Mesh, PartitionSpec as P

AXES = ("dp", "tp")


def make(devs, x, axis):
    mesh = Mesh(devs, AXES)
    spec = P(("dp", "tp"), None)
    a = jax.lax.psum(x, "dp")
    b = jax.lax.pmean(x, ("dp", "tp"))
    c = jax.lax.psum(x, axis)  # variable axis: caller's contract
    return mesh, spec, a, b, c
