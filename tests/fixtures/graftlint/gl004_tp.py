# graftlint-fixture-path: dpu_operator_tpu/daemon/fx_gl004_tp.py
"""GL004 true positive: a mutex held across subprocess + socket +
thread-join work — every other contender (heartbeat, kubelet poll)
queues behind the slow path (the TpuVsp.Init-vs-Ping stall)."""
import subprocess
import threading

_lock = threading.Lock()


def reapply(sock, worker_thread, payload):
    with _lock:
        subprocess.run(["ip", "link", "set", "up"], check=True)
        sock.sendall(payload)
        worker_thread.join()
