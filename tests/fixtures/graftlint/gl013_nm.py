# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl013_nm.py
"""GL013 near-misses that must stay silent: the same two locks nested
in the SAME order on both roots (no cycle), a bounded Condition.wait
under the shared lock (timeout + wait releases the lock it wraps),
and wire blocking under a lock only ONE root ever takes (no
contender to stall)."""

import threading


class Ledger:
    def __init__(self, peer):
        self._meta_lock = threading.Lock()
        self._cv = threading.Condition(self._meta_lock)
        self._data_lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._peer = peer
        self.rows = {}
        self._stop = threading.Event()

    def start(self):
        threading.Thread(target=self._ingest, daemon=True).start()
        threading.Thread(target=self._flush, daemon=True).start()
        threading.Thread(target=self._pump, daemon=True).start()
        threading.Thread(target=self._push, daemon=True).start()

    def _ingest(self):
        while not self._stop.is_set():
            with self._meta_lock:          # meta -> data
                with self._data_lock:
                    self.rows["head"] = 1

    def _flush(self):
        while not self._stop.is_set():
            with self._meta_lock:          # same order: no cycle
                with self._data_lock:
                    self.rows["head"] = 0

    def _pump(self):
        while not self._stop.is_set():
            with self._meta_lock:
                # Bounded, and wait() releases the wrapped lock while
                # parked — the AdmissionQueue shape, not a stall.
                self._cv.wait(0.05)
                self.rows["tail"] = 1

    def _push(self):
        while not self._stop.is_set():
            # _io_lock has exactly one acquiring root: nobody queues
            # behind the send.
            with self._io_lock:
                self._peer.sendall(b"rows")
