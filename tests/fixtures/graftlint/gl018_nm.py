# graftlint-fixture-path: dpu_operator_tpu/serving/disagg/fx_gl018_nm.py
"""GL018 near-misses that must stay silent: geometry-only arithmetic,
the fabric plane's shard split over non-KV state, and per-rank
geometry taken from the KVSpec rank_* family (the discipline the rule
enforces)."""


class Streamer:
    def blocks_for(self, tokens):
        # Geometry-only: tokens to block count, no shard topology.
        return (tokens + self.block_size - 1) // self.block_size

    def row_split(self, world):
        # Shard arithmetic over NON-KV state: the fabric plane's row
        # shard of the activation width — its own subsystem.
        return self.d // world

    def owned(self, spec, rank, blocks):
        # The derived way: the spec's partition is the single truth.
        lo, hi = spec.rank_blocks(rank, self.num_blocks)
        return [b for b in blocks if lo <= b < hi]

    def wire_bytes(self, spec, rank, codec, count):
        return spec.rank_wire_block_nbytes(rank, codec) * count
