# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl009_tp.py
"""GL009 true positives: KV pages acquired with no way back. Two
findings: a bare allocator.acquire whose blocks are stashed on an ad
hoc attribute (no release, no lease — the leak ledger fails the
teardown), and a prefix-tree fork held the same way."""


class Batcher:
    def admit(self, req):
        # TP 1: acquired, stashed, never released, no KVLease.
        blocks = self.allocator.acquire(4, req.request_id)
        self.tables[req.request_id] = blocks

    def warm(self, req):
        # TP 2: prefix fork with the same bare-stash shape.
        cached, n = self.prefix.match_and_fork(req.prompt_tokens,
                                               req.request_id)
        self.tables[req.request_id] = cached
        return n
