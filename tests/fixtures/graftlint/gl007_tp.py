# graftlint-fixture-path: dpu_operator_tpu/daemon/fx_gl007_tp.py
"""GL007 true positive: the pre-fix fabric dial shape — a while-True
loop that swallows a refused connect and retries with neither an
attempt bound nor a backoff sleep. A dead peer turns this into a
busy-spin for the whole deadline, and a fleet restart into a
synchronized retry storm."""
import socket


def dial_forever(addr):
    while True:
        s = socket.socket()
        try:
            s.connect(addr)
            return s
        except OSError:
            s.close()
