# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl022_nm.py
"""GL022 near-misses that must stay silent: the kv_match_prefix
unwind (except: release; raise), a finally-checkin covering every
path including break, ownership handed off to a KVLease before
anything can raise, and a handler that releases before swallowing —
the designed shed shape."""


class Plane:
    def match_with_unwind(self, tokens, owner):
        blocks, cached = self.prefix.match_and_fork(tokens, owner)
        try:
            meta = self.spec.fingerprint(tokens)
        except Exception:
            self.allocator.release(blocks, owner)
            raise
        self.allocator.release(blocks, owner)
        return meta, cached

    def finally_checkin(self, keys, owner):
        for key in keys:
            entry = self.tier.checkout(key, owner)
            if entry is None:
                break
            try:
                if not self.decode_segments(key):
                    break
            finally:
                # Covers the normal step, the raise, AND the break.
                self.tier.checkin(key, owner)
        return owner

    def handoff_before_raise(self, tokens, owner):
        blocks, cached = self.prefix.match_and_fork(tokens, owner)
        lease = KVLease(self.allocator, 0, owner, blocks,
                        tuple(tokens), cached)
        self.registry[owner] = lease
        # May raise: the blocks are leased (the lease's idempotent
        # release runs on every settle path) and the lease escaped.
        self.audit(owner)
        return lease

    def handler_releases_then_sheds(self, owner):
        blocks = self.allocator.acquire(2, owner)
        try:
            self.admit(owner)
        except Exception:
            self.allocator.release(blocks, owner)
            return []
        return self.finish(blocks, owner)
