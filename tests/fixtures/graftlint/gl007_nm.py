# graftlint-fixture-path: dpu_operator_tpu/daemon/fx_gl007_nm.py
"""GL007 near-misses that must stay silent: a retry loop with a
backoff sleep (the fixed fabric shape), an attempt-bounded for-range
retry, a handler that surfaces the failure at expiry, and a
non-network retry body."""
import socket
import time


def dial_with_backoff(addr):
    delay = 0.05
    while True:
        s = socket.socket()
        try:
            s.connect(addr)
            return s
        except OSError:
            s.close()
            time.sleep(delay)          # backoff: the fix
            delay = min(1.0, delay * 2)


def dial_bounded(addr):
    for _ in range(5):                 # attempt bound
        s = socket.socket()
        try:
            s.connect(addr)
            return s
        except OSError:
            s.close()
    raise TimeoutError(addr)


def dial_surfaces(addr, deadline):
    while True:
        s = socket.socket()
        try:
            s.connect(addr)
            return s
        except OSError:
            s.close()
            if time.monotonic() > deadline:
                raise                  # expiry is surfaced, not eaten


def recompute_forever(state):
    while True:
        try:
            state.refresh()            # no network pedigree
        except ValueError:
            continue
