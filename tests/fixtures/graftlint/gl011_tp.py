# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl011_tp.py
"""GL011 true positives: full array copies materialized inside
transport hot loops — a per-iteration tobytes() on the send path (the
shard worker's shipped shape) and an np.copy ahead of a recv decode.
Every iteration pays a payload-sized allocation+copy on the wire
path."""
import numpy as np


def reply_loop(sock, states, send_msg):
    for state in states:
        payload = state.tobytes()          # full copy per reply
        send_msg(sock, {"op": "tokens"}, payload)


def pump_chunks(sock, chunks, scratch):
    while chunks:
        arr = chunks.pop()
        staged = np.copy(arr)              # full copy per chunk
        sock.sendall(staged)
