# graftlint-fixture-path: dpu_operator_tpu/serving/kvcache/fx_gl017_nm.py
"""GL017 near-misses that must stay silent: the same attribute writes
inside the collect owner-guard region, constructor initialization,
the _reattach settled-token rebuild, plan-time writes to PLAN-owned
cursors, and locals that merely share the names."""


class SlotState:
    def __init__(self, ctx):
        # Construction is not mutation of live collect state.
        self.last_token = None
        self.confirmed = int(ctx)


class Executor:
    def __init__(self):
        self.decode_tokens = 0

    def collect(self, handle):
        raw = self._materialize(handle.raw)
        with self._slock:
            if handle.plan.gen == self._gen:
                for s, st in enumerate(self._states):
                    if st is None or st.req_id != handle.plan.owners[s]:
                        continue
                    # The owner-guard region: exactly where these
                    # writes belong.
                    st.confirmed = max(st.confirmed, int(raw[s]))
                    st.last_token = int(raw[s])
                    self.decode_tokens += 1
        return raw

    def _collect_spec(self, handle):
        with self._slock:
            for st in self._states:
                if st is not None:
                    st.last_token = 0
                    self.decode_tokens += 1

    def _reattach(self, slot, req):
        # Cursors rebuilt from SETTLED tokens — durable truth.
        st = self._states[slot]
        st.last_token = int(req.tokens[-1])
        st.confirmed = len(req.tokens)

    def _plan_step(self):
        # Plan-owned cursors: plan time is exactly where these move.
        last_token = None
        for st in self._states:
            if st is None:
                continue
            st.ctx += 1
            st.prefill_pos += 1
            st.pending_emit = True
            last_token = st.ctx  # local, not slot state
        return last_token
