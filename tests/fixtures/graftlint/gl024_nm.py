# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl024_nm.py
"""GL024 near-misses that must stay silent: the same stores made
legal by a settle/route call in the same function, self-owned state,
and non-request error stores."""


class Settler:
    def shed_oldest(self, req):
        # Routed through the choke point: fail() settles the event
        # AND fires on_request_settled (lease release included).
        req.fail("queue full")

    def reprefill_foreign(self, req):
        # kv_lease cleared AFTER the release call — the kv_attach
        # foreign-lease shape.
        req.kv_lease.release()
        req.kv_lease = None
        req.tokens.clear()

    def requeue_preempted(self, req):
        # Routing onward is the other legal move.
        self.queue.requeue(req, preempted=True)

    def rebind(self, req, lease):
        # A lease REBIND is an attach, not a drop (None stores only).
        req.kv_lease = lease
        return self.finish(req)

    def own_state(self, exc):
        # Self-owned bookkeeping: a worker ticket managing itself.
        self.error = exc
        self._done.set()

    def ticket_error(self, pending, exc):
        # Non-request receiver: worker handles stamp errors freely.
        pending.error = exc
        pending.event.set()
