# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl024_tp.py
"""GL024 true positives: drop paths that bypass the finish() settle
choke point in functions with no settle/route call. Three findings:
a hand-set done event on a shed path, an error stamped directly on a
request, and a kv_lease cleared to None with the lease object (and
its pages or tier pins) still live behind it."""


class Shedder:
    def shed_oldest(self, req):
        # TP 1: settling someone else's done event by hand — the
        # on_request_settled hook chain never runs.
        req.tokens.clear()
        req._done.set()

    def mark_failed(self, victim_req, exc):
        # TP 2: error stamped outside the choke point; the handler
        # waits forever and the lease never releases.
        victim_req.error = str(exc)

    def forget_lease(self, req):
        # TP 3: oblivion for whatever KVLease/ParkedKV rode there.
        req.kv_lease = None
        return req
