# graftlint-fixture-path: dpu_operator_tpu/serving/kvcache/fx_gl019_nm.py
"""GL019 near-misses that must stay silent: the same publishes with
the chained-hash verify present, the plain local-prefill insert
(tokens ARE the ground truth the executor just consumed), and tier
checkout/put traffic that never touches the tree."""

from .tiering import verify_block_tokens


class Restorer:
    def restore_chain(self, key, owner):
        # NM 1: the blessed path — chain recomputed before publish.
        entry = self.tier.checkout(key, owner)
        if not verify_block_tokens(entry.parent, entry.tokens, key,
                                   entry.tokens):
            self.tier.checkin(key, owner, corrupt=True)
            return None
        blk, created = self.prefix.attach_restored(
            entry.parent, entry.tokens, self._scatter(entry), owner)
        self.tier.checkin(key, owner, restored=created)
        return blk

    def accept_pull(self, meta, blocks):
        # NM 2: remote publish behind the same verify helper.
        for parent, chunk, key in self._chain(meta):
            if not verify_block_tokens(parent, chunk, key):
                raise ValueError("pull chain mismatch")
        self.prefix.insert(meta["tokens"], blocks, origin="remote")

    def publish_prefill(self, lease, full, bs):
        # NM 3: plain two-argument insert — local prefill, the tokens
        # are ground truth; no foreign bytes involved.
        self.prefix.insert(lease.prompt[:full], lease.blocks[:full // bs])

    def spill(self, parent, tokens, key, block):
        # NM 4: tier put/checkout traffic with no tree publish at all.
        self.tier.put(key, parent, tokens, self._gather(block))
