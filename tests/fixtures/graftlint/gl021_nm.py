# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl021_nm.py
"""GL021 near-misses that must stay silent: idempotent lease settle
(release is legal from every state, by design), detach made legal
again by the failure-path reattach, a tier pin checked in exactly
once per path, and a conditional shed where no single path releases
twice."""


class Plane:
    def lease_settle_is_idempotent(self, owner):
        lease = KVLease(self.allocator, 1, owner, [1], (), 0)
        try:
            self.audit(owner)
        finally:
            lease.release()
        # Legal: release/on_request_settled are idempotent settle
        # funnels — every settle path may call them again.
        lease.release()

    def detach_reattach_detach(self, owner):
        lease = KVLease(self.allocator, 1, owner, [1], (), 0)
        try:
            lease.detach()
            lease.reattach()
            # Legal: the reattach restored `attached`.
            lease.detach()
        finally:
            lease.release()

    def tier_roundtrip(self, key, owner):
        entry = self.tier.checkout(key, owner)
        if entry is None:
            return 0
        try:
            self.decode_segments(key)
        finally:
            self.tier.checkin(key, owner)
        return 1

    def conditional_shed(self, owner):
        blocks = self.allocator.acquire(4, owner)
        try:
            ok = self.admit(owner)
        except Exception:
            self.allocator.release(blocks, owner)
            raise
        if not ok:
            self.allocator.release(blocks, owner)
            return []
        return self.finish(blocks, owner)
