# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl016_tp.py
"""GL016 true positives: a KV lease detached for a hand-off with no
paired ack anywhere in the function. Two findings: a kv_detach_slot
whose result is stashed on an ad hoc dict (no handoff, no reattach —
the request is now invisible to every supervisor/settle recovery
path), and a bare lease.detach() dropped on the floor."""


class Router:
    def pull(self, slot, req):
        # TP 1: detached and stashed; nobody will ever ack this.
        detach = self.executor.kv_detach_slot(slot)
        self.parked[req.request_id] = detach

    def mark(self, req):
        # TP 2: detach with no hand-off and no failure-path reattach.
        req.kv_lease.detach()
        return req.request_id
