# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl002_nm.py
"""GL002 near-misses that must stay silent: float() over a len() call
(host int, no sync), np.asarray over a bare name (host value — the
scheduler's prompt_vec path), and a sync helper NOT reachable from the
hot roots."""
import jax
import numpy as np


class DecodeStep:
    def __call__(self, x, updates=()):
        count = float(len(updates))     # len() result: host-side
        vec = np.asarray(x, np.float32)  # bare name arg: host value
        return vec, count


def _sync_baseline(ex, state):
    # The measured sync loop — deliberately outside the hot roots.
    return np.asarray(ex.step(state))
