# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl012_tp.py
"""GL012 true positive: two thread roots write the same attribute and
one side writes BARE — the drain side mutates under the lock, the fill
side doesn't, so there is no consistent lock and both of _fill's
compound writes (a non-atomic list insert and a read-modify-write
counter bump) can interleave with _drain's locked pop."""

import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []  # plain list: no atomic pedigree
        self.total = 0
        self._stop = threading.Event()

    def start(self):
        threading.Thread(target=self._fill, daemon=True).start()
        threading.Thread(target=self._drain, daemon=True).start()

    def _fill(self):
        while not self._stop.is_set():
            self.pending.insert(0, object())  # bare mutate: fires
            self.total += 1                   # bare RMW: fires

    def _drain(self):
        while not self._stop.is_set():
            with self._lock:
                if self.pending:
                    self.pending.pop()
                    self.total -= 1
