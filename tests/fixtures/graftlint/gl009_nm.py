# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl009_nm.py
"""GL009 near-misses that must stay silent: the OOM-unwind shape
(acquire paired with a release in the same function), acquire handed
to a KVLease (the registered finalizer — release() runs on every
settle path), lease release through the settle hook, and acquire/fork
on receivers with no allocator pedigree (a lock, os.fork)."""

import os
import threading

from dpu_operator_tpu.serving.kvcache.allocator import KVCacheOOM, KVLease


class Batcher:
    def attach(self, req):
        # Registered finalizer: the blocks flow into a KVLease.
        cached, n = self.prefix.match_and_fork(req.prompt_tokens,
                                               req.request_id)
        try:
            fresh = self.allocator.acquire(4, req.request_id)
        except KVCacheOOM:
            # Error-path unwind: paired release.
            self.allocator.release(cached, req.request_id)
            raise
        req.kv_lease = KVLease(self.allocator, "ex", req.request_id,
                               cached + fresh, req.prompt_tokens, n)
        return n

    def scratch(self):
        # Acquire paired with release in the same function.
        blocks = self.allocator.acquire(1, "probe")
        try:
            return list(blocks)
        finally:
            self.allocator.release(blocks, "probe")

    def settle(self, req):
        # Settle-hook release counts: the lease's choke point.
        req.kv_lease.on_request_settled()

    def unrelated(self):
        # No allocator pedigree: a lock's acquire and a process fork.
        lock = threading.Lock()
        lock.acquire()
        try:
            pid = os.fork() if hasattr(os, "fork") else 0
        finally:
            lock.release()
        return pid
