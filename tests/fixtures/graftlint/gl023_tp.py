# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl023_tp.py
"""GL023 true positives: fault seams wired in but referenced by no
test under tests/. Three findings, one per collection form: a
faults.fire literal, a faults.wrap literal, and a fault_site=
parameter default (the sharded-executor idiom)."""
from dpu_operator_tpu import faults


def spill(buf):
    faults.fire("fxgl023.spill-seam-nobody-drives")
    return buf


def restore(thunk):
    return faults.wrap("fxgl023.restore-seam-nobody-drives", thunk)


def submit(payload, fault_site="fxgl023.submit-seam-nobody-drives"):
    faults.fire(fault_site)
    return payload
