# graftlint-fixture-path: dpu_operator_tpu/parallel/fx_gl001_tp.py
"""GL001 true positive: mask-multiply on a cotangent inside a
gradient-bearing function (the PR 2 pipeline_1f1b bug shape — the VJP
runs over zero-filled IDLE buffers, NaN * 0 poisons the accumulator)."""
import jax
import jax.numpy as jnp


def accumulate_step(params, x, gmask, grads):
    def loss(p):
        return jnp.sum(p / jnp.sum(p))  # division: NaN on zero input

    _, vjp = jax.vjp(loss, params)
    (dpl,) = vjp(jnp.float32(1.0))
    # BUG: scaling by the mask keeps NaN (NaN * 0 == NaN).
    return jax.tree.map(lambda g, d: g + d * gmask, grads, dpl)
