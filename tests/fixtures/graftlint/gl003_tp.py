# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl003_tp.py
"""GL003 true positive: the handler reads a name whose only binding is
inside its own try body (the PR 3 `_admit` NameError-masking bug — a
failure BEFORE the bind raises NameError in the handler, replacing the
real error)."""


def admit(free, queue, slots):
    for req in queue:
        try:
            i = free.pop(0)
            slots[i] = req
        except Exception:
            slots[i] = None  # NameError when pop() itself raised
            req.fail("admission failed")
