# graftlint-fixture-path: dpu_operator_tpu/serving/kvcache/fx_gl019_tp.py
"""GL019 true positives: foreign bytes published into the prefix tree
with no chained-hash re-verification anywhere in the function. Two
findings: a host-tier restore that attaches the entry straight into
the tree, and a remote pull that inserts with an origin tag on a
peer's unchecked claim."""


class Restorer:
    def restore_chain(self, key, owner):
        # TP 1: tier bytes re-enter the tree without recomputing the
        # chain — a rotted entry now serves on every prefix hit.
        entry = self.tier.checkout(key, owner)
        blk, created = self.prefix.attach_restored(
            entry.parent, entry.tokens, self._scatter(entry), owner)
        self.tier.checkin(key, owner, restored=created)
        return blk

    def accept_pull(self, meta, blocks):
        # TP 2: origin= is exactly the marker that these blocks did
        # NOT come from local prefill — publishing on the peer's
        # say-so alone mis-keys the whole chain if the peer is wrong.
        self.prefix.insert(meta["tokens"], blocks, origin="remote")
