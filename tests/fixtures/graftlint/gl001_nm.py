# graftlint-fixture-path: dpu_operator_tpu/parallel/fx_gl001_nm.py
"""GL001 near-miss: forward-only routing math scaling by a mask (the
moe.py capacity-bucketing shape). No vjp/grad flows through it at the
masked points — multiplication is the correct tool and must NOT fire."""
import jax
import jax.numpy as jnp


def route(y, row_mask, onehot):
    mask_all = jnp.tile(row_mask.astype(y.dtype), 2)
    onehot = onehot * mask_all[:, None]
    keep = jnp.cumsum(onehot, axis=0) * mask_all[:, None]
    return keep
