# graftlint-fixture-path: dpu_operator_tpu/serving/fx_gl011_nm.py
"""GL011 near-misses that must stay silent: the zero-copy send idiom
(memoryview/ascontiguousarray parts), a one-shot tobytes OUTSIDE any
loop (setup serialization), a loop that copies but never touches the
wire (scheduler bookkeeping, not a transport path), and the .copy()
METHOD (a deliberate defensive copy of a received buffer)."""
import numpy as np


def reply_loop_zero_copy(sock, states, send_msg):
    for state in states:
        part = np.ascontiguousarray(state, np.float32)  # view when
        send_msg(sock, {"op": "tokens"}, part)          # contiguous


def save_params_once(path, params):
    blob = params.tobytes()                # one-shot, not a loop
    with open(path, "wb") as f:
        f.write(blob)


def snapshot_states(states, out):
    for state in states:
        out.append(np.copy(state))         # no transport in the loop


def recv_loop_defensive_copy(sock, recv_msg, frames):
    while True:
        msg, payload = recv_msg(sock, timeout=5.0)
        if msg is None:
            return
        arr = np.frombuffer(payload, np.float32)
        frames.append(arr.copy())          # ownership, not send-path
