"""API-machinery tests: store semantics (RV/conflict/finalizers/GC/watch)
and the controller framework's reconcile loop."""

import threading
import time

import pytest

from dpu_operator_tpu.k8s import (
    AlreadyExists,
    Conflict,
    InMemoryClient,
    InMemoryCluster,
    Manager,
    NotFound,
    Reconciler,
    Request,
    Result,
    add_finalizer,
    remove_finalizer,
    set_condition,
    get_condition,
)
from dpu_operator_tpu.k8s.objects import set_owner


def mk(kind, name, namespace=None, api_version="v1", **extra):
    obj = {"apiVersion": api_version, "kind": kind, "metadata": {"name": name}}
    if namespace:
        obj["metadata"]["namespace"] = namespace
    obj.update(extra)
    return obj


@pytest.fixture
def client():
    return InMemoryClient(InMemoryCluster())


def test_create_get_conflict(client):
    obj = client.create(mk("ConfigMap", "a", "ns1", data={"k": "v"}))
    assert obj["metadata"]["uid"]
    with pytest.raises(AlreadyExists):
        client.create(mk("ConfigMap", "a", "ns1"))
    got = client.get("v1", "ConfigMap", "ns1", "a")
    got_stale = client.get("v1", "ConfigMap", "ns1", "a")
    got["data"] = {"k": "v2"}
    client.update(got)
    got_stale["data"] = {"k": "v3"}
    with pytest.raises(Conflict):
        client.update(got_stale)


def test_finalizer_blocks_deletion(client):
    obj = mk("Pod", "p", "ns1")
    add_finalizer(obj, "test/finalizer")
    client.create(obj)
    client.delete("v1", "Pod", "ns1", "p")
    cur = client.get("v1", "Pod", "ns1", "p")
    assert "deletionTimestamp" in cur["metadata"]
    remove_finalizer(cur, "test/finalizer")
    client.update(cur)
    assert client.get_or_none("v1", "Pod", "ns1", "p") is None


def test_owner_gc_cascade(client):
    owner = client.create(mk("DpuOperatorConfig", "cfg", "ns1", api_version="config.tpu.io/v1"))
    child = mk("DaemonSet", "ds", "ns1", api_version="apps/v1")
    set_owner(child, owner)
    client.create(child)
    client.delete("config.tpu.io/v1", "DpuOperatorConfig", "ns1", "cfg")
    assert client.get_or_none("apps/v1", "DaemonSet", "ns1", "ds") is None


def test_status_subresource_isolated(client):
    obj = client.create(mk("DataProcessingUnit", "d", None, api_version="config.tpu.io/v1"))
    obj["status"] = {}
    set_condition(obj, "Ready", "True", "Up", "all good")
    client.update_status(obj)
    cur = client.get("config.tpu.io/v1", "DataProcessingUnit", None, "d")
    assert get_condition(cur, "Ready")["status"] == "True"


def test_apply_create_then_merge(client):
    obj = mk("ConfigMap", "c", "ns1", data={"a": "1"})
    client.apply(obj)
    obj2 = mk("ConfigMap", "c", "ns1", data={"a": "2"})
    obj2["metadata"]["labels"] = {"x": "y"}
    client.apply(obj2)
    cur = client.get("v1", "ConfigMap", "ns1", "c")
    assert cur["data"] == {"a": "2"}
    assert cur["metadata"]["labels"] == {"x": "y"}


def test_watch_stream(client):
    client.create(mk("Node", "n0"))
    w = client.watch("v1", "Node")
    ev = w.events.get(timeout=1)
    assert ev.type == "ADDED" and ev.object["metadata"]["name"] == "n0"
    client.create(mk("Node", "n1"))
    ev = w.events.get(timeout=1)
    assert ev.type == "ADDED" and ev.object["metadata"]["name"] == "n1"
    client.delete("v1", "Node", None, "n1")
    ev = w.events.get(timeout=1)
    assert ev.type == "DELETED"


class _Recorder(Reconciler):
    def __init__(self):
        self.seen = []
        self.event = threading.Event()

    def reconcile(self, req):
        self.seen.append(req)
        self.event.set()
        return Result()


def test_controller_reconciles_on_events(client):
    mgr = Manager(client)
    rec = _Recorder()
    mgr.new_controller("test", rec).watches("v1", "ConfigMap", "ns1")
    mgr.start()
    try:
        client.create(mk("ConfigMap", "x", "ns1"))
        assert rec.event.wait(timeout=3)
        assert Request("ns1", "x") in rec.seen
    finally:
        mgr.stop()


class _FailOnce(Reconciler):
    def __init__(self):
        self.calls = 0
        self.done = threading.Event()

    def reconcile(self, req):
        self.calls += 1
        if self.calls == 1:
            raise RuntimeError("transient")
        self.done.set()
        return Result()


def test_controller_retries_with_backoff(client):
    mgr = Manager(client)
    rec = _FailOnce()
    mgr.new_controller("retry", rec).watches("v1", "Secret", "ns1")
    mgr.start()
    try:
        client.create(mk("Secret", "s", "ns1"))
        assert rec.done.wait(timeout=5)
        assert rec.calls >= 2
    finally:
        mgr.stop()
