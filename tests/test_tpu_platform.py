"""TPU platform tests: slice topology model, tpuvsp contract behavior,
and the converged-node attach path with the real bridge dataplane."""

import subprocess
import uuid

from google.protobuf import empty_pb2

from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb
from dpu_operator_tpu.parallel.topology import SliceTopology
from dpu_operator_tpu.vsp.tpu_dataplane import DebugDataplane
from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

V5E8_ENV = {
    "TPU_ACCELERATOR_TYPE": "v5litepod-8",
    "TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1",
    "TPU_WORKER_ID": "0",
}


class _Ctx:
    """Minimal grpc context stand-in for direct servicer calls."""

    def abort(self, code, details):
        raise RuntimeError(f"{code}: {details}")

    def is_active(self):
        return True


def test_topology_v5e8_grid_and_links():
    topo = SliceTopology.from_env(V5E8_ENV)
    assert topo.num_chips == 8
    assert topo.grid == (2, 4, 1)
    assert len(topo.local_chips()) == 4  # one host's chips
    # Interior chip has neighbours along both active dims.
    chip = topo.chips[0]
    neigh = topo.neighbors(chip)
    assert 2 <= len(neigh) <= 4
    assert topo.bisection_gbps() > 0


def test_topology_single_chip_fallback():
    topo = SliceTopology.from_env({})
    assert topo.num_chips >= 1
    assert topo.grid[0] >= 1


def test_tpuvsp_contract_init_devices_endpoints():
    vsp = TpuVsp(
        topology=SliceTopology.from_env(V5E8_ENV),
        dataplane=DebugDataplane(),
        opi_port=50199,
    )
    ctx = _Ctx()
    ipport = vsp.Init(
        pb.InitRequest(dpu_mode=pb.DPU_MODE_DPU, dpu_identifier="tpu-v5litepod-8-w0"),
        ctx,
    )
    assert (ipport.ip, ipport.port) == ("127.0.0.1", 50199)

    devices = vsp.GetDevices(empty_pb2.Empty(), ctx).devices
    assert len(devices) == 8
    sample = next(iter(devices.values()))
    assert sample.topology.coords
    assert sample.topology.links[0].gbps == 400
    assert sample.backing.startswith("/dev/accel")

    assert vsp.SetNumEndpoints(pb.EndpointCount(count=16), ctx).count == 16
    assert len(vsp.GetDevices(empty_pb2.Empty(), ctx).devices) == 16


def test_tpuvsp_nf_wiring_records():
    dp = DebugDataplane()
    vsp = TpuVsp(topology=SliceTopology.single_chip(), dataplane=dp)
    ctx = _Ctx()
    vsp.Init(pb.InitRequest(dpu_mode=pb.DPU_MODE_DPU, dpu_identifier="x"), ctx)
    vsp.CreateNetworkFunction(pb.NFRequest(input="aa:bb", output="cc:dd"), ctx)
    assert dp.nf_pairs == [("aa:bb", "cc:dd")]
    vsp.DeleteNetworkFunction(pb.NFRequest(input="aa:bb", output="cc:dd"), ctx)
    assert dp.nf_pairs == []


def test_converged_tpu_node_full_attach(netns, tmp_root):
    """The flagship single-node TPU-VM path: daemon-shaped converged
    manager + real tpuvsp + real linux bridge. CNI ADD plumbs a veth into
    a pod netns AND the veth host end lands on br-fabric via the local
    OPI chain."""
    import socket as pysock

    from dpu_operator_tpu.cni import CniRequest, do_cni
    from dpu_operator_tpu.daemon.converged_side import ConvergedSideManager
    from dpu_operator_tpu.daemon.plugin import GrpcPlugin
    from dpu_operator_tpu.vsp import VspServer
    from dpu_operator_tpu.vsp.tpu_dataplane import TpuFabricDataplane

    with pysock.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    bridge = "brtst" + uuid.uuid4().hex[:6]
    dp = TpuFabricDataplane(bridge=bridge)
    vsp = TpuVsp(
        topology=SliceTopology.from_env(V5E8_ENV), dataplane=dp, opi_port=port
    )
    vsp_server = VspServer(vsp, tmp_root)
    vsp_server.start()
    mgr = ConvergedSideManager(
        GrpcPlugin(tmp_root.vendor_plugin_socket()),
        "tpu-v5litepod-8-w0",
        path_manager=tmp_root,
        register_device_plugin=False,
    )
    ns = "tstconv-" + uuid.uuid4().hex[:6]
    subprocess.run(["ip", "netns", "add", ns], check=True)
    try:
        mgr.start_vsp()
        mgr.setup_devices()
        mgr.listen()
        mgr.serve()

        container_id = "conv" + uuid.uuid4().hex[:12]
        req = CniRequest(
            command="ADD", container_id=container_id, netns=ns, ifname="net1",
            config={"cniVersion": "1.0.0", "name": "default-ici-net", "type": "dpu-cni"},
        )
        result = do_cni(mgr.cni_server.socket_path, req)
        assert result["ips"]

        # Host veth end is enslaved to the fabric bridge.
        from dpu_operator_tpu.cni.dataplane.fabric import _host_ifname

        host_if = _host_ifname(container_id, "net1")
        out = subprocess.run(
            ["ip", "-d", "link", "show", "dev", host_if],
            capture_output=True, text=True, check=True,
        ).stdout
        assert bridge in out, f"{host_if} not enslaved to {bridge}: {out}"

        do_cni(mgr.cni_server.socket_path, CniRequest(
            command="DEL", container_id=container_id, netns=ns, ifname="net1",
            config=req.config,
        ))
        assert dp.ports == {}
    finally:
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)
        mgr.stop()
        vsp_server.stop()


def test_fabric_bridge_enslaves_uplink(netns):
    """DPU_FABRIC_UPLINK semantics: ensure_bridge attaches the VM's
    fabric-facing netdev to the bridge so pod traffic rides the ICI
    uplink (the role of the Marvell SDP/OVS uplink wiring)."""
    import subprocess
    import uuid

    from dpu_operator_tpu.vsp.tpu_dataplane import TpuFabricDataplane

    bridge = "brUP" + uuid.uuid4().hex[:6]
    up_a = "up" + uuid.uuid4().hex[:6]
    up_b = "ub" + uuid.uuid4().hex[:6]
    subprocess.run(
        ["ip", "link", "add", up_a, "type", "veth", "peer", "name", up_b],
        check=True,
    )
    try:
        dp = TpuFabricDataplane(bridge=bridge, uplink=up_a)
        dp.ensure_bridge()
        out = subprocess.run(
            ["ip", "-j", "link", "show", "dev", up_a],
            capture_output=True, text=True, check=True,
        ).stdout
        import json

        assert json.loads(out)[0].get("master") == bridge, "uplink not enslaved"
        # Idempotent re-run.
        dp.ensure_bridge()
    finally:
        subprocess.run(["ip", "link", "del", up_a], capture_output=True)
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)


def test_ping_not_blocked_by_slow_init():
    """Regression (graftlint GL004 triage): Init used to hold the state
    lock across bridge bring-up — which shells out to ip/nft and can
    retry for seconds on old kernels — so Ping and GetDevices queued
    behind it, heartbeats timed out, and the daemon declared a healthy
    VSP dead in the middle of its own bring-up. The request path must
    answer while bring-up is in flight (tpu_vsp's no-inline-refresh
    contract)."""
    import threading
    import time

    entered = threading.Event()
    release = threading.Event()

    class SlowBridgeDataplane(DebugDataplane):
        def ensure_bridge(self):
            entered.set()
            # Released by the test AFTER the request path answers;
            # pre-fix, Ping could not run until this returned.
            if not release.wait(8.0):
                raise RuntimeError("bring-up never released")
            return super().ensure_bridge()

    vsp = TpuVsp(
        topology=SliceTopology.single_chip(),
        dataplane=SlowBridgeDataplane(),
        opi_port=50198,
    )
    ctx = _Ctx()
    init_t = threading.Thread(
        target=vsp.Init,
        args=(pb.InitRequest(dpu_mode=pb.DPU_MODE_DPU,
                             dpu_identifier="slow"), ctx),
        daemon=True,
    )
    init_t.start()
    assert entered.wait(5.0), "Init never reached bring-up"
    try:
        t0 = time.monotonic()
        resp = vsp.Ping(pb.PingRequest(timestamp_ns=0, sender_id="hb"), ctx)
        devices = vsp.GetDevices(empty_pb2.Empty(), ctx).devices
        elapsed = time.monotonic() - t0
        assert resp.healthy
        assert len(devices) >= 1
        assert elapsed < 2.0, (
            f"request path stalled {elapsed:.1f}s behind Init bring-up")
    finally:
        release.set()
        init_t.join(10.0)
    assert not init_t.is_alive()
