"""NF chain wiring through the AUTOMATED path (VERDICT r3 Next #2).

The reference's VSPs program their match-action engines from the
CNI/NF path, not a CLI: marvell installs OVS flows in
CreateBridgePort/AddNetworkFunction (main.go:372-449, 515-588), intel
builds P4 rule sets per port/VF/NF (p4rtclient.go:612-939). These tests
pin the same property onto the TPU VSP: ports get baseline counter
rules at attach, NF wiring programs steering + CR-declared policies,
rules appear/disappear with port and NF lifecycle, and a `police:`
policy measurably caps a real traffic flow through a real (userspace)
network function."""

import json
import subprocess
import textwrap
import time
import uuid

import pytest

from dpu_operator_tpu.vsp.flow_table import FlowTable
from dpu_operator_tpu.vsp.tpu_dataplane import (
    BASELINE_PREF, NF_STEER_PREF, DebugDataplane, TpuFabricDataplane)


# -- unit tier ---------------------------------------------------------------


def test_vsp_passes_policies_to_dataplane():
    """CreateNetworkFunction carries FlowPolicy entries through the gRPC
    contract into the dataplane — the CR's policy surface reaches the
    engine without any CLI in the path."""
    from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb
    from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

    dp = DebugDataplane()
    vsp = TpuVsp(dataplane=dp)
    req = pb.NFRequest(input="02:00:00:00:00:01", output="02:00:00:00:00:02",
                       transparent=True)
    req.policies.add(pref=10, action="police:200", proto="tcp")
    vsp.CreateNetworkFunction(req, None)
    assert dp.nf_pairs == [("02:00:00:00:00:01", "02:00:00:00:00:02")]
    assert dp.nf_policies and dp.nf_policies[0]["action"] == "police:200"
    assert dp.nf_policies[0]["pref"] == 10
    assert dp.nf_transparent is True


def test_sfc_policies_render_to_pod_annotation():
    """The SFC reconciler rides policies from the CR to the NF pod as an
    annotation the DPU-side daemon reads back at CNI time."""
    from dpu_operator_tpu.daemon.sfc import (
        NF_POLICY_ANNOTATION, network_function_pod)

    policies = [{"pref": 5, "action": "police:100", "proto": "udp"}]
    pod = network_function_pod("fw", "img", {}, policies=policies,
                               transparent=True)
    spec = json.loads(pod["metadata"]["annotations"][NF_POLICY_ANNOTATION])
    assert spec == {"policies": policies, "transparent": True}
    # No chain spec -> no annotation (don't ship empty surface).
    pod = network_function_pod("fw", "img", {})
    assert NF_POLICY_ANNOTATION not in pod["metadata"]["annotations"]


def test_sfc_reconciler_converges_policy_annotation():
    from dpu_operator_tpu.api import v1
    from dpu_operator_tpu.daemon.sfc import (
        NF_POLICY_ANNOTATION, SfcNodeReconciler)
    from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster, Request
    from dpu_operator_tpu import vars as v

    client = InMemoryClient(InMemoryCluster())
    client.create({"apiVersion": "v1", "kind": "Node",
                   "metadata": {"name": "n1", "labels": {}}})
    sfc = v1.new_service_function_chain(
        "chain", network_functions=[
            {"name": "fw", "image": "img",
             "policies": [{"pref": 3, "action": "drop", "proto": "icmp"}]}])
    client.create(sfc)
    rec = SfcNodeReconciler(client, "n1")
    rec.reconcile(Request(v.NAMESPACE, "chain"))
    pod = client.get("v1", "Pod", v.NAMESPACE, "fw")
    assert json.loads(
        pod["metadata"]["annotations"][NF_POLICY_ANNOTATION]
    )["policies"][0]["action"] == "drop"
    # CR policy change converges onto the existing pod.
    sfc["spec"]["networkFunctions"][0]["policies"] = [
        {"pref": 3, "action": "police:50", "proto": "tcp"}]
    client.update(sfc)
    rec.reconcile(Request(v.NAMESPACE, "chain"))
    pod = client.get("v1", "Pod", v.NAMESPACE, "fw")
    assert json.loads(
        pod["metadata"]["annotations"][NF_POLICY_ANNOTATION]
    )["policies"][0]["action"] == "police:50"


def test_sfc_policy_validation():
    """Bad policies die at admission (`kubectl apply`), not in a daemon
    log: pref collisions with the VSP's reserved range, junk actions,
    unknown keys."""
    from dpu_operator_tpu.api import v1

    def chain(policies):
        return v1.new_service_function_chain(
            "c", network_functions=[
                {"name": "fw", "image": "img", "policies": policies}])

    v1.validate_service_function_chain_spec(
        chain([{"pref": 10, "action": "police:200", "proto": "tcp"}]))
    for bad in (
        [{"pref": 30000, "action": "drop"}],          # reserved range
        [{"pref": 0, "action": "drop"}],
        [{"pref": 1, "action": "teleport"}],
        [{"pref": 1, "action": "drop", "proto": "gre"}],
        [{"pref": 1, "action": "drop", "dstPort": 0}],
        [{"pref": 1, "action": "drop", "banana": 1}],  # unknown key
        [{"pref": 1, "action": "drop"}, {"pref": 1, "action": "accept"}],
    ):
        with pytest.raises(v1.ValidationError):
            v1.validate_service_function_chain_spec(chain(bad))


# -- root tier ---------------------------------------------------------------


def _sh(*args):
    subprocess.run(args, check=True, capture_output=True)


def _mk_pod(ns, host_if, bridge, ip, mac=None):
    _sh("ip", "netns", "add", ns)
    _sh("ip", "link", "add", host_if, "type", "veth",
        "peer", "name", "eth0", "netns", ns)
    if mac:
        _sh("ip", "-n", ns, "link", "set", "eth0", "address", mac)
    _sh("ip", "-n", ns, "link", "set", "eth0", "up")
    _sh("ip", "-n", ns, "link", "set", "lo", "up")
    if ip:
        _sh("ip", "-n", ns, "addr", "add", f"{ip}/24", "dev", "eth0")
    # The chain's NF re-injects frames from a raw socket: veth TX
    # checksum offload would hand it frames with UNFILLED L4 checksums,
    # which the far stack then rightly drops. Real NF pods face real
    # NICs (checksums complete on the wire); emulate that by completing
    # checksums at the workload edge. TSO/GSO likewise: a userspace NF
    # sees wire-sized frames, not 64 KB superframes.
    _sh("ip", "netns", "exec", ns, "ethtool", "-K", "eth0",
        "tx", "off", "tso", "off", "gso", "off", "gro", "off")


_L2_FORWARDER = textwrap.dedent("""
    import select, socket
    ETH_P_ALL = 3
    socks = []
    for dev in ("eth0", "eth1"):
        s = socket.socket(socket.AF_PACKET, socket.SOCK_RAW,
                          socket.htons(ETH_P_ALL))
        s.bind((dev, 0))
        socks.append(s)
    a, b = socks
    peer = {a.fileno(): b, b.fileno(): a}
    by_fd = {s.fileno(): s for s in socks}
    while True:
        r, _, _ = select.select(socks, [], [], 30)
        if not r:
            break
        for s in r:
            data, addr = s.recvfrom(65535)
            if addr[2] == socket.PACKET_OUTGOING:
                continue  # our own transmissions echoed back
            peer[s.fileno()].send(data)
""")


@pytest.fixture
def nf_chain_topology(netns):
    """A fabric bridge with two workload pods and a REAL network
    function: a netns with two interfaces joined by a userspace L2
    forwarder (the bump-in-the-wire every SFC assumes)."""
    tag = uuid.uuid4().hex[:5]
    bridge = "brC" + tag
    nsa, nsb, nsn = "nfa" + tag, "nfb" + tag, "nfn" + tag
    wa, wb = "wa" + tag, "wb" + tag
    nfi, nfo = "ni" + tag, "no" + tag
    mac_a, mac_b = "02:aa:00:00:00:01", "02:aa:00:00:00:02"
    mac_i, mac_o = "02:bb:00:00:00:01", "02:bb:00:00:00:02"
    fwd = None
    try:
        _sh("ip", "link", "add", bridge, "type", "bridge")
        _sh("ip", "link", "set", bridge, "up")
        _mk_pod(nsa, wa, bridge, "10.95.0.1", mac_a)
        _mk_pod(nsb, wb, bridge, "10.95.0.2", mac_b)
        # NF pod: two interfaces, no IPs, forwarder between them.
        _sh("ip", "netns", "add", nsn)
        _sh("ip", "link", "add", nfi, "type", "veth",
            "peer", "name", "eth0", "netns", nsn)
        _sh("ip", "link", "add", nfo, "type", "veth",
            "peer", "name", "eth1", "netns", nsn)
        _sh("ip", "-n", nsn, "link", "set", "eth0", "address", mac_i)
        _sh("ip", "-n", nsn, "link", "set", "eth1", "address", mac_o)
        for dev in ("eth0", "eth1"):
            _sh("ip", "-n", nsn, "link", "set", dev, "up")
            _sh("ip", "-n", nsn, "link", "set", dev, "promisc", "on")
        fwd = subprocess.Popen(
            ["ip", "netns", "exec", nsn, "python", "-c", _L2_FORWARDER])

        dp = TpuFabricDataplane(bridge=bridge)
        dp.ensure_bridge()
        for port, mac in ((wa, mac_a), (wb, mac_b),
                          (nfi, mac_i), (nfo, mac_o)):
            dp.attach_port(port, mac)
        yield {"dp": dp, "bridge": bridge, "nsa": nsa, "nsb": nsb,
               "wa": wa, "wb": wb, "nfi": nfi, "nfo": nfo,
               "mac_i": mac_i, "mac_o": mac_o}
    finally:
        if fwd is not None:
            fwd.kill()
        for dev in (nfi, nfo, bridge):
            subprocess.run(["ip", "link", "del", dev], capture_output=True)
        for ns in (nsa, nsb, nsn):
            subprocess.run(["ip", "netns", "del", ns], capture_output=True)


def test_attach_programs_baseline_counter(netns):
    """Port attach installs the per-port baseline counter rule; traffic
    moves its counters; detach flushes the chain (rule lifecycle ==
    port lifecycle, the reference's per-port rule set shape)."""
    tag = uuid.uuid4().hex[:5]
    bridge, ns, host_if = "brB" + tag, "nsB" + tag, "pb" + tag
    try:
        _sh("ip", "link", "add", bridge, "type", "bridge")
        _sh("ip", "link", "set", bridge, "up")
        _sh("ip", "addr", "add", "10.95.1.1/24", "dev", bridge)
        _mk_pod(ns, host_if, bridge, "10.95.1.2")
        dp = TpuFabricDataplane(bridge=bridge)
        dp.ensure_bridge()
        dp.attach_port(host_if, "02:cc:00:00:00:01")
        assert dp.flow_state == "ok", dp.flow_state

        rules = FlowTable(host_if).list(stats=True)
        assert [r["pref"] for r in rules] == [BASELINE_PREF]
        before = rules[0]["packets"]
        # Idempotent re-attach: no duplicate baseline, still ok.
        dp.attach_port(host_if, "02:cc:00:00:00:01")
        assert dp.flow_state == "ok"
        assert len(FlowTable(host_if).list()) == 1

        # Traffic from the pod moves the counter.
        subprocess.run(
            ["ip", "netns", "exec", ns, "python", "-c",
             "import socket; s=socket.socket(socket.AF_INET,"
             "socket.SOCK_DGRAM); [s.sendto(b'x'*512, ('10.95.1.1', 9)) "
             "for _ in range(50)]"], check=True, capture_output=True)
        time.sleep(0.2)
        after = FlowTable(host_if).list(stats=True)[0]["packets"]
        assert after >= before + 50

        dp.detach_port(host_if)
        assert FlowTable(host_if).list() == []
    finally:
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)


def test_missing_tc_degrades_shaping_state_not_attach(netns, tmp_path,
                                                      monkeypatch):
    """Yank tc from PATH (the minimal-node-image scenario the repo's own
    nftnl design argument invokes): the pod attach must still succeed,
    the flow table (pure netlink) must still program, and the failure
    must be RECORDED in shaping_state — the string the VSP heartbeats to
    the daemon for the FabricShaping CR condition — not just logged."""
    import shutil

    bindir = tmp_path / "bin"
    bindir.mkdir()
    # ethtool is only for the test's own pod helper, not the dataplane.
    for tool in ("ip", "bridge", "ethtool"):
        (bindir / tool).symlink_to(shutil.which(tool))
    monkeypatch.setenv("PATH", str(bindir))
    assert shutil.which("tc") is None

    from dpu_operator_tpu.tft import ConnectionSpec
    from dpu_operator_tpu.tft.tft import run_connection
    from dpu_operator_tpu.vsp.tpu_dataplane import SHARE_POLICE_PREF

    tag = uuid.uuid4().hex[:5]
    bridge, ns, host_if = "brT" + tag, "nsT" + tag, "pt" + tag
    try:
        _sh("ip", "link", "add", bridge, "type", "bridge")
        _sh("ip", "link", "set", bridge, "up")
        _sh("ip", "addr", "add", "10.95.2.1/24", "dev", bridge)
        _mk_pod(ns, host_if, bridge, "10.95.2.2")
        dp = TpuFabricDataplane(bridge=bridge, fabric_gbps=2.0)
        dp.ensure_bridge()
        dp.partition_endpoints(4)
        dp.attach_port(host_if, "02:dd:00:00:00:01")
        # The attach itself landed...
        assert host_if in dp.ports
        # ...the netlink-only flow table programmed the baseline AND the
        # nft police fallback for the 2.0/4 = 0.5 Gb/s share...
        prefs = {r["pref"] for r in FlowTable(host_if).list()}
        assert prefs == {SHARE_POLICE_PREF, BASELINE_PREF}
        # ...the degradation is state (heartbeated to the CR condition),
        # naming the active fallback...
        assert "nft ingress police fallback" in dp.shaping_state
        # ...and the fallback has a MEASURED dataplane effect: pod→host
        # throughput capped at ~the endpoint share, not line rate.
        r = run_connection(ConnectionSpec(name="cap", type="iperf-tcp"),
                           None, ns, "10.95.2.1", duration=1.2, port=15311)
        assert float(r["gbps"]) < 1.0, (
            f"nft police share let {r['gbps']} Gb/s through a 0.5 share")
    finally:
        subprocess.run(["ip", "link", "del", bridge], capture_output=True)
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)


def test_nf_wiring_programs_and_removes_rules(nf_chain_topology):
    """NF lifecycle == rule lifecycle (transparent mode): wiring
    installs workload steering + policies; unwiring removes them and
    leaves the baselines."""
    t = nf_chain_topology
    dp = t["dp"]
    dp.wire_network_function(
        t["mac_i"], t["mac_o"], transparent=True,
        policies=[{"pref": 10, "action": "police:100", "proto": "tcp"}])
    assert dp.flow_state == "ok", dp.flow_state

    # Workload ports: baseline + steer into the NF input.
    for port in (t["wa"], t["wb"]):
        prefs = {r["pref"]: r for r in FlowTable(port).list()}
        assert set(prefs) == {NF_STEER_PREF, BASELINE_PREF}
        assert prefs[NF_STEER_PREF]["action"] == f"redirect:{t['nfi']}"
    # NF ports: baseline + the CR policy.
    for port in (t["nfi"], t["nfo"]):
        prefs = {r["pref"] for r in FlowTable(port).list()}
        assert prefs == {10, BASELINE_PREF}

    dp.unwire_network_function(t["mac_i"], t["mac_o"])
    for port in (t["wa"], t["wb"], t["nfi"], t["nfo"]):
        assert [r["pref"] for r in FlowTable(port).list()] == [BASELINE_PREF]


def test_endpoint_nf_wiring_uses_dst_mac_fwd_rules(nf_chain_topology):
    """Endpoint mode (the default, matching the reference e2e pod↔NF
    shape): chaining rides dst-MAC fwd rules on the workload ports —
    NF-bound traffic is flow-steered and counted, everything else is
    untouched, and no bridge-port isolation happens (an endpoint NF
    must stay reachable by ARP from unmanaged ports)."""
    t = nf_chain_topology
    dp = t["dp"]
    dp.wire_network_function(t["mac_i"], t["mac_o"])
    assert dp.flow_state == "ok", dp.flow_state
    for port in (t["wa"], t["wb"]):
        rules = {r["pref"]: r for r in FlowTable(port).list()}
        assert set(rules) == {NF_STEER_PREF, NF_STEER_PREF + 1,
                              BASELINE_PREF}
        assert rules[NF_STEER_PREF]["dst_mac"] == t["mac_i"]
        assert rules[NF_STEER_PREF + 1]["dst_mac"] == t["mac_o"]
    # NF ports keep flooding enabled in endpoint mode.
    out = subprocess.run(["bridge", "-d", "link", "show", "dev", t["nfi"]],
                         capture_output=True, text=True).stdout
    assert "flood on" in out, out
    dp.unwire_network_function(t["mac_i"], t["mac_o"])
    for port in (t["wa"], t["wb"]):
        assert [r["pref"] for r in FlowTable(port).list()] == [BASELINE_PREF]


def test_transparent_chain_with_uplink_keeps_eastwest(nf_chain_topology):
    """With an uplink configured, the transparent chain's catch-all
    redirect toward the fabric must NOT swallow east-west traffic:
    frames for local workload MACs (and the ARP broadcast) accept into
    normal delivery before the uplink redirect — pod→pod through the
    chain still works, and the rule order proves why."""
    from dpu_operator_tpu.tft import ConnectionSpec
    from dpu_operator_tpu.tft.tft import run_connection
    from dpu_operator_tpu.vsp.tpu_dataplane import NF_UPLINK_PREF

    t = nf_chain_topology
    dp = t["dp"]
    tag = t["bridge"][3:]
    up, upp = "ul" + tag, "up" + tag
    try:
        _sh("ip", "link", "add", up, "type", "veth", "peer", "name", upp)
        _sh("ip", "link", "set", up, "master", t["bridge"])
        _sh("ip", "link", "set", up, "up")
        _sh("ip", "link", "set", upp, "up")
        dp.uplink = up
        dp.wire_network_function(t["mac_i"], t["mac_o"], transparent=True)
        assert dp.flow_state == "ok", dp.flow_state

        # Rule order on the NF output: east-west accepts (broadcast +
        # both workload MACs) strictly before the uplink catch-all.
        rules = FlowTable(t["nfo"]).list()
        prefs = [r["pref"] for r in rules]
        accepts = [r for r in rules if r["action"] == "accept"
                   and r["pref"] < NF_UPLINK_PREF and "dst_mac" in r]
        assert {r["dst_mac"] for r in accepts} >= {
            "ff:ff:ff:ff:ff:ff", "02:aa:00:00:00:01", "02:aa:00:00:00:02"}
        assert NF_UPLINK_PREF in prefs
        assert prefs.index(NF_UPLINK_PREF) > max(
            prefs.index(r["pref"]) for r in accepts)

        # And the traffic proof: pod→pod through the chain still flows.
        r = run_connection(ConnectionSpec(name="ew", type="iperf-tcp"),
                           t["nsb"], t["nsa"], "10.95.0.2",
                           duration=1.0, port=15321)
        assert float(r["gbps"]) > 0.05, r
        dp.unwire_network_function(t["mac_i"], t["mac_o"])
        assert [x["pref"] for x in FlowTable(t["nfo"]).list()] == \
            [BASELINE_PREF]
    finally:
        dp.uplink = None
        subprocess.run(["ip", "link", "del", up], capture_output=True)


@pytest.mark.slow
def test_cr_police_policy_caps_chain_traffic(nf_chain_topology):
    """The VERDICT's done-criterion: a CR-declared police: policy
    measurably caps a traffic flow riding the chain — and the steering
    rule counters prove the bytes really crossed the NF."""
    from dpu_operator_tpu.tft import ConnectionSpec
    from dpu_operator_tpu.tft.tft import run_connection

    t = nf_chain_topology
    dp = t["dp"]
    conn = ConnectionSpec(name="cap", type="iperf-tcp")

    def measure(port):
        r = run_connection(conn, t["nsb"], t["nsa"], "10.95.0.2",
                           duration=1.2, port=port)
        return float(r["gbps"])

    # Uncapped through the NF first: proves the userspace forwarder
    # carries real traffic before we attribute the cap to the policy.
    dp.wire_network_function(t["mac_i"], t["mac_o"], transparent=True)
    assert dp.flow_state == "ok", dp.flow_state
    uncapped = measure(15301)
    assert uncapped > 0.1, f"chain carries no traffic ({uncapped} Gb/s)"
    steer = {r["pref"]: r for r in
             FlowTable(t["wa"]).list(stats=True)}[NF_STEER_PREF]
    assert steer["packets"] > 0, "traffic did not ride the steering rule"
    dp.unwire_network_function(t["mac_i"], t["mac_o"])

    # Same chain, now with a 100 Mbit police policy from the CR surface.
    dp.wire_network_function(
        t["mac_i"], t["mac_o"], transparent=True,
        policies=[{"pref": 10, "action": "police:100", "proto": "tcp"}])
    assert dp.flow_state == "ok", dp.flow_state
    capped = measure(15302)
    # Generous windows (TCP vs policer is bursty) that still cleanly
    # separate: 100 Mbit cap on a >100 Mbit/s chain.
    assert capped < 0.6 * uncapped, (uncapped, capped)
    assert capped < 0.35, f"police:100 let {capped} Gb/s through"
