"""Ring attention (parallel/ring_attention.py) — sequence-parallel exact
attention streaming K/V around the ring. Proof standard matches the ring
family: the XLA path against a dense full-attention reference on the
virtual mesh (causal and unmasked, bf16 and f32), the pallas kernel
EXECUTED under TPU interpret mode against the XLA path, and AOT Mosaic
lowering."""

import numpy as np
import pytest

from virtual_mesh import REPO, run_virtual as _run_virtual


def _reference(q, k, v, causal):
    s = (q.astype(np.float32) @ k.astype(np.float32).T) / np.sqrt(q.shape[1])
    if causal:
        sq, sk = s.shape
        mask = np.arange(sk)[None, :] <= np.arange(sq)[:, None]
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(axis=1, keepdims=True))
    return (p / p.sum(axis=1, keepdims=True)) @ v.astype(np.float32)


def test_xla_ring_attention_matches_dense():
    """The decomposed ppermute recurrence computes EXACT attention over
    the full sequence — the online-softmax fold and the cross-shard
    causal mask (global positions) are the parts worth distrusting."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dpu_operator_tpu.parallel.ring_attention import make_ring_attention

    for shape, n in (((1, 8, 1), 8), ((2, 4, 1), 4), ((1, 2, 4), 2)):
        mesh = Mesh(np.array(jax.devices()).reshape(shape),
                    axis_names=("dp", "sp", "tp"))
        S, dk, dv = 4 * n, 16, 8
        q = np.asarray(jax.random.normal(jax.random.PRNGKey(0), (S, dk)))
        k = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (S, dk)))
        v = np.asarray(jax.random.normal(jax.random.PRNGKey(2), (S, dv)))
        sh = NamedSharding(mesh, P("sp", None))
        args = [jax.device_put(jnp.asarray(a), sh) for a in (q, k, v)]
        for causal in (False, True):
            fn = make_ring_attention(mesh, "sp", causal=causal)
            out = np.asarray(fn(*args))
            np.testing.assert_allclose(
                out, _reference(q, k, v, causal), rtol=2e-5, atol=2e-5)


def test_xla_ring_attention_bf16_stable():
    """bf16 inputs keep an f32 softmax: the output must track the f32
    reference to bf16 resolution even at 8 ring steps."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dpu_operator_tpu.parallel.ring_attention import make_ring_attention

    mesh = Mesh(np.array(jax.devices()).reshape(1, 8, 1),
                axis_names=("dp", "sp", "tp"))
    S, dk, dv = 32, 16, 8
    q = np.asarray(jax.random.normal(jax.random.PRNGKey(3), (S, dk)))
    k = np.asarray(jax.random.normal(jax.random.PRNGKey(4), (S, dk)))
    v = np.asarray(jax.random.normal(jax.random.PRNGKey(5), (S, dv)))
    sh = NamedSharding(mesh, P("sp", None))
    args = [
        jax.device_put(jnp.asarray(a).astype(jnp.bfloat16), sh)
        for a in (q, k, v)
    ]
    out = np.asarray(
        make_ring_attention(mesh, "sp", causal=True)(*args)
    ).astype(np.float32)
    # bf16 q/k quantization moves scores before the softmax; compare at
    # bf16-appropriate tolerance.
    np.testing.assert_allclose(
        out, _reference(q, k, v, True), rtol=0.1, atol=0.06)


def test_pallas_ring_attention_interpret_mode():
    """The pallas kernel EXECUTES under TPU interpret mode on the
    virtual mesh and matches the XLA path — the online-softmax scratch
    protocol on top of the shared ring stream, causal and unmasked,
    including the 8-wide max-skew ring."""
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "from dpu_operator_tpu.parallel.ring_attention import make_ring_attention\n"
        "with pltpu.force_tpu_interpret_mode():\n"
        "    for shape, n in (((1, 8, 1), 8), ((2, 4, 1), 4), ((1, 2, 4), 2)):\n"
        "        mesh = Mesh(np.array(jax.devices()).reshape(shape),\n"
        "                    axis_names=('dp', 'sp', 'tp'))\n"
        "        S, dk, dv = 4 * n, 16, 8\n"
        "        sh = NamedSharding(mesh, P('sp', None))\n"
        "        q = jax.device_put(jax.random.normal(jax.random.PRNGKey(0),\n"
        "            (S, dk)), sh)\n"
        "        k = jax.device_put(jax.random.normal(jax.random.PRNGKey(1),\n"
        "            (S, dk)), sh)\n"
        "        v = jax.device_put(jax.random.normal(jax.random.PRNGKey(2),\n"
        "            (S, dv)), sh)\n"
        "        for causal in (False, True):\n"
        "            ref = np.asarray(make_ring_attention(mesh, 'sp',\n"
        "                  causal=causal, use_pallas=False)(q, k, v))\n"
        "            out = np.asarray(make_ring_attention(mesh, 'sp',\n"
        "                  causal=causal, use_pallas=True)(q, k, v))\n"
        "            np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout


@pytest.mark.slow
def test_pallas_ring_attention_aot_lowers_for_tpu():
    """Mosaic compilation proof for the ring-attention kernel on an
    8-device TPU topology."""
    r = _run_virtual(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "from dpu_operator_tpu.parallel.ring_attention import make_ring_attention\n"
        "mesh = Mesh(np.array(jax.devices()).reshape(1, 8, 1),\n"
        "            axis_names=('dp', 'sp', 'tp'))\n"
        "sh = NamedSharding(mesh, P('sp', None))\n"
        "S, dk, dv = 1024, 128, 128\n"
        "qa = jax.ShapeDtypeStruct((S, dk), jnp.bfloat16, sharding=sh)\n"
        "ka = jax.ShapeDtypeStruct((S, dk), jnp.bfloat16, sharding=sh)\n"
        "va = jax.ShapeDtypeStruct((S, dv), jnp.bfloat16, sharding=sh)\n"
        "for causal in (False, True):\n"
        "    fn = make_ring_attention(mesh, 'sp', causal=causal,\n"
        "                             use_pallas=True)\n"
        "    exp = jax.export.export(fn, platforms=['tpu'])(qa, ka, va)\n"
        "    assert 'tpu_custom_call' in exp.mlir_module()\n"
        "print('ok')\n" % REPO
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout
