"""parallel package — mesh factoring, topology, and the driver contracts.

The jax-running checks go through a subprocess with PYTHONPATH cleared:
this environment pre-imports jax against the live TPU tunnel via a
sitecustomize hook, so an in-process backend switch to the virtual
8-device CPU platform is impossible (same reason the driver runs
dryrun_multichip in its own process)."""

import json
import os
import subprocess
import sys

import pytest

from dpu_operator_tpu.parallel.mesh import axis_sizes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_axis_sizes_factorings():
    assert axis_sizes(1) == (1, 1, 1)
    assert axis_sizes(2) == (1, 1, 2)
    assert axis_sizes(4) == (1, 2, 2)
    assert axis_sizes(8) == (2, 2, 2)
    assert axis_sizes(3) == (3, 1, 1)
    for n in (1, 2, 3, 4, 6, 8, 16):
        dp, sp, tp = axis_sizes(n)
        assert dp * sp * tp == n


def _run_graft(n: int) -> str:
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py"), str(n)],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


@pytest.mark.slow
def test_graft_entry_multichip_8():
    out = _run_graft(8)
    assert "'dp': 2, 'sp': 2, 'tp': 2" in out
    assert "probe loss" in out


def test_bench_json_contract():
    """bench.py's one-line stdout contract: metric/value/unit/vs_baseline
    (driver parses this into BENCH_r{N}.json)."""
    env = dict(os.environ)
    # The on-chip section legitimately takes many minutes through the
    # tunnel; the contract under test is the JSON shape, not chip perf.
    env["DPU_BENCH_SKIP_TPU"] = "1"
    # Gate verdicts are advisory here: this bench run shares the machine
    # with the rest of the suite, so a throughput dip measures the
    # neighbors. The trip-on-regression behavior is unit-tested in
    # test_bench_operator_gates_trip_on_regression; the driver's
    # standalone run keeps gates fatal.
    env["DPU_BENCH_ADVISORY_GATES"] = "1"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        timeout=480,  # jax-over-fabric adds two worker startups (~50 s)
        cwd=REPO,
        env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    line = r.stdout.strip().splitlines()[-1]
    data = json.loads(line)
    assert {"metric", "value", "unit", "vs_baseline"} <= set(data)
    assert data["metric"] == "pod_attach_p50"
    assert data["value"] > 0
    # Multi-metric payload rides along under "extra" (VERDICT r1 #1).
    assert data["extra"]["pod_attach_p50_ms"] == data["value"]


def test_pallas_kblocked_matmul_matches_xla_in_interpret_mode():
    """The K-blocked benchmark matmul (mxu_bench.pallas_matmul) agrees
    with XLA's f32-accumulated matmul across an uneven M/N/K split."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c", (
            "import sys; sys.path.insert(0, %r)\n"
            "import jax, jax.numpy as jnp\n"
            "from dpu_operator_tpu.parallel.mxu_bench import pallas_matmul\n"
            "kx, kw = jax.random.split(jax.random.PRNGKey(0))\n"
            "x = jax.random.normal(kx, (256, 512)).astype(jnp.bfloat16)\n"
            "w = jax.random.normal(kw, (512, 384)).astype(jnp.bfloat16)\n"
            "got = pallas_matmul(x, w, bm=128, bn=128, bk=128, interpret=True)\n"
            "want = (x.astype(jnp.float32) @ w.astype(jnp.float32)).astype(jnp.bfloat16)\n"
            "err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))\n"
            "assert err < 0.5, err\n"
            "print('ok', err)\n"
        ) % REPO],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout


def test_pallas_burn_matches_jnp_in_interpret_mode():
    """The pallas MXU burn kernel agrees with the XLA-scheduled version
    (run via the interpreter on CPU, pallas_guide.md interpret mode)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c", (
            "import sys; sys.path.insert(0, %r)\n"
            "import jax.numpy as jnp, jax\n"
            "from dpu_operator_tpu.parallel.pallas_burn import burn_step_pallas\n"
            "from dpu_operator_tpu.parallel.fabric_probe import burn_step\n"
            "k1, k2 = jax.random.split(jax.random.PRNGKey(3))\n"
            "x = jax.random.normal(k1, (256, 256), dtype=jnp.bfloat16)\n"
            "w = jax.random.normal(k2, (256, 256), dtype=jnp.bfloat16) * 0.05\n"
            "a = float(burn_step_pallas(x, w, interpret=True))\n"
            "b = float(burn_step(x, w))\n"
            "assert abs(a - b) / max(abs(b), 1e-6) < 0.05, (a, b)\n"
            "print('ok', a, b)\n"
        ) % REPO],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ok" in r.stdout


def test_hybrid_mesh_dcn_outermost():
    """build_hybrid_mesh groups devices by slice and puts the DCN axis
    outermost; a gradient-sync collective over ("dcn", "dp") crosses
    slices and averages everything."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dpu_operator_tpu.parallel.mesh import build_hybrid_mesh

    devices = jax.devices()
    assert len(devices) == 8
    # Virtual CPU devices carry no slice_index; fabricate 2 slices of 4.
    mesh = build_hybrid_mesh(devices, slice_index_of=lambda d: d.id // 4)
    assert mesh.axis_names == ("dcn", "dp", "sp", "tp")
    assert mesh.devices.shape == (2, 1, 2, 2)
    # Slice grouping: every device in dcn row i belongs to slice i.
    for i in range(2):
        assert {d.id // 4 for d in mesh.devices[i].flat} == {i}

    # Cross-slice gradient sync: mean over dcn+dp of per-device values.
    from dpu_operator_tpu.parallel._compat import shard_map

    x = jnp.arange(8.0).reshape(8, 1)
    xs = jax.device_put(
        x, NamedSharding(mesh, P(("dcn", "dp"), None)))

    def sync(v):
        return jax.lax.pmean(v, ("dcn", "dp"))

    out = jax.jit(shard_map(
        sync, mesh=mesh, in_specs=P(("dcn", "dp"), None),
        out_specs=P(("dcn", "dp"), None), check_vma=False,
    ))(xs)
    # 8 rows sharded over ("dcn","dp") = 2 shards of 4 rows; pmean
    # averages the two shards elementwise and every shard gets the mean.
    expected = np.tile((x[:4] + x[4:]).reshape(4, 1) / 2, (2, 1))
    np.testing.assert_allclose(np.asarray(out), expected)

    # Ragged slices must error loudly, not build a lying mesh.
    with pytest.raises(ValueError, match="ragged"):
        build_hybrid_mesh(devices, slice_index_of=lambda d: 0 if d.id < 3 else 1)


def test_hybrid_inner_shape_grid_aligned():
    """The hybrid mesh's per-slice factoring follows the physical grid
    when topology + coords are available (every inner-axis step one ICI
    hop on a 4x4 slice), and only falls back to the generic factoring
    when it can't know better."""
    from dpu_operator_tpu.parallel.mesh import axis_sizes, hybrid_inner_shape
    from dpu_operator_tpu.parallel.topology import SliceTopology

    v5e16 = SliceTopology.from_env({
        "TPU_ACCELERATOR_TYPE": "v5litepod-16",
        "TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1",
        "TPU_HOST_BOUNDS": "2,2,1",
    })
    assert v5e16.grid == (4, 4, 1)
    # Grid-aligned: (dp, sp, tp) = (z, y, x) = (1, 4, 4) — NOT the
    # generic axis_sizes(16) = (4, 2, 2), which strides sp across
    # non-adjacent chips on a 4x4 grid.
    assert hybrid_inner_shape(16, v5e16, True) == (1, 4, 4)
    assert hybrid_inner_shape(16, v5e16, False) == axis_sizes(16)
    assert hybrid_inner_shape(8, v5e16, True) == axis_sizes(8)  # mismatch
    assert hybrid_inner_shape(16, None, True) == axis_sizes(16)


def test_bench_operator_gates_trip_on_regression():
    """VERDICT r4 Next #2's 'done' condition: a genuine operator-path
    regression makes the bench fail (rc=1 comes from evaluate_gates
    returning a False gate). Healthy sessions inside the measured noise
    band pass; metrics with no artifact history get no gate at all."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "benchmod", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    history = {"fabric_tcp_gbps": [18.9, 20.9],
               "fabric_tcp_rr_tps": [139053.0, 152447.0],
               "pod_attach_p50_ms": [3.758, 3.567, 4.594],
               "fabric_jax_allreduce_gbps": [3.017, 6.1],
               "fabric_udp_gbps": [12.9, 12.202, 10.964],
               "fabric_clusterip_tcp_gbps": [18.5, 20.006],
               "pod_attach_concurrent_per_s": [142.2, 131.0, 103.3, 107.2]}
    # Healthy session (r4/r5's own numbers): all gates true.
    healthy = {"fabric_tcp_gbps": 18.9, "fabric_tcp_rr_tps": 152447.6,
               "pod_attach_p50_ms": 4.594,
               "fabric_jax_allreduce_gbps": 6.0,
               "fabric_udp_gbps": 10.964,
               "fabric_clusterip_tcp_gbps": 20.006,
               "pod_attach_concurrent_per_s": 107.2}
    gates = bench.evaluate_gates(dict(healthy), history)
    assert gates and all(gates.values()), gates
    # The previously-ungated metrics (ISSUE 1) each carry a gate now.
    for label in ("allreduce_ge_085_median", "fabric_udp_ge_085_median",
                  "clusterip_ge_085_median",
                  "concurrent_attach_ge_085_median"):
        assert label in gates, gates
    # Regressions: each metric tripping alone.
    for key, bad in (("fabric_tcp_gbps", 10.0),
                     ("fabric_tcp_rr_tps", 90000.0),
                     ("pod_attach_p50_ms", 9.0),
                     ("fabric_jax_allreduce_gbps", 2.0),
                     ("fabric_udp_gbps", 6.0),
                     ("fabric_clusterip_tcp_gbps", 11.0),
                     ("pod_attach_concurrent_per_s", 60.0)):
        m = dict(healthy)
        m[key] = bad
        gates = bench.evaluate_gates(m, history)
        assert not all(gates.values()), (key, gates)
    # The ISSUE 13 residency gates are ABSOLUTE (no history needed):
    # the >= 3.5x bytes/slot floor and the CPU interpret-equivalence.
    m = dict(healthy)
    m.update(serving_kv_bytes_reduction=3.99,
             serving_paged_attn_equiv_ok=True)
    gates = bench.evaluate_gates(m, history)
    assert gates["serving_kv_bytes_reduction_ge_35"] is True
    assert gates["serving_paged_attn_equiv_ok"] is True
    m.update(serving_kv_bytes_reduction=2.0,
             serving_paged_attn_equiv_ok=False)
    gates = bench.evaluate_gates(m, history)
    assert gates["serving_kv_bytes_reduction_ge_35"] is False
    assert gates["serving_paged_attn_equiv_ok"] is False
    # TPU rounds: the pallas-beats-xla acceptance comparison is its
    # own absolute gate — a Pallas-only regression cannot hide behind
    # the deploy headline's rolling median.
    m = dict(healthy)
    m.update(serving_paged_attn_pallas_ms=2.0,
             serving_paged_attn_xla_ms=1.0)
    assert bench.evaluate_gates(m, history)[
        "serving_paged_attn_pallas_le_xla"] is False
    m.update(serving_paged_attn_pallas_ms=0.8)
    assert bench.evaluate_gates(m, history)[
        "serving_paged_attn_pallas_le_xla"] is True
    # No history → no operator gates.
    assert bench.evaluate_gates(dict(healthy), {}) == {}
    # The real artifact files parse into usable history.
    real = bench._artifact_history()
    assert real.get("fabric_tcp_gbps") and real.get("pod_attach_p50_ms")
    # Every newly gated metric has real artifact history to gate against.
    for key in ("fabric_udp_gbps", "fabric_clusterip_tcp_gbps",
                "pod_attach_concurrent_per_s", "fabric_jax_allreduce_gbps"):
        assert real.get(key), key
