"""Regression tests for the two lock-discipline bugs GL012 found
(ISSUE 10 triage) — event-sequenced interleavings in the PR 5
settle-race style: every ordering below is forced by events, not
sleeps, so the pre-fix failure reproduced on every run.

1. ContinuousBatcher._fail_occupants settled occupants OUTSIDE the
   settle lock. A standalone (crash_only=False) batcher failing a step
   while stop() runs could settle the same request TWICE: the stop
   path failed it "server stopped" between _fail_occupants' fail and
   its slot clear, then the batcher's own fail overwrote the error
   AFTER the handler thread had already been woken — the exact
   no-double-settle contract the settle lock exists for.

2. Daemon.stop() raced an in-flight tick. stop() tore down and cleared
   _managed while the serve thread was mid-tick; a detection completing
   after the teardown started its side manager into a dict nobody
   would ever stop again — an orphan manager thread plus a re-created
   CR. stop() now joins the tick thread before tearing down, and
   _managed mutations share _mlock.
"""

import threading
import time

import numpy as np
import pytest

from dpu_operator_tpu.obs import trace as obs_trace
from dpu_operator_tpu.platform.detector import DetectedDpu
from dpu_operator_tpu.serving.api import GenerateRequest
from dpu_operator_tpu.serving.queue import AdmissionQueue
from dpu_operator_tpu.serving.scheduler import ContinuousBatcher


# -- 1. batcher: _fail_occupants vs stop() ------------------------------------


class _BoomExecutor:
    """step() fails immediately — the batcher admits, then lands in its
    failure path on the first decode step."""

    slots = 1
    d = 4
    pipelined = False
    kv = False

    def step(self, x):
        raise RuntimeError("boom")

    def reset(self):
        pass

    def close(self):
        pass


class _SequencedFail:
    """Wraps req.fail: the FIRST call (the batcher's _fail_occupants)
    parks on an event so the test can interleave stop() at the exact
    point the race lived; later calls pass straight through."""

    def __init__(self, req):
        self.calls = 0
        self.in_fail = threading.Event()
        self.release = threading.Event()
        self._orig = req.fail

    def __call__(self, error):
        self.calls += 1
        if self.calls == 1:
            self.in_fail.set()
            assert self.release.wait(10), "test sequencing wedged"
        self._orig(error)


def test_fail_occupants_settles_exactly_once_against_stop():
    """Pre-fix: stop() found the request still in its slot while the
    batcher was mid-_fail_occupants (no lock held) and settled it a
    second time (fail called twice, error overwritten after the
    handler woke). Post-fix _fail_occupants runs under the settle lock
    with an _abandoned re-check: exactly one settle, whoever wins."""
    tracer = obs_trace.Tracer()
    tracer.enabled = False
    queue = AdmissionQueue(max_depth=4, tracer=tracer)
    batcher = ContinuousBatcher(
        _BoomExecutor(), queue, replica="r0", idle_wait_s=0.01,
        crash_only=False, tracer=tracer)
    req = GenerateRequest(
        prompt_vec=np.zeros(4, np.float32), max_tokens=4,
        deadline=time.monotonic() + 30.0)
    box = _SequencedFail(req)
    req.fail = box
    queue.submit(req)
    batcher.start()
    assert box.in_fail.wait(10), "batcher never reached its fail path"

    # stop() with a tiny join budget: the batcher thread is parked
    # inside the fail wrapper, so the join always times out and stop
    # proceeds to its settle section while the failure path is still
    # in flight — the pre-fix double-settle window.
    stopper = threading.Thread(target=lambda: batcher.stop(timeout=0.05))
    stopper.start()
    # Pre-fix stop() completes through the free lock (second settle
    # already done); post-fix it parks on the settle lock the batcher
    # holds. Either way, release the batcher only after stop() has
    # committed to its path.
    stopper.join(timeout=1.0)
    box.release.set()
    stopper.join(15)
    assert not stopper.is_alive(), "stop() wedged"
    batcher._thread.join(10)

    assert box.calls == 1, (
        f"request settled {box.calls} times — the no-double-settle "
        f"contract broke (error now {req.error!r})")
    assert req.error is not None and \
        req.error.startswith("executor failed"), req.error


# -- 2. daemon: stop() vs in-flight tick --------------------------------------


class _FakeClient:
    def __init__(self):
        self.created = []

    def list(self, *a, **k):
        return []

    def create(self, obj):
        self.created.append(obj)
        return obj

    def update(self, obj):
        return obj

    def update_status(self, obj):
        return obj

    def get_or_none(self, *a, **k):
        return None

    def delete(self, *a, **k):
        return None


class _FakePlatform:
    def node_name(self):
        return "node-a"

    def pci_devices(self):
        return []


class _FakePlugin:
    def __init__(self, *a, **k):
        pass

    def close(self):
        pass

    def is_initialized(self):
        return True

    def set_num_endpoints(self, n):
        pass


class _FakeManager:
    def __init__(self):
        self.stopped = False

    def start_vsp(self):
        pass

    def setup_devices(self, num_endpoints: int = 8) -> bool:
        return True

    def listen(self):
        pass

    def serve(self):
        pass

    def check_ping(self):
        return True

    def stop(self):
        self.stopped = True


def test_daemon_stop_joins_inflight_tick(monkeypatch):
    """Pre-fix: stop() returned while the tick thread was still inside
    detect_all; the tick then started a side manager AFTER stop's
    teardown, leaving an orphan manager nothing would ever stop.
    Post-fix stop() joins the serve thread first, so the in-flight
    tick's manager is torn down like any other."""
    from dpu_operator_tpu.daemon import daemon as daemon_mod

    monkeypatch.setattr(daemon_mod, "GrpcPlugin", _FakePlugin)
    managers = []

    def factory(det, plugin):
        mgr = _FakeManager()
        managers.append(mgr)
        return mgr

    d = daemon_mod.Daemon(
        client=_FakeClient(), platform=_FakePlatform(),
        detectors=[], tick_interval=0.01,
        register_device_plugin=False, side_manager_factory=factory)

    det = DetectedDpu(identifier="tpu-test-0", product_name="tpu",
                      is_dpu_side=True, vendor="tpu",
                      node_name="node-a")
    entered = threading.Event()
    release = threading.Event()

    def blocking_detect_all():
        entered.set()
        assert release.wait(10), "test sequencing wedged"
        return [det]

    d._detector.detect_all = blocking_detect_all
    d.start()
    assert entered.wait(10), "tick never started"

    stopper = threading.Thread(target=d.stop)
    stopper.start()
    assert d._stop.wait(10)
    # The tick is mid-flight (parked in detection) while stop() runs:
    # pre-fix, stop() has already finished its teardown by the time
    # the detection returns; post-fix it is joining the serve thread.
    release.set()
    stopper.join(15)
    assert not stopper.is_alive(), "daemon stop() wedged"
    d._thread.join(10)

    assert managers, "the in-flight tick never started its manager"
    assert all(m.stopped for m in managers), (
        "a side manager started by the in-flight tick survived "
        "stop() — orphaned thread + re-created CR")
    assert d.managed() == {}


def test_daemon_tick_refuses_registration_after_stop_teardown(
        monkeypatch):
    """The bounded-join escape hatch: a tick wedged PAST stop()'s join
    budget resumes after the teardown — it must tear its own manager
    down instead of registering it into the emptied dict (which would
    recreate the orphan the join exists to prevent)."""
    from dpu_operator_tpu.daemon import daemon as daemon_mod

    monkeypatch.setattr(daemon_mod, "GrpcPlugin", _FakePlugin)
    managers = []

    def factory(det, plugin):
        mgr = _FakeManager()
        managers.append(mgr)
        return mgr

    d = daemon_mod.Daemon(
        client=_FakeClient(), platform=_FakePlatform(),
        detectors=[], tick_interval=0.01,
        register_device_plugin=False, side_manager_factory=factory)
    det = DetectedDpu(identifier="tpu-test-1", product_name="tpu",
                      is_dpu_side=True, vendor="tpu",
                      node_name="node-a")
    # Simulate the wedged-tick case directly: stop() has fully torn
    # down (no serve thread to join), THEN the stale tick runs.
    d.stop()
    d._detector.detect_all = lambda: [det]
    d.tick()
    assert managers and all(m.stopped for m in managers), (
        "post-stop tick registered/orphaned its manager")
    assert d.managed() == {}
