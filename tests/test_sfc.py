"""Per-node ServiceFunctionChain reconciler (daemon/sfc.py) — the
counterpart of the reference's SFC coverage in e2e_test.go:458-486 and
the sfc-reconciler behavior (internal/daemon/sfc-reconciler/sfc.go)."""

import time

import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.api import v1
from dpu_operator_tpu.daemon.sfc import SfcNodeReconciler, setup_sfc_controller
from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster, Manager, Request


def wait_for(predicate, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


@pytest.fixture
def client():
    c = InMemoryClient(InMemoryCluster())
    c.create(
        {
            "apiVersion": "v1",
            "kind": "Node",
            "metadata": {
                "name": "node-a",
                "labels": {v.DPU_SIDE_LABEL: v.DPU_SIDE_DPU},
            },
        }
    )
    return c


def make_sfc(client, name="chain1", node_selector=None, nfs=None):
    sfc = v1.new_service_function_chain(
        name,
        v.NAMESPACE,
        node_selector=node_selector,
        network_functions=nfs
        or [{"name": "nf-a", "image": "quay.io/example/nf:1"}],
    )
    return client.create(sfc)


def test_nf_pod_created_with_reference_shape(client):
    """NF pod: two NAD attachments, 2 fabric-endpoint requests/limits,
    privileged + NET_RAW/NET_ADMIN (reference sfc.go:35-76,
    e2e assertions e2e_test.go:458-478)."""
    make_sfc(client, node_selector={v.DPU_SIDE_LABEL: v.DPU_SIDE_DPU})
    r = SfcNodeReconciler(client, "node-a")
    r.reconcile(Request(v.NAMESPACE, "chain1"))

    pod = client.get("v1", "Pod", v.NAMESPACE, "nf-a")
    nets = pod["metadata"]["annotations"]["k8s.v1.cni.cncf.io/networks"]
    assert nets == f"{v.NF_NAD_NAME}, {v.NF_NAD_NAME}"
    ctr = pod["spec"]["containers"][0]
    assert ctr["image"] == "quay.io/example/nf:1"
    assert ctr["resources"]["requests"][v.DPU_RESOURCE_NAME] == "2"
    assert ctr["resources"]["limits"][v.DPU_RESOURCE_NAME] == "2"
    sec = ctr["securityContext"]
    assert sec["privileged"] is True
    assert set(sec["capabilities"]["add"]) == {"NET_RAW", "NET_ADMIN"}
    # Owned by the SFC so chain deletion GCs the pod.
    owners = pod["metadata"]["ownerReferences"]
    assert owners[0]["kind"] == v1.KIND_SERVICE_FUNCTION_CHAIN


def test_node_selector_mismatch_creates_nothing(client):
    make_sfc(client, node_selector={v.DPU_SIDE_LABEL: v.DPU_SIDE_HOST})
    r = SfcNodeReconciler(client, "node-a")
    r.reconcile(Request(v.NAMESPACE, "chain1"))
    assert client.get_or_none("v1", "Pod", v.NAMESPACE, "nf-a") is None


def test_empty_selector_matches_all_nodes(client):
    make_sfc(client, node_selector={})
    SfcNodeReconciler(client, "node-a").reconcile(Request(v.NAMESPACE, "chain1"))
    assert client.get_or_none("v1", "Pod", v.NAMESPACE, "nf-a") is not None


def test_image_update_converges(client):
    sfc = make_sfc(client)
    r = SfcNodeReconciler(client, "node-a")
    r.reconcile(Request(v.NAMESPACE, "chain1"))
    sfc["spec"]["networkFunctions"][0]["image"] = "quay.io/example/nf:2"
    client.update(sfc)
    r.reconcile(Request(v.NAMESPACE, "chain1"))
    pod = client.get("v1", "Pod", v.NAMESPACE, "nf-a")
    assert pod["spec"]["containers"][0]["image"] == "quay.io/example/nf:2"


def test_controller_watch_and_gc(client):
    """Wired through the Manager: creating the SFC CR produces the pod;
    deleting the CR garbage-collects it (ownerReference cascade)."""
    mgr = Manager(client)
    setup_sfc_controller(mgr, client, "node-a")
    mgr.start()
    try:
        make_sfc(client, nfs=[
            {"name": "nf-1", "image": "img:a"},
            {"name": "nf-2", "image": "img:b"},
        ])
        assert wait_for(
            lambda: client.get_or_none("v1", "Pod", v.NAMESPACE, "nf-1") is not None
            and client.get_or_none("v1", "Pod", v.NAMESPACE, "nf-2") is not None
        ), "NF pods never created"
        client.delete(v1.GROUP_VERSION, v1.KIND_SERVICE_FUNCTION_CHAIN, v.NAMESPACE, "chain1")
        assert wait_for(
            lambda: client.get_or_none("v1", "Pod", v.NAMESPACE, "nf-1") is None
            and client.get_or_none("v1", "Pod", v.NAMESPACE, "nf-2") is None
        ), "NF pods survived chain deletion"
    finally:
        mgr.stop()


def test_node_label_change_triggers_rematch(client):
    """An SFC whose selector doesn't match is picked up when this node
    gains the label (covered by the Node watch; the reference only
    rechecks on its 1-minute requeue)."""
    mgr = Manager(client)
    setup_sfc_controller(mgr, client, "node-a")
    mgr.start()
    try:
        make_sfc(client, node_selector={"sfc": "yes"})
        time.sleep(0.3)
        assert client.get_or_none("v1", "Pod", v.NAMESPACE, "nf-a") is None
        node = client.get("v1", "Node", None, "node-a")
        node["metadata"]["labels"]["sfc"] = "yes"
        client.update(node)
        assert wait_for(
            lambda: client.get_or_none("v1", "Pod", v.NAMESPACE, "nf-a") is not None
        ), "label change did not trigger reconcile"
    finally:
        mgr.stop()
