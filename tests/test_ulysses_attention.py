"""Ulysses attention (parallel/ulysses_attention.py) — the all-to-all
twin of ring attention. Same proof standard as the ring family: XLA path
against a dense multi-head reference (causal and unmasked, bf16 and
f32), round-trip layout identity, the pallas exchange EXECUTED under TPU
interpret mode against the XLA path, and agreement with ring attention
itself on the same problem."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]).reshape(1, n, 1),
                axis_names=("dp", "sp", "tp"))


def _mk_qkv(S, H, dk, dv, dtype=np.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = np.asarray(jax.random.normal(ks[0], (S, H, dk))).astype(dtype)
    k = np.asarray(jax.random.normal(ks[1], (S, H, dk))).astype(dtype)
    v = np.asarray(jax.random.normal(ks[2], (S, H, dv))).astype(dtype)
    return q, k, v


def _shard(mesh, *arrays):
    sh = NamedSharding(mesh, P("sp", None, None))
    return [jax.device_put(jnp.asarray(a), sh) for a in arrays]


def test_ulysses_matches_dense_reference():
    """Both exchanges and the head regrouping must be layout-exact:
    every (position, head) pair's output equals plain attention — with
    distinct per-head values so a head permutation cannot pass."""
    from dpu_operator_tpu.parallel.ulysses_attention import (
        dense_attention_reference, make_ulysses_attention)

    for n in (2, 4, 8):
        mesh = _mesh(n)
        S, H, dk, dv = 4 * n, 2 * n, 16, 8
        q, k, v = _mk_qkv(S, H, dk, dv, seed=n)
        args = _shard(mesh, q, k, v)
        for causal in (False, True):
            fn = make_ulysses_attention(mesh, "sp", causal=causal)
            out = np.asarray(fn(*args))
            ref = np.asarray(dense_attention_reference(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal))
            np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_ulysses_agrees_with_ring_attention():
    """The two sp decompositions are interchangeable: on the same
    single-head problem (ring attention's contract), Ulysses with the
    head dim folded away must produce ring attention's output."""
    from dpu_operator_tpu.parallel.ring_attention import make_ring_attention
    from dpu_operator_tpu.parallel.ulysses_attention import (
        make_ulysses_attention)

    n = 4
    mesh = _mesh(n)
    S, H, dk, dv = 4 * n, n, 8, 8
    q, k, v = _mk_qkv(S, H, dk, dv, seed=3)
    args3 = _shard(mesh, q, k, v)
    for causal in (False, True):
        uly = np.asarray(make_ulysses_attention(
            mesh, "sp", causal=causal)(*args3))
        # Ring attention is single-head [S, D]; run it per head.
        for h in range(H):
            sh = NamedSharding(mesh, P("sp", None))
            ring = np.asarray(make_ring_attention(mesh, "sp", causal=causal)(
                jax.device_put(jnp.asarray(q[:, h]), sh),
                jax.device_put(jnp.asarray(k[:, h]), sh),
                jax.device_put(jnp.asarray(v[:, h]), sh)))
            np.testing.assert_allclose(uly[:, h], ring,
                                       rtol=2e-5, atol=2e-5)


def test_ulysses_bf16_keeps_f32_softmax():
    from dpu_operator_tpu.parallel.ulysses_attention import (
        dense_attention_reference, make_ulysses_attention)

    n = 8
    mesh = _mesh(n)
    S, H = 4 * n, n
    qf, kf, vf = _mk_qkv(S, H, 16, 8, seed=5)
    qb, kb, vb = (jnp.asarray(a, jnp.bfloat16) for a in (qf, kf, vf))
    out = np.asarray(make_ulysses_attention(mesh, "sp", causal=True)(
        *_shard(mesh, qb, kb, vb))).astype(np.float32)
    ref = np.asarray(dense_attention_reference(
        jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf), True))
    np.testing.assert_allclose(out, ref, rtol=0.05, atol=0.05)


def test_ulysses_rejects_unsplittable_heads():
    from dpu_operator_tpu.parallel.ulysses_attention import (
        make_ulysses_attention)

    mesh = _mesh(4)
    S, H = 16, 3  # 3 heads over 4 devices
    q, k, v = _mk_qkv(S, H, 8, 8)
    fn = make_ulysses_attention(mesh, "sp")
    with pytest.raises(ValueError, match="ring attention"):
        fn(*_shard(mesh, q, k, v))


def test_pallas_ulysses_interpret_mode():
    """The pallas remote-DMA exchange path EXECUTES under TPU interpret
    mode and matches the XLA path exactly (the same standard the ring
    family holds)."""
    from jax.experimental.pallas import tpu as pltpu

    from dpu_operator_tpu.parallel.ulysses_attention import (
        make_ulysses_attention)

    n = 4
    mesh = _mesh(n)
    S, H, dk, dv = 4 * n, n, 8, 8
    q, k, v = _mk_qkv(S, H, dk, dv, seed=9)
    args = _shard(mesh, q, k, v)
    for causal in (False, True):
        xla = np.asarray(make_ulysses_attention(
            mesh, "sp", causal=causal, use_pallas=False)(*args))
        with pltpu.force_tpu_interpret_mode():
            pal = np.asarray(make_ulysses_attention(
                mesh, "sp", causal=causal, use_pallas=True)(*args))
        np.testing.assert_allclose(pal, xla, rtol=2e-5, atol=2e-5)


def test_ulysses_is_differentiable_like_dense():
    """Training-completeness: jax.grad through both all-to-alls and the
    local softmax must equal the dense reference's gradients — Ulysses
    has to be usable as a training-time sp block, not just inference."""
    from dpu_operator_tpu.parallel.ulysses_attention import (
        dense_attention_reference, make_ulysses_attention)

    n = 4
    mesh = _mesh(n)
    S, H, dk, dv = 4 * n, n, 8, 8
    q, k, v = _mk_qkv(S, H, dk, dv, seed=31)
    fn = make_ulysses_attention(mesh, "sp", causal=True)

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(dense_attention_reference(q, k, v, True) ** 2)

    args = _shard(mesh, q, k, v)
    grads = jax.grad(loss, argnums=(0, 1, 2))(*args)
    ref = jax.grad(ref_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for g, r, name in zip(grads, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-4, atol=1e-6, err_msg=name)
