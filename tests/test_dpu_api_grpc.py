"""Round-trip the gRPC contract over a real unix socket — the same process
boundary the daemon↔VSP split crosses in production."""

import concurrent.futures

import grpc
from google.protobuf import empty_pb2

from dpu_operator_tpu.dpu_api import dpu_api_pb2 as pb
from dpu_operator_tpu.dpu_api import services


class _Life(services.LifeCycleServicer):
    def Init(self, request, context):
        assert request.dpu_mode == pb.DPU_MODE_DPU
        return pb.IpPort(ip="127.0.0.1", port=50051)


class _Dev(services.DeviceServicer):
    def GetDevices(self, request, context):
        resp = pb.DeviceListResponse()
        d = resp.devices["tpu-0-ep0"]
        d.id = "tpu-0-ep0"
        d.health = pb.HEALTHY
        d.topology.coords = "0,0,0"
        d.topology.links.add(neighbor="1,0,0", gbps=400)
        return resp

    def SetNumEndpoints(self, request, context):
        return pb.EndpointCount(count=request.count)


class _Beat(services.HeartbeatServicer):
    def Ping(self, request, context):
        return pb.PingResponse(healthy=True)


def test_vsp_contract_over_unix_socket(tmp_root):
    sock = tmp_root.vendor_plugin_socket()
    tmp_root.ensure_socket_dir(sock)
    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=4))
    services.add_lifecycle(_Life(), server)
    services.add_device(_Dev(), server)
    services.add_heartbeat(_Beat(), server)
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    try:
        channel = grpc.insecure_channel(f"unix://{sock}")
        life = services.LifeCycleStub(channel)
        ipport = life.Init(
            pb.InitRequest(dpu_mode=pb.DPU_MODE_DPU, dpu_identifier="tpu-v5e-w0")
        )
        assert (ipport.ip, ipport.port) == ("127.0.0.1", 50051)

        dev = services.DeviceStub(channel)
        devices = dev.GetDevices(empty_pb2.Empty()).devices
        assert devices["tpu-0-ep0"].topology.links[0].gbps == 400
        assert dev.SetNumEndpoints(pb.EndpointCount(count=8)).count == 8

        beat = services.HeartbeatStub(channel)
        assert beat.Ping(pb.PingRequest(timestamp_ns=1, sender_id="host")).healthy
    finally:
        server.stop(0)
