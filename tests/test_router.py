"""Prefix-aware router (ISSUE 17): content-addressed chain keys,
gossip staleness, contiguous-prefix scoring, affinity vs load-skew
placement, the cross-replica KV pull (hello-checked both ends,
chained-hash re-verified on import), and every fallback's ledger
hygiene — a failed or refused pull must leave BOTH replicas' allocator
and tier ledgers clean and degrade to local prefill of the same
stream."""

import time

import pytest

from dpu_operator_tpu.serving import (AdmissionQueue, ContinuousBatcher,
                                      GenerateRequest,
                                      SyntheticKVExecutor)
from dpu_operator_tpu.serving.kvcache import CACHE_OWNER, PrefixTree
from dpu_operator_tpu.serving.kvcache.allocator import _ROOT
from dpu_operator_tpu.serving.router import (GossipBoard, PrefixRouter,
                                             ReplicaGossip,
                                             RouterReplica, chain_keys)
from dpu_operator_tpu.utils.metrics import Registry

PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]  # 3 blocks at bs=4


def _req(prompt=PROMPT, max_tokens=5, deadline_s=60.0):
    return GenerateRequest(prompt_vec=None, max_tokens=max_tokens,
                           deadline=time.monotonic() + deadline_s,
                           prompt_tokens=list(prompt))


def _replica(name, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("vocab", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("host_tier_bytes", 1 << 20)
    ex = SyntheticKVExecutor(**kw)
    return RouterReplica(name, AdmissionQueue(max_depth=64), ex)


def _run_on(rep, reqs, timeout=30.0):
    b = ContinuousBatcher(rep.executor, rep.queue)
    b.start()
    try:
        for r in reqs:
            assert r.wait(timeout=timeout), "request lost"
    finally:
        b.stop()
    for r in reqs:
        assert r.error is None, r.error
    return [list(r.tokens) for r in reqs]


def _assert_clean(rep):
    ex = rep.executor
    ex.prefix.flush()
    ex.allocator.assert_clean()
    if ex.tier is not None:
        ex.tier.assert_clean()


# -- chain keys and gossip ---------------------------------------------------


def test_chain_keys_match_the_prefix_tree_chain():
    keys = chain_keys(PROMPT, 4)
    # (len - 1) // bs: the last prompt token always recomputes.
    assert len(keys) == 2
    parent = _ROOT
    for i, key in enumerate(keys):
        chunk = tuple(PROMPT[i * 4:(i + 1) * 4])
        parent = PrefixTree._key(parent, chunk)
        assert key == parent
    assert chain_keys(PROMPT[:4], 4) == []  # no FULL cacheable block


def test_gossip_staleness_reads_as_empty():
    board = GossipBoard()
    board.publish("a", {"k1": "hbm"}, now=100.0)
    board.publish("b", {"k2": "host"}, now=104.0)
    view = board.snapshot(max_age_s=5.0, now=106.0)
    assert view["a"] == {}            # 6s old: stale, reads empty
    assert view["b"] == {"k2": "host"}
    # No age filter: everything reads.
    assert board.snapshot()["a"] == {"k1": "hbm"}


def test_replica_gossip_collects_hbm_over_host_and_rate_limits():
    rep = _replica("a")
    try:
        _run_on(rep, [rep.queue.submit(r) or r for r in [_req()]])
        board = GossipBoard()
        g = ReplicaGossip(board, "a", [rep.executor], cadence_s=30.0)
        assert g.maybe_publish()
        keymap = board.snapshot()["a"]
        assert set(keymap.values()) == {"hbm"}
        assert len(keymap) == 3
        # Cadence: a second publish inside the window is a no-op...
        assert not g.maybe_publish()
        # ...unless forced (the router's route-time refresh path).
        rep.executor.prefix.evict(99)
        assert g.maybe_publish(force=True)
        assert set(board.snapshot()["a"].values()) == {"host"}
        _assert_clean(rep)
    finally:
        rep.close()
        rep.executor.close()


# -- construction contracts --------------------------------------------------


def test_router_refuses_mixed_block_sizes_and_bad_policy():
    a, b = _replica("a"), _replica("b", block_size=8)
    try:
        with pytest.raises(ValueError, match="block_size"):
            PrefixRouter([a, b])
        with pytest.raises(ValueError, match="policy"):
            PrefixRouter([a], policy="sticky")
        with pytest.raises(ValueError, match="at least one"):
            PrefixRouter([])
    finally:
        for r in (a, b):
            r.close()
            r.executor.close()


# -- scoring and placement ---------------------------------------------------


def test_scores_require_contiguous_chain_from_root():
    a, b = _replica("a"), _replica("b")
    router = PrefixRouter([a, b], cadence_s=0.0)
    try:
        keys = chain_keys(PROMPT, 4)
        router.board.publish("a", {k: "hbm" for k in keys})
        # An island past a gap is unreachable by the restore walk.
        router.board.publish("b", {keys[1]: "hbm"})
        scored = router.scores(PROMPT)
        assert scored == {"a": 8, "b": 0}
    finally:
        router.close()
        for r in (a, b):
            r.executor.close()


def test_affinity_routes_to_the_replica_holding_the_prefix():
    a, b = _replica("a"), _replica("b")
    reg = Registry()
    router = PrefixRouter([a, b], cadence_s=0.0, registry=reg)
    try:
        r1 = _req()
        chosen = router.submit(r1)
        first = _run_on(chosen, [r1])[0]

        r2 = _req()
        chosen2 = router.submit(r2)
        assert chosen2 is chosen      # the prefix pins the request
        again = _run_on(chosen2, [r2])[0]
        assert again == first
        assert chosen2.executor.kv_stats()["prefix_hit_tokens_hbm"] == 8
        assert reg.counter_value("serving_router_routed_total",
                                 {"outcome": "affinity"}) == 1
        for rep in (a, b):
            _assert_clean(rep)
    finally:
        router.close()
        for r in (a, b):
            r.executor.close()


def test_round_robin_policy_alternates_and_never_pulls():
    a, b = _replica("a"), _replica("b")
    reg = Registry()
    router = PrefixRouter([a, b], policy="round_robin", registry=reg)
    try:
        picks = [router.route(_req()).name for _ in range(4)]
        assert picks == ["a", "b", "a", "b"]
        assert reg.counter_value("serving_router_routed_total",
                                 {"outcome": "rr"}) == 4
    finally:
        router.close()
        for r in (a, b):
            r.executor.close()


# -- the cross-replica pull --------------------------------------------------


def test_load_skew_pulls_prefix_to_the_cold_replica():
    """The affinity-miss pull end to end: the owner is swamped, the
    request lands on the least-loaded replica, and the prefix blocks
    arrive there over KVPageStream before prefill — first serve is
    credited to the REMOTE tier and the stream is identical."""
    a, b = _replica("a"), _replica("b")
    reg = Registry()
    router = PrefixRouter([a, b], cadence_s=0.0, max_load_skew=2,
                          registry=reg)
    try:
        r1 = _req()
        assert router.submit(r1) is a  # cold: ties break to a
        first = _run_on(a, [r1])[0]

        # Swamp a's queue past the skew (never driven — pure load).
        for _ in range(5):
            a.queue.submit(_req())

        r2 = _req()
        chosen = router.submit(r2)
        assert chosen is b
        assert reg.counter_value("serving_router_routed_total",
                                 {"outcome": "load"}) == 1
        assert reg.counter_value(
            "serving_router_pulled_blocks_total") == 2
        again = _run_on(b, [r2])[0]
        assert again == first
        st = b.executor.kv_stats()
        assert st["prefix_hit_tokens_remote"] == 8
        for rep in (a, b):
            _assert_clean(rep)
    finally:
        router.close()
        for r in (a, b):
            r.executor.close()


def test_pull_refused_on_kv_spec_mismatch_falls_back_to_prefill():
    """KVSpec hello-checks both ends: replicas with different model
    geometry refuse the stream at hello, the pull counts as failed,
    and the request still completes by local prefill — both ledgers
    clean."""
    a = _replica("a")
    # Same model (identical streams), different pool layout: the spec
    # fingerprint disagrees on max_blocks_per_req, so the hello must
    # refuse the stream before any payload moves.
    b = _replica("b", max_blocks_per_req=8)
    reg = Registry()
    router = PrefixRouter([a, b], cadence_s=0.0, max_load_skew=2,
                          registry=reg)
    try:
        r1 = _req()
        assert router.submit(r1) is a
        first = _run_on(a, [r1])[0]
        for _ in range(5):
            a.queue.submit(_req())

        r2 = _req()
        chosen = router.submit(r2)
        assert chosen is b            # placement still by load
        assert reg.counter_value(
            "serving_router_pull_failed_total") == 1
        again = _run_on(b, [r2])[0]
        assert again == first         # deterministic either way
        assert b.executor.kv_stats()["prefix_hit_tokens_remote"] == 0
        for rep in (a, b):
            _assert_clean(rep)
    finally:
        router.close()
        for r in (a, b):
            r.executor.close()


def test_pull_import_rejects_lying_chain_keys():
    """The import side re-derives every claimed chain key from the
    shipped token ids (GL019): a sender whose keys do not match its
    tokens is refused before any block is acquired."""
    a = _replica("a")
    try:
        keys = chain_keys(PROMPT, 4)
        meta = {"prefix_pull": True, "req": "x", "xfer": "x",
                "tokens": 8, "n_blocks": 2,
                "prompt_tokens": PROMPT[:8], "settled": [],
                "max_tokens": 0, "keys": [keys[0], "forged"]}
        with pytest.raises(ValueError, match="re-verification"):
            a._pull_import(meta, [])
        meta["keys"] = keys            # right keys, wrong geometry
        meta["n_blocks"] = 3
        with pytest.raises(ValueError, match="geometry"):
            a._pull_import(meta, [])
        assert not a._pull_import.__self__.executor.allocator.leaked(
            ignore=(CACHE_OWNER,))
        _assert_clean(a)
    finally:
        a.close()
        a.executor.close()
