"""Test configuration.

Sharding/mesh tests run on a virtual 8-device CPU platform (the same
technique the driver uses for the multi-chip dry-run); env vars must be
set before jax initialises its backends, hence at conftest import time.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize imports jax before this file runs, pinned to the
# tunnelled TPU; when that tunnel is down, any in-process jax.devices()
# blocks forever in a claim-retry loop. The backend is registered but not
# yet initialised, so a config update here still redirects the whole test
# process onto the virtual CPU platform. The real chip stays the domain
# of bench.py (subprocess, timeout-guarded).
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def netns():
    """CNI dataplane tests need root + netlink; probe lazily (only when a
    test actually asks for the fixture) and skip gracefully elsewhere."""
    import subprocess
    import uuid

    if os.geteuid() != 0:
        pytest.skip("needs root for netns/veth")
    probe = f"pr{uuid.uuid4().hex[:8]}"
    r = subprocess.run(
        ["ip", "link", "add", probe + "a", "type", "veth", "peer", "name", probe + "b"],
        capture_output=True,
    )
    if r.returncode != 0:
        pytest.skip(f"veth creation unavailable: {r.stderr.decode().strip()}")
    subprocess.run(["ip", "link", "del", probe + "a"], capture_output=True)
    return True


@pytest.fixture
def tmp_root():
    """A re-rooted PathManager temp dir (reference tests re-root every
    socket path the same way, internal/utils/path_manager.go:16-18).

    Unix socket paths are capped at ~107 chars, so this uses a short
    /tmp/dpu-* dir rather than pytest's deeply nested tmp_path."""
    import shutil
    import tempfile

    from dpu_operator_tpu.utils import PathManager

    d = tempfile.mkdtemp(prefix="dpu-")
    try:
        yield PathManager(root=d)
    finally:
        shutil.rmtree(d, ignore_errors=True)
