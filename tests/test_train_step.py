"""The five-axis training step (parallel/train_step.py): loss AND
gradients must match a dense single-device reference of the same math —
the only evidence that a distributed training step is actually the
training step it claims to be. Covers three mesh factorings so every
axis is exercised with size > 1 somewhere."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


def _mesh(shape):
    from jax.sharding import Mesh

    n = int(np.prod([s for s in shape.values()]))
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]).reshape(*shape.values()),
                tuple(shape.keys()))


@pytest.mark.parametrize("shape", [
    {"dp": 2, "pp": 2, "sp": 1, "tp": 1, "ep": 2},
    {"dp": 1, "pp": 2, "sp": 1, "tp": 2, "ep": 2},
    {"dp": 1, "pp": 1, "sp": 2, "tp": 2, "ep": 2},
])
def test_five_axis_step_matches_dense_reference(shape):
    from dpu_operator_tpu.parallel.train_step import (
        dense_loss_reference, init_params, make_train_step, shard_params)

    mesh = _mesh(shape)
    S, E = shape["pp"], shape["ep"]
    d, h = 8, 16
    M, mb, seq = 3, 4 * shape["dp"], 2 * shape["sp"]
    cf = float(E)  # capacity >= local tokens: no drops, exact compare

    params = init_params(S, d, h, E, seed=3)
    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, seq, d))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (M, mb, seq, d))

    train_step, loss_fn = make_train_step(mesh, capacity_factor=cf,
                                          lr=0.05)
    sharded = shard_params(params, mesh)

    # Forward: distributed loss == dense reference loss.
    loss = float(loss_fn(sharded, x, tgt))
    ref_loss = float(dense_loss_reference(
        params, x, tgt, capacity_factor=cf, shards=shape))
    np.testing.assert_allclose(loss, ref_loss, rtol=2e-5)

    # Backward: every gradient leaf == dense reference gradient. This
    # is where wrong collective transposes (missing dp sync, bad
    # all_to_all transpose) show up.
    grads = jax.grad(loss_fn)(sharded, x, tgt)
    ref_grads = jax.grad(
        lambda p: dense_loss_reference(p, x, tgt, capacity_factor=cf,
                                       shards=shape))(params)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(grads[key]), np.asarray(ref_grads[key]),
            rtol=5e-4, atol=1e-6, err_msg=key)

    # And the STEP steps: one update lowers the loss.
    loss1, new_params = train_step(sharded, x, tgt)
    loss2 = float(loss_fn(new_params, x, tgt))
    assert loss2 < float(loss1), (loss1, loss2)


def test_five_axis_step_capacity_drops_still_train():
    """With real capacity pressure (drops happening) the step must stay
    finite and still descend — drops zero some expert outputs, they
    must not poison gradients with NaNs."""
    from dpu_operator_tpu.parallel.train_step import (
        init_params, make_train_step, shard_params)

    shape = {"dp": 2, "pp": 2, "sp": 1, "tp": 1, "ep": 2}
    mesh = _mesh(shape)
    params = shard_params(init_params(2, 8, 16, 2, seed=9), mesh)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 8, 2, 8))
    tgt = jax.random.normal(jax.random.PRNGKey(5), (2, 8, 2, 8))
    train_step, loss_fn = make_train_step(mesh, capacity_factor=0.5,
                                          lr=0.01)
    loss1, new_params = train_step(params, x, tgt)
    loss2, _ = train_step(new_params, x, tgt)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss1)


@pytest.mark.parametrize("shape,v", [
    ({"dp": 2, "pp": 2, "sp": 1, "tp": 1, "ep": 2}, 1),
    ({"dp": 1, "pp": 2, "sp": 1, "tp": 2, "ep": 2}, 2),
])
def test_five_axis_1f1b_step_matches_dense_reference(shape, v):
    """The 1F1B-scheduled five-axis step: hand-VJP pipeline backward +
    explicit per-leaf grad sync must equal the dense reference exactly —
    including v=2 interleaved chunks, where the model is twice as deep
    and chunk placement is round-robin."""
    from dpu_operator_tpu.parallel.train_step import (
        dense_loss_reference, init_params, interleave_params,
        make_train_step_1f1b, shard_params, uninterleave_params)

    mesh = _mesh(shape)
    pp, E = shape["pp"], shape["ep"]
    S = pp * v
    d, h = 8, 16
    M, mb, seq = 4, 4 * shape["dp"], 2 * shape["sp"]
    cf = float(E)

    params = init_params(S, d, h, E, seed=5)
    x = jax.random.normal(jax.random.PRNGKey(4), (M, mb, seq, d))
    tgt = jax.random.normal(jax.random.PRNGKey(6), (M, mb, seq, d))

    step = make_train_step_1f1b(mesh, capacity_factor=cf, lr=0.05,
                                M=M, v=v)
    sharded = shard_params(interleave_params(params, pp, v), mesh)
    loss, new_params = step(sharded, x, tgt)

    ref_loss = float(dense_loss_reference(
        params, x, tgt, capacity_factor=cf, shards=shape))
    np.testing.assert_allclose(float(loss), ref_loss, rtol=2e-5)

    # Recover the implied gradients from the SGD update and compare to
    # the dense reference — catches wrong sync axes or VJP masking.
    ref_grads = jax.grad(
        lambda p: dense_loss_reference(p, x, tgt, capacity_factor=cf,
                                       shards=shape))(params)
    inter = interleave_params(params, pp, v)
    implied = uninterleave_params(
        {k: (np.asarray(inter[k]) - np.asarray(new_params[k])) / 0.05
         for k in params}, pp, v)
    for key in params:
        np.testing.assert_allclose(
            implied[key], np.asarray(ref_grads[key]),
            rtol=5e-4, atol=1e-6, err_msg=key)

    # And the step descends.
    loss2, _ = step(new_params, x, tgt)
    assert float(loss2) < float(loss), (loss, loss2)


def test_replicated_ep_compat_path_still_exact():
    """token_shard_ep=False keeps the rounds-<=4 replicated program —
    still gradient-exact against its own dense reference (the dryrun
    uses the pair to measure what the token sharding buys)."""
    from dpu_operator_tpu.parallel.train_step import (
        dense_loss_reference, init_params, make_train_step, shard_params)

    shape = {"dp": 1, "pp": 2, "sp": 1, "tp": 2, "ep": 2}
    mesh = _mesh(shape)
    d, h = 8, 16
    M, mb, seq = 3, 4, 2
    cf = float(shape["ep"])
    params = init_params(shape["pp"], d, h, shape["ep"], seed=9)
    x = jax.random.normal(jax.random.PRNGKey(8), (M, mb, seq, d))
    tgt = jax.random.normal(jax.random.PRNGKey(9), (M, mb, seq, d))

    _, loss_fn = make_train_step(mesh, capacity_factor=cf,
                                 token_shard_ep=False)
    sharded = shard_params(params, mesh)
    loss = float(loss_fn(sharded, x, tgt))
    ref = float(dense_loss_reference(params, x, tgt, capacity_factor=cf,
                                     shards=shape, token_shard_ep=False))
    np.testing.assert_allclose(loss, ref, rtol=2e-5)
    grads = jax.grad(loss_fn)(sharded, x, tgt)
    ref_grads = jax.grad(
        lambda p: dense_loss_reference(p, x, tgt, capacity_factor=cf,
                                       shards=shape,
                                       token_shard_ep=False))(params)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(grads[key]), np.asarray(ref_grads[key]),
            rtol=5e-4, atol=1e-6, err_msg=key)


@pytest.mark.parametrize("shape", [
    {"dp": 2, "pp": 1, "sp": 2, "tp": 1, "ep": 2},
    {"dp": 1, "pp": 2, "sp": 2, "tp": 2, "ep": 1},
])
def test_five_axis_step_with_ring_attention_matches_dense(shape):
    """attention=True makes sp (and token-sharded ep) REAL cross-token
    axes: every stage opens with causal ring attention whose K/V blocks
    stream around the combined ("sp","ep") ring. Loss and gradients
    must equal a dense reference computing full-sequence attention —
    only possible if the ring's global causal masking and the
    sp-major/ep-minor shard order are exactly right."""
    from dpu_operator_tpu.parallel.train_step import (
        dense_loss_reference, init_params, make_train_step, shard_params)

    mesh = _mesh(shape)
    S, E = shape["pp"], shape["ep"]
    d, h = 8, 16
    M, mb, seq = 2, 2 * shape["dp"], 4 * shape["sp"] * shape["ep"]
    cf = float(E)

    params = init_params(S, d, h, E, seed=11, attention=True)
    x = jax.random.normal(jax.random.PRNGKey(12), (M, mb, seq, d))
    tgt = jax.random.normal(jax.random.PRNGKey(13), (M, mb, seq, d))

    train_step, loss_fn = make_train_step(mesh, capacity_factor=cf,
                                          attention=True)
    sharded = shard_params(params, mesh)
    loss = float(loss_fn(sharded, x, tgt))
    ref_loss = float(dense_loss_reference(
        params, x, tgt, capacity_factor=cf, shards=shape))
    np.testing.assert_allclose(loss, ref_loss, rtol=2e-5)

    grads = jax.grad(loss_fn)(sharded, x, tgt)
    ref_grads = jax.grad(
        lambda p: dense_loss_reference(p, x, tgt, capacity_factor=cf,
                                       shards=shape))(params)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(grads[key]), np.asarray(ref_grads[key]),
            rtol=1e-3, atol=1e-6, err_msg=key)

    loss1, new_params = train_step(sharded, x, tgt)
    assert float(loss_fn(new_params, x, tgt)) < float(loss1)


def test_five_axis_1f1b_step_with_attention_matches_dense():
    """The 1F1B variant with attention: jax.vjp must differentiate the
    ring recurrence inside the masked schedule executor, and the
    explicit grad sync must cover the new replicated projections."""
    from dpu_operator_tpu.parallel.train_step import (
        dense_loss_reference, init_params, interleave_params,
        make_train_step_1f1b, shard_params, uninterleave_params)

    shape = {"dp": 1, "pp": 2, "sp": 2, "tp": 1, "ep": 2}
    mesh = _mesh(shape)
    pp, E, v = shape["pp"], shape["ep"], 1
    d, h = 8, 16
    M, mb, seq = 3, 2, 4 * shape["sp"] * shape["ep"]
    cf = float(E)

    params = init_params(pp * v, d, h, E, seed=15, attention=True)
    x = jax.random.normal(jax.random.PRNGKey(16), (M, mb, seq, d))
    tgt = jax.random.normal(jax.random.PRNGKey(17), (M, mb, seq, d))

    step = make_train_step_1f1b(mesh, capacity_factor=cf, lr=0.05,
                                M=M, v=v, attention=True)
    sharded = shard_params(interleave_params(params, pp, v), mesh)
    loss, new_params = step(sharded, x, tgt)
    ref_loss = float(dense_loss_reference(
        params, x, tgt, capacity_factor=cf, shards=shape))
    np.testing.assert_allclose(float(loss), ref_loss, rtol=2e-5)

    ref_grads = jax.grad(
        lambda p: dense_loss_reference(p, x, tgt, capacity_factor=cf,
                                       shards=shape))(params)
    inter = interleave_params(params, pp, v)
    implied = uninterleave_params(
        {k: (np.asarray(inter[k]) - np.asarray(new_params[k])) / 0.05
         for k in params}, pp, v)
    for key in params:
        np.testing.assert_allclose(
            implied[key], np.asarray(ref_grads[key]),
            rtol=1e-3, atol=1e-6, err_msg=key)

    loss2, _ = step(new_params, x, tgt)
    assert float(loss2) < float(loss), (loss, loss2)


def test_attention_with_replicated_ep_and_interleaved_1f1b():
    """The two shipped-but-otherwise-uncovered attention combinations:
    (a) token_shard_ep=False — the ring runs over sp alone and ep
    replicates the attention compute; (b) the 1F1B variant with v=2
    interleaved chunks — attention params slice per chunk inside the
    masked executor. Both must stay gradient-exact vs dense."""
    from dpu_operator_tpu.parallel.train_step import (
        dense_loss_reference, init_params, interleave_params,
        make_train_step, make_train_step_1f1b, shard_params,
        uninterleave_params)

    # (a) replicated-ep attention, GPipe path.
    shape = {"dp": 1, "pp": 1, "sp": 2, "tp": 1, "ep": 2}
    mesh = _mesh(shape)
    d, h = 8, 16
    M, mb, seq = 2, 2, 4 * shape["sp"]
    cf = float(shape["ep"])
    params = init_params(1, d, h, shape["ep"], seed=21, attention=True)
    x = jax.random.normal(jax.random.PRNGKey(22), (M, mb, seq, d))
    tgt = jax.random.normal(jax.random.PRNGKey(23), (M, mb, seq, d))
    _, loss_fn = make_train_step(mesh, capacity_factor=cf,
                                 token_shard_ep=False, attention=True)
    sharded = shard_params(params, mesh)
    loss = float(loss_fn(sharded, x, tgt))
    ref = float(dense_loss_reference(params, x, tgt, capacity_factor=cf,
                                     shards=shape, token_shard_ep=False))
    np.testing.assert_allclose(loss, ref, rtol=2e-5)
    grads = jax.grad(loss_fn)(sharded, x, tgt)
    ref_grads = jax.grad(
        lambda p: dense_loss_reference(p, x, tgt, capacity_factor=cf,
                                       shards=shape,
                                       token_shard_ep=False))(params)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(grads[key]), np.asarray(ref_grads[key]),
            rtol=1e-3, atol=1e-6, err_msg=f"replicated-ep {key}")

    # (b) attention under interleaved 1F1B (v=2), token-sharded ep.
    shape2 = {"dp": 1, "pp": 2, "sp": 1, "tp": 1, "ep": 2}
    mesh2 = _mesh(shape2)
    pp, v = shape2["pp"], 2
    M2, mb2, seq2 = 4, 2, 4 * shape2["ep"]
    params2 = init_params(pp * v, d, h, shape2["ep"], seed=25,
                          attention=True)
    x2 = jax.random.normal(jax.random.PRNGKey(26), (M2, mb2, seq2, d))
    t2 = jax.random.normal(jax.random.PRNGKey(27), (M2, mb2, seq2, d))
    step = make_train_step_1f1b(mesh2, capacity_factor=cf, lr=0.05,
                                M=M2, v=v, attention=True)
    sh2 = shard_params(interleave_params(params2, pp, v), mesh2)
    loss2, newp2 = step(sh2, x2, t2)
    ref2 = float(dense_loss_reference(params2, x2, t2, capacity_factor=cf,
                                      shards=shape2))
    np.testing.assert_allclose(float(loss2), ref2, rtol=2e-5)
    ref_g2 = jax.grad(
        lambda p: dense_loss_reference(p, x2, t2, capacity_factor=cf,
                                       shards=shape2))(params2)
    inter = interleave_params(params2, pp, v)
    implied = uninterleave_params(
        {k: (np.asarray(inter[k]) - np.asarray(newp2[k])) / 0.05
         for k in params2}, pp, v)
    for key in params2:
        np.testing.assert_allclose(
            implied[key], np.asarray(ref_g2[key]),
            rtol=1e-3, atol=1e-6, err_msg=f"1f1b-v2 {key}")
