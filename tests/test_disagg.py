"""Disaggregated prefill/decode serving (ISSUE 14).

Correctness strategy, carried over from the PR 7 invariance suite:
the SAME fixed trace must decode the SAME byte-identical token
streams whether a request lives its whole life in one colocated
replica or is prefilled on one replica, its KV pages streamed over
the fabric, and decoded on another — across Synthetic and real
jitted paged executors, sync and pipelined decode loops, int8 and
fp32 resident pools, prefix-cache-hit prefills, and a transfer cut
mid-stream by an injected fault. Every test asserts ZERO leaked
blocks on BOTH pools at teardown, and the chaos cases assert
exactly-once settle through the monkeypatched finish() counter.
"""

import json
import time
import urllib.request
from collections import Counter

import numpy as np
import pytest

from dpu_operator_tpu import faults
from dpu_operator_tpu.faults import FaultyExecutor
from dpu_operator_tpu.obs import FlightRecorder
from dpu_operator_tpu.obs import trace as obs_trace
from dpu_operator_tpu.serving import (AdmissionQueue, ContinuousBatcher,
                                      DisaggPool, GenerateRequest,
                                      KVSpecMismatch, ServingServer,
                                      SyntheticKVExecutor)
from dpu_operator_tpu.serving.disagg import (KVPageStream,
                                             KVPageStreamServer,
                                             KVSpec, KVStreamNack)
from dpu_operator_tpu.serving.disagg.spec import CodecMismatch
from dpu_operator_tpu.utils.metrics import Registry

# The PR 7 invariance trace: the 26-token prompt fills the whole
# block table; the 25-token one chunk-prefills mid-run.
PROMPTS = [list(np.arange(25) % 13), [3, 1, 4, 1, 5], [9] * 12,
           list(np.arange(26) % 13)]
MAX_TOKENS = 6

POOL_OPTS = dict(watchdog_s=0.5, restart_backoff_s=0.01, poll_s=0.005)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    leaked = faults.active_plan()
    faults.uninstall()
    assert leaked is None, "test leaked an installed FaultPlan"


@pytest.fixture()
def settle_counts(monkeypatch):
    counts = Counter()
    orig = GenerateRequest.finish

    def counting(self):
        counts[self.request_id] += 1
        orig(self)

    monkeypatch.setattr(GenerateRequest, "finish", counting)
    return counts


def _req(prompt, max_tokens=MAX_TOKENS, deadline_s=60.0):
    return GenerateRequest(prompt_vec=None, max_tokens=max_tokens,
                           deadline=time.monotonic() + deadline_s,
                           prompt_tokens=list(prompt))


def _drive_colocated(ex, prompts, **req_kw):
    q = AdmissionQueue(max_depth=len(prompts) + 4)
    b = ContinuousBatcher(ex, q)
    reqs = [_req(p, **req_kw) for p in prompts]
    for r in reqs:
        q.submit(r)
    b.start()
    try:
        for r in reqs:
            assert r.wait(30), "request lost"
    finally:
        b.stop()
    for r in reqs:
        assert r.error is None, r.error
    ex.allocator.assert_clean()
    return [list(r.tokens) for r in reqs]


def _drive_disagg(pool, queue, prompts, timeout=30.0, **req_kw):
    reqs = [_req(p, **req_kw) for p in prompts]
    for r in reqs:
        queue.submit(r)
    for r in reqs:
        assert r.wait(timeout), "request lost"
    for r in reqs:
        assert r.error is None, r.error
    return [list(r.tokens) for r in reqs], reqs


def _synth(**kw):
    args = dict(slots=2, block_size=4, num_blocks=64,
                max_blocks_per_req=16, prefill_chunk=8, pipelined=True)
    args.update(kw)
    return SyntheticKVExecutor(**args)


# -- KVSpec: layout declared once, slice math derived -------------------------


def test_spec_derives_wire_bytes_and_segments():
    spec = KVSpec(model="paged", block_size=4, heads=2, d_head=8,
                  vocab=32, max_blocks_per_req=8, pool_dtype="int8")
    # 4*2*8 = 64 int8 code bytes + 4 scale bytes, twice (K and V).
    assert spec.wire_block_nbytes("int8") == 2 * (64 + 4)
    # Segments cover exactly, in order, each under the byte budget.
    segs = spec.segments(7, "int8", max_seg_bytes=3 * 136)
    assert segs == [(0, 3), (3, 3), (6, 1)]
    assert spec.segments(0, "int8") == []
    # The receiver's parse is the same function as the sender's frame.
    pay, sc = spec.plane_part_nbytes("int8", 3)
    assert (pay, sc) == (3 * 64, 12)
    assert spec.blocks_for_tokens(9) == 3


def test_spec_hello_rejects_codec_and_layout_mismatch():
    spec = KVSpec(model="paged", block_size=4, heads=2, d_head=8,
                  vocab=32, max_blocks_per_req=8, pool_dtype="fp32")
    with pytest.raises(CodecMismatch):
        spec.check_hello(spec.fingerprint(), "fp32", "int8")
    other = KVSpec(model="paged", block_size=8, heads=2, d_head=8,
                   vocab=32, max_blocks_per_req=8, pool_dtype="fp32")
    with pytest.raises(KVSpecMismatch, match="block_size"):
        spec.check_hello(other.fingerprint(), "fp32", "fp32")
    # A different SEED is a different model: its pages are not KV here.
    reseeded = KVSpec(model="paged", block_size=4, heads=2, d_head=8,
                      vocab=32, max_blocks_per_req=8,
                      pool_dtype="fp32", seed=7)
    with pytest.raises(KVSpecMismatch, match="seed"):
        spec.check_hello(reseeded.fingerprint(), "fp32", "fp32")


def test_spec_int8_pool_requires_int8_wire():
    spec = KVSpec(model="paged", block_size=4, heads=2, d_head=8,
                  vocab=32, max_blocks_per_req=8, pool_dtype="int8")
    assert spec.default_codec() == "int8"
    with pytest.raises(ValueError, match="int8"):
        spec.validate_codec("fp32")


# -- the page stream: framed transport + hello + segmentation ----------------


def test_stream_roundtrip_and_mismatch_rejection():
    """Pages round-trip the real socket path byte-exactly (fp32 wire),
    the segmentation really splits (tiny seg budget), and a client
    with a different layout or codec is refused at hello with the
    typed error — before any payload byte moves."""
    spec = KVSpec(model="paged", block_size=2, heads=2, d_head=4,
                  vocab=32, max_blocks_per_req=8, pool_dtype="fp32")
    got = {}

    def import_fn(meta, planes):
        got["meta"] = meta
        got["planes"] = planes
        return {"ok_extra": 1}

    srv = KVPageStreamServer(spec, import_fn, codec="fp32")
    try:
        rng = np.random.RandomState(0)
        k = rng.randn(5, 2, 2, 4).astype(np.float32)
        v = rng.randn(5, 2, 2, 4).astype(np.float32)
        ones = np.ones((5,), np.float32)
        st = KVPageStream(spec, srv.addr, codec="fp32", seg_bytes=80)
        assert len(spec.segments(5, "fp32", 80)) > 1
        ack = st.send_pages(
            {"req": "r1", "n_blocks": 5, "tokens": 10,
             "prompt_tokens": [1], "settled": [], "max_tokens": 1,
             "cached": 0}, [(k, ones), (v, ones)])
        assert ack["ok"] and ack["ok_extra"] == 1
        np.testing.assert_array_equal(got["planes"][0][0], k)
        np.testing.assert_array_equal(got["planes"][1][0], v)

        # Layout mismatch: refused at hello, typed.
        other = KVSpec(model="paged", block_size=4, heads=2, d_head=4,
                       vocab=32, max_blocks_per_req=8,
                       pool_dtype="fp32")
        bad = KVPageStream(other, srv.addr, codec="fp32")
        with pytest.raises(KVStreamNack, match="block_size"):
            bad.connect()
        mixed = KVPageStream(spec, srv.addr, codec="int8")
        with pytest.raises(KVStreamNack, match="codec"):
            mixed.connect()
        st.close()
    finally:
        srv.close()


def test_stream_import_failure_nacks_with_oom_flag():
    spec = KVSpec(model="paged", block_size=2, heads=1, d_head=2,
                  vocab=32, max_blocks_per_req=4, pool_dtype="fp32",
                  planes=1)

    def import_fn(meta, planes):
        raise RuntimeError("kv cache exhausted: need 4, 0 free")

    srv = KVPageStreamServer(spec, import_fn, codec="fp32")
    try:
        st = KVPageStream(spec, srv.addr, codec="fp32")
        blocks = np.zeros((1, 2, 1, 2), np.float32)
        with pytest.raises(KVStreamNack) as ei:
            st.send_pages({"req": "r", "n_blocks": 1, "tokens": 2,
                           "prompt_tokens": [1], "settled": [],
                           "max_tokens": 1, "cached": 0},
                          [(blocks, np.ones((1,), np.float32))])
        assert ei.value.oom
        st.close()
    finally:
        srv.close()


# -- lease detach/ack ---------------------------------------------------------


def test_lease_detach_reattach_contract():
    from dpu_operator_tpu.serving.kvcache import (KVBlockAllocator,
                                                  KVLease)

    a = KVBlockAllocator(num_blocks=4, block_size=2)
    lease = KVLease(a, "ex", "r1", a.acquire(2, "r1"), (1, 2), 0)
    assert lease.detach() is True
    assert lease.in_transit and lease.resumable
    with pytest.raises(ValueError, match="double detach"):
        lease.detach()
    lease.reattach()
    assert not lease.in_transit
    assert lease.detach() is True
    # release is the success-path ack: terminal, pages return.
    assert lease.release() is True
    a.assert_clean()
    # Detach-of-released is the BENIGN settle race (the handler's
    # finish() can release from its own thread at any time): False,
    # never a raise that would crash the retiring batcher.
    assert lease.detach() is False


def test_detach_slot_of_settled_request_is_none_not_crash():
    """Review finding: a handler-thread finish() landing between the
    retire loop's done-check and kv_detach_slot releases the lease
    first; the detach must report 'already settled' (None) — raising
    through the crash-only batcher would convert a benign settle race
    into a full replica restart."""
    ex = _synth(pipelined=False)
    r = _req(PROMPTS[1])
    ex.kv_attach(0, r)
    r.fail("handler abandoned")  # settle choke point releases lease
    assert ex.kv_detach_slot(0) is None
    ex.allocator.assert_clean()
    ex.close()


def test_late_import_after_sender_gave_up_releases_pages():
    """Review finding: an import completing AFTER the sender's ack
    deadline (sender popped _pending and moved on) must release its
    decode-side pages instead of registering them in _imported
    forever — orphaned worst-case reservations would silently drain
    the decode pool."""
    pre, dec = _synth(), _synth()
    q = AdmissionQueue(max_depth=4)
    pool = DisaggPool([pre], [dec], q, pool_opts=dict(POOL_OPTS))
    try:
        import_fn = pool._import_fn(0)
        meta = {"req": "ghost", "xfer": "dead-xfer", "n_blocks": 1,
                "tokens": 4, "cached": 0, "max_tokens": 1,
                "prompt_tokens": [1, 2, 3, 4], "settled": [5]}
        planes = [(np.asarray([[1.0], [2.0], [3.0], [4.0]],
                              np.float32).reshape(1, 4, 1, 1),
                   np.ones((1,), np.float32))]
        # No _pending entry for this xfer: the sender is gone.
        with pytest.raises(RuntimeError, match="abandoned"):
            import_fn(meta, planes)
        assert pool._imported == {}
        dec.allocator.assert_clean()
    finally:
        pool.stop()
    pre.close()
    dec.close()


def test_kv_attach_refuses_mid_transfer_lease():
    ex = _synth(pipelined=False)
    r = _req(PROMPTS[1])
    ex.kv_attach(0, r)
    detach = ex.kv_detach_slot(0)
    with pytest.raises(ValueError, match="mid-transfer"):
        ex.kv_attach(1, r)
    detach["lease"].reattach()
    assert ex.kv_attach(1, r) == 0  # resumes through _reattach
    ex.kv_release_slot(1, cache=False)
    r.finish()
    ex.allocator.assert_clean()
    ex.close()


# -- equivalence: disagg streams == colocated streams -------------------------


@pytest.mark.parametrize("decode_pipelined", [True, False])
def test_disagg_streams_match_colocated_synthetic(decode_pipelined):
    """The acceptance invariance on the jax-free plane, both decode
    loop shapes: prefill-replica + page transfer + decode-replica
    produces the colocated executor's exact streams — and the roles
    really split (the prefill executor decodes exactly the one
    hand-off token per request, the decode executor everything
    else)."""
    colo = _synth()
    baseline = _drive_colocated(colo, PROMPTS)
    colo.close()

    pre, dec = _synth(), _synth()
    q = AdmissionQueue(max_depth=16)
    pool = DisaggPool(
        [pre], [dec], q, pool_opts=dict(POOL_OPTS),
        decode_pool_opts=dict(
            POOL_OPTS,
            batcher_kwargs={"pipelined": decode_pipelined}))
    pool.start()
    try:
        streams, _ = _drive_disagg(pool, q, PROMPTS)
    finally:
        pool.stop()
    assert streams == baseline
    assert any(len(set(s)) > 1 for s in baseline), \
        "degenerate streams would make this equality vacuous"
    # Role split: prefill emitted ONE token per request (the
    # prefill-finish emit), decode everything else, via _reattach.
    assert pre.decode_tokens == len(PROMPTS)
    assert dec.decode_tokens == len(PROMPTS) * (MAX_TOKENS - 1)
    assert dec.resumed_total == len(PROMPTS)
    pre.allocator.assert_clean()
    dec.allocator.assert_clean()
    pre.close()
    dec.close()


@pytest.mark.parametrize("pool_dtype", ["int8", "fp32"])
def test_disagg_streams_match_colocated_paged(pool_dtype):
    """The real jitted path: int8-resident pools ship their codes +
    scales VERBATIM (the acceptance's int8-pool transfer case), fp32
    pools ship lossless rows — both byte-identical to colocated
    decode, including a second wave whose prefill hits the PREFILL
    replica's prefix cache (cached_tokens rides the transfer, so the
    client-visible proof survives the migration)."""
    from dpu_operator_tpu.serving import PagedKVExecutor

    args = dict(slots=2, block_size=4, num_blocks=64,
                max_blocks_per_req=8, prefill_chunk=8, seed=0,
                vocab=32, d=16, heads=2, mode="pipelined",
                pool_dtype=pool_dtype)
    colo = PagedKVExecutor(**args)
    # Two waves of the same trace: the second wave's prefill is a
    # prefix-cache hit (colocated inserts at retire; so does the
    # prefill replica's post-ack release).
    baseline = _drive_colocated(colo, PROMPTS)
    baseline2 = _drive_colocated(colo, PROMPTS)
    assert baseline2 == baseline  # PR 7 invariance, still true

    pre = PagedKVExecutor(**args)
    dec = PagedKVExecutor(**args)
    q = AdmissionQueue(max_depth=16)
    pool = DisaggPool([pre], [dec], q, pool_opts=dict(POOL_OPTS))
    assert pool.codec == ("int8" if pool_dtype == "int8" else "fp32")
    pool.start()
    try:
        streams, _ = _drive_disagg(pool, q, PROMPTS)
        streams2, reqs2 = _drive_disagg(pool, q, PROMPTS)
    finally:
        pool.stop()
    assert streams == baseline
    assert streams2 == baseline
    # The prefix-cache-hit prefill: wave 2 saw cached tokens, and the
    # count survived the lease migration into the response surface.
    cached = [r.kv_lease.cached_tokens for r in reqs2]
    assert any(c > 0 for c in cached), cached
    assert dec.resumed_total == 2 * len(PROMPTS)
    pre.allocator.assert_clean()
    dec.allocator.assert_clean()

    # Third wave through a SYNC decode batcher over the same
    # executors (fresh pool, sessions reset at start): the ISSUE 3
    # sync<->pipelined equivalence, carried to the disagg path on the
    # real jitted model.
    q2 = AdmissionQueue(max_depth=16)
    pool_sync = DisaggPool(
        [pre], [dec], q2, pool_opts=dict(POOL_OPTS),
        decode_pool_opts=dict(
            POOL_OPTS, batcher_kwargs={"pipelined": False}))
    pool_sync.start()
    try:
        streams3, _ = _drive_disagg(pool_sync, q2, PROMPTS)
    finally:
        pool_sync.stop()
    assert streams3 == baseline
    pre.allocator.assert_clean()
    dec.allocator.assert_clean()


# -- chaos: kill the transfer mid-stream --------------------------------------


def test_kill_transfer_mid_stream_recovers_exactly_once(
        settle_counts, tmp_path):
    """The ISSUE 14 chaos headline: the page stream is CUT between
    segments (twice, on different requests) — the decode side's
    partial accumulation dies with the connection (zero allocated
    blocks), the prefill-side lease reattaches, the request requeues
    to the prefill front, re-attaches its surviving pages, re-decodes
    exactly one token and hands off again. Must hold: byte-identical
    streams vs the uninjected run, exactly-once settle, both leak
    ledgers clean, and ONE flight-recorder file showing the
    injection -> detection -> migration timeline across both
    replicas."""
    t0 = time.perf_counter()

    def run(inject, flight_dir=None):
        pre, dec = _synth(), _synth()
        reg = Registry()
        q = AdmissionQueue(max_depth=16)
        rec = (FlightRecorder(flight_dir=str(flight_dir))
               if flight_dir is not None else None)
        # seg_bytes=16 -> every transfer is multi-segment, so the
        # at_calls=[2] fault lands genuinely MID-transfer.
        pool = DisaggPool([pre], [dec], q, registry=reg, seg_bytes=16,
                          flight_recorder=rec,
                          pool_opts=dict(POOL_OPTS))
        pool.start()
        try:
            streams, reqs = _drive_disagg(pool, q, PROMPTS)
        finally:
            pool.stop()
        pre.allocator.assert_clean()
        dec.allocator.assert_clean()
        pre.close()
        dec.close()
        return streams, reqs, reg, dec

    baseline, _, _, _ = run(inject=False)
    with obs_trace.scoped() as tr:
        with faults.injected() as plan:
            plan.inject("kvstream.send",
                        exc=RuntimeError("cut mid-transfer"),
                        at_calls=[2, 6])
            injected, reqs, reg, dec = run(inject=True,
                                           flight_dir=tmp_path)
        spans = tr.spans_snapshot()
    assert injected == baseline, (injected, baseline)
    assert set(settle_counts.values()) == {1}, settle_counts
    # The decode side attached each request exactly once — after the
    # failed transfer the request went BACK to prefill, never to a
    # half-imported decode state.
    assert dec.resumed_total == len(PROMPTS)
    assert reg.counter_value("serving_kv_transfers_total",
                             {"outcome": "requeued_prefill"}) >= 1
    assert reg.counter_value("serving_kv_transfers_total",
                             {"outcome": "ok"}) == len(PROMPTS)

    # The migration is visible in the TRACE, not just the counters:
    # for some victim, handoff -> failed transfer -> queue.requeue ->
    # second handoff -> import on the decode replica, in order.
    failed = [s for s in spans if s.name == "disagg.transfer"
              and s.attrs.get("error")]
    assert failed, "no failed transfer span recorded"
    victim = failed[0].request_id
    vspans = [s for s in spans if s.request_id == victim]
    names = [s.name for s in vspans]
    assert names.count("disagg.handoff") >= 2, names
    assert "queue.requeue" in names
    ok_import = [s for s in vspans if s.name == "disagg.import"]
    assert len(ok_import) == 1, "decode side must import exactly once"
    assert ok_import[-1].t0 >= failed[0].t1, \
        "import must follow the failed transfer"

    # One flight file, written at the failure, carrying the whole
    # chain: the injected fault, the erroring transfer leg, and the
    # requeue-to-prefill migration decision — across both replicas'
    # span streams (prefill's handoff event + the transfer plane).
    files = sorted(tmp_path.glob("flight-kv_transfer_failed-*.json"))
    assert files, sorted(p.name for p in tmp_path.iterdir())
    doc = json.loads(files[0].read_text())
    fspans = doc["spans"]
    fault = next(s for s in fspans if s["name"] == "fault.fired"
                 and s["attrs"].get("site") == "kvstream.send")
    xfer = next(s for s in fspans if s["name"] == "disagg.transfer"
                and s["attrs"].get("error"))
    hand = next(s for s in fspans if s["name"] == "disagg.handoff"
                and s["request_id"] == xfer["request_id"])
    rq = next(s for s in fspans if s["name"] == "queue.requeue"
              and s["request_id"] == xfer["request_id"])
    assert (hand["t0"] <= xfer["t0"] <= fault["t0"] <= rq["t0"]), \
        "injection -> detection -> migration out of order"
    assert doc["extra"]["outcome"] == "requeued_prefill"
    assert time.perf_counter() - t0 < 24.0


def test_connect_and_import_faults_requeue_to_prefill(settle_counts):
    """The transfer plane's other two seams, same contract as the
    mid-stream cut: a failed dial (kvstream.connect — the decode
    listener unreachable on the first hand-off) and a server-side
    import blowup (kvstream.import — the decode pool rejecting pages
    before attach) both degrade to requeue-to-prefill with
    byte-identical streams and clean ledgers on both sides."""

    def run(site=None):
        pre, dec = _synth(), _synth()
        reg = Registry()
        q = AdmissionQueue(max_depth=16)
        pool = DisaggPool([pre], [dec], q, registry=reg, seg_bytes=16,
                          pool_opts=dict(POOL_OPTS))
        pool.start()
        try:
            if site is None:
                streams, _ = _drive_disagg(pool, q, PROMPTS)
            else:
                with faults.injected() as plan:
                    plan.inject(site,
                                exc=RuntimeError(f"{site} down"),
                                at_calls=[1])
                    streams, _ = _drive_disagg(pool, q, PROMPTS)
        finally:
            pool.stop()
        pre.allocator.assert_clean()
        dec.allocator.assert_clean()
        pre.close()
        dec.close()
        return streams, reg

    baseline, _ = run()
    for site in ("kvstream.connect", "kvstream.import"):
        streams, reg = run(site)
        assert streams == baseline, site
        assert reg.counter_value(
            "serving_kv_transfers_total",
            {"outcome": "requeued_prefill"}) >= 1, site
    assert set(settle_counts.values()) == {1}, settle_counts


def test_kill_prefill_replica_mid_run_recovers(settle_counts):
    """The replica-level kill composed with disagg: the PREFILL
    batcher dies mid-run (executor fault), its supervisor seizes and
    requeues the occupants to the shared front queue, the restarted
    prefill replica re-attaches (or re-prefills) them, and hand-offs
    resume — streams byte-identical, settle exactly once, ledgers
    clean on both pools."""
    def run(inject):
        inner = _synth(fault_site="pf0" if inject else None)
        ex = FaultyExecutor(inner, site="pf0") if inject else inner
        dec = _synth()
        q = AdmissionQueue(max_depth=16)
        pool = DisaggPool([ex], [dec], q, pool_opts=dict(POOL_OPTS))
        pool.start()
        try:
            streams, _ = _drive_disagg(pool, q, PROMPTS)
        finally:
            pool.stop()
        inner.allocator.assert_clean()
        dec.allocator.assert_clean()
        inner.close()
        dec.close()
        return streams, pool

    baseline, _ = run(inject=False)
    with faults.injected() as plan:
        plan.inject("pf0.submit", exc=RuntimeError("injected kill"),
                    at_calls=[3])
        injected, pool = run(inject=True)
    assert injected == baseline
    assert set(settle_counts.values()) == {1}, settle_counts
    assert sum(pool.prefill_pool.restarts) >= 1


def test_decode_oom_nack_requeues_to_prefill(settle_counts):
    """A decode pool too small for the request's worst case nacks the
    import (oom) — the transfer fails typed, the request burns an
    attempt and retries via prefill until the budget exhausts: a 500
    retries_exhausted, never a hang, never a leak."""
    pre = _synth()
    dec = _synth(num_blocks=2)  # cannot hold any request's worst case
    q = AdmissionQueue(max_depth=8)
    reg = Registry()
    pool = DisaggPool([pre], [dec], q, registry=reg, max_attempts=2,
                      pool_opts=dict(POOL_OPTS))
    r = _req(PROMPTS[1])
    pool.start()
    try:
        q.submit(r)
        assert r.wait(20), "request lost"
    finally:
        pool.stop()
    assert r.error == "retries_exhausted"
    assert settle_counts[r.request_id] == 1
    assert reg.counter_value("serving_kv_transfers_total",
                             {"outcome": "retries_exhausted"}) == 1
    pre.allocator.assert_clean()
    dec.allocator.assert_clean()
    pre.close()
    dec.close()


# -- HTTP integration + metrics exposition ------------------------------------


def _post(url, body):
    data = json.dumps(body).encode()
    try:
        r = urllib.request.urlopen(
            urllib.request.Request(url + "/v1/generate", data=data),
            timeout=20)
        return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_disagg_server_http_roundtrip_and_metrics(tmp_path):
    """The whole front door over a DisaggPool: generate round-trips
    (with the transferred lease's cached_tokens in the response),
    /metrics exposes the transfer series and the role-labelled pool
    gauge, and a drain completes in-flight work through the transfer
    plane."""
    pre, dec = _synth(), _synth()
    reg = Registry()

    def factory(execs, queue, registry, tracer, flight_recorder):
        return DisaggPool([pre], [dec], queue, registry=registry,
                          tracer=tracer,
                          flight_recorder=flight_recorder,
                          pool_opts=dict(POOL_OPTS))

    srv = ServingServer([pre, dec], registry=reg,
                        pool_factory=factory).start()
    try:
        toks = [int(t) for t in PROMPTS[0]]
        code, body = _post(srv.url, {"prompt_tokens": toks,
                                     "max_tokens": 4,
                                     "deadline_ms": 20000})
        assert code == 200 and len(body["tokens"]) == 4
        # Same prompt again: the prefill replica's prefix cache hits,
        # and the cached count survives the migration to the decode
        # lease the response reads.
        code2, body2 = _post(srv.url, {"prompt_tokens": toks,
                                       "max_tokens": 4,
                                       "deadline_ms": 20000})
        assert code2 == 200 and body2["tokens"] == body["tokens"]
        assert body2["kv"]["cached_tokens"] > 0

        text = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=10).read().decode()
        assert 'serving_kv_transfer_bytes_total{codec="fp32"}' in text
        assert "serving_kv_transfer_seconds_bucket" in text
        assert ('serving_pool_replicas{role="prefill",'
                'sharded="false",state="live"} 1' in text)
        assert ('serving_pool_replicas{role="decode",'
                'sharded="false",state="live"} 1' in text)
        # Transfers really moved the derived bytes: n_blocks * wire.
        assert reg.counter_value("serving_kv_transfer_bytes_total",
                                 {"codec": "fp32"}) > 0
        assert srv.begin_drain(timeout=10.0)
    finally:
        srv.stop()
    pre.allocator.assert_clean()
    dec.allocator.assert_clean()
    pre.close()
    dec.close()


def test_disagg_pool_rejects_mismatched_executors():
    pre = _synth()
    dec = _synth(block_size=8, num_blocks=32)
    with pytest.raises(KVSpecMismatch):
        DisaggPool([pre], [dec], AdmissionQueue(max_depth=4))
    pre.close()
    dec.close()
