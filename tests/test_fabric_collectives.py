"""The custom collective transport (parallel/fabric_collectives.py):
ring wiring, segmented-allreduce correctness across world sizes and
ragged payloads, the raw-exchange ceiling mode, accounting, and the
failure modes callers fall back to gloo on. Loopback sockets with one
thread per rank — no netns, no root: the transport is plain TCP, so
everything but the veth underneath is the production code path."""

import json
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

from dpu_operator_tpu.parallel.fabric_collectives import (
    CodecMismatch, FabricConnectError, RingError, RingTransport,
    _segment_bounds, bench_ring, quantized_error_bound)

PORTS = iter(range(29500, 29900, 10))


def _ring(world, fn, streams=1, chunk_bytes=64 << 10, timeout=20.0,
          codec=None, error_feedback=False):
    """Run fn(transport, rank) on every rank concurrently; returns the
    per-rank results, re-raising the first rank failure. ``codec`` may
    be per-rank (a list) for the mismatch contract.

    Pre-agreed ring ports come from a fixed pool that this kernel's
    ephemeral range (16000-65535) overlaps, so any server or client
    socket elsewhere in the suite can transiently squat one — a bind
    failure rolls the WHOLE ring forward to the next port base
    (bounded retries; every other failure propagates untouched)."""
    import errno

    for _attempt in range(3):
        base = next(PORTS)
        peers = [f"127.0.0.1:{base + r}" for r in range(world)]
        results, errors = [None] * world, []

        def rank(r, peers=peers, results=results, errors=errors):
            t = RingTransport(r, world, "127.0.0.1", peers,
                              streams=streams,
                              chunk_bytes=chunk_bytes,
                              codec=(codec[r]
                                     if isinstance(codec, list)
                                     else codec),
                              error_feedback=error_feedback)
            try:
                t.connect(timeout=timeout)
                results[r] = fn(t, r)
            except BaseException as e:
                errors.append(e)
            finally:
                t.close()

        threads = [threading.Thread(target=rank, args=(r,),
                                    daemon=True)
                   for r in range(world)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        if errors and isinstance(errors[0], OSError) \
                and errors[0].errno == errno.EADDRINUSE:
            continue
        if errors:
            raise errors[0]
        return results
    raise errors[0]


@pytest.mark.parametrize("world,elems,streams", [
    (2, 1 << 16, 1),   # the pair fast path the capstone runs
    (2, 1 << 16, 2),   # multi-stream pair
    (3, (1 << 16) + 7, 1),   # general ring, ragged payload
    (4, 33333, 2),     # general ring, multi-stream, uneven segments
    (5, 97, 1),        # payload smaller than a chunk, odd world
])
def test_allreduce_sums_across_ranks(world, elems, streams):
    def fn(t, r):
        local = np.arange(elems, dtype=np.float32) * (r + 1)
        return t.allreduce(local)

    want = np.arange(elems, dtype=np.float32) * sum(range(1, world + 1))
    for out in _ring(world, fn, streams=streams):
        assert np.array_equal(out, want)


def test_allreduce_world_one_is_identity_and_input_untouched():
    t = RingTransport(0, 1, "127.0.0.1", ["127.0.0.1"])
    local = np.arange(100, dtype=np.float32)
    out = t.allreduce(local)
    assert np.array_equal(out, local) and out is not local
    # Multi-rank path must also leave the caller's array alone.
    def fn(tr, r):
        src = np.full(1000, float(r + 1), np.float32)
        tr.allreduce(src)
        return src

    for r, src in enumerate(_ring(2, fn)):
        assert np.all(src == r + 1), "allreduce clobbered its input"


def test_allreduce_reuses_caller_buffers():
    """The loop-calling contract bench_ring relies on: out/scratch are
    reused, the result lands in `out`."""
    def fn(t, r):
        local = np.full(5000, float(r + 1), np.float32)
        out = np.empty_like(local)
        scratch = np.empty_like(local)
        got = t.allreduce(local, out, scratch)
        return got is out, np.all(out == 3.0)

    for was_out, correct in _ring(2, fn):
        assert was_out and correct


def test_exchange_moves_wire_bytes_without_reduce():
    """Raw-ceiling mode: same schedule, no arithmetic — must complete
    (liveness) for every world size the allreduce supports."""
    for world in (2, 3):
        _ring(world, lambda t, r: t.exchange(
            np.ones(10000, np.float32)))


def test_bench_ring_reports_and_verifies():
    res = _ring(2, lambda t, r: bench_ring(t, 1 << 18, 3,
                                           mode="allreduce"))
    for r in res:
        assert r["ok"] and r["gbps"] > 0 and r["mode"] == "allreduce"
    raw = _ring(2, lambda t, r: bench_ring(t, 1 << 18, 3,
                                           mode="exchange"))
    for r in raw:
        assert r["ok"] and r["gbps"] > 0 and r["mode"] == "exchange"


def test_wire_accounting_is_ring_cost():
    """2(n-1)/n · D per rank — the same denominator the gloo path
    reports, so the two figures compare 1:1 in the artifact."""
    t2 = RingTransport(0, 2, "127.0.0.1", ["a", "b"])
    assert t2.wire_bytes(16 << 20) == 16 << 20
    t4 = RingTransport(0, 4, "127.0.0.1", ["a", "b", "c", "d"])
    assert t4.wire_bytes(16 << 20) == (16 << 20) * 3 // 2


def test_segment_bounds_cover_exactly():
    for n, world in ((10, 3), (7, 7), (5, 8), (0, 2), (1 << 20, 6)):
        bounds = _segment_bounds(n, world)
        assert len(bounds) == world
        assert bounds[0][0] == 0 and bounds[-1][1] == n
        for (a, b), (c, d) in zip(bounds, bounds[1:]):
            assert b == c and b - a >= 0


def test_bad_ring_shapes_raise():
    with pytest.raises(RingError):
        RingTransport(2, 2, "127.0.0.1", ["a", "b"])  # rank out of range
    with pytest.raises(RingError):
        RingTransport(0, 3, "127.0.0.1", ["a", "b"])  # peer count mismatch


def test_absent_peer_fails_fast_not_forever():
    t = RingTransport(0, 2, "127.0.0.1",
                      ["127.0.0.1:29990", "127.0.0.1:29991"])
    with pytest.raises(RingError, match="never came up"):
        t.connect(timeout=0.5)
    t.close()


def test_injected_dial_fault_retries_within_deadline():
    """Chaos at the fabric.connect seam: the first dial of the ring
    dies with a transient OSError (injected — a peer's listener not
    yet up, RST mid-bringup). The dial loop's backoff-retry must
    absorb it inside the connect deadline, and the ring formed on the
    retry must allreduce correctly — a refused first SYN is bringup
    noise, never a wiring failure."""
    import errno

    from dpu_operator_tpu import faults

    def fn(t, r):
        local = np.arange(512, dtype=np.float32) * (r + 1)
        return t.allreduce(local)

    with faults.injected() as plan:
        plan.inject("fabric.connect",
                    exc=OSError(errno.ECONNREFUSED,
                                "injected: connection refused"),
                    at_calls=[1])
        results = _ring(2, fn)
        assert plan.fired.get("fabric.connect") == 1
    want = np.arange(512, dtype=np.float32) * 3
    for out in results:
        assert np.array_equal(out, want)


def test_dead_peer_typed_error_with_backoff_not_busy_spin():
    """Regression (ISSUE 5 satellite): the dial loop used to retry a
    refused connect on a fixed 50 ms beat — ~20 socket churns in a 1 s
    deadline, and an untyped RingError at expiry. Now: exponential
    backoff + jitter inside the deadline (attempt count stays small),
    and a typed FabricConnectError carrying the peer address and the
    attempt count."""
    import time as _time

    t = RingTransport(0, 2, "127.0.0.1",
                      ["127.0.0.1:29992", "127.0.0.1:29993"])
    t0 = _time.monotonic()
    with pytest.raises(FabricConnectError) as ei:
        t.connect(timeout=1.0)
    elapsed = _time.monotonic() - t0
    t.close()
    e = ei.value
    assert e.peer == ("127.0.0.1", 29993)
    # Bounded time: the deadline, not the kernel's syn-retry cycle.
    assert elapsed < 5.0, elapsed
    # Backoff means FEW attempts, not a deadline-long churn: doubling
    # from 50 ms covers a 1 s budget in well under 10 dials (the old
    # fixed beat needed ~20; a tight loop, thousands).
    assert 1 <= e.attempts <= 10, e.attempts
    # The typed error still IS a RingError: the gloo-fallback callers
    # keep working unchanged.
    assert isinstance(e, RingError)


# -- quantized collectives (ISSUE 9) ------------------------------------------


@pytest.mark.parametrize("world,elems,codec", [
    (2, 40000, "int8"),       # pair fast path, quarter wire bytes
    (2, 40000, "bf16"),       # pair fast path, half wire bytes
    (3, 40007, "int8"),       # general ring, ragged payload
    (3, 2, "int8"),           # world > n_elems: zero-length segments
    (2, (64 << 10) + 17, "int8"),  # odd count vs int8 wire chunking
])
def test_quantized_allreduce_within_bound_and_bit_identical(
        world, elems, codec):
    """The quantized ring reduces in fp32 after decode: the result
    stays inside `quantized_error_bound` of the exact sum, and every
    rank lands on BIT-IDENTICAL floats (the sharded-serving
    replicated-state contract — the final segment encodes once and
    every rank decodes the same wire bytes)."""
    base = (np.arange(elems, dtype=np.float64) * 0.6180339887
            % 2.0 - 1.0).astype(np.float32)

    def fn(t, r):
        return t.allreduce(base * (r + 1))

    results = _ring(world, fn, codec=codec)
    want = base * sum(range(1, world + 1))
    bound = quantized_error_bound(world, float(world), codec)
    for out in results:
        assert float(np.max(np.abs(out - want))) <= bound
    for out in results[1:]:
        assert np.array_equal(results[0], out), \
            "ranks diverged: replicated decode states would fork"


def test_quantized_allreduce_input_untouched_and_error_feedback():
    """The caller's array survives a quantized allreduce, and the
    error-feedback knob keeps the repeated-payload mean error below
    the plain codec's fixed rounding (the per-step serving shape)."""
    def fn(t, r):
        src = np.full(5000, 0.7003 * (r + 1), np.float32)
        outs = [t.allreduce(src) for _ in range(16)]
        assert np.all(src == np.float32(0.7003 * (r + 1))), \
            "allreduce clobbered its input"
        return float(np.mean([o[0] for o in outs]))

    want = 0.7003 * 3
    plain = _ring(2, fn, codec="int8")[0]
    ef = _ring(2, fn, codec="int8", error_feedback=True)[0]
    assert abs(ef - want) < abs(plain - want) or \
        abs(ef - want) < 1e-4, (ef, plain)


def test_mixed_codec_ring_fails_typed_at_connect():
    """A ring whose members disagree on the wire codec must refuse at
    the hello handshake with the typed CodecMismatch — decoding int8
    payload bytes as fp32 is silent corruption, the one failure mode
    worse than an outage."""
    with pytest.raises(CodecMismatch):
        _ring(2, lambda t, r: t.allreduce(np.ones(64, np.float32)),
              codec=["int8", "fp32"])


def test_bench_ring_quantized_reports_effective_gbps_and_error():
    """bench_ring on a quantized transport: effective fp32-equivalent
    Gb/s (same wire denominator as the raw ring — the numbers compare
    1:1), measured max-abs error, and the documented bound it was
    verified against."""
    res = _ring(2, lambda t, r: bench_ring(t, 1 << 18, 2,
                                           mode="allreduce"),
                codec="int8")
    for r in res:
        assert r["ok"] and r["codec"] == "int8" and r["gbps"] > 0
        assert 0.0 <= r["max_abs_err"] <= r["err_bound"]


# -- close() hardening (ISSUE 9 satellite) ------------------------------------


def test_close_after_half_connect_releases_listener_port():
    """Regression: a transport whose dial SUCCEEDED but whose accept
    never completed (the peer listens but never dials back) must fail
    typed inside the deadline, release every socket — the listener
    port is immediately rebindable, not squatted for the process
    lifetime — and tolerate a second close()."""
    base = next(PORTS)
    my_port, peer_port = base, base + 1
    peer = socket.socket()
    peer.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    peer.bind(("127.0.0.1", peer_port))
    peer.listen(2)  # accepts rank 0's dial, never dials back
    t = RingTransport(0, 2, "127.0.0.1",
                      [f"127.0.0.1:{my_port}",
                       f"127.0.0.1:{peer_port}"])
    try:
        with pytest.raises(RingError, match="never dialled in"):
            t.connect(timeout=1.0)
        t.close()
        t.close()  # idempotent: detach-then-close
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", my_port))  # leaked listener -> EADDRINUSE
        finally:
            s.close()
    finally:
        peer.close()


def test_close_tracks_socket_that_died_mid_hello():
    """The dial-side socket joins _send BEFORE the hello write: a peer
    that accepts then drops mid-hello must not leak the dialled
    socket through close()."""
    base = next(PORTS)
    my_port, peer_port = base, base + 1
    accepted = []
    peer = socket.socket()
    peer.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    peer.bind(("127.0.0.1", peer_port))
    peer.listen(2)

    def accept_and_hold():
        try:
            c, _ = peer.accept()
            accepted.append(c)
        except OSError:
            pass

    th = threading.Thread(target=accept_and_hold, daemon=True)
    th.start()
    t = RingTransport(0, 2, "127.0.0.1",
                      [f"127.0.0.1:{my_port}",
                       f"127.0.0.1:{peer_port}"])
    try:
        with pytest.raises(RingError):
            t.connect(timeout=1.0)
        # The failed connect's own cleanup already ran: nothing left.
        assert t._send == [] and t._recv == [] and t._listener is None
    finally:
        t.close()
        peer.close()
        for c in accepted:
            c.close()
        th.join(timeout=5)


def test_cli_raw_mode_prints_json_result():
    """The bench.py contract: one rank per process, --mode raw, one
    JSON line on stdout with the measured gbps."""
    base = next(PORTS)
    peers = f"127.0.0.1:{base},127.0.0.1:{base + 1}"
    procs = [subprocess.Popen(
        [sys.executable, "-m", "dpu_operator_tpu.parallel.fabric_collectives",
         "--rank", str(r), "--world", "2", "--bind-ip", "127.0.0.1",
         "--peer-ips", peers, "--mode", "raw",
         "--payload-mb", "0.25", "--iters", "2"],
        stdout=subprocess.PIPE, text=True) for r in range(2)]
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=60)
        assert p.returncode == 0, out
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["ok"] and doc["mode"] == "exchange" and doc["gbps"] > 0
        assert doc["rank"] == r
