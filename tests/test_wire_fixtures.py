"""Golden-fixture kube-apiserver wire replay (VERDICT r3 Next #6).

The suite's two test_kind.py skips name the boundary honestly: real
kube-apiserver semantics are validated against this repo's OWN model
(`k8s/http_server.py`). These tests shrink that trust gap from the
other side: canned apiserver RESPONSE BODIES — the exact envelope the
real server speaks — are replayed through a dumb fixture HTTP server
into the production `HttpClient`, asserting the client and the
controllers behave the same as on the modeled tier.

Fixture provenance: this container has no cluster to capture from
(zero egress), so the fixtures in tests/fixtures/k8s_wire/ are AUTHORED
byte-shape-faithful to the upstream apimachinery wire contract — the
`Status` failure envelope (kind/status/message/reason/details/code),
newline-delimited watch framing with BOOKMARK metadata-skeleton and
ERROR(410 Expired) frames, and a full server-shaped Pod carrying
managedFields / ownerReferences / creationTimestamp / qosClass — i.e.
fields and frames this repo's model server NEVER emits, which is
exactly what makes the replay worth running. Anyone with a real
cluster can re-capture them with `kubectl get --raw` / a watch curl and
drop them in; the tests only read the files.
"""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dpu_operator_tpu.k8s.http_client import HttpClient
from dpu_operator_tpu.k8s.store import AlreadyExists, Conflict, NotFound

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "k8s_wire")


def _load(name):
    with open(os.path.join(FIXTURES, name)) as f:
        return json.load(f)


class FixtureApiServer:
    """Replays canned (method, path-suffix) → (code, body) exchanges,
    plus one newline-framed watch stream, exactly as a real apiserver
    would put them on the wire. Records every request for assertions."""

    def __init__(self):
        self.routes = {}  # (method, path contains) -> (code, dict body)
        self.watch = None  # (list_response, [frames])
        self.requests = []
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code, body_bytes, chunked=False):
                # A client that got what it wanted from a watch stream
                # closes mid-frame; the resulting EPIPE is the normal
                # end of a fixture exchange, not a failure — swallowing
                # it here keeps teardown output clean (a raised
                # BrokenPipeError would splat a traceback from the
                # server thread over the test summary).
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    if chunked:
                        self.send_header("Transfer-Encoding", "chunked")
                    else:
                        self.send_header(
                            "Content-Length", str(len(body_bytes)))
                    self.end_headers()
                    if chunked:
                        for line in body_bytes:
                            self.wfile.write(
                                b"%x\r\n%s\r\n" % (len(line), line))
                            self.wfile.flush()
                            time.sleep(0.01)
                        self.wfile.write(b"0\r\n\r\n")
                    else:
                        self.wfile.write(body_bytes)
                except BrokenPipeError:
                    pass

            def _handle(self, method):
                srv.requests.append((method, self.path))
                if "watch=1" in self.path and srv.watch is not None:
                    frames = [
                        (json.dumps(fr) + "\n").encode()
                        for fr in srv.watch[1]
                    ]
                    return self._reply(200, frames, chunked=True)
                for (m, frag), (code, body) in srv.routes.items():
                    if m == method and frag in self.path:
                        return self._reply(code, json.dumps(body).encode())
                if method == "GET" and srv.watch is not None:
                    return self._reply(
                        200, json.dumps(srv.watch[0]).encode())
                self._reply(404, json.dumps(
                    {"kind": "Status", "status": "Failure",
                     "reason": "NotFound", "code": 404}).encode())

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                self._handle("POST")

            def do_PUT(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                self._handle("PUT")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


@pytest.fixture
def fixture_server():
    s = FixtureApiServer()
    yield s
    s.stop()


def test_conflict_status_body_classifies_as_conflict(fixture_server):
    """A real 409 Conflict Status body (reason: Conflict, the
    'object has been modified' message) must raise Conflict — the
    retry-with-fresh-read signal — NOT AlreadyExists."""
    fixture_server.routes[("PUT", "/dataprocessingunits/")] = (
        409, _load("status_conflict_put.json"))
    client = HttpClient(fixture_server.url)
    with pytest.raises(Conflict):
        client.update({
            "apiVersion": "config.tpu.io/v1",
            "kind": "DataProcessingUnit",
            "metadata": {"name": "tpu-v5litepod-8-w0-dpu",
                         "namespace": "dpu-operator-system"},
        })


def test_already_exists_status_body_classifies(fixture_server):
    """The OTHER 409: reason AlreadyExists on POST → AlreadyExists (the
    create-race signal the controllers treat as success-if-converged)."""
    fixture_server.routes[("POST", "/pods")] = (
        409, _load("status_already_exists_post.json"))
    client = HttpClient(fixture_server.url)
    with pytest.raises(AlreadyExists):
        client.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "nf-fw", "namespace": "x"},
        })


def test_not_found_status_body(fixture_server):
    fixture_server.routes[("GET", "/pods/vanished")] = (
        404, _load("status_not_found_get.json"))
    client = HttpClient(fixture_server.url)
    with pytest.raises(NotFound):
        client.get("v1", "Pod", "x", "vanished")


def test_full_server_shaped_pod_flows_through_daemon_logic(fixture_server):
    """A Pod exactly as a real apiserver returns it — managedFields,
    ownerReferences, creationTimestamp, qosClass, the whole envelope —
    must flow through the client AND the daemon's NF chain-spec reader
    without choking on fields the modeled tier never emits."""
    pod = _load("pod_full_server_shape.json")
    fixture_server.routes[("GET", "/pods/nf-fw")] = (200, pod)
    client = HttpClient(fixture_server.url)
    got = client.get("v1", "Pod", "dpu-operator-system", "nf-fw")
    assert got["metadata"]["managedFields"][1]["subresource"] == "status"

    # The dpu-side daemon's annotation reader consumes it as-is.
    from dpu_operator_tpu.cni.types import CniRequest
    from dpu_operator_tpu.daemon.dpu_side import DpuSideManager

    mgr = object.__new__(DpuSideManager)  # only _client/_nf_chain_spec used
    mgr._client = client
    req = CniRequest(
        command="ADD", container_id="c1", netns="/proc/self/ns/net",
        ifname="net1",
        args={"K8S_POD_NAME": "nf-fw",
              "K8S_POD_NAMESPACE": "dpu-operator-system"})
    policies, transparent = mgr._nf_chain_spec(req)
    assert policies == [{"pref": 10, "action": "police:200", "proto": "tcp"}]
    assert transparent is False


def test_watch_resumes_from_bookmark_rv(fixture_server):
    """Bookmarks exist so clients can RESUME: after a clean stream end,
    the next watch request must carry the bookmark's resourceVersion —
    and no second LIST should happen (no duplicate-ADDED storm through
    the controllers on every idle-timeout reconnect)."""
    wf = _load("watch_stream_dpus.json")
    frames = [fr for fr in wf["watch_frames"] if fr["type"] != "ERROR"]
    fixture_server.watch = (wf["list_response"], frames)
    client = HttpClient(fixture_server.url)
    w = client.watch("config.tpu.io/v1", "DataProcessingUnit",
                     "dpu-operator-system")
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            watches = [p for (m, p) in fixture_server.requests
                       if "watch=1" in p]
            if len(watches) >= 2:
                break
            time.sleep(0.05)
        watches = [p for (m, p) in fixture_server.requests if "watch=1" in p]
        assert len(watches) >= 2, fixture_server.requests
        # First watch starts from the LIST's rv; the reconnect resumes
        # from the LAST event's rv (the ADDED at 482911 postdates the
        # 482910 bookmark) — never from scratch.
        assert "resourceVersion=482900" in watches[0]
        assert "resourceVersion=482911" in watches[1]
        assert "allowWatchBookmarks=true" in watches[0]
        lists = [p for (m, p) in fixture_server.requests
                 if "watch=1" not in p]
        assert len(lists) == 1, f"relist happened despite clean resume: " \
            f"{fixture_server.requests}"
    finally:
        client.stop_watch(w)


def test_watch_stream_bookmark_and_error_frames(fixture_server):
    """The real watch wire: newline-framed events over chunked
    encoding, including a BOOKMARK (metadata skeleton — must NOT be
    delivered as a resource event) and a terminal ERROR Status frame
    (410 Expired — must trigger relist, not surface as an object). The
    client must deliver exactly the real resource events, then relist."""
    wf = _load("watch_stream_dpus.json")
    fixture_server.watch = (wf["list_response"], wf["watch_frames"])
    client = HttpClient(fixture_server.url)
    w = client.watch("config.tpu.io/v1", "DataProcessingUnit",
                     "dpu-operator-system")
    try:
        seen = []
        deadline = time.monotonic() + 10
        # initial-list ADDED + MODIFIED + ADDED from the stream; then
        # the ERROR frame forces a relist, whose ADDED re-delivery we
        # use as proof the loop survived the Status frame.
        while time.monotonic() < deadline and len(seen) < 4:
            try:
                ev = w.events.get(timeout=1.0)
            except Exception:
                continue
            seen.append(ev)
        types_names = [
            (ev.type, ev.object.get("metadata", {}).get("name")) for ev in seen
        ]
        assert ("ADDED", "tpu-v5litepod-8-w0-dpu") in types_names
        assert ("MODIFIED", "tpu-v5litepod-8-w0-dpu") in types_names
        assert ("ADDED", "tpu-v5litepod-8-w1-dpu") in types_names
        # No ghost events: nothing with an empty name (the BOOKMARK
        # skeleton) and no Status object ever surfaced.
        for ev in seen:
            assert ev.object.get("metadata", {}).get("name"), ev.object
            assert ev.object.get("kind") != "Status", ev.object
        # The relist after ERROR really happened: >= 2 plain GETs.
        lists = [p for (m, p) in fixture_server.requests
                 if m == "GET" and "watch=1" not in p]
        assert len(lists) >= 2, fixture_server.requests
    finally:
        client.stop_watch(w)
