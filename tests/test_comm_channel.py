"""IPv6 link-local control channel (reference Marvell fe80::1/::2 on SDP,
marvell/main.go:32-52; NetSec configureCommChannelIPs,
intel-netsec/main.go:131-177): fixed per-side addresses on the device
joining the two sides, proven by a real gRPC heartbeat over the scoped
addresses on a veth wire."""

import concurrent.futures
import subprocess
import time
import uuid

import grpc
import pytest

from dpu_operator_tpu.dpu_api import services
from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb
from dpu_operator_tpu.vsp.comm_channel import (
    DPU_LINK_LOCAL,
    HOST_LINK_LOCAL,
    peer_target,
    setup_comm_channel,
)


@pytest.fixture
def veth_pair(netns):
    tag = uuid.uuid4().hex[:5]
    host_dev, dpu_dev = f"cch{tag}", f"ccd{tag}"
    r = subprocess.run(
        ["ip", "link", "add", host_dev, "type", "veth", "peer", "name", dpu_dev],
        capture_output=True, text=True,
    )
    if r.returncode != 0:
        pytest.skip(f"veth unavailable: {r.stderr.strip()}")
    try:
        yield host_dev, dpu_dev
    finally:
        subprocess.run(["ip", "link", "del", host_dev], capture_output=True)


def test_connection_strings_always_uri_encoded(veth_pair):
    """Both sides take the `%25` (URI-encoded) scope form: gRPC decodes
    the authority, so a raw `%` + hex-pair device name (like these
    `cc...`-prefixed veths) would be corrupted into a garbage byte. The
    reference's raw-% DPU-side form only works because its server binds
    via Go net.Listen (intel-netsec/main.go:163-168)."""
    host_dev, dpu_dev = veth_pair
    assert setup_comm_channel(dpu_dev, dpu_mode=True) == (
        f"[{DPU_LINK_LOCAL}%25{dpu_dev}]"
    )
    assert setup_comm_channel(host_dev, dpu_mode=False) == (
        f"[{HOST_LINK_LOCAL}%25{host_dev}]"
    )
    # Idempotent: re-running on an already-configured device is fine.
    assert setup_comm_channel(dpu_dev, dpu_mode=True) == (
        f"[{DPU_LINK_LOCAL}%25{dpu_dev}]"
    )


def test_heartbeat_over_link_local_channel(veth_pair):
    """A real OPI-style gRPC round trip over the channel: server bound on
    the DPU-side scoped address, client dialing the host-side %25 target
    across the veth wire."""
    host_dev, dpu_dev = veth_pair
    bind = setup_comm_channel(dpu_dev, dpu_mode=True)
    setup_comm_channel(host_dev, dpu_mode=False)

    class Heart(services.HeartbeatServicer):
        def Ping(self, request, context):
            return pb.PingResponse(healthy=True)

    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=2))
    services.add_heartbeat(Heart(), server)
    port = server.add_insecure_port(f"{bind}:0")
    assert port > 0, f"could not bind {bind}"
    server.start()
    try:
        target = f"{peer_target(host_dev)}:{port}"
        chan = grpc.insecure_channel(target)
        try:
            deadline = time.monotonic() + 10
            last = None
            while time.monotonic() < deadline:
                try:
                    resp = services.HeartbeatStub(chan).Ping(
                        pb.PingRequest(timestamp_ns=1, sender_id="host"),
                        timeout=2,
                    )
                    assert resp.healthy
                    break
                except grpc.RpcError as e:  # DAD may still be settling
                    last = e
                    time.sleep(0.2)
            else:
                raise AssertionError(f"ping over {target} never succeeded: {last}")
        finally:
            chan.close()
    finally:
        server.stop(0)


def test_tpuvsp_init_advertises_comm_channel(veth_pair, tmp_root, monkeypatch):
    """With DPU_COMM_CHANNEL_DEV set, Init returns the link-local
    connection string instead of a routed IP — the full reference shape
    (VSP does the bring-up inside Init and the daemon binds what Init
    returned)."""
    from dpu_operator_tpu.vsp.tpu_dataplane import DebugDataplane
    from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

    _, dpu_dev = veth_pair
    monkeypatch.setenv("DPU_COMM_CHANNEL_DEV", dpu_dev)
    vsp = TpuVsp(dataplane=DebugDataplane(), opi_port=50199)
    resp = vsp.Init(
        pb.InitRequest(dpu_mode=pb.DPU_MODE_DPU, dpu_identifier="cc-test"), None
    )
    assert resp.ip == f"[{DPU_LINK_LOCAL}%25{dpu_dev}]"
    assert resp.port == 50199


def test_tpuvsp_host_mode_advertises_peer_target(veth_pair, tmp_root, monkeypatch):
    """Host-mode Init must return the DPU side's address (the thing the
    host daemon will DIAL), not the host's own — and the end-to-end pair
    works: DPU-side VSP Init gives the bind address, host-side VSP Init
    gives a target that reaches a server bound there."""
    from dpu_operator_tpu.vsp.tpu_dataplane import DebugDataplane
    from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

    host_dev, dpu_dev = veth_pair
    monkeypatch.setenv("DPU_COMM_CHANNEL_DEV", host_dev)
    host_vsp = TpuVsp(dataplane=DebugDataplane(), opi_port=50201)
    resp = host_vsp.Init(
        pb.InitRequest(dpu_mode=pb.DPU_MODE_HOST, dpu_identifier="cc-host"), None
    )
    assert resp.ip == f"[{DPU_LINK_LOCAL}%25{host_dev}]"  # peer, not self

    # Bind a heartbeat server where the DPU-side Init would put it and
    # prove the host-advertised target reaches it over the wire.
    monkeypatch.setenv("DPU_COMM_CHANNEL_DEV", dpu_dev)
    dpu_vsp = TpuVsp(dataplane=DebugDataplane(), opi_port=0)
    dresp = dpu_vsp.Init(
        pb.InitRequest(dpu_mode=pb.DPU_MODE_DPU, dpu_identifier="cc-dpu"), None
    )

    class Heart(services.HeartbeatServicer):
        def Ping(self, request, context):
            return pb.PingResponse(healthy=True)

    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=2))
    services.add_heartbeat(Heart(), server)
    port = server.add_insecure_port(f"{dresp.ip}:0")
    assert port > 0
    server.start()
    try:
        chan = grpc.insecure_channel(f"{resp.ip}:{port}")
        try:
            deadline = time.monotonic() + 10
            while True:
                try:
                    assert services.HeartbeatStub(chan).Ping(
                        pb.PingRequest(timestamp_ns=1, sender_id="h"), timeout=2
                    ).healthy
                    break
                except grpc.RpcError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.2)
        finally:
            chan.close()
    finally:
        server.stop(0)
