"""Protocol-fidelity regression (round-2 verdict Missing #1 containment):
record every request HttpClient puts on the wire in http mode and assert
the shapes match kube-apiserver's documented REST forms — path grammar,
verbs, query params, content types — plus the documented response shapes
(Status bodies, List envelopes, watch event lines). The modeled ApiServer
accepting a malformed request would hide it; these assertions pin the
*client's* output against the upstream API convention independent of what
the model tolerates. The real-cluster tier (tests/test_kind.py) validates
the same client against an actual kube-apiserver when one is reachable.
"""

import json
import re
import urllib.request

import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.api import v1
from dpu_operator_tpu.k8s.http_client import HttpClient
from dpu_operator_tpu.k8s.http_server import ApiServer
from dpu_operator_tpu.k8s.store import Conflict, InMemoryCluster, NotFound


@pytest.fixture
def recording_stack():
    server = ApiServer(InMemoryCluster(), record_requests=True).start()
    client = HttpClient(server.url)
    try:
        yield server, client
    finally:
        server.stop()


def _find(log, method, path_re):
    for entry in log:
        if entry["method"] == method and re.fullmatch(path_re, entry["path"]):
            return entry
    raise AssertionError(
        f"no {method} {path_re} in wire log:\n"
        + "\n".join(f"{e['method']} {e['path']} {e['query']}" for e in log)
    )


def test_request_shapes_match_kube_rest_grammar(recording_stack):
    server, client = recording_stack
    ns = "default"

    # Namespaced custom resource CRUD + /status + list-by-label.
    cfg = v1.new_dpu_operator_config()
    cfg["metadata"]["namespace"] = ns
    created = client.create(cfg)
    created.setdefault("status", {})["phase"] = "Ready"
    client.update_status(created)
    fetched = client.get(v1.GROUP_VERSION, "DpuOperatorConfig", ns, v.DPU_OPERATOR_CONFIG_NAME)
    fetched["metadata"]["labels"] = {"a": "b"}
    client.update(fetched)
    client.list(v1.GROUP_VERSION, "DpuOperatorConfig", ns, label_selector={"a": "b"})
    client.delete(v1.GROUP_VERSION, "DpuOperatorConfig", ns, v.DPU_OPERATOR_CONFIG_NAME)

    # Core-group resource (different URL root) + cluster-scoped resource.
    client.create(
        {"apiVersion": "v1", "kind": "ConfigMap",
         "metadata": {"name": "cm1", "namespace": ns}, "data": {"k": "v"}}
    )
    client.create({"apiVersion": "v1", "kind": "Node", "metadata": {"name": "n0"}})
    client.list("v1", "Node")

    log = list(server.request_log)
    group = v1.GROUP_VERSION  # e.g. "config.tpu.io/v1"
    base = f"/apis/{group}/namespaces/{ns}/dpuoperatorconfigs"

    # Documented kube REST grammar:
    #   custom resources:  /apis/GROUP/VERSION/namespaces/NS/PLURAL[/NAME[/status]]
    #   core v1:           /api/v1/namespaces/NS/PLURAL[/NAME]
    #   cluster-scoped:    /api/v1/nodes
    post = _find(log, "POST", re.escape(base))
    assert post["content_type"] == "application/json"
    _find(log, "PUT", re.escape(f"{base}/{v.DPU_OPERATOR_CONFIG_NAME}/status"))
    _find(log, "GET", re.escape(f"{base}/{v.DPU_OPERATOR_CONFIG_NAME}"))
    _find(log, "PUT", re.escape(f"{base}/{v.DPU_OPERATOR_CONFIG_NAME}"))
    sel = _find(log, "GET", re.escape(base))
    assert sel["query"] == {"labelSelector": "a=b"}, sel["query"]
    _find(log, "DELETE", re.escape(f"{base}/{v.DPU_OPERATOR_CONFIG_NAME}"))
    _find(log, "POST", re.escape("/api/v1/namespaces/default/configmaps"))
    _find(log, "POST", re.escape("/api/v1/nodes"))
    _find(log, "GET", re.escape("/api/v1/nodes"))

    # No stray shapes: every logged path parses under the two documented
    # roots, and watch/namespaces never appear mangled.
    for entry in log:
        assert re.match(r"^/(api/v1|apis/[a-z0-9.\-]+/v[0-9a-z]+)/", entry["path"]), entry


def test_watch_request_and_event_wire_shape(recording_stack):
    import time

    server, client = recording_stack
    client.create({"apiVersion": "v1", "kind": "Node", "metadata": {"name": "w0"}})
    w = client.watch("v1", "Node")
    ev = w.events.get(timeout=10)  # initial relist
    assert ev.type in ("ADDED", "MODIFIED")
    assert ev.object["metadata"]["name"] == "w0"
    # An event arriving through the LIVE stream proves the watch GET is
    # on the wire (the first ADDED can come from the client's relist).
    client.create({"apiVersion": "v1", "kind": "Node", "metadata": {"name": "w1"}})
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        ev = w.events.get(timeout=10)
        if ev.object["metadata"]["name"] == "w1":
            break
    assert ev.object["metadata"]["name"] == "w1"
    client.stop_watch(w)

    watch_req = next(
        e for e in server.request_log
        if e["method"] == "GET" and e["query"].get("watch") in ("1", "true")
    )
    # watch=1 parses true under kube's strconv.ParseBool; resume point and
    # bookmark opt-in ride the documented query params (the client resumes
    # from bookmark RVs instead of relisting — test_wire_fixtures.py).
    assert watch_req["path"] == "/api/v1/nodes"
    assert "resourceVersion" in watch_req["query"]
    assert watch_req["query"]["allowWatchBookmarks"] == "true"

    # Raw wire: watch events are newline-delimited JSON {type, object}
    # exactly as a real apiserver streams them.
    with urllib.request.urlopen(
        f"{server.url}/api/v1/nodes?watch=1&resourceVersion=0", timeout=10
    ) as resp:
        line = resp.readline()
    parsed = json.loads(line)
    assert set(parsed) == {"type", "object"}
    assert parsed["object"]["kind"] == "Node"


def test_error_and_list_response_shapes(recording_stack):
    server, client = recording_stack
    client.create({"apiVersion": "v1", "kind": "Node", "metadata": {"name": "e0"}})

    # 409 Conflict carries a kube Status body.
    stale = client.get("v1", "Node", None, "e0")
    client.update(dict(stale))
    with pytest.raises(Conflict):
        client.update(stale)
    with pytest.raises(NotFound):
        client.get("v1", "Node", None, "nope")

    # Raw shapes: List envelope and Status error body.
    with urllib.request.urlopen(f"{server.url}/api/v1/nodes", timeout=10) as resp:
        body = json.loads(resp.read())
    assert body["kind"] == "NodeList"
    assert body["apiVersion"] == "v1"
    assert "resourceVersion" in body["metadata"]
    assert isinstance(body["items"], list)

    try:
        urllib.request.urlopen(f"{server.url}/api/v1/nodes/nope", timeout=10)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        status = json.loads(e.read())
        assert status["kind"] == "Status"
        assert status["apiVersion"] == "v1"
        assert status["status"] == "Failure"
        assert status["reason"] == "NotFound"
        assert status["code"] == 404
