"""Real-cluster validation tier (`make kind-test`) — round-2 verdict
Missing #1 containment.

Everything else in this suite validates against the project's own models
of the apiserver/kubelet (k8s/http_server.py, testutils.KubeletSim). This
tier runs the SAME production HttpClient and operator control plane
against a REAL kube-apiserver when one is reachable:

  * `TEST_KUBECONFIG` env — an externally provided cluster (the reference
    honors the same variable, internal/testutils/kindcluster.go:126-149);
  * else docker + `kind` — creates/reuses cluster
    `dpu-operator-test-cluster` like the reference's KindCluster
    (kindcluster.go:162-214);
  * else SKIP, naming the validated-vs-modeled boundary explicitly.

In this build container neither exists, so the skip line is the honest
record that real apiserver semantics (protobuf negotiation, admission
chains, exact watch framing) are validated only where a cluster is
supplied — the wire-shape regression (test_http_protocol.py) pins the
client's side of the contract everywhere.
"""

import os
import shutil
import subprocess
import tempfile
import time
import uuid

import pytest

CLUSTER_NAME = "dpu-operator-test-cluster"
SKIP_REASON = (
    "validated-vs-modeled boundary: no real kube-apiserver reachable — set "
    "TEST_KUBECONFIG or install docker+kind; apiserver/kubelet semantics are "
    "otherwise exercised against the project's modeled tier "
    "(k8s/http_server.py + testutils.KubeletSim) plus golden-fixture wire "
    "replay of real apiserver response shapes (test_wire_fixtures.py)"
)


def _resolve_kubeconfig():
    path = os.environ.get("TEST_KUBECONFIG")
    if path:
        if not os.path.exists(path):
            raise RuntimeError(f"TEST_KUBECONFIG={path} does not exist")
        return path
    if shutil.which("kind") and shutil.which("docker"):
        if subprocess.run(["docker", "info"], capture_output=True).returncode == 0:
            clusters = subprocess.run(
                ["kind", "get", "clusters"], capture_output=True, text=True
            ).stdout.split()
            if CLUSTER_NAME not in clusters:
                subprocess.run(
                    ["kind", "create", "cluster", "--name", CLUSTER_NAME,
                     "--wait", "180s"],
                    check=True,
                )
            fd, kc = tempfile.mkstemp(prefix="kindkc-", suffix=".yaml")
            os.close(fd)
            with open(kc, "w") as f:
                f.write(
                    subprocess.run(
                        ["kind", "get", "kubeconfig", "--name", CLUSTER_NAME],
                        check=True, capture_output=True, text=True,
                    ).stdout
                )
            return kc
    return None


@pytest.fixture(scope="module")
def real_client():
    kc = _resolve_kubeconfig()
    if kc is None:
        pytest.skip(SKIP_REASON)
    from dpu_operator_tpu.k8s.http_client import client_from_kubeconfig

    return client_from_kubeconfig(kc)


def _wait(predicate, timeout=60.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def test_httpclient_crud_conflict_watch_against_real_apiserver(real_client):
    """The production HttpClient's verbs against a genuine kube-apiserver:
    create/get/update, optimistic-concurrency 409, labelSelector listing,
    and the chunked watch stream."""
    from dpu_operator_tpu.k8s.store import Conflict

    client = real_client
    ns = "dpu-kind-" + uuid.uuid4().hex[:8]
    client.create({"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns}})
    try:
        cm = client.create(
            {"apiVersion": "v1", "kind": "ConfigMap",
             "metadata": {"name": "proto", "namespace": ns,
                          "labels": {"dpu-test": "yes"}},
             "data": {"k": "v1"}}
        )
        assert cm["metadata"]["resourceVersion"]

        w = client.watch("v1", "ConfigMap", ns)
        ev = w.events.get(timeout=30)  # raises Empty → fail if no event
        assert ev.object["metadata"]["name"] == "proto"

        fresh = client.get("v1", "ConfigMap", ns, "proto")
        fresh["data"]["k"] = "v2"
        client.update(dict(fresh))
        with pytest.raises(Conflict):
            client.update(fresh)  # stale resourceVersion

        listed = client.list(
            "v1", "ConfigMap", ns, label_selector={"dpu-test": "yes"}
        )
        assert [o["metadata"]["name"] for o in listed] == ["proto"]
        client.stop_watch(w)
    finally:
        client.delete("v1", "Namespace", None, ns)


def test_operator_reconciles_on_real_cluster(real_client):
    """Install the project CRDs, run the real operator control plane
    against the real apiserver, and assert a DpuOperatorConfig produces
    the daemon DaemonSet — the core of the modeled e2e, replayed against
    genuine cluster semantics."""
    import yaml

    from dpu_operator_tpu import vars as v
    from dpu_operator_tpu.api import v1
    from dpu_operator_tpu.controller.main import build_manager
    from dpu_operator_tpu.images import DummyImageManager
    from dpu_operator_tpu.k8s.store import AlreadyExists, NotFound

    client = real_client
    crd_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "config", "crd",
    )
    for fname in sorted(os.listdir(crd_dir)):
        if not fname.endswith(".yaml") or fname == "kustomization.yaml":
            continue
        with open(os.path.join(crd_dir, fname)) as f:
            for doc in yaml.safe_load_all(f):
                if not doc:
                    continue
                try:
                    client.create(doc)
                except AlreadyExists:
                    pass
    try:
        client.create(
            {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": v.NAMESPACE}}
        )
    except AlreadyExists:
        pass

    # CRDs need a moment to become Established before CR writes succeed.
    def crs_servable():
        try:
            client.list(v1.GROUP_VERSION, v1.KIND_DPU_OPERATOR_CONFIG, v.NAMESPACE)
            return True
        except Exception:
            return False

    assert _wait(crs_servable, timeout=60), "project CRDs never became servable"

    mgr = build_manager(client, DummyImageManager())
    mgr.start()
    try:
        try:
            client.create(v1.new_dpu_operator_config())
        except AlreadyExists:
            pass

        def daemonset_exists():
            try:
                client.get("apps/v1", "DaemonSet", v.NAMESPACE, "dpu-daemon")
                return True
            except NotFound:
                return False

        assert _wait(daemonset_exists, timeout=90), (
            "operator never rendered the daemon DaemonSet on the real cluster"
        )
    finally:
        mgr.stop()
        try:
            client.delete(
                v1.GROUP_VERSION, v1.KIND_DPU_OPERATOR_CONFIG, v.NAMESPACE,
                v.DPU_OPERATOR_CONFIG_NAME,
            )
        except Exception:
            pass
