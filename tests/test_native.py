"""Native-component tests: drive the REAL C++ binaries — cp-agent over
its framed-JSON socket (via the Python client the tpuvsp uses) and the
dpu-cni shim binary end-to-end against a live CNI server."""

import json
import os
import subprocess
import sys
import time
import uuid

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILD = os.path.join(REPO, "native", "build")


@pytest.fixture(scope="session")
def native_binaries():
    """Build native/ if binaries are missing (cached across runs)."""
    cp_agent = os.path.join(BUILD, "cp-agent")
    shim = os.path.join(BUILD, "dpu-cni")
    if not (os.path.exists(cp_agent) and os.path.exists(shim)):
        subprocess.run(
            ["cmake", "-S", os.path.join(REPO, "native"), "-B", BUILD, "-G", "Ninja"],
            check=True, capture_output=True,
        )
        subprocess.run(
            ["cmake", "--build", BUILD], check=True, capture_output=True
        )
    return {"cp_agent": cp_agent, "shim": shim}


@pytest.fixture
def cp_agent(native_binaries, tmp_root):
    sock = tmp_root.cp_agent_socket()
    env = dict(
        os.environ,
        TPU_ACCELERATOR_TYPE="v5litepod-8",
        TPU_CHIPS_PER_HOST_BOUNDS="2,2,1",
        TPU_WORKER_ID="1",
    )
    proc = subprocess.Popen(
        [native_binaries["cp_agent"], "--socket", sock, "--root", tmp_root.root],
        env=env, stderr=subprocess.PIPE,
    )
    deadline = time.monotonic() + 5
    while not os.path.exists(sock) and time.monotonic() < deadline:
        time.sleep(0.02)
    assert os.path.exists(sock), "cp-agent socket never appeared"
    yield sock
    proc.terminate()
    proc.wait(timeout=5)


def test_cp_agent_ping_topology_health(cp_agent):
    from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient

    client = CpAgentClient(cp_agent)
    pong = client.ping()
    assert pong["healthy"] is True
    assert "uptime_s" in pong

    topo = client.topology()
    assert topo["acceleratorType"] == "v5litepod-8"
    assert topo["workerId"] == 1
    # 4 chips declared by bounds env (no /dev/accel* under the temp root).
    assert topo["numChips"] == 4

    health = client.chip_health()
    assert health == {0: True, 1: True, 2: True, 3: True}

    stats = client.stats()
    assert stats["requests"] >= 3


def test_cp_agent_unknown_op(cp_agent):
    from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient, CpAgentError

    with pytest.raises(CpAgentError, match="unknown op"):
        CpAgentClient(cp_agent)._call({"op": "explode"})


def test_cp_agent_detects_unhealthy_chip(native_binaries, tmp_root):
    """PERST-analogue: an unopenable device node flips chip health."""
    os.makedirs(os.path.join(tmp_root.root, "dev"), exist_ok=True)
    # accel0: a plain file (openable). accel1: dangling symlink (present in
    # listing but unopenable → unhealthy).
    open(os.path.join(tmp_root.root, "dev", "accel0"), "w").close()
    os.symlink("/nonexistent", os.path.join(tmp_root.root, "dev", "accel1"))
    out = subprocess.run(
        [native_binaries["cp_agent"], "--root", tmp_root.root, "--oneshot", "chip_health"],
        capture_output=True, text=True, env={"PATH": os.environ["PATH"]},
    )
    chips = json.loads(out.stdout)["chips"]
    assert chips == {"0": True, "1": False}


def _start_agent(native_binaries, root, sock, config=None, env_extra=None):
    args = [native_binaries["cp_agent"], "--socket", sock, "--root", root]
    if config:
        args += ["--config", config]
    env = {"PATH": os.environ["PATH"]}
    env.update(env_extra or {})
    proc = subprocess.Popen(args, env=env, stderr=subprocess.PIPE)
    deadline = time.monotonic() + 5
    while not os.path.exists(sock) and time.monotonic() < deadline:
        time.sleep(0.02)
    assert os.path.exists(sock), "cp-agent socket never appeared"
    return proc


def test_cp_agent_config_application(native_binaries, tmp_root):
    """app_config.c analogue: the config declares what SHOULD exist; a
    chip the config expects but the scan can't see is unhealthy, and
    min_healthy_chips relaxes the ping policy."""
    os.makedirs(os.path.join(tmp_root.root, "dev"), exist_ok=True)
    open(os.path.join(tmp_root.root, "dev", "accel0"), "w").close()
    cfg = os.path.join(tmp_root.root, "agent.cfg")
    with open(cfg, "w") as f:
        f.write("# test config\nexpected_chips = 2\nmin_healthy_chips = 1\n"
                "rescan_ms = 100\n")
    sock = tmp_root.cp_agent_socket()
    proc = _start_agent(native_binaries, tmp_root.root, sock, config=cfg)
    try:
        from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient

        client = CpAgentClient(sock)
        conf = client.config()
        assert conf["expected_chips"] == 2
        assert conf["min_healthy_chips"] == 1
        # accel1 is expected but absent → unhealthy.
        assert client.chip_health() == {0: True, 1: False}
        # min_healthy_chips=1 keeps overall ping healthy despite it.
        assert client.ping()["healthy"] is True
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_cp_agent_pushes_health_change_events(native_binaries, tmp_root):
    """The event loop: removing a chip node produces a pushed
    health_change frame on a subscribed connection well before the poll
    fallback (parked at 10 s here) could have noticed — proving the
    inotify push path, not a rescan (octep PERST-event analogue)."""
    devdir = os.path.join(tmp_root.root, "dev")
    os.makedirs(devdir, exist_ok=True)
    open(os.path.join(devdir, "accel0"), "w").close()
    open(os.path.join(devdir, "accel1"), "w").close()
    cfg = os.path.join(tmp_root.root, "agent.cfg")
    with open(cfg, "w") as f:
        f.write("expected_chips = 2\nrescan_ms = 10000\n")
    sock = tmp_root.cp_agent_socket()
    proc = _start_agent(native_binaries, tmp_root.root, sock, config=cfg)
    try:
        from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient

        client = CpAgentClient(sock)
        events = client.subscribe()
        baseline = next(events)
        assert baseline["event"] == "baseline"
        assert baseline["chips"] == {0: True, 1: True}

        os.unlink(os.path.join(devdir, "accel1"))
        t0 = time.monotonic()
        ev = next(events)
        latency = time.monotonic() - t0
        assert ev["event"] == "health_change"
        assert ev["chips"] == {0: True, 1: False}
        assert ev["healthy"] is False
        # The claim is "pushed, not polled": the poll fallback above is
        # 10 s, so anything well under it proves the inotify path (the
        # old 1.0 s bound with a 100 ms rescan neither discriminated
        # push from poll nor survived full-suite CPU contention).
        assert latency < 1.8, f"event took {latency:.2f}s"
        events.close()

        stats = client.stats()
        assert stats["events_pushed"] >= 1
        assert stats["generation"] >= 1
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_cp_agent_reset_event_on_chip_return(native_binaries, tmp_root):
    """octep PERST analogue (reference apps/octep_cp_agent/main.c:45-62):
    yank + restore a chip node → the subscriber sees health_change
    (down), then a distinct `reset` event naming the returned chip,
    then health_change (up). Consumers re-probe on reset instead of
    just trusting the reopened node."""
    devdir = os.path.join(tmp_root.root, "dev")
    os.makedirs(devdir, exist_ok=True)
    open(os.path.join(devdir, "accel0"), "w").close()
    open(os.path.join(devdir, "accel1"), "w").close()
    cfg = os.path.join(tmp_root.root, "agent.cfg")
    with open(cfg, "w") as f:
        f.write("expected_chips = 2\nrescan_ms = 100\n")
    sock = tmp_root.cp_agent_socket()
    proc = _start_agent(native_binaries, tmp_root.root, sock, config=cfg)
    try:
        from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient

        client = CpAgentClient(sock)
        events = client.subscribe()
        assert next(events)["event"] == "baseline"

        os.unlink(os.path.join(devdir, "accel1"))
        down = next(events)
        assert down["event"] == "health_change"
        assert down["chips"] == {0: True, 1: False}

        open(os.path.join(devdir, "accel1"), "w").close()
        reset = next(events)
        assert reset["event"] == "reset"
        assert reset["chips_reset"] == [1]
        assert reset["chips"] == {0: True, 1: True}
        up = next(events)
        assert up["event"] == "health_change"
        assert up["chips"] == {0: True, 1: True}
        assert up["healthy"] is True
        events.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_cp_agent_reset_during_no_subscriber_window_rides_baseline(
    native_binaries, tmp_root
):
    """A bounce that completes while nobody is subscribed (the VSP's
    reconnect window) must not be silently swallowed: the next
    subscriber's baseline carries chips_reset so the consumer still
    re-probes the returned chip."""
    devdir = os.path.join(tmp_root.root, "dev")
    os.makedirs(devdir, exist_ok=True)
    open(os.path.join(devdir, "accel0"), "w").close()
    open(os.path.join(devdir, "accel1"), "w").close()
    cfg = os.path.join(tmp_root.root, "agent.cfg")
    with open(cfg, "w") as f:
        f.write("expected_chips = 2\nrescan_ms = 50\n")
    sock = tmp_root.cp_agent_socket()
    proc = _start_agent(native_binaries, tmp_root.root, sock, config=cfg)
    try:
        from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient

        client = CpAgentClient(sock)

        def wait_health(want):
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if client.chip_health() == want:
                    return True
                time.sleep(0.05)
            return False

        # Bounce chip 1 with NO subscriber attached.
        os.unlink(os.path.join(devdir, "accel1"))
        assert wait_health({0: True, 1: False})
        open(os.path.join(devdir, "accel1"), "w").close()
        assert wait_health({0: True, 1: True})

        events = client.subscribe()
        baseline = next(events)
        assert baseline["event"] == "baseline"
        assert baseline["chips_reset"] == [1], baseline
        events.close()

        # NOT consumed by delivery: a second subscriber (e.g. the VSP
        # reconnecting after a debugging `fabric-ctl events` session took
        # the first baseline) still learns about the bounce — resets stay
        # visible for reset_memory_ms and re-probes are idempotent.
        events2 = client.subscribe()
        baseline2 = next(events2)
        assert baseline2["chips_reset"] == [1], baseline2
        events2.close()
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_cp_agent_min_healthy_counts_required_chips_only(native_binaries, tmp_root):
    """min_healthy_chips counts REQUIRED chips: another tenant's healthy
    chips must not mask this node's dead required chips."""
    os.makedirs(os.path.join(tmp_root.root, "dev"), exist_ok=True)
    # Chips 2,3 present+openable but marked required=false; required
    # chips 0,1 are expected-but-absent (dead).
    open(os.path.join(tmp_root.root, "dev", "accel2"), "w").close()
    open(os.path.join(tmp_root.root, "dev", "accel3"), "w").close()
    cfg = os.path.join(tmp_root.root, "agent.cfg")
    with open(cfg, "w") as f:
        f.write(
            "expected_chips = 4\nmin_healthy_chips = 2\nrescan_ms = 100\n"
            "chip.2.required = false\nchip.3.required = false\n"
        )
    sock = tmp_root.cp_agent_socket()
    proc = _start_agent(native_binaries, tmp_root.root, sock, config=cfg)
    try:
        from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient

        client = CpAgentClient(sock)
        assert client.chip_health() == {0: False, 1: False, 2: True, 3: True}
        # 2 healthy chips exist, but zero REQUIRED ones — unhealthy.
        assert client.ping()["healthy"] is False
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_cp_agent_per_chip_config(native_binaries, tmp_root):
    """Per-chip config entries (octep app_config.c applies per-PF/VF
    config): expected coords surface in `topology`, and a chip marked
    required=false cannot fail the node's ping."""
    os.makedirs(os.path.join(tmp_root.root, "dev"), exist_ok=True)
    open(os.path.join(tmp_root.root, "dev", "accel0"), "w").close()
    # accel1 is expected but absent — yet marked non-required.
    cfg = os.path.join(tmp_root.root, "agent.cfg")
    with open(cfg, "w") as f:
        f.write(
            "expected_chips = 2\nrescan_ms = 100\n"
            "chip.0.expected_coords = 0,0,0\n"
            "chip.1.expected_coords = 1,0,0\n"
            "chip.1.required = false\n"
        )
    sock = tmp_root.cp_agent_socket()
    proc = _start_agent(native_binaries, tmp_root.root, sock, config=cfg)
    try:
        from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient

        client = CpAgentClient(sock)
        topo = client.topology()
        assert topo["chipConfig"]["0"] == {
            "expectedCoords": "0,0,0", "required": True,
        }
        assert topo["chipConfig"]["1"] == {
            "expectedCoords": "1,0,0", "required": False,
        }
        # Raw chip state still reports the absence...
        assert client.chip_health() == {0: True, 1: False}
        # ...but the non-required chip can't fail the node.
        assert client.ping()["healthy"] is True
        conf = client.config()
        assert conf["chips"]["1"]["required"] is False
    finally:
        proc.terminate()
        proc.wait(timeout=5)


def test_fabric_ctl_events_streams_agent_frames(native_binaries, tmp_root):
    """`fabric-ctl events` tails the cp-agent event plane: baseline, then
    pushed health_change/reset frames, as JSON lines on stdout."""
    devdir = os.path.join(tmp_root.root, "dev")
    os.makedirs(devdir, exist_ok=True)
    open(os.path.join(devdir, "accel0"), "w").close()
    cfg = os.path.join(tmp_root.root, "agent.cfg")
    with open(cfg, "w") as f:
        f.write("expected_chips = 1\nrescan_ms = 50\n")
    sock = tmp_root.cp_agent_socket()
    proc = _start_agent(native_binaries, tmp_root.root, sock, config=cfg)

    ctl = subprocess.Popen(
        [sys.executable, "-m", "dpu_operator_tpu.fabric_ctl",
         "events", "--agent-socket", sock, "--count", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd=REPO,
    )
    try:
        # Subscribe confirmed (baseline on stdout) BEFORE bouncing, so
        # the down/reset/up frames arrive as live pushes.
        baseline = json.loads(ctl.stdout.readline())
        assert baseline["event"] == "baseline"
        os.unlink(os.path.join(devdir, "accel0"))
        time.sleep(0.5)
        open(os.path.join(devdir, "accel0"), "w").close()
        out, err = ctl.communicate(timeout=30)
        assert ctl.returncode == 0, err
        frames = [baseline] + [json.loads(ln) for ln in out.strip().splitlines()]
        assert [f["event"] for f in frames] == [
            "baseline", "health_change", "reset", "health_change",
        ]
        assert frames[2]["chips_reset"] == [0]
    finally:
        if ctl.poll() is None:
            ctl.kill()
        proc.terminate()
        proc.wait(timeout=5)



def test_cp_agent_stats_histograms(cp_agent):
    from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient

    client = CpAgentClient(cp_agent)
    client.ping()
    client.topology()
    client.ping()
    stats = client.stats()
    assert stats["ops"]["ping"] >= 2
    assert stats["ops"]["topology"] >= 1
    lat = stats["latency_us"]
    assert set(lat) == {"lt_100us", "lt_1ms", "lt_10ms", "ge_10ms"}
    # The in-flight stats request counts in `requests` but its own
    # latency is recorded only after the response is built.
    assert sum(lat.values()) == stats["requests"] - 1
    assert stats["heartbeats"] >= 0


def test_vsp_reacts_to_pushed_chip_loss(native_binaries, tmp_root):
    """End-to-end VERDICT r1 #4 'done' criterion: chip-node removal flips
    the tpuvsp's GetDevices health within 1 s WITHOUT any request-path
    probing — the VSP's background watcher consumes pushed events."""
    from dpu_operator_tpu.parallel.topology import SliceTopology
    from dpu_operator_tpu.vsp.cp_agent_client import CpAgentClient
    from dpu_operator_tpu.vsp.tpu_dataplane import DebugDataplane
    from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp
    from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb

    devdir = os.path.join(tmp_root.root, "dev")
    os.makedirs(devdir, exist_ok=True)
    open(os.path.join(devdir, "accel0"), "w").close()
    open(os.path.join(devdir, "accel1"), "w").close()
    cfg = os.path.join(tmp_root.root, "agent.cfg")
    with open(cfg, "w") as f:
        f.write("expected_chips = 2\nrescan_ms = 100\n")
    sock = tmp_root.cp_agent_socket()
    proc = _start_agent(native_binaries, tmp_root.root, sock, config=cfg)
    vsp = None
    try:
        topo = SliceTopology.from_env(
            {"TPU_CHIPS_PER_HOST_BOUNDS": "2,1,1", "TPU_WORKER_ID": "0"}
        )
        vsp = TpuVsp(
            topology=topo,
            dataplane=DebugDataplane(),
            cp_agent_client=CpAgentClient(sock),
            num_endpoints=2,
        )
        vsp.Init(pb.InitRequest(dpu_mode=pb.DPU_MODE_DPU, dpu_identifier="t"), None)

        from google.protobuf import empty_pb2

        def health_of(dev_id):
            devs = vsp.GetDevices(empty_pb2.Empty(), None).devices
            return devs[dev_id].health == pb.HEALTHY

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not (
            health_of("tpu0-ep0") and health_of("tpu1-ep0")
        ):
            time.sleep(0.05)
        assert health_of("tpu0-ep0") and health_of("tpu1-ep0")

        os.unlink(os.path.join(devdir, "accel1"))
        t0 = time.monotonic()
        while time.monotonic() - t0 < 3.0 and health_of("tpu1-ep0"):
            time.sleep(0.02)
        flipped_in = time.monotonic() - t0
        assert not health_of("tpu1-ep0"), "chip loss never surfaced"
        assert health_of("tpu0-ep0"), "healthy chip must stay healthy"
        assert flipped_in < 1.0, f"flip took {flipped_in:.2f}s (event path broken?)"

        # Restore the node: the agent pushes `reset` + health_change, the
        # VSP flips the device back AND schedules a compute re-probe
        # (resets_seen) instead of silently trusting the returned chip.
        open(os.path.join(devdir, "accel1"), "w").close()
        t0 = time.monotonic()
        while time.monotonic() - t0 < 3.0 and not health_of("tpu1-ep0"):
            time.sleep(0.02)
        assert health_of("tpu1-ep0"), "returned chip never re-advertised"
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and vsp.resets_seen == 0:
            time.sleep(0.02)
        assert vsp.resets_seen >= 1, "VSP never saw the reset event"
    finally:
        if vsp is not None:
            vsp.stop_watchers()
        proc.terminate()
        proc.wait(timeout=5)


def test_cni_shim_binary_against_live_server(native_binaries, tmp_root, netns):
    """The on-disk binary round-trips a real ADD: env + stdin → unix-socket
    HTTP → CNI server → veth in a real netns → JSON result on stdout."""
    from dpu_operator_tpu.cni import CniServer
    from dpu_operator_tpu.cni.dataplane import FabricDataplane
    from dpu_operator_tpu.cni.ipam import HostLocalIpam
    from dpu_operator_tpu.cni.statestore import StateStore

    store = StateStore(tmp_root.cni_state_dir())
    ipam = HostLocalIpam(tmp_root.cni_state_dir(), "10.77.0.0/24")
    dataplane = FabricDataplane(store, ipam)
    server = CniServer(tmp_root)
    server.set_handlers(
        lambda req: dataplane.cmd_add(req).to_json(),
        lambda req: dataplane.cmd_del(req)[0],
    )
    server.start()
    ns = "tstshim-" + uuid.uuid4().hex[:6]
    subprocess.run(["ip", "netns", "add", ns], check=True)
    container_id = "shim" + uuid.uuid4().hex[:12]
    try:
        env = {
            "PATH": os.environ["PATH"],
            "DPU_CNI_SOCKET": server.socket_path,
            "CNI_COMMAND": "ADD",
            "CNI_CONTAINERID": container_id,
            "CNI_NETNS": ns,
            "CNI_IFNAME": "net1",
            "CNI_PATH": "/opt/cni/bin",
            "CNI_ARGS": "K8S_POD_NAME=testpod;K8S_POD_NAMESPACE=default",
        }
        conf = json.dumps(
            {"cniVersion": "1.0.0", "name": "default-ici-net", "type": "dpu-cni"}
        )
        r = subprocess.run(
            [native_binaries["shim"]], input=conf, env=env,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        result = json.loads(r.stdout)
        assert result["interfaces"][0]["name"] == "net1"
        assert result["ips"][0]["address"].startswith("10.77.0.")

        env["CNI_COMMAND"] = "DEL"
        r = subprocess.run(
            [native_binaries["shim"]], input=conf, env=env,
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stdout + r.stderr

        # Error path: server down → code 11 JSON + exit 1.
        server.stop()
        r = subprocess.run(
            [native_binaries["shim"]], input=conf, env=env,
            capture_output=True, text=True,
        )
        assert r.returncode == 1
        assert json.loads(r.stdout)["code"] == 11
    finally:
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)
        server.stop()


def test_cni_shim_answers_version_without_daemon(native_binaries):
    """CNI VERSION is answered by the plugin binary itself (spec): the
    runtime probes it with no daemon around, so requiring the socket
    would report the plugin broken during every daemon restart."""
    r = subprocess.run(
        [native_binaries["shim"]],
        input="", capture_output=True, text=True, timeout=10,
        env={"PATH": os.environ["PATH"], "CNI_COMMAND": "VERSION",
             "DPU_CNI_SOCKET": "/nonexistent/sock"},
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout)
    assert out["cniVersion"] == "1.0.0"
    assert "1.0.0" in out["supportedVersions"]

    # Python shim: same contract.
    r = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.cni.shim"],
        input="", capture_output=True, text=True, timeout=30, cwd=REPO,
        env={**os.environ, "CNI_COMMAND": "VERSION",
             "DPU_CNI_SOCKET": "/nonexistent/sock"},
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(r.stdout)["cniVersion"] == "1.0.0"
