"""Daemon integration tests — the counterpart of the reference's Kind tier
(internal/daemon/daemon_test.go, dpusidemanager_test.go,
hostsidemanager_test.go): real gRPC process boundaries (unix sockets +
TCP OPI), FakePlatform detection, mock VSP, and — where the environment
allows netns — the full CNI ADD/DEL path with a real pod namespace."""

import socket
import subprocess
import time
import uuid

import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.api import v1
from dpu_operator_tpu.daemon import Daemon, GrpcPlugin
from dpu_operator_tpu.daemon.dpu_side import DpuSideManager
from dpu_operator_tpu.daemon.host_side import HostSideManager
from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster, get_condition
from dpu_operator_tpu.platform import FakePlatform
from dpu_operator_tpu.utils import PathManager
from dpu_operator_tpu.vsp import MockVsp, VspServer

TPU_ENV = {"TPU_ACCELERATOR_TYPE": "v5litepod-8", "TPU_WORKER_ID": "0"}


def wait_for(predicate, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def cluster_client():
    client = InMemoryClient(InMemoryCluster())
    client.create(
        {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "tpu-node-0"}}
    )
    return client


def test_daemon_detects_tpu_and_syncs_cr(cluster_client, tmp_root):
    """FakePlatform advertises a TPU-VM → DataProcessingUnit CR appears
    with isDpuSide and is removed when the platform stops matching
    (reference daemon_test.go:112-120 + EventuallyNoDpuCR :34-47)."""
    platform = FakePlatform(
        product="Google Cloud TPU", node="tpu-node-0", env=TPU_ENV
    )
    vsp = MockVsp(opi_port=free_port())
    vsp_server = VspServer(vsp, tmp_root)
    vsp_server.start()
    daemon = Daemon(
        cluster_client,
        platform,
        path_manager=tmp_root,
        tick_interval=0.05,
        register_device_plugin=False,
    )
    daemon.start()
    try:
        cr_name = "tpu-v5litepod-8-w0-dpu"
        assert wait_for(
            lambda: cluster_client.get_or_none(
                v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, cr_name
            )
            is not None
        ), "DataProcessingUnit CR never appeared"
        cr = cluster_client.get(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, cr_name
        )
        assert cr["spec"]["isDpuSide"] is True
        assert cr["spec"]["nodeName"] == "tpu-node-0"
        assert "TPU" in cr["spec"]["dpuProductName"]

        # VSP got Init with DPU mode + our identifier.
        assert wait_for(lambda: len(vsp.init_calls) > 0)
        mode, ident = vsp.init_calls[0]
        assert ident == "tpu-v5litepod-8-w0"

        # Node label was derived.
        node = cluster_client.get("v1", "Node", None, "tpu-node-0")
        assert wait_for(
            lambda: cluster_client.get("v1", "Node", None, "tpu-node-0")["metadata"][
                "labels"
            ].get(v.DPU_SIDE_LABEL)
            == v.DPU_SIDE_DPU
        )

        # Platform stops matching → CR cleaned up (orphan path).
        platform.set_product("")
        platform.set_env({})
        assert wait_for(
            lambda: cluster_client.get_or_none(
                v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, cr_name
            )
            is None
        ), "orphaned CR was not deleted"
    finally:
        daemon.stop()
        vsp_server.stop()


def test_fabric_shaping_degradation_surfaces_as_cr_condition(
        cluster_client, tmp_root):
    """VERDICT r3 Next #5: when the VSP's dataplane cannot program
    shaping/flow rules (no tc binary, rejected qdisc, nf_tables
    failure), the DataProcessingUnit CR carries FabricShaping=False
    with the reason — and recovers to True when the VSP reports clean
    again. The degradation rides the heartbeat (PingResponse
    .degradations), so it needs no extra RPC or poll loop."""
    platform = FakePlatform(
        product="Google Cloud TPU", node="tpu-node-0", env=TPU_ENV
    )
    vsp = MockVsp(opi_port=free_port())
    vsp_server = VspServer(vsp, tmp_root)
    vsp_server.start()
    daemon = Daemon(
        cluster_client,
        platform,
        path_manager=tmp_root,
        tick_interval=0.05,
        register_device_plugin=False,
    )
    daemon.start()
    cr_name = "tpu-v5litepod-8-w0-dpu"

    def condition():
        cr = cluster_client.get_or_none(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE,
            cr_name)
        return get_condition(cr, v1.COND_FABRIC_SHAPING) if cr else None

    try:
        assert wait_for(lambda: (condition() or {}).get("status") == "True"), \
            "healthy fabric never reported FabricShaping=True"

        vsp.degradations = ["endpoint share on ep0 failed: tc not found"]
        assert wait_for(
            lambda: (condition() or {}).get("status") == "False"), \
            "degradation never reached the CR condition"
        cond = condition()
        assert cond["reason"] == "Degraded"
        assert "tc not found" in cond["message"]

        vsp.degradations = []
        assert wait_for(lambda: (condition() or {}).get("status") == "True"), \
            "condition never recovered after the VSP reported clean"
    finally:
        daemon.stop()
        vsp_server.stop()


def test_daemon_rejects_multiple_dpus(cluster_client, tmp_root):
    """More than one detected DPU is an error (reference daemon.go:135-143)."""
    from dpu_operator_tpu.platform import DetectedDpu, FakeTpuDetector

    platform = FakePlatform(node="tpu-node-0")
    two = [
        DetectedDpu("a", "prod-a", True, "fake", "tpu-node-0"),
        DetectedDpu("b", "prod-b", True, "fake", "tpu-node-0"),
    ]
    daemon = Daemon(
        cluster_client,
        platform,
        path_manager=tmp_root,
        detectors=[
            FakeTpuDetector("d1", [two[0]]),
            FakeTpuDetector("d2", [two[1]]),
        ],
        register_device_plugin=False,
    )
    with pytest.raises(RuntimeError, match="only one"):
        daemon.tick()


class TwoSideHarness:
    """Both daemon roles in one process, separate PathManager roots, real
    gRPC boundaries — the shape of the reference's host/dpu manager tests."""

    def __init__(self, host_pm: PathManager, dpu_pm: PathManager):
        port = free_port()
        self.dpu_vsp = MockVsp(opi_port=port)
        self.dpu_vsp_server = VspServer(self.dpu_vsp, dpu_pm)
        self.dpu_vsp_server.start()
        self.host_vsp = MockVsp(opi_port=port)
        self.host_vsp_server = VspServer(self.host_vsp, host_pm)
        self.host_vsp_server.start()

        self.dpu = DpuSideManager(
            GrpcPlugin(dpu_pm.vendor_plugin_socket()),
            "tpu-v5litepod-8-w0",
            path_manager=dpu_pm,
            register_device_plugin=False,
        )
        self.host = HostSideManager(
            GrpcPlugin(host_pm.vendor_plugin_socket()),
            "tpu-host-0",
            path_manager=host_pm,
            register_device_plugin=False,
        )

    def start(self):
        self.dpu.start_vsp()
        self.dpu.setup_devices()
        self.dpu.listen()
        self.dpu.serve()
        self.host.start_vsp()
        self.host.setup_devices()
        self.host.listen()
        self.host.serve()

    def stop(self):
        self.host.stop()
        self.dpu.stop()
        self.host_vsp_server.stop()
        self.dpu_vsp_server.stop()


@pytest.fixture
def two_sides(tmp_root):
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="dpu-")
    harness = TwoSideHarness(host_pm=tmp_root, dpu_pm=PathManager(root=d))
    harness.start()
    try:
        yield harness
    finally:
        harness.stop()
        shutil.rmtree(d, ignore_errors=True)


def test_heartbeat_host_to_dpu(two_sides):
    """Host pings the DPU-side OPI server every second; both sides report
    fresh pings (reference §3.5 health loop)."""
    assert wait_for(two_sides.host.check_ping, timeout=10), "host never got a pong"
    assert two_sides.dpu.check_ping(), "dpu never recorded a ping"


def test_cni_add_del_full_path(two_sides, netns):
    """The 'forward pass' (SURVEY §3.3): CNI ADD through the shim protocol
    → host CNI server → veth fabric dataplane into a REAL pod netns →
    CreateBridgePort over TCP to the DPU-side daemon → DPU VSP. Then DEL
    tears it all down."""
    from dpu_operator_tpu.cni import CniRequest, do_cni

    ns = "tstpod-" + uuid.uuid4().hex[:6]
    subprocess.run(["ip", "netns", "add", ns], check=True)
    try:
        container_id = "cont" + uuid.uuid4().hex[:12]
        req = CniRequest(
            command="ADD",
            container_id=container_id,
            netns=ns,
            ifname="net1",
            config={"cniVersion": "1.0.0", "name": "default-ici-net", "type": "dpu-cni"},
        )
        sock = two_sides.host.cni_server.socket_path
        result = do_cni(sock, req)
        assert result["interfaces"][0]["name"] == "net1"
        assert result["ips"], "no IP allocated"

        # Interface really exists in the pod netns with the allocated IP.
        out = subprocess.run(
            ["ip", "-n", ns, "-j", "addr", "show", "dev", "net1"],
            capture_output=True, text=True, check=True,
        ).stdout
        assert result["ips"][0]["address"].split("/")[0] in out

        # The DPU-side VSP saw the bridge port (host→OPI→VSP chain).
        assert wait_for(lambda: len(two_sides.dpu_vsp.bridge_ports) == 1)

        # DEL is clean and releases the bridge port.
        req_del = CniRequest(
            command="DEL", container_id=container_id, netns=ns, ifname="net1",
            config=req.config,
        )
        do_cni(sock, req_del)
        assert wait_for(lambda: len(two_sides.dpu_vsp.bridge_ports) == 0)
        out = subprocess.run(
            ["ip", "-n", ns, "link", "show", "dev", "net1"],
            capture_output=True, text=True,
        )
        assert out.returncode != 0, "pod interface survived DEL"

        # DEL is idempotent (CNI spec).
        do_cni(sock, req_del)
    finally:
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)


from contextlib import contextmanager


@contextmanager
def _two_cluster_stack(host_pm, opi_ip="127.0.0.1", pci_serial="serA1"):
    """Two clusters, two daemons: DPU side runs the real tpuvsp (debug
    dataplane) as a converged manager; host side PCI-detects the
    accelerator and its MockVsp Init points at `opi_ip`:port for the
    DPU-side OPI. Everything is torn down on exit regardless of where
    setup or the test body fails."""
    import shutil
    import tempfile
    from types import SimpleNamespace

    from dpu_operator_tpu.platform import PciDevice
    from dpu_operator_tpu.vsp.tpu_dataplane import DebugDataplane
    from dpu_operator_tpu.vsp.tpu_vsp import TpuVsp

    st = SimpleNamespace(
        host_cluster=InMemoryClient(InMemoryCluster()),
        dpu_cluster=InMemoryClient(InMemoryCluster()),
        opi_port=free_port(),
        dpu_root=tempfile.mkdtemp(prefix="dpu-"),
        dpu_vsp=None, dpu_vsp_server=None, dpu_daemon=None,
        host_vsp=None, host_vsp_server=None, host_daemon=None,
    )
    try:
        st.host_cluster.create(
            {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "host-0"}}
        )
        st.dpu_cluster.create(
            {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "tpuvm-0"}}
        )
        dpu_pm = PathManager(root=st.dpu_root)
        st.dpu_vsp = TpuVsp(dataplane=DebugDataplane(), opi_port=st.opi_port)
        st.dpu_vsp_server = VspServer(st.dpu_vsp, dpu_pm)
        st.dpu_vsp_server.start()
        st.dpu_daemon = Daemon(
            st.dpu_cluster,
            FakePlatform(product="Google Cloud TPU", node="tpuvm-0", env=TPU_ENV),
            path_manager=dpu_pm,
            tick_interval=0.05,
            register_device_plugin=False,
        )
        st.dpu_daemon.start()

        host_platform = FakePlatform(node="host-0")
        host_platform.add_device(
            PciDevice(
                address="0000:00:05.0", vendor_id="1ae0", device_id="0063",
                class_name="0x120000", product_name="Google TPU accelerator",
            ),
            serial=pci_serial,
        )
        st.host_vsp = MockVsp(opi_ip=opi_ip, opi_port=st.opi_port)
        st.host_vsp_server = VspServer(st.host_vsp, host_pm)
        st.host_vsp_server.start()
        st.host_daemon = Daemon(
            st.host_cluster, host_platform, path_manager=host_pm,
            tick_interval=0.05, register_device_plugin=False,
        )
        st.host_daemon.start()
        yield st
    finally:
        for obj in (st.host_daemon, st.dpu_daemon, st.host_vsp_server,
                    st.dpu_vsp_server):
            if obj is not None:
                obj.stop()
        shutil.rmtree(st.dpu_root, ignore_errors=True)


def test_two_cluster_topology(tmp_root):
    """The reference's 2-cluster deployment shape (README.md:38-44): the
    host cluster node PCI-detects the accelerator (is_dpu_side=False →
    HostSideManager), the accelerator-side cluster runs the TPU-VM
    runtime (converged manager serving OPI); each cluster keeps its own
    DataProcessingUnit CR and side label, and the host's CNI ADD crosses
    the cluster boundary over OPI TCP to program the DPU-side VSP."""
    from dpu_operator_tpu.cni import CniRequest, do_cni

    with _two_cluster_stack(tmp_root) as st:
        # Each cluster gets its own CR with the right side.
        assert wait_for(
            lambda: st.dpu_cluster.get_or_none(
                v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE,
                "tpu-v5litepod-8-w0-dpu",
            ) is not None
        ), "DPU-side CR never appeared"
        assert wait_for(
            lambda: st.host_cluster.get_or_none(
                v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE,
                "tpu-sera1-host",
            ) is not None
        ), "host-side CR never appeared"
        assert st.host_cluster.get(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT, v.NAMESPACE, "tpu-sera1-host"
        )["spec"]["isDpuSide"] is False

        # Side labels derived per cluster (reference daemon.go:476-526).
        assert wait_for(
            lambda: st.dpu_cluster.get("v1", "Node", None, "tpuvm-0")["metadata"]
            .get("labels", {}).get(v.DPU_SIDE_LABEL) == v.DPU_SIDE_DPU
        )
        assert wait_for(
            lambda: st.host_cluster.get("v1", "Node", None, "host-0")["metadata"]
            .get("labels", {}).get(v.DPU_SIDE_LABEL) == v.DPU_SIDE_HOST
        )

        # Cross-cluster heartbeat: host manager pings DPU-side OPI over TCP.
        assert wait_for(lambda: len(st.host_daemon.managed()) == 1)
        host_mgr = list(st.host_daemon.managed().values())[0].manager
        assert wait_for(host_mgr.check_ping, timeout=15), "cross-cluster ping failed"

        # Host CNI ADD → CreateBridgePort lands in the DPU-side tpuvsp.
        from bench import RecordingDataplane

        host_mgr.dataplane = RecordingDataplane()
        req = CniRequest(
            command="ADD",
            container_id="xcluster" + uuid.uuid4().hex[:8],
            netns="/proc/self/ns/net",
            ifname="net1",
            config={"cniVersion": "1.0.0", "name": "default-ici-net", "type": "dpu-cni"},
        )
        do_cni(host_mgr.cni_server.socket_path, req)
        assert wait_for(lambda: len(st.dpu_vsp._dataplane.ports) == 1), (
            "bridge port never reached the DPU-side VSP"
        )


def test_dpu_config_applies_endpoint_partitioning(cluster_client, tmp_root):
    """A DataProcessingUnitConfig whose dpuSelector matches this node's
    DPU applies spec.numEndpoints through the VSP (the reference ships
    this CRD as a placeholder; here the selector carries the real fabric
    knob)."""
    platform = FakePlatform(product="Google Cloud TPU", node="tpu-node-0", env=TPU_ENV)
    vsp = MockVsp(opi_port=free_port())
    vsp_server = VspServer(vsp, tmp_root)
    vsp_server.start()
    daemon = Daemon(
        cluster_client, platform, path_manager=tmp_root,
        tick_interval=0.05, register_device_plugin=False,
    )
    daemon.start()
    try:
        assert wait_for(lambda: len(daemon.managed()) == 1)
        # Selector matches the vendor label stamped on the DPU CR.
        cluster_client.create(
            v1.new_data_processing_unit_config(
                "tune-tpu", dpu_selector={"dpu.tpu.io/vendor": "tpu"}, num_endpoints=12
            )
        )
        assert wait_for(
            lambda: vsp.GetDevices(None, None).devices and len(
                vsp.GetDevices(None, None).devices
            ) == 12,
            timeout=10,
        ), "numEndpoints never applied"

        # The daemon records the feedback loop on the CR status: which DPU
        # the partition landed on (the reference's placeholder CRD has no
        # status at all).
        def applied_to():
            cfg = cluster_client.get_or_none(
                v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT_CONFIG,
                v.NAMESPACE, "tune-tpu",
            )
            return (cfg or {}).get("status", {}).get("appliedTo", [])

        def recorded():
            a = applied_to()
            return len(a) == 1 and a[0]["numEndpoints"] == 12

        assert wait_for(recorded, timeout=10), (
            f"status never recorded: {applied_to()}"
        )

        # Selector edit prunes the stale entry: the config no longer
        # matches any managed DPU, so the feedback loop must not keep
        # claiming it is applied.
        cfg = cluster_client.get(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT_CONFIG,
            v.NAMESPACE, "tune-tpu",
        )
        cfg["spec"]["dpuSelector"] = {"dpu.tpu.io/vendor": "nonesuch"}
        cluster_client.update(cfg)
        assert wait_for(lambda: applied_to() == [], timeout=10), (
            f"stale appliedTo never pruned: {applied_to()}"
        )

        # Non-matching selector is ignored.
        cluster_client.create(
            v1.new_data_processing_unit_config(
                "tune-other", dpu_selector={"dpu.tpu.io/vendor": "marvell"},
                num_endpoints=3,
            )
        )
        time.sleep(0.5)
        assert len(vsp.GetDevices(None, None).devices) == 12
        assert not cluster_client.get_or_none(
            v1.GROUP_VERSION, v1.KIND_DATA_PROCESSING_UNIT_CONFIG,
            v.NAMESPACE, "tune-other",
        ).get("status", {}).get("appliedTo")
    finally:
        daemon.stop()
        vsp_server.stop()


def test_two_cluster_over_link_local_comm_channel(tmp_root, netns, monkeypatch):
    """The 2-cluster control plane riding the IPv6 link-local channel
    end-to-end through the daemons: the DPU-side converged manager binds
    its OPI server on the channel's fixed scoped address (returned by
    TpuVsp Init with DPU_COMM_CHANNEL_DEV), and the host daemon — whose
    VSP advertises the peer target — heartbeats and programs bridge
    ports across the veth wire joining the two sides (reference Marvell
    fe80::1/::2 SDP channel, marvell/main.go:32-52)."""
    from dpu_operator_tpu.cni import CniRequest, do_cni
    from dpu_operator_tpu.vsp.comm_channel import peer_target, setup_comm_channel

    tag = uuid.uuid4().hex[:5]
    host_dev, dpu_dev = f"xch{tag}", f"xcd{tag}"
    r = subprocess.run(
        ["ip", "link", "add", host_dev, "type", "veth", "peer", "name", dpu_dev],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    try:
        # The DPU-side tpuvsp reads this at Init and binds the OPI on the
        # channel; the host-side MockVsp ignores it.
        monkeypatch.setenv("DPU_COMM_CHANNEL_DEV", dpu_dev)
        with _two_cluster_stack(
            tmp_root, opi_ip=peer_target(host_dev), pci_serial="serCC1"
        ) as st:
            # Bring the host's side of the wire up with its own address
            # (the host-mode bring-up a real host tpuvsp performs).
            setup_comm_channel(host_dev, dpu_mode=False)

            assert wait_for(lambda: len(st.host_daemon.managed()) == 1)
            host_mgr = list(st.host_daemon.managed().values())[0].manager
            assert wait_for(host_mgr.check_ping, timeout=20), (
                "heartbeat over the link-local channel never succeeded"
            )

            from bench import RecordingDataplane

            host_mgr.dataplane = RecordingDataplane()
            req = CniRequest(
                command="ADD",
                container_id="xcc" + uuid.uuid4().hex[:8],
                netns="/proc/self/ns/net",
                ifname="net1",
                config={"cniVersion": "1.0.0", "name": "default-ici-net",
                        "type": "dpu-cni"},
            )
            do_cni(host_mgr.cni_server.socket_path, req)
            assert wait_for(lambda: len(st.dpu_vsp._dataplane.ports) == 1), (
                "bridge port never crossed the channel to the DPU-side VSP"
            )
    finally:
        subprocess.run(["ip", "link", "del", host_dev], capture_output=True)


def test_mode_override_forces_role():
    """spec.mode=dpu|host forces every detection's side regardless of
    what the detector saw (the DPU_MODE env the daemonset renders from
    the CR; daemon/main.py -> Daemon(mode_override=...))."""
    from dpu_operator_tpu.daemon.daemon import Daemon
    from dpu_operator_tpu.platform import DetectedDpu

    det = DetectedDpu(
        identifier="tpu-x", product_name="TPU v5e", is_dpu_side=False,
        vendor="tpu", node_name="n0", topology=None,
    )

    # Daemon not started; only the override logic is under test.
    for mode, want in (("dpu", True), ("host", False), ("auto", False)):
        d = Daemon.__new__(Daemon)
        d._mode_override = mode
        out = Daemon._apply_mode_override(d, [det])
        assert out[0].is_dpu_side is want, mode
        assert out[0].identifier == "tpu-x"
