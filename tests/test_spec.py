"""Speculative decoding (ISSUE 15): acceptance math, the draft
contract, and the stream-equivalence lane.

Correctness strategy carries PR 7's: INVARIANCE. Greedy argmax
verification is deterministic, so a speculative stream must be
BYTE-IDENTICAL to the one-token stream on the same trace — at every
acceptance rate (0%: every step still emits its bonus token; 100%:
full windows accept), across the synthetic and the real jitted
planes, and on both paged-attention kernels. Rejection rollback is
exercised hardest at LOW acceptance (every step rolls ctx back), full
windows hardest at rate 1.0.

Real-model lanes pin ``pool_dtype="fp32"`` for exact byte-identity,
the PR 13 precedent: int8 per-block scales are set once by the step
that writes a block's row 0 over ALL that step's rows — a verify
window groups rejected rows into the amax, so speculative int8
quantization GROUPS differ from one-token runs by design and the
divergence is bounded by the documented paged_kv_error_bound, not
zero. Speculative int8 runs are still deterministic against
themselves, asserted below.

Every allocator-touching test asserts a clean leak ledger."""

import time

import numpy as np
import pytest

from dpu_operator_tpu.serving import (AdmissionQueue, ContinuousBatcher,
                                      GenerateRequest,
                                      SyntheticKVExecutor)
from dpu_operator_tpu.serving.spec import (NO_TOKEN, OracleDraft,
                                           SpecConfig, accept_length,
                                           accept_tree, clamp_spec_k,
                                           propose_full,
                                           synthetic_next_token,
                                           token_run)

MODEL = dict(vocab=32, d=16, heads=2)
VOCAB = 64  # the synthetic executors' default


def _req(prompt, max_tokens=6, deadline_s=60.0):
    return GenerateRequest(prompt_vec=None, max_tokens=max_tokens,
                           deadline=time.monotonic() + deadline_s,
                           prompt_tokens=list(prompt))


def _drive(ex, reqs, timeout=60.0):
    q = AdmissionQueue(max_depth=len(reqs) + 1)
    b = ContinuousBatcher(ex, q)
    for r in reqs:
        q.submit(r)
    b.start()
    try:
        for r in reqs:
            assert r.wait(timeout=timeout), "request lost"
    finally:
        b.stop()
    for r in reqs:
        assert r.error is None, r.error
    return [list(r.tokens) for r in reqs]


def _oracle_spec(k=4, accept_rate=0.7, seed=0):
    return SpecConfig(OracleDraft(k=k, accept_rate=accept_rate,
                                  vocab=VOCAB, target_seed=seed), k)


def _synth(spec=None, **kw):
    args = dict(slots=2, num_blocks=64, pipelined=spec is None)
    args.update(kw)
    return SyntheticKVExecutor(spec=spec, **args)


# The PR 7 invariance trace (test_kvcache.PROMPTS): a long prompt
# chunk-prefilled mid-run, a short one, a constant one, and the
# full-table 26-token edge.
PROMPTS = [list(np.arange(25) % 13), [3, 1, 4, 1, 5], [9] * 12,
           list(np.arange(26) % 13)]


# -- acceptance math + contracts ---------------------------------------------


def test_accept_length_is_longest_prefix_match():
    assert accept_length([1, 2, 3], [1, 2, 3, 9]) == 3
    assert accept_length([1, 2, 3], [1, 7, 3, 9]) == 1
    assert accept_length([5], [4, 4]) == 0
    assert accept_length([], [4]) == 0


def test_token_run_stops_at_first_pad():
    assert token_run([5, 0, 7, NO_TOKEN, 9]) == [5, 0, 7]
    assert token_run([NO_TOKEN, 3]) == []
    assert token_run(np.int32(4)) == [4]
    assert token_run(np.int32(NO_TOKEN)) == []


def test_clamp_spec_k_never_exceeds_reserved_pages():
    # owed = max_total - ctx - 1 tokens; drafting past owed-1 would
    # append KV beyond the admission-time worst case.
    assert clamp_spec_k(4, ctx=10, max_total=20, chunk=8) == 4
    assert clamp_spec_k(4, ctx=16, max_total=20, chunk=8) == 2
    assert clamp_spec_k(4, ctx=18, max_total=20, chunk=8) == 0
    assert clamp_spec_k(9, ctx=0, max_total=99, chunk=8) == 7  # window


def test_oracle_draft_is_deterministic_and_rate_controlled():
    d = OracleDraft(k=4, accept_rate=0.7, vocab=VOCAB, target_seed=0)
    last = np.arange(8, dtype=np.int32)
    ctx = np.arange(8, dtype=np.int32) * 3
    a, b = d.propose(last, ctx), d.propose(last, ctx)
    assert np.array_equal(a, b)
    # rate 1.0 is the exact oracle; rate 0.0 always misses its FIRST
    # proposal (later ones chain on the corrupted token — dead past
    # the first mismatch anyway, so acceptance is structurally 0).
    exact = OracleDraft(k=4, accept_rate=1.0, vocab=VOCAB,
                        target_seed=0).propose(last, ctx)
    never = OracleDraft(k=4, accept_rate=0.0, vocab=VOCAB,
                        target_seed=0).propose(last, ctx)
    for s in range(8):
        t = int(last[s])
        for j in range(4):
            want = synthetic_next_token(t, int(ctx[s]) + j, 0, VOCAB)
            assert int(exact[s, j]) == want
            if j == 0:
                assert int(never[s, j]) != want
            t = want


def test_spec_config_validates_k_and_loop_shape():
    with pytest.raises(ValueError, match="k must be >= 1"):
        SpecConfig(OracleDraft(k=1), 0)
    with pytest.raises(ValueError, match="draft proposes k=2"):
        SpecConfig(OracleDraft(k=2), 4)
    with pytest.raises(ValueError, match="prefill_chunk"):
        SyntheticKVExecutor(prefill_chunk=4, pipelined=False,
                            spec=_oracle_spec(k=4))
    # ISSUE 18: pipelined speculation is now a supported mode — the
    # executor composes spec with the plan-ahead loop natively.
    ex = SyntheticKVExecutor(pipelined=True, spec=_oracle_spec(k=4))
    assert ex.speculative and ex.pipelined
    ex.close()
    # What stays guarded: forcing the batcher's pipelined override
    # over an executor BUILT for the sync shape — its collect
    # discipline assumes one window in flight from a settled cursor.
    ex = SyntheticKVExecutor(pipelined=False, spec=_oracle_spec(k=4))
    with pytest.raises(ValueError, match="sync loop shape"):
        ContinuousBatcher(ex, AdmissionQueue(max_depth=2),
                          pipelined=True)
    ex.close()


# -- synthetic plane: byte-identical streams at every acceptance rate --------


@pytest.mark.parametrize("accept_rate", [0.0, 0.6, 1.0])
def test_synthetic_spec_streams_byte_identical_to_both_loop_shapes(
        accept_rate):
    """ISSUE 15 acceptance: speculative streams == non-speculative
    streams on the PR 7 invariance trace, against BOTH the sync and
    the pipelined one-token loops (the extended sync↔pipelined
    equivalence lane). Rate 0 exercises rollback on every verify
    step; rate 1 full-window acceptance; 0.6 the mixed regime."""
    golden = {}
    for pipelined in (False, True):
        ex = _synth(pipelined=pipelined)
        golden[pipelined] = _drive(
            ex, [_req(p, max_tokens=6) for p in PROMPTS])
        ex.allocator.assert_clean()
        ex.close()
    assert golden[False] == golden[True]

    ex = _synth(spec=_oracle_spec(accept_rate=accept_rate))
    streams = _drive(ex, [_req(p, max_tokens=6) for p in PROMPTS])
    st = ex.kv_stats()
    ex.allocator.assert_clean()
    ex.close()
    # The PR 7 counter contract carries to spec mode: accepted runs
    # are clamped to the request budget, so absent deadline
    # truncation the counter equals exactly what clients received.
    assert st["decode_tokens"] == sum(len(s) for s in streams)
    assert streams == golden[False], (streams, golden[False])
    assert any(len(set(s)) > 1 for s in streams), \
        "degenerate streams would make the equality vacuous"
    assert st["spec_verify_steps"] > 0
    if accept_rate == 0.0:
        assert st["spec_accepted_tokens"] == 0
        assert st["spec_tokens_per_step"] == 1.0
    if accept_rate == 1.0:
        assert st["spec_accepted_tokens"] == st["spec_proposed_tokens"]
        assert st["spec_tokens_per_step"] > 2.0


def test_spec_uses_strictly_fewer_steps_at_full_acceptance():
    """The throughput lever itself: same trace, same streams, fewer
    target-model steps — tokens-per-step > 1 is the whole point."""
    base = _synth(pipelined=False)
    _drive(base, [_req(p, max_tokens=8) for p in PROMPTS[:2]])
    base_steps = base._step_no
    base.allocator.assert_clean()
    base.close()

    ex = _synth(spec=_oracle_spec(accept_rate=1.0))
    _drive(ex, [_req(p, max_tokens=8) for p in PROMPTS[:2]])
    spec_steps = ex._step_no
    ex.allocator.assert_clean()
    ex.close()
    assert spec_steps < base_steps, (spec_steps, base_steps)


def test_spec_resume_reattaches_from_confirmed_watermark():
    """Kill-between-steps at the executor seam: a speculative
    executor reset mid-run re-attaches from SETTLED tokens (the
    confirmed watermark's durable shadow) and the resumed stream is
    byte-identical — accepted-but-uncollected draft positions never
    leak into the resume cursors."""
    prompt = list(np.arange(16) % 9)
    ref = _synth(spec=_oracle_spec(accept_rate=0.6), slots=1)
    (golden,) = _drive(ref, [_req(prompt, max_tokens=8)])
    ref.allocator.assert_clean()
    ref.close()

    ex = _synth(spec=_oracle_spec(accept_rate=0.6), slots=1)
    req = _req(prompt, max_tokens=8)
    ex.kv_attach(0, req)
    while len(req.tokens) < 3:            # part-way, then "die"
        runs = ex.collect(ex.submit((), gen=ex.kv_gen()))
        req.tokens.extend(token_run(runs[0]))
    ex.reset()
    assert req.kv_lease.resumable
    ex.kv_attach(0, req)
    assert ex.resumed_total == 1
    while len(req.tokens) < 8:
        runs = ex.collect(ex.submit((), gen=ex.kv_gen()))
        for t in token_run(runs[0]):
            if len(req.tokens) < 8:
                req.tokens.append(t)
    assert list(req.tokens) == golden
    ex.kv_release_slot(0)
    req.finish()
    ex.allocator.assert_clean()
    ex.close()


def test_spec_prefix_cache_hit_reproduces_uncached_stream():
    """The confirmed watermark bounds the cache insert in spec mode
    too: a second same-prefix request must hit the cache AND decode
    the identical stream."""
    prompt = list(np.arange(21) % 11)
    ex = _synth(spec=_oracle_spec(accept_rate=0.6))
    (first,) = _drive(ex, [_req(prompt, max_tokens=5)])
    hits0 = ex.prefix.hit_tokens
    req = _req(prompt, max_tokens=5)
    (second,) = _drive(ex, [req])
    assert second == first
    assert req.kv_lease.cached_tokens > 0
    assert ex.prefix.hit_tokens > hits0
    ex.allocator.assert_clean()
    ex.close()


# -- the real jitted plane: both kernels, fp32-exact ------------------------


def _paged(**kw):
    from dpu_operator_tpu.serving import PagedKVExecutor

    args = dict(slots=2, block_size=4, num_blocks=64,
                max_blocks_per_req=8, prefill_chunk=8, seed=0,
                pool_dtype="fp32", **MODEL)
    args.update(kw)
    return PagedKVExecutor(**args)


@pytest.mark.parametrize("kernel", ["xla", "pallas"])
def test_paged_spec_streams_byte_identical_both_kernels(kernel):
    """The real target model: speculative (truncated-stage draft —
    whatever acceptance the truncation earns, correctness must not
    depend on it) equals the sync one-token loop, byte-identical, on
    the XLA composition and the fused Pallas kernel (interpreter on
    CPU). fp32 pools: the exact lane (see module docstring)."""
    interp = True if kernel == "pallas" else None
    prompts = PROMPTS[:3] if kernel == "xla" else PROMPTS[:2]
    toks = 6 if kernel == "xla" else 4
    sync = _paged(mode="sync", kernel=kernel, interpret=interp)
    golden = _drive(sync, [_req(p, max_tokens=toks) for p in prompts])
    sync.allocator.assert_clean()

    spec = _paged(mode="speculative", spec_k=3, kernel=kernel,
                  interpret=interp)
    streams = _drive(spec, [_req(p, max_tokens=toks)
                            for p in prompts])
    st = spec.kv_stats()
    spec.allocator.assert_clean()
    assert streams == golden, (streams, golden)
    assert any(len(set(s)) > 1 for s in golden)
    assert st["spec_verify_steps"] > 0


def test_paged_spec_int8_is_deterministic_against_itself():
    """int8 residency under speculation: quantization groups differ
    from the one-token run by design (scale-once over a verify
    window's rows, rejected included), so the contract is
    DETERMINISM — two identical spec runs produce identical streams
    — not cross-mode byte-identity (the documented PR 13 carve-out)."""
    runs = []
    for _ in range(2):
        ex = _paged(mode="speculative", spec_k=3, pool_dtype="int8")
        runs.append(_drive(ex, [_req(p, max_tokens=5)
                                for p in PROMPTS[:2]]))
        ex.allocator.assert_clean()
    assert runs[0] == runs[1]


def test_truncated_draft_shares_target_token_space():
    from dpu_operator_tpu.serving.spec import TruncatedDraft

    ex = _paged(mode="speculative", spec_k=3)
    draft = ex.spec.draft
    assert isinstance(draft, TruncatedDraft)
    out = draft.propose(np.zeros(2, np.int32), np.zeros(2, np.int32))
    assert out.shape == (2, 3)
    assert (0 <= out).all() and (out < MODEL["vocab"]).all()


# -- ISSUE 18: pipelined speculation + tree drafts ---------------------------


def test_propose_full_extends_chain_by_one():
    """propose_full returns [S, k+1]: the k-chain plus the draft's
    prediction of the verify step's bonus token — the token the
    pipelined plan-ahead chains the NEXT window from."""
    d = OracleDraft(k=3, accept_rate=1.0, vocab=VOCAB, target_seed=0)
    last = np.array([7, 2], np.int32)
    ctx = np.array([10, 4], np.int32)
    pf = propose_full(d, last, ctx)
    assert pf.shape == (2, 4)
    assert np.array_equal(pf[:, :3], d.propose(last, ctx))
    # With the exact oracle the predicted bonus IS the true chain:
    for s in range(2):
        t = int(last[s])
        for j in range(4):
            t = synthetic_next_token(t, int(ctx[s]) + j, 0, VOCAB)
            if j == 3:
                assert int(pf[s, j]) == t


def test_accept_tree_paths():
    # trunk partial accept: identical to accept_length + bonus
    assert accept_tree([5, 6, 7], [9, 4], [5, 6, 8, 1],
                       [0, 0]) == ([5, 6, 8], -1)
    # trunk miss, no sibling matches: single corrected token
    assert accept_tree([5, 6, 7], [9, 4], [3, 6, 8, 1],
                       [0, 7]) == ([3], -1)
    # trunk miss, sibling 1 == true first token: two tokens via the
    # side branch (the sibling's own verify output is its bonus)
    assert accept_tree([5, 6, 7], [9, 3], [3, 6, 8, 1],
                       [0, 7]) == ([3, 7], 1)
    # trunk accepts >= 1 token: trunk wins even if a sib also matches
    assert accept_tree([5, 6, 7], [5, 3], [5, 6, 8, 1],
                       [0, 7]) == ([5, 6, 8], -1)
    # no siblings proposed degrades to the chain contract
    assert accept_tree([5], [], [4, 2], []) == ([4], -1)


def test_oracle_draft_sibling_proposals():
    """sib_rate=1.0 with accept_rate=0.0: the trunk always misses its
    first token and sibling 0 always carries the true one — the tree
    rescues exactly one extra token per window."""
    d = OracleDraft(k=4, accept_rate=0.0, vocab=VOCAB, target_seed=0,
                    tree_width=3, sib_rate=1.0)
    last = np.arange(6, dtype=np.int32)
    ctx = np.arange(6, dtype=np.int32) * 2
    sibs = d.propose_sibs(last, ctx)
    assert sibs.shape == (6, 2)
    trunk = d.propose(last, ctx)
    for s in range(6):
        true0 = synthetic_next_token(int(last[s]), int(ctx[s]), 0,
                                     VOCAB)
        assert int(trunk[s, 0]) != true0        # trunk misses
        assert int(sibs[s, 0]) == true0         # sib 0 rescues
        assert int(sibs[s, 1]) != true0         # later sibs distinct


def test_spec_config_tree_and_adaptive_dials():
    d = OracleDraft(k=6, accept_rate=0.5, vocab=VOCAB, tree_width=2)
    cfg = SpecConfig(d, 6, adaptive=True, k_min=2)
    assert cfg.k_for(1.0) == 6 and cfg.k_for(0.0) == 2
    ks = [cfg.k_for(e) for e in np.linspace(0, 1, 11)]
    assert ks == sorted(ks)                     # monotone dial
    # high acceptance collapses the tree to a chain (siblings only
    # pay off when the trunk's first token is at risk)
    assert cfg.width_for(0.95) == 1
    assert cfg.width_for(0.5) == 2
    fixed = SpecConfig(OracleDraft(k=6, vocab=VOCAB), 6)
    assert fixed.k_for(0.0) == 6                # non-adaptive: fixed
    with pytest.raises(ValueError, match="tree_width"):
        SpecConfig(OracleDraft(k=4, vocab=VOCAB), 4, tree_width=0)

    class _ChainOnly:                   # a draft with no sibling hook
        k = 4

        def propose(self, last, ctx):
            return np.zeros((len(last), 4), np.int32)

    with pytest.raises(ValueError, match="propose_sibs"):
        SpecConfig(_ChainOnly(), 4, tree_width=2)
    with pytest.raises(ValueError, match="k_min"):
        SpecConfig(OracleDraft(k=4, vocab=VOCAB), 4, adaptive=True,
                   k_min=9)


def _manual_steps(ex, req, n_steps):
    for _ in range(n_steps):
        runs = ex.collect(ex.submit((), gen=ex.kv_gen()))
        req.tokens.extend(token_run(runs[0]))


def test_adaptive_dial_converges_both_directions():
    """Satellite: the per-slot accept-rate EWMA dials k down on a
    cold slot and back up on a hot one."""
    # Down: rate-0 draft, EWMA decays 1.0 -> ~0 and k hits k_min.
    cfg = SpecConfig(OracleDraft(k=4, accept_rate=0.0, vocab=VOCAB),
                     4, adaptive=True, k_min=1)
    ex = _synth(spec=cfg, slots=1, pipelined=False)
    req = _req(list(np.arange(12) % 7), max_tokens=40)
    ex.kv_attach(0, req)
    _manual_steps(ex, req, 10)
    st = ex._states[0]
    assert st.spec_ewma < 0.1
    assert cfg.k_for(st.spec_ewma) == 1
    ex.kv_release_slot(0, cache=False)
    ex.close()

    # Up: exact draft but a pessimistic prior — EWMA recovers.
    cfg = SpecConfig(OracleDraft(k=4, accept_rate=1.0, vocab=VOCAB),
                     4, adaptive=True, k_min=1)
    ex = _synth(spec=cfg, slots=1, pipelined=False)
    req = _req(list(np.arange(12) % 7), max_tokens=50)
    ex.kv_attach(0, req)
    ex._states[0].spec_ewma = 0.05
    _manual_steps(ex, req, 10)
    st = ex._states[0]
    assert st.spec_ewma > 0.8
    assert cfg.k_for(st.spec_ewma) == 4
    ex.kv_release_slot(0, cache=False)
    ex.close()


@pytest.mark.parametrize("accept_rate", [0.0, 0.6, 1.0])
def test_synthetic_pipelined_spec_matrix_byte_identical(accept_rate):
    """ISSUE 18 acceptance: the full equivalence matrix on the
    synthetic plane — pipelined-spec vs sync-spec vs the one-token
    loop, byte-identical at every acceptance rate. Rate 0 forces a
    plan-ahead rollback + re-plan on nearly every window; rate 1
    keeps the plan-ahead chain unbroken (zero re-plans)."""
    base = _synth(pipelined=False)
    golden = _drive(base, [_req(p, max_tokens=6) for p in PROMPTS])
    base.allocator.assert_clean()
    base.close()

    streams = {}
    stats = {}
    for pipelined in (False, True):
        ex = _synth(spec=_oracle_spec(accept_rate=accept_rate),
                    pipelined=pipelined)
        streams[pipelined] = _drive(
            ex, [_req(p, max_tokens=6) for p in PROMPTS])
        stats[pipelined] = ex.kv_stats()
        ex.allocator.assert_clean()
        ex.close()
    assert streams[False] == golden
    assert streams[True] == golden, (streams[True], golden)
    assert any(len(set(s)) > 1 for s in golden)
    st = stats[True]
    assert st["spec_pipeline_peak"] >= 2     # overlap actually happened
    assert st["spec_pipeline_depth"] == 0    # drained at stop
    if accept_rate == 0.0:
        assert st["spec_replans"] > 0        # every miss re-plans
    if accept_rate == 1.0:
        assert st["spec_replans"] == 0       # chain never breaks


@pytest.mark.parametrize("accept_rate", [0.0, 0.5, 1.0])
def test_synthetic_tree_spec_byte_identical_and_rescues(accept_rate):
    """Tree drafts on the synthetic plane: streams stay byte-identical
    and, at low trunk acceptance with a hot sibling, the side branch
    rescues windows the chain would lose (path_len 2 entries)."""
    base = _synth(pipelined=False)
    golden = _drive(base, [_req(p, max_tokens=6) for p in PROMPTS])
    base.allocator.assert_clean()
    base.close()

    d = OracleDraft(k=4, accept_rate=accept_rate, vocab=VOCAB,
                    target_seed=0, tree_width=3, sib_rate=1.0)
    ex = _synth(spec=SpecConfig(d, 4), pipelined=True)
    streams = _drive(ex, [_req(p, max_tokens=6) for p in PROMPTS])
    st = ex.kv_stats()
    ex.allocator.assert_clean()
    ex.close()
    assert streams == golden, (streams, golden)
    if accept_rate == 0.0:
        # every window: trunk misses, sibling 0 carries the truth
        assert st["spec_path_len"].get(2, 0) > 0
        assert st["spec_tokens_per_step"] > 1.0


def test_tree_sibling_repair_row_closes_the_kv_hole():
    """After a sibling acceptance the trunk's wrong token sits
    appended at the accepted position — the next window's repair row
    must overwrite it, or every later decode attends to stale KV.
    Long generation after many sibling accepts proves the repair."""
    base = _synth(pipelined=False, slots=1)
    (golden,) = _drive(base, [_req([3, 1, 4, 1, 5], max_tokens=24)])
    base.allocator.assert_clean()
    base.close()

    d = OracleDraft(k=3, accept_rate=0.0, vocab=VOCAB, target_seed=0,
                    tree_width=2, sib_rate=1.0)
    ex = _synth(spec=SpecConfig(d, 3), pipelined=True, slots=1)
    (stream,) = _drive(ex, [_req([3, 1, 4, 1, 5], max_tokens=24)])
    st = ex.kv_stats()
    ex.allocator.assert_clean()
    ex.close()
    assert stream == golden, (stream, golden)
    assert st["spec_path_len"].get(2, 0) >= 8


@pytest.mark.parametrize("kernel", ["xla", "pallas"])
def test_paged_pipelined_spec_streams_byte_identical(kernel):
    """The real jitted plane, both kernels: mode
    \"speculative-pipelined\" (plan-ahead draft + device-chained base
    row) equals the sync one-token loop byte-for-byte. fp32 pools —
    the exact lane."""
    interp = True if kernel == "pallas" else None
    prompts = PROMPTS[:2]
    toks = 5 if kernel == "xla" else 4
    sync = _paged(mode="sync", kernel=kernel, interpret=interp)
    golden = _drive(sync, [_req(p, max_tokens=toks) for p in prompts])
    sync.allocator.assert_clean()
    sync.close()

    spec = _paged(mode="speculative-pipelined", spec_k=3,
                  kernel=kernel, interpret=interp)
    streams = _drive(spec, [_req(p, max_tokens=toks)
                            for p in prompts])
    st = spec.kv_stats()
    spec.allocator.assert_clean()
    spec.close()
    assert streams == golden, (streams, golden)
    assert any(len(set(s)) > 1 for s in golden)
    assert st["spec_verify_steps"] > 0
    assert st["spec_pipeline_peak"] >= 2


def test_paged_tree_spec_streams_byte_identical():
    """Tree verify on the real model: the XLA tree-mask executable
    (score-only sibling rows, strict plim on the shared position)
    produces byte-identical streams. TruncatedDraft's top-k siblings
    supply the side branches. Pallas falls back to the same XLA
    composition for tree windows, so one kernel lane suffices."""
    sync = _paged(mode="sync")
    golden = _drive(sync, [_req(p, max_tokens=5) for p in PROMPTS[:2]])
    sync.allocator.assert_clean()
    sync.close()

    spec = _paged(mode="speculative-pipelined", spec_k=3,
                  spec_tree_width=3)
    streams = _drive(spec, [_req(p, max_tokens=5)
                            for p in PROMPTS[:2]])
    st = spec.kv_stats()
    spec.allocator.assert_clean()
    spec.close()
    assert streams == golden, (streams, golden)
    assert st["spec_verify_steps"] > 0


def test_truncated_draft_sibling_ranks():
    from dpu_operator_tpu.serving.spec import TruncatedDraft

    ex = _paged(mode="speculative", spec_k=3, spec_tree_width=3)
    draft = ex.spec.draft
    assert isinstance(draft, TruncatedDraft)
    assert draft.tree_width == 3
    last = np.zeros(2, np.int32)
    ctx = np.zeros(2, np.int32)
    sibs = draft.propose_sibs(last, ctx)
    trunk = draft.propose(last, ctx)
    assert sibs.shape == (2, 2)
    assert (0 <= sibs).all() and (sibs < MODEL["vocab"]).all()
    for s in range(2):                          # ranks 2..W: disjoint
        assert int(trunk[s, 0]) not in set(int(x) for x in sibs[s])
    ex.close()


def test_pipelined_spec_resume_reattaches_from_confirmed_watermark():
    """Kill with a plan-ahead window in flight: reset() drops the
    uncollected window, re-attach replays only SETTLED tokens, and
    the resumed stream is byte-identical."""
    prompt = list(np.arange(16) % 9)
    ref = _synth(spec=_oracle_spec(accept_rate=0.6), slots=1,
                 pipelined=True)
    (golden,) = _drive(ref, [_req(prompt, max_tokens=8)])
    ref.allocator.assert_clean()
    ref.close()

    ex = _synth(spec=_oracle_spec(accept_rate=0.6), slots=1,
                pipelined=True)
    req = _req(prompt, max_tokens=8)
    ex.kv_attach(0, req)
    # pipelined shape: keep one window in flight, then "die" with it
    pending = ex.submit((), gen=ex.kv_gen())
    while len(req.tokens) < 3:
        nxt = ex.submit((), gen=ex.kv_gen())
        runs = ex.collect(pending)
        req.tokens.extend(token_run(runs[0]))
        pending = nxt
    ex.reset()                      # in-flight window dies with us
    assert req.kv_lease.resumable
    ex.kv_attach(0, req)
    assert ex.resumed_total == 1
    while len(req.tokens) < 8:
        runs = ex.collect(ex.submit((), gen=ex.kv_gen()))
        for t in token_run(runs[0]):
            if len(req.tokens) < 8:
                req.tokens.append(t)
    assert list(req.tokens) == golden
    ex.kv_release_slot(0)
    req.finish()
    ex.allocator.assert_clean()
    ex.close()


# -- /metrics exposition -----------------------------------------------------


def test_metrics_exposition_of_spec_series():
    """Satellite: the speculative series appear in a real /metrics
    scrape — proposed/accepted counters with real values plus the
    scrape-time acceptance and tokens-per-step gauges."""
    import json
    import urllib.request

    from dpu_operator_tpu.serving import ServingServer

    ex = SyntheticKVExecutor(slots=2, num_blocks=64, pipelined=False,
                             spec=_oracle_spec(accept_rate=1.0))
    srv = ServingServer([ex]).start()
    try:
        body = json.dumps({"prompt_tokens": list(range(1, 10)),
                           "max_tokens": 6,
                           "deadline_ms": 10000}).encode()
        for _ in range(2):
            urllib.request.urlopen(
                urllib.request.Request(srv.url + "/v1/generate",
                                       data=body), timeout=10).read()
        text = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=5).read().decode()
    finally:
        srv.stop()
    for series in ("serving_spec_proposed_tokens_total",
                   "serving_spec_accepted_tokens_total",
                   "serving_spec_accept_rate",
                   "serving_spec_tokens_per_step"):
        assert series in text, series
    acc = [l for l in text.splitlines()
           if l.startswith("serving_spec_accepted_tokens_total")]
    rate = [l for l in text.splitlines()
            if l.startswith("serving_spec_accept_rate")]
    assert float(acc[0].split()[-1]) > 0        # oracle at rate 1.0
    assert float(rate[0].split()[-1]) == 1.0
    ex.allocator.assert_clean()
    ex.close()


def test_metrics_exposition_of_pipelined_spec_series():
    """ISSUE 18 satellite: re-plan counter, tree path-length
    histogram and pipeline-depth gauges appear in a live /metrics
    scrape of a pipelined tree-speculative replica. accept_rate 0 +
    sib_rate 1 forces re-plans AND sibling paths every window, so
    both new series carry non-trivial values."""
    import json
    import urllib.request

    from dpu_operator_tpu.serving import ServingServer

    d = OracleDraft(k=4, accept_rate=0.0, vocab=VOCAB, target_seed=0,
                    tree_width=2, sib_rate=1.0)
    ex = SyntheticKVExecutor(slots=2, num_blocks=64, pipelined=True,
                             spec=SpecConfig(d, 4))
    srv = ServingServer([ex]).start()
    try:
        body = json.dumps({"prompt_tokens": list(range(1, 10)),
                           "max_tokens": 8,
                           "deadline_ms": 10000}).encode()
        for _ in range(2):
            urllib.request.urlopen(
                urllib.request.Request(srv.url + "/v1/generate",
                                       data=body), timeout=10).read()
        text = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=5).read().decode()
    finally:
        srv.stop()
    for series in ("serving_spec_replans_total",
                   "serving_spec_pipeline_depth",
                   "serving_spec_pipeline_peak",
                   "serving_spec_tree_path_len_bucket"):
        assert series in text, series
    lines = text.splitlines()
    replans = [l for l in lines
               if l.startswith("serving_spec_replans_total")]
    assert float(replans[0].split()[-1]) > 0    # rate 0 re-plans
    peak = [l for l in lines
            if l.startswith("serving_spec_pipeline_peak")]
    assert float(peak[0].split()[-1]) >= 2      # overlap happened
    depth = [l for l in lines
             if l.startswith("serving_spec_pipeline_depth")]
    assert float(depth[0].split()[-1]) == 0     # drained at scrape
    # histogram: the sib-rescued two-token paths land in le="2.0"
    cnt = [l for l in lines
           if l.startswith("serving_spec_tree_path_len_count")]
    assert float(cnt[0].split()[-1]) > 0
    ex.allocator.assert_clean()
    ex.close()
