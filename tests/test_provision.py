"""Provisioning driver: the cda.py-analogue plans stay executable.

The reference drives cluster-deployment-automation from
taskfiles/clusters.yaml over hack/cluster-configs/*.yaml; our
scripts/provision.py expands the same-shaped configs into ordered command
plans. These tests pin the plan structure (CI catches config drift
without cloud access — the dry-run IS the testable surface)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _plan(config: str) -> dict:
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "provision.py"),
         os.path.join(REPO, "hack", "cluster-configs", config),
         "--dry-run", "--json"],
        capture_output=True, text=True, check=True,
    )
    return json.loads(r.stdout)


def test_one_cluster_plan():
    plan = _plan("config-1-cluster.yaml")
    descs = [s["desc"] for s in plan["steps"]]
    joined = "\n".join(descs)
    # Slice creation → k3s server → token → agent joins → kubeconfig →
    # labels → operator deploy → e2e → traffic tests, in that order.
    assert "create TPU slice" in descs[0]
    assert descs.index("bootstrap k3s server on worker 0") < descs.index(
        "join worker 1 as k3s agent"
    )
    assert "label tpu-dpu-1c nodes for operator opt-in" in joined
    assert "deploy operator" in joined
    assert joined.index("deploy operator") < joined.index("e2e")

    # The slice creation step is a complete gcloud command.
    create = plan["steps"][0]["argv"]
    assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "--accelerator-type" in create
    assert create[create.index("--accelerator-type") + 1] == "v5litepod-8"

    # Join steps consume captured state from earlier steps.
    join = next(s for s in plan["steps"] if s["desc"].startswith("join worker"))
    cmd = " ".join(join["argv"])
    assert "{{captured.tpu_dpu_1c_token}}" in cmd
    assert "{{captured.tpu_dpu_1c_internal_ip}}" in cmd
    captures = {s.get("capture") for s in plan["steps"]}
    assert {"tpu_dpu_1c_token", "tpu_dpu_1c_internal_ip",
            "tpu_dpu_1c_external_ip", "tpu_dpu_1c_kubeconfig"} <= captures
    # Local kubectl must point at the EXTERNAL address, not the VPC one.
    write = next(s for s in plan["steps"] if "write kubeconfig" in s["desc"])
    assert "{{captured.tpu_dpu_1c_external_ip}}" in " ".join(write["argv"])

    # Node labels come from the config.
    label = next(s for s in plan["steps"] if "label" in s["desc"])
    assert "dpu=true" in label["argv"]


def test_two_cluster_plan():
    plan = _plan("config-2-cluster.yaml")
    joined = "\n".join(s["desc"] for s in plan["steps"])
    # Host cluster is plain VMs; TPU cluster is a slice; both labelled.
    assert "create host VM host-cluster-worker-0" in joined
    assert "create TPU slice" in joined
    assert joined.count("label") >= 2
    # Host-side workers beyond 0 would join as agents (count:1 here, so
    # just assert the kubeconfig materializes for BOTH clusters).
    kubeconfig_writes = [d for d in joined.splitlines() if "write kubeconfig" in d]
    assert len(kubeconfig_writes) == 2
    # Both gcloud families carry an explicit --project.
    for s_ in plan["steps"]:
        if s_["argv"][0] == "gcloud":
            assert "--project" in s_["argv"], s_


def test_plan_run_executes_with_capture_substitution(tmp_path, capsys):
    """Plan.run's REAL execution branch (round-2 verdict Weak #6: it had
    only ever dry-run): stub argv proves steps execute in order, captured
    stdout substitutes into later steps, secrets stay out of the printed
    plan (unsubstituted argv), and a failing step propagates its rc and
    stops the plan."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from provision import Plan
    finally:
        sys.path.pop(0)

    marker = tmp_path / "out.txt"
    plan = Plan()
    plan.add("capture a token", ["/bin/echo", "sekret-tok"], capture="token")
    plan.add(
        "use the token",
        ["/bin/sh", "-c", f"echo got={{{{captured.token}}}} > {marker}"],
    )
    assert plan.run(dry_run=False) == 0
    assert marker.read_text().strip() == "got=sekret-tok"
    # The printed plan shows the UNsubstituted argv: captured values
    # (join tokens, kubeconfigs) never land in CI logs through later
    # steps' command lines.
    out_lines = capsys.readouterr().out.splitlines()
    use_line = next(ln for ln in out_lines if "use the token" in ln)
    assert "{{captured.token}}" in use_line
    assert "got=sekret-tok" not in use_line

    # Unresolved capture references stay literal (no KeyError, no empty
    # substitution hiding a wiring bug).
    plan2 = Plan()
    plan2.add("echo literal", ["/bin/echo", "{{captured.missing}}"], capture="x")
    assert plan2.run(dry_run=False) == 0

    # Failure propagation: rc surfaces and later steps never run.
    plan3 = Plan()
    plan3.add("fail", ["/bin/sh", "-c", "exit 7"])
    plan3.add("never", ["/bin/sh", "-c", f"echo no >> {marker}"])
    assert plan3.run(dry_run=False) == 7
    assert marker.read_text().strip() == "got=sekret-tok"


def test_execute_refuses_without_project(tmp_path):
    env = {k: v for k, v in os.environ.items() if k != "GCP_PROJECT"}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "provision.py"),
         os.path.join(REPO, "hack", "cluster-configs", "config-1-cluster.yaml")],
        capture_output=True, text=True, env=env,
    )
    assert r.returncode == 2
    assert "refusing to execute" in r.stderr
