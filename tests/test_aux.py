"""Auxiliary subsystems: drain (reference pkgs/drain), metrics exposition
(controller-runtime prometheus equivalent), fabric-ctl CLI (p4rt-ctl
analogue)."""

import json
import socket as socketlib
import urllib.request

import pytest

from dpu_operator_tpu import vars as v
from dpu_operator_tpu.drain import Drainer
from dpu_operator_tpu.k8s import InMemoryClient, InMemoryCluster
from dpu_operator_tpu.utils.metrics import MetricsServer, Registry


def free_port() -> int:
    with socketlib.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- drain --------------------------------------------------------------------


@pytest.fixture
def client():
    c = InMemoryClient(InMemoryCluster())
    c.create({"apiVersion": "v1", "kind": "Node", "metadata": {"name": "n1"}})
    return c


def _pod(name, node, requests=None):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "nodeName": node,
            "containers": [
                {"name": "c", "image": "img", "resources": {"requests": requests or {}}}
            ],
        },
    }


def test_drain_cordons_and_evicts_fabric_pods(client):
    client.create(_pod("fabric-pod", "n1", {v.DPU_RESOURCE_NAME: "2"}))
    client.create(_pod("plain-pod", "n1"))
    d = Drainer(client)
    assert d.drain_node("n1") is True
    node = client.get("v1", "Node", None, "n1")
    assert node["spec"]["unschedulable"] is True
    assert client.get_or_none("v1", "Pod", "default", "fabric-pod") is None
    # Non-fabric pods stay.
    assert client.get_or_none("v1", "Pod", "default", "plain-pod") is not None
    assert d.complete_drain_node("n1") is True
    assert client.get("v1", "Node", None, "n1")["spec"]["unschedulable"] is False


def test_drain_respects_no_evict_unless_forced(client):
    pod = _pod("precious", "n1", {v.DPU_RESOURCE_NAME: "1"})
    pod["metadata"]["annotations"] = {"dpu.tpu.io/no-evict": "true"}
    client.create(pod)
    d = Drainer(client)
    assert d.drain_node("n1") is False
    assert client.get_or_none("v1", "Pod", "default", "precious") is not None
    assert d.drain_node("n1", force=True) is True
    assert client.get_or_none("v1", "Pod", "default", "precious") is None


# -- metrics ------------------------------------------------------------------


def test_registry_renders_prometheus_text():
    r = Registry()
    r.counter_inc("dpu_cni_requests_total", {"command": "ADD", "result": "ok"},
                  help="reqs")
    r.counter_inc("dpu_cni_requests_total", {"command": "ADD", "result": "ok"})
    r.gauge_set("dpu_daemon_managed_dpus", 1)
    r.observe("dpu_cni_request_seconds", 0.004, {"command": "ADD"})
    text = r.render()
    assert '# TYPE dpu_cni_requests_total counter' in text
    assert 'dpu_cni_requests_total{command="ADD",result="ok"} 2.0' in text
    assert "dpu_daemon_managed_dpus 1" in text
    assert 'dpu_cni_request_seconds_bucket{command="ADD",le="0.005"} 1' in text
    assert 'dpu_cni_request_seconds_count{command="ADD"} 1' in text


def test_registry_render_exact_custom_buckets_and_escaping():
    """The full exposition text, byte for byte: HELP/TYPE ordering,
    label-value escaping (backslash, quote, newline — the three the
    Prometheus text format mandates), per-metric custom buckets with
    bounds rendered str(float)-style (le="1.0" — the spelling the
    PRE-EXISTING histogram series already scrape under; le is a
    series-identity label, so it must never change), cumulative bucket
    counts, sum and count."""
    r = Registry()
    r.counter_inc("req_total", {"path": 'a"b\\c\nd'}, help="requests")
    r.gauge_set("depth", 2)
    r.observe("lat_seconds", 0.25, {"replica": "r0"}, help="latency",
              buckets=(0.5, 1.0))
    r.observe("lat_seconds", 0.5, {"replica": "r0"})
    r.observe("lat_seconds", 2.0, {"replica": "r0"})
    assert r.render() == (
        "# HELP req_total requests\n"
        "# TYPE req_total counter\n"
        'req_total{path="a\\"b\\\\c\\nd"} 1.0\n'
        "# TYPE depth gauge\n"
        "depth 2\n"
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{replica="r0",le="0.5"} 2\n'
        'lat_seconds_bucket{replica="r0",le="1.0"} 2\n'
        'lat_seconds_bucket{replica="r0",le="+Inf"} 3\n'
        'lat_seconds_sum{replica="r0"} 2.75\n'
        'lat_seconds_count{replica="r0"} 3\n'
    )


def test_registry_quantile_estimator():
    """quantile() — histogram_quantile's estimate, in-process: linear
    interpolation inside the containing bucket, implicit 0 lower bound
    on the first, clamp to the last finite bound for the +Inf bucket,
    None for series with no data."""
    r = Registry()
    assert r.quantile("missing", 0.99) is None
    for v in (0.25, 0.5, 2.0):
        r.observe("lat", v, {"replica": "r0"}, buckets=(0.5, 1.0))
    # count=3: q=0.5 → target 1.5 of the 2 in (0, 0.5] → 0.375.
    assert r.quantile("lat", 0.5, {"replica": "r0"}) == pytest.approx(0.375)
    # q=0.99 → target 2.97 falls past the last finite bucket → clamp.
    assert r.quantile("lat", 0.99, {"replica": "r0"}) == pytest.approx(1.0)
    # Exact bucket edge: q such that target == cumulative count.
    assert r.quantile("lat", 2 / 3, {"replica": "r0"}) == pytest.approx(0.5)
    # Default buckets still work and label-less series resolve.
    r.observe("plain", 0.003)
    est = r.quantile("plain", 0.5)
    assert 0.001 < est <= 0.005
    with pytest.raises(ValueError):
        r.quantile("lat", 0.0)
    # +Inf is implicit (render appends it from count); explicit inf/NaN
    # or unsorted bounds would corrupt le= formatting and interpolation.
    for bad in ((0.5, float("inf")), (float("nan"),), (1.0, 0.5),
                (0.5, 0.5)):
        with pytest.raises(ValueError, match="buckets"):
            r.observe("bad_hist", 0.1, buckets=bad)
    # Re-registering with a CONFLICTING spec is loud (call-order bugs);
    # repeating the same spec — the hot observe path — is fine.
    r.observe("lat", 0.3, {"replica": "r0"}, buckets=(0.5, 1.0))
    with pytest.raises(ValueError, match="conflicting"):
        r.observe("lat", 0.3, {"replica": "r0"}, buckets=(0.25, 1.0))


def test_metrics_server_serves_http():
    r = Registry()
    r.counter_inc("x_total", help="x")
    srv = MetricsServer(registry=r, port=0)
    srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics"
        ).read().decode()
        assert "x_total 1.0" in body
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz"
        ).read()
        assert health == b"ok"
    finally:
        srv.stop()


def test_metrics_server_bearer_auth():
    """With a token configured, /metrics is 401 without the right
    Authorization header; /healthz stays open (reference authn/authz
    filter, cmd/main.go:82-86)."""
    r = Registry()
    r.counter_inc("x_total", help="x")
    srv = MetricsServer(registry=r, port=0, auth_token="s3cret")
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/metrics")
        assert ei.value.code == 401

        with pytest.raises(urllib.error.HTTPError) as ei:
            req = urllib.request.Request(
                f"{base}/metrics", headers={"Authorization": "Bearer wrong"}
            )
            urllib.request.urlopen(req)
        assert ei.value.code == 401

        req = urllib.request.Request(
            f"{base}/metrics", headers={"Authorization": "Bearer s3cret"}
        )
        assert "x_total 1.0" in urllib.request.urlopen(req).read().decode()
        assert urllib.request.urlopen(f"{base}/healthz").read() == b"ok"
    finally:
        srv.stop()


def test_cni_requests_counted_through_server(tmp_root):
    """The CNI server increments dpu_cni_requests_total on handled calls."""
    from dpu_operator_tpu.cni import CniRequest, CniServer, do_cni
    from dpu_operator_tpu.utils.metrics import default_registry

    server = CniServer(tmp_root)
    server.set_handlers(lambda req: {"ok": True}, lambda req: {})
    server.start()
    try:
        do_cni(server.socket_path, CniRequest(
            command="ADD", container_id="m" * 12, netns="/proc/self/ns/net",
            ifname="net1", config={"cniVersion": "1.0.0", "name": "n", "type": "dpu-cni"},
        ))
        text = default_registry.render()
        assert 'dpu_cni_requests_total{command="ADD",result="ok"}' in text
    finally:
        server.stop()


# -- fabric-ctl ---------------------------------------------------------------


def test_fabric_ctl_devices_and_ping(tmp_root, capsys):
    from dpu_operator_tpu.fabric_ctl import main as fabric_ctl
    from dpu_operator_tpu.vsp import MockVsp, VspServer

    vsp = MockVsp(opi_port=free_port())
    server = VspServer(vsp, tmp_root)
    server.start()
    try:
        sock = tmp_root.vendor_plugin_socket()
        assert fabric_ctl(["--socket", sock, "devices"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert len(out) == 4
        assert all(d["health"] == "HEALTHY" for d in out.values())

        assert fabric_ctl(["--socket", sock, "ping"]) == 0
        assert json.loads(capsys.readouterr().out)["healthy"] is True

        assert fabric_ctl(["--socket", sock, "set-endpoints", "6"]) == 0
        assert json.loads(capsys.readouterr().out)["count"] == 6

        assert fabric_ctl(
            ["--socket", sock, "add-port", "p0", "02:00:00:00:00:01"]
        ) == 0
        assert json.loads(capsys.readouterr().out)["created"] == "p0"
        assert vsp.bridge_ports == ["p0"]

        assert fabric_ctl(["--socket", sock, "del-port", "p0"]) == 0
        capsys.readouterr()
        assert vsp.bridge_ports == []
    finally:
        server.stop()


def test_fabric_ctl_add_nf_attributes_degradations(tmp_root, capsys):
    """add-nf diffs Ping.degradations across the call, but only blames
    this chain for reasons tagged with ITS [nf:in->out] key — a racing
    attach's baseline failure on another port must not turn a clean
    chain-add into rc 1 (it is still surfaced, as unrelated)."""
    from dpu_operator_tpu.fabric_ctl import main as fabric_ctl
    from dpu_operator_tpu.vsp import MockVsp, VspServer

    mac0, mac1 = "02:00:00:00:00:0a", "02:00:00:00:00:0b"

    class RacingVsp(MockVsp):
        inject: str = ""

        def CreateNetworkFunction(self, request, context):
            if self.inject:
                self.degradations.append(self.inject)
            return super().CreateNetworkFunction(request, context)

    vsp = RacingVsp(opi_port=free_port())
    server = VspServer(vsp, tmp_root)
    server.start()
    try:
        sock = tmp_root.vendor_plugin_socket()
        # Unrelated degradation arises mid-call: NOT this add's fault.
        vsp.inject = "[baseline:ep7] baseline flow rule on ep7 failed: enoent"
        assert fabric_ctl(["--socket", sock, "add-nf", mac0, mac1]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["unrelated_degradations"], out
        # This chain's own key in a new reason: fail loudly.
        vsp.degradations = []
        vsp.inject = f"[nf:{mac0}->{mac1}] NF flow programming failed: boom"
        assert fabric_ctl(["--socket", sock, "add-nf", mac0, mac1]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["degraded"] and not out["unrelated_degradations"], out
        # Attribution survives MAC-format normalization (ADVICE r5 #4):
        # operator typed uppercase, VSP canonicalized to lowercase — a
        # genuine chain failure must still be blamed on this chain.
        vsp.degradations = []
        vsp.inject = f"[nf:{mac0}->{mac1}] NF flow programming failed: boom"
        assert fabric_ctl(["--socket", sock, "add-nf",
                           mac0.upper(), mac1.upper()]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["degraded"] and not out["unrelated_degradations"], out
    finally:
        server.stop()


def test_fabric_ctl_topology(capsys, monkeypatch):
    from dpu_operator_tpu.fabric_ctl import main as fabric_ctl

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-8")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    assert fabric_ctl(["topology"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["numChips"] == 8
    assert out["bisectionGbps"] > 0


# -- daemon drain wiring ------------------------------------------------------


def test_daemon_drains_before_setup(client, tmp_root):
    """drain_on_setup=True: fabric pods evicted before SetNumEndpoints,
    node uncordoned after."""
    import time

    from dpu_operator_tpu.daemon import Daemon
    from dpu_operator_tpu.platform import FakePlatform
    from dpu_operator_tpu.vsp import MockVsp, VspServer

    client.create(
        {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "tpu-node-0"}}
    )
    client.create(_pod("victim", "tpu-node-0", {v.DPU_RESOURCE_NAME: "1"}))
    vsp = MockVsp(opi_port=free_port())
    server = VspServer(vsp, tmp_root)
    server.start()
    daemon = Daemon(
        client,
        FakePlatform(
            product="Google Cloud TPU",
            node="tpu-node-0",
            env={"TPU_ACCELERATOR_TYPE": "v5litepod-8", "TPU_WORKER_ID": "0"},
        ),
        path_manager=tmp_root,
        tick_interval=0.05,
        register_device_plugin=False,
        drain_on_setup=True,
    )
    daemon.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if client.get_or_none("v1", "Pod", "default", "victim") is None:
                break
            time.sleep(0.05)
        assert client.get_or_none("v1", "Pod", "default", "victim") is None
        # Node ends uncordoned.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            node = client.get("v1", "Node", None, "tpu-node-0")
            if not node.get("spec", {}).get("unschedulable"):
                break
            time.sleep(0.05)
        assert not client.get("v1", "Node", None, "tpu-node-0")["spec"].get("unschedulable")
    finally:
        daemon.stop()
        server.stop()


# -- cni file logger ----------------------------------------------------------


def test_cnilogging_request_context(tmp_path, monkeypatch):
    """Per-request context prefix + file output (reference
    dpu-cni/pkgs/cnilogging/cnilogging.go:26-86)."""
    import importlib

    from dpu_operator_tpu.cni import cnilogging

    log_file = str(tmp_path / "cni.log")
    monkeypatch.setenv("DPU_CNI_LOG_FILE", log_file)
    importlib.reload(cnilogging)
    rlog = cnilogging.for_request("abcdef0123456789", "/ns/x", "net1")
    rlog.info("hello %s", "world")
    content = open(log_file).read()
    assert "containerID=abcdef0123456" in content
    assert "ifname=net1" in content
    assert "hello world" in content


# -- gratuitous ARP -----------------------------------------------------------


def test_garp_frame_shape():
    from dpu_operator_tpu.cni.arp import _build_garp

    frame = _build_garp(bytes.fromhex("020000000001"), bytes([10, 56, 0, 2]))
    assert len(frame) == 14 + 28
    assert frame[:6] == b"\xff" * 6  # broadcast dst
    assert frame[12:14] == b"\x08\x06"  # ethertype ARP
    # opcode 1 (request), sender == target IP (gratuitous).
    assert frame[20:22] == b"\x00\x01"
    assert frame[28:32] == frame[38:42] == bytes([10, 56, 0, 2])


def test_garp_announce_over_real_veth(netns):
    """Send a real GARP from a veth end and capture it on the peer."""
    import socket as s_mod
    import struct
    import subprocess
    import threading
    import time
    import uuid

    from dpu_operator_tpu.cni.arp import ETH_P_ARP, announce

    a = "ga" + uuid.uuid4().hex[:6]
    b = "gb" + uuid.uuid4().hex[:6]
    subprocess.run(["ip", "link", "add", a, "type", "veth", "peer", "name", b], check=True)
    try:
        for dev in (a, b):
            subprocess.run(["ip", "link", "set", dev, "up"], check=True)
        cap = s_mod.socket(s_mod.AF_PACKET, s_mod.SOCK_RAW, s_mod.htons(ETH_P_ARP))
        cap.bind((b, 0))
        cap.settimeout(5)
        got = []

        def rx():
            try:
                got.append(cap.recv(100))
            except OSError:
                pass

        t = threading.Thread(target=rx, daemon=True)
        t.start()
        assert announce(a, "02:00:00:00:00:07", "10.99.0.5/24") is True
        t.join(timeout=6)
        cap.close()
        assert got, "no GARP captured on peer"
        assert got[0][12:14] == struct.pack("!H", ETH_P_ARP)
    finally:
        subprocess.run(["ip", "link", "del", a], capture_output=True)


def test_fabric_ctl_ports_and_stats(netns, capsys):
    """ports dumps bridge enslavement/hairpin/FDB; stats reads kernel
    counters — the p4rt-ctl table/counter-inspection surface (VERDICT r1
    Missing #7) against a real linux-bridge dataplane."""
    import subprocess

    from dpu_operator_tpu.fabric_ctl import main as fabric_ctl

    br = "br-fctl0"
    subprocess.run(["ip", "link", "del", br], capture_output=True)
    subprocess.run(["ip", "link", "add", br, "type", "bridge"], check=True)
    try:
        subprocess.run(["ip", "link", "add", "fctl-a", "type", "veth",
                        "peer", "name", "fctl-b"], check=True)
        subprocess.run(["ip", "link", "set", "fctl-a", "master", br], check=True)
        subprocess.run(["ip", "link", "set", "fctl-a", "up"], check=True)
        subprocess.run(["bridge", "link", "set", "dev", "fctl-a",
                        "hairpin", "on"], check=True)
        subprocess.run(["bridge", "fdb", "replace", "02:aa:bb:cc:dd:ee",
                        "dev", "fctl-a", "master", "static"], check=True)

        assert fabric_ctl(["ports", "--bridge", br]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["bridge"] == br
        port = out["ports"]["fctl-a"]
        assert port["hairpin"] is True
        assert port["mtu"] > 0
        assert any(e["mac"] == "02:aa:bb:cc:dd:ee" for e in port["fdb"])

        assert fabric_ctl(["stats", "--bridge", br]) == 0
        stats = json.loads(capsys.readouterr().out)
        assert set(stats) == {"fctl-a"}
        assert "rx_bytes" in stats["fctl-a"] and "tx_dropped" in stats["fctl-a"]

        assert fabric_ctl(["stats", "fctl-a", "--rate", "0.2"]) == 0
        rated = json.loads(capsys.readouterr().out)
        assert "per_second" in rated["fctl-a"]
        assert "totals" in rated["fctl-a"]
    finally:
        subprocess.run(["ip", "link", "del", "fctl-a"], capture_output=True)
        subprocess.run(["ip", "link", "del", br], capture_output=True)


def test_fabric_ctl_watch_streams_inventory_changes(tmp_root):
    """watch emits a snapshot then added/removed events as the VSP's
    inventory changes between polls. Runs as a real subprocess so the
    snapshot can be awaited on its stdout pipe (line-by-line, no capture
    races)."""
    import subprocess
    import sys

    import grpc as grpclib

    from dpu_operator_tpu.dpu_api import services
    from dpu_operator_tpu.dpu_api.gen import dpu_api_pb2 as pb
    from dpu_operator_tpu.vsp import MockVsp, VspServer

    vsp = MockVsp(opi_port=free_port())
    server = VspServer(vsp, tmp_root)
    server.start()
    proc = None
    try:
        sock = tmp_root.vendor_plugin_socket()
        proc = subprocess.Popen(
            [sys.executable, "-m", "dpu_operator_tpu.fabric_ctl",
             "--socket", sock, "watch", "--interval", "0.3", "--count", "3"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        lines = [json.loads(proc.stdout.readline())]
        assert lines[0]["event"] == "snapshot"
        assert len(lines[0]["devices"]) == 4
        # Snapshot seen — shrink the inventory, then drain the stream.
        chan = grpclib.insecure_channel(f"unix://{sock}")
        services.DeviceStub(chan).SetNumEndpoints(pb.EndpointCount(count=2), timeout=10)
        chan.close()
        out, err = proc.communicate(timeout=30)
        assert proc.returncode == 0, err
        lines += [json.loads(l) for l in out.strip().splitlines() if l]
        removed = {l["id"] for l in lines if l["event"] == "removed"}
        assert removed == {"mock-ep2", "mock-ep3"}
    finally:
        if proc and proc.poll() is None:
            proc.kill()
        server.stop()
