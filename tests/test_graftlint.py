"""graftlint: the tier-1 gate plus the analyzer's own test suite.

Three layers:
  * THE GATE — the whole package must analyze clean against the
    checked-in baseline (this is the test that makes every rule a
    permanent regression guard);
  * per-rule fixture pairs — each rule's minimal true positive fires
    and its near-miss stays silent (tests/fixtures/graftlint/);
  * machinery — pragma suppression (line / line-above / file), the
    baseline ratchet (count caps, stale entries stay green), and the
    acceptance scratch-copies: re-introducing the PR 2 mask-multiply
    bug or the PR 3 except-binding bug into a copy of the REAL source
    must make the analyzer fail.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from dpu_operator_tpu.analysis import (DEFAULT_BASELINE, default_rules,
                                       run_analysis)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).parent / "fixtures" / "graftlint"


def _analyze(path, baseline=None):
    return run_analysis([str(path)], baseline=baseline)


def _analyze_source(tmp_path, source, name="fx.py", baseline=None):
    p = tmp_path / name
    p.write_text(source)
    return _analyze(p, baseline=baseline)


# -- the gate -----------------------------------------------------------------


def test_package_gate_clean_and_fast():
    """The tier-1 gate: zero non-baselined findings over the whole
    package, in well under the 10 s lint-lane budget."""
    t0 = time.perf_counter()
    report = run_analysis([str(REPO / "dpu_operator_tpu")],
                          baseline=DEFAULT_BASELINE)
    elapsed = time.perf_counter() - t0
    assert report.clean, "\n".join(f.format() for f in report.findings)
    assert report.checked_files > 100  # really saw the package
    assert elapsed < 10.0, f"analyzer took {elapsed:.1f}s (budget 10s)"


def test_rule_ids_unique_and_documented():
    rules = default_rules()
    ids = [r.rule_id for r in rules]
    assert len(set(ids)) == len(ids) == 11
    for r in rules:
        assert r.title and r.hint and r.severity in ("error", "warning")


# -- per-rule fixture pairs ---------------------------------------------------

_EXPECT = {
    "GL001": 1,  # the lambda cotangent-scale
    "GL002": 3,  # float(), np.asarray(call), .item()
    "GL003": 1,  # handler reads try-bound slot index
    "GL004": 3,  # subprocess, socket send, thread join under lock
    "GL005": 2,  # except: pass, except BaseException: continue
    "GL006": 1,  # psum over the 'pd' typo
    "GL007": 1,  # while-True connect retry, no bound, no sleep
    "GL008": 2,  # bare replica-only logs in the request-scoped graph
    "GL009": 2,  # acquire and prefix-fork with no release, no lease
    "GL010": 2,  # loop recv and loop collect, no deadline anywhere
    "GL011": 2,  # loop-send tobytes and loop-send np.copy
}


@pytest.mark.parametrize("rule_id", sorted(_EXPECT))
def test_true_positive_fires(rule_id):
    report = _analyze(FIXTURES / f"{rule_id.lower()}_tp.py")
    assert len(report.findings) == _EXPECT[rule_id], [
        f.format() for f in report.findings]
    assert all(f.rule == rule_id for f in report.findings)


@pytest.mark.parametrize("rule_id", sorted(_EXPECT))
def test_near_miss_stays_silent(rule_id):
    report = _analyze(FIXTURES / f"{rule_id.lower()}_nm.py")
    assert report.clean, [f.format() for f in report.findings]


def test_relpath_stable_when_checkout_dir_shares_package_name():
    """A checkout directory itself named dpu_operator_tpu must not
    produce doubled-prefix baseline keys (which would unmatch the
    checked-in baseline and turn a clean gate red)."""
    from dpu_operator_tpu.analysis.core import _canonical_relpath
    assert _canonical_relpath(
        "/home/u/dpu_operator_tpu/dpu_operator_tpu/vsp/tpu_vsp.py"
    ) == "dpu_operator_tpu/vsp/tpu_vsp.py"


def test_gl003_fires_at_module_level(tmp_path):
    """Module-level init code is import-time code: a module try whose
    handler reads a try-bound name NameErrors at import — GL003 must
    see it, not only function bodies."""
    src = (
        "import logging\n"
        "log = logging.getLogger(__name__)\n"
        "try:\n"
        "    sock = _dial()\n"
        "except Exception:\n"
        "    log.warning('dial failed: %s', sock)\n"
        "def _dial():\n"
        "    return None\n"
    )
    report = _analyze_source(tmp_path, src)
    assert any(f.rule == "GL003" and "'sock'" in f.message
               for f in report.findings), [
        f.format() for f in report.findings]


def test_gl010_module_settimeout_grant_silences(tmp_path):
    """The module-wide near-miss that cannot share the nm fixture: a
    transport module that arms its sockets with settimeout at connect
    time (fabric_collectives' discipline) statically bounds every
    later recv — the SAME loop that fires without the grant must stay
    silent with it."""
    loop = (
        "def pump(sock, frames):\n"
        "    while True:\n"
        "        data = sock.recv(65536)\n"
        "        if not data:\n"
        "            return\n"
        "        frames.append(data)\n")
    header = ("# graftlint-fixture-path: "
              "dpu_operator_tpu/parallel/fx_gl010_grant.py\n")
    fired = _analyze_source(tmp_path, header + loop, name="a.py")
    assert any(f.rule == "GL010" for f in fired.findings), [
        f.format() for f in fired.findings]
    granted = _analyze_source(
        tmp_path,
        header
        + "def connect(sock, addr, io_timeout):\n"
          "    sock.connect(addr)\n"
          "    sock.settimeout(io_timeout)\n"
        + loop,
        name="b.py")
    assert not any(f.rule == "GL010" for f in granted.findings), [
        f.format() for f in granted.findings]


# -- pragma suppression -------------------------------------------------------


def _gl005_tp_source():
    return (FIXTURES / "gl005_tp.py").read_text()


def test_pragma_on_finding_line(tmp_path):
    src = _gl005_tp_source().replace(
        "    except Exception:",
        "    except Exception:  # graftlint: disable=GL005")
    report = _analyze_source(tmp_path, src)
    # Only the pragma'd handler is silenced; the other still fires.
    assert len(report.findings) == 1
    assert report.findings[0].func == "teardown"


def test_pragma_on_line_above(tmp_path):
    src = _gl005_tp_source().replace(
        "    except Exception:",
        "    # graftlint: disable=GL005\n    except Exception:")
    report = _analyze_source(tmp_path, src)
    assert len(report.findings) == 1


def test_pragma_wrong_rule_does_not_suppress(tmp_path):
    src = _gl005_tp_source().replace(
        "    except Exception:",
        "    except Exception:  # graftlint: disable=GL001")
    report = _analyze_source(tmp_path, src)
    assert len(report.findings) == 2


def test_file_level_pragma(tmp_path):
    src = _gl005_tp_source().replace(
        '"""GL005',
        '# graftlint: disable-file=GL005\n"""GL005')
    report = _analyze_source(tmp_path, src)
    assert report.clean


# -- baseline ratchet ---------------------------------------------------------

_TWO_SILENT = '''\
# graftlint-fixture-path: dpu_operator_tpu/cni/fx_ratchet.py
def teardown(a, b):
    try:
        a.close()
    except Exception:
        pass
    try:
        b.close()
    except Exception:
        pass
'''


def _baseline(tmp_path, count):
    p = tmp_path / "baseline.toml"
    p.write_text(
        '[[suppress]]\n'
        'rule = "GL005"\n'
        'path = "dpu_operator_tpu/cni/fx_ratchet.py"\n'
        'func = "teardown"\n'
        f'count = {count}\n')
    return str(p)


def test_baseline_absorbs_up_to_count(tmp_path):
    report = _analyze_source(tmp_path, _TWO_SILENT,
                             baseline=_baseline(tmp_path, 2))
    assert report.clean and report.suppressed_baseline == 2


def test_baseline_ratchets_past_count(tmp_path):
    """count=1 with two findings: the second is REPORTED — a baselined
    function can't silently grow more instances."""
    report = _analyze_source(tmp_path, _TWO_SILENT,
                             baseline=_baseline(tmp_path, 1))
    assert len(report.findings) == 1
    assert report.suppressed_baseline == 1


def test_removing_baselined_entry_after_fix_stays_green(tmp_path):
    """Fix the site, delete the entry: gate stays green (no baseline at
    all over a clean file)."""
    clean = _TWO_SILENT.replace("pass", "raise")
    report = _analyze_source(tmp_path, clean, baseline=None)
    assert report.clean


def test_stale_baseline_entry_is_note_not_failure(tmp_path):
    """Entry outlives its fixed site: reported stale, exit still
    clean — deleting baseline entries is always safe."""
    clean = _TWO_SILENT.replace("pass", "raise")
    report = _analyze_source(tmp_path, clean,
                             baseline=_baseline(tmp_path, 1))
    assert report.clean
    assert report.stale_baseline and \
        report.stale_baseline[0]["func"] == "teardown"


# -- acceptance scratch-copies: re-introduce the historical bugs --------------


def test_reintroducing_pr2_mask_multiply_fails(tmp_path):
    """Flip pipeline_1f1b's jnp.where SELECTION back to the PR 2
    `dpl * gmask` multiply in a scratch copy of the REAL source: the
    analyzer must fail it (and pass the unmodified copy)."""
    real = (REPO / "dpu_operator_tpu" / "parallel"
            / "pipeline_1f1b.py").read_text()
    header = ("# graftlint-fixture-path: "
              "dpu_operator_tpu/parallel/pipeline_1f1b.py\n")
    assert _analyze_source(tmp_path, header + real,
                           name="control.py").clean
    wanted = "jnp.where(is_b, dpl, jnp.zeros_like(dpl))"
    assert wanted in real, "pipeline_1f1b selection site moved"
    bugged = header + real.replace(wanted, "dpl * gmask")
    report = _analyze_source(tmp_path, bugged, name="bugged.py")
    assert any(f.rule == "GL001" for f in report.findings), [
        f.format() for f in report.findings]


def test_reintroducing_pr3_except_binding_fails(tmp_path):
    """Move `i = free.pop(0)` back inside the try in a scratch copy of
    the REAL scheduler: the handler's `self._slots[i]` NameErrors when
    the failure precedes the bind — the analyzer must fail it."""
    real = (REPO / "dpu_operator_tpu" / "serving"
            / "scheduler.py").read_text()
    header = ("# graftlint-fixture-path: "
              "dpu_operator_tpu/serving/scheduler.py\n")
    assert _analyze_source(tmp_path, header + real,
                           name="control.py").clean
    wanted = "            i = free.pop(0)\n            try:"
    assert wanted in real, "scheduler admission site moved"
    bugged = header + real.replace(
        wanted, "            try:\n                i = free.pop(0)")
    report = _analyze_source(tmp_path, bugged, name="bugged.py")
    assert any(f.rule == "GL003" and "'i'" in f.message
               for f in report.findings), [
        f.format() for f in report.findings]


# -- CLI ----------------------------------------------------------------------


def test_cli_json_and_exit_codes():
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         str(FIXTURES / "gl005_tp.py"), "--no-baseline",
         "--format", "json"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 1, proc.stderr
    out = json.loads(proc.stdout)
    assert len(out["findings"]) == 2 and not out["clean"]
    assert all(f["rule"] == "GL005" for f in out["findings"])

    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         "--list-rules"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 0
    for rid in _EXPECT:
        assert rid in proc.stdout


def test_cli_zero_files_is_usage_error_not_green():
    """A typo'd path must not read as a clean lint lane."""
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         "no_such_dir_xyz"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 2
    assert "no python files" in proc.stderr
