"""graftlint: the tier-1 gate plus the analyzer's own test suite.

Three layers:
  * THE GATE — the whole package must analyze clean against the
    checked-in baseline (this is the test that makes every rule a
    permanent regression guard);
  * per-rule fixture pairs — each rule's minimal true positive fires
    and its near-miss stays silent (tests/fixtures/graftlint/);
  * machinery — pragma suppression (line / line-above / file), the
    baseline ratchet (count caps, stale entries stay green), and the
    acceptance scratch-copies: re-introducing the PR 2 mask-multiply
    bug or the PR 3 except-binding bug into a copy of the REAL source
    must make the analyzer fail.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from dpu_operator_tpu.analysis import (DEFAULT_BASELINE, default_rules,
                                       run_analysis)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).parent / "fixtures" / "graftlint"


def _analyze(path, baseline=None):
    return run_analysis([str(path)], baseline=baseline)


def _analyze_source(tmp_path, source, name="fx.py", baseline=None):
    p = tmp_path / name
    p.write_text(source)
    return _analyze(p, baseline=baseline)


# -- the gate -----------------------------------------------------------------


def test_package_gate_clean_and_fast():
    """The tier-1 gate: zero non-baselined findings over the whole
    package with ALL 24 rules active (including the interprocedural
    GL012/GL013 lockset and GL021/GL022 typestate passes), inside the
    30 s lint-lane budget docs/ci.md carries (measured ~9 s on the
    2-cpu container) — and no single rule above 10 s, so one rule
    regressing cannot silently eat the whole lane."""
    t0 = time.perf_counter()
    report = run_analysis([str(REPO / "dpu_operator_tpu")],
                          baseline=DEFAULT_BASELINE)
    elapsed = time.perf_counter() - t0
    assert report.clean, "\n".join(f.format() for f in report.findings)
    assert report.checked_files > 100  # really saw the package
    assert elapsed < 30.0, f"analyzer took {elapsed:.1f}s (budget 30s)"
    slow = {r: s for r, s in report.rule_timings.items() if s > 10.0}
    assert not slow, f"per-rule 10s budget blown: {slow}"


def test_rule_ids_unique_and_documented():
    rules = default_rules()
    ids = [r.rule_id for r in rules]
    assert len(set(ids)) == len(ids) == 24
    for r in rules:
        assert r.title and r.hint and r.severity in ("error", "warning")


# -- per-rule fixture pairs ---------------------------------------------------

_EXPECT = {
    "GL001": 1,  # the lambda cotangent-scale
    "GL002": 3,  # float(), np.asarray(call), .item()
    "GL003": 1,  # handler reads try-bound slot index
    "GL004": 3,  # subprocess, socket send, thread join under lock
    "GL005": 2,  # except: pass, except BaseException: continue
    "GL006": 1,  # psum over the 'pd' typo
    "GL007": 1,  # while-True connect retry, no bound, no sleep
    "GL008": 2,  # bare replica-only logs in the request-scoped graph
    "GL009": 2,  # acquire and prefix-fork with no release, no lease
    "GL010": 2,  # loop recv and loop collect, no deadline anywhere
    "GL011": 2,  # loop-send tobytes and loop-send np.copy
    "GL012": 2,  # bare list insert + bare counter RMW, second root locked
    "GL013": 3,  # two inversion edges + a send under a cross-root lock
    "GL014": 3,  # direct subtract, assign-then-subtract, wall-vs-mono compare
    "GL015": 2,  # explicit fp32 pool + implicit-default-dtype pool
    "GL016": 2,  # stashed kv_detach_slot + bare lease.detach()
    "GL017": 2,  # plan-time decode_tokens bump + submit last_token stamp
    "GL018": 2,  # inline even split + inline rank*blocks//world range
    "GL019": 2,  # unverified tier restore + unverified origin-tagged insert
    "GL020": 2,  # ctx-as-progress stats export + ctx-sized cache publish
    "GL021": 3,  # double release, double detach, checkin-not-held
    "GL022": 2,  # happy-path-only release + swallowed-exception tier pin
    "GL023": 3,  # fire, wrap, and fault_site=default seams nobody tests
    "GL024": 3,  # hand-set done event, request error store, kv_lease=None
}


@pytest.mark.parametrize("rule_id", sorted(_EXPECT))
def test_true_positive_fires(rule_id):
    report = _analyze(FIXTURES / f"{rule_id.lower()}_tp.py")
    assert len(report.findings) == _EXPECT[rule_id], [
        f.format() for f in report.findings]
    assert all(f.rule == rule_id for f in report.findings)


@pytest.mark.parametrize("rule_id", sorted(_EXPECT))
def test_near_miss_stays_silent(rule_id):
    report = _analyze(FIXTURES / f"{rule_id.lower()}_nm.py")
    assert report.clean, [f.format() for f in report.findings]


def test_relpath_stable_when_checkout_dir_shares_package_name():
    """A checkout directory itself named dpu_operator_tpu must not
    produce doubled-prefix baseline keys (which would unmatch the
    checked-in baseline and turn a clean gate red)."""
    from dpu_operator_tpu.analysis.core import _canonical_relpath
    assert _canonical_relpath(
        "/home/u/dpu_operator_tpu/dpu_operator_tpu/vsp/tpu_vsp.py"
    ) == "dpu_operator_tpu/vsp/tpu_vsp.py"


def test_gl003_fires_at_module_level(tmp_path):
    """Module-level init code is import-time code: a module try whose
    handler reads a try-bound name NameErrors at import — GL003 must
    see it, not only function bodies."""
    src = (
        "import logging\n"
        "log = logging.getLogger(__name__)\n"
        "try:\n"
        "    sock = _dial()\n"
        "except Exception:\n"
        "    log.warning('dial failed: %s', sock)\n"
        "def _dial():\n"
        "    return None\n"
    )
    report = _analyze_source(tmp_path, src)
    assert any(f.rule == "GL003" and "'sock'" in f.message
               for f in report.findings), [
        f.format() for f in report.findings]


def test_gl010_module_settimeout_grant_silences(tmp_path):
    """The module-wide near-miss that cannot share the nm fixture: a
    transport module that arms its sockets with settimeout at connect
    time (fabric_collectives' discipline) statically bounds every
    later recv — the SAME loop that fires without the grant must stay
    silent with it."""
    loop = (
        "def pump(sock, frames):\n"
        "    while True:\n"
        "        data = sock.recv(65536)\n"
        "        if not data:\n"
        "            return\n"
        "        frames.append(data)\n")
    header = ("# graftlint-fixture-path: "
              "dpu_operator_tpu/parallel/fx_gl010_grant.py\n")
    fired = _analyze_source(tmp_path, header + loop, name="a.py")
    assert any(f.rule == "GL010" for f in fired.findings), [
        f.format() for f in fired.findings]
    granted = _analyze_source(
        tmp_path,
        header
        + "def connect(sock, addr, io_timeout):\n"
          "    sock.connect(addr)\n"
          "    sock.settimeout(io_timeout)\n"
        + loop,
        name="b.py")
    assert not any(f.rule == "GL010" for f in granted.findings), [
        f.format() for f in granted.findings]


# -- pragma suppression -------------------------------------------------------


def _gl005_tp_source():
    return (FIXTURES / "gl005_tp.py").read_text()


def test_pragma_on_finding_line(tmp_path):
    src = _gl005_tp_source().replace(
        "    except Exception:",
        "    except Exception:  # graftlint: disable=GL005")
    report = _analyze_source(tmp_path, src)
    # Only the pragma'd handler is silenced; the other still fires.
    assert len(report.findings) == 1
    assert report.findings[0].func == "teardown"


def test_pragma_on_line_above(tmp_path):
    src = _gl005_tp_source().replace(
        "    except Exception:",
        "    # graftlint: disable=GL005\n    except Exception:")
    report = _analyze_source(tmp_path, src)
    assert len(report.findings) == 1


def test_pragma_wrong_rule_does_not_suppress(tmp_path):
    src = _gl005_tp_source().replace(
        "    except Exception:",
        "    except Exception:  # graftlint: disable=GL001")
    report = _analyze_source(tmp_path, src)
    assert len(report.findings) == 2


def test_file_level_pragma(tmp_path):
    src = _gl005_tp_source().replace(
        '"""GL005',
        '# graftlint: disable-file=GL005\n"""GL005')
    report = _analyze_source(tmp_path, src)
    assert report.clean


# -- baseline ratchet ---------------------------------------------------------

_TWO_SILENT = '''\
# graftlint-fixture-path: dpu_operator_tpu/cni/fx_ratchet.py
def teardown(a, b):
    try:
        a.close()
    except Exception:
        pass
    try:
        b.close()
    except Exception:
        pass
'''


def _baseline(tmp_path, count):
    p = tmp_path / "baseline.toml"
    p.write_text(
        '[[suppress]]\n'
        'rule = "GL005"\n'
        'path = "dpu_operator_tpu/cni/fx_ratchet.py"\n'
        'func = "teardown"\n'
        f'count = {count}\n')
    return str(p)


def test_baseline_absorbs_up_to_count(tmp_path):
    report = _analyze_source(tmp_path, _TWO_SILENT,
                             baseline=_baseline(tmp_path, 2))
    assert report.clean and report.suppressed_baseline == 2


def test_baseline_ratchets_past_count(tmp_path):
    """count=1 with two findings: the second is REPORTED — a baselined
    function can't silently grow more instances."""
    report = _analyze_source(tmp_path, _TWO_SILENT,
                             baseline=_baseline(tmp_path, 1))
    assert len(report.findings) == 1
    assert report.suppressed_baseline == 1


def test_removing_baselined_entry_after_fix_stays_green(tmp_path):
    """Fix the site, delete the entry: gate stays green (no baseline at
    all over a clean file)."""
    clean = _TWO_SILENT.replace("pass", "raise")
    report = _analyze_source(tmp_path, clean, baseline=None)
    assert report.clean


def test_stale_baseline_entry_is_note_not_failure(tmp_path):
    """Entry outlives its fixed site: reported stale, exit still
    clean — deleting baseline entries is always safe."""
    clean = _TWO_SILENT.replace("pass", "raise")
    report = _analyze_source(tmp_path, clean,
                             baseline=_baseline(tmp_path, 1))
    assert report.clean
    assert report.stale_baseline and \
        report.stale_baseline[0]["func"] == "teardown"


# -- acceptance scratch-copies: re-introduce the historical bugs --------------


def test_reintroducing_pr2_mask_multiply_fails(tmp_path):
    """Flip pipeline_1f1b's jnp.where SELECTION back to the PR 2
    `dpl * gmask` multiply in a scratch copy of the REAL source: the
    analyzer must fail it (and pass the unmodified copy)."""
    real = (REPO / "dpu_operator_tpu" / "parallel"
            / "pipeline_1f1b.py").read_text()
    header = ("# graftlint-fixture-path: "
              "dpu_operator_tpu/parallel/pipeline_1f1b.py\n")
    assert _analyze_source(tmp_path, header + real,
                           name="control.py").clean
    wanted = "jnp.where(is_b, dpl, jnp.zeros_like(dpl))"
    assert wanted in real, "pipeline_1f1b selection site moved"
    bugged = header + real.replace(wanted, "dpl * gmask")
    report = _analyze_source(tmp_path, bugged, name="bugged.py")
    assert any(f.rule == "GL001" for f in report.findings), [
        f.format() for f in report.findings]


_CONC_SCRATCH_FILES = (
    # The minimal real-source set that gives procset.py its second
    # thread root: the batcher thread (scheduler), the supervisor +
    # worker roots (executor), the FabricExecutor bridge into the
    # shard duck contract, and the framed protocol whose send/recv
    # bodies carry the blocking pedigree.
    "dpu_operator_tpu/serving/scheduler.py",
    "dpu_operator_tpu/serving/executor.py",
    "dpu_operator_tpu/serving/sharded/executor.py",
    "dpu_operator_tpu/serving/sharded/protocol.py",
    "dpu_operator_tpu/serving/sharded/procset.py",
)


def _write_scratch_plane(tmp_path, procset_source: str) -> None:
    """Copy the real serving/sharded subset into a scratch dir, each
    file declaring its real path (the concurrency rules scope by path
    and the baseline keys on it); `procset_source` substitutes for the
    real procset.py."""
    for rel in _CONC_SCRATCH_FILES:
        src = (procset_source if rel.endswith("procset.py")
               else (REPO / rel).read_text())
        name = rel.rsplit("/", 1)[-1]
        (tmp_path / name).write_text(
            f"# graftlint-fixture-path: {rel}\n" + src)


def test_reintroducing_pr8_lock_across_reap_fails(tmp_path):
    """The ISSUE 10 acceptance scratch-test: put PR 8's original
    single-lifecycle-lock shape back into the REAL ShardProcessSet —
    the teardown reap (blocking socket close + process wait) moved
    back UNDER `_lock`, the fast-path lock the batcher-rooted
    collect() and the main-rooted close() both need — and GL013 must
    fail it, while the unmodified plane stays clean against the
    checked-in baseline (which carries the reviewed `_life` entries)."""
    real = (REPO / "dpu_operator_tpu" / "serving" / "sharded"
            / "procset.py").read_text()
    scratch = tmp_path / "control"
    scratch.mkdir()
    _write_scratch_plane(scratch, real)
    report = _analyze(scratch, baseline=DEFAULT_BASELINE)
    assert report.clean, "\n".join(f.format() for f in report.findings)

    wanted = ("            self._up = False\n"
              "        _reap(procs, socks, listener, kill=kill)")
    assert wanted in real, "procset teardown detach site moved"
    bugged = real.replace(
        wanted,
        "            self._up = False\n"
        "            _reap(procs, socks, listener, kill=kill)")
    scratch2 = tmp_path / "bugged"
    scratch2.mkdir()
    _write_scratch_plane(scratch2, bugged)
    report = _analyze(scratch2, baseline=DEFAULT_BASELINE)
    hits = [f for f in report.findings
            if f.rule in ("GL013", "GL004")]
    assert hits, [f.format() for f in report.findings]
    assert any(f.func == "ShardProcessSet._teardown" for f in hits), [
        f.format() for f in hits]


def test_reintroducing_pr17_match_prefix_unwind_loss_fails(tmp_path):
    """The ISSUE 19 acceptance scratch-test, side A: strip PR 17's
    unwind (except: release; raise) back out of the REAL
    kv_match_prefix — a raise inside _extend_from_tier once again
    strands the forked chain — and GL022 must fail it, while the
    unmodified module stays clean against the checked-in baseline."""
    real = (REPO / "dpu_operator_tpu" / "serving" / "kvcache"
            / "executor.py").read_text()
    header = ("# graftlint-fixture-path: "
              "dpu_operator_tpu/serving/kvcache/executor.py\n")
    report = _analyze_source(tmp_path, header + real, name="control.py",
                             baseline=DEFAULT_BASELINE)
    assert report.clean, "\n".join(f.format() for f in report.findings)

    wanted = (
        "            try:\n"
        "                if self.tier is not None:\n"
        "                    cached = self._extend_from_tier(\n"
        "                        tokens, owner, blocks, cached, by_tier)\n"
        "            except Exception:\n"
        "                self.allocator.release(blocks, owner)\n"
        "                raise\n")
    assert wanted in real, "kv_match_prefix unwind site moved"
    bugged = header + real.replace(
        wanted,
        "            if self.tier is not None:\n"
        "                cached = self._extend_from_tier(\n"
        "                    tokens, owner, blocks, cached, by_tier)\n")
    report = _analyze_source(tmp_path, bugged, name="bugged.py",
                             baseline=DEFAULT_BASELINE)
    hits = [f for f in report.findings if f.rule == "GL022"]
    assert any(f.func == "KVExecutorBase.kv_match_prefix"
               and "'blocks'" in f.message for f in hits), [
        f.format() for f in report.findings]


def test_reintroducing_pr7_slot_poison_on_admit_unwind_fails(tmp_path):
    """The ISSUE 19 acceptance scratch-test, side B: drop the admit
    handler's kv_release_slot back out of the REAL scheduler — a
    post-kv_attach raise once again leaves the slot bound (poisoned
    for every future admit) while the handler swallows into
    req.fail — and GL022 must fail it; the unmodified module stays
    clean."""
    real = (REPO / "dpu_operator_tpu" / "serving"
            / "scheduler.py").read_text()
    header = ("# graftlint-fixture-path: "
              "dpu_operator_tpu/serving/scheduler.py\n")
    report = _analyze_source(tmp_path, header + real, name="control.py",
                             baseline=DEFAULT_BASELINE)
    assert report.clean, "\n".join(f.format() for f in report.findings)

    wanted = "self.executor.kv_release_slot(i, cache=False)"
    assert wanted in real, "admit-unwind release site moved"
    bugged = header + real.replace(wanted, "pass", 1)
    report = _analyze_source(tmp_path, bugged, name="bugged.py",
                             baseline=DEFAULT_BASELINE)
    hits = [f for f in report.findings if f.rule == "GL022"]
    assert hits and all("slot binding" in f.message for f in hits), [
        f.format() for f in report.findings]


def test_reintroducing_pr3_except_binding_fails(tmp_path):
    """Move `i = free.pop(0)` back inside the try in a scratch copy of
    the REAL scheduler: the handler's `self._slots[i]` NameErrors when
    the failure precedes the bind — the analyzer must fail it."""
    real = (REPO / "dpu_operator_tpu" / "serving"
            / "scheduler.py").read_text()
    header = ("# graftlint-fixture-path: "
              "dpu_operator_tpu/serving/scheduler.py\n")
    assert _analyze_source(tmp_path, header + real,
                           name="control.py").clean
    wanted = "            i = free.pop(0)\n            try:"
    assert wanted in real, "scheduler admission site moved"
    bugged = header + real.replace(
        wanted, "            try:\n                i = free.pop(0)")
    report = _analyze_source(tmp_path, bugged, name="bugged.py")
    assert any(f.rule == "GL003" and "'i'" in f.message
               for f in report.findings), [
        f.format() for f in report.findings]


# -- CLI ----------------------------------------------------------------------


def test_cli_json_and_exit_codes():
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         str(FIXTURES / "gl005_tp.py"), "--no-baseline",
         "--format", "json"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 1, proc.stderr
    out = json.loads(proc.stdout)
    assert len(out["findings"]) == 2 and not out["clean"]
    assert all(f["rule"] == "GL005" for f in out["findings"])

    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         "--list-rules"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 0
    for rid in _EXPECT:
        assert rid in proc.stdout


def test_cli_zero_files_is_usage_error_not_green():
    """A typo'd path must not read as a clean lint lane."""
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         "no_such_dir_xyz"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 2
    assert "no python files" in proc.stderr


def test_cli_sarif_round_trip_with_rule_filter():
    """`--format sarif --rules GL005`: the SARIF result carries the
    file, line, rule id and message of a known fixture finding, the
    driver block carries the rule metadata, and the filter keeps
    every other rule out of the run."""
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         str(FIXTURES / "gl005_tp.py"), "--no-baseline",
         "--format", "sarif", "--rules", "GL005"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 1, proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == \
        ["GL005"]
    results = run["results"]
    assert len(results) == _EXPECT["GL005"]
    first = results[0]
    assert first["ruleId"] == "GL005"
    loc = first["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == \
        "dpu_operator_tpu/cni/fx_gl005_tp.py"
    assert loc["region"]["startLine"] > 0
    assert "swallows silently" in first["message"]["text"]


def test_cli_unknown_rule_id_is_usage_error():
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         str(FIXTURES / "gl005_tp.py"), "--rules", "GL999"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 2
    assert "GL999" in proc.stderr


def test_cli_rules_filter_excludes_other_rules():
    """The gl013 TP fixture analyzed with only GL001 active is clean:
    the filter controls which rules RUN, not just which report."""
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         str(FIXTURES / "gl013_tp.py"), "--no-baseline",
         "--rules", "GL001"],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- ratchet report + stale TOML notes ----------------------------------------


def _run_cli(tmp_path, fixture_src, baseline_text, *extra):
    fx = tmp_path / "fx.py"
    fx.write_text(fixture_src)
    bl = tmp_path / "baseline.toml"
    bl.write_text(baseline_text)
    return subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis", str(fx),
         "--baseline", str(bl), *extra],
        capture_output=True, text=True, cwd=str(REPO))


def test_ratchet_report_counts_baseline_vs_current(tmp_path):
    """--ratchet-report: per-(rule, path) baselined vs current counts,
    with shrink advice once the tree produces fewer findings than the
    baseline tolerates."""
    proc = _run_cli(
        tmp_path, _TWO_SILENT,
        '[[suppress]]\n'
        'rule = "GL005"\n'
        'path = "dpu_operator_tpu/cni/fx_ratchet.py"\n'
        'func = "teardown"\n'
        'count = 3\n',
        "--ratchet-report")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    row = next(l for l in proc.stdout.splitlines()
               if l.startswith("ratchet: GL005"))
    assert "dpu_operator_tpu/cni/fx_ratchet.py" in row
    # 3 tolerated, 2 produced: progress the operator should commit.
    assert " 3 " in row and " 2 " in row and "shrink" in row


def test_rules_filter_scopes_stale_and_ratchet_advice(tmp_path):
    """Under --rules, baseline entries for rules that DID NOT RUN must
    not be reported stale (their sites weren't analyzed — advising
    deletion would turn the full gate red) nor appear in the ratchet
    table."""
    proc = _run_cli(
        tmp_path, _TWO_SILENT,
        '[[suppress]]\n'
        'rule = "GL005"\n'
        'path = "dpu_operator_tpu/cni/fx_ratchet.py"\n'
        'func = "teardown"\n'
        'count = 2\n',
        "--rules", "GL001", "--ratchet-report")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "delete this from baseline.toml" not in proc.stdout
    assert "GL005" not in proc.stdout
    assert "nothing grandfathered" in proc.stdout


def test_stale_note_includes_deletable_toml_block(tmp_path):
    """A fully-unused entry's note carries the commit-able TOML block
    to delete — fix-then-delete without hand-reconstructing the key."""
    clean = _TWO_SILENT.replace("pass", "raise")
    proc = _run_cli(
        tmp_path, clean,
        '[[suppress]]\n'
        'rule = "GL005"\n'
        'path = "dpu_operator_tpu/cni/fx_ratchet.py"\n'
        'func = "teardown"\n'
        'count = 2\n')
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert "delete this from baseline.toml" in out
    assert '    [[suppress]]' in out
    assert '    rule = "GL005"' in out
    assert '    path = "dpu_operator_tpu/cni/fx_ratchet.py"' in out
    assert '    func = "teardown"' in out
    assert '    count = 2' in out


def test_ratchet_combined_block_round_trips(tmp_path):
    """--ratchet-report groups every fully-unused entry by rule into
    ONE deletable block — and that block (indentation and per-rule
    comment headers included) must re-parse through the baseline
    parser verbatim, so pasting it next to baseline.toml for
    comparison can never produce a different key set."""
    from dpu_operator_tpu.analysis.baseline import _parse_toml_subset

    clean = _TWO_SILENT.replace("pass", "raise")
    proc = _run_cli(
        tmp_path, clean,
        '[[suppress]]\n'
        'rule = "GL005"\n'
        'path = "dpu_operator_tpu/cni/fx_ratchet.py"\n'
        'func = "teardown"\n'
        'count = 2\n'
        '\n'
        '[[suppress]]\n'
        'rule = "GL001"\n'
        'path = "dpu_operator_tpu/cni/fx_ratchet.py"\n'
        'func = "setup"\n',
        "--ratchet-report")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = proc.stdout.splitlines()
    head = next(i for i, l in enumerate(lines)
                if "fully-unused baseline" in l)
    assert "2 fully-unused baseline entries across 2 rule(s)" in lines[head]
    block = []
    for l in lines[head + 1:]:
        if not l.startswith("    "):
            break
        block.append(l)
    # Per-rule comment headers, sorted rule order.
    assert block[0].lstrip().startswith("# -- GL001")
    entries = _parse_toml_subset("\n".join(block), "stdout")
    assert [e["rule"] for e in entries] == ["GL001", "GL005"]
    assert entries[0]["func"] == "setup"
    assert entries[1] == {"rule": "GL005",
                          "path": "dpu_operator_tpu/cni/fx_ratchet.py",
                          "func": "teardown", "count": 2}


def test_profile_flag_reports_per_rule_time_and_findings(tmp_path):
    """--profile appends a per-rule wall-time table (the docs/ci.md
    lint-budget breakdown): every registered rule gets a row, and the
    finding column counts RAW findings (before baseline filtering) so
    a fully-baselined rule still shows its cost."""
    proc = _run_cli(
        tmp_path, _TWO_SILENT,
        '[[suppress]]\n'
        'rule = "GL005"\n'
        'path = "dpu_operator_tpu/cni/fx_ratchet.py"\n'
        'func = "teardown"\n'
        'count = 2\n',
        "--profile")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = [l for l in proc.stdout.splitlines()
            if l.startswith("profile: GL")]
    assert len(rows) == len(default_rules())
    gl005 = next(l for l in rows if l.startswith("profile: GL005"))
    assert gl005.split()[-1] == "2"  # raw findings despite baseline
    assert "ms in rules)" in proc.stdout
